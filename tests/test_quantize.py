"""Weight-only int8 post-training quantization (bigdl_tpu/quantize.py).

Net-new vs the reference (no quantization in BigDL v0.3); the contract is
near-lossless serving: per-output-channel symmetric int8 on matmul-bearing
weights, activations untouched.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.quantize import (QuantLinear, QuantMultiHeadAttention,
                                quantize, quantize_array)


def test_quantize_array_roundtrip():
    w = np.random.default_rng(0).normal(size=(16, 32)).astype(np.float32)
    q, scale = quantize_array(w, channel_axis=0)
    assert q.dtype == jnp.int8
    deq = np.asarray(q, np.float32) * np.asarray(scale)[:, None]
    # per-channel symmetric int8: max error is scale/2 per channel
    err = np.abs(deq - w)
    assert (err <= np.asarray(scale)[:, None] * 0.5 + 1e-7).all()


def test_linear_parity():
    m = nn.Linear(64, 32).build(jax.random.key(0))
    x = jnp.asarray(np.random.default_rng(1).normal(size=(8, 64)),
                    jnp.float32)
    y_f = m.forward(x)
    qm = quantize(m)
    assert isinstance(qm, QuantLinear)
    assert qm.params["q"].dtype == jnp.int8
    y_q = qm.forward(x)
    rel = float(jnp.linalg.norm(y_q - y_f) / jnp.linalg.norm(y_f))
    assert rel < 0.01, rel


def test_float_model_untouched():
    m = nn.Linear(8, 4).build(jax.random.key(0))
    w_before = np.asarray(m.params["weight"]).copy()
    quantize(m)
    np.testing.assert_array_equal(np.asarray(m.params["weight"]), w_before)


def test_unbuilt_model_rejected():
    with pytest.raises(ValueError):
        quantize(nn.Linear(4, 4))


def test_trained_lenet_accuracy_preserved():
    """Train LeNet on the separable synthetic task, quantize, and the
    held-out accuracy must survive int8 weights."""
    from bigdl_tpu.optim import Evaluator, Top1Accuracy
    from test_e2e_lenet import make_optimizer, synthetic_mnist
    from bigdl_tpu.dataset import DataSet
    from bigdl_tpu.utils.engine import Engine

    Engine.reset()
    Engine.init()
    model, opt = make_optimizer()
    opt.optimize()
    val = DataSet.array(synthetic_mnist(256, seed=9))
    acc_f = Evaluator(model).test(val, [Top1Accuracy()],
                                  batch_size=64)[0][1].result()[0]
    qmodel = quantize(model)
    acc_q = Evaluator(qmodel).test(val, [Top1Accuracy()],
                                   batch_size=64)[0][1].result()[0]
    assert acc_q >= acc_f - 0.02, (acc_f, acc_q)
    # conv + linear weights really are int8 now
    leaves = jax.tree.leaves(qmodel.params)
    assert any(l.dtype == jnp.int8 for l in leaves)


def test_transformer_quantized_cached_decode():
    """Quantized MHA inherits the cache path: cached_generate on the int8
    model must agree with the int8 full forward (and stay close to f32)."""
    from bigdl_tpu.models import TransformerLM
    from bigdl_tpu.models.decode import cached_generate
    from bigdl_tpu.models.transformer_lm import greedy_generate
    from bigdl_tpu.common import set_seed

    set_seed(4)
    vocab, t = 12, 8
    model = TransformerLM(vocab_size=vocab, max_len=t, d_model=32,
                          num_heads=4, num_layers=2).build(jax.random.key(1))
    qmodel = quantize(model)
    mhas = [m for m in
            __import__("bigdl_tpu.models.decode",
                       fromlist=["_mha_modules"])._mha_modules(qmodel)]
    assert mhas and all(isinstance(m, QuantMultiHeadAttention)
                        for m in mhas)
    prompt = [[3, 4, 5]]
    full_q = greedy_generate(qmodel, prompt, num_tokens=4, max_len=t)
    cached_q = cached_generate(qmodel, prompt, num_tokens=4, max_len=t)
    np.testing.assert_array_equal(np.asarray(full_q), np.asarray(cached_q))
    # logits of the quantized model track the float model closely
    tok = jnp.asarray(prompt, jnp.int32)
    lf, _ = model.apply(model.params, model.state, tok, training=False,
                        rng=None)
    lq, _ = qmodel.apply(qmodel.params, qmodel.state, tok, training=False,
                         rng=None)
    assert float(jnp.max(jnp.abs(lf - lq))) < 0.15
