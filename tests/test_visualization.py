"""Visualization subsystem tests: proto wire codec roundtrips, event-file
framing, TrainSummary/ValidationSummary end-to-end through the Optimizer.

Reference analog: visualization specs read event files back via
FileReader.scala; our reader plays the same oracle role."""

import numpy as np
import pytest

from bigdl_tpu.visualization import (TrainSummary, ValidationSummary, proto,
                                     read_scalar)


def test_varint_roundtrip():
    for v in (0, 1, 127, 128, 300, 2 ** 32, 2 ** 63 - 1):
        buf = proto.encode_varint(v)
        got, pos = proto.decode_varint(buf, 0)
        assert got == v and pos == len(buf)


def test_scalar_event_roundtrip():
    summ = proto.scalar_summary("Loss", 1.5)
    ev = proto.event_bytes(123.25, step=7, summary=summ)
    parsed = proto.parse_event(ev)
    assert parsed["wall_time"] == 123.25
    assert parsed["step"] == 7
    v = parsed["values"][0]
    assert v["tag"] == "Loss"
    assert v["simple_value"] == pytest.approx(1.5)


def test_histogram_event_roundtrip():
    x = np.concatenate([np.zeros(5), np.linspace(-3, 3, 100)])
    ev = proto.event_bytes(1.0, step=2,
                           summary=proto.histogram_summary("w", x))
    h = proto.parse_event(ev)["values"][0]["histo"]
    assert h["num"] == pytest.approx(105)
    assert h["min"] == pytest.approx(-3)
    assert h["max"] == pytest.approx(3)
    assert h["sum"] == pytest.approx(float(x.sum()))
    assert sum(h["bucket"]) == pytest.approx(105)
    assert len(h["bucket_limit"]) == len(h["bucket"])


def test_file_version_header(tmp_path):
    ts = TrainSummary(str(tmp_path), "app")
    ts.add_scalar("Loss", 0.5, 1)
    ts.close()
    from bigdl_tpu.visualization.reader import list_events
    events = list(list_events(ts.summary_dir))
    assert events[0]["file_version"] == "brain.Event:2"


def test_train_summary_scalar_readback(tmp_path):
    ts = TrainSummary(str(tmp_path), "app")
    for i in range(10):
        ts.add_scalar("Loss", 1.0 / (i + 1), i)
        ts.add_scalar("Throughput", 100.0 + i, i)
    got = ts.read_scalar("Loss")
    assert [s for s, _, _ in got] == list(range(10))
    assert got[4][1] == pytest.approx(0.2)
    assert len(ts.read_scalar("Throughput")) == 10
    assert ts.summary_dir.endswith("app/train")
    ts.close()


def test_validation_summary_dir(tmp_path):
    vs = ValidationSummary(str(tmp_path), "app")
    vs.add_scalar("Top1Accuracy", 0.9, 3)
    assert vs.read_scalar("Top1Accuracy")[0][:2] == (3, pytest.approx(0.9))
    assert vs.summary_dir.endswith("app/validation")
    vs.close()


def test_summary_trigger_validation(tmp_path):
    ts = TrainSummary(str(tmp_path), "app")
    from bigdl_tpu.optim import Trigger
    ts.set_summary_trigger("Parameters", Trigger.several_iteration(5))
    assert ts.get_summary_trigger("Parameters") is not None
    with pytest.raises(ValueError):
        ts.set_summary_trigger("NotATag", Trigger.every_epoch())
    ts.close()


def test_optimizer_writes_summaries(tmp_path):
    import bigdl_tpu.nn as nn
    from bigdl_tpu import Engine
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.optim import (Adam, Optimizer, Top1Accuracy, Trigger)

    rng = np.random.default_rng(0)
    samples = [Sample(rng.standard_normal(8).astype(np.float32),
                      np.float32(i % 2)) for i in range(64)]
    ds = DataSet.array(samples).transform(
        SampleToMiniBatch(16, drop_last=True))
    model = (nn.Sequential().add(nn.Linear(8, 2)).add(nn.LogSoftMax()))
    ts = TrainSummary(str(tmp_path), "job")
    ts.set_summary_trigger("Parameters", Trigger.several_iteration(2))
    vs = ValidationSummary(str(tmp_path), "job")
    opt = (Optimizer(model, ds, nn.ClassNLLCriterion())
           .set_optim_method(Adam(1e-2))
           .set_end_when(Trigger.max_epoch(2))
           .set_train_summary(ts)
           .set_validation(Trigger.every_epoch(), ds, [Top1Accuracy()])
           .set_validation_summary(vs)
           .set_log_interval(1))
    opt.optimize()
    loss = ts.read_scalar("Loss")
    assert len(loss) >= 4
    assert all(np.isfinite(v) for _, v, _ in loss)
    assert len(ts.read_scalar("LearningRate")) == len(loss)
    # histograms were written for every parameter leaf
    from bigdl_tpu.visualization.reader import list_events
    histo_tags = {v["tag"] for ev in list_events(ts.summary_dir)
                  for v in ev["values"] if v["histo"] is not None}
    assert histo_tags, "expected parameter histograms"
    acc = vs.read_scalar("Top1Accuracy")
    assert len(acc) == 2
    ts.close()
    vs.close()
