"""Variable-length (bucketed-sequence) serving tests.

The ISSUE-20 text-serving contract: token requests of ANY length that
fits the ladder coalesce onto a fixed (batch-bucket, seq-bucket) shape
grid — one device batch per seq bucket per collect — bit-identical to a
bulk Predictor fed the same padded rows; oversized or rank-stray
samples are rejected TYPED at admission (never truncated); deadlines
and tenant quotas behave exactly as on fixed-shape workloads (the
zero-workload-specific-serving claim)."""

import time

import numpy as np
import jax
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu import Engine
from bigdl_tpu.optim import Predictor
from bigdl_tpu.serve import (InferenceServer, QuotaExceeded, RequestTimeout,
                             ServeError, fit_bucket, pad_tail)

LADDER = (4, 8, 16)
VOCAB, DIM = 32, 4


def _token_model(seed=0):
    return nn.Sequential().add(nn.LookupTable(VOCAB, DIM)).build(
        jax.random.key(seed))


def _tokens(length, seed=0):
    return np.random.default_rng(seed).integers(
        0, VOCAB, size=(length,)).astype(np.int32)


# ----------------------------------------------------- ladder helpers


def test_fit_bucket_ladder():
    assert fit_bucket(1, LADDER) == 4
    assert fit_bucket(4, LADDER) == 4
    assert fit_bucket(5, LADDER) == 8
    assert fit_bucket(16, LADDER) == 16
    assert fit_bucket(17, LADDER) is None  # overflow: no silent clamp


def test_pad_tail_trailing_axis_only():
    x = np.arange(6, dtype=np.int32).reshape(2, 3)
    p = pad_tail(x, 5)
    assert p.shape == (2, 5)
    np.testing.assert_array_equal(p[:, :3], x)
    np.testing.assert_array_equal(p[:, 3:], 0)
    assert pad_tail(x, 3) is x  # exact fit untouched
    with pytest.raises(ValueError):
        pad_tail(x, 2)  # refuses to truncate
    with pytest.raises(ValueError):
        pad_tail(np.int32(3), 2)  # scalars have no trailing axis


# ------------------------------------------------------- acceptance


def test_variable_lengths_coalesce_bit_identical():
    """Six requests at five distinct lengths land on exactly three
    (batch, seq) grid points — one device batch per seq bucket — and
    every answer bit-matches bulk Predictor fed the same padded row."""
    Engine.init()
    model = _token_model()
    lengths = [3, 4, 6, 7, 8, 12]
    xs = [_tokens(n, seed=i) for i, n in enumerate(lengths)]
    server = InferenceServer(model, max_batch=8, max_wait_ms=10,
                             queue_limit=32, seq_buckets=LADDER,
                             example=np.zeros((4,), np.int32))
    # queued before start -> one collect sees all six
    handles = [server.submit(x) for x in xs]
    server.start()
    outs = [h.result(30) for h in handles]
    stats = server.stats()
    assert stats["batches"] == 3, stats  # one per distinct seq bucket
    assert stats["batch_rows"] == len(lengths)
    for x, out in zip(xs, outs):
        seq = fit_bucket(len(x), LADDER)
        ref = Predictor(model).predict(pad_tail(x, seq)[None, :])[0]
        assert out.shape == (seq, DIM)
        np.testing.assert_array_equal(out, ref)

    # hot swap keeps the ladder: the new version is warmed per seq
    # bucket and answers with ITS numbers
    model_b = _token_model(seed=9)
    server.swap(model_b)
    x = _tokens(6, seed=99)
    out = server.submit(x).result(30)
    ref = Predictor(model_b).predict(pad_tail(x, 8)[None, :])[0]
    np.testing.assert_array_equal(out, ref)
    server.stop()
    assert server.stats()["shed_overload"] == 0
    assert server.stats()["shed_timeout"] == 0


# ----------------------------------------------------- typed rejects


def test_overflow_and_rank_strays_rejected_at_admission():
    Engine.init()
    with InferenceServer(_token_model(), max_wait_ms=2, seq_buckets=LADDER,
                         example=np.zeros((4,), np.int32)) as server:
        with pytest.raises(ServeError):
            server.submit(np.zeros((LADDER[-1] + 1,), np.int32))
        with pytest.raises(ServeError):
            server.submit(np.zeros((2, 4), np.int32))  # rank stray
        # the server keeps serving well-shaped variable-length traffic
        assert server.predict(_tokens(5), timeout=30).shape == (8, DIM)


def test_expired_deadline_sheds_before_device():
    """Deadline shedding is workload-agnostic: expired token requests
    die typed at dequeue and never reach a (batch, seq) grid point."""
    Engine.init()
    server = InferenceServer(_token_model(), max_batch=4, queue_limit=8,
                             max_wait_ms=2, seq_buckets=LADDER,
                             example=np.zeros((4,), np.int32))
    late = [server.submit(_tokens(n, seed=n), deadline_ms=1)
            for n in (3, 6, 12)]
    fresh = server.submit(_tokens(6, seed=0))
    time.sleep(0.05)
    server.start()
    for h in late:
        with pytest.raises(RequestTimeout):
            h.result(30)
    assert fresh.result(30).shape == (8, DIM)
    stats = server.stats()
    assert stats["shed_timeout"] == 3
    assert stats["batch_rows"] == 1
    server.stop()


def test_tenant_quota_on_token_workload():
    Engine.init()
    with InferenceServer(_token_model(), max_wait_ms=2, seq_buckets=LADDER,
                         example=np.zeros((4,), np.int32),
                         tenant_qps=0.001, tenant_burst=1.0) as server:
        ok = server.submit(_tokens(6, seed=1), tenant="t0")
        with pytest.raises(QuotaExceeded):
            server.submit(_tokens(3, seed=2), tenant="t0")
        other = server.submit(_tokens(3, seed=3), tenant="t1")
        assert ok.result(30).shape == (8, DIM)
        assert other.result(30).shape == (4, DIM)
