"""Continuous-batching decode serving (bigdl_tpu/serve/decode.py — ISSUE 18).

The generative serving contract under test (docs/serving.md "Generative
decode"):
  - a persistent step loop over fixed KV-cache slots: sequences join via
    prefill into a free slot, every tick decodes ALL active slots in one
    kernel call, and a finished sequence frees its slot the SAME step;
  - greedy outputs BIT-match the offline ``cached_generate`` oracle per
    sequence, regardless of what else shares the batch (the per-slot
    masked attention gives stale cache rows exactly zero weight);
  - the (batch-slots, cache-page) ladder grows the cache mid-flight and
    the footprint is exact and observable (``cache_bytes_per_slot``);
  - prefill and decode are SEPARATE jitted executables with separate
    compile cards (``decode.prefill`` / ``decode.step``);
  - admission is a per-sequence ``DecodeQueue``: bounded, deadline =
    time-to-last-token (shed typed at dequeue), tenant token buckets;
  - a ``serve.decode@<slot>`` chaos fault fails ONE sequence typed and
    the other slots keep decoding with zero loss;
  - under a (1,1,2) tp mesh the per-device KV cache halves and greedy
    tokens match the single-device run.
"""

import os

import numpy as np
import jax
import pytest

from bigdl_tpu.models.decode import cached_generate, init_kv_cache
from bigdl_tpu.models.transformer_lm import TransformerLM
from bigdl_tpu.serve import (DecodeEngine, DecodeQueue, QuotaExceeded,
                             RequestTimeout, ServeError, SlotFault,
                             TraceEvent, page_ladder, pad_rows, read_trace,
                             write_trace)
from bigdl_tpu.utils import chaos


@pytest.fixture(scope="module")
def lm():
    return TransformerLM(vocab_size=64, max_len=64, d_model=32,
                         num_heads=2, num_layers=2).build(jax.random.key(0))


def _prompts(n, lo=3, hi=10, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, 64, size=int(rng.integers(lo, hi)))
            .astype(np.int32) for _ in range(n)]


def _oracle(lm, prompt, max_tokens):
    return cached_generate(lm, prompt, max_tokens,
                           max_len=len(prompt) + max_tokens)


# ---------------------------------------------------------------------------
# pad_rows trailing-axis padding (satellite: serve/batcher.py)
# ---------------------------------------------------------------------------

def test_pad_rows_trailing_axis_pads_with_zeros():
    arr = np.arange(6, dtype=np.int32).reshape(2, 3)
    out = pad_rows(arr, 4, length=8)
    assert out.shape == (4, 8)
    assert out.dtype == np.int32
    np.testing.assert_array_equal(out[:2, :3], arr)
    # rows pad by repeating the last row (the legacy fixed-batch
    # contract); the NEW trailing axis pads with zeros
    np.testing.assert_array_equal(out[2:, :3], np.tile(arr[-1], (2, 1)))
    assert not out[:, 3:].any()


def test_pad_rows_length_zero_rows_and_dtype():
    # zero-row input: row padding alone can't invent the trailing size,
    # so the length= form must (the legacy no-length call keeps its
    # empty-array behavior)
    out = pad_rows(np.zeros((0, 3), np.float16), 2, length=5)
    assert out.shape == (2, 5) and out.dtype == np.float16
    assert not out.any()


def test_pad_rows_refuses_to_truncate():
    with pytest.raises(ValueError, match="refusing to truncate"):
        pad_rows(np.ones((2, 9), np.float32), 2, length=4)


# ---------------------------------------------------------------------------
# DecodeQueue admission (per-sequence queue under the step loop)
# ---------------------------------------------------------------------------

def test_decode_queue_take_is_nonblocking_and_bounded():
    q = DecodeQueue(queue_limit=8)
    reqs = [q.submit({"max_tokens": 4, "i": i}) for i in range(3)]
    assert q.take(0) == []
    got = q.take(2)
    assert [r.payload["i"] for r in got] == [0, 1]
    assert q.take(5) == [reqs[2]]
    assert q.take(1) == []  # empty: returns, never parks


def test_decode_queue_sheds_expired_deadline_at_dequeue():
    t = [0.0]
    q = DecodeQueue(queue_limit=8, clock=lambda: t[0])
    late = q.submit({"max_tokens": 4}, deadline=1.0)
    live = q.submit({"max_tokens": 4}, deadline=50.0)
    t[0] = 2.0
    got = q.take(2)
    assert got == [live]
    with pytest.raises(RequestTimeout):
        late.result(0.1)
    assert q.shed_timeout == 1


def test_decode_queue_retry_after_scales_with_token_budget():
    q = DecodeQueue(queue_limit=64)
    q.note_service(100, 1.0)  # EMA learns 10ms/token
    q.submit({"max_tokens": 200})
    q.submit({"max_tokens": 200})
    # 400 queued tokens at ~10ms/token >> the 0.05s floor
    assert q.retry_after_s() >= 1.0


# ---------------------------------------------------------------------------
# the engine: page ladder, oracle parity, same-step slot reuse
# ---------------------------------------------------------------------------

def test_page_ladder_pow2_pages_capped_at_max_len():
    assert page_ladder(16, 128) == (16, 32, 64, 128)
    assert page_ladder(16, 100) == (16, 32, 64, 100)
    assert page_ladder(8, 8) == (8,)
    with pytest.raises(ValueError):
        page_ladder(0, 64)


def test_continuous_batching_bit_matches_oracle(lm):
    # 5 mixed-length sequences through 2 slots: forces same-step slot
    # reuse AND mixed in-flight positions; every output must equal the
    # offline single-sequence oracle bit for bit
    prompts = _prompts(5, seed=1)
    budgets = [4, 7, 3, 6, 5]
    with DecodeEngine(lm, slots=2, page=8) as eng:
        handles = [eng.submit(p, mt) for p, mt in zip(prompts, budgets)]
        outs = [h.result(120.0) for h in handles]
        st = eng.stats()
    for p, mt, out in zip(prompts, budgets, outs):
        np.testing.assert_array_equal(out, _oracle(lm, p, mt))
    assert st["seqs_done"] == 5 and st["seqs_failed"] == 0
    assert st["prefill_steps"] == 5  # one prefill per admitted sequence
    assert st["tokens_out"] == sum(budgets)


def test_eos_frees_slot_same_step(lm):
    prompt = _prompts(1, seed=2)[0]
    full = _oracle(lm, prompt, 8)
    eos = int(full[len(prompt) + 2])  # the oracle's 3rd generated token
    with DecodeEngine(lm, slots=1, page=8) as eng:
        out = eng.generate(prompt, 8, eos_token=eos)
        st = eng.stats()
    # truncated AT the EOS token (inclusive), budget unspent
    np.testing.assert_array_equal(out, full[: len(prompt) + 3])
    assert st["tokens_out"] == 3


def test_cache_grows_through_the_page_ladder(lm):
    import time as _time
    short, long = _prompts(2, lo=4, hi=6, seed=3)
    with DecodeEngine(lm, slots=2, page=8, min_step_s=0.01) as eng:
        # sequence A occupies a slot at the 32-page; once it is IN
        # FLIGHT, B needs the 64 bucket -> a mid-flight concat grow
        # (idle re-page would be a fresh alloc, cache_grows stays 0)
        ha = eng.submit(short, 25)
        deadline = _time.monotonic() + 60.0
        while eng.stats()["active"] == 0:
            assert _time.monotonic() < deadline, "A never admitted"
            _time.sleep(0.002)
        assert eng.stats()["cache_len"] == 32
        hb = eng.submit(long, 50)
        first, out = ha.result(120.0), hb.result(120.0)
        st = eng.stats()
    np.testing.assert_array_equal(first, _oracle(lm, short, 25))
    np.testing.assert_array_equal(out, _oracle(lm, long, 50))
    assert st["cache_len"] == 64 and st["cache_grows"] >= 1
    # exact structural footprint: layers x {k,v} x heads x len x head_dim
    assert st["cache_bytes_per_slot"] == 2 * 2 * 2 * st["cache_len"] * 16 * 4


def test_batch_admission_mode_is_run_to_completion(lm):
    prompts = _prompts(4, seed=4)
    with DecodeEngine(lm, slots=2, page=8, admission="batch") as eng:
        handles = [eng.submit(p, 4) for p in prompts]
        outs = [h.result(120.0) for h in handles]
    for p, out in zip(prompts, outs):
        np.testing.assert_array_equal(out, _oracle(lm, p, 4))
    with pytest.raises(ValueError, match="admission"):
        DecodeEngine(lm, admission="sometimes")


def test_prefill_and_decode_emit_separate_compile_cards(lm, monkeypatch):
    from bigdl_tpu.utils import hlostats
    monkeypatch.setenv("BIGDL_TPU_COMPILE_CARDS", "1")
    hlostats.reset()
    try:
        with DecodeEngine(lm, slots=2, page=8) as eng:
            eng.generate(_prompts(1, seed=5)[0], 3)
        ledger = hlostats.ledger()
        assert ledger.get("decode.prefill", 0) >= 1
        assert ledger.get("decode.step", 0) >= 1
    finally:
        hlostats.reset()


# ---------------------------------------------------------------------------
# typed rejection, deadlines, quotas, chaos
# ---------------------------------------------------------------------------

def test_submit_rejects_bad_requests_typed(lm):
    eng = DecodeEngine(lm, slots=1, page=8)  # never started: pure checks
    with pytest.raises(ServeError, match="non-empty"):
        eng.submit(np.zeros((0,), np.int32), 4)
    with pytest.raises(ServeError, match="max_tokens"):
        eng.submit(np.ones(3, np.int32), 0)
    with pytest.raises(ServeError, match="max_len"):
        eng.submit(np.ones(3, np.int32), 1000)
    with pytest.raises(ValueError, match="max_len"):
        DecodeEngine(lm, max_len=4096)  # beyond the PE cap


def test_queue_deadline_times_out_typed(lm):
    # slot pinned busy by a long sequence at a paced step floor; the
    # queued request's time-to-last-token deadline passes before a slot
    # frees -> typed RequestTimeout at dequeue, engine keeps serving
    prompt = _prompts(1, seed=6)[0]
    with DecodeEngine(lm, slots=1, page=8, min_step_s=0.02) as eng:
        slow = eng.submit(prompt, 30)
        late = eng.submit(prompt, 4, deadline_ms=40.0)
        with pytest.raises(RequestTimeout):
            late.result(120.0)
        np.testing.assert_array_equal(slow.result(120.0),
                                      _oracle(lm, prompt, 30))
        assert eng.stats()["queue"]["shed_timeout"] == 1


def test_tenant_quota_rejects_typed(lm):
    with DecodeEngine(lm, slots=1, page=8, tenant_qps=0.001,
                      tenant_burst=1) as eng:
        prompt = _prompts(1, seed=7)[0]
        first = eng.submit(prompt, 2, tenant="team-a")
        with pytest.raises(QuotaExceeded):
            eng.submit(prompt, 2, tenant="team-a")
        first.result(120.0)


def test_chaos_slot_fault_fails_one_sequence_others_bit_match(lm):
    # the serve.decode@<slot> drill: slot 1's sequence dies typed, the
    # slot frees, every OTHER sequence still bit-matches the oracle
    prompts = _prompts(4, seed=8)
    with chaos.scoped("serve.decode@1=fail@2"):
        with DecodeEngine(lm, slots=2, page=8) as eng:
            handles = [eng.submit(p, 5) for p in prompts]
            failed, survived = [], []
            for p, h in zip(prompts, handles):
                try:
                    survived.append((p, h.result(120.0)))
                except chaos.ChaosFault:
                    failed.append(h)
            st = eng.stats()
    assert len(failed) == 1 and st["seqs_failed"] == 1
    assert len(survived) == 3 and st["seqs_done"] == 3
    for p, out in survived:
        np.testing.assert_array_equal(out, _oracle(lm, p, 5))


# ---------------------------------------------------------------------------
# tp-sharded decode (satellite: (1,1,2) mesh parity + halved cache)
# ---------------------------------------------------------------------------

@pytest.mark.skipif(jax.device_count() < 2, reason="needs >= 2 devices")
def test_tp_sharded_cached_generate_matches_single_device(lm):
    from bigdl_tpu.parallel import MeshLayout
    mesh = MeshLayout(1, 1, 2).build_mesh(jax.devices()[:2])
    prompt = _prompts(1, seed=9)[0]
    ref = _oracle(lm, prompt, 6)
    got = cached_generate(lm, prompt, 6, max_len=len(prompt) + 6,
                          mesh=mesh)
    # greedy TOKENS match (the tp o-projection all-reduce reorders float
    # sums, so logits are close-not-equal; argmax is the contract)
    np.testing.assert_array_equal(got, ref)


@pytest.mark.skipif(jax.device_count() < 2, reason="needs >= 2 devices")
def test_tp_sharded_kv_cache_halves_per_device(lm):
    from bigdl_tpu.parallel import MeshLayout
    mesh = MeshLayout(1, 1, 2).build_mesh(jax.devices()[:2])
    caches = init_kv_cache(lm, batch=2, max_len=32, mesh=mesh)
    for cache in caches:
        for arr in (cache["k"], cache["v"]):
            # head axis (2 heads) split exactly in half over tp
            assert len(arr.sharding.device_set) == 2
            shard_bytes = {s.data.nbytes for s in arr.addressable_shards}
            assert shard_bytes == {arr.nbytes // 2}


# ---------------------------------------------------------------------------
# trace + telemetry integration
# ---------------------------------------------------------------------------

def test_trace_event_gen_metadata_round_trips(tmp_path):
    path = str(tmp_path / "gen.trace")
    ev = TraceEvent(0.5, np.arange(4, dtype=np.int32), tenant="t",
                    priority=2, deadline_ms=100.0,
                    gen={"max_tokens": 8, "temperature": 0.0})
    write_trace(path, [ev, TraceEvent(0.1, np.ones(2, np.float32))])
    header, events = read_trace(path)
    assert header["count"] == 2
    assert events[0].gen == {"max_tokens": 8, "temperature": 0.0}
    assert events[1].gen is None  # non-generative events unchanged
    np.testing.assert_array_equal(events[0].payload,
                                  np.arange(4, dtype=np.int32))


def test_engine_records_gen_trace(lm, tmp_path):
    path = str(tmp_path / "rec.trace")
    prompt = _prompts(1, seed=10)[0]
    with DecodeEngine(lm, slots=1, page=8) as eng:
        eng.record_trace(path)
        eng.generate(prompt, 3, tenant="team-a")
        eng.stop_trace()
    _, events = read_trace(path)
    assert len(events) == 1 and events[0].tenant == "team-a"
    assert events[0].gen["max_tokens"] == 3
    np.testing.assert_array_equal(events[0].payload, prompt)


def test_http_generate_route_bit_matches_and_types_errors(lm):
    import json
    import sys
    import urllib.error
    import urllib.request

    import bigdl_tpu.nn as nn
    from bigdl_tpu.serve import InferenceServer
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    import serve_http

    model = nn.Sequential().add(nn.Linear(4, 3)).build(jax.random.key(0))
    server = InferenceServer(model, example=np.zeros((4,), np.float32))
    server.start()
    engine = DecodeEngine(lm, slots=2, page=8).start()
    server.decode_engine = engine  # what main() --generate wires up
    httpd = serve_http.serve_forever(server, "127.0.0.1", 0)
    try:
        port = httpd.server_address[1]
        prompt = [3, 9, 21, 5]
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/generate",
            data=json.dumps({"prompt": prompt, "max_tokens": 5}).encode(),
            headers={"Content-Type": "application/json"})
        resp = json.loads(urllib.request.urlopen(req, timeout=60).read())
        ref = _oracle(lm, np.asarray(prompt, np.int32), 5)
        assert resp["tokens"] == ref.tolist() and resp["generated"] == 5
        st = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/v1/stats", timeout=10).read())
        assert st["decode"]["seqs_done"] == 1
        # typed rejection surfaces as HTTP 400, not a 500
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/generate",
                data=json.dumps({"prompt": [],
                                 "max_tokens": 2}).encode(),
                headers={"Content-Type": "application/json"}), timeout=10)
        assert exc.value.code == 400
    finally:
        httpd.shutdown()
        engine.stop()
        server.stop()


def test_decode_counter_track_promotes_to_report_section(lm):
    from bigdl_tpu.utils import telemetry
    bd = telemetry.phase_breakdown({"traceEvents": [
        {"ph": "C", "name": "serve.decode", "ts": 1.0,
         "args": {"tokens_per_s": 350.0, "fill": 0.75,
                  "cache_bytes_per_slot": 16384}},
    ]})
    assert bd["decode"]["tokens_per_s"] == 350.0
    assert bd["decode"]["fill"] == 0.75
    assert "decode:" in telemetry.format_report(bd)
    # and the live engine actually emits the track
    with DecodeEngine(lm, slots=1, page=8) as eng:
        eng.generate(_prompts(1, seed=11)[0], 2)
        st = eng.stats()
    assert st["tokens_per_s"] > 0 and st["cache_bytes_per_slot"] > 0
