"""Gradient-parity goldens against pytorch (CPU) as an independent oracle.

tests/test_torch_golden.py checks FORWARD numerics; training correctness
rests on the backward pass, which the reference validates layer-by-layer
through its Torch7-golden specs' accGradParameters/updateGradInput
comparisons (SURVEY.md §4, test/.../torch/ — e.g. SpatialConvolutionSpec
drives both gradInput and gradWeight through `th`).  Here the same idea:
push an identical random cotangent through our jax.grad and through
torch.autograd and compare input/weight/bias gradients elementwise.

Layout notes as in test_torch_golden.py: ours NHWC/HWIO, torch NCHW/OIHW;
every test permutes explicitly.  All grads are wrt a scalar loss
sum(out * cot) with a fixed nonuniform cotangent so reductions/broadcasts
are exercised with per-element weights, not an all-ones dy.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import bigdl_tpu.nn as nn

torch = pytest.importorskip("torch")


def rng():
    return jax.random.key(0)


def _np(shape, seed=0, scale=1.0):
    return (np.random.default_rng(seed).normal(size=shape) * scale
            ).astype(np.float32)


def _t(a, requires_grad=False):
    t = torch.tensor(np.asarray(a))
    if requires_grad:
        t.requires_grad_(True)
    return t


def _our_grads(m, x, cot, training=True):
    """d loss / d (params, x) for loss = sum(apply(x) * cot)."""

    def loss(params, xx):
        out, _ = m.apply(params, m.state, xx, training=training,
                         rng=jax.random.key(1))
        return jnp.sum(out * cot)

    gp, gx = jax.grad(loss, (0, 1))(m.params, jnp.asarray(x))
    return jax.tree.map(np.asarray, gp), np.asarray(gx)


def test_conv2d_grads_match_torch():
    m = nn.SpatialConvolution(3, 8, 5, 3, 2, 1, 2, 1).build(rng())
    x = _np((2, 9, 11, 3), 1)
    cot = _np((2, 9, 6, 8), 2)          # NHWC cotangent (h=9/1 pad1k3; w=6)
    gp, gx = _our_grads(m, x, jnp.asarray(cot))

    conv = torch.nn.Conv2d(3, 8, kernel_size=(3, 5), stride=(1, 2),
                           padding=(1, 2))
    with torch.no_grad():
        conv.weight.copy_(_t(np.asarray(m.params["weight"]).transpose(3, 2, 0, 1)))
        conv.bias.copy_(_t(np.asarray(m.params["bias"])))
    xt = _t(x.transpose(0, 3, 1, 2), requires_grad=True)
    (conv(xt) * _t(cot.transpose(0, 3, 1, 2))).sum().backward()

    np.testing.assert_allclose(gx.transpose(0, 3, 1, 2), xt.grad.numpy(),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(gp["weight"].transpose(3, 2, 0, 1),
                               conv.weight.grad.numpy(), rtol=1e-4, atol=1e-3)
    np.testing.assert_allclose(gp["bias"], conv.bias.grad.numpy(),
                               rtol=1e-4, atol=1e-4)


def test_linear_grads_match_torch():
    m = nn.Linear(7, 4).build(rng())
    x = _np((5, 7), 3)
    cot = _np((5, 4), 4)
    gp, gx = _our_grads(m, x, jnp.asarray(cot))

    lin = torch.nn.Linear(7, 4)
    with torch.no_grad():
        # ours (out, in) == torch (out, in) — reference nn/Linear.scala layout
        lin.weight.copy_(_t(np.asarray(m.params["weight"])))
        lin.bias.copy_(_t(np.asarray(m.params["bias"])))
    xt = _t(x, requires_grad=True)
    (lin(xt) * _t(cot)).sum().backward()

    np.testing.assert_allclose(gx, xt.grad.numpy(), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(gp["weight"], lin.weight.grad.numpy(),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(gp["bias"], lin.bias.grad.numpy(),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("variant", ["baseline", "fused_vjp",
                                     "pallas_interpret"])
def test_batchnorm_train_mode_grads_match_torch(variant, monkeypatch):
    """Backward through the BATCH statistics — the exact program the
    resnet bench's BN-bandwidth analysis times (docs/benchmarking.md);
    torch differentiates through mean/var the same way.  Every
    implementation variant (autodiff baseline, hand-written fused VJP,
    Pallas kernel) must produce the SAME grads and running-stat updates —
    identical numerics is the contract that lets the bench swap them
    freely (nn/normalization.py)."""
    if variant == "fused_vjp":
        monkeypatch.setenv("BIGDL_TPU_BN_FUSED_VJP", "1")
    elif variant == "pallas_interpret":
        monkeypatch.setenv("BIGDL_TPU_BN_IMPL", "pallas_interpret")
    m = nn.SpatialBatchNormalization(6, eps=1e-5, momentum=0.1).build(rng())
    bn = torch.nn.BatchNorm2d(6, eps=1e-5, momentum=0.1)
    with torch.no_grad():
        bn.weight.copy_(_t(np.asarray(m.params["weight"])))
        bn.bias.copy_(_t(np.asarray(m.params["bias"])))
    x = _np((4, 5, 5, 6), 5)
    cot = _np((4, 5, 5, 6), 6)
    gp, gx = _our_grads(m, x, jnp.asarray(cot), training=True)

    bn.train()
    xt = _t(x.transpose(0, 3, 1, 2), requires_grad=True)
    (bn(xt) * _t(cot.transpose(0, 3, 1, 2))).sum().backward()

    np.testing.assert_allclose(gx.transpose(0, 3, 1, 2), xt.grad.numpy(),
                               rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(gp["weight"], bn.weight.grad.numpy(),
                               rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(gp["bias"], bn.bias.grad.numpy(),
                               rtol=1e-3, atol=1e-4)
    # running-stat EMA (torch-lineage unbiased-var convention) must match
    _, new_state = m.apply(m.params, m.state, jnp.asarray(x), training=True)
    np.testing.assert_allclose(np.asarray(new_state["running_mean"]),
                               bn.running_mean.numpy(), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(new_state["running_var"]),
                               bn.running_var.numpy(), rtol=1e-4, atol=1e-5)


def test_maxpool_grad_matches_torch():
    """Routing of the cotangent to argmax positions (the reduce_window /
    select-and-scatter pair vs torch's MaxPool2d backward)."""
    m = nn.SpatialMaxPooling(2, 2, 2, 2).build(rng())
    x = _np((3, 8, 8, 4), 7)
    cot = _np((3, 4, 4, 4), 8)

    def loss(xx):
        out, _ = m.apply(m.params, m.state, xx, training=True, rng=None)
        return jnp.sum(out * jnp.asarray(cot))

    gx = np.asarray(jax.grad(loss)(jnp.asarray(x)))

    xt = _t(x.transpose(0, 3, 1, 2), requires_grad=True)
    (torch.nn.MaxPool2d(2, 2)(xt) * _t(cot.transpose(0, 3, 1, 2))
     ).sum().backward()
    np.testing.assert_allclose(gx.transpose(0, 3, 1, 2), xt.grad.numpy(),
                               rtol=1e-5, atol=1e-6)


def test_logsoftmax_nll_grad_matches_torch():
    """The classification loss path every zoo model trains through."""
    logits = _np((6, 9), 9)
    tgt = np.array([0, 3, 8, 1, 1, 5])
    crit = nn.ClassNLLCriterion()
    lsm = nn.LogSoftMax().build(rng())

    def loss(z):
        out, _ = lsm.apply(lsm.params, lsm.state, z, training=True, rng=None)
        return crit.loss(out, jnp.asarray(tgt))

    gz = np.asarray(jax.grad(loss)(jnp.asarray(logits)))

    zt = _t(logits, requires_grad=True)
    torch.nn.NLLLoss()(torch.nn.LogSoftmax(dim=-1)(zt),
                       torch.tensor(tgt)).backward()
    np.testing.assert_allclose(gz, zt.grad.numpy(), rtol=1e-5, atol=1e-6)


def test_lstm_sequence_grads_match_torch():
    """Backprop-through-time through our lax.scan vs torch's unrolled cell
    loop: kernel/bias grads accumulated over all timesteps."""
    H, I, T, B = 7, 5, 4, 3
    m = nn.Recurrent(nn.LSTM(I, H)).build(rng())
    kernel = np.asarray(m.params[0]["kernel"])
    bias = np.asarray(m.params[0]["bias"])
    x = _np((B, T, I), 10)
    cot = _np((B, T, H), 11)

    def loss(params, xx):
        out, _ = m.apply(params, m.state, xx, training=True,
                         rng=jax.random.key(1))
        return jnp.sum(out * jnp.asarray(cot))

    gp, gx = jax.grad(loss, (0, 1))(m.params, jnp.asarray(x))
    gk, gb = np.asarray(gp[0]["kernel"]), np.asarray(gp[0]["bias"])
    gx = np.asarray(gx)

    cell = torch.nn.LSTMCell(I, H)
    with torch.no_grad():
        cell.weight_ih.copy_(_t(kernel[:I].T))
        cell.weight_hh.copy_(_t(kernel[I:].T))
        cell.bias_ih.copy_(_t(bias))
        cell.bias_hh.copy_(torch.zeros(4 * H))
    xt = _t(x, requires_grad=True)
    h = torch.zeros(B, H)
    c = torch.zeros(B, H)
    total = torch.zeros(())
    for t in range(T):
        h, c = cell(xt[:, t], (h, c))
        total = total + (h * _t(cot[:, t])).sum()
    total.backward()

    np.testing.assert_allclose(gx, xt.grad.numpy(), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(gk[:I], cell.weight_ih.grad.numpy().T,
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(gk[I:], cell.weight_hh.grad.numpy().T,
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(gb, cell.bias_ih.grad.numpy(),
                               rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------- forwards
# layers the round-2 forward suite did not cover against torch


def test_bilinear_matches_torch():
    m = nn.Bilinear(4, 5, 3).build(rng())
    x1, x2 = _np((6, 4), 12), _np((6, 5), 13)
    y = np.asarray(m.forward([jnp.asarray(x1), jnp.asarray(x2)]))
    bl = torch.nn.Bilinear(4, 5, 3)
    with torch.no_grad():
        bl.weight.copy_(_t(np.asarray(m.params["weight"])))
        bl.bias.copy_(_t(np.asarray(m.params["bias"])))
        ref = bl(_t(x1), _t(x2)).numpy()
    np.testing.assert_allclose(y, ref, rtol=1e-4, atol=1e-5)


def test_temporal_convolution_matches_torch_conv1d():
    m = nn.TemporalConvolution(5, 8, 3, 2).build(rng())
    x = _np((2, 12, 5), 14)             # (batch, time, features)
    y = np.asarray(m.forward(jnp.asarray(x)))
    conv = torch.nn.Conv1d(5, 8, 3, stride=2)
    with torch.no_grad():
        # ours (k, in, out) -> torch (out, in, k)
        conv.weight.copy_(_t(np.asarray(m.params["weight"]).transpose(2, 1, 0)))
        conv.bias.copy_(_t(np.asarray(m.params["bias"])))
        ref = conv(_t(x.transpose(0, 2, 1))).numpy()  # (B, out, T')
    np.testing.assert_allclose(y.transpose(0, 2, 1), ref,
                               rtol=1e-4, atol=1e-5)


def test_prelu_matches_torch():
    for n, torch_n in ((0, 1), (5, 5)):
        m = nn.PReLU(n).build(rng())
        x = _np((3, 4, 4, 5), 15)
        y = np.asarray(m.forward(jnp.asarray(x)))
        pr = torch.nn.PReLU(torch_n, init=0.25)
        with torch.no_grad():
            ref = pr(_t(x.transpose(0, 3, 1, 2))).numpy()
        np.testing.assert_allclose(y.transpose(0, 3, 1, 2), ref,
                                   rtol=1e-6, atol=1e-7)


# ---------------------------------------------------------------- criterions
# margin/embedding family vs the torch losses of the same Torch lineage


def test_cosine_embedding_matches_torch():
    c = nn.CosineEmbeddingCriterion(margin=0.2)
    x1, x2 = _np((5, 6), 16), _np((5, 6), 17)
    y = np.array([1, -1, 1, -1, -1], np.float32)
    ours = float(c.loss([jnp.asarray(x1), jnp.asarray(x2)], jnp.asarray(y)))
    ref = float(torch.nn.CosineEmbeddingLoss(margin=0.2)(
        _t(x1), _t(x2), _t(y)))
    np.testing.assert_allclose(ours, ref, rtol=1e-5)


def test_hinge_embedding_matches_torch():
    c = nn.HingeEmbeddingCriterion(margin=1.5)
    x = _np((8,), 18)
    y = np.array([1, -1, 1, -1, 1, -1, -1, 1], np.float32)
    ours = float(c.loss(jnp.asarray(x), jnp.asarray(y)))
    ref = float(torch.nn.HingeEmbeddingLoss(margin=1.5)(_t(x), _t(y)))
    np.testing.assert_allclose(ours, ref, rtol=1e-5)


def test_margin_ranking_matches_torch():
    c = nn.MarginRankingCriterion(margin=0.3)
    x1, x2 = _np((7,), 19), _np((7,), 20)
    y = np.array([1, -1, 1, 1, -1, -1, 1], np.float32)
    ours = float(c.loss([jnp.asarray(x1), jnp.asarray(x2)], jnp.asarray(y)))
    ref = float(torch.nn.MarginRankingLoss(margin=0.3)(_t(x1), _t(x2), _t(y)))
    np.testing.assert_allclose(ours, ref, rtol=1e-5)


@pytest.mark.parametrize("p", [1, 2])
def test_multi_margin_matches_torch(p):
    c = nn.MultiMarginCriterion(p=p, margin=1.0)
    x = _np((6, 9), 21)
    t = np.array([0, 4, 8, 2, 2, 7])
    ours = float(c.loss(jnp.asarray(x), jnp.asarray(t)))
    ref = float(torch.nn.MultiMarginLoss(p=p, margin=1.0)(
        _t(x), torch.tensor(t)))
    np.testing.assert_allclose(ours, ref, rtol=1e-5)


def test_multilabel_soft_margin_matches_torch():
    c = nn.MultiLabelSoftMarginCriterion()
    x = _np((4, 6), 22)
    t = (np.random.default_rng(23).random((4, 6)) > 0.5).astype(np.float32)
    ours = float(c.loss(jnp.asarray(x), jnp.asarray(t)))
    ref = float(torch.nn.MultiLabelSoftMarginLoss()(_t(x), _t(t)))
    np.testing.assert_allclose(ours, ref, rtol=1e-5)


def test_soft_margin_matches_torch():
    c = nn.SoftMarginCriterion()
    x = _np((3, 5), 24)
    y = np.sign(_np((3, 5), 25)).astype(np.float32)
    ours = float(c.loss(jnp.asarray(x), jnp.asarray(y)))
    ref = float(torch.nn.SoftMarginLoss()(_t(x), _t(y)))
    np.testing.assert_allclose(ours, ref, rtol=1e-5)


def test_criterion_grads_match_torch():
    """Backward parity for the two losses the zoo trains with."""
    logits = _np((5, 7), 26)
    tgt = np.array([2, 0, 6, 3, 1])

    ce = nn.CrossEntropyCriterion()
    g = np.asarray(jax.grad(
        lambda z: ce.loss(z, jnp.asarray(tgt)))(jnp.asarray(logits)))
    zt = _t(logits, requires_grad=True)
    torch.nn.CrossEntropyLoss()(zt, torch.tensor(tgt)).backward()
    np.testing.assert_allclose(g, zt.grad.numpy(), rtol=1e-5, atol=1e-6)

    mse = nn.MSECriterion()
    x, y = _np((4, 6), 27), _np((4, 6), 28)
    g = np.asarray(jax.grad(
        lambda z: mse.loss(z, jnp.asarray(y)))(jnp.asarray(x)))
    xt = _t(x, requires_grad=True)
    torch.nn.MSELoss()(xt, _t(y)).backward()
    np.testing.assert_allclose(g, xt.grad.numpy(), rtol=1e-5, atol=1e-6)


def test_gru_sequence_grads_match_torch_autograd():
    """BPTT through our fused-gate GRU scan vs torch AUTOGRAD over the same
    equations.  torch.nn.GRUCell is a different GRU variant (reset gate
    applied AFTER the hidden matmul, r*(W_hn h); ours — the original GRU and
    the reference nn/GRU.scala — applies it BEFORE, W_cand (r*h)), so the
    cells are not weight-mappable.  The forward golden already pins our
    equations against a numpy loop; here torch's tape differentiates the
    identical unrolled math, independently checking the lax.scan VJP."""
    H, I, T, B = 6, 4, 3, 2
    m = nn.Recurrent(nn.GRU(I, H)).build(rng())
    p = m.params[0]
    gk = np.asarray(p["gate_kernel"])   # (I+H, 2H) -> (r, u)
    gb = np.asarray(p["gate_bias"])
    ck = np.asarray(p["cand_kernel"])   # (I+H, H)
    cb = np.asarray(p["cand_bias"])
    x = _np((B, T, I), 30)
    cot = _np((B, T, H), 31)

    gp, gx = _our_grads(m, x, jnp.asarray(cot))

    gk_t = _t(gk, requires_grad=True)
    gb_t = _t(gb, requires_grad=True)
    ck_t = _t(ck, requires_grad=True)
    cb_t = _t(cb, requires_grad=True)
    xt = _t(x, requires_grad=True)
    h = torch.zeros(B, H)
    total = torch.zeros(())
    for t in range(T):
        zin = torch.cat([xt[:, t], h], dim=-1)
        gates = torch.sigmoid(zin @ gk_t + gb_t)
        r, u = gates[:, :H], gates[:, H:]
        cin = torch.cat([xt[:, t], r * h], dim=-1)
        cand = torch.tanh(cin @ ck_t + cb_t)
        h = (1.0 - u) * h + u * cand
        total = total + (h * _t(cot[:, t])).sum()
    total.backward()

    np.testing.assert_allclose(gx, xt.grad.numpy(), rtol=1e-4, atol=1e-5)
    for ours, theirs in ((gp[0]["gate_kernel"], gk_t), (gp[0]["gate_bias"], gb_t),
                         (gp[0]["cand_kernel"], ck_t), (gp[0]["cand_bias"], cb_t)):
        np.testing.assert_allclose(np.asarray(ours), theirs.grad.numpy(),
                                   rtol=1e-4, atol=1e-5)


# ------------------------------------------------- transformer family
# the long-context flagship's building blocks vs the torch oracle


def test_layernorm_grads_match_torch():
    m = nn.LayerNorm(6).build(rng())
    x = _np((4, 5, 6), 40)
    cot = _np((4, 5, 6), 41)
    gp, gx = _our_grads(m, x, jnp.asarray(cot), training=False)

    ln = torch.nn.LayerNorm(6, eps=1e-5)
    with torch.no_grad():
        ln.weight.copy_(_t(np.asarray(m.params["weight"])))
        ln.bias.copy_(_t(np.asarray(m.params["bias"])))
    xt = _t(x, requires_grad=True)
    (ln(xt) * _t(cot)).sum().backward()

    np.testing.assert_allclose(gx, xt.grad.numpy(), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(gp["weight"], ln.weight.grad.numpy(),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(gp["bias"], ln.bias.grad.numpy(),
                               rtol=1e-4, atol=1e-5)


def test_gelu_matches_torch_tanh_approximation():
    """jax.nn.gelu defaults to the tanh approximation — torch's
    GELU(approximate='tanh'), not the exact erf form."""
    m = nn.GELU().build(rng())
    x = _np((7, 9), 42, scale=2.0)
    y = np.asarray(m.forward(jnp.asarray(x)))
    ref = torch.nn.GELU(approximate="tanh")(_t(x)).numpy()
    np.testing.assert_allclose(y, ref, rtol=1e-5, atol=1e-6)

    def loss(z):
        out, _ = m.apply(m.params, m.state, z, training=False, rng=None)
        return jnp.sum(out ** 2)

    gx = np.asarray(jax.grad(loss)(jnp.asarray(x)))
    xt = _t(x, requires_grad=True)
    (torch.nn.GELU(approximate="tanh")(xt) ** 2).sum().backward()
    np.testing.assert_allclose(gx, xt.grad.numpy(), rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_multihead_attention_matches_torch(causal):
    """Ours: y = proj(x) with (in, out) weights; torch packs QKV row-major
    (3E, E) applied as x @ W^T — map W_q = wq.T etc.  Forward AND input
    grads must agree (softmax/scale/mask conventions)."""
    E, H, B, T = 8, 2, 2, 5
    m = nn.MultiHeadAttention(E, H, causal=causal).build(rng())
    x = _np((B, T, E), 43)
    cot = _np((B, T, E), 44)

    gp, gx = _our_grads(m, x, jnp.asarray(cot), training=False)
    y = np.asarray(m.apply(m.params, m.state, jnp.asarray(x),
                           training=False, rng=None)[0])

    mha = torch.nn.MultiheadAttention(E, H, batch_first=True, bias=True)
    p = {k: np.asarray(v) for k, v in m.params.items()}
    with torch.no_grad():
        mha.in_proj_weight.copy_(_t(np.concatenate(
            [p["wq"].T, p["wk"].T, p["wv"].T], axis=0)))
        mha.in_proj_bias.copy_(_t(np.concatenate(
            [p["bq"], p["bk"], p["bv"]])))
        mha.out_proj.weight.copy_(_t(p["wo"].T))
        mha.out_proj.bias.copy_(_t(p["bo"]))
    xt = _t(x, requires_grad=True)
    mask = (torch.triu(torch.ones(T, T), diagonal=1).bool()
            if causal else None)
    ref, _ = mha(xt, xt, xt, attn_mask=mask, need_weights=False)
    np.testing.assert_allclose(y, ref.detach().numpy(),
                               rtol=1e-4, atol=1e-5)
    (ref * _t(cot)).sum().backward()
    np.testing.assert_allclose(np.asarray(gx), xt.grad.numpy(),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(gp["wo"]).T,
                               mha.out_proj.weight.grad.numpy(),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(gp["bo"]),
                               mha.out_proj.bias.grad.numpy(),
                               rtol=1e-4, atol=1e-4)
    # QKV parameter grads: torch packs them (3E, E) row-major as x @ W^T
    ipw = mha.in_proj_weight.grad.numpy()
    ipb = mha.in_proj_bias.grad.numpy()
    E = 8
    for i, (wk_, bk_) in enumerate((("wq", "bq"), ("wk", "bk"),
                                    ("wv", "bv"))):
        np.testing.assert_allclose(np.asarray(gp[wk_]).T,
                                   ipw[i * E:(i + 1) * E],
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(np.asarray(gp[bk_]),
                                   ipb[i * E:(i + 1) * E],
                                   rtol=1e-4, atol=1e-4)
