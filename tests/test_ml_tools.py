"""Tests for the ML estimators, straggler mitigation, kth_largest, and the
ETL/perf tools (reference analogs: DLEstimator/DLClassifier ML-pipeline
specs, the straggler-drop path of DistriOptimizerSpec, Util.kthLargest,
ImageNetSeqFileGenerator)."""

import os

import numpy as np
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.ml import DLClassifier, DLEstimator
from bigdl_tpu.optim import Adam
from bigdl_tpu.utils import kth_largest


def test_kth_largest_matches_sort():
    rng = np.random.default_rng(0)
    vals = rng.standard_normal(101).tolist()
    ranked = sorted(vals, reverse=True)
    for k in (1, 2, 50, 101):
        assert kth_largest(vals, k) == ranked[k - 1]
    with pytest.raises(ValueError):
        kth_largest(vals, 0)
    with pytest.raises(ValueError):
        kth_largest(vals, 102)


def _toy_classification(n=192, d=10, classes=3, seed=0):
    rng = np.random.default_rng(seed)
    centers = rng.standard_normal((classes, d)) * 3
    y = np.arange(n) % classes
    X = centers[y] + rng.standard_normal((n, d)) * 0.3
    return X.astype(np.float32), y.astype(np.float32)


def test_dl_classifier_fit_predict_score():
    X, y = _toy_classification()
    model = (nn.Sequential().add(nn.Linear(10, 16)).add(nn.ReLU())
             .add(nn.Linear(16, 3)))
    est = DLClassifier(model, nn.CrossEntropyCriterion(), batch_size=32,
                       max_epoch=8, optim_method=Adam(1e-2))
    fitted = est.fit(X, y)
    preds = fitted.predict(X)
    assert preds.shape == (len(X),)
    assert fitted.score(X, y) > 0.95
    # transform returns raw outputs
    assert fitted.transform(X).shape == (len(X), 3)


def test_dl_estimator_regression():
    rng = np.random.default_rng(1)
    X = rng.standard_normal((128, 5)).astype(np.float32)
    w = rng.standard_normal((5, 1)).astype(np.float32)
    y = X @ w
    est = DLEstimator(nn.Linear(5, 1), nn.MSECriterion(),
                      label_size=(1,), batch_size=32, max_epoch=80,
                      optim_method=Adam(3e-2))
    fitted = est.fit(X, y)
    pred = fitted.transform(X)
    assert pred.shape == (128, 1)
    assert float(np.mean((pred - y) ** 2)) < 0.05


def test_feature_size_reshaping():
    X, y = _toy_classification(d=16)
    model = (nn.Sequential().add(nn.Reshape((16,))).add(nn.Linear(16, 3)))
    est = DLClassifier(model, nn.CrossEntropyCriterion(),
                       feature_size=(4, 4), batch_size=32, max_epoch=5,
                       optim_method=Adam(1e-2))
    fitted = est.fit(X, y)
    assert fitted.predict(X).shape == (len(X),)


def test_straggler_drop_property_validation():
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.optim import Optimizer
    ds = DataSet.array([Sample(np.zeros(4, np.float32), np.float32(0))] * 8)
    opt = Optimizer(nn.Linear(4, 2), ds.transform(SampleToMiniBatch(4)),
                    nn.CrossEntropyCriterion())
    with pytest.raises(ValueError):
        opt.set_drop_module_property(0.5, 0.2)
    opt.set_drop_module_property(0.1, 0.3, batch_size=10,
                                 warmup_iteration=2)
    assert opt.drop_percentage == 0.1


def test_straggler_check_drops_slow_iterations():
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.optim import Optimizer
    ds = DataSet.array([Sample(np.zeros(4, np.float32), np.float32(0))] * 8)
    opt = Optimizer(nn.Linear(4, 2), ds.transform(SampleToMiniBatch(4)),
                    nn.CrossEntropyCriterion())
    opt.set_drop_module_property(0.05, 0.5, batch_size=20,
                                 warmup_iteration=5)
    # feed a window of fast iterations, then a straggler
    dropped = []
    for i in range(30):
        dropped.append(opt._straggler_check(0.01, i + 1))
    assert not any(dropped)  # uniform times: nothing above threshold budget
    assert opt._straggler_check(1.0, 31) is True  # clear straggler
    got = opt.metrics.get("dropped iterations")
    assert got[0] == 1.0


def test_straggler_drop_budget_respected():
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.optim import Optimizer
    ds = DataSet.array([Sample(np.zeros(4, np.float32), np.float32(0))] * 8)
    opt = Optimizer(nn.Linear(4, 2), ds.transform(SampleToMiniBatch(4)),
                    nn.CrossEntropyCriterion())
    opt.set_drop_module_property(0.05, 0.1, batch_size=20,
                                 warmup_iteration=0)
    for i in range(20):
        opt._straggler_check(0.01, i + 1)
    n_dropped = sum(opt._straggler_check(5.0, 21 + i) for i in range(10))
    # max_drop_percentage=0.1 over a 20-wide window caps drops at 2
    assert n_dropped <= 2


def test_straggler_ramping_waits_capped():
    # regression: a monotonically slowing pipeline must not get every
    # iteration dropped — the budget caps drops per threshold window
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.optim import Optimizer
    ds = DataSet.array([Sample(np.zeros(4, np.float32), np.float32(0))] * 8)
    opt = Optimizer(nn.Linear(4, 2), ds.transform(SampleToMiniBatch(4)),
                    nn.CrossEntropyCriterion())
    opt.set_drop_module_property(0.05, 0.1, batch_size=20,
                                 warmup_iteration=0)
    wait = 0.01
    for i in range(20):
        opt._straggler_check(wait, i + 1)
    dropped = 0
    for i in range(30):
        wait *= 2.0
        dropped += opt._straggler_check(wait, 21 + i)
    # 0.1 * 20 = 2 drops allowed per 20-iteration budget window; 30 iters
    # span at most 2 windows
    assert dropped <= 4


def test_straggler_batch_size_validation():
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.optim import Optimizer
    ds = DataSet.array([Sample(np.zeros(4, np.float32), np.float32(0))] * 8)
    opt = Optimizer(nn.Linear(4, 2), ds.transform(SampleToMiniBatch(4)),
                    nn.CrossEntropyCriterion())
    with pytest.raises(ValueError):
        opt.set_drop_module_property(0.1, 0.2, batch_size=1)
    with pytest.raises(ValueError):
        opt.set_drop_module_property(0.1, 0.2, warmup_iteration=-1)


def test_record_generator_end_to_end(tmp_path):
    from bigdl_tpu.tools.record_generator import convert
    from bigdl_tpu.utils.recordio import read_records
    # build a tiny 2-class image tree (PPM — decodable without PIL)
    for cls in ("cat", "dog"):
        os.makedirs(tmp_path / "imgs" / cls)
        for i in range(3):
            arr = np.full((4, 5, 3), 10 * i, np.uint8)
            _write_ppm(str(tmp_path / "imgs" / cls / f"{i}.ppm"), arr)
    out = str(tmp_path / "out" / "train.bdr")
    paths, n = convert(str(tmp_path / "imgs"), out, shards=2, quiet=True)
    assert n == 6 and len(paths) == 2
    recs = list(read_records(out + "-*-of-*"))
    assert len(recs) == 6
    labels = sorted(r["label"] for r in recs)
    assert labels == [0.0, 0.0, 0.0, 1.0, 1.0, 1.0]
    assert recs[0]["data"].shape == (4, 5, 3)
    # pixel VALUES must survive the uint8 storage roundtrip (i=2 -> 20)
    maxes = sorted(int(r["data"].max()) for r in recs)
    assert maxes == [0, 0, 10, 10, 20, 20]
    # and the training loader must rescale uint8 by dtype
    from bigdl_tpu.models.run import _load_samples
    samples = _load_samples(out + "-*-of-*", (4, 5, 3))
    vals = sorted(round(float(s.feature.max()), 4) for s in samples)
    assert vals[-1] == round(20 / 255, 4)


def _write_ppm(path, arr):
    h, w, _ = arr.shape
    with open(path, "wb") as f:
        f.write(b"P6\n%d %d\n255\n" % (w, h))
        f.write(arr.tobytes())


def test_perf_tool_lenet():
    from bigdl_tpu.tools.perf import run
    out = run("lenet", batch_size=8, iters=2, warmup=1)
    assert out["records_per_second"] > 0
    assert out["model"] == "lenet"


# ----------------------------------------------------------------------
# DataFrame column semantics + validation/early stopping (round-2 verdict
# weak #7: DLEstimator.scala:53-109's featuresCol/labelCol/prediction
# contract and validation support)
# ----------------------------------------------------------------------

def _toy_frame(n=96, d=5, classes=3, seed=0):
    pd = pytest.importorskip("pandas")
    r = np.random.default_rng(seed)
    X = r.normal(size=(n, d)).astype(np.float32)
    y = r.integers(0, classes, size=n)
    X[np.arange(n), y] += 2.5  # separable
    df = pd.DataFrame({f"f{i}": X[:, i] for i in range(d)})
    df["label"] = y
    return df, X, y


def test_estimator_fits_from_dataframe_columns():
    df, X, y = _toy_frame()
    model = nn.Sequential().add(nn.Linear(5, 3))
    est = DLClassifier(model, nn.CrossEntropyCriterion(), batch_size=32,
                       max_epoch=30, label_col="label",
                       optim_method=Adam(1e-2))
    fitted = est.fit(df)  # labels resolved from the label column
    acc = fitted.score(df)
    assert acc > 0.8, acc
    out = fitted.transform(df)
    assert "prediction" in out.columns
    assert "prediction" not in df.columns  # transform returns a COPY
    assert np.mean(np.asarray(out["prediction"]) == y) == acc


def test_estimator_explicit_feature_columns():
    df, X, y = _toy_frame()
    est = DLClassifier(nn.Sequential().add(nn.Linear(2, 3)),
                       nn.CrossEntropyCriterion(), batch_size=32,
                       max_epoch=2, features_col=["f0", "f1"])
    fitted = est.fit(df)
    assert fitted.predict(df).shape == (len(df),)


def test_early_stopping_plateau_ends_training():
    """With patience=2 and an EXACTLY constant val loss (lr=0 — the hardest
    plateau), training must end after ~patience+1 validations, not at
    max_epoch=200."""
    from bigdl_tpu.optim import SGD
    df, X, y = _toy_frame(n=64)
    est = DLClassifier(nn.Sequential().add(nn.Linear(5, 3)),
                       nn.CrossEntropyCriterion(), batch_size=32,
                       max_epoch=200,
                       optim_method=SGD(learning_rate=0.0))
    est.set_validation(X, y, early_stopping_patience=2)
    fitted = est.fit(X, y)
    assert fitted is not None
    epochs_run = est.optimizer_.optim_method.hyper["epoch"] - 1
    assert epochs_run <= 5, f"early stopping never fired: {epochs_run} epochs"


def test_plateau_trigger_semantics():
    from bigdl_tpu.optim import Trigger
    # with the validation-observation counter: constant values still count
    t = Trigger.plateau("val_loss", patience=2)
    assert not t({"val_loss": 1.0, "val_obs": 1})  # baseline
    assert not t({"val_loss": 1.0, "val_obs": 1})  # same tick: no-op
    assert not t({"val_loss": 1.0, "val_obs": 2})  # constant: bad 1
    assert t({"val_loss": 1.0, "val_obs": 3})      # constant: bad 2 -> fire
    # without a counter (external state dicts): value-change fallback
    t2 = Trigger.plateau("val_loss", patience=2, counter=None)
    assert not t2({"val_loss": 1.0})
    assert not t2({"val_loss": 0.5})   # improved
    assert not t2({"val_loss": 0.6})   # bad 1
    assert not t2({"val_loss": 0.6})   # unchanged: not a new observation
    assert t2({"val_loss": 0.7})       # bad 2 -> fire
    t3 = Trigger.plateau("score", patience=1, mode="max", counter=None)
    assert not t3({"score": 0.5})
    assert not t3({"score": 0.9})
    assert t3({"score": 0.8})


def test_plateau_trigger_latches_after_firing():
    """Once fired, plateau stays True: the driver polls end triggers at
    several points and a one-shot True could be consumed by the inner-loop
    check without ending training."""
    from bigdl_tpu.optim import Trigger
    t = Trigger.plateau("val_loss", patience=1)
    assert not t({"val_loss": 1.0, "val_obs": 1})
    assert t({"val_loss": 1.0, "val_obs": 2})   # fires
    assert t({"val_loss": 1.0, "val_obs": 2})   # latched, same tick
    assert t({"val_loss": 0.1, "val_obs": 3})   # latched even on improvement


@pytest.mark.slow  # CLI smoke via subprocess-scale work: slow lane
def test_cli_transformer_synthetic_smoke():
    """Train CLI drives the transformer LM workload (token-spec synthetic
    data, TimeDistributedCriterion, per-token Top1 validation)."""
    import sys
    from bigdl_tpu.models import run as run_cli
    argv_save = sys.argv
    try:
        sys.argv = ["run", "train", "--model", "transformer", "--synthetic",
                    "--class-num", "64", "--batch-size", "32",
                    "--max-epoch", "1", "--max-iteration", "3",
                    "--learning-rate", "0.003", "--optim", "adam"]
        opt = run_cli.main()
        assert opt.optim_method.hyper["neval"] > 3
    finally:
        sys.argv = argv_save


def test_serving_bench_tool_smoke(capsys):
    """tools/serving_bench runs all three decode paths and emits one JSON
    line (bench.py conventions); ratios are hardware-dependent so only the
    contract is asserted here."""
    import json

    from bigdl_tpu.tools.serving_bench import main

    out = main(["--d-model", "32", "--num-heads", "4", "--num-layers", "1",
                "--vocab", "64", "--max-len", "16", "--batch", "1",
                "--num-tokens", "4"])
    assert {r["path"] for r in out["results"]} == \
        {"full_fwd", "kv_cache", "kv_int8"}
    line = capsys.readouterr().out.strip().splitlines()[-1]
    parsed = json.loads(line)
    assert parsed["metric"] == "serving_decode_tokens_per_sec"
    assert all(r["tokens_per_sec"] > 0 for r in parsed["results"])
