"""Text pipeline tests (reference analog: dataset/text specs and the
models/rnn/Train.scala:49-96 pipeline)."""

import numpy as np
import pytest

from bigdl_tpu.dataset import (Dictionary, LabeledSentenceToSample,
                               SentenceBiPadding, SentenceSplitter,
                               SentenceTokenizer, TextToLabeledSentence)
from bigdl_tpu.dataset.text import SENTENCE_END, SENTENCE_START


def test_sentence_splitter():
    docs = ["One sentence. Two sentences! Three? yes.", "  single  "]
    out = list(SentenceSplitter()(iter(docs)))
    assert out[0] == ["One sentence.", "Two sentences!", "Three?", "yes."]
    assert out[1] == ["single"]


def test_tokenizer():
    toks = list(SentenceTokenizer()(iter(["Hello, world! It's fine."])))[0]
    assert toks == ["hello", ",", "world", "!", "it's", "fine", "."]


def test_bi_padding():
    out = list(SentenceBiPadding()(iter([["a", "b"]])))[0]
    assert out == [SENTENCE_START, "a", "b", SENTENCE_END]


def test_dictionary_ranking_and_unk():
    sents = [["a", "a", "a", "b", "b", "c"], ["a", "d"]]
    d = Dictionary(sents, vocab_size=2)
    assert d.vocab_size() == 3  # a, b + <unk>
    assert d.get_index("a") == 0
    assert d.get_index("b") == 1
    unk = d.get_index("zzz")
    assert unk == d.get_index("c") == d.word2index()[Dictionary.UNK]
    assert d.get_word(0) == "a"


def test_dictionary_save_load(tmp_path):
    d = Dictionary([["x", "y", "x"]])
    d.save(str(tmp_path))
    d2 = Dictionary.load(str(tmp_path))
    assert d2.word2index() == d.word2index()
    assert d2.index2word() == d.index2word()


def test_dictionary_unk_pinned_last():
    """PINNED contract: UNK is always the LAST index — models size their
    LookupTable as vocab_size() and a moving UNK would scramble
    embeddings between a trained checkpoint and its server."""
    d = Dictionary([["a", "a", "b", "c"]], vocab_size=2)
    assert d.unk_index() == d.vocab_size() - 1
    assert d.get_word(d.unk_index()) == Dictionary.UNK
    assert d.get_index("never-seen") == d.unk_index()


def test_dictionary_versioned_payload_and_unk_contract(tmp_path):
    """save() writes a versioned JSON payload through file_io; load()
    rejects unknown formats and UNK-contract violations loud."""
    import json
    import os

    d = Dictionary([["a", "b", "a"]])
    d.save(str(tmp_path))
    raw = json.load(open(os.path.join(str(tmp_path), "dictionary.json")))
    assert raw["format"] == "bigdl_tpu-dictionary-v1"
    assert raw["index2word"][-1] == Dictionary.UNK
    d2 = Dictionary.load(str(tmp_path))
    assert d2.unk_index() == d.unk_index() == d.vocab_size() - 1

    bad = dict(raw, format="somebody-elses-v9")
    open(os.path.join(str(tmp_path), "dictionary.json"), "w").write(
        json.dumps(bad))
    with pytest.raises(ValueError):
        Dictionary.load(str(tmp_path))

    nounk = dict(raw, index2word=["a", "b"])  # UNK not last: refuse
    open(os.path.join(str(tmp_path), "dictionary.json"), "w").write(
        json.dumps(nounk))
    with pytest.raises(ValueError):
        Dictionary.load(str(tmp_path))


def test_dictionary_legacy_bare_list_loads(tmp_path):
    """Pre-v1 files were a bare JSON list — they still load, under the
    same UNK-last check."""
    import json
    import os

    open(os.path.join(str(tmp_path), "dictionary.json"), "w").write(
        json.dumps(["x", "y", Dictionary.UNK]))
    d = Dictionary.load(str(tmp_path))
    assert d.index2word() == ["x", "y", Dictionary.UNK]
    assert d.get_index("x") == 0 and d.unk_index() == 2


def test_text_to_labeled_sentence():
    d = Dictionary([["a", "b", "c"]])
    ls = list(TextToLabeledSentence(d)(iter([["a", "b", "c"]])))[0]
    np.testing.assert_array_equal(ls.data, d.encode(["a", "b"]))
    np.testing.assert_array_equal(ls.label, d.encode(["b", "c"]))
    # too-short sentences are dropped
    assert list(TextToLabeledSentence(d)(iter([["a"]]))) == []


def test_labeled_sentence_to_sample_onehot_and_padding():
    d = Dictionary([["a", "b", "c"]])
    # `>>` == reference's `->` chaining (Transformer.scala:49)
    chain = TextToLabeledSentence(d) >> LabeledSentenceToSample(
        vocab_length=d.vocab_size(), fixed_data_length=5,
        fixed_label_length=5)
    s = list(chain(iter([["a", "b", "c"]])))[0]
    assert s.feature.shape == (5, d.vocab_size())
    assert s.feature[0, d.get_index("a")] == 1.0
    assert s.feature[3].sum() == 0.0  # padded rows are zero
    assert s.label.shape == (5,)


def test_label_padding_is_masked_by_criterion():
    # padded label positions (-1) must not contribute to the loss
    import jax.numpy as jnp
    from bigdl_tpu.nn import ClassNLLCriterion
    logp = jnp.log(jnp.array([[0.7, 0.3], [0.2, 0.8], [0.5, 0.5]]))
    full = ClassNLLCriterion()(logp, jnp.array([0.0, 1.0, 0.0]))
    padded = ClassNLLCriterion()(logp, jnp.array([0.0, 1.0, -1.0]))
    expected = -(np.log(0.7) + np.log(0.8)) / 2
    np.testing.assert_allclose(float(padded), expected, rtol=1e-6)
    assert float(full) != float(padded)


def test_full_char_rnn_pipeline_composes():
    corpus = ["the cat sat. the dog sat. the cat ran."]
    sentences = [s for doc in SentenceSplitter()(iter(corpus)) for s in doc]
    tokens = list(SentenceTokenizer()(iter(sentences)))
    tokens = list(SentenceBiPadding()(iter(tokens)))
    d = Dictionary(tokens, vocab_size=10)
    chain = (TextToLabeledSentence(d) >>
             LabeledSentenceToSample(fixed_data_length=8,
                                     fixed_label_length=8))
    samples = list(chain(iter(tokens)))
    assert len(samples) == 3
    for s in samples:
        assert s.feature.shape == (8,)
        assert s.label.shape == (8,)
        assert s.feature.dtype == np.int32
