"""Model-interop tests: caffe/tf/t7 roundtrips through our own writers and
readers, with forward-output equivalence where weights are carried.

Reference analog: BigDL's caffe/tf specs load fixture models and compare
layer outputs (utils/caffe and utils/tf test suites); .t7 roundtrips are the
TorchFile specs' job.  We use our savers to produce the fixtures — wire
compatibility is guaranteed by encoding the public schemas directly."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.interop import (load_caffe, load_t7, load_tf, save_caffe,
                               save_t7, save_tf)


def _forward(model, params, state, x):
    out, _ = model.apply(params, state, x, training=False)
    return np.asarray(out)


@pytest.fixture
def mlp():
    m = (nn.Sequential()
         .add(nn.Linear(12, 20))
         .add(nn.ReLU())
         .add(nn.Linear(20, 5))
         .add(nn.SoftMax()))
    params, state = m.init(jax.random.key(0))
    return m, params, state


@pytest.fixture
def convnet():
    m = (nn.Sequential()
         .add(nn.SpatialConvolution(3, 8, 3, 3, 1, 1, 1, 1))
         .add(nn.ReLU())
         .add(nn.SpatialMaxPooling(2, 2))
         .add(nn.SpatialConvolution(8, 4, 3, 3)))
    params, state = m.init(jax.random.key(1))
    return m, params, state


# ------------------------------------------------------------------- caffe

def test_caffe_mlp_roundtrip(tmp_path, mlp):
    model, params, state = mlp
    path = str(tmp_path / "mlp.caffemodel")
    save_caffe(model, params, path)
    loaded, lparams = load_caffe(path)
    x = jnp.asarray(np.random.default_rng(0).standard_normal((4, 12)),
                    jnp.float32)
    ref = _forward(model, params, state, x)
    got = _forward(loaded, lparams, loaded.state, x)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_caffe_convnet_roundtrip(tmp_path, convnet):
    model, params, state = convnet
    path = str(tmp_path / "conv.caffemodel")
    save_caffe(model, params, path)
    loaded, lparams = load_caffe(path)
    x = jnp.asarray(np.random.default_rng(1).standard_normal((2, 8, 8, 3)),
                    jnp.float32)
    ref = _forward(model, params, state, x)
    got = _forward(loaded, lparams, loaded.state, x)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


def test_caffe_shape_mismatch_raises(tmp_path, mlp):
    model, params, state = mlp
    path = str(tmp_path / "bad.caffemodel")
    save_caffe(model, params, path)
    # a bias blob whose size disagrees with the layer must fail loud
    # (reference: CaffeLoader.copyParameters raises on mismatch)
    from bigdl_tpu.interop.caffe import CaffeLoader
    loader = CaffeLoader(path)
    loader.layers[0].blobs[1] = loader.layers[0].blobs[1][:7]
    with pytest.raises(ValueError):
        loader.build()


# ---------------------------------------------------------------------- tf

def test_tf_mlp_roundtrip(tmp_path, mlp):
    model, params, state = mlp
    path = str(tmp_path / "mlp.pb")
    save_tf(model, params, path)
    loaded, lparams = load_tf(path)
    x = jnp.asarray(np.random.default_rng(2).standard_normal((4, 12)),
                    jnp.float32)
    ref = _forward(model, params, state, x)
    got = _forward(loaded, lparams, loaded.state, x)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_tf_conv_same_padding_roundtrip(tmp_path):
    m = (nn.Sequential()
         .add(nn.SpatialConvolution(3, 6, 3, 3, 1, 1, -1, -1))  # SAME
         .add(nn.ReLU()))
    params, state = m.init(jax.random.key(3))
    path = str(tmp_path / "conv.pb")
    save_tf(m, params, path)
    loaded, lparams = load_tf(path)
    x = jnp.asarray(np.random.default_rng(3).standard_normal((2, 7, 7, 3)),
                    jnp.float32)
    ref = _forward(m, params, state, x)
    got = _forward(loaded, lparams, loaded.state, x)
    assert ref.shape == (2, 7, 7, 6)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


def test_tf_conv_reshape_linear_roundtrip(tmp_path):
    # conv (explicit symmetric padding -> SAME), pool, flatten, linear:
    # the full LeNet-ish shape chain incl. fused BiasAdds referenced by
    # downstream nodes
    m = (nn.Sequential()
         .add(nn.SpatialConvolution(2, 8, 3, 3, 1, 1, 1, 1))
         .add(nn.ReLU())
         .add(nn.SpatialMaxPooling(2, 2))
         .add(nn.Reshape((8 * 3 * 3,)))
         .add(nn.Linear(72, 4)))
    params, state = m.init(jax.random.key(5))
    path = str(tmp_path / "lenetish.pb")
    save_tf(m, params, path)
    loaded, lparams = load_tf(path)
    x = jnp.asarray(np.random.default_rng(8).standard_normal((3, 6, 6, 2)),
                    jnp.float32)
    ref = _forward(m, params, state, x)
    got = _forward(loaded, lparams, loaded.state, x)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


def test_tf_same_pool_roundtrip(tmp_path):
    # SAME-padded pooling must survive the roundtrip (loader maps SAME to
    # our pad=-1; TF AvgPool semantics exclude padding from the divisor)
    m = (nn.Sequential()
         .add(nn.SpatialConvolution(2, 4, 3, 3, 1, 1, -1, -1))
         .add(nn.SpatialMaxPooling(3, 3, 2, 2, -1, -1))
         .add(nn.ReLU()))
    params, state = m.init(jax.random.key(9))
    path = str(tmp_path / "samepool.pb")
    save_tf(m, params, path)
    loaded, lparams = load_tf(path)
    x = jnp.asarray(np.random.default_rng(9).standard_normal((2, 7, 7, 2)),
                    jnp.float32)
    ref = _forward(m, params, state, x)
    assert ref.shape == (2, 4, 4, 4)  # ceil(7/2) = 4 (TF SAME)
    got = _forward(loaded, lparams, loaded.state, x)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


def test_loaded_module_backward_works(tmp_path, mlp):
    # loaders must attach grads so the stateful facade (forward/backward/
    # get_parameters) works on a loaded model
    model, params, state = mlp
    model.params, model.state = params, state
    p = str(tmp_path / "m.bigdl")
    model.save(p)
    loaded = nn.Module.load(p)
    x = jnp.ones((2, 12), jnp.float32)
    out = loaded.forward(x)
    loaded.backward(x, jnp.ones_like(out))
    ws, gs = loaded.get_parameters()
    assert len(ws) == len(gs) > 0


def test_tf_save_rejects_unrepresentable_padding(tmp_path):
    m = nn.Sequential().add(nn.SpatialConvolution(2, 4, 3, 3, 2, 2, 1, 1))
    params, _ = m.init(jax.random.key(6))
    with pytest.raises(ValueError):
        save_tf(m, params, str(tmp_path / "bad.pb"))


def test_tf_graphdef_parsed_by_real_tensorflow_if_available(tmp_path, mlp):
    # if the image has tensorflow, cross-validate our GraphDef bytes
    tf = pytest.importorskip("tensorflow")
    model, params, state = mlp
    path = str(tmp_path / "x.pb")
    save_tf(model, params, path)
    gd = tf.compat.v1.GraphDef()
    gd.ParseFromString(open(path, "rb").read())
    ops = [n.op for n in gd.node]
    assert "MatMul" in ops and "Softmax" in ops


# ---------------------------------------------------------------------- t7

def test_t7_scalar_table_roundtrip(tmp_path):
    obj = {"lr": 0.5, "name": "sgd", "nested": {"flag": True, "none": None},
           "arr": [1, 2, 3]}
    p = str(tmp_path / "o.t7")
    save_t7(obj, p)
    got = load_t7(p)
    assert got["lr"] == 0.5
    assert got["name"] == "sgd"
    assert got["nested"]["flag"] is True
    assert got["nested"]["none"] is None
    # contiguous 1..n integer keys come back as a Python list (Lua array)
    assert got["arr"] == [1, 2, 3]


def test_t7_tensor_roundtrip(tmp_path):
    rng = np.random.default_rng(4)
    for dtype in (np.float32, np.float64, np.int32, np.int64):
        arr = (rng.standard_normal((3, 4, 5)) * 10).astype(dtype)
        p = str(tmp_path / f"{np.dtype(dtype).name}.t7")
        save_t7(arr, p)
        got = load_t7(p)
        np.testing.assert_array_equal(got, arr)
        assert got.dtype == arr.dtype


def test_t7_params_tree_roundtrip(tmp_path, mlp):
    model, params, state = mlp
    tree = [{k: np.asarray(v) for k, v in p.items()} for p in params]
    p = str(tmp_path / "params.t7")
    save_t7(tree, p)
    got = load_t7(p)
    for orig, back in zip(tree, got):
        for k in orig:
            np.testing.assert_allclose(back[k], orig[k])


def test_torch_module_roundtrip(tmp_path, mlp):
    from bigdl_tpu.interop import load_torch_module, save_torch_module
    model, params, state = mlp
    p = str(tmp_path / "model.t7")
    save_torch_module(model, params, p)
    loaded, lparams = load_torch_module(p)
    x = jnp.asarray(np.random.default_rng(5).standard_normal((4, 12)),
                    jnp.float32)
    ref = _forward(model, params, state, x)
    got = _forward(loaded, lparams, loaded.state, x)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_torch_module_conv_roundtrip(tmp_path, convnet):
    from bigdl_tpu.interop import load_torch_module, save_torch_module
    model, params, state = convnet
    p = str(tmp_path / "conv.t7")
    save_torch_module(model, params, p)
    loaded, lparams = load_torch_module(p)
    x = jnp.asarray(np.random.default_rng(6).standard_normal((2, 8, 8, 3)),
                    jnp.float32)
    ref = _forward(model, params, state, x)
    got = _forward(loaded, lparams, loaded.state, x)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


def test_native_module_save_load(tmp_path, mlp):
    model, params, state = mlp
    model.params, model.state = params, state
    p = str(tmp_path / "model.bigdl")
    model.save(p)
    loaded = type(model).load(p)
    x = jnp.asarray(np.random.default_rng(7).standard_normal((4, 12)),
                    jnp.float32)
    ref = _forward(model, params, state, x)
    got = _forward(loaded, loaded.params, loaded.state, x)
    np.testing.assert_allclose(got, ref, rtol=1e-6)
    # original facade must be intact after save (weights re-attached)
    assert model.params is not None


def test_t7_read_by_torch_if_available(tmp_path):
    torchfile_mod = pytest.importorskip("torchfile")
    arr = np.arange(12, dtype=np.float32).reshape(3, 4)
    p = str(tmp_path / "x.t7")
    save_t7({"w": arr, "n": 3}, p)
    got = torchfile_mod.load(p)
    np.testing.assert_array_equal(got[b"w"], arr)
    assert got[b"n"] == 3


# ------------------------------------------- caffe depth (round-2 additions)

def test_caffe_fc_layout_semantics():
    """The FC column permutation must match real caffe semantics: caffe
    flattens NCHW (C,H,W); ours flattens NHWC (H,W,C).  W_caffe applied to
    a CHW-flat vector must equal the permuted weight applied to the
    HWC-flat vector (round-1 advisor finding)."""
    from bigdl_tpu.interop.caffe import _fc_cols_chw_to_hwc, _fc_cols_hwc_to_chw
    rng = np.random.default_rng(0)
    C, H, W, out = 3, 4, 5, 7
    x = rng.standard_normal((C, H, W)).astype(np.float32)
    w_caffe = rng.standard_normal((out, C * H * W)).astype(np.float32)
    y_caffe = w_caffe @ x.reshape(-1)                      # CHW flatten
    w_ours = _fc_cols_chw_to_hwc(w_caffe, C)
    y_ours = w_ours @ x.transpose(1, 2, 0).reshape(-1)     # HWC flatten
    np.testing.assert_allclose(y_ours, y_caffe, rtol=1e-5)
    np.testing.assert_allclose(_fc_cols_hwc_to_chw(w_ours, C), w_caffe)


def test_caffe_lenet_roundtrip(tmp_path):
    """LeNet crosses a conv->Flatten->InnerProduct boundary with H*W > 1,
    so forward parity proves the FC layout permutation end-to-end."""
    from bigdl_tpu.models.lenet import LeNet5
    m = LeNet5(10)
    m.build(jax.random.key(4))
    path = str(tmp_path / "lenet.caffemodel")
    save_caffe(m, m.params, path, state=m.state)
    loaded, lparams = load_caffe(path)
    x = jnp.asarray(np.random.default_rng(4).standard_normal((2, 28, 28, 1)),
                    jnp.float32)
    ref = _forward(m, m.params, m.state, x)
    got = _forward(loaded, lparams, loaded.state, x)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


@pytest.mark.slow  # full-model caffe roundtrip: slow lane
def test_caffe_resnet_roundtrip(tmp_path):
    """ResNet-20/CIFAR: BatchNorm+Scale fold, ConcatTable->Eltwise residual
    branches, type-A shortcut (Concat + Power-as-MulConstant), pooling
    (reference: LayerConverter.scala's BN/Scale/Eltwise converters)."""
    from bigdl_tpu.models.resnet import ResNet
    m = ResNet(20, class_num=10, dataset="cifar10")
    m.build(jax.random.key(5))
    x = jnp.asarray(np.random.default_rng(5).standard_normal((2, 32, 32, 3)),
                    jnp.float32)
    # one training forward moves BN running stats off their init values so
    # the round-trip actually carries information
    _, trained_state = m.apply(m.params, m.state, x, training=True,
                               rng=jax.random.key(6))
    m.attach(m.params, trained_state)
    path = str(tmp_path / "resnet20.caffemodel")
    save_caffe(m, m.params, path, state=m.state)
    loaded, lparams = load_caffe(path)
    ref = _forward(m, m.params, m.state, x)
    got = _forward(loaded, lparams, loaded.state, x)
    np.testing.assert_allclose(got, ref, rtol=1e-3, atol=1e-3)


@pytest.mark.slow  # full-model caffe roundtrip: slow lane
def test_caffe_inception_roundtrip(tmp_path):
    """Inception-v1 (no aux): LRN, ceil-mode pooling, Concat towers,
    Dropout, global 7x7 avgpool + classifier."""
    from bigdl_tpu.models.inception import Inception_v1_NoAuxClassifier
    m = Inception_v1_NoAuxClassifier(10)
    m.build(jax.random.key(7))
    path = str(tmp_path / "inception.caffemodel")
    save_caffe(m, m.params, path, state=m.state)
    loaded, lparams = load_caffe(path)
    x = jnp.asarray(np.random.default_rng(7).standard_normal((1, 224, 224, 3)),
                    jnp.float32)
    ref = _forward(m, m.params, m.state, x)
    got = _forward(loaded, lparams, loaded.state, x)
    np.testing.assert_allclose(got, ref, rtol=1e-3, atol=1e-3)


def test_caffe_deconv_eltwise_max_roundtrip(tmp_path):
    m = (nn.Sequential()
         .add(nn.SpatialFullConvolution(3, 6, 3, 3, 2, 2, 1, 1))
         .add(nn.ReLU())
         .add(nn.ConcatTable()
              .add(nn.SpatialConvolution(6, 4, 1, 1))
              .add(nn.SpatialConvolution(6, 4, 1, 1)))
         .add(nn.CMaxTable()))
    m.build(jax.random.key(8))
    path = str(tmp_path / "deconv.caffemodel")
    save_caffe(m, m.params, path, state=m.state)
    loaded, lparams = load_caffe(path)
    x = jnp.asarray(np.random.default_rng(8).standard_normal((2, 6, 6, 3)),
                    jnp.float32)
    ref = _forward(m, m.params, m.state, x)
    got = _forward(loaded, lparams, loaded.state, x)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


def test_caffe_standalone_scale_power(tmp_path):
    m = (nn.Sequential()
         .add(nn.SpatialConvolution(3, 4, 3, 3, 1, 1, 1, 1))
         .add(nn.Scale((4,)))
         .add(nn.Power(2.0, 0.5, 1.0)))
    m.build(jax.random.key(9))
    path = str(tmp_path / "scale.caffemodel")
    save_caffe(m, m.params, path, state=m.state)
    loaded, lparams = load_caffe(path)
    x = jnp.asarray(np.random.default_rng(9).standard_normal((2, 5, 5, 3)),
                    jnp.float32)
    ref = _forward(m, m.params, m.state, x)
    got = _forward(loaded, lparams, loaded.state, x)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


def test_caffe_unsupported_raises_unless_permissive(tmp_path, mlp):
    model, params, state = mlp
    path = str(tmp_path / "unk.caffemodel")
    save_caffe(model, params, path)
    from bigdl_tpu.interop.caffe import CaffeLoader
    loader = CaffeLoader(path)
    loader.layers[0].type = "MVN"  # a type we do not convert
    with pytest.raises(ValueError):
        loader.build()
    loader2 = CaffeLoader(path, permissive=True)
    loader2.layers[0].type = "MVN"
    loader2.build()  # maps to Identity with a warning


def test_torch_lenet_roundtrip(tmp_path):
    """LeNet through the .t7 codec: exercises the NCHW (C,H,W) <-> NHWC
    (H,W,C) FC-column permutation and 3-D reshape transposition."""
    from bigdl_tpu.interop.torchfile import (load_torch_module,
                                             save_torch_module)
    from bigdl_tpu.models.lenet import LeNet5
    m = LeNet5(10)
    m.build(jax.random.key(10))
    path = str(tmp_path / "lenet.t7")
    save_torch_module(m, m.params, path)
    loaded, lparams = load_torch_module(path)
    x = jnp.asarray(np.random.default_rng(10).standard_normal((2, 28, 28, 1)),
                    jnp.float32)
    ref = _forward(m, m.params, m.state, x)
    got = _forward(loaded, lparams, loaded.state, x)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


def test_caffe_load_then_save_roundtrip(tmp_path):
    """load_caffe returns a Graph; persisting that Graph again must work
    (load -> modify -> save is the reference CaffePersister use case)."""
    from bigdl_tpu.models.lenet import LeNet5
    m = LeNet5(10)
    m.build(jax.random.key(11))
    p1 = str(tmp_path / "l1.caffemodel")
    save_caffe(m, m.params, p1, state=m.state)
    g, gp = load_caffe(p1)
    p2 = str(tmp_path / "l2.caffemodel")
    save_caffe(g, gp, p2, state=g.state)
    g2, gp2 = load_caffe(p2)
    x = jnp.asarray(np.random.default_rng(11).standard_normal((2, 28, 28, 1)),
                    jnp.float32)
    ref = _forward(m, m.params, m.state, x)
    got = _forward(g2, gp2, g2.state, x)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


# ------------------------------------------------ tf depth (round-2 additions)

def test_tf_batchnorm_roundtrip(tmp_path):
    """FusedBatchNormV3 save/load with running stats."""
    m = (nn.Sequential()
         .add(nn.SpatialConvolution(3, 8, 3, 3, 1, 1, -1, -1))
         .add(nn.SpatialBatchNormalization(8))
         .add(nn.ReLU()))
    m.build(jax.random.key(12))
    x = jnp.asarray(np.random.default_rng(12).standard_normal((2, 8, 8, 3)),
                    jnp.float32)
    _, st = m.apply(m.params, m.state, x, training=True,
                    rng=jax.random.key(13))
    m.attach(m.params, st)
    path = str(tmp_path / "bn.pb")
    save_tf(m, m.params, path, state=m.state)
    loaded, lparams = load_tf(path)
    ref = _forward(m, m.params, m.state, x)
    got = _forward(loaded, lparams, loaded.state, x)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


def test_tf_decomposed_bn_const_folding(tmp_path):
    """A frozen decomposed BatchNorm (Mul/Add over Rsqrt(var+eps) const
    arithmetic) must import via constant folding and match numpy
    (reference: TensorflowToBigDL.scala's BN patterns)."""
    from bigdl_tpu.interop.tensorflow import (_const_node, _node_def,
                                              load_tf as _load)
    from bigdl_tpu.utils import pbwire
    rng = np.random.default_rng(13)
    c = 5
    gamma = rng.standard_normal(c).astype(np.float32)
    beta = rng.standard_normal(c).astype(np.float32)
    mean = rng.standard_normal(c).astype(np.float32)
    var = np.abs(rng.standard_normal(c)).astype(np.float32) + 0.5
    eps = np.float32(1e-3)
    out = bytearray()
    out += _node_def("input", "Placeholder", [],
                     {"dtype": pbwire.field_varint(6, 1)})
    out += _const_node("var", var)
    out += _const_node("eps", np.array([eps], np.float32))
    out += _const_node("gamma", gamma)
    out += _const_node("beta", beta)
    out += _const_node("mean", mean)
    out += _node_def("add_eps", "Add", ["var", "eps"])
    out += _node_def("rsqrt", "Rsqrt", ["add_eps"])
    out += _node_def("scale", "Mul", ["rsqrt", "gamma"])
    out += _node_def("scaled", "Mul", ["input", "scale"])
    out += _node_def("mean_scale", "Mul", ["mean", "scale"])
    out += _node_def("offset", "Sub", ["beta", "mean_scale"])
    out += _node_def("output", "Add", ["scaled", "offset"])
    path = str(tmp_path / "dbn.pb")
    with open(path, "wb") as f:
        f.write(out)
    loaded, lparams = _load(path)
    x = rng.standard_normal((2, 4, 4, c)).astype(np.float32)
    got = _forward(loaded, lparams, loaded.state, jnp.asarray(x))
    scale = gamma / np.sqrt(var + eps)
    want = x * scale + (beta - mean * scale)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_tf_concat_axis(tmp_path):
    """ConcatV2 must honor its axis input (round-1 advisor: it was ignored
    and always joined on -1)."""
    from bigdl_tpu.interop.tensorflow import _const_node, _node_def
    from bigdl_tpu.utils import pbwire
    out = bytearray()
    out += _node_def("input", "Placeholder", [],
                     {"dtype": pbwire.field_varint(6, 1)})
    out += _node_def("r", "Relu", ["input"])
    out += _const_node("axis", np.array(1, np.int32), 3)
    out += _node_def("cat", "ConcatV2", ["input", "r", "axis"],
                     {"N": pbwire.field_varint(3, 2)})
    path = str(tmp_path / "cat.pb")
    with open(path, "wb") as f:
        f.write(out)
    loaded, lparams = load_tf(path)
    x = np.random.default_rng(14).standard_normal((2, 3, 4, 5)).astype(
        np.float32)
    got = _forward(loaded, lparams, loaded.state, jnp.asarray(x))
    want = np.concatenate([x, np.maximum(x, 0)], axis=1)  # height concat
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_tf_unrolled_lstm_cell_import(tmp_path):
    """A BasicLSTMCell-style op graph (ConcatV2/MatMul/BiasAdd/Split/
    Sigmoid/Tanh/Mul/Add) imports as raw ops and computes a correct LSTM
    step (reference: TensorflowToBigDL.scala's LSTM subgraph pattern)."""
    from bigdl_tpu.interop.tensorflow import _const_node, _node_def
    from bigdl_tpu.utils import pbwire
    rng = np.random.default_rng(15)
    n_in, n_hid, b = 3, 4, 2
    W = rng.standard_normal((n_in + n_hid, 4 * n_hid)).astype(np.float32)
    bias = rng.standard_normal(4 * n_hid).astype(np.float32)
    x = rng.standard_normal((b, n_in)).astype(np.float32)
    h = rng.standard_normal((b, n_hid)).astype(np.float32)
    c = rng.standard_normal((b, n_hid)).astype(np.float32)

    out = bytearray()
    out += _node_def("x", "Placeholder", [],
                     {"dtype": pbwire.field_varint(6, 1)})
    out += _node_def("h", "Placeholder", [],
                     {"dtype": pbwire.field_varint(6, 1)})
    out += _node_def("c", "Placeholder", [],
                     {"dtype": pbwire.field_varint(6, 1)})
    out += _const_node("axis1", np.array(1, np.int32), 3)
    out += _node_def("xh", "ConcatV2", ["x", "h", "axis1"],
                     {"N": pbwire.field_varint(3, 2)})
    out += _const_node("W", W)
    out += _const_node("bvec", bias)
    out += _node_def("gates0", "MatMul", ["xh", "W"])
    out += _node_def("gates", "BiasAdd", ["gates0", "bvec"])
    out += _const_node("axis_s", np.array(1, np.int32), 3)
    out += _node_def("split", "Split", ["axis_s", "gates"],
                     {"num_split": pbwire.field_varint(3, 4)})
    # TF BasicLSTMCell order: i, j (candidate), f, o
    out += _node_def("ig", "Sigmoid", ["split:0"])
    out += _node_def("jg", "Tanh", ["split:1"])
    out += _node_def("fg", "Sigmoid", ["split:2"])
    out += _node_def("og", "Sigmoid", ["split:3"])
    out += _node_def("fc", "Mul", ["fg", "c"])
    out += _node_def("ij", "Mul", ["ig", "jg"])
    out += _node_def("c_new", "Add", ["fc", "ij"])
    out += _node_def("c_act", "Tanh", ["c_new"])
    out += _node_def("h_new", "Mul", ["og", "c_act"])
    path = str(tmp_path / "lstm.pb")
    with open(path, "wb") as f:
        f.write(out)
    loaded, lparams = load_tf(path, outputs="h_new")
    got = _forward(loaded, lparams, loaded.state,
                   [jnp.asarray(x), jnp.asarray(h), jnp.asarray(c)])

    def sig(v):
        return 1.0 / (1.0 + np.exp(-v))
    gates = np.concatenate([x, h], 1) @ W + bias
    i_, j_, f_, o_ = np.split(gates, 4, axis=1)
    c_new = sig(f_) * c + sig(i_) * np.tanh(j_)
    want = sig(o_) * np.tanh(c_new)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_tf_unsupported_raises_unless_permissive(tmp_path):
    from bigdl_tpu.interop.tensorflow import _node_def
    from bigdl_tpu.utils import pbwire
    out = bytearray()
    out += _node_def("input", "Placeholder", [],
                     {"dtype": pbwire.field_varint(6, 1)})
    out += _node_def("w", "WeirdOp", ["input"])
    path = str(tmp_path / "weird.pb")
    with open(path, "wb") as f:
        f.write(out)
    with pytest.raises(ValueError):
        load_tf(path)
    loaded, _ = load_tf(path, permissive=True)


def test_tf_saved_graph_executes_in_real_tensorflow(tmp_path):
    """Our GraphDef must not just parse — real TensorFlow must EXECUTE it
    with numeric parity (the true saver contract: the reference's saved
    graphs run under TF, utils/tf/TensorflowSaver.scala)."""
    tf = pytest.importorskip("tensorflow")
    m = (nn.Sequential()
         .add(nn.SpatialConvolution(2, 4, 3, 3, 1, 1, 1, 1))
         .add(nn.ReLU())
         .add(nn.SpatialMaxPooling(2, 2, 2, 2))
         .add(nn.Reshape((4 * 4 * 4,)))
         .add(nn.Linear(4 * 4 * 4, 5))
         .add(nn.SoftMax()))
    params, state = m.init(jax.random.key(11))
    x = np.random.default_rng(11).standard_normal((2, 8, 8, 2)) \
        .astype(np.float32)
    ref = np.asarray(_forward(m, params, state, jnp.asarray(x)))
    path = str(tmp_path / "convnet.pb")
    save_tf(m, params, path)

    gd = tf.compat.v1.GraphDef()
    gd.ParseFromString(open(path, "rb").read())
    g = tf.Graph()
    with g.as_default():
        tf.import_graph_def(gd, name="")
        inp = g.get_tensor_by_name("input:0")
        # last op's first output is the model head (saver emits topo order)
        out_t = g.get_operations()[-1].outputs[0]
        with tf.compat.v1.Session(graph=g) as sess:
            got = sess.run(out_t, {inp: x})
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)
