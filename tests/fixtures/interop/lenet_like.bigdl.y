|Fx/oe	QC+@
#s'-i_Y4l"W6q