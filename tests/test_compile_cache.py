"""Persistent XLA compilation cache (utils/platform.enable_compilation_cache).

The mitigation for this backend's pathological remote compiles
(docs/benchmarking.md): entries must be written to the configured dir and
reused across processes. Driven in subprocesses so the cache config lands
before any compile, as in real bench runs.
"""

import json
import os
import subprocess
import sys
import textwrap


def _run(cache_dir, repo):
    code = textwrap.dedent(f"""
        import json, os, sys, time
        import jax
        jax.config.update("jax_platforms", "cpu")
        sys.path.insert(0, {repo!r})
        os.environ["BIGDL_TPU_XLA_CACHE_DIR"] = {cache_dir!r}
        from bigdl_tpu.utils.platform import enable_compilation_cache
        path = enable_compilation_cache()
        assert path == {cache_dir!r}, path
        import jax.numpy as jnp
        @jax.jit
        def f(x):
            return jnp.tanh(x @ x) * 2 + 1
        x = jnp.ones((333, 333))
        t0 = time.time()
        float(f(x).sum())
        print(json.dumps({{"seconds": time.time() - t0}}))
    """)
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=180, env=env)
    assert r.returncode == 0, r.stderr[-1500:]
    return json.loads(r.stdout.strip().splitlines()[-1])


def test_cache_written_and_reused_across_processes(tmp_path):
    cache = str(tmp_path / "xla")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    _run(cache, repo)
    entries = os.listdir(cache)
    assert entries, "no cache entries written"
    mtimes = {e: os.path.getmtime(os.path.join(cache, e)) for e in entries}
    _run(cache, repo)  # second process: must REUSE, not rewrite, the entry
    # Only the "-cache" payload files hold the compiled executable; newer
    # jax (>=0.4.36 LRUCache) also writes a "-atime" bookkeeping sidecar
    # that is REWRITTEN on every hit by design — asserting on it would
    # fail exactly when the cache works.  Older jax wrote bare entries:
    # fall back to all jit_f files when no "-cache" suffix exists.
    jit_entries = [e for e in os.listdir(cache) if e.startswith("jit_f")]
    payload = [e for e in jit_entries if e.endswith("-cache")] or jit_entries
    assert payload
    for e in payload:
        assert os.path.getmtime(os.path.join(cache, e)) == mtimes.get(e), \
            "jit_f cache entry rewritten on warm run"


def test_cache_disabled_by_env(tmp_path):
    env_backup = dict(os.environ)
    try:
        os.environ["BIGDL_TPU_XLA_CACHE"] = "0"
        from bigdl_tpu.utils.platform import enable_compilation_cache
        assert enable_compilation_cache(str(tmp_path / "nope")) is None
        assert not (tmp_path / "nope").exists()
    finally:
        os.environ.clear()
        os.environ.update(env_backup)
