"""Tabular recommendation pipeline tests (dataset/recsys): stable
cross-process hashing, Criteo-style featurization layout, CorruptRecord
semantics for schema strays, seeded download-free generation, and shard
write / stream read with bounded quarantine — the recsys records ride
the SAME DataSet -> Transformer -> quarantine chain as every other
workload (the ISSUE-20 zero-workload-specific-pipeline claim)."""

import zlib

import numpy as np
import pytest

from bigdl_tpu.dataset import (DataSet, FeatureSpec, SampleToMiniBatch,
                               TabularToSample, cross_bucket, hash_bucket,
                               synthetic_criteo_records, write_criteo_shards)
from bigdl_tpu.utils.recordio import CorruptRecord


def _record(spec=None):
    spec = spec or FeatureSpec()
    return {"cats": [f"c{i}:v1" for i in range(spec.n_cat)],
            "tags": ["t:v1", "t:v2"],
            "dense": [float(i) for i in range(spec.n_dense)],
            "label": 1}


# ------------------------------------------------------------- hashing


def test_hash_bucket_stable_across_processes():
    """crc32-based, NOT hash(): the same value must land in the same
    bucket on every host/run (rank shards and bit-match oracles
    desynchronize otherwise)."""
    assert hash_bucket("c0:v7", 100) == \
        zlib.crc32("\x1fc0:v7".encode()) % 100
    assert hash_bucket("c0:v7", 100, salt="col3") == \
        zlib.crc32("col3\x1fc0:v7".encode()) % 100
    for v in range(50):
        assert 0 <= hash_bucket(f"v{v}", 17) < 17
    # the salt actually separates columns
    assert any(hash_bucket(f"v{v}", 1000, salt="a")
               != hash_bucket(f"v{v}", 1000, salt="b") for v in range(20))


def test_cross_bucket_order_sensitive():
    assert cross_bucket(("x", "y"), 4096) != cross_bucket(("y", "x"), 4096)
    assert 0 <= cross_bucket(("x", "y"), 64) < 64


# -------------------------------------------------------- feature spec


def test_feature_spec_layout():
    spec = FeatureSpec()
    assert spec.n_deep_slots == 12 and spec.n_wide == 7
    assert spec.input_dim == 12 + 7 + 4
    # every one-hot column owns a disjoint row range of the ONE shared
    # deep table (so a single 1/N-sharded LookupTable serves them all)
    for c in range(spec.n_cat):
        rid = spec.deep_id(c, "some:value")
        assert rid // spec.stride == c
    assert spec.tag_id("t:v1") // spec.stride == spec.n_cat


def test_feature_spec_validation():
    with pytest.raises(ValueError):
        FeatureSpec(n_cat=0)
    with pytest.raises(ValueError):
        FeatureSpec(cross_pairs=[(0, 99)])
    with pytest.raises(ValueError):
        FeatureSpec(n_cat=64, multihot_slots=1, deep_buckets=32)


# --------------------------------------------------------- featurize


def test_featurize_layout_and_determinism():
    spec = FeatureSpec()
    s1 = spec.featurize(_record(spec))
    s2 = spec.featurize(_record(spec))
    np.testing.assert_array_equal(s1.feature, s2.feature)
    assert s1.feature.shape == (spec.input_dim,)
    assert s1.feature.dtype == np.float32
    assert s1.label.dtype == np.int32 and int(s1.label) == 1
    # 2 tags fill 2 multihot slots; the rest pad with -1 (model masks)
    slots = s1.feature[spec.n_cat:spec.n_deep_slots]
    assert np.sum(slots >= 0) == 2 and np.sum(slots == -1.0) == 2
    # dense floats are log1p-compressed
    np.testing.assert_allclose(
        s1.feature[spec.n_deep_slots + spec.n_wide:],
        np.log1p(np.arange(spec.n_dense, dtype=np.float64)), rtol=1e-6)


def test_featurize_schema_strays_raise_corrupt_record():
    spec = FeatureSpec()
    bad_missing = _record(spec)
    del bad_missing["cats"]
    bad_arity = _record(spec)
    bad_arity["dense"] = bad_arity["dense"][:-1]
    bad_value = _record(spec)
    bad_value["dense"] = ["not-a-number"] * spec.n_dense
    for bad in (bad_missing, bad_arity, bad_value, "not a dict", None):
        with pytest.raises(CorruptRecord):
            spec.featurize(bad)


# ---------------------------------------------------------- generator


def test_generator_seeded_and_learnable_labels():
    a = list(synthetic_criteo_records(64, seed=7))
    b = list(synthetic_criteo_records(64, seed=7))
    assert a == b  # byte-identical stream per seed, no download
    labels = [r["label"] for r in a]
    assert 0 < sum(labels) < len(labels)  # both classes present
    assert list(synthetic_criteo_records(8, seed=8)) != a[:8]


# ------------------------------------------- shards + streaming chain


def test_shards_stream_through_generic_chain(tmp_path):
    spec = FeatureSpec()
    paths = write_criteo_shards(str(tmp_path / "criteo.bd"), 64, shards=4,
                                seed=3, spec=spec)
    assert len(paths) == 4
    ds = DataSet.record_stream(sorted(paths)).transform(
        TabularToSample(spec) >> SampleToMiniBatch(16, drop_last=True))
    batches = list(ds.data(train=False))
    assert len(batches) == 4
    for mb in batches:
        assert mb.input.shape == (16, spec.input_dim)
        assert mb.target.shape in ((16,), (16, 1))  # gather_rows keeps
        # scalar labels as one trailing unit axis (same as the LeNet e2e
        # chain; ClassNLLCriterion squeezes it)
    # byte-identical to in-memory featurization of the same seed
    # (write_records round-robins over shards, so compare as a SET of
    # feature rows, order-free)
    got = sorted(tuple(map(float, row)) for mb in batches
                 for row in np.asarray(mb.input))
    want = sorted(tuple(map(float, spec.featurize(r).feature)) for r in
                  synthetic_criteo_records(64, seed=3, spec=spec))
    assert got == want


def test_corrupt_shard_quarantined_under_budget(tmp_path):
    """On-disk rot in a recsys shard rides the SAME CRC/quarantine chain
    as every other record stream: skipped under budget, loud without."""
    from bigdl_tpu.dataset import StreamingRecordDataSet

    spec = FeatureSpec()
    [p] = write_criteo_shards(str(tmp_path / "c.bd"), 20, shards=1,
                              seed=1, spec=spec)
    data = bytearray(open(p, "rb").read())
    data[len(data) // 2] ^= 0xFF  # mid-payload bit flip
    open(p, "wb").write(bytes(data))
    ds = StreamingRecordDataSet([p], skip_budget=2)
    out = [spec.featurize(r) for r in ds.data(train=False)]
    assert ds.last_quarantined >= 1
    assert len(out) + ds.last_quarantined == 20
    with pytest.raises(CorruptRecord):
        list(StreamingRecordDataSet([p]).data(train=False))
