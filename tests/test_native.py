"""Native C++ runtime (csrc/) tests: crc32c parity, record IO roundtrips
through the native reader/writer, bf16 wire conversion.

Reference analog: BigDL's native layer tests exercised the MKL JNI wrapper
indirectly through tensor specs; the wire format had dedicated roundtrip
specs (test/.../parameters/FP16ParameterSpec.scala)."""

import os
import shutil
import struct
import subprocess

import numpy as np
import pytest

from bigdl_tpu.utils import native, recordio

_CSRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "csrc")


@pytest.fixture(scope="module", autouse=True)
def built_native():
    if not native.is_native_loaded():
        if shutil.which("make") is None or shutil.which("g++") is None:
            pytest.skip("no C++ toolchain")
        assert native.build(quiet=False), "native build failed"
    assert native.is_native_loaded()


def test_crc32c_known_vectors():
    # Standard CRC32C test vectors (RFC 3720 appendix B.4 style).
    assert native.crc32c(b"") == 0
    assert native.crc32c(b"123456789") == 0xE3069283
    assert native.crc32c(bytes(32)) == 0x8A9136AA


def test_crc32c_matches_python_fallback():
    rng = np.random.default_rng(0)
    for n in (1, 7, 8, 9, 63, 64, 1000, 65537):
        data = rng.integers(0, 256, n, dtype=np.uint8).tobytes()
        assert native.crc32c(data) == recordio._crc32c_py(data)


def test_crc32c_extend_streaming_parity():
    """The checkpoint framer's streaming continuation: chunked extend ==
    one-shot, native == pure-Python table loop, for chunk splits crossing
    the sliced-by-8 word boundary."""
    if native.crc32c_extend is None:
        pytest.skip("built library predates bigdl_crc32c_extend")
    rng = np.random.default_rng(1)
    data = rng.integers(0, 256, 4097, dtype=np.uint8).tobytes()
    for split in (0, 1, 7, 8, 9, 2048, 4096):
        a, b = data[:split], data[split:]
        got = native.crc32c_extend(native.crc32c_extend(0, a), b)
        assert got == native.crc32c(data)
    # pure-Python incremental path agrees (what runs without the .so)
    py = recordio._crc32c_py(data[:100])
    tb = recordio._table()
    c = py ^ 0xFFFFFFFF
    for byte in data[100:]:
        c = tb[(c ^ byte) & 0xFF] ^ (c >> 8)
    assert (c ^ 0xFFFFFFFF) == native.crc32c(data)


def test_masked_crc_matches():
    data = b"the quick brown fox"
    expected = recordio.masked_crc32c(data)
    got = native.lib.bigdl_masked_crc32c(data, len(data))
    assert got == expected


def test_record_roundtrip_native_to_python(tmp_path):
    p = str(tmp_path / "nat.bdr")
    payloads = [b"a", b"", b"x" * 10000, struct.pack("<I", 42)]
    with native.NativeRecordWriter(p) as w:
        for pl in payloads:
            w.write(pl)
    # Read back with the pure-Python framing parser.
    got = []
    with open(p, "rb") as f:
        while True:
            try:
                got.append(recordio.read_record_bytes(f))
            except EOFError:
                break
    assert got == payloads


def test_record_roundtrip_python_to_native(tmp_path):
    p = str(tmp_path / "py.bdr")
    payloads = [b"hello", b"world" * 321, b""]
    with open(p, "wb") as f:
        for pl in payloads:
            recordio.write_record_bytes(f, pl)
    with native.NativeRecordReader(p) as r:
        assert list(r) == payloads


def test_record_corruption_detected(tmp_path):
    p = str(tmp_path / "bad.bdr")
    with native.NativeRecordWriter(p) as w:
        w.write(b"payload-bytes")
    raw = bytearray(open(p, "rb").read())
    raw[14] ^= 0xFF  # flip a payload byte
    open(p, "wb").write(bytes(raw))
    with native.NativeRecordReader(p) as r:
        with pytest.raises(IOError):
            next(r)


def test_write_read_records_sharded(tmp_path):
    base = str(tmp_path / "data.bdr")
    recs = [{"i": i, "x": np.arange(i)} for i in range(23)]
    paths = recordio.write_records(base, recs, shards=4)
    assert len(paths) == 4
    got = sorted(recordio.read_records(base), key=lambda r: r["i"])
    assert [r["i"] for r in got] == list(range(23))
    np.testing.assert_array_equal(got[7]["x"], np.arange(7))


def test_bf16_roundtrip():
    rng = np.random.default_rng(1)
    x = rng.standard_normal(100000).astype(np.float32) * 100
    enc = native.f32_to_bf16(x)
    dec = native.bf16_to_f32(enc)
    # bf16 has 8 significand bits -> rel error < 2^-8.
    np.testing.assert_allclose(dec, x, rtol=2 ** -8)


def test_bf16_matches_jax_semantics():
    import jax.numpy as jnp
    x = np.linspace(-5, 5, 4097, dtype=np.float32)
    enc = native.f32_to_bf16(x)
    ref = np.asarray(jnp.asarray(x).astype(jnp.bfloat16)).view(np.uint16)
    np.testing.assert_array_equal(enc, ref)


def test_bf16_special_values():
    specials = np.array([np.inf, -np.inf, np.nan, -np.nan, 0.0, -0.0],
                        dtype=np.float32)
    for conv in (native.f32_to_bf16,):
        enc = conv(specials)
        dec = native.bf16_to_f32(enc)
        assert np.isposinf(dec[0]) and np.isneginf(dec[1])
        assert np.isnan(dec[2]) and np.isnan(dec[3])
        assert dec[4] == 0.0 and dec[5] == 0.0
    # sNaN payloads must stay NaN (not overflow to Inf) in both paths.
    snan = np.uint32(0x7F800001).view(np.float32).reshape(1)
    assert np.isnan(native.bf16_to_f32(native.f32_to_bf16(snan)))[0]
    # Force the pure-Python fallback path too.
    saved = native.lib
    native.lib = None
    try:
        enc_py = native.f32_to_bf16(np.concatenate([specials, snan]))
    finally:
        native.lib = saved
    np.testing.assert_array_equal(
        enc_py, native.f32_to_bf16(np.concatenate([specials, snan])))


def test_gather_rows():
    rng = np.random.default_rng(2)
    rows = [rng.standard_normal((3, 5)).astype(np.float32) for _ in range(9)]
    np.testing.assert_array_equal(native.gather_rows(rows), np.stack(rows))
    big = [rng.standard_normal(40000).astype(np.float32) for _ in range(4)]
    np.testing.assert_array_equal(native.gather_rows(big), np.stack(big))


def test_reduce_sum_f32():
    rng = np.random.default_rng(3)
    bufs = [rng.standard_normal(70001).astype(np.float32) for _ in range(5)]
    got = native.reduce_sum_f32(bufs)
    np.testing.assert_allclose(got, np.sum(bufs, axis=0), rtol=1e-5)
    one = native.reduce_sum_f32(bufs[:1])
    np.testing.assert_array_equal(one, bufs[0])


@pytest.mark.slow  # writes+scans a multi-GB record: slow lane
def test_truncated_large_length_record(tmp_path):
    # A header that claims an 8 GB payload but passes its own CRC must yield
    # a catchable IOError, not a bad_alloc abort through the FFI.
    import struct as _s
    p = str(tmp_path / "trunc.bdr")
    header = _s.pack("<Q", 8 << 30)
    with open(p, "wb") as f:
        f.write(header)
        f.write(_s.pack("<I", recordio.masked_crc32c(header)))
    with native.NativeRecordReader(p) as r:
        with pytest.raises(IOError):
            next(r)


def test_num_threads_api():
    native.set_num_threads(3)
    assert native.get_num_threads() == 3
    native.set_num_threads(os.cpu_count() or 1)


def test_make_build_is_idempotent():
    rc = subprocess.run(["make", "-C", _CSRC, "-q"],
                        capture_output=True).returncode
    assert rc in (0, 1)  # 0 = up to date; 1 = would rebuild (still fine)


class TestNativePrefetch:
    """csrc/prefetch.cc: multithreaded shard reader behind
    utils.native.NativePrefetchReader and DataSet.record_files(num_threads)."""

    def _write_shards(self, tmp_path, n_shards=6, per_shard=40):
        import pickle
        from bigdl_tpu.utils.recordio import write_records
        paths, expect = [], []
        for s in range(n_shards):
            p = str(tmp_path / f"shard-{s:03d}.bd")
            recs = [f"shard{s}-rec{i}" * (i % 7 + 1)
                    for i in range(per_shard)]
            write_records(p, recs)
            expect.extend(pickle.dumps(r, pickle.HIGHEST_PROTOCOL)
                          for r in recs)
            paths.append(p)
        return paths, expect

    def test_reads_exact_multiset(self, tmp_path):
        from bigdl_tpu.utils import native
        if not native.is_native_loaded():
            pytest.skip("native library not built")
        paths, expect = self._write_shards(tmp_path)
        with native.NativePrefetchReader(paths, num_threads=4,
                                         capacity=16) as r:
            got = list(r)
        assert sorted(got) == sorted(expect)
        # per-shard order is preserved even though shards interleave
        for s, p in enumerate(paths):
            prefix = f"shard{s}-".encode()
            mine = [g for g in got if g.startswith(prefix)]
            assert mine == [e for e in expect if e.startswith(prefix)]

    def test_more_threads_than_shards(self, tmp_path):
        from bigdl_tpu.utils import native
        if not native.is_native_loaded():
            pytest.skip("native library not built")
        paths, expect = self._write_shards(tmp_path, n_shards=2, per_shard=5)
        with native.NativePrefetchReader(paths, num_threads=16) as r:
            assert sorted(list(r)) == sorted(expect)

    def test_missing_shard_raises(self, tmp_path):
        from bigdl_tpu.utils import native
        if not native.is_native_loaded():
            pytest.skip("native library not built")
        paths, _ = self._write_shards(tmp_path, n_shards=2, per_shard=3)
        paths.append(str(tmp_path / "missing.bd"))
        with native.NativePrefetchReader(paths, num_threads=2) as r:
            # the error latch guarantees IOError, never a silent clean end —
            # a regression that skips unreadable shards must fail here
            with pytest.raises(IOError):
                while True:
                    next(r)

    def test_early_close_does_not_hang(self, tmp_path):
        from bigdl_tpu.utils import native
        if not native.is_native_loaded():
            pytest.skip("native library not built")
        paths, _ = self._write_shards(tmp_path, n_shards=4, per_shard=200)
        r = native.NativePrefetchReader(paths, num_threads=4, capacity=4)
        next(r)  # consume one record, leave producers blocked on the ring
        r.close()  # must join all workers without deadlock

    def test_record_files_num_threads(self, tmp_path):
        import pickle
        from bigdl_tpu.dataset import DataSet
        paths, expect = self._write_shards(tmp_path, n_shards=3,
                                           per_shard=10)
        ds = DataSet.record_files(paths, num_threads=4)
        seq = DataSet.record_files(paths)
        objs = sorted(pickle.loads(b) for b in expect)
        assert sorted(ds.records) == sorted(seq.records) == objs
