"""Regression tests for the round-3 advisor findings (ADVICE.md)."""

import numpy as np
import jax
import jax.numpy as jnp

import bigdl_tpu.nn as nn
from bigdl_tpu.nn.module import scale_epoch


def test_direct_scale_assignment_bumps_epoch():
    """ADVICE #1: m.scale_w = x (no setter) must invalidate cached trees."""
    lin = nn.Linear(4, 3)
    lin.build(jax.random.PRNGKey(0))
    assert lin._grad_scale_tree() is None  # all-ones fast path, cached
    before = scale_epoch()
    lin.scale_w = 2.0  # direct attribute assignment, not set_scale_w
    assert scale_epoch() > before
    tree = lin._grad_scale_tree()
    assert tree is not None
    assert float(tree["weight"]) == 2.0 and float(tree["bias"]) == 1.0


def test_dense_hoist_cap(monkeypatch):
    """ADVICE #2: the HBM hoist cap applies to dense cells, and the fallback
    scan path computes the same values."""
    cell = nn.LSTM(8, 16)
    params, _ = cell.init(jax.random.PRNGKey(0))
    xs = jax.random.normal(jax.random.PRNGKey(1), (12, 4, 8))  # (T, B, I)

    proj = cell.project_inputs(params, xs)
    assert proj is not None  # under the default cap: hoisted

    monkeypatch.setenv("BIGDL_TPU_RNN_HOIST_MAX_ELEMENTS", "16")
    assert cell.project_inputs(params, xs) is None  # capped out
    # t == 1 exemption (Cell.step delegation must keep working)
    assert cell.project_inputs(params, xs[:1]) is not None

    rec_capped = nn.Recurrent().add(cell)
    y_capped = rec_capped.forward(jnp.swapaxes(xs, 0, 1))
    monkeypatch.delenv("BIGDL_TPU_RNN_HOIST_MAX_ELEMENTS")
    rec = nn.Recurrent().add(cell)
    rec.params = rec_capped.params
    rec.state = rec_capped.state
    y_hoisted = rec.forward(jnp.swapaxes(xs, 0, 1))
    np.testing.assert_allclose(np.asarray(y_capped), np.asarray(y_hoisted),
                               rtol=1e-5, atol=1e-6)


def test_preemption_armed_without_main_thread(tmp_path):
    """ADVICE #3: arming is derived from rank-consistent inputs, so a
    non-main thread (where signal.signal raises) still arms."""
    import threading

    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.optim import Optimizer, SGD, Trigger
    from bigdl_tpu.optim.trigger import Trigger as _T

    rng = np.random.default_rng(0)
    samples = [Sample.from_ndarray(rng.normal(size=(4,)).astype(np.float32),
                                   np.int32(rng.integers(0, 2)))
               for _ in range(16)]
    model = nn.Sequential().add(nn.Linear(4, 2)).add(nn.LogSoftMax())
    ds = DataSet.array(samples).transform(SampleToMiniBatch(8))
    opt = (Optimizer(model, ds, nn.ClassNLLCriterion())
           .set_optim_method(SGD(0.1))
           .set_end_when(Trigger.max_epoch(1))
           .set_checkpoint(str(tmp_path), _T.every_epoch()))
    armed = {}

    def run():
        opt.optimize()
        armed["value"] = opt._preemption_armed

    t = threading.Thread(target=run)
    t.start()
    t.join(120)
    assert armed.get("value") is True


def test_evaluator_peek_does_not_drop_generator_sample():
    """ADVICE #4: one-shot generator-backed datasets keep their first sample
    through Evaluator's batch-size autodetect peek."""
    from bigdl_tpu.dataset import Sample
    from bigdl_tpu.optim import Evaluator, Top1Accuracy

    n = 10
    rng = np.random.default_rng(1)
    feats = rng.normal(size=(n, 4)).astype(np.float32)
    labels = np.arange(n) % 2

    class OneShot:
        """Minimal dataset whose data() is a single-use generator."""

        def __init__(self):
            self._used = False

        def size(self):
            return n

        def transform(self, transformer):
            from bigdl_tpu.dataset import TransformedDataSet
            return TransformedDataSet(self, transformer)

        def data(self, train=False):
            assert not self._used, "one-shot source iterated twice"
            self._used = True
            return (Sample.from_ndarray(feats[i], np.int32(labels[i]))
                    for i in range(n))

    model = nn.Sequential().add(nn.Linear(4, 2)).add(nn.LogSoftMax())
    model.build(jax.random.PRNGKey(0))
    res = Evaluator(model).test(OneShot(), [Top1Accuracy()])
    counted = res[0][1].result()[1] if hasattr(res[0][1], "result") else None
    # every one of the n samples must be evaluated — the peeked one included
    assert int(getattr(res[0][1], "count", counted)) == n
