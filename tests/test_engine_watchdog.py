"""Engine.init device-discovery watchdog (BIGDL_TPU_DEVICE_TIMEOUT).

On a tunneled/remote TPU backend, jax.devices() blocks forever when the
accelerator service is unreachable (verified live against this image's
dead axon tunnel, 2026-07-31); the opt-in watchdog turns the silent hang
into an actionable TimeoutError.  Engine state is reset around every test
by conftest's autouse fixture.
"""

import time

import pytest

from bigdl_tpu.utils import engine as engine_mod
from bigdl_tpu.utils.engine import Engine


def test_transparent_on_healthy_backend(monkeypatch):
    import jax
    monkeypatch.setenv("BIGDL_TPU_DEVICE_TIMEOUT", "60")
    mesh = Engine.init()
    assert mesh.devices.size == jax.device_count()


def test_timeout_fires_on_hanging_backend(monkeypatch):
    monkeypatch.setenv("BIGDL_TPU_DEVICE_TIMEOUT", "0.2")

    class _HangingJax:
        @staticmethod
        def devices():
            time.sleep(30)
            return []

    monkeypatch.setattr(engine_mod, "jax", _HangingJax)
    t0 = time.time()
    with pytest.raises(TimeoutError, match="BIGDL_TPU_DEVICE_TIMEOUT"):
        Engine._discover_devices()
    assert time.time() - t0 < 5


def test_probe_exception_propagates(monkeypatch):
    monkeypatch.setenv("BIGDL_TPU_DEVICE_TIMEOUT", "5")

    class _FailingJax:
        @staticmethod
        def devices():
            raise RuntimeError("backend exploded")

    monkeypatch.setattr(engine_mod, "jax", _FailingJax)
    with pytest.raises(RuntimeError, match="backend exploded"):
        Engine._discover_devices()


def test_disabled_by_default(monkeypatch):
    """timeout <= 0 (the default) must not spawn a watchdog thread at all:
    multi-host init legitimately blocks until every process joins."""
    import jax
    monkeypatch.delenv("BIGDL_TPU_DEVICE_TIMEOUT", raising=False)
    devs = Engine._discover_devices()
    assert len(devs) == jax.device_count()


def test_invalid_timeout_value_raises(monkeypatch):
    """A typo'd value ('60s') must raise, not silently disable the guard —
    silent disablement reproduces exactly the hang the knob prevents."""
    monkeypatch.setenv("BIGDL_TPU_DEVICE_TIMEOUT", "60s")
    with pytest.raises(ValueError, match="not a number of seconds"):
        Engine._discover_devices()


def test_disabled_default_spawns_no_thread(monkeypatch):
    """timeout unset must take the direct path (multi-host init blocks in
    jax.devices() legitimately until all processes join — a probe thread
    there would be wrong), pinned by making Thread creation explode."""
    import threading
    import jax

    def boom(*a, **k):
        raise AssertionError("watchdog thread spawned with timeout unset")

    monkeypatch.delenv("BIGDL_TPU_DEVICE_TIMEOUT", raising=False)
    monkeypatch.setattr(threading, "Thread", boom)
    devs = Engine._discover_devices()
    assert len(devs) == jax.device_count()
