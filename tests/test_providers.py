"""Dataset providers (dataset/providers.py): IDX/CIFAR-binary/news20-dir
parsers against synthesized files in the genuine formats (reference:
pyspark/bigdl/dataset/{mnist,news20}.py parsing halves)."""

import gzip
import os
import struct

import numpy as np

from bigdl_tpu.dataset.providers import (load_cifar10_binary,
                                         load_labeled_text_dir, load_mnist)


def _write_idx_images(path, arr: np.ndarray, gz=False):
    header = struct.pack(">I", 0x0803) + b"".join(
        struct.pack(">I", d) for d in arr.shape)
    data = header + arr.astype(np.uint8).tobytes()
    (gzip.open(path, "wb") if gz else open(path, "wb")).write(data)


def _write_idx_labels(path, labels: np.ndarray, gz=False):
    data = struct.pack(">I", 0x0801) + struct.pack(">I", len(labels)) + \
        labels.astype(np.uint8).tobytes()
    (gzip.open(path, "wb") if gz else open(path, "wb")).write(data)


def test_mnist_idx_roundtrip(tmp_path):
    r = np.random.default_rng(0)
    imgs = r.integers(0, 256, size=(10, 28, 28)).astype(np.uint8)
    labels = r.integers(0, 10, size=10).astype(np.uint8)
    _write_idx_images(str(tmp_path / "train-images-idx3-ubyte.gz"), imgs,
                      gz=True)
    _write_idx_labels(str(tmp_path / "train-labels-idx1-ubyte.gz"), labels,
                      gz=True)
    samples = load_mnist(str(tmp_path), "train")
    assert len(samples) == 10
    assert samples[0].feature.shape == (28, 28, 1)
    np.testing.assert_allclose(samples[3].feature[..., 0],
                               imgs[3] / 255.0, rtol=1e-6)
    assert int(samples[3].label) == int(labels[3])


def test_cifar10_binary(tmp_path):
    r = np.random.default_rng(1)
    n = 6
    rows = np.zeros((n, 3073), np.uint8)
    rows[:, 0] = r.integers(0, 10, size=n)
    rows[:, 1:] = r.integers(0, 256, size=(n, 3072))
    rows[:3].tofile(str(tmp_path / "data_batch_1.bin"))
    rows[3:].tofile(str(tmp_path / "data_batch_2.bin"))
    samples = load_cifar10_binary(str(tmp_path), train=True)
    assert len(samples) == 6
    assert samples[0].feature.shape == (32, 32, 3)
    # CHW -> HWC: red channel of row 4 is bytes 1..1024
    expect_red = rows[4, 1:1025].reshape(32, 32) / 255.0
    np.testing.assert_allclose(samples[4].feature[..., 0], expect_red,
                               rtol=1e-6)
    assert int(samples[4].label) == int(rows[4, 0])


def test_labeled_text_dir(tmp_path):
    for cat, texts in (("alt.atheism", ["doc a", "doc b"]),
                       ("sci.space", ["rockets"])):
        os.makedirs(tmp_path / "news" / cat)
        for i, t in enumerate(texts):
            (tmp_path / "news" / cat / f"{i}.txt").write_text(t)
    docs, cats = load_labeled_text_dir(str(tmp_path / "news"))
    assert cats == ["alt.atheism", "sci.space"]
    assert ("rockets", 1) in docs and ("doc a", 0) in docs
    assert len(docs) == 3


def test_labeled_text_tarball(tmp_path):
    """Tarball whose top-level dir differs from the archive basename (the
    real news20 case) extracts once and loads."""
    import tarfile
    src = tmp_path / "corpus-src" / "20news-tiny"
    for cat, text in (("a", "alpha"), ("b", "beta")):
        os.makedirs(src / cat)
        (src / cat / "0.txt").write_text(text)
    tar_path = tmp_path / "news20.tar.gz"
    with tarfile.open(tar_path, "w:gz") as tf:
        tf.add(src, arcname="20news-tiny")
    docs, cats = load_labeled_text_dir(str(tar_path))
    assert cats == ["a", "b"] and len(docs) == 2
    # second call reuses the extraction (no error, same result)
    docs2, _ = load_labeled_text_dir(str(tar_path))
    assert docs2 == docs


def test_labeled_text_tarball_dot_prefixed_members(tmp_path):
    """GNU tar's './dir/...' member naming must not defeat top-dir
    detection or skip extraction."""
    import tarfile
    src = tmp_path / "src" / "corpus"
    os.makedirs(src / "x")
    (src / "x" / "0.txt").write_text("hello")
    tar_path = tmp_path / "c.tar.gz"
    with tarfile.open(tar_path, "w:gz") as tf:
        tf.add(src, arcname="./corpus")
    docs, cats = load_labeled_text_dir(str(tar_path))
    assert cats == ["x"] and docs == [("hello", 0)]


# ---------------------------------------------------------------------------
# fetch_file: the maybe_download role, on file_io's retry/backoff layer
# ---------------------------------------------------------------------------

def _memory_fixture(path, payload):
    import fsspec
    fsspec.filesystem("memory").pipe_file(path, payload)


def _zero_cost_retries():
    from bigdl_tpu.utils import file_io
    return file_io.set_retry_timebase(lambda: 0.0, lambda d: None)


def test_fetch_file_verifies_size_and_sha256(tmp_path):
    import hashlib
    from bigdl_tpu.dataset.providers import fetch_file

    payload = b"mnist-bytes" * 200
    _memory_fixture("/prov_f/a.bin", payload)
    dest = str(tmp_path / "a.bin")
    got = fetch_file("memory://prov_f/a.bin", dest,
                     expected_size=len(payload),
                     expected_sha256=hashlib.sha256(payload).hexdigest())
    assert got == dest
    assert open(dest, "rb").read() == payload
    # cached copy passing verification is reused (no tmp leftovers)
    fetch_file("memory://prov_f/a.bin", dest, expected_size=len(payload))
    assert not os.path.exists(dest + ".tmp")


def test_fetch_file_checksum_mismatch_fails_loud(tmp_path):
    from bigdl_tpu.dataset.providers import (DownloadIntegrityError,
                                             fetch_file)
    from bigdl_tpu.utils import file_io
    import pytest

    _memory_fixture("/prov_g/b.bin", b"payload")
    prev = _zero_cost_retries()
    try:
        with pytest.raises(DownloadIntegrityError, match="sha256 mismatch"):
            fetch_file("memory://prov_g/b.bin", str(tmp_path / "b.bin"),
                       expected_sha256="0" * 64)
    finally:
        file_io.set_retry_timebase(*prev)
    # a failed fetch must not leave a half-written destination behind
    assert not os.path.exists(str(tmp_path / "b.bin"))


def test_fetch_file_absorbs_transient_remote_faults(tmp_path):
    """Two injected fs.remote faults are retried below fetch_file — the
    reference's maybe_download never had backoff; this one rides
    file_io's."""
    import hashlib
    from bigdl_tpu.dataset.providers import fetch_file
    from bigdl_tpu.utils import chaos, file_io

    payload = b"flaky-store" * 50
    _memory_fixture("/prov_h/c.bin", payload)
    prev = _zero_cost_retries()
    try:
        with chaos.scoped("fs.remote=fail*2@1"):
            fetch_file("memory://prov_h/c.bin", str(tmp_path / "c.bin"),
                       expected_sha256=hashlib.sha256(payload).hexdigest())
    finally:
        file_io.set_retry_timebase(*prev)
    assert open(str(tmp_path / "c.bin"), "rb").read() == payload


def test_load_mnist_fetches_missing_files_from_source(tmp_path):
    """load_mnist(source=...) pulls the standard idx.gz names through
    fetch_file into the local directory, then parses as usual."""
    import hashlib
    import io

    r = np.random.default_rng(1)
    imgs = r.integers(0, 256, size=(6, 28, 28)).astype(np.uint8)
    labels = r.integers(0, 10, size=6).astype(np.uint8)
    buf_i, buf_l = io.BytesIO(), io.BytesIO()
    _write_idx_images(buf_i, imgs, gz=True)
    _write_idx_labels(buf_l, labels, gz=True)
    names = ("train-images-idx3-ubyte.gz", "train-labels-idx1-ubyte.gz")
    blobs = dict(zip(names, (buf_i.getvalue(), buf_l.getvalue())))
    for name, blob in blobs.items():
        _memory_fixture("/prov_mnist/" + name, blob)
    checksums = {n: hashlib.sha256(b).hexdigest()
                 for n, b in blobs.items()}
    samples = load_mnist(str(tmp_path), "train",
                         source="memory://prov_mnist",
                         checksums=checksums)
    assert len(samples) == 6
    assert int(samples[2].label) == int(labels[2])
    # the files landed locally: a second call parses without the source
    assert len(load_mnist(str(tmp_path), "train")) == 6
