"""Dataset providers (dataset/providers.py): IDX/CIFAR-binary/news20-dir
parsers against synthesized files in the genuine formats (reference:
pyspark/bigdl/dataset/{mnist,news20}.py parsing halves)."""

import gzip
import os
import struct

import numpy as np

from bigdl_tpu.dataset.providers import (load_cifar10_binary,
                                         load_labeled_text_dir, load_mnist)


def _write_idx_images(path, arr: np.ndarray, gz=False):
    header = struct.pack(">I", 0x0803) + b"".join(
        struct.pack(">I", d) for d in arr.shape)
    data = header + arr.astype(np.uint8).tobytes()
    (gzip.open(path, "wb") if gz else open(path, "wb")).write(data)


def _write_idx_labels(path, labels: np.ndarray, gz=False):
    data = struct.pack(">I", 0x0801) + struct.pack(">I", len(labels)) + \
        labels.astype(np.uint8).tobytes()
    (gzip.open(path, "wb") if gz else open(path, "wb")).write(data)


def test_mnist_idx_roundtrip(tmp_path):
    r = np.random.default_rng(0)
    imgs = r.integers(0, 256, size=(10, 28, 28)).astype(np.uint8)
    labels = r.integers(0, 10, size=10).astype(np.uint8)
    _write_idx_images(str(tmp_path / "train-images-idx3-ubyte.gz"), imgs,
                      gz=True)
    _write_idx_labels(str(tmp_path / "train-labels-idx1-ubyte.gz"), labels,
                      gz=True)
    samples = load_mnist(str(tmp_path), "train")
    assert len(samples) == 10
    assert samples[0].feature.shape == (28, 28, 1)
    np.testing.assert_allclose(samples[3].feature[..., 0],
                               imgs[3] / 255.0, rtol=1e-6)
    assert int(samples[3].label) == int(labels[3])


def test_cifar10_binary(tmp_path):
    r = np.random.default_rng(1)
    n = 6
    rows = np.zeros((n, 3073), np.uint8)
    rows[:, 0] = r.integers(0, 10, size=n)
    rows[:, 1:] = r.integers(0, 256, size=(n, 3072))
    rows[:3].tofile(str(tmp_path / "data_batch_1.bin"))
    rows[3:].tofile(str(tmp_path / "data_batch_2.bin"))
    samples = load_cifar10_binary(str(tmp_path), train=True)
    assert len(samples) == 6
    assert samples[0].feature.shape == (32, 32, 3)
    # CHW -> HWC: red channel of row 4 is bytes 1..1024
    expect_red = rows[4, 1:1025].reshape(32, 32) / 255.0
    np.testing.assert_allclose(samples[4].feature[..., 0], expect_red,
                               rtol=1e-6)
    assert int(samples[4].label) == int(rows[4, 0])


def test_labeled_text_dir(tmp_path):
    for cat, texts in (("alt.atheism", ["doc a", "doc b"]),
                       ("sci.space", ["rockets"])):
        os.makedirs(tmp_path / "news" / cat)
        for i, t in enumerate(texts):
            (tmp_path / "news" / cat / f"{i}.txt").write_text(t)
    docs, cats = load_labeled_text_dir(str(tmp_path / "news"))
    assert cats == ["alt.atheism", "sci.space"]
    assert ("rockets", 1) in docs and ("doc a", 0) in docs
    assert len(docs) == 3


def test_labeled_text_tarball(tmp_path):
    """Tarball whose top-level dir differs from the archive basename (the
    real news20 case) extracts once and loads."""
    import tarfile
    src = tmp_path / "corpus-src" / "20news-tiny"
    for cat, text in (("a", "alpha"), ("b", "beta")):
        os.makedirs(src / cat)
        (src / cat / "0.txt").write_text(text)
    tar_path = tmp_path / "news20.tar.gz"
    with tarfile.open(tar_path, "w:gz") as tf:
        tf.add(src, arcname="20news-tiny")
    docs, cats = load_labeled_text_dir(str(tar_path))
    assert cats == ["a", "b"] and len(docs) == 2
    # second call reuses the extraction (no error, same result)
    docs2, _ = load_labeled_text_dir(str(tar_path))
    assert docs2 == docs


def test_labeled_text_tarball_dot_prefixed_members(tmp_path):
    """GNU tar's './dir/...' member naming must not defeat top-dir
    detection or skip extraction."""
    import tarfile
    src = tmp_path / "src" / "corpus"
    os.makedirs(src / "x")
    (src / "x" / "0.txt").write_text("hello")
    tar_path = tmp_path / "c.tar.gz"
    with tarfile.open(tar_path, "w:gz") as tf:
        tf.add(src, arcname="./corpus")
    docs, cats = load_labeled_text_dir(str(tar_path))
    assert cats == ["x"] and docs == [("hello", 0)]
