"""Failure-recovery tests.

Reference: DistriOptimizerSpec exercises the retry loop with an
`ExceptionTest` layer inserted as the model's last stage, throwing on
scheduled invocation counts (test/.../utils/TestUtils.scala:103,
DistriOptimizerSpec.scala:89-97); recovery reloads the latest snapshot
(DistriOptimizer.scala:750-816)."""

import os

import numpy as np
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
from bigdl_tpu.optim import Adam, Optimizer, Trigger


from bigdl_tpu.dataset import Transformer


class ExceptionTest(Transformer):
    """Host-side fault injector: raises when the batch counter hits any
    scheduled count.  The reference injected an ExceptionTest *layer*
    (TestUtils.scala:103) because its hot loop ran layers on the host;
    under XLA the per-iteration host code is the data pipeline, so the
    injection point is a Transformer."""

    def __init__(self, failure_counts):
        self.failure_counts = set(failure_counts)
        self.count = 0

    def __call__(self, it):
        for batch in it:
            self.count += 1
            if self.count in self.failure_counts:
                raise RuntimeError(
                    f"injected failure at batch {self.count}")
            yield batch


def _dataset(fault=None, n=64, d=6):
    rng = np.random.default_rng(0)
    samples = [Sample(rng.standard_normal(d).astype(np.float32),
                      np.float32(i % 2)) for i in range(n)]
    ds = DataSet.array(samples).transform(
        SampleToMiniBatch(16, drop_last=True))
    return ds.transform(fault) if fault is not None else ds


def test_retry_before_first_checkpoint_restores_initial_weights(tmp_path):
    """A failure BEFORE any snapshot exists must retry from the user's
    starting weights (pretrained fine-tune case), not a fresh random init
    (DistriOptimizer.scala:828-845 restarts from the initial model)."""
    import jax
    # fails on batch 2 of epoch 1: step 1 already DONATED the params, and
    # no checkpoint exists yet
    fault = ExceptionTest([2])
    model = nn.Sequential().add(nn.Linear(6, 2)).build(jax.random.key(5))
    pretrained = jax.tree.map(np.asarray, model.params)
    opt = (Optimizer(model, _dataset(fault), nn.CrossEntropyCriterion())
           .set_optim_method(Adam(1e-2))
           .set_end_when(Trigger.max_epoch(1))
           # checkpoint trigger that never fires before the fault
           .set_checkpoint(str(tmp_path), Trigger.several_iteration(1000)))
    # spy on recovery: the blob is released after a successful run, so
    # capture what the recovery path actually restored from
    captured = {}
    orig_recover = opt._recover_from_checkpoint

    def spy():
        captured["blob"] = opt._initial_blob
        orig_recover()

    opt._recover_from_checkpoint = spy
    trained = opt.optimize()
    # completion proves recovery restored usable weights (device_put of the
    # donated originals would have raised); the captured blob must be the
    # USER's starting weights, not a re-rolled init
    assert trained.params is not None
    assert "blob" in captured and captured["blob"] is not None
    for a, b in zip(jax.tree.leaves(captured["blob"][0]),
                    jax.tree.leaves(pretrained)):
        np.testing.assert_array_equal(a, b)


def test_retry_recovers_from_checkpoint(tmp_path):
    fault = ExceptionTest([6])
    model = nn.Sequential().add(nn.Linear(6, 2))
    opt = (Optimizer(model, _dataset(fault), nn.CrossEntropyCriterion())
           .set_optim_method(Adam(1e-2))
           .set_end_when(Trigger.max_epoch(4))
           .set_checkpoint(str(tmp_path), Trigger.several_iteration(1)))
    trained = opt.optimize()  # must not raise: retry loop recovers
    assert trained.params is not None
    assert fault.count > 6  # training continued past the fault
    files = os.listdir(str(tmp_path))
    assert any(f.startswith("model.") for f in files)


def test_retry_exhaustion_raises(tmp_path):
    # continuous failure beyond BIGDL_TPU_RETRY_TIMES must surface
    os.environ["BIGDL_TPU_RETRY_TIMES"] = "2"
    try:
        fault = ExceptionTest(range(1, 10_000))
        model = nn.Sequential().add(nn.Linear(6, 2))
        opt = (Optimizer(model, _dataset(fault), nn.CrossEntropyCriterion())
               .set_optim_method(Adam(1e-2))
               .set_end_when(Trigger.max_epoch(2))
               .set_checkpoint(str(tmp_path), Trigger.several_iteration(1)))
        with pytest.raises(RuntimeError, match="injected failure"):
            opt.optimize()
    finally:
        del os.environ["BIGDL_TPU_RETRY_TIMES"]


def test_no_checkpoint_fails_fast():
    fault = ExceptionTest([2])
    model = nn.Sequential().add(nn.Linear(6, 2))
    opt = (Optimizer(model, _dataset(fault), nn.CrossEntropyCriterion())
           .set_end_when(Trigger.max_epoch(2)))
    with pytest.raises(RuntimeError, match="injected failure"):
        opt.optimize()


def test_config_env_tiers():
    from bigdl_tpu.utils import config
    assert config.retry_times() == 5
    os.environ["BIGDL_TPU_RETRY_TIMES"] = "7"
    try:
        assert config.retry_times() == 7
    finally:
        del os.environ["BIGDL_TPU_RETRY_TIMES"]
    assert config.get_bool("NOPE_MISSING", True) is True
    os.environ["BIGDL_TPU_FLAG"] = "yes"
    try:
        assert config.get_bool("FLAG") is True
    finally:
        del os.environ["BIGDL_TPU_FLAG"]
    assert config.get_int("RETRY_TIMES", 5) == 5  # unset -> default


def test_logger_filter(tmp_path):
    import logging

    from bigdl_tpu.utils import logger_filter
    log_path = str(tmp_path / "noise.log")
    got = logger_filter.redirect(["bigdl_tpu_test_noise"],
                                 log_file=log_path)
    assert got == log_path
    lg = logging.getLogger("bigdl_tpu_test_noise")
    lg.info("hello noise")
    for h in lg.handlers:
        h.flush()
    assert "hello noise" in open(log_path).read()
    # disabled via env
    os.environ["BIGDL_TPU_DISABLE_LOGGER_FILTER"] = "1"
    try:
        assert logger_filter.redirect(["x"]) is None
    finally:
        del os.environ["BIGDL_TPU_DISABLE_LOGGER_FILTER"]


def test_model_zoo_cli_train_and_test(tmp_path):
    from bigdl_tpu.models.run import main
    save = str(tmp_path / "m.bigdl")
    main(["train", "--model", "lenet", "--synthetic", "--batch-size", "32",
          "--max-epoch", "1", "--optim", "adam", "--learning-rate", "0.01",
          "--summary-dir", str(tmp_path / "tb"),
          "--checkpoint", str(tmp_path / "ckpt"),
          "--model-save", save])
    assert os.path.exists(save)
    assert os.listdir(str(tmp_path / "ckpt"))
    main(["test", "--model", "lenet", "--synthetic", "--batch-size", "32",
          "--snapshot", save])


def test_model_zoo_cli_resume_from_snapshots(tmp_path):
    """--model-snapshot/--state-snapshot resume (the reference Train CLIs'
    --model/--state contract, models/lenet/Train.scala:48-59): the second
    run continues from the first's checkpoint files."""
    from bigdl_tpu.models.run import main
    from bigdl_tpu.utils import file_io
    ck = str(tmp_path / "ckpt")
    main(["train", "--model", "lenet", "--synthetic", "--batch-size", "32",
          "--max-epoch", "1", "--checkpoint", ck, "--overwrite"])
    latest = file_io.latest_checkpoint(ck)
    assert latest is not None
    model_path, optim_path, neval = latest
    save2 = str(tmp_path / "resumed.bigdl")
    opt2 = main(["train", "--model", "lenet", "--synthetic",
                 "--batch-size", "32",
                 "--max-epoch", "2", "--model-snapshot", model_path,
                 "--state-snapshot", optim_path, "--model-save", save2])
    assert os.path.exists(save2)
    # the resumed run CONTINUED the first run's driver state: the first run
    # ended with epoch=2 (one epoch done), so the resumed run trains exactly
    # one more epoch and finishes at epoch=3 — a fresh run would show 3 only
    # after TWO epochs, and a failed state restore would also restart neval
    first_state = file_io.load(optim_path)["driver_state"]
    final_state = opt2.optim_method.hyper
    assert final_state["epoch"] == 3
    assert final_state["neval"] > first_state["neval"]


def test_async_checkpoint_roundtrip(tmp_path):
    """set_checkpoint(async_write=True): writes land from the background
    thread (joined at run end), are readable by latest_checkpoint, and
    resume exactly like sync checkpoints."""
    import jax

    from bigdl_tpu.utils import file_io
    from bigdl_tpu.utils.engine import Engine
    from test_e2e_lenet import make_optimizer, synthetic_mnist

    Engine.reset()
    Engine.init()
    model, opt = make_optimizer(samples=synthetic_mnist(128))
    opt.set_end_when(Trigger.max_epoch(1))
    opt.set_checkpoint(str(tmp_path), Trigger.several_iteration(1),
                       async_write=True)
    opt.optimize()  # joins pending writes before returning
    latest = file_io.latest_checkpoint(str(tmp_path))
    assert latest is not None
    blob = file_io.load(latest[0])
    assert "params" in blob and "state" in blob
    w0 = jax.tree.leaves(blob["params"])[0]
    assert np.all(np.isfinite(np.asarray(w0)))
    # values are host numpy (snapshot taken before donation), not stale refs
    assert not isinstance(w0, jax.Array)


def test_async_checkpoint_write_error_surfaces(tmp_path):
    """A failing background write must raise on the join, not vanish."""
    from bigdl_tpu.utils import file_io

    target = tmp_path / "not-a-dir"
    target.write_text("file blocks the directory")
    file_io.save_checkpoint_async(str(target), 1, {"p": np.zeros(2)},
                                  {"o": 1})
    with pytest.raises(Exception):
        file_io.wait_for_async_checkpoints()


def test_checkpoint_restores_rng_stream(tmp_path):
    """The global RNG stream position rides the optimMethod snapshot:
    resume_from replays the exact key sequence the interrupted run would
    have produced (dropout masks, shuffle draws)."""
    import jax

    from bigdl_tpu.common import get_default_rng, next_rng_key, set_seed
    from bigdl_tpu.utils import file_io
    from bigdl_tpu.utils.engine import Engine
    from test_e2e_lenet import make_optimizer, synthetic_mnist

    Engine.reset()
    Engine.init()
    set_seed(7)
    model, opt = make_optimizer(samples=synthetic_mnist(128))
    opt.set_end_when(Trigger.max_epoch(1))
    opt.set_checkpoint(str(tmp_path), Trigger.several_iteration(1))
    opt.optimize()
    # the keys the ORIGINAL stream would produce next
    expect = [np.asarray(jax.random.key_data(next_rng_key()))
              for _ in range(3)]
    # clobber the stream, then resume: positions must be restored
    set_seed(12345)
    latest = file_io.latest_checkpoint(str(tmp_path))
    model2, opt2 = make_optimizer(samples=synthetic_mnist(128))
    opt2.resume_from(latest[0], latest[1])
    got = [np.asarray(jax.random.key_data(next_rng_key()))
           for _ in range(3)]
    for a, b in zip(expect, got):
        np.testing.assert_array_equal(a, b)
