"""Tests for Nms, SpatialConvolutionMap, TreeLSTM/BinaryTreeLSTM
(reference analogs: nn/Nms.scala, nn/SpatialConvolutionMap.scala,
nn/BinaryTreeLSTM.scala + the treeLSTMSentiment example)."""

import jax
import jax.numpy as jnp
import numpy as np

import bigdl_tpu.nn as nn
from bigdl_tpu.nn.detection import nms


def _ref_nms(boxes, scores, thr):
    """Plain numpy greedy NMS oracle."""
    order = np.argsort(-scores)
    keep, suppressed = [], np.zeros(len(boxes), bool)
    areas = np.maximum(boxes[:, 2] - boxes[:, 0], 0) * \
        np.maximum(boxes[:, 3] - boxes[:, 1], 0)
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        for j in order:
            if suppressed[j] or j == i:
                continue
            ix1, iy1 = np.maximum(boxes[i, :2], boxes[j, :2])
            ix2, iy2 = np.minimum(boxes[i, 2:], boxes[j, 2:])
            inter = max(ix2 - ix1, 0) * max(iy2 - iy1, 0)
            union = areas[i] + areas[j] - inter
            if union > 0 and inter / union > thr:
                suppressed[j] = True
    return keep


def test_nms_matches_numpy_oracle():
    rng = np.random.default_rng(0)
    xy = rng.uniform(0, 10, (30, 2))
    wh = rng.uniform(1, 5, (30, 2))
    boxes = np.concatenate([xy, xy + wh], 1).astype(np.float32)
    scores = rng.uniform(0, 1, 30).astype(np.float32)
    idx, count = jax.jit(nms)(jnp.asarray(boxes), jnp.asarray(scores), 0.5)
    got = [int(i) for i in np.asarray(idx) if i >= 0]
    assert got == _ref_nms(boxes, scores, 0.5)
    assert int(count) == len(got)


def test_nms_max_output_and_padding():
    boxes = jnp.array([[0, 0, 1, 1], [10, 10, 11, 11], [20, 20, 21, 21]],
                      jnp.float32)
    scores = jnp.array([0.9, 0.8, 0.7])
    idx, count = nms(boxes, scores, 0.5, max_output=2)
    assert list(np.asarray(idx)) == [0, 1]
    assert int(count) == 2
    idx, count = nms(boxes, scores, 0.5, max_output=5)
    assert list(np.asarray(idx)) == [0, 1, 2, -1, -1]
    assert int(count) == 3


def test_nms_module():
    m = nn.Nms(iou_threshold=0.5, max_output=4)
    params, state = m.init(jax.random.key(0))
    boxes = jnp.array([[0, 0, 2, 2], [0, 0, 2.1, 2.1], [5, 5, 6, 6]],
                      jnp.float32)
    scores = jnp.array([0.5, 0.9, 0.3])
    out, _ = m.apply(params, state, (boxes, scores))
    assert list(np.asarray(out)) == [1, 2, -1, -1]


def test_spatial_convolution_map_masks_connections():
    table = nn.SpatialConvolutionMap.one_to_one(3)
    m = nn.SpatialConvolutionMap(table, 3, 3, pad_w=1, pad_h=1)
    params, state = m.init(jax.random.key(0))
    x = jnp.asarray(np.random.default_rng(1).standard_normal(
        (2, 8, 8, 3)), jnp.float32)
    out, _ = m.apply(params, state, x)
    assert out.shape == (2, 8, 8, 3)
    # channel k of the output must depend only on channel k of the input
    x2 = x.at[..., 1].set(0.0)
    out2, _ = m.apply(params, state, x2)
    np.testing.assert_allclose(out[..., 0], out2[..., 0], atol=1e-6)
    np.testing.assert_allclose(out[..., 2], out2[..., 2], atol=1e-6)
    assert not np.allclose(out[..., 1], out2[..., 1])


def test_spatial_convolution_map_explicit_planes():
    # a sparse random table may never mention the highest input map;
    # explicit plane counts must win over table inference
    table = np.array([[0, 0], [1, 1]], np.int32)
    m = nn.SpatialConvolutionMap(table, 3, 3, pad_w=1, pad_h=1,
                                 n_input_plane=5, n_output_plane=4)
    params, state = m.init(jax.random.key(0))
    x = jnp.zeros((1, 6, 6, 5), jnp.float32)
    out, _ = m.apply(params, state, x)
    assert out.shape == (1, 6, 6, 4)
    import pytest as _pytest
    with _pytest.raises(ValueError):
        nn.SpatialConvolutionMap(table, 3, 3, n_input_plane=1)


def test_spatial_convolution_map_full_equals_dense():
    table = nn.SpatialConvolutionMap.full(2, 4)
    m = nn.SpatialConvolutionMap(table, 3, 3)
    dense = nn.SpatialConvolution(2, 4, 3, 3)
    params, state = m.init(jax.random.key(0))
    x = jnp.asarray(np.random.default_rng(2).standard_normal(
        (1, 6, 6, 2)), jnp.float32)
    out_m, _ = m.apply(params, state, x)
    out_d, _ = dense.apply(params, state, x)
    np.testing.assert_allclose(np.asarray(out_m), np.asarray(out_d),
                               rtol=1e-5, atol=1e-5)


def _encode_tree():
    """( (the cat) (sat) ) — 3 leaves, 2 internal nodes, topo order."""
    # slots: 0=leaf0, 1=leaf1, 2=internal(0,1), 3=leaf2, 4=internal(2,3)
    children = np.array([[-1, -1], [-1, -1], [0, 1], [-1, -1], [2, 3]],
                        np.int32)
    leaf_ids = np.array([0, 1, -1, 2, -1], np.int32)
    return children, leaf_ids


def test_binary_tree_lstm_shapes_and_validity():
    m = nn.BinaryTreeLSTM(input_size=8, hidden_size=6)
    params, state = m.init(jax.random.key(0))
    children, leaf_ids = _encode_tree()
    rng = np.random.default_rng(3)
    inputs = jnp.asarray(rng.standard_normal((2, 3, 8)), jnp.float32)
    batch = (inputs,
             jnp.asarray(np.stack([children, children])),
             jnp.asarray(np.stack([leaf_ids, leaf_ids])))
    out, _ = jax.jit(lambda p, s, b: m.apply(p, s, b))(params, state, batch)
    assert out.shape == (2, 5, 6)
    assert np.all(np.isfinite(np.asarray(out)))
    # root state must differ between the two (different-input) examples
    assert not np.allclose(out[0, 4], out[1, 4])


def test_binary_tree_lstm_padding_is_zero():
    m = nn.BinaryTreeLSTM(input_size=4, hidden_size=3)
    params, state = m.init(jax.random.key(1))
    # tree with 2 leaves + 1 internal, padded to 5 slots
    children = np.array([[-1, -1], [-1, -1], [0, 1], [-1, -1], [-1, -1]],
                        np.int32)
    leaf_ids = np.array([0, 1, -1, -1, -1], np.int32)
    inputs = jnp.ones((1, 2, 4), jnp.float32)
    out, _ = m.apply(params, state,
                     (inputs, jnp.asarray(children[None]),
                      jnp.asarray(leaf_ids[None])))
    np.testing.assert_array_equal(np.asarray(out[0, 3]), 0)
    np.testing.assert_array_equal(np.asarray(out[0, 4]), 0)
    assert not np.allclose(np.asarray(out[0, 2]), 0)


def test_binary_tree_lstm_gradients_flow():
    m = nn.BinaryTreeLSTM(input_size=4, hidden_size=3)
    params, state = m.init(jax.random.key(2))
    children, leaf_ids = _encode_tree()
    inputs = jnp.asarray(np.random.default_rng(4).standard_normal((1, 3, 4)),
                         jnp.float32)
    batch = (inputs, jnp.asarray(children[None]),
             jnp.asarray(leaf_ids[None]))

    def loss(p):
        out, _ = m.apply(p, state, batch)
        return jnp.sum(out[0, 4] ** 2)

    grads = jax.grad(loss)(params)
    for name in ("leaf_c", "comp_w", "comp_b"):
        assert np.any(np.asarray(grads[name]) != 0), name
