"""Asynchronous input pipeline: threaded prefetch + host->device
double-buffering (dataset/prefetch.py) and its train-loop wiring.

Covers the PR-3 acceptance contracts: deterministic overlap (wall clock
~= max(data, step), not sum), bit-identical training between
BIGDL_TPU_PREFETCH_DEPTH=0 and =2, typed exceptions (CorruptRecord,
chaos fail@, supervisor StallError) re-raised at the consumer's next(),
data.stall fired inside the worker still tripping the supervisor 'data'
deadline, no thread leak across a StallError retry re-entry, and the
straggler detector's queue-depth guard."""

import glob
import json
import threading
import time

import numpy as np
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu import Engine
from bigdl_tpu.dataset import (DataSet, PrefetchIterator, Sample,
                               SampleToMiniBatch, ThreadedShardReader)
from bigdl_tpu.dataset.prefetch import prefetch_depth
from bigdl_tpu.optim import Adam, Optimizer, Predictor, Trigger
from bigdl_tpu.utils import chaos


# ---------------------------------------------------------------------------
# PrefetchIterator core contracts
# ---------------------------------------------------------------------------

def test_depth_env_knob(monkeypatch):
    assert prefetch_depth() == 2  # the documented default
    monkeypatch.setenv("BIGDL_TPU_PREFETCH_DEPTH", "0")
    assert prefetch_depth() == 0
    monkeypatch.setenv("BIGDL_TPU_PREFETCH_DEPTH", "5")
    assert prefetch_depth() == 5


def test_order_completeness_and_transform():
    with PrefetchIterator(iter(range(100)), depth=3,
                          transform=lambda x: x * 2) as pipe:
        out = list(pipe)
    assert out == [2 * i for i in range(100)]
    assert not pipe._thread.is_alive()


def test_exception_reraised_in_order():
    def source():
        yield from (0, 1, 2)
        raise ValueError("boom at item 4")

    pipe = PrefetchIterator(source(), depth=2)
    try:
        assert [next(pipe) for _ in range(3)] == [0, 1, 2]
        with pytest.raises(ValueError, match="boom at item 4"):
            next(pipe)
        with pytest.raises(StopIteration):  # terminal after the raise
            next(pipe)
    finally:
        pipe.close()


def test_close_unblocks_producer_and_joins():
    """A worker parked on a FULL queue (infinite source) must observe
    close() and exit — the no-leaked-threads discipline."""
    before = threading.active_count()

    def forever():
        i = 0
        while True:
            yield i
            i += 1

    pipe = PrefetchIterator(forever(), depth=2)
    assert next(pipe) == 0
    pipe.close()
    assert not pipe._thread.is_alive()
    deadline = time.monotonic() + 2.0
    while threading.active_count() > before and time.monotonic() < deadline:
        time.sleep(0.01)
    assert threading.active_count() <= before


def test_overlap_wallclock_near_single_cost_bound():
    """THE acceptance bound: 50 ms/batch transformer + 50 ms/step
    consumer, 20 steps, depth 2 -> wall < 1.6x the single-cost bound
    (serialized execution would be ~2x)."""
    n, data_s, step_s = 20, 0.05, 0.05

    def source():
        for i in range(n):
            time.sleep(data_s)  # the slow transformer chain
            yield i

    t0 = time.perf_counter()
    consumed = 0
    with PrefetchIterator(source(), depth=2) as pipe:
        for _ in pipe:
            time.sleep(step_s)  # the device step the data work hides under
            consumed += 1
    wall = time.perf_counter() - t0
    assert consumed == n
    bound = n * max(data_s, step_s)
    assert wall < 1.6 * bound, (
        f"prefetch failed to overlap: {wall:.2f}s for {n} steps "
        f"(single-cost bound {bound:.2f}s, serialized ~{2 * bound:.2f}s)")


# ---------------------------------------------------------------------------
# training determinism: depth 0 vs depth 2 bit-identical
# ---------------------------------------------------------------------------

class _LossCapture:
    def __init__(self):
        self.losses = []

    def add_scalar(self, name, value, step):
        if name == "Loss":
            self.losses.append(value)


def _mnist_samples(n=192, seed=0):
    rng = np.random.default_rng(seed)
    images = rng.normal(0.0, 0.1, size=(n, 28, 28)).astype(np.float32)
    labels = rng.integers(0, 10, size=n)
    return [Sample.from_ndarray(images[i], np.int32(labels[i]))
            for i in range(n)]


def test_training_bit_identical_depth0_vs_depth2(monkeypatch):
    """The sync path is preserved behind BIGDL_TPU_PREFETCH_DEPTH=0 and
    the prefetched (staged) path produces the SAME loss sequence and the
    SAME final params on the LeNet smoke — batch order, RNG draws, and
    device placement are all bit-identical."""
    from bigdl_tpu.common import set_seed
    from bigdl_tpu.models.lenet import LeNet5

    Engine.init()
    samples = _mnist_samples()

    def train(depth):
        monkeypatch.setenv("BIGDL_TPU_PREFETCH_DEPTH", depth)
        set_seed(11)
        model = LeNet5(10)
        ds = DataSet.array(samples).transform(
            SampleToMiniBatch(32, drop_last=True))
        cap = _LossCapture()
        opt = (Optimizer(model, ds, nn.ClassNLLCriterion())
               .set_optim_method(Adam(1e-3))
               .set_end_when(Trigger.max_iteration(5))
               .set_log_interval(1)
               .set_train_summary(cap))
        opt.optimize()
        import jax
        return cap.losses, [np.asarray(l) for l in
                            jax.tree.leaves(model.params)]

    losses_sync, params_sync = train("0")
    losses_pre, params_pre = train("2")
    assert len(losses_sync) == 5
    assert losses_sync == losses_pre  # exact float equality, not allclose
    for a, b in zip(params_sync, params_pre):
        np.testing.assert_array_equal(a, b)


def test_predictor_prefetch_equivalence(monkeypatch):
    Engine.init()
    from bigdl_tpu.models.lenet import LeNet5
    model = LeNet5(10).build()
    x = np.random.default_rng(0).normal(size=(50, 28, 28)).astype(np.float32)
    ds = DataSet.array([Sample.from_ndarray(x[i]) for i in range(50)])

    monkeypatch.setenv("BIGDL_TPU_PREFETCH_DEPTH", "0")
    probs_sync = Predictor(model, batch_size=16).predict(ds)
    monkeypatch.setenv("BIGDL_TPU_PREFETCH_DEPTH", "2")
    probs_pre = Predictor(model, batch_size=16).predict(ds)
    np.testing.assert_array_equal(probs_sync, probs_pre)


# ---------------------------------------------------------------------------
# robustness contracts through the worker thread
# ---------------------------------------------------------------------------

def _record_stream(tmp_path, skip_budget, n=60):
    from bigdl_tpu.utils.recordio import write_records
    shard = str(tmp_path / "recs.bd")
    write_records(shard, list(range(n)))
    return DataSet.record_stream([shard], skip_budget=skip_budget)


def test_corrupt_record_skip_budget_through_worker(tmp_path):
    """data.record corruption with budget 1, consumed THROUGH the
    prefetch worker: the pass completes, exactly one record is
    quarantined, and the dataset's accounting (set in the generator's
    finally, running on the worker) is intact."""
    ds = _record_stream(tmp_path, skip_budget=1)
    with chaos.scoped("data.record=truncate@5"):
        with PrefetchIterator(ds.data(train=True), depth=2) as pipe:
            got = list(pipe)
    assert len(got) == 59
    assert ds.last_quarantined == 1


def test_corrupt_record_budget_zero_raises_at_consumer(tmp_path):
    from bigdl_tpu.utils.recordio import CorruptRecord
    ds = _record_stream(tmp_path, skip_budget=0)
    with chaos.scoped("data.record=truncate@5"):
        with PrefetchIterator(ds.data(train=True), depth=2) as pipe:
            with pytest.raises(CorruptRecord):
                list(pipe)


def _linear_dataset(n=64, d=6):
    rng = np.random.default_rng(0)
    samples = [Sample(rng.standard_normal(d).astype(np.float32),
                      np.float32(i % 2)) for i in range(n)]
    return DataSet.array(samples).transform(
        SampleToMiniBatch(16, drop_last=True))


def test_data_stall_in_worker_trips_data_deadline_no_thread_leak(tmp_path):
    """data.stall fires INSIDE the prefetch worker; the worker's
    supervision channel must trip the 'data' deadline, the StallError
    must land in the retry loop (forwarded through the queue), the run
    must complete via checkpoint recovery — and the retry re-entry must
    not leak the stalled worker thread (threading.active_count check)."""
    before = threading.active_count()
    with chaos.scoped("data.stall=stall*8@3"):
        opt = (Optimizer(nn.Sequential().add(nn.Linear(6, 2)),
                         _linear_dataset(), nn.CrossEntropyCriterion())
               .set_optim_method(Adam(1e-2))
               .set_end_when(Trigger.max_epoch(2))
               .set_checkpoint(str(tmp_path), Trigger.several_iteration(1))
               .set_supervision(data=0.4, poll_interval=0.1))
        trained = opt.optimize()
    assert trained.params is not None
    reports = glob.glob(str(tmp_path / "crash_report*.json"))
    assert reports
    # the report names the worker channel as the stalled party
    blob = json.loads(open(reports[0]).read())
    assert "worker channel" in blob["reason"], blob["reason"]
    # every pipeline/supervisor thread joined after optimize()
    deadline = time.monotonic() + 3.0
    while threading.active_count() > before and time.monotonic() < deadline:
        time.sleep(0.02)
    assert threading.active_count() <= before


def test_chaos_fail_through_worker_reaches_retry_loop(tmp_path):
    """A data.batch fail@ schedule (run by the worker now) must land in
    the retry loop at the same batch position as the sync path."""
    with chaos.scoped("data.batch=fail@6"):
        opt = (Optimizer(nn.Sequential().add(nn.Linear(6, 2)),
                         _linear_dataset(), nn.CrossEntropyCriterion())
               .set_optim_method(Adam(1e-2))
               .set_end_when(Trigger.max_epoch(3))
               .set_checkpoint(str(tmp_path), Trigger.several_iteration(1)))
        trained = opt.optimize()
        assert chaos.counts()["data.batch"] > 6  # continued past the fault
    assert trained.params is not None


# ---------------------------------------------------------------------------
# straggler detector: queue-depth guard
# ---------------------------------------------------------------------------

def test_straggler_skips_drop_when_queue_nonempty():
    opt = Optimizer(nn.Sequential().add(nn.Linear(6, 2)), [],
                    nn.CrossEntropyCriterion())
    opt.set_drop_module_property(0.05, 0.5, batch_size=20,
                                 warmup_iteration=0)
    for i in range(30):
        assert opt._straggler_check(0.01, i + 1) is False
    # a clear straggler wait, but the queue had items ready: the consumer
    # (not the pipeline) set the pace — never dropped
    assert opt._straggler_check(1.0, 31, queue_depth=2) is False
    # same magnitude with an EMPTY queue: genuine pipeline straggler
    assert opt._straggler_check(2.0, 32, queue_depth=0) is True


# ---------------------------------------------------------------------------
# ThreadedShardReader: the pure-Python native-prefetch fallback
# ---------------------------------------------------------------------------

def _shards(tmp_path, k=3, per=20):
    from bigdl_tpu.utils.recordio import write_records
    paths = []
    for s in range(k):
        p = str(tmp_path / f"shard{s}.bd")
        write_records(p, [s * per + i for i in range(per)])
        paths.append(p)
    return paths


def test_threaded_shard_reader_yields_everything(tmp_path):
    from bigdl_tpu.utils.recordio import read_records
    paths = _shards(tmp_path)
    with ThreadedShardReader(paths, 2, read_records) as reader:
        got = list(reader)
    assert sorted(got) == list(range(60))


def test_threaded_shard_reader_surfaces_corruption(tmp_path):
    from bigdl_tpu.utils.recordio import CorruptRecord, read_records
    paths = _shards(tmp_path)
    data = open(paths[1], "rb").read()
    open(paths[1], "wb").write(data[:-3])  # torn tail
    with pytest.raises(CorruptRecord):
        with ThreadedShardReader(paths, 2, read_records) as reader:
            list(reader)


def test_record_files_python_threaded_fallback(tmp_path, monkeypatch):
    """num_threads>0 with no native prefetch symbols must use the
    threaded Python reader, not silently degrade to sequential reads
    (dataset/__init__ record_files + StreamingRecordDataSet)."""
    from bigdl_tpu.utils import native
    monkeypatch.setattr(native, "has_prefetch", lambda: False)
    paths = _shards(tmp_path)
    used = {"threaded": False}
    from bigdl_tpu.dataset import prefetch as prefetch_mod
    orig = prefetch_mod.ThreadedShardReader

    class Spy(orig):
        def __init__(self, *a, **kw):
            used["threaded"] = True
            super().__init__(*a, **kw)

    monkeypatch.setattr(prefetch_mod, "ThreadedShardReader", Spy)
    ds = DataSet.record_files(paths, num_threads=2)
    assert used["threaded"] and sorted(ds.records) == list(range(60))

    used["threaded"] = False
    stream = DataSet.record_stream(paths, num_threads=2)
    got = sorted(stream.data(train=True))
    assert used["threaded"] and got == list(range(60))
    # eval passes stay sequential (order must match input order)
    used["threaded"] = False
    assert list(stream.data(train=False)) == list(range(60))
    assert not used["threaded"]


# ---------------------------------------------------------------------------
# MTImageToBatch: the MTLabeledBGRImgToBatch analog
# ---------------------------------------------------------------------------

def test_mt_image_batcher_matches_sequential():
    from bigdl_tpu.dataset.image import (ImgToSample, LabeledImage,
                                         MTImageToBatch)
    rng = np.random.default_rng(0)
    images = [LabeledImage(rng.standard_normal((8, 8, 3)).astype(np.float32),
                           float(i % 10)) for i in range(70)]
    seq = list((ImgToSample() >> SampleToMiniBatch(16))(iter(images)))
    mt = list(MTImageToBatch(16, num_threads=3)(iter(images)))
    assert len(mt) == len(seq) == 5  # 4 full + 1 partial (drop_last off)
    for a, b in zip(seq, mt):
        np.testing.assert_array_equal(a.get_input(), b.get_input())
        np.testing.assert_array_equal(a.get_target(), b.get_target())


def test_mt_image_batcher_rejects_filtering_transformer():
    from bigdl_tpu.dataset.image import LabeledImage, MTImageToBatch
    from bigdl_tpu.dataset import Transformer

    class DropHalf(Transformer):
        def __call__(self, it):
            for i, img in enumerate(it):
                if i % 2 == 0:
                    yield img

    images = [LabeledImage(np.zeros((4, 4, 3), np.float32), 0.0)
              for _ in range(16)]
    mt = MTImageToBatch(16, transformer=DropHalf(), num_threads=2)
    with pytest.raises(ValueError, match="1:1"):
        list(mt(iter(images)))


def test_mt_image_batcher_pad_last_and_valid():
    from bigdl_tpu.dataset.image import LabeledImage, MTImageToBatch
    images = [LabeledImage(np.full((4, 4, 3), float(i), np.float32),
                           float(i)) for i in range(10)]
    batches = list(MTImageToBatch(8, num_threads=2,
                                  pad_last=True)(iter(images)))
    assert [b.size() for b in batches] == [8, 8]
    assert batches[0].valid == 8 and batches[1].valid == 2
