"""Pipeline schedule tables (parallel/schedule.py, ISSUE 13): tick
counts, bubble fractions per (schedule, virtual_stages), in-flight
activation bounds, the stack-order permutation, and the always-on table
verifier.  Pure host-side — no devices, no jax programs."""

import pytest

from bigdl_tpu.parallel.schedule import (FWD, IDLE, ScheduleTable,
                                         build_schedule, bubble_fraction,
                                         stack_index, stage_of_stack_index)


class TestBubbleFraction:
    def test_gpipe_closed_form_back_compat(self):
        """The original two-arg spelling keeps its exact closed form
        (callers from ISSUE 12 pass (pipe_n, m) positionally)."""
        for n, m in [(2, 4), (2, 8), (4, 8), (4, 16), (8, 32)]:
            assert bubble_fraction(n, m) == (n - 1) / (m + n - 1)
        assert bubble_fraction(1, 8) == 0.0
        assert bubble_fraction(1, 8, "1f1b", 1) == 0.0

    def test_gpipe_table_matches_closed_form_at_v1(self):
        for n, m in [(2, 4), (2, 8), (4, 8), (3, 6)]:
            tbl = build_schedule("gpipe", n, m, 1)
            assert tbl.ticks == m + n - 1
            assert tbl.bubble_fraction == pytest.approx(
                (n - 1) / (m + n - 1))

    def test_1f1b_v1_equals_gpipe_bubble(self):
        """Classic 1F1B keeps GPipe's bubble — its win is memory, not
        idle time (docs/parallelism.md 'Choosing a schedule')."""
        for n, m in [(2, 8), (4, 16), (2, 4)]:
            assert bubble_fraction(n, m, "1f1b", 1) == pytest.approx(
                bubble_fraction(n, m))

    def test_interleaving_strictly_lowers_the_bubble(self):
        """The acceptance geometry: (n=2, m=8) — 1F1B at v=2 is 1/17
        vs GPipe's 1/9, and more slices keep helping."""
        g = bubble_fraction(2, 8)
        f2 = bubble_fraction(2, 8, "1f1b", 2)
        f4 = bubble_fraction(2, 8, "1f1b", 4)
        assert g == pytest.approx(1 / 9)
        assert f2 == pytest.approx(1 / 17)
        assert f2 < g
        assert f4 < f2
        # deeper pipeline too
        assert bubble_fraction(4, 16, "1f1b", 2) < bubble_fraction(4, 16)


class TestInflight:
    def test_1f1b_v1_peak_is_pipeline_depth(self):
        """Steady state holds <= n microbatch activations per device —
        the O(n)-instead-of-O(m) memory claim, exact at v=1."""
        for n, m in [(2, 8), (4, 16), (3, 9)]:
            tbl = build_schedule("1f1b", n, m, 1)
            assert tbl.peak_inflight == n
            assert tbl.peak_inflight_per_device[0] == n
            # later devices drain sooner
            assert tbl.peak_inflight_per_device[-1] <= n

    def test_interleaved_peak_bounded_and_below_gpipe(self):
        tbl = build_schedule("1f1b", 2, 8, 2)
        # warmup bound 2(n-1) + (v-1)n + 1 = 5 for n=2, v=2
        assert tbl.peak_inflight == 5
        assert tbl.peak_inflight < 8 * 2  # GPipe keeps all m*v
        # m-independence: doubling m does not grow the stash
        assert build_schedule("1f1b", 2, 16, 2).peak_inflight == 5

    def test_gpipe_table_reports_keep_all(self):
        assert build_schedule("gpipe", 2, 8, 1).peak_inflight == 8
        assert build_schedule("gpipe", 2, 8, 2).peak_inflight == 16


class TestTableStructure:
    @pytest.mark.parametrize("sched", ["gpipe", "1f1b"])
    @pytest.mark.parametrize("n,m,v", [
        (2, 8, 1), (2, 8, 2), (4, 16, 2), (3, 6, 2),
        (2, 3, 2),   # ragged: m not a multiple of n
        (2, 1, 1), (2, 1, 2),  # single microbatch
    ])
    def test_build_verifies(self, sched, n, m, v):
        """build_schedule always re-verifies: every unit exactly once,
        every stash read/write consistent (ScheduleTable.verify)."""
        tbl = build_schedule(sched, n, m, v)
        assert tbl.ticks > 0
        assert 0.0 <= tbl.bubble_fraction < 1.0
        work = n * v * m * (2 if sched == "1f1b" else 1)
        busy = sum(1 for row in tbl.act for a in row if a != IDLE)
        assert busy == work

    def test_verifier_has_teeth(self):
        """Corrupting a built table must fail verification — the
        verifier is the correctness proof for every new geometry."""
        tbl = build_schedule("1f1b", 2, 4, 1)
        broken = ScheduleTable(**{**tbl.__dict__})
        broken.mb = [list(r) for r in tbl.mb]
        for t in range(broken.ticks):
            if broken.act[t][0] == FWD:
                broken.mb[t][0] = (broken.mb[t][0] + 1) % 4
                break
        with pytest.raises(AssertionError):
            broken.verify()

    def test_build_rejects_bad_inputs(self):
        with pytest.raises(ValueError):
            build_schedule("pipedream", 2, 4, 1)
        with pytest.raises(ValueError):
            build_schedule("1f1b", 0, 4, 1)
        with pytest.raises(ValueError):
            build_schedule("1f1b", 2, 0, 1)


class TestStackOrder:
    def test_roundtrip_and_identity_at_v1(self):
        for n, v in [(2, 1), (2, 2), (4, 3)]:
            rows = [stack_index(s, n, v) for s in range(n * v)]
            assert sorted(rows) == list(range(n * v))
            for s in range(n * v):
                assert stage_of_stack_index(stack_index(s, n, v), n, v) == s
        # v=1 is the identity: ISSUE 12 layouts are untouched
        assert [stack_index(s, 4, 1) for s in range(4)] == [0, 1, 2, 3]

    def test_device_major_blocks(self):
        """P('pipe') splits the stack into contiguous per-device blocks:
        device d's rows must hold exactly its interleaved stages."""
        n, v = 2, 2
        for d in range(n):
            rows = range(d * v, (d + 1) * v)
            stages = {stage_of_stack_index(k, n, v) for k in rows}
            assert stages == {j * n + d for j in range(v)}
