"""Hadoop SequenceFile ingestion (reference ImageNetSeqFileGenerator format).

The writer here emits the exact framing BGRImgToLocalSeqFile produces
(SEQ v6, Text/Text, vint-prefixed payloads, sync escapes); the reader is
additionally pinned against a byte-literal fixture so reader and writer
cannot drift together.
"""

import io
import os
import struct

import numpy as np
import pytest

from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
from bigdl_tpu.dataset.seqfile import (SeqFileDataSet, count_seq_records,
                                       read_byte_records, read_seq_file,
                                       write_seq_file, _read_vint,
                                       _write_vint)


def _images(n, w=8, h=6, seed=0):
    r = np.random.default_rng(seed)
    return [(int(r.integers(1, 11)),
             r.integers(0, 256, size=(h, w, 3), dtype=np.uint8).astype(np.uint8))
            for _ in range(n)]


def test_vint_roundtrip():
    for v in (0, 1, -1, 127, -112, 128, -113, 255, 65535, -65536,
              2 ** 31 - 1, -2 ** 31, 2 ** 60):
        b = io.BytesIO()
        _write_vint(b, v)
        b.seek(0)
        assert _read_vint(b) == v, v


def test_write_read_roundtrip(tmp_path):
    recs = _images(12)
    p = str(tmp_path / "part_0.seq")
    write_seq_file(p, recs, sync_interval=4)  # exercises the sync escape
    back = list(read_byte_records(p))
    assert len(back) == 12
    for (label, img), rec in zip(recs, back):
        assert rec["label"] == float(label)
        np.testing.assert_array_equal(rec["data"], img)
    assert count_seq_records(p) == 12


def test_named_keys_and_class_filter(tmp_path):
    recs = [("n%d.jpg" % i, lab, img)
            for i, (lab, img) in enumerate(_images(10, seed=1))]
    p = str(tmp_path / "named.seq")
    write_seq_file(p, recs)
    # readLabel takes the SECOND line of a name\nlabel key (DataSet.scala:496)
    labels = [r["label"] for r in read_byte_records(p)]
    assert labels == [float(lab) for _n, lab, _i in recs]
    kept = [r["label"] for r in read_byte_records(p, class_num=5)]
    assert kept == [l for l in labels if l <= 5]


def test_byte_literal_header():
    """Reader pinned against hand-assembled bytes (not our own writer)."""
    key = b"3"
    img = np.arange(2 * 2 * 3, dtype=np.uint8)
    value = struct.pack(">ii", 2, 2) + img.tobytes()
    buf = io.BytesIO()
    buf.write(b"SEQ\x06")
    for s in (b"org.apache.hadoop.io.Text",) * 2:
        buf.write(bytes([len(s)]))  # vint < 127 is the raw length byte
        buf.write(s)
    buf.write(b"\x00\x00")
    buf.write(struct.pack(">i", 0))
    buf.write(b"\x01" * 16)
    kb = bytes([len(key)]) + key
    vb = bytes([len(value)]) + value
    buf.write(struct.pack(">ii", len(kb) + len(vb), len(kb)))
    buf.write(kb)
    buf.write(vb)
    import tempfile
    with tempfile.NamedTemporaryFile(suffix=".seq", delete=False) as f:
        f.write(buf.getvalue())
        path = f.name
    try:
        [rec] = list(read_byte_records(path))
        assert rec["label"] == 3.0
        np.testing.assert_array_equal(rec["data"].reshape(-1), img)
    finally:
        os.unlink(path)


def test_compressed_fails_loud(tmp_path):
    p = tmp_path / "gz.seq"
    buf = io.BytesIO()
    buf.write(b"SEQ\x06")
    for s in (b"org.apache.hadoop.io.Text",) * 2:
        buf.write(bytes([len(s)]) + s)
    buf.write(b"\x01\x00")  # compressed!
    codec = b"org.apache.hadoop.io.compress.DefaultCodec"
    buf.write(bytes([len(codec)]) + codec)
    buf.write(struct.pack(">i", 0))
    buf.write(b"\x02" * 16)
    p.write_bytes(buf.getvalue())
    with pytest.raises(ValueError, match="DefaultCodec"):
        list(read_seq_file(str(p)))


def test_streams_into_training(tmp_path):
    """VERDICT r3 #5 'done': generator-format shards stream through the
    dataset into actual training."""
    import jax

    import bigdl_tpu.nn as nn
    from bigdl_tpu import Engine
    from bigdl_tpu.optim import Adam, Optimizer, Trigger

    r = np.random.default_rng(3)
    # 2 shards of separable 6x6 BGR images, labels 1/2 (reference labels
    # are 1-based)
    for shard in range(2):
        recs = []
        for i in range(32):
            lab = int(r.integers(1, 3))
            img = r.integers(0, 40, size=(6, 6, 3), dtype=np.uint8)
            if lab == 1:
                img[:, :3, :] += 180
            else:
                img[:, 3:, :] += 180
            recs.append((lab, img))
        write_seq_file(str(tmp_path / f"part_{shard}.seq"), recs)

    ds = DataSet.seq_file_folder(str(tmp_path))
    assert ds.size() == 64

    # the documented pipeline shape: LabeledImage transformers then
    # ImgToSample (reference: SeqFileFolder -> BytesToBGRImg -> ... )
    from bigdl_tpu.dataset.image import ImgToSample, ImgNormalizer
    from bigdl_tpu.dataset.transformer import Transformer

    class ShiftLabel(Transformer):
        def __call__(self, it):
            for s in it:
                yield type(s)(s.feature, np.int32(s.label - 1))  # 1->0 based

    pipeline = (ds.transform(ImgNormalizer((127.5,) * 3, (127.5,) * 3))
                .transform(ImgToSample())
                .transform(ShiftLabel())
                .transform(SampleToMiniBatch(16, drop_last=True)))
    model = nn.Sequential(nn.Reshape([6 * 6 * 3]), nn.Linear(6 * 6 * 3, 2),
                          nn.LogSoftMax())
    Engine.reset()
    Engine.init()
    opt = (Optimizer(model, pipeline, nn.ClassNLLCriterion())
           .set_optim_method(Adam(5e-2))
           .set_end_when(Trigger.max_epoch(5)))
    opt.optimize()
    assert opt.optim_method.hyper["loss"] < 0.2


def test_record_generator_import(tmp_path):
    """bigdl-tpu-record-generator --from-seq re-shards .seq corpora into
    BDRecord shards (the drop-in import path)."""
    from bigdl_tpu.tools.record_generator import main
    from bigdl_tpu.utils.recordio import read_records

    write_seq_file(str(tmp_path / "in_0.seq"), _images(9, seed=4))
    out = str(tmp_path / "out.bdr")
    main(["--from-seq", "--folder", str(tmp_path), "--output", out,
          "--shards", "2"])
    recs = []
    for shard in sorted(os.listdir(tmp_path)):
        if "out.bdr-" in shard:
            recs += list(read_records(str(tmp_path / shard)))
    assert len(recs) == 9
    assert all(set(r) == {"data", "label"} for r in recs)


def test_shard_striding_and_cap(tmp_path):
    """Rank-strided shard assignment + equal-step cap (distributed=True)."""
    for shard, count in enumerate((6, 4)):
        write_seq_file(str(tmp_path / f"p{shard}.seq"),
                       _images(count, seed=shard))
    ds0 = SeqFileDataSet([str(tmp_path / "p0.seq"), str(tmp_path / "p1.seq")],
                         distributed=True, process_index=0, process_count=2)
    ds1 = SeqFileDataSet([str(tmp_path / "p0.seq"), str(tmp_path / "p1.seq")],
                         distributed=True, process_index=1, process_count=2)
    # both ranks truncate to the smaller shard's count (equal collectives)
    assert len(list(ds0.data(train=False))) == 4
    assert len(list(ds1.data(train=False))) == 4


def test_chaos_corrupt_record_skip_budget_two(tmp_path):
    """Injected corrupt records (chaos data.record, truncate mode — the
    detectable corruption: SequenceFiles carry no CRC) with skip budget
    2: the pass completes with exactly 2 quarantined records counted."""
    from bigdl_tpu.utils import chaos

    p = str(tmp_path / "c.seq")
    write_seq_file(p, _images(12, seed=7))
    with chaos.scoped("data.record=truncate@3,8"):
        ds = SeqFileDataSet([p], skip_budget=2)
        out = list(ds.data(train=False))
    assert len(out) == 10
    assert ds.last_quarantined == 2


def test_chaos_corrupt_record_budget_zero_fails_loud(tmp_path):
    """Default budget 0 keeps today's fail-loud semantics, now with the
    typed CorruptRecord carrying path + byte offset."""
    from bigdl_tpu.utils import chaos
    from bigdl_tpu.utils.recordio import CorruptRecord

    p = str(tmp_path / "c0.seq")
    write_seq_file(p, _images(6, seed=8))
    with chaos.scoped("data.record=truncate@2"):
        with pytest.raises(CorruptRecord) as ei:
            list(read_byte_records(p))
    assert ei.value.path == p and ei.value.offset is not None
    # CorruptRecord stays catchable as the historical types
    assert isinstance(ei.value, (IOError, ValueError))


def test_on_disk_truncated_record_quarantined(tmp_path):
    """Real corruption (file torn mid-final-record): structural
    validation catches the short value; budget 1 absorbs it, budget 0
    raises."""
    from bigdl_tpu.utils.recordio import CorruptRecord, SkipBudget

    p = str(tmp_path / "torn.seq")
    write_seq_file(p, _images(8, seed=9))
    data = open(p, "rb").read()
    open(p, "wb").write(data[:len(data) - 40])  # tear the last record
    with pytest.raises(CorruptRecord):
        list(read_byte_records(p))
    skip = SkipBudget(1)
    out = list(read_byte_records(p, skip=skip))
    assert len(out) == 7 and skip.count == 1
    assert skip.quarantined[0][0] == p  # (path, offset, reason) logged


def test_corrupt_sync_marker_fatal_regardless_of_budget(tmp_path):
    """Framing-level corruption cannot be resynced past: stays fatal even
    with budget (the record lengths themselves are untrusted)."""
    from bigdl_tpu.utils.recordio import CorruptRecord, SkipBudget

    p = str(tmp_path / "sync.seq")
    write_seq_file(p, _images(10, seed=10), sync_interval=4)
    data = bytearray(open(p, "rb").read())
    # find the first sync escape (-1 int32) and corrupt the marker after it
    esc = struct.pack(">i", -1)
    i = data.index(esc, 100)
    data[i + 4] ^= 0xFF
    open(p, "wb").write(bytes(data))
    with pytest.raises(CorruptRecord, match="sync marker"):
        list(read_byte_records(p, skip=SkipBudget(100)))


def test_class_filter_respects_equal_step_cap(tmp_path):
    """class_num filtering must feed the FILTERED counts into the
    distributed cap, or ranks would take unequal step counts into the
    per-step collectives."""
    r = np.random.default_rng(9)

    def shard(path, labels):
        write_seq_file(path, [(l, r.integers(0, 256, size=(4, 4, 3),
                                             dtype=np.uint8))
                              for l in labels])

    shard(str(tmp_path / "a.seq"), [1, 2, 3, 4, 5, 6])   # 3 survive <= 3
    shard(str(tmp_path / "b.seq"), [1, 1, 2, 9, 9, 9])   # 3 survive <= 3
    paths = [str(tmp_path / "a.seq"), str(tmp_path / "b.seq")]
    dss = [SeqFileDataSet(paths, class_num=3, distributed=True,
                          process_index=i, process_count=2)
           for i in range(2)]
    assert dss[0].size() == 6  # filtered global count, not 12
    n0 = len(list(dss[0].data(train=False)))
    n1 = len(list(dss[1].data(train=False)))
    assert n0 == n1 == 3
