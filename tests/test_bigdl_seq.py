"""BigDL native-format interop for the sequence/embedding zoo.

Two layers of evidence:
- READER fidelity: streams are hand-assembled in reference STRUCTURE
  (nn/RNN.scala:46-80, nn/LSTM.scala:74-184, nn/GRU.scala:79-180) from raw
  reference-layout weights, and the loaded model's forward is compared
  against the reference cell EQUATIONS computed independently in numpy —
  the reader cannot be validated by the writer here (circularity).
- ROUNDTRIP: save(load(x)) / load(save(m)) parity for every new class,
  including the SimpleRNN shape (models/rnn/SimpleRNN.scala:29-31) and a
  Graph DAG, plus a fine-tune step on the migrated model.
"""

import io

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.interop import bigdl as bigdl_fmt
from bigdl_tpu.interop.bigdl import _DescCache, _w_tensor, load_bytes
from bigdl_tpu.interop.bigdl_seq import _obj, _buffer, _container, _seq, \
    _time_distributed, _linear, _simple, _hiddens_shape
from bigdl_tpu.interop.javaser import JavaWriter

_PKG = "com.intel.analytics.bigdl.nn."


def _rand(shape, seed):
    return np.asarray(
        jax.random.normal(jax.random.PRNGKey(seed), shape), np.float32)


def _stream_bytes(root):
    from bigdl_tpu.interop.bigdl import _fill_base_fields
    _fill_base_fields(root)  # inherited AbstractModule defaults
    w = JavaWriter()
    w.write_object(root)
    return w.getvalue()


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


# ---------------------------------------------------------------------------
# reader vs the reference equations (hand-built streams)
# ---------------------------------------------------------------------------

def test_reader_rnncell_matches_reference_equations():
    I, H, B, T = 3, 4, 2, 5
    wi, bi = _rand((H, I), 0) * 0.3, _rand((H,), 1) * 0.1
    wh, bh = _rand((H, H), 2) * 0.3, _rand((H,), 3) * 0.1
    dc = _DescCache()
    pre = _time_distributed(dc, _linear(dc, wi, bi))
    h2h = _linear(dc, wh, bh)
    from bigdl_tpu.interop.bigdl_seq import _parallel_table
    pt = _parallel_table(dc)  # structure placeholder (empty container)
    cell_seq = _seq(dc, pt, _obj(dc, "CAddTable", [], []),
                    _simple(dc, "Tanh"),
                    _simple(dc, "Identity"))
    topo = _obj(dc, "RnnCell", [],
                [("hiddensShape", "[I", _hiddens_shape(dc, [H])),
                 ("h2h", "Lx;", h2h), ("cell", "Lx;", cell_seq)])
    rec = _container(dc, "Recurrent", [pre, topo])
    model = load_bytes(_stream_bytes(rec))

    x = _rand((B, T, I), 4)
    y, _ = model.apply(model.params, model.state, jnp.asarray(x))
    # reference recurrence: h_t = tanh(Wi x_t + bi + Wh h_{t-1} + bh)
    h = np.zeros((B, H), np.float32)
    expect = []
    for t in range(T):
        h = np.tanh(x[:, t] @ wi.T + bi + h @ wh.T + bh)
        expect.append(h)
    np.testing.assert_allclose(np.asarray(y), np.stack(expect, 1),
                               rtol=1e-4, atol=1e-5)


def test_reader_lstm_matches_reference_equations():
    """Gate chunk order on the wire is [input, gain(tanh), forget, output]
    (LSTM.scala:124-133); the reader must permute into ours."""
    I, H, B, T = 3, 4, 2, 4
    wi, bi = _rand((4 * H, I), 0) * 0.3, _rand((4 * H,), 1) * 0.1
    wh = _rand((4 * H, H), 2) * 0.3
    dc = _DescCache()
    pre = _time_distributed(dc, _linear(dc, wi, bi))
    cell_seq = _seq(dc, _linear(dc, wh, None))  # h2g, found by subtree scan
    topo = _obj(dc, "LSTM",
                [("I", "inputSize", I), ("I", "hiddenSize", H),
                 ("D", "p", 0.0)],
                [("hiddensShape", "[I", _hiddens_shape(dc, [H, H])),
                 ("cell", "Lx;", cell_seq)])
    rec = _container(dc, "Recurrent", [pre, topo])
    model = load_bytes(_stream_bytes(rec))

    x = _rand((B, T, I), 3)
    y, _ = model.apply(model.params, model.state, jnp.asarray(x))
    h = np.zeros((B, H), np.float32)
    c = np.zeros((B, H), np.float32)
    expect = []
    for t in range(T):
        pre_t = x[:, t] @ wi.T + bi + h @ wh.T
        ig = _sigmoid(pre_t[:, 0:H])            # input
        g = np.tanh(pre_t[:, H:2 * H])          # gain ("hidden")
        fg = _sigmoid(pre_t[:, 2 * H:3 * H])    # forget
        og = _sigmoid(pre_t[:, 3 * H:4 * H])    # output
        c = ig * g + fg * c
        h = og * np.tanh(c)
        expect.append(h)
    np.testing.assert_allclose(np.asarray(y), np.stack(expect, 1),
                               rtol=1e-4, atol=1e-5)


def test_reader_gru_matches_reference_equations():
    """Reference combination h' = (1-z)*cand + z*h (GRU.scala:155-172);
    ours is h' = (1-u)*h + u*cand with u = 1-z, so the z weights must be
    negated on the way in — exact, not approximate."""
    I, O, B, T = 3, 4, 2, 4
    wi, bi = _rand((3 * O, I), 0) * 0.3, _rand((3 * O,), 1) * 0.1
    wh2g = _rand((2 * O, O), 2) * 0.3
    whh = _rand((O, O), 3) * 0.3
    dc = _DescCache()
    pre = _time_distributed(dc, _linear(dc, wi, bi))
    cell_seq = _seq(dc, _linear(dc, wh2g, None), _linear(dc, whh, None))
    topo = _obj(dc, "GRU",
                [("I", "inputSize", I), ("I", "outputSize", O),
                 ("D", "p", 0.0)],
                [("hiddensShape", "[I", _hiddens_shape(dc, [O])),
                 ("cell", "Lx;", cell_seq)])
    rec = _container(dc, "Recurrent", [pre, topo])
    model = load_bytes(_stream_bytes(rec))

    x = _rand((B, T, I), 4)
    y, _ = model.apply(model.params, model.state, jnp.asarray(x))
    h = np.zeros((B, O), np.float32)
    expect = []
    for t in range(T):
        xt = x[:, t]
        r = _sigmoid(xt @ wi[:O].T + bi[:O] + h @ wh2g[:O].T)
        z = _sigmoid(xt @ wi[O:2 * O].T + bi[O:2 * O] + h @ wh2g[O:].T)
        cand = np.tanh(xt @ wi[2 * O:].T + bi[2 * O:] + (r * h) @ whh.T)
        h = (1 - z) * cand + z * h
        expect.append(h)
    np.testing.assert_allclose(np.asarray(y), np.stack(expect, 1),
                               rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# roundtrips
# ---------------------------------------------------------------------------

def _roundtrip(m, x, tmp_path, rtol=1e-4, atol=1e-5):
    m.build(jax.random.PRNGKey(0))
    y0, _ = m.apply(m.params, m.state, x)
    p = str(tmp_path / "model.bigdl")
    bigdl_fmt.save(m, p)
    m2 = bigdl_fmt.load(p)
    y1, _ = m2.apply(m2.params, m2.state, x)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y0),
                               rtol=rtol, atol=atol)
    # and a second generation: save(load(x)) is stable
    p2 = str(tmp_path / "model2.bigdl")
    bigdl_fmt.save(m2, p2)
    m3 = bigdl_fmt.load(p2)
    y2, _ = m3.apply(m3.params, m3.state, x)
    np.testing.assert_allclose(np.asarray(y2), np.asarray(y1),
                               rtol=1e-6, atol=1e-6)
    return m2


@pytest.mark.parametrize("cell_ctor", [
    lambda: nn.RnnCell(6, 8),
    lambda: nn.LSTM(6, 8),
    lambda: nn.GRU(6, 8),
])
def test_recurrent_roundtrip(cell_ctor, tmp_path):
    m = nn.Sequential()
    m.add(nn.Recurrent(cell_ctor()))
    m.add(nn.TimeDistributed(nn.Linear(8, 5)))
    x = jnp.asarray(_rand((3, 7, 6), 11))
    _roundtrip(m, x, tmp_path)


def test_simple_rnn_migrates_and_fine_tunes(tmp_path):
    """The SimpleRNN shape (models/rnn/SimpleRNN.scala:29-31): roundtrip
    through the wire format, then fine-tune the migrated model and verify
    the loss drops — the 'a reference user can keep training' contract."""
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.optim import Optimizer, SGD, Trigger

    I, H, O = 10, 12, 4
    m = nn.Sequential()
    m.add(nn.Recurrent(nn.RnnCell(I, H, jnp.tanh)))
    m.add(nn.TimeDistributed(nn.Linear(H, O)))
    m.build(jax.random.PRNGKey(1))
    p = str(tmp_path / "simple_rnn.bigdl")
    bigdl_fmt.save(m, p)
    model = bigdl_fmt.load(p)

    rng = np.random.RandomState(0)
    xs = rng.randn(64, 6, I).astype(np.float32)
    ys = (rng.rand(64, 6) * O).astype(np.int32)
    samples = [Sample(x, y) for x, y in zip(xs, ys)]
    ds = DataSet.array(samples).transform(
        SampleToMiniBatch(16, drop_last=True))
    crit = nn.TimeDistributedCriterion(nn.CrossEntropyCriterion())

    def loss_of(mdl):
        out, _ = mdl.apply(mdl.params, mdl.state, jnp.asarray(xs))
        return float(crit.forward(out, jnp.asarray(ys)))

    before = loss_of(model)
    opt = (Optimizer(model, ds, crit)
           .set_optim_method(SGD(learning_rate=0.05))
           .set_end_when(Trigger.max_epoch(3)))
    tuned = opt.optimize()
    assert loss_of(tuned) < before


def test_lookup_temporal_textclassifier_roundtrip(tmp_path):
    """The text-classifier front half: embedding + temporal conv
    (example/textclassification; nn/LookupTable.scala,
    nn/TemporalConvolution.scala)."""
    m = nn.Sequential()
    m.add(nn.LookupTable(20, 8, one_based=True))
    m.add(nn.TemporalConvolution(8, 6, 3))
    m.add(nn.ReLU())
    x = jnp.asarray(
        np.random.RandomState(3).randint(1, 21, (2, 9)).astype(np.float32))
    _roundtrip(m, x, tmp_path)


def test_graph_dag_roundtrip(tmp_path):
    """A diamond DAG through the Node wire graph (utils/DirectedGraph.scala
    Node element/nexts/prevs; Graph.scala inputs/outputs)."""
    inp = nn.Input()
    h = nn.Linear(10, 16)(inp)
    a = nn.ReLU()(h)
    b = nn.Tanh()(h)
    out = nn.CAddTable()([a, b])
    m = nn.Graph(inp, out)
    x = jnp.asarray(_rand((4, 10), 7))
    m2 = _roundtrip(m, x, tmp_path)
    assert isinstance(m2, nn.Graph)
    assert len(m2.modules) == len(m.modules)


def test_unsupported_cell_variant_fails_loud(tmp_path):
    """p!=0 LSTM restructures the reference graph (per-gate dropout
    stacks, no preTopology) — must refuse, not mis-load."""
    dc = _DescCache()
    topo = _obj(dc, "LSTM",
                [("I", "inputSize", 3), ("I", "hiddenSize", 4),
                 ("D", "p", 0.25)],
                [("hiddensShape", "[I", _hiddens_shape(dc, [4, 4])),
                 ("cell", "Lx;", _seq(dc))])
    rec = _container(dc, "Recurrent", [topo])
    with pytest.raises(ValueError, match="p!=0|preTopology"):
        load_bytes(_stream_bytes(rec))


@pytest.mark.parametrize("merge", ["sum", "concat"])
def test_birecurrent_roundtrip(merge, tmp_path):
    """BiRecurrent (BiRecurrent.scala:33): independent fwd/rev weights,
    CAddTable or JoinTable merge."""
    m = nn.Sequential()
    m.add(nn.BiRecurrent(nn.LSTM(5, 7), merge))
    x = jnp.asarray(_rand((2, 6, 5), 13))
    m2 = _roundtrip(m, x, tmp_path)
    bi = m2.modules[0]
    assert isinstance(bi, nn.BiRecurrent) and bi.merge == merge
    # fwd/rev weights must stay independent through the wire
    fwd_k = np.asarray(m2.params[0][0][0]["kernel"])
    rev_k = np.asarray(m2.params[0][1][0]["kernel"])
    assert not np.allclose(fwd_k, rev_k)


def test_frozen_canonical_fixture_loads_and_predicts():
    """A frozen stream written by the JVM-canonical writer (super chains,
    AbstractModule base fields, JOS field order): the BYTES are the
    contract, like the round-4 lenet fixture for the flat format."""
    import os
    import struct

    fx = os.path.join(os.path.dirname(__file__), "fixtures", "interop",
                      "simple_rnn_canonical.bigdl")
    raw = open(fx, "rb").read()
    assert struct.unpack(">HH", raw[:4]) == (0xACED, 5)
    assert b"com.intel.analytics.bigdl.nn.Recurrent" in raw
    assert b"abstractnn.AbstractModule" in raw      # real super chain
    assert b"com.intel.analytics.bigdl.nn.Container" in raw

    model = bigdl_fmt.load(fx)
    assert model.modules[0].modules[0].scale_w == 1.5  # base field survived
    x = np.fromfile(fx + ".x", dtype=np.float32).reshape(2, 5, 6)
    golden = np.fromfile(fx + ".y", dtype=np.float32).reshape(2, 5, 4)
    y, _ = model.apply(model.params, model.state, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(y), golden, rtol=1e-5, atol=1e-5)


def test_lstmpeephole_roundtrip(tmp_path):
    """LSTMPeephole (LSTMPeephole.scala:50): gate chunks [i,f,g,o] keyed
    by Narrow offsets, CMul peephole weights (i/f on c_prev, o on c_new)."""
    m = nn.Sequential()
    m.add(nn.Recurrent(nn.LSTMPeephole(5, 7)))
    x = jnp.asarray(_rand((2, 6, 5), 17))
    _roundtrip(m, x, tmp_path)


def test_reader_lstmpeephole_matches_reference_equations():
    """Hand-built reference-structure stream -> our cell equations
    (chunk order [i, f, g, o] — offsets 1, 1+H, 1+2H, 1+3H)."""
    from bigdl_tpu.interop.bigdl_seq import (_cadd, _concat_table,
                                             _parallel_table, _select)

    I, H, B, T = 3, 4, 2, 4
    wi, bi = _rand((4 * H, I), 0) * 0.3, _rand((4 * H,), 1) * 0.1
    whs = [_rand((H, H), 2 + c) * 0.3 for c in range(4)]
    peeps = {0: _rand((H,), 11) * 0.2, 1: _rand((H,), 12) * 0.2,
             3: _rand((H,), 13) * 0.2}
    dc = _DescCache()
    pre = _seq(dc, _obj(dc, "Dropout", [("D", "initP", 0.0)], []),
               _time_distributed(dc, _linear(dc, wi, bi)))

    def gate(chunk):
        members = [
            _obj(dc, "Narrow",
                 [("I", "dimension", 2), ("I", "offset", 1 + chunk * H),
                  ("I", "length", H)], []),
            _seq(dc, _linear(dc, whs[chunk], None)),
        ]
        if chunk in peeps:
            members.append(_obj(dc, "CMul", [],
                                [("weight", "Lx;",
                                  _w_tensor(dc, peeps[chunk]))]))
        return _seq(dc, _parallel_table(dc, *members), _cadd(dc, False),
                    _simple(dc, "Sigmoid" if chunk != 2 else "Tanh"))

    cell_seq = _seq(dc, gate(0), gate(1), gate(2), gate(3))
    topo = _obj(dc, "LSTMPeephole",
                [("I", "inputSize", I), ("I", "hiddenSize", H),
                 ("D", "p", 0.0)],
                [("cell", "Lx;", cell_seq)])
    topo.fields["hiddensShape"] = _hiddens_shape(dc, [H, H])
    rec = _container(dc, "Recurrent", [pre, topo])
    model = load_bytes(_stream_bytes(rec))

    x = _rand((B, T, I), 4)
    y, _ = model.apply(model.params, model.state, jnp.asarray(x))
    h = np.zeros((B, H), np.float32)
    c = np.zeros((B, H), np.float32)
    expect = []
    for t in range(T):
        pre_t = x[:, t] @ wi.T + bi
        i_pre = pre_t[:, 0:H] + h @ whs[0].T + peeps[0] * c
        f_pre = pre_t[:, H:2 * H] + h @ whs[1].T + peeps[1] * c
        g_pre = pre_t[:, 2 * H:3 * H] + h @ whs[2].T
        ig, fg = _sigmoid(i_pre), _sigmoid(f_pre)
        g = np.tanh(g_pre)
        c = fg * c + ig * g
        o_pre = pre_t[:, 3 * H:4 * H] + h @ whs[3].T + peeps[3] * c
        og = _sigmoid(o_pre)
        h = og * np.tanh(c)
        expect.append(h)
    np.testing.assert_allclose(np.asarray(y), np.stack(expect, 1),
                               rtol=1e-4, atol=1e-5)


def test_binarytreelstm_roundtrip(tmp_path):
    """BinaryTreeLSTM (BinaryTreeLSTM.scala:36, withGraph=true): the ten
    composer gate Linears re-home into the fused (2H,5H) kernel by graph
    ROLE (update=Tanh, f_l/f_r multiply the lc/rc Inputs, i multiplies
    the update, o gates h) — and back out into the reference-shaped
    leaf/composer Graphs."""
    m = nn.Sequential()
    m.add(nn.BinaryTreeLSTM(6, 5))
    m.build(jax.random.PRNGKey(3))
    # a tiny batch of two 3-leaf trees: nodes [leaf0, leaf1, (0,1), ...]
    inputs = jnp.asarray(_rand((2, 3, 6), 21))
    children = jnp.asarray(
        np.tile(np.array([[-1, -1], [-1, -1], [0, 1], [-1, -1]],
                         np.int32), (2, 1, 1)))
    leaf_ids = jnp.asarray(
        np.tile(np.array([0, 1, -1, -1], np.int32), (2, 1)))
    x = (inputs, children, leaf_ids)
    y0, _ = m.apply(m.params, m.state, x)

    p = str(tmp_path / "tree.bigdl")
    bigdl_fmt.save(m, p)
    raw = open(p, "rb").read()
    assert b"BinaryTreeLSTM" in raw and b"TreeLSTM" in raw
    m2 = bigdl_fmt.load(p)
    assert isinstance(m2.modules[0], nn.BinaryTreeLSTM)
    y1, _ = m2.apply(m2.params, m2.state, x)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y0),
                               rtol=1e-4, atol=1e-5)
    # second generation stability
    p2 = str(tmp_path / "tree2.bigdl")
    bigdl_fmt.save(m2, p2)
    m3 = bigdl_fmt.load(p2)
    y2, _ = m3.apply(m3.params, m3.state, x)
    np.testing.assert_allclose(np.asarray(y2), np.asarray(y1),
                               rtol=1e-6, atol=1e-6)
