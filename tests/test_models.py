"""Model-zoo tests (reference workload surface, SURVEY.md §2.11)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import models as M
from bigdl_tpu import nn


def _forward(model, shape, training=False):
    model.build(jax.random.key(0))
    x = jnp.ones(shape, jnp.float32)
    out, _ = model.apply(model.params, model.state, x, training=training,
                         rng=jax.random.key(1))
    return out


@pytest.mark.parametrize("depth", [20, 32])
def test_resnet_cifar_shape(depth):
    out = _forward(M.ResNet(depth, 10, "cifar10"), (2, 32, 32, 3))
    assert out.shape == (2, 10)


def test_resnet_imagenet_bottleneck_shape():
    out = _forward(M.ResNet(50, 17, "imagenet"), (1, 224, 224, 3))
    assert out.shape == (1, 17)


def test_resnet_shortcut_type_a_pads_channels():
    # type A shortcut (CIFAR default) must double channels with zeros, not conv
    model = M.ResNet(20, 10, "cifar10", shortcut_type=M.ShortcutType.A)
    out = _forward(model, (2, 32, 32, 3))
    assert out.shape == (2, 10)


def test_inception_v1_shapes():
    out = _forward(M.Inception_v1_NoAuxClassifier(11), (1, 224, 224, 3))
    assert out.shape == (1, 11)
    # log-softmax head: rows are log-probabilities
    np.testing.assert_allclose(np.exp(np.asarray(out)).sum(-1), 1.0, atol=1e-4)


def test_inception_v1_aux_concat():
    # full v1 concatenates [main | aux2 | aux1] along classes (3x classNum)
    out = _forward(M.Inception_v1(7), (1, 224, 224, 3))
    assert out.shape == (1, 21)


def test_vgg_cifar_shape():
    out = _forward(M.VggForCifar10(10), (2, 32, 32, 3))
    assert out.shape == (2, 10)


def test_autoencoder_roundtrip_shape():
    out = _forward(M.Autoencoder(32), (2, 28, 28, 1))
    assert out.shape == (2, 784)
    # Sigmoid head keeps output in (0, 1)
    assert float(out.min()) > 0.0 and float(out.max()) < 1.0


def test_simplernn_shape():
    out = _forward(M.SimpleRNN(40, 64, 40), (2, 25, 40))
    assert out.shape == (2, 25, 40)


def test_textclassifier_shape():
    out = _forward(M.TextClassifier(20, embed_dim=50, seq_len=500),
                   (2, 500, 50))
    assert out.shape == (2, 20)


def test_textclassifier_token_id_front():
    """vocab_size set: a trained LookupTable front takes raw token ids
    (batch, seq) instead of pre-embedded (batch, seq, dim) floats —
    the end-to-end text workload's input contract, and the table the
    embedding_row role shards 1/N."""
    model = M.TextClassifier(5, embed_dim=32, seq_len=192, vocab_size=64)
    model.build(jax.random.key(0))
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 64, (3, 192)),
                      jnp.int32)
    out, _ = model.apply(model.params, model.state, ids)
    assert out.shape == (3, 5)
    front = model.modules[0]
    assert isinstance(front, nn.LookupTable)
    assert front.param_roles() == {"weight": "embedding_row"}


def test_ptb_lstm_shape():
    model = M.PTBModel(500, 32, 32, num_layers=2)
    model.build(jax.random.key(0))
    x = jnp.ones((2, 35), jnp.int32)
    out, _ = model.apply(model.params, model.state, x, training=False, rng=None)
    assert out.shape == (2, 35, 500)


def test_resnet_trains_one_step():
    """Gradients flow through the residual graph (ConcatTable/CAddTable)."""
    model = M.ResNet(20, 10, "cifar10").build(jax.random.key(0))
    crit = nn.CrossEntropyCriterion()
    x = jnp.asarray(np.random.RandomState(0).randn(4, 32, 32, 3), jnp.float32)
    y = jnp.asarray([0, 1, 2, 3], jnp.int32)

    def loss_fn(p):
        out, ns = model.apply(p, model.state, x, training=True,
                              rng=jax.random.key(1))
        return crit.loss(out, y)

    loss, grads = jax.value_and_grad(loss_fn)(model.params)
    assert np.isfinite(float(loss))
    gnorm = sum(float(jnp.sum(jnp.abs(g))) for g in jax.tree.leaves(grads))
    assert gnorm > 0.0


def test_inception_v2_shapes():
    """BN-Inception (reference: models/inception/Inception_v2.scala)."""
    m = M.Inception_v2_NoAuxClassifier(10).build(jax.random.key(0))
    m.evaluate()
    out = m.forward(jnp.zeros((1, 224, 224, 3), jnp.float32))
    assert out.shape == (1, 10)
    m2 = M.Inception_v2(10).build(jax.random.key(0))
    m2.evaluate()
    out2 = m2.forward(jnp.zeros((1, 224, 224, 3), jnp.float32))
    assert out2.shape == (1, 30)  # [main | aux2 | aux1]


def test_inception_v2_block_trains():
    """One BN-Inception block: gradients flow through all four towers,
    including the stride-2 reduction variant."""
    from bigdl_tpu.models.inception import Inception_Layer_v2
    x = jnp.asarray(np.random.RandomState(1).randn(2, 14, 14, 192),
                    jnp.float32)
    for cfg, out_ch in (
            (((64,), (64, 64), (64, 96), ("avg", 32)), 256),
            (((0,), (128, 160), (64, 96), ("max", 0)), 448),
    ):
        m = Inception_Layer_v2(192, cfg).build(jax.random.key(0))

        def loss_fn(p):
            out, _ = m.apply(p, m.state, x, training=True,
                             rng=jax.random.key(1))
            return jnp.sum(jnp.square(out))

        loss, grads = jax.value_and_grad(loss_fn)(m.params)
        assert np.isfinite(float(loss))
        out, _ = m.apply(m.params, m.state, x)
        assert out.shape[-1] == out_ch


def test_alexnet_shape():
    """reference: example/loadmodel/AlexNet.scala (caffe grouped-conv
    variant, 227x227 crop)."""
    m = M.AlexNet(10).build(jax.random.key(0))
    m.evaluate()
    out = m.forward(jnp.zeros((2, 227, 227, 3), jnp.float32))
    assert out.shape == (2, 10)
    # log-probabilities (LogSoftMax head)
    assert np.allclose(np.exp(np.asarray(out)).sum(-1), 1.0, atol=1e-4)


def test_vit_forward_shape_and_training():
    """ViT: patch-embed + bidirectional transformer encoder; trains on the
    separable synthetic task through the standard Optimizer."""
    import bigdl_tpu.nn as nn
    from bigdl_tpu.common import set_seed
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.models import ViT
    from bigdl_tpu.optim import Adam, Optimizer, Trigger
    from bigdl_tpu.utils.engine import Engine

    Engine.reset()
    Engine.init()
    set_seed(0)
    model = ViT(image_size=28, patch_size=7, class_num=10, d_model=32,
                num_heads=4, num_layers=2, in_channels=1)
    x = jnp.zeros((2, 28, 28, 1), jnp.float32)
    out, _ = model.build(jax.random.key(0)).apply(
        model.params, model.state, x, training=False, rng=None)
    assert out.shape == (2, 10)
    np.testing.assert_allclose(np.asarray(jnp.exp(out)).sum(-1), 1.0,
                               rtol=1e-4)  # log-probs

    r = np.random.default_rng(0)
    images = r.normal(0.0, 0.1, size=(256, 28, 28, 1)).astype(np.float32)
    labels = r.integers(0, 10, size=256)
    for i, l in enumerate(labels):
        rr, c = divmod(int(l), 5)
        images[i, 4 + rr * 10: 12 + rr * 10, 2 + c * 5: 7 + c * 5, 0] += 1.5
    samples = [Sample(images[i], np.int32(labels[i])) for i in range(256)]
    ds = DataSet.array(samples).transform(
        SampleToMiniBatch(32, drop_last=True))
    opt = (Optimizer(model, ds, nn.ClassNLLCriterion())
           .set_optim_method(Adam(1e-3))
           .set_end_when(Trigger.max_epoch(6)))
    opt.optimize()
    assert opt.optim_method.hyper["loss"] < 1.0


def test_vit_rejects_indivisible_patches():
    from bigdl_tpu.models import ViT

    import pytest
    with pytest.raises(ValueError):
        ViT(image_size=28, patch_size=5)
