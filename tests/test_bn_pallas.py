"""Pallas fused-BN kernel parity vs the jnp oracle (interpret mode on CPU)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from bigdl_tpu.ops.batchnorm import bn_train, bn_train_reference

EPS = 1e-5


def _rand(shape, seed, dtype=jnp.float32):
    return jax.random.normal(jax.random.PRNGKey(seed), shape).astype(dtype)


@pytest.mark.parametrize("shape,block_r", [
    ((4, 6, 6, 3), 16),      # C below a lane, rows not a block multiple
    ((32, 128), 8),          # 2-D input, many row blocks
    ((2, 7, 5, 130), 32),    # C just past one lane, ragged rows
])
def test_forward_parity(shape, block_r):
    x = _rand(shape, 0)
    w = 1.0 + 0.1 * _rand(shape[-1:], 1)
    b = 0.1 * _rand(shape[-1:], 2)
    y, mean, var = bn_train(x, w, b, EPS, block_r, True)
    yr, mr, vr = bn_train_reference(x, w, b, EPS)
    np.testing.assert_allclose(np.asarray(mean), np.asarray(mr), atol=1e-5)
    np.testing.assert_allclose(np.asarray(var), np.asarray(vr), atol=1e-5)
    np.testing.assert_allclose(np.asarray(y), np.asarray(yr),
                               rtol=1e-4, atol=1e-5)


def test_grad_parity():
    shape = (8, 5, 5, 67)
    x = _rand(shape, 3)
    w = 1.0 + 0.1 * _rand(shape[-1:], 4)
    b = 0.1 * _rand(shape[-1:], 5)
    t = _rand(shape, 6)

    def loss_pallas(x, w, b):
        y, _, _ = bn_train(x, w, b, EPS, 16, True)
        return jnp.sum((y - t) ** 2)

    def loss_ref(x, w, b):
        y, mean, var = bn_train_reference(x, w, b, EPS)
        return jnp.sum((y - t) ** 2)

    gp = jax.grad(loss_pallas, argnums=(0, 1, 2))(x, w, b)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(x, w, b)
    for a, b_, name in zip(gp, gr, ("dx", "dw", "db")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-3, atol=1e-4, err_msg=name)


def test_bf16_activations():
    shape = (16, 4, 4, 32)
    x = _rand(shape, 7, jnp.bfloat16)
    w = jnp.ones((32,), jnp.float32)
    b = jnp.zeros((32,), jnp.float32)
    y, mean, var = bn_train(x, w, b, EPS, 32, True)
    assert y.dtype == jnp.bfloat16
    yr, mr, vr = bn_train_reference(x, w, b, EPS)
    np.testing.assert_allclose(np.asarray(mean), np.asarray(mr), atol=1e-2)
    np.testing.assert_allclose(
        np.asarray(y, np.float32), np.asarray(yr, np.float32),
        rtol=0.05, atol=0.05)


def test_module_pallas_impl(monkeypatch):
    """BatchNormalization routes through the kernel under BIGDL_TPU_BN_IMPL."""
    from bigdl_tpu.nn import SpatialBatchNormalization
    bn = SpatialBatchNormalization(12)
    params, state = bn.init(jax.random.PRNGKey(0))
    x = _rand((6, 5, 5, 12), 8)

    monkeypatch.setenv("BIGDL_TPU_BN_IMPL", "pallas_interpret")
    y1, s1 = bn.apply(params, state, x, training=True)
    monkeypatch.delenv("BIGDL_TPU_BN_IMPL")
    y0, s0 = bn.apply(params, state, x, training=True)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y0),
                               rtol=1e-4, atol=1e-5)
    for k in s0:
        np.testing.assert_allclose(np.asarray(s1[k]), np.asarray(s0[k]),
                                   rtol=1e-4, atol=1e-5, err_msg=k)


# ---------------------------------------------------------------------------
# GSPMD-composable sync-BN (round-4 verdict #3): kernel inside shard_map
# ---------------------------------------------------------------------------

def test_sync_kernel_shardmap_parity():
    """bn_train_sync inside shard_map over 8 shards == global-batch oracle,
    forward and grads (dw/db must NOT double-count the shard psum)."""
    from jax.sharding import Mesh, PartitionSpec as P
    from bigdl_tpu.ops.batchnorm import bn_train_sync
    from bigdl_tpu.utils.compat import shard_map_unchecked

    x = _rand((32, 6, 5), 0) * 2 + 1
    w = 1.0 + 0.1 * _rand((5,), 1)
    b = 0.1 * _rand((5,), 2)
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("data",))
    xs = P("data", None, None)

    def body(xl, w, b):
        return bn_train_sync(xl, w, b, EPS, "data", 1024, True)

    f = shard_map_unchecked(body, mesh=mesh,
                            in_specs=(xs, P(None), P(None)),
                            out_specs=(xs, P(None), P(None)))
    y, mean, var = jax.jit(f)(x, w, b)
    yr, mr, vr = bn_train_reference(x, w, b, EPS)
    np.testing.assert_allclose(np.asarray(y), np.asarray(yr),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(mean), np.asarray(mr), atol=1e-5)
    np.testing.assert_allclose(np.asarray(var), np.asarray(vr), atol=1e-5)

    t = _rand((32, 6, 5), 9)

    def loss_sync(x, w, b):
        return jnp.sum((f(x, w, b)[0] - t) ** 2)

    def loss_ref(x, w, b):
        return jnp.sum((bn_train_reference(x, w, b, EPS)[0] - t) ** 2)

    gs = jax.jit(jax.grad(loss_sync, argnums=(0, 1, 2)))(x, w, b)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(x, w, b)
    for a, b_, name in zip(gs, gr, ("dx", "dw", "db")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-3, atol=1e-4, err_msg=name)


def test_module_pallas_multidevice(monkeypatch):
    """BIGDL_TPU_BN_IMPL=pallas now works on a mesh: the layer wraps the
    kernel in shard_map over the Engine data axis (previously single-device
    only, nn/normalization.py round-3 caveat)."""
    from bigdl_tpu.nn import SpatialBatchNormalization
    from bigdl_tpu.utils.engine import Engine

    Engine.init()  # 8-device 'data' mesh from conftest's virtual CPUs
    bn = SpatialBatchNormalization(12)
    params, state = bn.init(jax.random.PRNGKey(0))
    x = _rand((16, 5, 5, 12), 8)  # batch divisible by the 8-way data axis

    monkeypatch.setenv("BIGDL_TPU_BN_IMPL", "pallas")
    y1, s1 = jax.jit(
        lambda p, s, x: bn.apply(p, s, x, training=True))(params, state, x)
    monkeypatch.delenv("BIGDL_TPU_BN_IMPL")
    y0, s0 = bn.apply(params, state, x, training=True)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y0),
                               rtol=1e-4, atol=1e-5)
    for k in s0:
        np.testing.assert_allclose(np.asarray(s1[k]), np.asarray(s0[k]),
                                   rtol=1e-4, atol=1e-5, err_msg=k)

    # gradients through the shard_map route match the jnp route
    t = _rand((16, 5, 5, 12), 10)

    def loss(p):
        y, _ = bn.apply(p, state, x, training=True)
        return jnp.sum((y - t) ** 2)

    monkeypatch.setenv("BIGDL_TPU_BN_IMPL", "pallas")
    g1 = jax.jit(jax.grad(loss))(params)
    monkeypatch.delenv("BIGDL_TPU_BN_IMPL")
    g0 = jax.grad(loss)(params)
    for k in g0:
        np.testing.assert_allclose(np.asarray(g1[k]), np.asarray(g0[k]),
                                   rtol=1e-3, atol=1e-4, err_msg=k)


def test_module_sync_axis_pallas(monkeypatch):
    """sync_axis= + BN_IMPL=pallas: the kernel runs per shard inside the
    caller's shard_map and psums stats over the named axis."""
    from jax.sharding import Mesh, PartitionSpec as P
    from bigdl_tpu.nn import BatchNormalization
    from bigdl_tpu.utils.compat import shard_map_unchecked

    bn = BatchNormalization(10, sync_axis="data")
    params, state = bn.init(jax.random.PRNGKey(0))
    x = _rand((24, 10), 11)
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("data",))
    xs = P("data", None)

    def body(xl, p, s):
        y, ns = bn.apply(p, s, xl, training=True)
        return y, ns

    monkeypatch.setenv("BIGDL_TPU_BN_IMPL", "pallas")
    y1, s1 = jax.jit(shard_map_unchecked(
        body, mesh=mesh,
        in_specs=(xs, P(None), P(None)),
        out_specs=(xs, P(None))))(x, params, state)
    monkeypatch.delenv("BIGDL_TPU_BN_IMPL")
    # oracle: plain global-batch BN (sync semantics == global batch)
    bn0 = BatchNormalization(10)
    y0, s0 = bn0.apply(params, state, x, training=True)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y0),
                               rtol=1e-4, atol=1e-5)
    for k in s0:
        np.testing.assert_allclose(np.asarray(s1[k]), np.asarray(s0[k]),
                                   rtol=1e-4, atol=1e-5, err_msg=k)
