"""End-to-end request tracing + the live /metrics plane (ISSUE 19).

Covers the acceptance surface: flow events are valid Perfetto-loadable
Chrome-trace JSON (one ``s``/``t``/``f`` arrow chain per request id);
multi-process traces merge into per-request critical paths keyed by the
``X-BigDL-Request-Id`` the fleet front propagates (and the HTTP tier
echoes); ``GET /metrics`` renders Prometheus text exposition with
correct counter/gauge/histogram line syntax and a fleet rollup; and with
tracing off and metrics unarmed the serving path emits no events, holds
no registry, and spawns no extra thread.
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import jax
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu import Engine
from bigdl_tpu.serve import InferenceServer
from bigdl_tpu.utils import chaos, file_io, metrics_export, telemetry
from bigdl_tpu.utils.telemetry import (FLOW_CAT, FLOW_NAME,
                                       REQUEST_ID_HEADER, Tracer,
                                       format_requests, merge_traces,
                                       request_breakdown)

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_TOOLS_DIR = os.path.join(_REPO_ROOT, "tools")
if _TOOLS_DIR not in sys.path:
    sys.path.insert(0, _TOOLS_DIR)


@pytest.fixture(autouse=True)
def _clean_observability(monkeypatch):
    monkeypatch.delenv("BIGDL_TPU_TRACE", raising=False)
    monkeypatch.delenv("BIGDL_TPU_METRICS", raising=False)
    telemetry.set_active(None)
    metrics_export.disarm()
    chaos.clear()
    yield
    tr = telemetry.get_active()
    if tr is not None:
        tr.close()
    telemetry.set_active(None)
    metrics_export.disarm()
    chaos.clear()


def _linear_model(seed=0):
    return nn.Sequential().add(nn.Linear(4, 3)).build(jax.random.key(seed))


# ---------------------------------------------------------------------------
# flow events: Perfetto-shaped JSON
# ---------------------------------------------------------------------------

def test_flow_events_are_perfetto_shaped(tmp_path):
    tr = Tracer(str(tmp_path), rank=0)
    telemetry.set_active(tr)
    rid = telemetry.mint_request_id()
    assert rid and isinstance(rid, str)
    telemetry.flow_start(rid, hop="front.admit")
    telemetry.flow_step(rid, hop="queue.enqueue", depth=1)
    telemetry.flow_finish(rid, hop="front.done", status="ok")
    path = tr.flush()
    blob = json.loads(file_io.get_filesystem(path).read_bytes(path))
    evs = [e for e in blob["traceEvents"] if e.get("name") == FLOW_NAME]
    assert [e["ph"] for e in evs] == ["s", "t", "f"]
    for e in evs:
        # the (name, cat, id) triple is what Perfetto uses to link the
        # arrow chain — every phase must carry the identical triple
        assert e["cat"] == FLOW_CAT and e["id"] == rid
        assert isinstance(e["ts"], (int, float))
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
    assert evs[-1].get("bp") == "e"  # arrow binds to the finish slice
    tr.close()


def test_minted_ids_are_unique_and_process_tagged(tmp_path):
    tr = Tracer(str(tmp_path), rank=3)
    telemetry.set_active(tr)
    ids = {telemetry.mint_request_id() for _ in range(100)}
    assert len(ids) == 100
    for rid in ids:
        assert rid.split("-")[0] == f"{os.getpid():x}"
        assert rid.split("-")[1] == "3"
    tr.close()


# ---------------------------------------------------------------------------
# cross-process merge: one request id, one critical path
# ---------------------------------------------------------------------------

def test_cross_process_merge_links_by_request_id(tmp_path):
    front = Tracer(str(tmp_path), rank=0)
    worker = Tracer(str(tmp_path), rank=10)
    rid = "feed-0-1"
    front.flow_start(rid, hop="front.admit")
    time.sleep(0.002)
    front.flow_step(rid, hop="front.send", member=1)
    time.sleep(0.002)
    worker.flow_step(rid, hop="queue.enqueue", depth=0)
    time.sleep(0.002)
    worker.flow_step(rid, hop="batch.assemble", size=1)
    time.sleep(0.002)
    worker.flow_step(rid, hop="resolve", status="ok")
    time.sleep(0.002)
    front.flow_finish(rid, hop="front.done", status="ok")
    front.close()
    worker.close()

    rb = request_breakdown(merge_traces(str(tmp_path)))
    assert rb["count"] == 1
    req = rb["requests"][rid]
    assert req["ranks"] == [0, 10]          # spans BOTH processes
    assert req["hops"] == 6
    assert req["status"] == "ok"
    assert req["members"] == [1]
    # the wall-clock gaps were attributed to pipeline segments
    assert set(req["segments"]) <= {"dispatch", "queue", "device",
                                    "transport", "failover"}
    assert req["segments"]["queue"] > 0 and req["segments"]["device"] > 0
    assert rb["total_p50_ms"] > 0 and rb["segments"]
    text = format_requests(rb)
    assert rid in text and "segment" in text


def test_failover_flow_carries_both_members(tmp_path):
    tr = Tracer(str(tmp_path), rank=0)
    rid = "dead-0-2"
    tr.flow_start(rid, hop="front.admit")
    tr.flow_step(rid, hop="front.send", member=0)
    tr.flow_step(rid, hop="fleet.retry", member=0, error="URLError")
    tr.flow_step(rid, hop="front.send", member=2)
    tr.flow_finish(rid, hop="front.done", status="ok")
    tr.close()
    rb = request_breakdown(merge_traces(str(tmp_path)))
    req = rb["requests"][rid]
    assert req["members"] == [0, 2]         # the two-member failover story
    assert "failover" in req["segments"]


# ---------------------------------------------------------------------------
# the serving path end to end (in-process server)
# ---------------------------------------------------------------------------

def test_server_submit_emits_owned_flow(tmp_path):
    Engine.init()
    tr = Tracer(str(tmp_path), rank=0)
    telemetry.set_active(tr)
    server = InferenceServer(_linear_model(), max_wait_ms=5,
                             example=np.zeros((4,), np.float32)).start()
    try:
        h = server.submit(np.zeros((4,), np.float32))
        h.result(timeout=30)
        assert h.rid and h.rid_owner        # minted here -> owns the "f"
    finally:
        server.stop()
        tr.close()
    merged = merge_traces(str(tmp_path))
    rb = request_breakdown(merged)
    assert h.rid in rb["requests"]
    phases = [e["ph"] for e in merged["traceEvents"]
              if e.get("name") == FLOW_NAME and str(e.get("id")) == h.rid]
    assert phases[0] == "s" and phases[-1] == "f"
    hops = [(e.get("args") or {}).get("hop") for e in merged["traceEvents"]
            if e.get("name") == FLOW_NAME and str(e.get("id")) == h.rid]
    assert "queue.enqueue" in hops and "batch.assemble" in hops \
        and "resolve" in hops


def test_disabled_mode_zero_overhead():
    """BIGDL_TPU_TRACE unset + metrics unarmed: no events, no registry,
    no extra thread — the PR 4 contract extended to the request plane."""
    Engine.init()
    server = InferenceServer(_linear_model(), max_wait_ms=5,
                             example=np.zeros((4,), np.float32)).start()
    try:
        before = threading.active_count()
        assert telemetry.mint_request_id() is None
        h = server.submit(np.zeros((4,), np.float32))
        h.result(timeout=30)
        assert h.rid is None and not h.rid_owner
        assert telemetry.get_active() is None
        assert metrics_export.registry() is None
        assert not metrics_export.armed()
        assert threading.active_count() == before
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# HTTP tier: request-id echo + GET /metrics
# ---------------------------------------------------------------------------

def test_http_request_id_echo_and_metrics(tmp_path):
    import serve_http

    Engine.init()
    tr = Tracer(str(tmp_path), rank=10)
    telemetry.set_active(tr)
    server = InferenceServer(_linear_model(), max_wait_ms=5,
                             example=np.zeros((4,), np.float32)).start()
    httpd = serve_http.serve_forever(server, "127.0.0.1", 0)
    port = httpd.server_address[1]
    base = f"http://127.0.0.1:{port}"
    rid = "cafe-0-7"
    try:
        req = urllib.request.Request(
            base + "/v1/predict",
            data=json.dumps({"inputs": [0.0, 0.0, 0.0, 0.0]}).encode(),
            headers={REQUEST_ID_HEADER: rid}, method="POST")
        with urllib.request.urlopen(req, timeout=30) as r:
            assert r.status == 200
            assert r.headers.get(REQUEST_ID_HEADER) == rid  # echoed back
        # serve_forever armed the plane (BIGDL_TPU_METRICS defaults on)
        assert metrics_export.armed()
        with urllib.request.urlopen(base + "/metrics", timeout=30) as r:
            assert r.status == 200
            assert r.headers.get("Content-Type") == \
                metrics_export.CONTENT_TYPE
            text = r.read().decode()
    finally:
        httpd.shutdown()
        server.stop()
        tr.close()
    assert "# TYPE bigdl_serve_requests_total counter" in text
    assert 'bigdl_serve_requests_total{status="ok"} 1' in text
    assert "# TYPE bigdl_serve_request_latency_seconds histogram" in text
    assert 'le="+Inf"' in text
    assert "bigdl_serve_request_latency_seconds_count 1" in text
    assert "bigdl_serve_slo_attainment" in text
    # the fleet-arrived id joined THIS process's trace as flow steps
    # (never "s"/"f" — the minting front owns the chain's endpoints)
    merged = merge_traces(str(tmp_path))
    rb = request_breakdown(merged)
    assert rid in rb["requests"]
    phases = {e["ph"] for e in merged["traceEvents"]
              if e.get("name") == FLOW_NAME and str(e.get("id")) == rid}
    assert phases == {"t"}


def test_metrics_disabled_knob_gives_404(monkeypatch):
    import serve_http

    monkeypatch.setenv("BIGDL_TPU_METRICS", "0")
    Engine.init()
    server = InferenceServer(_linear_model(), max_wait_ms=5,
                             example=np.zeros((4,), np.float32)).start()
    httpd = serve_http.serve_forever(server, "127.0.0.1", 0)
    try:
        assert not metrics_export.armed()   # serve_forever did NOT arm
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{httpd.server_address[1]}/metrics",
                timeout=30)
        assert ei.value.code == 404
    finally:
        httpd.shutdown()
        server.stop()


def test_retry_after_helper_rounds_up():
    from serve_http import retry_after_headers
    assert retry_after_headers(0.2) == {"Retry-After": "1"}
    assert retry_after_headers(1.0) == {"Retry-After": "1"}
    assert retry_after_headers(1.01) == {"Retry-After": "2"}
    assert retry_after_headers(7) == {"Retry-After": "7"}


# ---------------------------------------------------------------------------
# exposition format + fleet rollup (unit level)
# ---------------------------------------------------------------------------

def test_metrics_exposition_and_fleet_rollup():
    reg = metrics_export.MetricsRegistry(slo_ms=100.0, window=8)
    reg.observe_request(0.003, "ok")
    reg.observe_request(0.250, "RequestTimeout")
    reg.shed("overloaded")
    reg.feed_counter("serve", {"depth": 3, "batch_fill": 0.5})
    text = reg.render()
    # counter line syntax
    assert 'bigdl_serve_shed_total{cause="overloaded"} 1' in text
    assert 'bigdl_serve_requests_total{status="ok"} 1' in text
    # gauges fed straight from the telemetry.counter track
    assert "# TYPE bigdl_serve_depth gauge" in text
    assert "bigdl_serve_depth 3" in text
    assert "bigdl_serve_batch_fill 0.5" in text
    # histogram: cumulative le= buckets + _sum/_count
    assert ('bigdl_serve_request_latency_seconds_bucket{le="0.005"} 1'
            in text)
    assert ('bigdl_serve_request_latency_seconds_bucket{le="+Inf"} 2'
            in text)
    assert "bigdl_serve_request_latency_seconds_count 2" in text
    # SLO window: 1 of 2 resolved ok under 100ms
    assert "bigdl_serve_slo_attainment 0.5" in text

    parsed = metrics_export.parse_exposition(text)
    assert parsed["bigdl_serve_requests_total"]["type"] == "counter"
    assert parsed["bigdl_serve_request_latency_seconds"]["type"] == \
        "histogram"
    assert parsed["bigdl_serve_depth"]["type"] == "gauge"

    rollup = metrics_export.render_rollup("", {"0": text, "1": text})
    # fleet-wide sums for counters/histograms, member labels throughout
    assert "# TYPE fleet_bigdl_serve_requests_total counter" in rollup
    assert 'fleet_bigdl_serve_requests_total{status="ok"} 2' in rollup
    assert 'member="0"' in rollup and 'member="1"' in rollup
    # gauges are per-member only (no meaningless cross-member sum line)
    assert 'fleet_bigdl_serve_batch_fill{member="0"} 0.5' in rollup
    assert "fleet_bigdl_serve_batch_fill 1" not in rollup


def test_telemetry_counter_feeds_armed_registry(tmp_path):
    tr = Tracer(str(tmp_path), rank=0)
    telemetry.set_active(tr)
    reg = metrics_export.arm()
    telemetry.counter("serve.decode", tokens_out=128, slots_busy=2)
    text = reg.render()
    assert "bigdl_serve_decode_tokens_out 128" in text
    assert "bigdl_serve_decode_slots_busy 2" in text
    tr.close()


# ---------------------------------------------------------------------------
# the CLI + diff sections
# ---------------------------------------------------------------------------

def test_trace_report_requests_cli(tmp_path):
    tr = Tracer(str(tmp_path), rank=0)
    rid = "beef-0-1"
    tr.flow_start(rid, hop="front.admit")
    time.sleep(0.002)
    tr.flow_step(rid, hop="queue.enqueue", depth=0)
    time.sleep(0.002)
    tr.flow_finish(rid, hop="resolve", status="ok")
    tr.close()
    out = subprocess.run(
        [sys.executable, os.path.join(_TOOLS_DIR, "trace_report.py"),
         str(tmp_path), "--requests", "--json"],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "PYTHONPATH": _REPO_ROOT})
    assert out.returncode == 0, out.stderr
    rb = json.loads(out.stdout)
    assert rb["count"] == 1 and rid in rb["requests"]
    # human table too
    out = subprocess.run(
        [sys.executable, os.path.join(_TOOLS_DIR, "trace_report.py"),
         str(tmp_path), "--requests"],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "PYTHONPATH": _REPO_ROOT})
    assert out.returncode == 0, out.stderr
    assert rid in out.stdout


def test_diff_gains_fleet_and_decode_sections(tmp_path):
    from bigdl_tpu.utils.telemetry import (diff_breakdowns, format_diff,
                                           phase_breakdown)
    dirs = {}
    for name, n in (("a", 2), ("b", 5)):
        d = tmp_path / name
        tr = Tracer(str(d), rank=0)
        with tr.span("step", kind="proxy"):
            time.sleep(0.001)
        tr.counter("fleet", live=n)
        tr.counter("serve.decode", tokens_out=n * 10.0)
        tr.close()
        dirs[name] = phase_breakdown(merge_traces(str(d)))
    diff = diff_breakdowns(dirs["a"], dirs["b"])
    assert diff["fleet"]["live"] == {"last": [2.0, 5.0], "delta": 3.0}
    assert diff["decode"]["tokens_out"] == {"last": [20.0, 50.0],
                                            "delta": 30.0}
    text = format_diff(diff)
    assert "fleet:" in text and "decode:" in text
