"""Multi-host integration: 2 real OS processes, each with 2 virtual CPU
devices, jointly training LeNet through the public API.

This is the TPU-native analog of the reference's DistriOptimizerSpec
"distributed-without-a-cluster" pattern (SURVEY.md §4) taken one step
further: the processes here are REAL separate runtimes joined via
jax.distributed (Gloo over localhost), so the test drives the genuinely
multi-process paths — Engine.init_distributed's env contract,
DistributedDataSet per-process sharding, and Optimizer._put_batch's
`make_array_from_process_local_data` branch — that a single-process
8-device mesh cannot reach.
"""

import textwrap

import pytest

# subprocess integration: the slow lane (pyproject addopts)
pytestmark = pytest.mark.slow

from conftest import spawn_multihost_workers

_WORKER = textwrap.dedent("""
    import json, os, sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 2)

    import numpy as np
    from bigdl_tpu.utils.engine import Engine
    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.models.lenet import LeNet5
    from bigdl_tpu.optim import Optimizer, SGD, Trigger

    # env contract: BIGDL_TPU_COORDINATOR/NUM_PROCESSES/PROCESS_ID are set by
    # the launcher (the test); Engine.init() auto-joins the cluster.
    mesh = Engine.init()
    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 4, jax.device_count()

    rank = jax.process_index()
    r = np.random.default_rng(1234)  # SAME dataset on every process
    n, classes = 256, 10
    xs = r.normal(0.0, 0.1, size=(n, 28, 28, 1)).astype(np.float32)
    ys = r.integers(0, classes, size=n)
    for i, l in enumerate(ys):  # class k = bright k-th block (separable)
        row, col = divmod(int(l), 5)
        xs[i, 4 + row * 10: 12 + row * 10, 2 + col * 5: 7 + col * 5, 0] += 1.5
    samples = [Sample(x, np.int32(y)) for x, y in zip(xs, ys)]

    # DistributedDataSet: each process keeps its process_index-th shard
    ds = DataSet.rdd(samples).transform(SampleToMiniBatch(32, drop_last=True))

    from bigdl_tpu.optim import Adam, Top1Accuracy
    model = LeNet5(classes)
    opt = (Optimizer(model, ds, nn.ClassNLLCriterion())
           .set_optim_method(Adam(learning_rate=3e-3))
           .set_end_when(Trigger.max_epoch(8))
           # per-shard local scoring + cross-process result reduction:
           # every rank must report the SAME global accuracy
           .set_validation(Trigger.every_epoch(), ds, [Top1Accuracy()]))
    trained = opt.optimize()

    # verify the model learned AND both processes agree bit-for-bit
    w, _ = trained.get_parameters()
    digest = float(np.abs(np.asarray(w)).sum())
    loss = opt.optim_method.hyper["loss"]  # driver state Table (SGD.scala)
    print(json.dumps({"rank": rank, "loss": loss, "digest": digest,
                      "score": opt.optim_method.hyper.get("score")}),
          flush=True)
""")


def test_two_process_training(tmp_path):
    outs = spawn_multihost_workers(_WORKER, tmp_path)
    by_rank = {o["rank"]: o for o in outs}
    assert set(by_rank) == {0, 1}
    # training happened and converged on the separable data
    for o in outs:
        assert o["loss"] < 1.0, o
    # replicated parameters must be identical across processes
    assert by_rank[0]["digest"] == pytest.approx(by_rank[1]["digest"],
                                                 rel=1e-6)
    # validation ran multi-host: global accuracy, identical on every rank
    assert by_rank[0]["score"] == pytest.approx(by_rank[1]["score"])
    assert by_rank[0]["score"] > 0.8, by_rank


_STREAM_WORKER = textwrap.dedent("""
    import json, os, sys, glob, time
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 2)

    import numpy as np
    from bigdl_tpu.utils.engine import Engine
    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.models.lenet import LeNet5
    from bigdl_tpu.optim import Adam, Optimizer, Trigger
    from bigdl_tpu.utils.recordio import write_records

    mesh = Engine.init()
    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 4, jax.device_count()
    rank = jax.process_index()

    # shard dir lives next to this generated worker script (tmp_path) —
    # no env side channel
    shard_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "shards")
    if rank == 0:  # rank 0 writes the corpus; a marker file gates readers
        # same separable corpus as _WORKER above (duplicated because the
        # two worker scripts need it at different indentation; keep in sync)
        r = np.random.default_rng(1234)
        n, classes = 256, 10
        xs = r.normal(0.0, 0.1, size=(n, 28, 28, 1)).astype(np.float32)
        ys = r.integers(0, classes, size=n)
        for i, l in enumerate(ys):
            row, col = divmod(int(l), 5)
            xs[i, 4 + row * 10: 12 + row * 10,
               2 + col * 5: 7 + col * 5, 0] += 1.5
        samples = [Sample(x, np.int32(y)) for x, y in zip(xs, ys)]
        write_records(os.path.join(shard_dir, "c.bd"), samples, shards=4)
        open(os.path.join(shard_dir, "DONE"), "w").close()
    else:
        deadline = time.monotonic() + 120  # bounded: a rank-0 crash must
        while not os.path.exists(os.path.join(shard_dir, "DONE")):
            assert time.monotonic() < deadline, "rank 0 never wrote shards"
            time.sleep(0.1)

    paths = sorted(glob.glob(os.path.join(shard_dir, "c.bd-*")))
    # out-of-core distributed streaming: each process streams its strided
    # disjoint shard subset straight from disk every epoch
    ds = DataSet.record_stream(paths, distributed=True).transform(
        SampleToMiniBatch(32, drop_last=True))
    opt = (Optimizer(LeNet5(10), ds, nn.ClassNLLCriterion())
           .set_optim_method(Adam(learning_rate=3e-3))
           .set_end_when(Trigger.max_epoch(8)))
    trained = opt.optimize()
    w, _ = trained.get_parameters()
    digest = float(np.abs(np.asarray(w)).sum())
    print(json.dumps({"rank": rank, "loss": opt.optim_method.hyper["loss"],
                      "digest": digest}), flush=True)
""")


def test_two_process_streaming_shards(tmp_path):
    """Distributed out-of-core streaming: both processes train from their
    disjoint shard subsets and end with identical replicated weights."""
    (tmp_path / "shards").mkdir()
    outs = spawn_multihost_workers(_STREAM_WORKER, tmp_path)
    by_rank = {o["rank"]: o for o in outs}
    assert set(by_rank) == {0, 1}
    for o in outs:
        assert o["loss"] < 1.5, o
    assert by_rank[0]["digest"] == pytest.approx(by_rank[1]["digest"],
                                                 rel=1e-6)


_ZERO_CKPT_WORKER = textwrap.dedent("""
    import json, os, sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 2)

    import numpy as np
    from bigdl_tpu.utils.engine import Engine
    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.models.lenet import LeNet5
    from bigdl_tpu.optim import Adam, Optimizer, Trigger
    from bigdl_tpu.parallel import ShardedDataParallel

    mesh = Engine.init()
    assert jax.process_count() == 2, jax.process_count()
    rank = jax.process_index()

    # same separable corpus as _WORKER above (keep in sync)
    r = np.random.default_rng(1234)
    n, classes = 128, 10
    xs = r.normal(0.0, 0.1, size=(n, 28, 28, 1)).astype(np.float32)
    ys = r.integers(0, classes, size=n)
    for i, l in enumerate(ys):
        row, col = divmod(int(l), 5)
        xs[i, 4 + row * 10: 12 + row * 10, 2 + col * 5: 7 + col * 5, 0] += 1.5
    samples = [Sample(x, np.int32(y)) for x, y in zip(xs, ys)]
    ds = DataSet.rdd(samples).transform(SampleToMiniBatch(32, drop_last=True))

    ckpt = os.path.join(os.path.dirname(os.path.abspath(__file__)), "ckpt")
    opt = (Optimizer(LeNet5(classes), ds, nn.ClassNLLCriterion(),
                     strategy=ShardedDataParallel(min_size=1))
           .set_optim_method(Adam(learning_rate=3e-3))
           .set_end_when(Trigger.max_epoch(2))
           .set_checkpoint(ckpt, Trigger.every_epoch()))
    opt.optimize()

    from bigdl_tpu.utils import file_io
    latest = file_io.latest_checkpoint(ckpt)
    ok = latest is not None
    if ok and rank == 0:
        blob = file_io.load(latest[1])  # optimMethod.<n>: ZeRO slots live here
        leaves = [np.asarray(l) for l in
                  __import__("jax").tree.leaves(blob["opt_state"])]
        ok = all(np.all(np.isfinite(l)) for l in leaves if l.dtype.kind == "f")

    # multi-host bulk eval (_ShardedForward + _local_rows): every process
    # feeds the full rows and gets back complete host-local predictions
    from bigdl_tpu.optim import Evaluator, Top1Accuracy
    res = Evaluator(opt.model).test(DataSet.array(samples),
                                    [Top1Accuracy()], batch_size=32)
    acc, n_eval = res[0][1].result()
    print(json.dumps({"rank": rank, "ok": bool(ok),
                      "loss": opt.optim_method.hyper["loss"],
                      "eval_acc": float(acc), "eval_n": int(n_eval)}),
          flush=True)
""")


def test_two_process_zero_checkpoint(tmp_path):
    """Multi-host + ZeRO (ShardedDataParallel): checkpointing must
    process_allgather the process-sharded optimizer slots (a collective on
    every rank) before rank 0 writes — np.asarray on a non-addressable
    global array would otherwise crash the run."""
    (tmp_path / "ckpt").mkdir()
    outs = spawn_multihost_workers(_ZERO_CKPT_WORKER, tmp_path)
    by_rank = {o["rank"]: o for o in outs}
    assert set(by_rank) == {0, 1}
    for o in outs:
        assert o["ok"], o
        # bulk eval returned complete per-host results on both ranks
        assert o["eval_n"] == 128 and o["eval_acc"] > 0.5, o
    assert by_rank[0]["eval_acc"] == pytest.approx(by_rank[1]["eval_acc"])


def test_multihost_stale_peer_heartbeat_files_memory_store():
    """Multi-host liveness (utils/supervisor): every rank publishes a
    heartbeat file through file_io; when one rank stops beating — a dead
    host whose collectives would hang everyone forever — the survivors'
    supervisors flag it by rank and age, and the crash report / stall
    message carry the actionable "host N last seen Xs ago" line instead
    of an eternal allgather hang.  Driven on memory:// (the same file_io
    path a gs:// checkpoint dir would use) with an injected wall clock,
    so the scenario is deterministic and wall-clock-free."""
    import os
    from bigdl_tpu.utils.supervisor import Supervisor

    peer_dir = f"memory://mh_hb_{os.getpid()}"
    wall = {"now": 5000.0}
    sups = [Supervisor({"step": 60.0}, peer_dir=peer_dir, rank=r, world=3,
                       peer_stale=30.0, wall_clock=lambda: wall["now"],
                       publish_interval=0.0) for r in range(3)]
    try:
        for s in sups:
            s.beat("step")
            s._publish_heartbeat()
        # everyone fresh: no rank flags anyone
        assert all(s.check_peers() == {} for s in sups)

        # rank 2 dies (its supervised thread stops beating; in a real run
        # its monitor would keep publishing the STALE last-beat time)
        wall["now"] = 5094.0
        for s in sups[:2]:
            s.beat("step")
            s._publish_heartbeat()
        for survivor in sups[:2]:
            stale = survivor.check_peers()
            assert list(stale) == [2], stale
            assert stale[2] == pytest.approx(94.0)
        # the dead rank's own view flags the survivors as fresh
        assert sups[2].check_peers() == {}

        # the survivors' crash report names the host and its age
        report = sups[0].crash_report("step", 70.0, 60.0,
                                      sups[0].check_peers())
        assert report["stale_peers"] == {"2": 94.0}
    finally:
        import fsspec
        try:
            fsspec.filesystem("memory").rm("/", recursive=True)
        except Exception:
            pass
