"""Multi-host integration: 2 real OS processes, each with 2 virtual CPU
devices, jointly training LeNet through the public API.

This is the TPU-native analog of the reference's DistriOptimizerSpec
"distributed-without-a-cluster" pattern (SURVEY.md §4) taken one step
further: the processes here are REAL separate runtimes joined via
jax.distributed (Gloo over localhost), so the test drives the genuinely
multi-process paths — Engine.init_distributed's env contract,
DistributedDataSet per-process sharding, and Optimizer._put_batch's
`make_array_from_process_local_data` branch — that a single-process
8-device mesh cannot reach.
"""

import textwrap

import pytest

from conftest import spawn_multihost_workers

_WORKER = textwrap.dedent("""
    import json, os, sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 2)

    import numpy as np
    from bigdl_tpu.utils.engine import Engine
    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.models.lenet import LeNet5
    from bigdl_tpu.optim import Optimizer, SGD, Trigger

    # env contract: BIGDL_TPU_COORDINATOR/NUM_PROCESSES/PROCESS_ID are set by
    # the launcher (the test); Engine.init() auto-joins the cluster.
    mesh = Engine.init()
    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 4, jax.device_count()

    rank = jax.process_index()
    r = np.random.default_rng(1234)  # SAME dataset on every process
    n, classes = 256, 10
    xs = r.normal(0.0, 0.1, size=(n, 28, 28, 1)).astype(np.float32)
    ys = r.integers(0, classes, size=n)
    for i, l in enumerate(ys):  # class k = bright k-th block (separable)
        row, col = divmod(int(l), 5)
        xs[i, 4 + row * 10: 12 + row * 10, 2 + col * 5: 7 + col * 5, 0] += 1.5
    samples = [Sample(x, np.int32(y)) for x, y in zip(xs, ys)]

    # DistributedDataSet: each process keeps its process_index-th shard
    ds = DataSet.rdd(samples).transform(SampleToMiniBatch(32, drop_last=True))

    from bigdl_tpu.optim import Adam
    model = LeNet5(classes)
    opt = (Optimizer(model, ds, nn.ClassNLLCriterion())
           .set_optim_method(Adam(learning_rate=3e-3))
           .set_end_when(Trigger.max_epoch(8)))
    trained = opt.optimize()

    # verify the model learned AND both processes agree bit-for-bit
    w, _ = trained.get_parameters()
    digest = float(np.abs(np.asarray(w)).sum())
    loss = opt.optim_method.hyper["loss"]  # driver state Table (SGD.scala)
    print(json.dumps({"rank": rank, "loss": loss, "digest": digest}),
          flush=True)
""")


def test_two_process_training(tmp_path):
    outs = spawn_multihost_workers(_WORKER, tmp_path)
    by_rank = {o["rank"]: o for o in outs}
    assert set(by_rank) == {0, 1}
    # training happened and converged on the separable data
    for o in outs:
        assert o["loss"] < 1.0, o
    # replicated parameters must be identical across processes
    assert by_rank[0]["digest"] == pytest.approx(by_rank[1]["digest"],
                                                 rel=1e-6)
