"""Training-run supervision: stall watchdog, crash reports, multi-host
liveness (bigdl_tpu.utils.supervisor).

The failure mode under test is the one PR-1's checkpoint lineage cannot
reach: a hang raises no exception, so nothing recovers.  The supervisor
turns phase-tagged heartbeat silence into (1) a JSON crash report with
all-thread stacks + the heartbeat timeline and (2) a typed StallError
async-raised into the supervised thread, which the optimizer's existing
retry machinery converts into checkpoint-lineage recovery.  Chaos
``step.stall`` schedules make the whole loop deterministic.
"""

import glob
import json
import os
import threading
import time

import numpy as np
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
from bigdl_tpu.optim import Adam, Optimizer, Trigger
from bigdl_tpu.utils import chaos, file_io
from bigdl_tpu.utils import supervisor as sup_mod
from bigdl_tpu.utils.supervisor import StallError, Supervisor


@pytest.fixture(autouse=True)
def _clean_chaos_and_active():
    chaos.clear()
    yield
    chaos.clear()
    sup_mod.set_active(None)
    try:
        import fsspec
        fsspec.filesystem("memory").rm("/", recursive=True)
    except Exception:
        pass


def _named_threads(prefix):
    return [t for t in threading.enumerate() if t.name.startswith(prefix)]


# ---------------------------------------------------------------------------
# the watchdog core
# ---------------------------------------------------------------------------

def test_deadline_fires_report_written_and_stallerror_raised(tmp_path):
    """Missed deadline -> crash report JSON (>= 2 thread stacks, heartbeat
    timeline, chaos counters) + StallError delivered to the supervised
    thread."""
    caught = {}
    sup = Supervisor({"step": 0.2}, report_dir=str(tmp_path),
                     poll_interval=0.05)

    def worker():
        sup.beat("data")
        sup.beat("step")
        try:
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:  # the "hung collective"
                time.sleep(0.01)
            caught["err"] = None
        except StallError as e:
            caught["err"] = e

    t = threading.Thread(target=worker, name="supervised-worker")
    t.start()
    sup.start()
    t.join(10)
    sup.stop()
    assert not t.is_alive(), "StallError never landed in the worker"
    assert isinstance(caught["err"], StallError)
    assert "'step'" in str(caught["err"])  # names the stalled phase

    reports = glob.glob(str(tmp_path / "crash_report*.json"))
    assert len(reports) == 1
    rep = json.load(open(reports[0]))
    assert len(rep["threads"]) >= 2         # worker + monitor at least
    assert any("worker" in label for label in rep["threads"])
    assert rep["timeline"] and rep["timeline"][-1]["phase"] == "step"
    assert rep["phase"] == "step"
    assert rep["idle_seconds"] >= rep["deadline_seconds"]
    assert "chaos_counts" in rep and "platform" in rep


def test_healthy_run_no_report_no_stray_threads(tmp_path):
    sup = Supervisor({"step": 0.3}, report_dir=str(tmp_path),
                     poll_interval=0.05, name="sup-healthy")
    sup.start()
    for _ in range(6):
        sup.beat("step")
        time.sleep(0.05)
    sup.stop()
    assert glob.glob(str(tmp_path / "crash_report*")) == []
    assert sup.stalls == 0
    assert _named_threads("sup-healthy") == []  # monitor joined, not leaked


def test_report_written_through_file_io_on_memory_scheme():
    """Crash reports route through file_io like checkpoints: remote
    schemes work (the report must land where the checkpoints are, which
    is gs:// in production)."""
    dir_ = f"memory://sup_rep_{os.getpid()}"
    sup = Supervisor({"step": 1.0}, report_dir=dir_)
    path = sup._write_report("step", 2.0, 1.0, {}, "test stall")
    assert path is not None and path.startswith("memory://")
    rep = json.loads(file_io.get_filesystem(path).read_bytes(path))
    assert rep["reason"] == "test stall" and rep["threads"]


def test_exit_policy_validated_and_env_deadlines(monkeypatch):
    with pytest.raises(ValueError, match="unknown policy"):
        Supervisor({"step": 1.0}, policy="explode")
    monkeypatch.setenv("BIGDL_TPU_SUPERVISE_STEP", "12.5")
    monkeypatch.setenv("BIGDL_TPU_SUPERVISE_DEADLINE", "99")
    deadlines, default = sup_mod.env_deadlines()
    assert deadlines == {"step": 12.5} and default == 99.0
    monkeypatch.delenv("BIGDL_TPU_SUPERVISE_STEP")
    monkeypatch.delenv("BIGDL_TPU_SUPERVISE_DEADLINE")
    deadlines, default = sup_mod.env_deadlines()
    assert deadlines == {} and default is None


def test_deadline_lookup_prefix_and_default():
    sup = Supervisor({"compile": 900.0, "step": 1.0}, 300.0)
    assert sup.deadline_for("compile:resnet50") == 900.0  # bench stages
    assert sup.deadline_for("step") == 1.0
    assert sup.deadline_for("build:lenet") == 300.0
    sup2 = Supervisor({"step": 1.0})
    assert sup2.deadline_for("data") is None  # unwatched without default


def test_notify_refreshes_active_supervisor_current_phase():
    sup = Supervisor({"step": 5.0})
    sup.beat("step")
    count0 = sup._count
    sup_mod.set_active(sup)
    sup_mod.notify()  # the timing.measure_* heartbeat: phase preserved
    assert sup._count == count0 + 1
    assert sup._last[0] == "step"
    sup_mod.set_active(None)
    sup_mod.notify()  # no active supervisor: must be a no-op
    assert sup._count == count0 + 1


# ---------------------------------------------------------------------------
# multi-host liveness (heartbeat files)
# ---------------------------------------------------------------------------

def test_stale_peer_flagged_on_memory_store():
    """Two ranks share a memory:// heartbeat dir; rank 1 goes silent and
    rank 0's supervisor names it with its age."""
    peer_dir = f"memory://sup_hb_{os.getpid()}"
    wall = {"now": 1000.0}
    sup0 = Supervisor({"step": 60.0}, peer_dir=peer_dir, rank=0, world=2,
                      peer_stale=30.0, wall_clock=lambda: wall["now"],
                      publish_interval=0.0)
    sup1 = Supervisor({"step": 60.0}, peer_dir=peer_dir, rank=1, world=2,
                      peer_stale=30.0, wall_clock=lambda: wall["now"],
                      publish_interval=0.0)
    sup0.beat("step")
    sup1.beat("step")
    sup0._publish_heartbeat()
    sup1._publish_heartbeat()
    assert sup0.check_peers() == {}  # both fresh

    wall["now"] = 1094.0  # rank 1 never beats again
    sup0.beat("step")
    sup0._publish_heartbeat()
    stale = sup0.check_peers()
    assert list(stale) == [1]
    assert stale[1] == pytest.approx(94.0)
    # ...and the stall error message carries the actionable line
    msg_stale = sup0._check_peers(log=False)
    report = sup0.crash_report("step", 70.0, 60.0, msg_stale)
    assert report["stale_peers"] == {"1": 94.0}


def test_own_heartbeat_and_fresh_peers_not_flagged():
    peer_dir = f"memory://sup_hb2_{os.getpid()}"
    wall = {"now": 50.0}
    sups = [Supervisor({"step": 60.0}, peer_dir=peer_dir, rank=r, world=3,
                       peer_stale=30.0, wall_clock=lambda: wall["now"],
                       publish_interval=0.0) for r in range(3)]
    for s in sups:
        s.beat("step")
        s._publish_heartbeat()
    wall["now"] = 60.0
    for s in sups:
        assert s.check_peers() == {}  # nobody stale, self excluded


# ---------------------------------------------------------------------------
# end-to-end: chaos step.stall -> report -> StallError -> lineage recovery
# ---------------------------------------------------------------------------

def _dataset(n=64, d=6, batch=16):
    rng = np.random.default_rng(0)
    samples = [Sample(rng.standard_normal(d).astype(np.float32),
                      np.float32(i % 2)) for i in range(n)]
    return DataSet.array(samples).transform(
        SampleToMiniBatch(batch, drop_last=True))


def test_optimizer_stall_recovers_from_checkpoint_lineage(tmp_path):
    """The acceptance scenario: injected step.stall at minibatch 5
    (deterministic chaos) -> crash report JSON written with all-thread
    stacks + heartbeat timeline, StallError raised into the retry loop,
    run recovers from the PR-1 checkpoint lineage and completes."""
    import jax
    recovered = {}
    with chaos.scoped("step.stall=stall*30@5"):
        opt = (Optimizer(nn.Sequential().add(nn.Linear(6, 2)), _dataset(),
                         nn.CrossEntropyCriterion())
               .set_optim_method(Adam(1e-2))
               .set_end_when(Trigger.max_epoch(2))
               .set_checkpoint(str(tmp_path), Trigger.several_iteration(1))
               .set_supervision(step=0.4))
        orig = opt._load_snapshot

        def spy(mp, op=None):
            recovered["path"] = mp
            return orig(mp, op)

        opt._load_snapshot = spy
        trained = opt.optimize()
        assert chaos.counts()["step.stall"] > 5  # training continued past
    assert trained.params is not None
    assert all(np.all(np.isfinite(np.asarray(leaf)))
               for leaf in jax.tree.leaves(trained.params))
    # recovery actually walked the lineage
    assert "model." in recovered["path"]
    reports = sorted(glob.glob(str(tmp_path / "crash_report*.json")))
    assert reports, "no crash report written next to the checkpoint dir"
    rep = json.load(open(reports[0]))
    assert len(rep["threads"]) >= 2
    assert rep["timeline"], "heartbeat timeline missing"
    assert {e["phase"] for e in rep["timeline"]} >= {"data", "step"}
    assert rep["chaos_counts"].get("step.stall") == 5
    # the supervisor thread did not outlive optimize()
    assert _named_threads("bigdl-supervisor") == []


def test_optimizer_without_supervision_unchanged(tmp_path):
    """No deadlines configured anywhere -> no supervisor is built, no
    monitor thread runs (the tier-1 default)."""
    opt = (Optimizer(nn.Sequential().add(nn.Linear(6, 2)), _dataset(),
                     nn.CrossEntropyCriterion())
           .set_optim_method(Adam(1e-2))
           .set_end_when(Trigger.max_epoch(1)))
    assert opt._build_supervisor() is None
    opt.optimize()
    assert _named_threads("bigdl-supervisor") == []


def test_first_step_compile_phase_immune_to_step_deadline(tmp_path):
    """The first device step holds the XLA compile (~25s for LeNet on a
    TPU backend) and is tagged 'compile': a slow first step must NOT
    trip a tight steady-state 'step' deadline."""
    with chaos.scoped("step.stall=stall*1.2@1"):  # slow FIRST step only
        opt = (Optimizer(nn.Sequential().add(nn.Linear(6, 2)), _dataset(),
                         nn.CrossEntropyCriterion())
               .set_optim_method(Adam(1e-2))
               .set_end_when(Trigger.max_epoch(1))
               .set_checkpoint(str(tmp_path), Trigger.several_iteration(1))
               .set_supervision(step=0.4))  # << the 1.2s "compile"
        opt.optimize()
    assert glob.glob(str(tmp_path / "crash_report*.json")) == []
    # an explicit compile deadline DOES watch the first step
    sup = (Optimizer(nn.Sequential().add(nn.Linear(6, 2)), _dataset(),
                     nn.CrossEntropyCriterion())
           .set_supervision(step=0.4, compile=60)._build_supervisor())
    assert sup.deadline_for("compile") == 60
    assert sup.deadline_for("step") == 0.4


def test_data_stall_chaos_caught_by_data_deadline(tmp_path):
    """data.stall hangs the input pipeline; the 'data' deadline catches
    it and the run still completes via recovery."""
    with chaos.scoped("data.stall=stall*30@3"):
        opt = (Optimizer(nn.Sequential().add(nn.Linear(6, 2)), _dataset(),
                         nn.CrossEntropyCriterion())
               .set_optim_method(Adam(1e-2))
               .set_end_when(Trigger.max_epoch(2))
               .set_checkpoint(str(tmp_path), Trigger.several_iteration(1))
               .set_supervision(data=0.4))
        trained = opt.optimize()
    assert trained.params is not None
    assert glob.glob(str(tmp_path / "crash_report*.json"))
