"""Parametrized per-layer sweep: every exported nn class gets at least a
forward check, most get a numpy golden value, parameterized layers get a
finite-difference gradient check.

This is the rebuild's analog of the reference's per-layer spec coverage
(SURVEY.md §4: 122 Torch-golden specs under test/.../torch/ + 75 layer specs
under test/.../nn/).  The Torch7 oracle is replaced by numpy formulas and,
for a few criterions, by pytorch (CPU) as a genuine independent oracle.

`test_every_exported_class_is_tested` at the bottom enforces closure: any
newly exported nn class without a test anywhere under tests/ fails the suite.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import bigdl_tpu.nn as nn


def rng():
    return jax.random.key(7)


def _x(shape, seed=0, positive=False, scale=1.0):
    r = np.random.default_rng(seed)
    v = r.normal(size=shape).astype(np.float32) * scale
    if positive:
        v = np.abs(v) + 0.5
    return jnp.asarray(v)


# ---------------------------------------------------------------------------
# elementwise / activation golden sweep: (ctor, input, numpy golden)
# ---------------------------------------------------------------------------

ELEMENTWISE = [
    ("Abs", lambda: nn.Abs(), lambda: _x((3, 4)), lambda x: np.abs(x)),
    ("AddConstant", lambda: nn.AddConstant(2.5), lambda: _x((3, 4)),
     lambda x: x + 2.5),
    ("Clamp", lambda: nn.Clamp(-0.5, 0.5), lambda: _x((3, 4)),
     lambda x: np.clip(x, -0.5, 0.5)),
    ("Contiguous", lambda: nn.Contiguous(), lambda: _x((3, 4)), lambda x: x),
    ("Echo", lambda: nn.Echo(), lambda: _x((3, 4)), lambda x: x),
    ("Exp", lambda: nn.Exp(), lambda: _x((3, 4)), lambda x: np.exp(x)),
    ("Log", lambda: nn.Log(), lambda: _x((3, 4), positive=True),
     lambda x: np.log(x)),
    ("Sqrt", lambda: nn.Sqrt(), lambda: _x((3, 4), positive=True),
     lambda x: np.sqrt(x)),
    ("Square", lambda: nn.Square(), lambda: _x((3, 4)), lambda x: x * x),
    ("HardShrink", lambda: nn.HardShrink(0.5), lambda: _x((3, 4)),
     lambda x: np.where(np.abs(x) > 0.5, x, 0.0)),
    ("SoftShrink", lambda: nn.SoftShrink(0.5), lambda: _x((3, 4)),
     lambda x: np.where(x > 0.5, x - 0.5, np.where(x < -0.5, x + 0.5, 0.0))),
    ("Threshold", lambda: nn.Threshold(0.1, -1.0), lambda: _x((3, 4)),
     lambda x: np.where(x > 0.1, x, -1.0)),
    ("LogSigmoid", lambda: nn.LogSigmoid(), lambda: _x((3, 4)),
     lambda x: -np.log1p(np.exp(-x))),
    ("SoftPlus", lambda: nn.SoftPlus(2.0), lambda: _x((3, 4)),
     lambda x: np.log1p(np.exp(2.0 * x)) / 2.0),
    ("SoftMin", lambda: nn.SoftMin(), lambda: _x((3, 4)),
     lambda x: np.exp(-x) / np.exp(-x).sum(-1, keepdims=True)),
    ("Normalize", lambda: nn.Normalize(2.0), lambda: _x((3, 4)),
     lambda x: x / (np.linalg.norm(x, axis=-1, keepdims=True) + 1e-10)),
]


@pytest.mark.parametrize("name,ctor,inp,golden", ELEMENTWISE,
                         ids=[e[0] for e in ELEMENTWISE])
def test_elementwise_golden(name, ctor, inp, golden):
    m = ctor().build(rng())
    x = inp()
    y = m.forward(x)
    np.testing.assert_allclose(np.asarray(y), golden(np.asarray(x)),
                               rtol=1e-5, atol=1e-5)
    # input gradient exists and is finite
    gx = m.backward(x, jnp.ones_like(y))
    assert np.all(np.isfinite(np.asarray(gx)))


def test_rrelu_eval_and_train():
    m = nn.RReLU(0.1, 0.3).build(rng())
    x = _x((4, 5))
    m.evaluate()
    y_eval = np.asarray(m.forward(x))
    xn = np.asarray(x)
    np.testing.assert_allclose(
        y_eval, np.where(xn >= 0, xn, xn * 0.2), rtol=1e-5, atol=1e-6)
    m.training()
    out, _ = m.apply(m.params, m.state, x, training=True,
                     rng=jax.random.key(3))
    y_tr = np.asarray(out)
    neg = xn < 0
    slopes = y_tr[neg] / xn[neg]
    assert np.all(slopes >= 0.1 - 1e-6) and np.all(slopes <= 0.3 + 1e-6)
    np.testing.assert_allclose(y_tr[~neg], xn[~neg], rtol=1e-6)


# ---------------------------------------------------------------------------
# reductions / shape ops
# ---------------------------------------------------------------------------

REDUCTIONS = [
    ("Max", lambda: nn.Max(dim=1), (2, 5), lambda x: x.max(1)),
    ("Min", lambda: nn.Min(dim=1), (2, 5), lambda x: x.min(1)),
    ("Mean", lambda: nn.Mean(dimension=1), (2, 5), lambda x: x.mean(1)),
    ("Sum", lambda: nn.Sum(dimension=1), (2, 5), lambda x: x.sum(1)),
]


@pytest.mark.parametrize("name,ctor,shape,golden", REDUCTIONS,
                         ids=[e[0] for e in REDUCTIONS])
def test_reduction_golden(name, ctor, shape, golden):
    m = ctor().build(rng())
    x = _x(shape)
    np.testing.assert_allclose(np.asarray(m.forward(x)),
                               golden(np.asarray(x)), rtol=1e-5, atol=1e-6)


def test_view_reshape():
    m = nn.View(2, 3, 4).build(rng())
    x = _x((2, 12))
    y = m.forward(x)
    assert y.shape == (2, 3, 4)
    np.testing.assert_allclose(np.asarray(y),
                               np.asarray(x).reshape(2, 3, 4))


def test_infer_reshape_zero_and_minus_one():
    m = nn.InferReshape((0, -1)).build(rng())
    x = _x((2, 3, 4))
    y = m.forward(x)
    assert y.shape == (2, 12)
    np.testing.assert_allclose(np.asarray(y), np.asarray(x).reshape(2, 12))


def test_replicate():
    m = nn.Replicate(3, dim=1).build(rng())
    x = _x((2, 4))
    y = m.forward(x)
    assert y.shape == (2, 3, 4)
    np.testing.assert_allclose(np.asarray(y),
                               np.tile(np.asarray(x)[:, None, :], (1, 3, 1)))


def test_index_gathers_rows():
    m = nn.Index(dim=0).build(rng())
    t, idx = _x((5, 3)), jnp.asarray([3, 1])
    y = m.forward([t, idx])
    np.testing.assert_allclose(np.asarray(y), np.asarray(t)[[3, 1]])


def test_masked_select_outside_jit():
    m = nn.MaskedSelect().build(rng())
    t = _x((3, 4))
    mask = jnp.asarray(np.asarray(t) > 0)
    y = m.forward([t, mask])
    np.testing.assert_allclose(np.asarray(y),
                               np.asarray(t)[np.asarray(mask)])


# ---------------------------------------------------------------------------
# table ops
# ---------------------------------------------------------------------------

def _pair(seed=0):
    return [_x((3, 4), seed), _x((3, 4), seed + 1, positive=True)]


TABLE_OPS = [
    ("CSubTable", lambda: nn.CSubTable(), lambda a, b: a - b),
    ("CDivTable", lambda: nn.CDivTable(), lambda a, b: a / b),
    ("CMulTable", lambda: nn.CMulTable(), lambda a, b: a * b),
    ("CMinTable", lambda: nn.CMinTable(), lambda a, b: np.minimum(a, b)),
]


@pytest.mark.parametrize("name,ctor,golden", TABLE_OPS,
                         ids=[e[0] for e in TABLE_OPS])
def test_binary_table_op(name, ctor, golden):
    m = ctor().build(rng())
    a, b = _pair()
    y = m.forward([a, b])
    np.testing.assert_allclose(np.asarray(y),
                               golden(np.asarray(a), np.asarray(b)),
                               rtol=1e-5, atol=1e-6)


def test_flatten_table():
    m = nn.FlattenTable().build(rng())
    a, b = _pair()
    c = _x((2, 2), 9)
    out = m.forward([a, [b, c]])
    assert len(out) == 3
    np.testing.assert_allclose(np.asarray(out[2]), np.asarray(c))


def test_narrow_table_and_select_table():
    a, b = _pair()
    c = _x((2, 2), 5)
    out = nn.NarrowTable(1, 2).build(rng()).forward([a, b, c])
    assert len(out) == 2
    np.testing.assert_allclose(np.asarray(out[0]), np.asarray(b))
    sel = nn.SelectTable(2).build(rng()).forward([a, b, c])
    np.testing.assert_allclose(np.asarray(sel), np.asarray(c))


def test_split_table_and_pack_roundtrip():
    x = _x((2, 3, 4))
    parts = nn.SplitTable(1).build(rng()).forward(x)
    assert len(parts) == 3 and parts[0].shape == (2, 4)
    packed = nn.Pack(1).build(rng()).forward(parts)
    np.testing.assert_allclose(np.asarray(packed), np.asarray(x))


def test_mixture_table_blend():
    gate = jax.nn.softmax(_x((2, 3), 3), axis=-1)
    experts = [_x((2, 4), i + 10) for i in range(3)]
    y = nn.MixtureTable().build(rng()).forward([gate, experts])
    g = np.asarray(gate)
    expect = sum(g[:, i:i + 1] * np.asarray(experts[i]) for i in range(3))
    np.testing.assert_allclose(np.asarray(y), expect, rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# parameterized math layers (+ finite-difference gradient checks)
# ---------------------------------------------------------------------------

def _fd_check_param(m, x, leaf_path, idx, eps=1e-2, rtol=5e-2, atol=1e-3):
    """Finite-difference check of d(sum(out^2))/d(params[leaf_path][idx])
    — the reference's GradientChecker role."""
    def f(params):
        y, _ = m.apply(params, m.state, x)
        leaves = [jnp.sum(jnp.square(t)) for t in jax.tree.leaves(y)]
        return sum(leaves)

    g = jax.grad(f)(m.params)

    def peek(tree):
        node = tree
        for k in leaf_path:
            node = node[k]
        return node

    grad_val = float(peek(g)[idx])
    plus = jax.tree.map(lambda t: t, m.params)
    minus = jax.tree.map(lambda t: t, m.params)

    def poke(tree, delta):
        node = tree
        for k in leaf_path[:-1]:
            node = node[k]
        node[leaf_path[-1]] = node[leaf_path[-1]].at[idx].add(delta)

    poke(plus, eps)
    poke(minus, -eps)
    fd = (float(f(plus)) - float(f(minus))) / (2 * eps)
    np.testing.assert_allclose(grad_val, fd, rtol=rtol, atol=atol)


def test_bilinear_golden_and_grad():
    m = nn.Bilinear(3, 4, 2).build(rng())
    x1, x2 = _x((2, 3)), _x((2, 4), 1)
    y = m.forward([x1, x2])
    w, b = np.asarray(m.params["weight"]), np.asarray(m.params["bias"])
    expect = np.einsum("bi,kij,bj->bk", np.asarray(x1), w, np.asarray(x2)) + b
    np.testing.assert_allclose(np.asarray(y), expect, rtol=1e-4, atol=1e-5)
    _fd_check_param(m, [x1, x2], ("weight",), (0, 1, 2))


def test_cadd_cmul_golden_and_grad():
    x = _x((3, 4))
    ma = nn.CAdd((4,)).build(rng())
    np.testing.assert_allclose(np.asarray(ma.forward(x)),
                               np.asarray(x) + np.asarray(ma.params["bias"]),
                               rtol=1e-6)
    _fd_check_param(ma, x, ("bias",), (1,))
    mm = nn.CMul((4,)).build(rng())
    np.testing.assert_allclose(np.asarray(mm.forward(x)),
                               np.asarray(x) * np.asarray(mm.params["weight"]),
                               rtol=1e-6)
    _fd_check_param(mm, x, ("weight",), (2,))


def test_cosine_layer_golden():
    m = nn.Cosine(4, 3).build(rng())
    x = _x((2, 4))
    y = m.forward(x)
    xn_ = np.asarray(x)
    w = np.asarray(m.params["weight"])
    xn = xn_ / (np.linalg.norm(xn_, axis=-1, keepdims=True) + 1e-12)
    wn = w / (np.linalg.norm(w, axis=-1, keepdims=True) + 1e-12)
    np.testing.assert_allclose(np.asarray(y), xn @ wn.T, rtol=1e-4, atol=1e-5)
    _fd_check_param(m, x, ("weight",), (0, 1))


def test_euclidean_layer_golden():
    m = nn.Euclidean(4, 3).build(rng())
    x = _x((2, 4))
    y = m.forward(x)
    w = np.asarray(m.params["weight"])
    expect = np.sqrt(
        ((np.asarray(x)[:, None, :] - w[None]) ** 2).sum(-1) + 1e-12)
    np.testing.assert_allclose(np.asarray(y), expect, rtol=1e-4, atol=1e-5)
    _fd_check_param(m, x, ("weight",), (1, 2))


def test_rowwise_pair_layers_golden():
    a, b = _x((3, 4)), _x((3, 4), 1)
    an, bn = np.asarray(a), np.asarray(b)
    np.testing.assert_allclose(
        np.asarray(nn.DotProduct().build(rng()).forward([a, b])),
        (an * bn).sum(-1), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(nn.PairwiseDistance(2).build(rng()).forward([a, b])),
        np.linalg.norm(an - bn, axis=-1), rtol=1e-5)
    cos = np.asarray(nn.CosineDistance().build(rng()).forward([a, b]))
    expect = (an * bn).sum(-1) / (
        np.linalg.norm(an, axis=-1) * np.linalg.norm(bn, axis=-1))
    np.testing.assert_allclose(cos, expect, rtol=1e-4, atol=1e-5)


def test_mm_mv_golden():
    a, b = _x((2, 3, 4)), _x((2, 4, 5), 1)
    y = nn.MM().build(rng()).forward([a, b])
    np.testing.assert_allclose(np.asarray(y),
                               np.asarray(a) @ np.asarray(b),
                               rtol=1e-4, atol=1e-5)
    yt = nn.MM(trans_a=True).build(rng()).forward(
        [jnp.swapaxes(a, -1, -2), b])
    np.testing.assert_allclose(np.asarray(yt), np.asarray(y),
                               rtol=1e-4, atol=1e-5)
    m, v = _x((2, 3, 4)), _x((2, 4), 1)
    got = nn.MV().build(rng()).forward([m, v])
    np.testing.assert_allclose(
        np.asarray(got),
        np.einsum("bij,bj->bi", np.asarray(m), np.asarray(v)),
        rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# containers
# ---------------------------------------------------------------------------

def test_bottle_flattens_leading_dims():
    inner = nn.Linear(4, 2)
    m = nn.Bottle(inner, n_input_dim=2).build(rng())
    x = _x((3, 5, 4))
    y = m.forward(x)
    assert y.shape == (3, 5, 2)
    w = np.asarray(m.params[0]["weight"])
    b = np.asarray(m.params[0]["bias"])
    np.testing.assert_allclose(np.asarray(y),
                               np.asarray(x) @ w.T + b, rtol=1e-4, atol=1e-5)


def test_parallel_table_applies_per_element():
    m = nn.ParallelTable(nn.Linear(3, 2), nn.ReLU()).build(rng())
    x1, x2 = _x((2, 3)), _x((2, 5))
    y1, y2 = m.forward([x1, x2])
    assert y1.shape == (2, 2)
    np.testing.assert_allclose(np.asarray(y2),
                               np.maximum(np.asarray(x2), 0.0))


def test_map_table_shares_parameters():
    m = nn.MapTable(nn.Linear(3, 2)).build(rng())
    x = _x((2, 3))
    y1, y2 = m.forward([x, x])
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2))
    # one underlying param set despite two applications
    assert len(m.params) == 1


def test_container_and_cell_hierarchy():
    assert isinstance(nn.Sequential(), nn.Container)
    assert isinstance(nn.MapTable(nn.ReLU()), nn.Container)
    assert issubclass(nn.ConvLSTMPeephole, nn.Cell)
    assert issubclass(nn.LSTM, nn.Cell)


def test_module_node_graph_construction():
    """ModuleNode is the Graph-building node handle (reference:
    Graph.scala ModuleNode / utils/Node.scala)."""
    inp = nn.Input()
    h = nn.Linear(4, 3)(inp)
    out = nn.Linear(3, 2)(h)
    assert isinstance(h, nn.ModuleNode)
    g = nn.Graph([inp], [out]).build(rng())
    y = g.forward(_x((2, 4)))
    assert y.shape == (2, 2)


# ---------------------------------------------------------------------------
# convolutional / pooling extras
# ---------------------------------------------------------------------------

def test_volumetric_convolution_shape_and_grad():
    m = nn.VolumetricConvolution(2, 3, 3, 3, 3).build(rng())
    x = _x((1, 5, 6, 6, 2))
    y = m.forward(x)
    assert y.shape == (1, 3, 4, 4, 3)
    _fd_check_param(m, x, ("bias",), (0,), rtol=5e-2, atol=5e-3)


def test_volumetric_max_pooling_golden():
    m = nn.VolumetricMaxPooling(2, 2, 2).build(rng())
    x = _x((1, 4, 4, 4, 2))
    y = m.forward(x)
    assert y.shape == (1, 2, 2, 2, 2)
    xn = np.asarray(x)
    expect = xn.reshape(1, 2, 2, 2, 2, 2, 2, 2).max(axis=(2, 4, 6))
    np.testing.assert_allclose(np.asarray(y), expect, rtol=1e-6)


def test_spatial_share_convolution_matches_spatial_convolution():
    """SpatialShareConvolution is the reference's memory-sharing variant of
    SpatialConvolution (same math, SpatialShareConvolution.scala) — outputs
    must be identical given identical params."""
    a = nn.SpatialConvolution(2, 3, 3, 3).build(rng())
    b = nn.SpatialShareConvolution(2, 3, 3, 3).build(rng())
    b.attach(a.params, a.state)
    x = _x((2, 6, 6, 2))
    np.testing.assert_allclose(np.asarray(a.forward(x)),
                               np.asarray(b.forward(x)), rtol=1e-6)


def test_roi_pooling_golden():
    m = nn.RoiPooling(2, 2, spatial_scale=1.0).build(rng())
    feats = _x((1, 8, 8, 3))
    rois = jnp.asarray([[0, 0, 0, 3, 3]], jnp.float32)
    y = m.forward([feats, rois])
    assert y.shape == (1, 2, 2, 3)
    region = np.asarray(feats)[0, 0:4, 0:4, :]
    expect = region.reshape(2, 2, 2, 2, 3).max(axis=(1, 3))
    np.testing.assert_allclose(np.asarray(y)[0], expect, rtol=1e-5)


def test_conv_lstm_peephole_in_recurrent():
    cell = nn.ConvLSTMPeephole(2, 4, 3, 3)
    m = nn.Recurrent(cell).build(rng())
    x = _x((1, 3, 5, 5, 2))  # (batch, time, H, W, C)
    y = m.forward(x)
    assert y.shape == (1, 3, 5, 5, 4)
    assert np.all(np.isfinite(np.asarray(y)))


def test_conv_lstm_peephole_3d_in_recurrent():
    cell = nn.ConvLSTMPeephole3D(2, 3, 3, 3)
    m = nn.Recurrent(cell).build(rng())
    x = _x((1, 2, 4, 5, 5, 2))  # (batch, time, D, H, W, C)
    y = m.forward(x)
    assert y.shape == (1, 2, 4, 5, 5, 3)
    assert np.all(np.isfinite(np.asarray(y)))
    # gradient flows through the scan + 3D conv
    gx = m.backward(x, jnp.ones_like(y))
    assert np.all(np.isfinite(np.asarray(gx)))


# ---------------------------------------------------------------------------
# local normalization family
# ---------------------------------------------------------------------------

def test_spatial_subtractive_normalization_zeroes_constant_input():
    m = nn.SpatialSubtractiveNormalization(2, 5).build(rng())
    x = jnp.full((1, 9, 9, 2), 3.0)
    y = np.asarray(m.forward(x))
    assert y.shape == (1, 9, 9, 2)
    # center pixels: local mean == value -> ~0 (borders may differ)
    np.testing.assert_allclose(y[0, 4, 4], 0.0, atol=1e-4)


def test_spatial_divisive_normalization_scales_down_variance():
    m = nn.SpatialDivisiveNormalization(2, 5).build(rng())
    x = _x((1, 9, 9, 2), scale=4.0)
    y = np.asarray(m.forward(x))
    assert y.shape == x.shape
    assert np.all(np.isfinite(y))
    assert np.std(y) < np.std(np.asarray(x))


def test_spatial_contrastive_normalization_runs():
    m = nn.SpatialContrastiveNormalization(2, 5).build(rng())
    x = _x((1, 9, 9, 2))
    y = np.asarray(m.forward(x))
    assert y.shape == x.shape and np.all(np.isfinite(y))


def test_spatial_within_channel_lrn_suppresses_large_windows():
    m = nn.SpatialWithinChannelLRN(3, alpha=1.0, beta=0.75).build(rng())
    x = jnp.full((1, 7, 7, 2), 2.0)
    y = np.asarray(m.forward(x))
    assert y.shape == x.shape
    assert np.all(y[0, 3, 3] < 2.0)  # denominator > 1 for constant maps


# ---------------------------------------------------------------------------
# criterions
# ---------------------------------------------------------------------------

def test_l1_cost_and_penalty_golden():
    x = _x((3, 4))
    got = float(nn.L1Cost().loss(x, None))
    np.testing.assert_allclose(got, np.abs(np.asarray(x)).sum(), rtol=1e-5)
    got = float(nn.L1Penalty(0.3).loss(x))
    np.testing.assert_allclose(got, 0.3 * np.abs(np.asarray(x)).sum(),
                               rtol=1e-5)


def test_cosine_distance_criterion_zero_at_equality():
    x = _x((3, 4))
    assert float(nn.CosineDistanceCriterion().loss(x, x)) < 1e-5
    y = -x
    np.testing.assert_allclose(
        float(nn.CosineDistanceCriterion().loss(x, y)), 2.0, rtol=1e-4)


def test_class_simplex_criterion_zero_at_vertex():
    c = nn.ClassSimplexCriterion(4)
    t = jnp.asarray([0, 2], jnp.int32)
    out = c.simplex[np.asarray(t)]
    assert float(c.loss(out, t)) < 1e-10
    assert float(c.loss(out + 0.1, t)) > 0.0


def test_l1_hinge_embedding_criterion_golden():
    a, b = _x((3, 4)), _x((3, 4), 1)
    d = np.abs(np.asarray(a) - np.asarray(b)).sum(-1)
    t = jnp.asarray([1.0, -1.0, 1.0])
    got = float(nn.L1HingeEmbeddingCriterion(margin=2.0).loss([a, b], t))
    expect = np.mean([d[0], max(0.0, 2.0 - d[1]), d[2]])
    np.testing.assert_allclose(got, expect, rtol=1e-5)


def test_margin_ranking_criterion_golden():
    x1, x2 = _x((4,)), _x((4,), 1)
    t = jnp.asarray([1.0, -1.0, 1.0, -1.0])
    got = float(nn.MarginRankingCriterion(margin=0.5).loss([x1, x2], t))
    d = np.asarray(x1) - np.asarray(x2)
    expect = np.maximum(0.0, -np.asarray(t) * d + 0.5).mean()
    np.testing.assert_allclose(got, expect, rtol=1e-5)


def test_soft_margin_criterion_vs_torch():
    torch = pytest.importorskip("torch")
    x, t = _x((3, 4)), jnp.asarray(np.sign(np.asarray(_x((3, 4), 5))))
    got = float(nn.SoftMarginCriterion().loss(x, t))
    expect = torch.nn.SoftMarginLoss()(
        torch.tensor(np.asarray(x)), torch.tensor(np.asarray(t))).item()
    np.testing.assert_allclose(got, expect, rtol=1e-5)


def test_multi_label_margin_criterion_vs_torch():
    torch = pytest.importorskip("torch")
    x = _x((2, 4))
    t = np.array([[2, 0, -1, -1], [1, -1, -1, -1]], np.int64)
    got = float(nn.MultiLabelMarginCriterion().loss(x, jnp.asarray(t)))
    expect = torch.nn.MultiLabelMarginLoss()(
        torch.tensor(np.asarray(x)), torch.tensor(t)).item()
    np.testing.assert_allclose(got, expect, rtol=1e-4)


def test_smooth_l1_with_weights_golden():
    x, t = _x((3, 4)), _x((3, 4), 1)
    d = np.asarray(x) - np.asarray(t)
    ad = np.abs(d)
    base = np.where(ad < 1.0, 0.5 * d * d, ad - 0.5).sum()
    got = float(nn.SmoothL1CriterionWithWeights(sigma=1.0).loss(x, [t]))
    np.testing.assert_allclose(got, base, rtol=1e-5)
    got_n = float(nn.SmoothL1CriterionWithWeights(sigma=1.0, num=3).loss(
        x, [t]))
    np.testing.assert_allclose(got_n, base / 3.0, rtol=1e-5)


# ---------------------------------------------------------------------------
# initialization methods
# ---------------------------------------------------------------------------

def test_const_initializers():
    m = nn.Linear(4, 3).build(rng())
    m.set_init_method(weight_init=nn.Zeros(), bias_init=nn.Ones())
    assert float(jnp.abs(m.params["weight"]).sum()) == 0.0
    np.testing.assert_allclose(np.asarray(m.params["bias"]), 1.0)
    m.set_init_method(weight_init=nn.ConstInitMethod(0.3),
                      bias_init=nn.ConstInitMethod(-1.0))
    np.testing.assert_allclose(np.asarray(m.params["weight"]), 0.3)
    np.testing.assert_allclose(np.asarray(m.params["bias"]), -1.0)


def test_random_initializers_statistics():
    k = jax.random.key(0)
    u = np.asarray(nn.RandomUniform(-0.2, 0.2)(k, (200, 200)))
    assert u.min() >= -0.2 and u.max() <= 0.2 and u.std() > 0.05
    g = np.asarray(nn.RandomNormal(1.0, 0.5)(k, (200, 200)))
    np.testing.assert_allclose(g.mean(), 1.0, atol=0.02)
    np.testing.assert_allclose(g.std(), 0.5, atol=0.02)


def test_fan_based_initializers():
    k = jax.random.key(1)
    w = np.asarray(nn.Xavier()(k, (100, 200)))  # (out, in) linear layout
    a = np.sqrt(6.0 / (200 + 100))
    assert w.min() >= -a - 1e-6 and w.max() <= a + 1e-6
    np.testing.assert_allclose(w.std(), np.sqrt(2.0 / (200 + 100)),
                               rtol=0.15)
    m = np.asarray(nn.MsraFiller()(k, (100, 200)))
    np.testing.assert_allclose(m.std(), np.sqrt(2.0 / 200), rtol=0.15)


def test_bilinear_filler_kernel_shape():
    """BilinearFiller builds the deconv upsampling kernel
    (InitializationMethod.scala:277): symmetric, peaked at center."""
    w = np.asarray(nn.BilinearFiller()(jax.random.key(0), (4, 4, 2, 2)))
    k = w[:, :, 0, 0]
    np.testing.assert_allclose(k, k[::-1, ::-1], rtol=1e-6)  # symmetric
    assert k.max() <= 1.0 + 1e-6 and k.min() >= 0.0


# ---------------------------------------------------------------------------
# closure: every exported nn class must be named somewhere under tests/
# ---------------------------------------------------------------------------

def test_every_exported_class_is_tested():
    import pathlib
    import re
    text = ""
    for p in pathlib.Path(__file__).parent.glob("*.py"):
        if p.name != "test_layer_sweep.py":
            text += p.read_text()
    here = pathlib.Path(__file__).read_text()
    exported = sorted(n for n in dir(nn) if n[0:1].isupper())
    untested = []
    for name in exported:
        if not (re.search(rf"\b{re.escape(name)}\b", text) or
                re.search(rf"\bnn\.{re.escape(name)}\b", here) or
                re.search(rf"\b{re.escape(name)}\b", here)):
            untested.append(name)
    assert not untested, f"exported nn classes with no test: {untested}"


def test_layernorm_golden_and_grad():
    m = nn.LayerNorm(6).build(rng())
    x = _x((3, 5, 6), 11)
    y = np.asarray(m.forward(x))
    xn = np.asarray(x)
    mean = xn.mean(-1, keepdims=True)
    var = ((xn - mean) ** 2).mean(-1, keepdims=True)
    expect = (xn - mean) / np.sqrt(var + 1e-5)
    expect = expect * np.asarray(m.params["weight"]) + \
        np.asarray(m.params["bias"])
    np.testing.assert_allclose(y, expect, rtol=1e-4, atol=1e-5)
    _fd_check_param(m, x, ("weight",), (2,))


def test_gelu_golden():
    x = _x((4, 7), 12, scale=2.0)
    m = nn.GELU().build(rng())
    y = np.asarray(m.forward(x))
    xn = np.asarray(x, np.float64)
    # tanh approximation (jax.nn.gelu default)
    expect = 0.5 * xn * (1 + np.tanh(np.sqrt(2 / np.pi) *
                                     (xn + 0.044715 * xn ** 3)))
    np.testing.assert_allclose(y, expect, rtol=1e-4, atol=1e-5)
    # gradient THROUGH the module under test
    gx = jax.grad(lambda v: jnp.sum(jnp.square(m.apply(m.params, m.state,
                                                       v)[0])))(x)
    assert np.all(np.isfinite(np.asarray(gx)))


def test_multi_head_attention_golden_and_grad():
    """MHA forward == reference softmax attention composed from the same
    projections; wq gets a finite-difference gradient check."""
    m = nn.MultiHeadAttention(8, 2, causal=True).build(rng())
    x = _x((2, 5, 8), 13)
    y = np.asarray(m.forward(x))
    p = {k: np.asarray(v) for k, v in m.params.items()}
    q = np.asarray(x) @ p["wq"] + p["bq"]
    k_ = np.asarray(x) @ p["wk"] + p["bk"]
    v = np.asarray(x) @ p["wv"] + p["bv"]

    def split(a):  # [B,T,E] -> [B,H,T,D]
        return a.reshape(2, 5, 2, 4).transpose(0, 2, 1, 3)

    qh, kh, vh = split(q), split(k_), split(v)
    logits = qh @ kh.transpose(0, 1, 3, 2) / np.sqrt(4.0)
    mask = np.tril(np.ones((5, 5), bool))
    logits = np.where(mask, logits, -np.inf)
    w = np.exp(logits - logits.max(-1, keepdims=True))
    w /= w.sum(-1, keepdims=True)
    ctx = (w @ vh).transpose(0, 2, 1, 3).reshape(2, 5, 8)
    expect = ctx @ p["wo"] + p["bo"]
    np.testing.assert_allclose(y, expect, rtol=2e-4, atol=2e-5)
    _fd_check_param(m, x, ("wq",), (0, 1))
