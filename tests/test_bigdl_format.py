"""Native BigDL model format (Java object serialization) interop.

Reference: Module.save/load = ObjectOutputStream (nn/Module.scala:41-43,
utils/File.scala:25).  No JVM exists in this image, so the fixture is
hand-built to the Java Object Serialization Specification by
interop/bigdl.save and frozen on disk — the reader is pinned against those
exact bytes, not just an in-memory roundtrip.
"""

import io
import os
import struct

import numpy as np
import jax
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.interop import bigdl as bigdl_fmt
from bigdl_tpu.interop.javaser import (JavaObject, JavaWriter, loads,
                                       load_stream)

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures", "interop",
                       "lenet_like.bigdl")


def _model():
    m = nn.Sequential()
    m.add(nn.SpatialConvolution(1, 4, 5, 5))
    m.add(nn.SpatialBatchNormalization(4))
    m.add(nn.ReLU())
    m.add(nn.SpatialMaxPooling(2, 2, 2, 2))
    m.add(nn.Reshape([12 * 12 * 4]))
    m.add(nn.Linear(12 * 12 * 4, 10))
    m.add(nn.LogSoftMax())
    m.build(jax.random.PRNGKey(3))
    return m


def test_javaser_roundtrip_primitives():
    """The generic codec: write(read(x)) == x for a mixed object graph."""
    from bigdl_tpu.interop.javaser import JavaArray, JavaClassDesc

    cd = JavaClassDesc("com.example.Foo", 42, 2,
                       [("I", "n", None), ("D", "x", None),
                        ("[", "arr", "[F"),
                        ("L", "name", "Ljava/lang/String;")], None)
    arr = JavaArray(JavaClassDesc("[F", 1, 2, [], None),
                    np.arange(5, dtype=np.float32))
    obj = JavaObject(cd, {"n": 7, "x": 2.5, "arr": arr, "name": "hello"})
    w = JavaWriter()
    w.write_object(obj)
    [back] = loads(w.getvalue())
    assert back.classname == "com.example.Foo"
    assert back.fields["n"] == 7 and back.fields["x"] == 2.5
    assert back.fields["name"] == "hello"
    np.testing.assert_array_equal(back.fields["arr"].values, arr.values)


def test_save_load_roundtrip(tmp_path):
    m = _model()
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 28, 28, 1))
    y_ref, _ = m.apply(m.params, m.state, x)

    p = str(tmp_path / "model.bigdl")
    bigdl_fmt.save(m, p)
    loaded = bigdl_fmt.load(p)
    y, _ = loaded.apply(loaded.params, loaded.state, x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                               rtol=1e-5, atol=1e-6)


def test_frozen_fixture_loads_and_predicts():
    """The checked-in fixture's BYTES are the contract: stream magic, class
    names with the reference's SerialVersionUIDs, and a prediction that
    matches the recorded golden output."""
    with open(FIXTURE, "rb") as fh:
        raw = fh.read()
    assert struct.unpack(">HH", raw[:4]) == (0xACED, 5)
    assert b"com.intel.analytics.bigdl.nn.Sequential" in raw
    assert b"com.intel.analytics.bigdl.tensor.DenseTensor" in raw

    model = bigdl_fmt.load(FIXTURE)
    x = np.fromfile(FIXTURE + ".x", dtype=np.float32).reshape(2, 28, 28, 1)
    golden = np.fromfile(FIXTURE + ".y", dtype=np.float32).reshape(2, 10)
    y, _ = model.apply(model.params, model.state, x)
    np.testing.assert_allclose(np.asarray(y), golden, rtol=1e-5, atol=1e-5)


def test_concat_branch_roundtrip(tmp_path):
    """Inception-style branched topology (Concat + nested Sequentials,
    CAddTable residual) survives the wire format."""
    m = nn.Sequential()
    m.add(nn.SpatialConvolution(3, 8, 3, 3, pad_w=1, pad_h=1))
    c = nn.Concat(-1)
    b1 = nn.Sequential()
    b1.add(nn.SpatialConvolution(8, 4, 1, 1))
    b1.add(nn.ReLU())
    b2 = nn.Sequential()
    b2.add(nn.SpatialConvolution(8, 6, 3, 3, pad_w=1, pad_h=1))
    b2.add(nn.ReLU())
    c.add(b1)
    c.add(b2)
    m.add(c)
    m.add(nn.SpatialBatchNormalization(10))
    m.build(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 8, 3))
    y0, _ = m.apply(m.params, m.state, x)
    p = str(tmp_path / "branch.bigdl")
    bigdl_fmt.save(m, p)
    m2 = bigdl_fmt.load(p)
    y1, _ = m2.apply(m2.params, m2.state, x)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y0),
                               rtol=1e-5, atol=1e-6)
    # the wire carries the reference's 1-based NCHW channel dim
    with open(p, "rb") as fh:
        contents = load_stream(fh)
    [root] = [c_ for c_ in contents if isinstance(c_, JavaObject)]
    concat = root.fields["modules"].fields["array"].values[1]
    assert concat.classname.endswith(".Concat")
    assert concat.fields["dimension"] == 2


def test_table_layers_roundtrip(tmp_path):
    """Residual-style table plumbing (ConcatTable/JoinTable/CAddTable with
    its inplace flag, SpatialZeroPadding) survives both directions."""
    m = nn.Sequential()
    m.add(nn.SpatialZeroPadding(1))
    m.add(nn.ConcatTable().add(nn.Identity()).add(nn.Identity()))
    m.add(nn.JoinTable(-1))
    m.add(nn.ConcatTable().add(nn.Identity()).add(nn.Identity()))
    m.add(nn.CAddTable(True))
    m.add(nn.Dropout(0.3))            # identity in eval mode
    m.add(nn.SpatialAveragePooling(2, 2, 2, 2))
    m.add(nn.SpatialCrossMapLRN(3, 0.5, 0.7, 1.5))
    m.add(nn.Threshold(0.1, -0.2))
    m.add(nn.Power(2.0, 1.5, 0.25))
    m.build(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 4, 4, 3))
    y0, _ = m.apply(m.params, m.state, x)
    p = str(tmp_path / "tables.bigdl")
    bigdl_fmt.save(m, p)
    m2 = bigdl_fmt.load(p)
    y1, _ = m2.apply(m2.params, m2.state, x)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y0))
    assert m2.modules[4].inplace is True  # wire fidelity, not hardcoded


def test_wire_layout_matches_reference():
    """The serialized Linear weight must be (out, in) ON THE WIRE — the
    reference's nn/Linear.scala layout.  A matched pair of spurious
    transposes in save+load would pass the roundtrip test; this pins the
    actual bytes' tensor shape."""
    with open(FIXTURE, "rb") as fh:
        contents = load_stream(fh)
    [root] = [c for c in contents if isinstance(c, JavaObject)]

    def find(obj, cls):
        if isinstance(obj, JavaObject):
            if obj.classname.endswith(cls):
                yield obj
            for v in obj.fields.values():
                yield from find(v, cls)
        elif hasattr(obj, "values") and isinstance(obj.values, list):
            for v in obj.values:
                yield from find(v, cls)

    [linear] = find(root, ".Linear")
    size = np.asarray(linear.fields["weight"].fields["_size"].values)
    np.testing.assert_array_equal(size[:2], [10, 12 * 12 * 4])  # (out, in)
    [conv] = find(root, ".SpatialConvolution")
    csize = np.asarray(conv.fields["weight"].fields["_size"].values)
    np.testing.assert_array_equal(csize[:5], [1, 4, 1, 5, 5])  # g,o/g,i/g,kh,kw


def test_unknown_layer_fails_loud(tmp_path):
    from bigdl_tpu.interop.javaser import JavaClassDesc

    cd = JavaClassDesc("com.intel.analytics.bigdl.nn.RoiPooling",
                       1, 2, [], None)
    w = JavaWriter()
    w.write_object(JavaObject(cd, {}))
    p = tmp_path / "weird.bigdl"
    p.write_bytes(w.getvalue())
    with pytest.raises(ValueError, match="RoiPooling"):
        bigdl_fmt.load(str(p))


def test_model_validator_bigdl_format(tmp_path):
    """model_validator's bigdl type sniffs the JVM wire format
    (VERDICT r3 #4) and still reads this framework's own pickle."""
    from bigdl_tpu.tools.model_validator import load_model

    m = _model()
    jvm = str(tmp_path / "m_jvm.bigdl")
    bigdl_fmt.save(m, jvm)
    ours = str(tmp_path / "m_ours.bigdl")
    m.save(ours)

    x = jax.random.normal(jax.random.PRNGKey(1), (2, 28, 28, 1))
    y_ref, _ = m.apply(m.params, m.state, x)
    for path in (jvm, ours):
        loaded = load_model("bigdl", path)
        y, _ = loaded.apply(loaded.params, loaded.state, x)
        np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                                   rtol=1e-5, atol=1e-6, err_msg=path)


def test_javaser_fuzz_roundtrip():
    """Property test: random object graphs (nested objects, shared refs,
    primitive arrays of every type, strings, nulls, class hierarchies with
    writeObject annotations) roundtrip bit-exactly through the codec."""
    import random

    from bigdl_tpu.interop.javaser import (SC_SERIALIZABLE, SC_WRITE_METHOD,
                                           JavaArray, JavaClassDesc)

    rng = random.Random(1234)
    prim_types = list("BCDFIJSZ")

    def rand_value(depth, shared):
        kind = rng.randrange(6 if depth < 3 else 4)
        if kind == 0:
            return None
        if kind == 1:
            return "s%d" % rng.randrange(5)  # small pool: exercises refs
        if kind == 2:
            t = rng.choice(prim_types)
            from bigdl_tpu.interop.javaser import _PRIM
            _fmt, dt = _PRIM[t]
            vals = np.array([rng.randrange(0, 100) for _ in range(
                rng.randrange(0, 9))]).astype(dt)
            return JavaArray(JavaClassDesc("[" + t, 1, 2, [], None), vals)
        if kind == 3 and shared:
            return rng.choice(shared)  # back-reference to an earlier object
        return rand_obj(depth + 1, shared)

    def rand_obj(depth, shared):
        nf = rng.randrange(0, 4)
        fields, vals = [], {}
        for i in range(nf):
            if rng.random() < 0.5:
                t = rng.choice(prim_types)
                fields.append((t, f"p{i}", None))
                vals[f"p{i}"] = (rng.randrange(0, 100) if t != "Z"
                                 else bool(rng.randrange(2)))
                if t == "D" or t == "F":
                    vals[f"p{i}"] = float(vals[f"p{i}"])
            else:
                fields.append(("L", f"o{i}", "Ljava/lang/Object;"))
                vals[f"o{i}"] = rand_value(depth, shared)
        flags = SC_SERIALIZABLE
        ann = []
        if rng.random() < 0.3:
            flags |= SC_WRITE_METHOD
            ann = [b"\x01\x02\x03", "annot"]
        sup = None
        if depth < 2 and rng.random() < 0.3:
            sup = JavaClassDesc(f"com.fuzz.Super{rng.randrange(3)}",
                                rng.randrange(1 << 40), SC_SERIALIZABLE,
                                [("I", "sx", None)], None)
            vals["sx"] = rng.randrange(1000)
        cd = JavaClassDesc(f"com.fuzz.C{rng.randrange(8)}",
                           rng.randrange(1 << 40), flags, fields, sup)
        o = JavaObject(cd, vals)
        if ann:
            o.annotations[cd.name] = ann
        shared.append(o)
        return o

    def compare(a, b, depth=0):
        assert depth < 50
        if isinstance(a, JavaObject):
            assert isinstance(b, JavaObject) and a.classname == b.classname
            assert set(a.fields) == set(b.fields)
            for k1 in a.fields:
                compare(a.fields[k1], b.fields[k1], depth + 1)
            # writeObject annotation payloads must survive, in order
            assert set(a.annotations) == set(b.annotations)
            for cls in a.annotations:
                aa, bb = a.annotations[cls], b.annotations[cls]
                assert len(aa) == len(bb), cls
                for x, y in zip(aa, bb):
                    compare(x, y, depth + 1)
        elif isinstance(a, JavaArray):
            np.testing.assert_array_equal(np.asarray(a.values),
                                          np.asarray(b.values))
        elif isinstance(a, (bytes, bytearray)):
            assert bytes(a) == bytes(b)
        else:
            assert a == b, (a, b)

    for trial in range(25):
        shared = []
        root = rand_obj(0, shared)
        w = JavaWriter()
        w.write_object(root)
        data = w.getvalue()
        [back] = loads(data)
        compare(root, back)
        # bit-exactness: re-serializing the parsed graph reproduces the
        # stream byte-for-byte (same handle assignment order)
        w2 = JavaWriter()
        w2.write_object(back)
        assert w2.getvalue() == data, f"trial {trial}: bytes drifted"


def test_blockdata_long_payload_roundtrip():
    """writeObject annotation payloads >255 bytes must take the
    TC_BLOCKDATALONG frame instead of crashing (round-4 advisor, low)."""
    from bigdl_tpu.interop.javaser import JavaClassDesc, SC_WRITE_METHOD

    cd = JavaClassDesc("com.example.Blob", 9, 2 | SC_WRITE_METHOD,
                       [("I", "n", None)], None)
    payload = bytes(range(256)) * 5  # 1280 bytes: needs the long frame
    o = JavaObject(cd, {"n": 1})
    o.annotations[cd.name] = [payload]
    w = JavaWriter()
    w.write_object(o)
    data = w.getvalue()
    assert b"\x7a\x00\x00\x05\x00" in data  # TC_BLOCKDATALONG + int32 len
    [back] = loads(data)
    assert bytes(back.annotations[cd.name][0]) == payload


def test_threshold_inplace_flag_roundtrips(tmp_path):
    """Threshold(ip=True) keeps its inPlace wire flag through save/load
    (round-4 advisor, low)."""
    m = nn.Sequential()
    m.add(nn.Threshold(0.5, -1.0, ip=True))
    m.build(jax.random.PRNGKey(0))
    p = str(tmp_path / "th.bigdl")
    bigdl_fmt.save(m, p)
    m2 = bigdl_fmt.load(p)
    assert m2.modules[0].ip is True
    with open(p, "rb") as fh:
        contents = load_stream(fh)
    [root] = [c for c in contents if isinstance(c, JavaObject)]
    th = root.fields["modules"].fields["array"].values[0]
    assert th.fields["inPlace"] is True


def test_layerwise_grad_scale_survives_migration(tmp_path):
    """scale_w/scale_b (the reference's AbstractModule scaleW/scaleB,
    :73-74) must round-trip as the REAL property the gradient-scaling
    machinery reads — not a dangling attribute (round-5 review catch)."""
    import jax.numpy as jnp

    m = nn.Sequential()
    lin = nn.Linear(6, 4)
    lin.scale_w = 2.0
    lin.scale_b = 0.5
    m.add(lin)
    m.add(nn.Tanh())
    rec = nn.Recurrent(nn.RnnCell(4, 4))
    rec.modules[0].scale_w = 3.0
    m2 = nn.Sequential()
    m2.add(rec)
    m.add(nn.Reshape([4]))
    m.build(jax.random.PRNGKey(0))
    m2.build(jax.random.PRNGKey(1))

    p1 = str(tmp_path / "scaled.bigdl")
    bigdl_fmt.save(m, p1)
    back = bigdl_fmt.load(p1)
    assert back.modules[0].scale_w == 2.0
    assert back.modules[0].scale_b == 0.5
    # the wire carries the reference field names
    with open(p1, "rb") as fh:
        raw = fh.read()
    assert b"scaleW" in raw

    p2 = str(tmp_path / "scaled_rnn.bigdl")
    bigdl_fmt.save(m2, p2)
    back2 = bigdl_fmt.load(p2)
    assert back2.modules[0].modules[0].scale_w == 3.0


def test_share_convolution_resnet_style_roundtrip(tmp_path):
    """The reference ResNet's default optnet=true path serializes
    SpatialShareConvolution (models/resnet/ResNet.scala:47-49, a
    buffer-sharing subclass with the identical wire layout,
    nn/SpatialShareConvolution.scala:28) — its streams must load, and the
    alias must re-export under its own class name + real SUID."""
    m = nn.Sequential()
    m.add(nn.SpatialShareConvolution(3, 8, 3, 3, pad_w=1, pad_h=1))
    m.add(nn.SpatialBatchNormalization(8))
    m.add(nn.ReLU())
    m.build(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 6, 6, 3))
    y0, _ = m.apply(m.params, m.state, x)
    p = str(tmp_path / "share.bigdl")
    bigdl_fmt.save(m, p)
    raw = open(p, "rb").read()
    assert b"SpatialShareConvolution" in raw
    m2 = bigdl_fmt.load(p)
    assert type(m2.modules[0]).__name__ == "SpatialShareConvolution"
    y1, _ = m2.apply(m2.params, m2.state, x)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y0),
                               rtol=1e-5, atol=1e-6)


def test_paralleltable_maptable_squeeze_roundtrip(tmp_path):
    """The treeLSTMSentiment front half's plumbing (TreeSentiment.scala:
    46-51): MapTable's SHARED child (field `module`), ParallelTable over a
    table input, Squeeze's 1-based dims array."""
    m = nn.Sequential()
    ct = nn.ConcatTable()
    ct.add(nn.Identity())
    ct.add(nn.Identity())
    m.add(ct)
    m.add(nn.MapTable(nn.Squeeze(2)))
    pt = nn.ParallelTable()
    pt.add(nn.Linear(6, 4))
    pt.add(nn.Tanh())
    m.add(pt)
    m.add(nn.JoinTable(-1))
    m.build(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (3, 6, 1))
    y0, _ = m.apply(m.params, m.state, x)
    p = str(tmp_path / "tree_front.bigdl")
    bigdl_fmt.save(m, p)
    m2 = bigdl_fmt.load(p)
    assert isinstance(m2.modules[1], nn.MapTable)
    assert isinstance(m2.modules[2], nn.ParallelTable)
    assert m2.modules[1].modules[0].dim == 2
    y1, _ = m2.apply(m2.params, m2.state, x)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y0),
                               rtol=1e-5, atol=1e-6)
