"""Serving scale-out (serve/autoscale.py, serve/router.py,
serve/tracefile.py): queue-driven autoscaling, topology-aware routing,
recorded-traffic replay.

The scale-out contract under test (docs/serving.md "Scale-out"):
  - the autoscaler grows the pool on sustained over-target queue wait
    (hysteresis + cooldown, never past max), shrinks it one step per
    sustained idle window (never below min), and freezes entirely on an
    unhealthy pool;
  - a pool shrink loses zero accepted requests: a condemned replica's
    requeued batch goes back to the queue HEAD and is never evicted
    below its original admission priority;
  - scale-up takes the warm spawn path — zero fresh lowers with the AOT
    executable cache armed (plain server AND router members);
  - the topology router places replicas on DISJOINT device subsets
    (typed PlacementError otherwise), routes by (bucket, per-replica
    queue depth), answers bit-identical to bulk Predictor.predict, and
    degrades to the surviving members on replica loss;
  - traces round-trip through the CRC-framed recordio format, replay
    with open-loop pacing, and reduce to per-tenant / per-priority SLO
    attainment with real errors in their own bucket;
  - replay acceptance: under a pinned per-batch service time, the
    autoscaled pool's attainment is STRICTLY higher than the fixed
    1-replica pool's on the same trace.
"""

import json
import os
import time

import numpy as np
import jax
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu import Engine
from bigdl_tpu.optim import Predictor
from bigdl_tpu.serve import (AutoScaler, DynamicBatcher, InferenceServer,
                             PlacementError, ServerOverloaded,
                             TopologyRouter, TraceEvent, TraceFormatError,
                             plan_subsets, read_trace, replay,
                             resolve_outcomes, slo_report, write_trace)
from bigdl_tpu.utils import chaos


def _linear_model(seed=0, din=4, dout=3):
    return nn.Sequential().add(nn.Linear(din, dout)).build(
        jax.random.key(seed))


def _rows(n, din=4, seed=0):
    return np.random.default_rng(seed).normal(size=(n, din)) \
        .astype(np.float32)


def _stall_spec(seconds, n=2000):
    counts = ",".join(str(i) for i in range(1, n + 1))
    return f"serve.batch=stall*{seconds}@{counts}"


# ------------------------------------------------- autoscaler decisions


class _StubPool:
    """Scripted scale-protocol target: pure controller-logic tests."""

    def __init__(self, replicas=1):
        self.replicas = replicas
        self.depth = 0
        self.row_s = None
        self.batches = 0
        self._healthy = True
        self.calls = []

    def healthy(self):
        return self._healthy

    def autoscale_signals(self):
        return {"depth": self.depth, "row_s_ema": self.row_s,
                "batches": self.batches, "live": self.replicas}

    def scale_to(self, n):
        self.calls.append(n)
        self.replicas = n


def test_autoscaler_up_hysteresis_cooldown_and_max():
    pool = _StubPool(replicas=1)
    sc = AutoScaler(pool, min_replicas=1, max_replicas=3,
                    target_wait_ms=100.0, up_polls=2, idle_s=10.0,
                    cooldown_s=0.5, step=1, clock=lambda: 0.0)
    pool.depth, pool.row_s = 40, 0.01  # est wait 0.4s >> 0.1s target
    assert sc.check(now=0.0) is None          # hysteresis: 1 poll is not
    assert sc.check(now=0.1) == "up"          # 2 consecutive polls are
    assert pool.replicas == 2
    assert sc.check(now=0.2) is None          # cooldown holds...
    assert sc.check(now=0.3) is None
    assert sc.check(now=0.7) == "up"          # ...then the next step
    assert pool.replicas == 3
    # at max: over-target forever never scales past the ceiling
    for t in (1.5, 1.6, 1.7, 2.5):
        assert sc.check(now=t) is None
    assert pool.replicas == 3
    assert sc.scale_ups == 2 and sc.scale_downs == 0
    st = sc.stats()
    assert st["events"][-1]["direction"] == "up"
    assert st["events"][-1]["to"] == 3


def test_autoscaler_idle_shrink_floor_and_unhealthy_freeze():
    pool = _StubPool(replicas=3)
    sc = AutoScaler(pool, min_replicas=1, max_replicas=4,
                    target_wait_ms=100.0, up_polls=1, idle_s=1.0,
                    cooldown_s=0.1, clock=lambda: 0.0)
    pool.depth = 0
    assert sc.check(now=0.0) is None          # idle window starts
    assert sc.check(now=0.5) is None          # not idle long enough
    assert sc.check(now=1.1) == "down"        # one step per window
    assert pool.replicas == 2
    assert sc.check(now=1.3) is None          # window restarted
    assert sc.check(now=2.2) == "down"
    assert pool.replicas == 1
    # at the floor: idle forever never goes below min
    assert sc.check(now=5.0) is None
    assert pool.replicas == 1
    # queued work interrupts the idle window (no shrink while busy)
    pool.replicas, pool.depth, pool.row_s = 2, 3, 0.0001
    sc._last_busy = None
    assert sc.check(now=10.0) is None
    assert sc.check(now=12.0) is None         # busy at 10.0 reset window
    # an unhealthy pool freezes the controller entirely
    pool._healthy = False
    pool.depth, pool.row_s = 100, 1.0
    for t in (20.0, 21.0):
        assert sc.check(now=t) is None
    assert pool.replicas == 2


def test_autoscaler_bounds_validated():
    with pytest.raises(ValueError):
        AutoScaler(_StubPool(), min_replicas=3, max_replicas=2)
    with pytest.raises(ValueError):
        AutoScaler(_StubPool(), min_replicas=0, max_replicas=2)


# --------------------------------------------- server pool elasticity


def test_server_scale_to_grow_and_shrink_live_workers():
    Engine.init()
    model = _linear_model()
    x = _rows(12)
    with InferenceServer(model, max_batch=4, max_wait_ms=2,
                         example=x[0]) as server:
        assert server.autoscale_signals()["live"] == 1
        server.scale_to(3)
        time.sleep(0.1)
        st = server.stats()
        assert st["replicas"] == 3 and st["replicas_live"] == 3
        outs = [server.submit(r) for r in x]
        got = np.stack([h.result(30) for h in outs])
        # per-sample oracle: every forward (server bucket or reference)
        # pads to the same shape on the 8-device mesh — the bit-identity
        # precondition (see test_serve.py's coalescing test)
        ref = np.stack([Predictor(model).predict(x[i:i + 1])[0]
                        for i in range(len(x))])
        np.testing.assert_array_equal(got, ref)
        server.scale_to(1)
        # condemned workers parked on the EMPTY queue must exit at the
        # next wait slice (collect stop_when), not linger until traffic
        deadline = time.monotonic() + 5.0
        while server.stats()["replicas_live"] > 1 and \
                time.monotonic() < deadline:
            time.sleep(0.05)
        st = server.stats()
        assert st["replicas"] == 1 and st["replicas_live"] == 1
        # the shrunken pool still serves
        assert server.predict(x[0], timeout=30) is not None


def test_scale_up_zero_fresh_lowers_plain_server(tmp_path, monkeypatch):
    """Plain-server scale-up shares the already-warm engine: the whole
    grow happens with zero fresh lowers on the AOT ledger."""
    from bigdl_tpu.utils import aot
    monkeypatch.setenv("BIGDL_TPU_AOT_CACHE", str(tmp_path / "aot"))
    aot.reset()
    Engine.init()
    model = _linear_model(seed=3)
    x = _rows(16, seed=3)
    with InferenceServer(model, max_batch=4, max_wait_ms=2,
                         example=x[0]) as server:
        s0 = aot.stats()
        server.scale_to(4)
        outs = [server.submit(r) for r in x]
        for h in outs:
            h.result(30)
        s1 = aot.stats()
        assert int(s1["lowers"] - s0["lowers"]) == 0
        assert int(s1["compiles"] - s0["compiles"]) == 0
        assert server.stats()["aot"]["lowers"] == int(s1["lowers"])


def test_autoscale_end_to_end_grows_then_shrinks():
    """Armed controller on a live server: a chaos-pinned service time +
    a request flood must grow the pool; the post-flood idle window must
    hand the capacity back.  Decisions land in stats()["autoscale"]."""
    Engine.init()
    model = _linear_model(seed=1)
    x = _rows(64, seed=1)
    with chaos.scoped(_stall_spec(0.03)):
        with InferenceServer(model, max_batch=4, max_wait_ms=2,
                             queue_limit=256, example=x[0],
                             autoscale_min=1, autoscale_max=3,
                             autoscale_target_wait_ms=30.0,
                             autoscale_up_polls=1,
                             autoscale_cooldown_s=0.05,
                             autoscale_idle_s=0.3,
                             autoscale_poll_s=0.01) as server:
            handles = [server.submit(r) for r in x]
            for h in handles:
                h.result(60)
            deadline = time.monotonic() + 5.0
            grew = server.stats()["autoscale"]["scale_ups"]
            while server.stats()["replicas"] > 1 and \
                    time.monotonic() < deadline:
                time.sleep(0.05)
            st = server.stats()
    assert grew >= 1
    assert st["autoscale"]["scale_ups"] >= 1
    assert st["autoscale"]["scale_downs"] >= 1
    assert st["replicas"] == 1
    ev = st["autoscale"]["events"]
    assert ev and {"direction", "from", "to", "est_wait_ms",
                   "queue_depth"} <= set(ev[0])


# ------------------------- requeue x priority-eviction x pool shrink


def test_requeue_not_evicted_below_admission_priority():
    """The satellite contract: a condemned replica's requeued batch goes
    back to the queue HEAD and keeps its ORIGINAL admission priority —
    equal- or lower-priority arrivals can never evict it; a strictly
    higher one still can (normal priority semantics)."""
    b = DynamicBatcher(max_batch=4, max_wait_s=0.01, queue_limit=4)
    held = [b.submit(i, priority=1) for i in range(4)]
    got = b.collect()
    assert [r.payload for r in got] == [0, 1, 2, 3]
    # the condemned replica hands its batch back (original order, HEAD)
    b.requeue(got)
    assert b.depth() == 4
    # a lower-priority arrival cannot displace the requeued batch: IT is
    # refused (typed), the batch is untouched
    with pytest.raises(ServerOverloaded):
        b.submit(99, priority=0)
    # an equal-priority arrival cannot either (eviction needs a STRICT
    # outrank)
    with pytest.raises(ServerOverloaded):
        b.submit(99, priority=1)
    assert b.depth() == 4 and not any(r.done() for r in held)
    # a strictly higher-priority arrival may evict — and evicts the
    # NEWEST of the lowest class, exactly one
    b.submit(100, priority=2)
    evicted = [r for r in held if r.done()]
    assert len(evicted) == 1 and evicted[0] is held[-1]
    with pytest.raises(ServerOverloaded):
        evicted[0].result(0.1)
    # the survivors drain in original order, head first (the arrival
    # that evicted joined the TAIL behind the requeued batch)
    out = b.collect()
    assert [r.payload for r in out] == [0, 1, 2, 100]


def test_shrink_requeues_condemned_replicas_batch_zero_loss():
    """End to end: replica 1 is wedged holding a collected batch while
    the pool shrinks to 1 — on waking it must notice its condemnation,
    requeue the batch, and exit; replica 0 serves everything.  Zero
    accepted-request loss across an autoscaler shrink."""
    Engine.init()
    model = _linear_model(seed=2)
    x = _rows(8, seed=2)
    ref = np.asarray(Predictor(model).predict(x))
    # serve.replica@1 wedges replica 1 AFTER it collected its 1st batch
    # and BEFORE it executes — it holds the batch through the shrink
    with chaos.scoped("serve.replica@1=wedge*0.4@1"):
        server = InferenceServer(model, replicas=2, max_batch=4,
                                 max_wait_ms=40, queue_limit=64,
                                 example=x[0]).start()
        try:
            handles = [server.submit(r) for r in x]
            time.sleep(0.1)          # let replica 1 collect + wedge
            server.scale_to(1)       # condemn slot 1 mid-wedge
            got = np.stack([h.result(30) for h in handles])
        finally:
            server.stop()
    np.testing.assert_array_equal(got, ref)


# --------------------------------------------------- topology routing


def test_plan_subsets_disjoint_and_typed_placement_error():
    devs = jax.devices()
    subsets = plan_subsets(devs, 2, 4)
    assert len(subsets) == 4 and all(len(s) == 2 for s in subsets)
    seen = [d for s in subsets for d in s]
    assert len(set(seen)) == len(seen)  # disjoint
    with pytest.raises(PlacementError):
        plan_subsets(devs, 3, 3)  # 9 > 8 devices
    with pytest.raises(PlacementError):
        TopologyRouter(_linear_model(), replicas=9,
                       example=np.zeros(4, np.float32))


def test_router_bit_match_and_bucket_depth_routing():
    Engine.init()
    model = _linear_model()
    x = _rows(16)
    with TopologyRouter(model, replicas=2, max_batch=4, max_wait_ms=5,
                        example=x[0]) as router:
        handles = [router.submit(r) for r in x]
        got = np.stack([h.result(30) for h in handles])
        np.testing.assert_array_equal(
            got, np.asarray(Predictor(model).predict(x)))
        st = router.stats()
        assert st["router"]["replicas"] == 2
        assert sum(st["router"]["routed"]) == 16
        assert set(st["router"]["members"]) == {"0", "1"}
    # the dispatch decision, on an UNSTARTED pool (no workers draining
    # the queues out from under the assertions): fewest full buckets,
    # then prefer the partially-filled coalescing batch, then depth,
    # then index
    probe = TopologyRouter(model, replicas=2, max_batch=4,
                           example=_rows(1)[0])
    for i in range(2):
        probe._members[i] = probe._build_member(i)
    m0, m1 = probe._members[0], probe._members[1]
    assert probe._pick() == 0                    # all idle -> index
    m0.batcher._q.extend([object()] * 4)         # 1 full bucket
    assert probe._pick() == 1
    m1.batcher._q.extend([object()] * 5)         # 1 full + a partial
    # equal full-bucket counts: the PARTIAL coalescing batch wins (its
    # flush window is already ticking; joining raises fill)
    assert probe._pick() == 1
    m0.batcher._q.clear()
    m1.batcher._q.clear()
    m1.batcher._q.append(object())               # lone partial batch
    assert probe._pick() == 1                    # join it, fill it
    m1.batcher._q.clear()
    # an unhealthy member never receives traffic
    from bigdl_tpu.serve import ReplicaLostError
    m1.batcher._q.clear()
    m0._unhealthy = ReplicaLostError("drill")
    assert probe._pick() == 1


def test_router_tp_sharded_members_serve_bit_identical():
    """Mesh-sharded members: layout (1,1,2) members own 2 devices each
    and serve tp-sharded through LayoutSharding — answers still
    bit-match bulk Predictor.predict (the PR 9 serving contract, now
    per-subset)."""
    from bigdl_tpu.parallel import MeshLayout
    Engine.init()
    model = nn.Sequential().add(nn.Linear(8, 6)).add(nn.ReLU()) \
        .add(nn.Linear(6, 4)).build(jax.random.key(5))
    x = _rows(12, din=8, seed=5)
    with TopologyRouter(model, layout=MeshLayout(1, 1, 2), replicas=2,
                        max_batch=4, example=x[0]) as router:
        st = router.stats()["router"]
        assert st["devices_per_replica"] == 2
        devs = [tuple(m["devices"]) for m in st["members"].values()]
        assert len(set(d for s in devs for d in s)) == 4  # disjoint
        handles = [router.submit(r) for r in x]
        got = np.stack([h.result(30) for h in handles])
    np.testing.assert_array_equal(
        got, np.asarray(Predictor(model).predict(x)))


def test_router_degrades_to_surviving_members_on_loss():
    """A member whose pool is beyond recovery stops receiving traffic;
    the router keeps serving through the survivors and stays healthy."""
    Engine.init()
    model = _linear_model(seed=7)
    x = _rows(12, seed=7)
    with TopologyRouter(model, replicas=2, max_batch=4,
                        example=x[0]) as router:
        # member 0's restart budget is spent: the PR 10 signal
        from bigdl_tpu.serve import ReplicaLostError
        router._members[0]._mark_unhealthy(
            ReplicaLostError("drill: member 0 lost"))
        routed_before = list(router._routed)
        handles = [router.submit(r) for r in x]
        got = np.stack([h.result(30) for h in handles])
        np.testing.assert_array_equal(
            got, np.asarray(Predictor(model).predict(x)))
        assert router._routed[0] == routed_before[0]  # nothing new to 0
        assert router.healthy()  # the POOL survives one member's loss
        st = router.stats()
        assert st["router"]["members"]["0"]["healthy"] is False
        assert st["router"]["members"]["1"]["healthy"] is True


def test_router_scale_up_is_aot_cache_reads(tmp_path, monkeypatch):
    """Router scale-up builds FRESH engines on new subsets — with the
    cache armed and subsets prewarmed, the whole grow is cache reads:
    zero fresh lowers, zero misses (the ISSUE 14 acceptance ledger).

    The XLA persistent cache is un-latched for the duration (same
    attribution discipline as the restart x AOT test in test_serve.py):
    an executable itself loaded from the XLA disk cache serializes into
    an unloadable AOT entry on CPU — quarantined + recompiled, correct
    but ledger-skewing."""
    from jax._src import compilation_cache as _cc

    from bigdl_tpu.utils import aot
    monkeypatch.setenv("BIGDL_TPU_AOT_CACHE", str(tmp_path / "aot"))
    aot.reset()
    prior_xla = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", None)
    _cc.reset_cache()
    Engine.init()
    model = _linear_model(seed=9)
    x = _rows(16, seed=9)
    router = TopologyRouter(model, replicas=1, max_replicas=3,
                            max_batch=4, example=x[0],
                            prewarm=True).start()
    try:
        s0 = aot.stats()
        router.scale_to(3)
        handles = [router.submit(r) for r in x]
        got = np.stack([h.result(30) for h in handles])
        s1 = aot.stats()
        assert int(s1["lowers"] - s0["lowers"]) == 0
        assert int(s1["misses"] - s0["misses"]) == 0
        assert int(s1["hits"] - s0["hits"]) > 0
        np.testing.assert_array_equal(
            got, np.asarray(Predictor(model).predict(x)))
        # shrink drains gracefully and the survivors keep serving
        router.scale_to(1)
        assert router.predict(x[0], timeout=30) is not None
    finally:
        router.stop()
        jax.config.update("jax_compilation_cache_dir", prior_xla)
        _cc.reset_cache()


# ------------------------------------------------ trace record/replay


def test_trace_roundtrip_and_corruption_typed(tmp_path):
    path = str(tmp_path / "trace.rec")
    x = _rows(3)
    events = [TraceEvent(0.0, x[0], tenant="a", priority=2,
                         deadline_ms=50.0),
              TraceEvent(0.01, x[1], tenant="b", priority=0),
              TraceEvent(0.25, x[2])]
    write_trace(path, events, meta={"source": "test"})
    header, loaded = read_trace(path)
    assert header["format"] == "bigdl_tpu-serve-trace-v1"
    assert header["count"] == 3
    assert header["sample_shape"] == [4]
    assert header["meta"]["source"] == "test"
    assert [e.dt for e in loaded] == [0.0, 0.01, 0.25]
    assert [e.tenant for e in loaded] == ["a", "b", None]
    assert [e.priority for e in loaded] == [2, 0, 0]
    assert loaded[0].deadline_ms == 50.0 and loaded[1].deadline_ms is None
    np.testing.assert_array_equal(loaded[2].payload, x[2])
    # a flipped payload byte is a typed CorruptRecord, not a bad bench
    from bigdl_tpu.utils.recordio import CorruptRecord
    blob = bytearray(open(path, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    open(path, "wb").write(bytes(blob))
    with pytest.raises(CorruptRecord):
        read_trace(path)
    # a non-trace recordio file is a typed format error
    other = str(tmp_path / "other.rec")
    from bigdl_tpu.utils import recordio
    recordio.write_records(other, [{"not": "a trace"}])
    with pytest.raises(TraceFormatError):
        read_trace(other)


def test_server_records_offered_traffic(tmp_path):
    """record_trace captures the OFFERED stream — shed requests
    included — with tenants/priorities/deadlines, through the real
    admission path, and stop_trace writes the recordio file."""
    Engine.init()
    model = _linear_model()
    x = _rows(8)
    path = str(tmp_path / "offered.rec")
    with InferenceServer(model, max_batch=4, queue_limit=2,
                         max_wait_ms=1, example=x[0]) as server:
        server.record_trace(path)
        shed = 0
        with chaos.scoped("serve.batch=stall*0.15@1"):
            for i, r in enumerate(x):
                try:
                    server.submit(r, tenant=f"t{i % 2}", priority=i % 3,
                                  deadline_ms=200.0)
                except ServerOverloaded:
                    shed += 1
        assert shed > 0  # the tiny queue really shed some
        assert server.stats()["trace_recording"]["events"] == len(x)
        n = len(server.stop_trace())
    header, events = read_trace(path)
    assert header["count"] == n == len(x)  # sheds recorded too
    assert {e.tenant for e in events} == {"t0", "t1"}
    assert all(e.deadline_ms == 200.0 for e in events)


def test_replay_open_loop_pacing_and_lag():
    """Pacing is open-loop on an injected clock: submit times follow the
    recorded arrivals / speed, and a slow submit shows up as LAG on the
    events behind it instead of stretching the schedule."""
    t = [0.0]
    sleeps = []

    def clock():
        return t[0]

    def sleep(s):
        sleeps.append(round(s, 6))
        t[0] += s

    events = [TraceEvent(0.0, 0), TraceEvent(1.0, 1), TraceEvent(1.0, 2)]
    seen = []

    def submit(e):
        seen.append((e.payload, round(t[0], 6)))
        return None

    out = replay(events, submit, speed=10.0, clock=clock, sleep=sleep)
    assert [p for p, _ in seen] == [0, 1, 2]
    assert [at for _, at in seen] == [0.0, 0.1, 0.2]
    assert sleeps == [0.1, 0.1]
    assert all(o.lag_s == 0.0 for o in out)

    # a slow submit makes later events LATE (lag), never re-paced
    t[0] = 0.0
    slow = [True]

    def slow_submit(e):
        if slow[0]:
            slow[0] = False
            t[0] += 0.5  # the first submit burns half a second
        return None

    out = replay(events, slow_submit, speed=10.0, clock=clock,
                 sleep=sleep)
    assert out[0].lag_s == 0.0
    assert out[1].lag_s == pytest.approx(0.4, abs=1e-6)
    assert out[2].lag_s == pytest.approx(0.3, abs=1e-6)
    with pytest.raises(ValueError):
        replay(events, submit, speed=0.0)


def test_slo_report_attainment_and_shed_classification():
    """Attainment counts served-within-own-deadline over OFFERED, per
    tenant and per priority; overload/timeout are shedding, anything
    else is a real error in its own bucket."""
    from bigdl_tpu.serve import RequestTimeout
    from bigdl_tpu.serve.tracefile import ReplayOutcome

    def ev(tenant, priority, deadline_ms):
        return TraceEvent(0.0, 0, tenant=tenant, priority=priority,
                          deadline_ms=deadline_ms)

    def served(e, lat_s):
        o = ReplayOutcome(e)
        o.handle = object()
        o.latency_s = lat_s
        return o

    def failed(e, err):
        return ReplayOutcome(e, error=err)

    outcomes = [
        served(ev("a", 2, 100.0), 0.05),            # attained
        served(ev("a", 2, 100.0), 0.25),            # served, too late
        served(ev("a", 0, None), 1.0),              # no deadline: attains
        failed(ev("b", 1, 100.0), ServerOverloaded("full")),
        failed(ev("b", 1, 100.0), RequestTimeout("late")),
        failed(ev("b", 0, 100.0), RuntimeError("backend died")),
    ]
    rep = slo_report(outcomes)
    assert rep["offered"] == 6 and rep["served"] == 3
    assert rep["attainment"] == pytest.approx(2 / 6, abs=1e-4)
    assert rep["shed"] == {"overload": 1, "timeout": 1, "errors": 1}
    a, b = rep["per_tenant"]["a"], rep["per_tenant"]["b"]
    assert a["attainment"] == pytest.approx(2 / 3, abs=1e-4)
    assert b["attainment"] == 0.0
    assert b["errors"] == 1 and b["shed_overload"] == 1
    assert rep["per_priority"]["2"]["offered"] == 2
    assert rep["p50_ms"] is not None
    # default deadline applies where the event carried none
    rep2 = slo_report([served(ev("c", 0, None), 1.0)],
                      default_deadline_ms=100.0)
    assert rep2["attainment"] == 0.0


def test_replay_acceptance_autoscaled_beats_fixed(tmp_path):
    """ISSUE 14 acceptance: a recorded trace replayed at >= 10x produces
    per-tenant SLO attainment, and under the same trace + pinned
    service time the autoscaled pool attains STRICTLY more than the
    fixed 1-replica pool."""
    Engine.init()
    model = _linear_model(seed=4)
    xs = _rows(16, seed=4)
    path = str(tmp_path / "accept.rec")
    # record a real offered stream through the server's admission path
    with InferenceServer(model, max_batch=4, queue_limit=512,
                         example=xs[0]) as rec_server:
        rec_server.record_trace(path)
        hs = []
        for i in range(90):
            hs.append(rec_server.submit(
                xs[i % len(xs)], tenant=f"t{i % 3}", priority=i % 3,
                deadline_ms=250.0))
            time.sleep(0.01)
        for h in hs:
            h.result(30)
        rec_server.stop_trace()
    _header, events = read_trace(path)
    assert len(events) == 90

    def run(pool):
        def submit(e):
            return pool.submit(e.payload, deadline_ms=e.deadline_ms,
                               tenant=e.tenant, priority=e.priority)
        outcomes = replay(events, submit, speed=10.0)
        resolve_outcomes(outcomes, timeout=60)
        return slo_report(outcomes)

    with chaos.scoped(_stall_spec(0.03)):
        with InferenceServer(model, max_batch=4, queue_limit=512,
                             example=xs[0]) as fixed:
            rep_fixed = run(fixed)
    with chaos.scoped(_stall_spec(0.03)):
        with InferenceServer(model, max_batch=4, queue_limit=512,
                             example=xs[0], autoscale_min=1,
                             autoscale_max=4,
                             autoscale_target_wait_ms=30.0,
                             autoscale_up_polls=1,
                             autoscale_cooldown_s=0.03,
                             autoscale_poll_s=0.01) as auto:
            rep_auto = run(auto)
            grew = auto.stats()["autoscale"]["scale_ups"]
    assert set(rep_auto["per_tenant"]) == {"t0", "t1", "t2"}
    assert set(rep_auto["per_priority"]) == {"0", "1", "2"}
    assert grew >= 1
    assert rep_auto["attainment"] > rep_fixed["attainment"]


# ------------------------------------------------- HTTP front end


def test_http_autoscale_stats_retry_after_503_and_trace_header(tmp_path):
    """/v1/stats surfaces the autoscaler block, the unhealthy 503 path
    carries Retry-After (healthz AND predict), and the
    X-BigDL-Record-Trace header arms/flushes trace recording."""
    import sys
    import urllib.error
    import urllib.request

    tools_dir = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools")
    if tools_dir not in sys.path:
        sys.path.insert(0, tools_dir)
    import serve_http

    Engine.init()
    model = _linear_model()
    x = _rows(2)
    server = InferenceServer(model, example=np.zeros((4,), np.float32),
                             autoscale_max=2).start()
    httpd = serve_http.serve_forever(server, "127.0.0.1", 0)
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    trace_path = str(tmp_path / "http_trace.rec")

    def post(path, obj, headers=None):
        req = urllib.request.Request(base + path,
                                     data=json.dumps(obj).encode(),
                                     method="POST",
                                     headers=headers or {})
        try:
            with urllib.request.urlopen(req, timeout=30) as r:
                return r.status, dict(r.headers), json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, dict(e.headers), json.loads(e.read())

    try:
        # autoscaler state in /v1/stats
        with urllib.request.urlopen(base + "/v1/stats", timeout=30) as r:
            stats = json.loads(r.read())
        assert stats["autoscale"]["max"] == 2
        assert stats["autoscale"]["replicas"] == 1
        # trace header arms recording; 'off' stops BEFORE its own
        # request and writes the file
        status, _h, _b = post("/v1/predict", {"inputs": x[0].tolist()},
                              headers={"X-BigDL-Record-Trace": trace_path})
        assert status == 200
        status, _h, _b = post("/v1/predict", {"inputs": x[1].tolist()})
        assert status == 200
        status, _h, _b = post("/v1/predict", {"inputs": x[0].tolist()},
                              headers={"X-BigDL-Record-Trace": "off"})
        assert status == 200
        header, events = read_trace(trace_path)
        assert header["count"] == len(events) == 2
        # unhealthy 503s carry Retry-After now (not just the 429 path):
        # budget-spent marker + a dead pool is the admission 503 path
        from bigdl_tpu.serve import ReplicaLostError
        server._unhealthy = ReplicaLostError("drill: budget spent")
        server.batcher.close(drain=True)
        for t in server._threads:
            t.join(5)
        code, headers, body = post("/v1/predict",
                                   {"inputs": x[0].tolist()})
        assert code == 503 and body["type"] in ("ReplicaLostError",
                                                "ServerClosed")
        assert "Retry-After" in headers
        req = urllib.request.Request(base + "/healthz")
        try:
            urllib.request.urlopen(req, timeout=30)
            assert False, "healthz should be 503"
        except urllib.error.HTTPError as e:
            assert e.code == 503
            assert e.headers.get("Retry-After") is not None
    finally:
        httpd.shutdown()
        server._unhealthy = None
        server.stop()


# ------------------------------------------------------- bench replay


def test_bench_replay_mode_record(tmp_path):
    """bench.py --serve --replay: per-tenant SLO attainment beside
    percentiles and shed-by-cause, from a trace file, with the
    fixed-pool comparison record."""
    import bench

    Engine.init()
    path = str(tmp_path / "bench.rec")
    xs = _rows(10)
    events = [TraceEvent(0.02 if i else 0.0, xs[i % len(xs)],
                         tenant=f"t{i % 2}", priority=i % 2,
                         deadline_ms=500.0) for i in range(30)]
    write_trace(path, events)

    def builder():
        return _linear_model(), np.zeros((4,), np.float32)

    rec = bench._serve_replay_bench(trace_path=path, speed=10.0,
                                    compare=True, autoscale_max=2,
                                    model_builder=builder)
    assert rec["metric"] == "serve_replay_slo_attainment"
    assert rec["events"] == 30 and rec["speed"] == 10.0
    rep = rec["replay"]
    assert set(rep["per_tenant"]) == {"t0", "t1"}
    assert set(rep["per_priority"]) == {"0", "1"}
    assert rep["shed"].keys() == {"overload", "timeout", "errors"}
    assert rep["offered"] == 30
    assert rep["p50_ms"] is not None
    assert rep["pool"]["autoscale_max"] == 2
    assert "fixed" in rec and "attainment_gain" in rec
    # telemetry promotion: the autoscale counter track becomes a report
    # section like the aot ledger
    from bigdl_tpu.utils import telemetry
    bd = telemetry.phase_breakdown({"traceEvents": [
        {"ph": "C", "name": "serve.autoscale", "ts": 1.0,
         "args": {"replicas": 2, "est_wait_ms": 12.0}},
        {"ph": "i", "name": "serve.autoscale", "ts": 1.0},
    ]})
    assert bd["autoscale"]["replicas"] == 2
    assert bd["autoscale"]["decisions"] == 1
    assert "autoscale:" in telemetry.format_report(bd)
