"""Pipeline + expert parallelism as first-class MeshLayout axes (ISSUE 12):
the 5-axis ``data/fsdp/tp/pipe/expert`` layout, the GPipe stage
partitioner + microbatched schedule through the ordinary compiled step,
expert_table-role MoE sharding, the elastic reform rules for the new
axes, and the ring-attention-over-tp seam — on the 8-virtual-CPU-device
mesh (conftest.py), exactly as tools/shard_smoke.py covers fsdp/tp."""

import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import bigdl_tpu.nn as nn
from bigdl_tpu.common import set_seed
from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
from bigdl_tpu.optim import Optimizer, SGD, Trigger
from bigdl_tpu.parallel import (GPipeSequential, LayoutSharding, MeshLayout,
                                MeshReformError, MoEFFN,
                                PipelinePartitionError, bubble_fraction,
                                load_balancing_loss, partition_pipeline,
                                top_k_routing)
from bigdl_tpu.utils import memstats
from bigdl_tpu.utils.engine import Engine

multidev = pytest.mark.skipif(
    jax.device_count() < 4,
    reason="needs >= 4 devices (conftest force_cpu)")

LOSS_TOL = 2e-3


def _mlp():
    """Two identical Linear+ReLU blocks and a head — the repeated-block
    body partition_pipeline targets; bias-free so shard-fraction
    arithmetic is exact."""
    return nn.Sequential(
        nn.Linear(64, 64, with_bias=False), nn.ReLU(),
        nn.Linear(64, 64, with_bias=False), nn.ReLU(),
        nn.Linear(64, 8, with_bias=False))


def _moe_mlp():
    return nn.Sequential(
        nn.Linear(64, 32, with_bias=False), nn.ReLU(),
        MoEFFN(32, 64, num_experts=4, capacity_factor=4.0),
        nn.Linear(32, 8, with_bias=False))


def _dataset(n, batch, seed=0):
    rng = np.random.default_rng(seed)
    xs = rng.normal(0.0, 1.0, size=(n, 64)).astype(np.float32)
    ys = rng.integers(0, 8, size=n)
    return DataSet.array(
        [Sample(x, np.int32(y)) for x, y in zip(xs, ys)]).transform(
        SampleToMiniBatch(16, drop_last=True))


def _train(model, ds, strategy, steps, lr=0.05):
    losses = []

    class Cap:
        def add_scalar(self, name, value, step):
            if name == "Loss":
                losses.append(float(value))

    opt = (Optimizer(model, ds, nn.CrossEntropyCriterion(),
                     strategy=strategy)
           .set_optim_method(SGD(learning_rate=lr, momentum=0.9))
           .set_end_when(Trigger.max_iteration(steps))
           .set_log_interval(1)
           .set_train_summary(Cap()))
    opt.optimize()
    return losses, opt


class TestFiveAxisLayout:
    def test_parse_three_and_five(self):
        assert MeshLayout.parse("2,2,1") == MeshLayout(2, 2, 1)
        lay = MeshLayout.parse("1,1,1,2,2")
        assert (lay.pipe, lay.expert) == (2, 2) and lay.size == 4
        with pytest.raises(ValueError):
            MeshLayout.parse("1,1,1,2")  # 4 ints is neither spelling
        with pytest.raises(ValueError):
            MeshLayout(1, 1, 1, 0, 1)

    def test_legacy_mesh_unchanged_at_pipe_expert_one(self):
        """pipe=expert=1 builds the SAME 3-axis mesh as before — the
        AOT-fingerprint/back-compat contract."""
        lay = MeshLayout(2, 1, 1)
        assert lay.axis_names == ("data", "fsdp", "tp")
        assert lay.sizes == (2, 1, 1)

    @multidev
    def test_build_and_of_mesh_roundtrip(self):
        lay = MeshLayout(1, 1, 1, 2, 2)
        mesh = lay.build_mesh()
        assert tuple(mesh.axis_names) == \
            ("data", "fsdp", "tp", "pipe", "expert")
        assert MeshLayout.of_mesh(mesh) == lay
        legacy = MeshLayout(2, 2, 1).build_mesh()
        assert tuple(legacy.axis_names) == ("data", "fsdp", "tp")
        assert MeshLayout.of_mesh(legacy) == MeshLayout(2, 2, 1)

    def test_pipeline_stage_role_spec(self):
        lay = MeshLayout(1, 1, 1, 2, 1)
        assert lay.spec_for("pipeline_stage", (2, 64, 64), min_size=0) == \
            P("pipe", None, None)
        # 1-wide pipe axis or indivisible stack: replicated
        assert lay.spec_for("pipeline_stage", (3, 64, 64), min_size=0) == \
            P(None, None, None)
        assert MeshLayout(1, 1, 1).spec_for(
            "pipeline_stage", (2, 64), min_size=0) == P(None, None)

    def test_expert_table_role_spec(self):
        lay = MeshLayout(1, 1, 1, 1, 2)
        assert lay.spec_for("expert_table", (4, 32, 64), min_size=0) == \
            P("expert", None, None)
        # expert x fsdp compose: experts on 0, fsdp on the largest
        # remaining divisible axis
        both = MeshLayout(1, 2, 1, 1, 2)
        assert both.spec_for("expert_table", (4, 32, 64), min_size=0) == \
            P("expert", None, "fsdp")
        # no expert axis: fsdp fallback alone
        assert MeshLayout(1, 2, 1).spec_for(
            "expert_table", (4, 32, 64), min_size=0) == \
            P(None, None, "fsdp")


class TestPartitioner:
    def test_partition_balanced_with_head(self):
        model = _mlp()
        out = partition_pipeline(model, 2)
        assert [type(m).__name__ for m in out.modules] == \
            ["GPipeSequential", "Linear"]
        assert len(out.modules[0].stages) == 2

    def test_partition_carries_built_params(self):
        set_seed(3)
        model = _mlp()
        model.build(jax.random.key(0))
        w0 = np.asarray(model.params[0]["weight"])
        w1 = np.asarray(model.params[2]["weight"])
        out = partition_pipeline(model, 2)
        stacked = out.params[0]  # [2, ...] stage stack
        leaves = jax.tree.leaves(stacked)
        assert leaves[0].shape[0] == 2
        np.testing.assert_array_equal(np.asarray(leaves[0][0]), w0)
        np.testing.assert_array_equal(np.asarray(leaves[0][1]), w1)

    def test_partition_typed_errors(self):
        # no repeated-block body of the requested width
        bad = nn.Sequential(nn.Linear(8, 16), nn.Linear(16, 8))
        with pytest.raises(PipelinePartitionError,
                           match="structurally identical"):
            partition_pipeline(bad, 2)
        # stateful stages (BatchNorm running stats) refuse loudly
        with pytest.raises(PipelinePartitionError, match="running state"):
            GPipeSequential([nn.BatchNormalization(8),
                             nn.BatchNormalization(8)])
        # non-chain containers refuse loudly
        with pytest.raises(PipelinePartitionError):
            partition_pipeline(nn.ConcatTable(nn.Linear(4, 4),
                                              nn.Linear(4, 4)), 2)

    def test_partition_linear_graph(self):
        from bigdl_tpu.nn.graph import Graph, Input
        inp = Input()
        h = nn.Linear(16, 16, with_bias=False)(inp)
        h = nn.Linear(16, 16, with_bias=False)(h)
        model = Graph(inp, h)
        out = partition_pipeline(model, 2)
        assert isinstance(out.modules[0], GPipeSequential)
        x = np.random.default_rng(0).normal(size=(4, 16)).astype(np.float32)
        out.build(jax.random.key(1))
        y = out.forward(x)
        assert y.shape == (4, 16)

    def test_stage_count_vs_mesh_mismatch_typed(self):
        model = partition_pipeline(_mlp(), 2)
        model.build(jax.random.key(0))
        mesh = Mesh(np.array(jax.devices()[:4]), ("pipe",))
        with pytest.raises(PipelinePartitionError, match="2 stages"):
            with mesh:
                model.apply(model.params, model.state,
                            jnp.zeros((8, 64), jnp.float32))

    def test_sequential_fallback_matches_plain_model(self):
        """On a mesh without a pipe axis the wrapper runs its stages
        sequentially — bit-identical to the unpartitioned model."""
        set_seed(5)
        model = _mlp()
        model.build(jax.random.key(0))
        x = np.random.default_rng(1).normal(size=(8, 64)).astype(np.float32)
        y_ref = np.asarray(model.forward(x))
        piped = partition_pipeline(model, 2)
        y = np.asarray(piped.forward(x))
        np.testing.assert_array_equal(y, y_ref)


@multidev
class TestPipelineTraining:
    def test_pipe2_parity_fraction_and_bubble_counter(self, tmp_path,
                                                      monkeypatch):
        """Acceptance: a pipe=2 Sequential trains 5 steps with loss
        parity <= 2e-3 vs the (4,1,1) DP run, stage params land 1/2 per
        device, and the traced run emits train.pipe_bubble_fraction."""
        set_seed(7)
        base = _mlp()
        Engine.reset()
        MeshLayout(4, 1, 1).install(jax.devices()[:4])
        base_losses, _ = _train(base, _dataset(160, 16),
                                LayoutSharding(base, min_size=0), 5)

        set_seed(7)
        plain = _mlp()
        plain.build()
        piped = partition_pipeline(plain, 2)
        Engine.reset()
        MeshLayout(1, 1, 1, 2, 1).install(jax.devices()[:2])
        monkeypatch.setenv("BIGDL_TPU_TRACE", str(tmp_path))
        pipe_losses, opt = _train(piped, _dataset(160, 16),
                                  LayoutSharding(piped, min_size=0), 5)
        assert len(pipe_losses) == len(base_losses) == 5
        np.testing.assert_allclose(pipe_losses, base_losses, atol=LOSS_TOL)
        # per-device stage-stack bytes: exactly 1/2
        stacked = piped.params[0]
        assert memstats.tree_device_bytes(stacked) * 2 == \
            memstats.tree_total_bytes(stacked)
        # the step self-described its schedule on the compile card
        assert opt._card_extra["pipe_stages"] == 2
        mb = opt._card_extra["pipe_microbatches"]
        assert opt._card_extra["pipe_bubble_fraction"] == pytest.approx(
            bubble_fraction(2, mb), abs=1e-4)
        # the counter reached the trace
        blob = ""
        for name in os.listdir(tmp_path):
            if name.startswith("trace."):
                blob += (tmp_path / name).read_text()
        assert "pipe_bubble_fraction" in blob

    def test_pipe_composes_with_fused_wire_knobs(self, monkeypatch):
        """The promotion claim: the pipelined step runs through the SAME
        _build_step machinery, so the fused update + bucketed wire knobs
        apply unchanged (and donation stays on)."""
        monkeypatch.setenv("BIGDL_TPU_FUSED_UPDATE", "1")
        monkeypatch.setenv("BIGDL_TPU_WIRE_BUCKET_MB", "4")
        set_seed(9)
        plain = _mlp()
        plain.build()
        piped = partition_pipeline(plain, 2)
        Engine.reset()
        MeshLayout(1, 1, 1, 2, 1).install(jax.devices()[:2])
        losses, opt = _train(piped, _dataset(96, 16),
                             LayoutSharding(piped, min_size=0), 3)
        assert len(losses) == 3 and all(np.isfinite(losses))
        assert opt._step_knobs["fused_update"] is True
        assert opt._step_knobs["donate"] is True
        assert opt._card_extra["fused_buffers"] >= 1

    def test_aot_warm_run_zero_fresh_compiles(self, tmp_path, monkeypatch):
        """Acceptance: with the AOT cache armed, a second training run of
        the same pipelined step deserializes the stored executable — the
        warm run performs ZERO fresh XLA compiles (lowering happens, the
        compile does not — utils/aot.cached_compile).

        The XLA persistent cache is un-latched for the duration (the
        test_serve/lenet_cold attribution discipline): an executable
        loaded from the XLA disk cache serializes into an unloadable AOT
        entry on CPU (quarantined + recompiled — correct, but it would
        make this ledger lie)."""
        from jax._src import compilation_cache as _cc

        from bigdl_tpu.utils import aot
        monkeypatch.setenv("BIGDL_TPU_AOT_CACHE", str(tmp_path))
        monkeypatch.setenv("BIGDL_TPU_XLA_CACHE", "0")
        aot.reset()
        prior_xla = jax.config.jax_compilation_cache_dir
        jax.config.update("jax_compilation_cache_dir", None)
        _cc.reset_cache()

        def run():
            set_seed(11)
            plain = _mlp()
            plain.build()
            piped = partition_pipeline(plain, 2)
            Engine.reset()
            MeshLayout(1, 1, 1, 2, 1).install(jax.devices()[:2])
            return _train(piped, _dataset(64, 16),
                          LayoutSharding(piped, min_size=0), 2)

        try:
            run()
            s1 = aot.stats()
            assert s1["compiles"] >= 1 and s1["stores"] >= 1
            jax.clear_caches()
            run()
            s2 = aot.stats()
            assert s2["compiles"] == s1["compiles"], \
                "warm pipelined step must not compile again"
            assert s2["misses"] == s1["misses"]
            assert s2["hits"] > s1["hits"]
        finally:
            jax.config.update("jax_compilation_cache_dir", prior_xla)
            _cc.reset_cache()


@multidev
class TestExpertTraining:
    def test_expert2_tables_sharded_trains_and_serves(self):
        """Acceptance: an expert=2 MoEFFN trains with tables sharded
        exactly 1/2 per device (bytes-asserted) and serves through
        _ShardedForward with outputs matching the dense forward."""
        set_seed(7)
        model = _moe_mlp()
        Engine.reset()
        MeshLayout(1, 1, 1, 1, 2).install(jax.devices()[:2])
        strategy = LayoutSharding(model, min_size=0)
        losses, _ = _train(model, _dataset(96, 16), strategy, 3)
        assert len(losses) == 3 and all(np.isfinite(losses))
        tables = {k: model.params[2][k] for k in ("w1", "w2", "b1", "b2")}
        assert model.params[2]["w1"].sharding.spec == \
            P("expert", None, None)
        assert memstats.tree_device_bytes(tables) * 2 == \
            memstats.tree_total_bytes(tables)
        # serve: the sharded forward answers like the dense math
        from bigdl_tpu.optim.optimizer import Predictor
        xs = np.random.default_rng(2).normal(size=(6, 64)).astype(np.float32)
        served = Predictor(model, batch_size=8, strategy=strategy).predict(
            [Sample(x, np.int32(0)) for x in xs])
        model.evaluate()
        host_params = jax.tree.map(np.asarray, model.params)
        ref, _ = model.apply(host_params, model.state, jnp.asarray(xs))
        np.testing.assert_allclose(np.asarray(served), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_expert2_loss_parity_vs_dense(self):
        set_seed(7)
        dense = _moe_mlp()
        Engine.reset()
        MeshLayout(1, 1, 1).install(jax.devices()[:1])
        dense_losses, _ = _train(dense, _dataset(96, 16),
                                 LayoutSharding(dense, min_size=0), 3)
        set_seed(7)
        ep = _moe_mlp()
        Engine.reset()
        MeshLayout(1, 1, 1, 1, 2).install(jax.devices()[:2])
        ep_losses, _ = _train(ep, _dataset(96, 16),
                              LayoutSharding(ep, min_size=0), 3)
        np.testing.assert_allclose(ep_losses, dense_losses, atol=LOSS_TOL)


class TestMoEFixes:
    def test_capacity_overflow_deterministic(self):
        """Dropped tokens are stable across runs: the routing is a pure
        function of the logits, so two evaluations (and a jitted one)
        agree bitwise even under heavy overflow."""
        logits = jax.random.normal(jax.random.key(2), (64, 4))
        a = top_k_routing(logits, capacity=3, k=2)
        b = top_k_routing(logits, capacity=3, k=2)
        j = jax.jit(lambda l: top_k_routing(l, capacity=3, k=2))(logits)
        for x, y, z in zip(a, b, j):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
            np.testing.assert_array_equal(np.asarray(x), np.asarray(z))
        # overflow really happened (otherwise this tests nothing)
        assert float(jnp.sum(a[1])) < 128.0

    def test_load_balancing_loss_values(self):
        """Known values: perfectly balanced uniform routing scores
        exactly 1.0; full collapse onto one expert scores E."""
        T, E = 32, 4
        probs = jnp.full((T, E), 1.0 / E)
        assign = jnp.tile(jnp.eye(E), (T // E, 1))
        assert float(load_balancing_loss(probs, assign)) == \
            pytest.approx(1.0, abs=1e-6)
        collapsed_p = jnp.zeros((T, E)).at[:, 0].set(1.0)
        collapsed_a = jnp.zeros((T, E)).at[:, 0].set(1.0)
        assert float(load_balancing_loss(collapsed_p, collapsed_a)) == \
            pytest.approx(float(E), abs=1e-6)

    @multidev
    def test_legacy_mesh_degrades_silently(self):
        """MoEFFN(expert_axis='expert') on a legacy data-only mesh:
        replicated tables, no all-to-all, NO warning — the documented
        graceful degrade (was: assumed the axis exists)."""
        MoEFFN._warned_no_mesh = False
        set_seed(3)
        model = _moe_mlp()
        Engine.reset()
        Engine.init(mesh_shape={"data": 2}, devices=jax.devices()[:2])
        losses, _ = _train(model, _dataset(64, 16), None, 2)
        assert all(np.isfinite(losses))
        assert MoEFFN._warned_no_mesh is False

    @multidev
    def test_expert_parallel_ffn_degrades_on_1wide_mesh(self):
        """expert_parallel_ffn on a mesh without the axis (or a 1-wide
        one) falls back to the dense math instead of crashing."""
        from bigdl_tpu.parallel import expert_parallel_ffn
        m = MoEFFN(16, 32, num_experts=4, capacity_factor=8.0,
                   expert_axis=None).build(jax.random.key(0)).evaluate()
        x = jax.random.normal(jax.random.key(4), (32, 16))
        y_dense = m.forward(x)
        legacy = Mesh(np.array(jax.devices()[:2]), ("data",))
        y = expert_parallel_ffn(legacy, m.params, x, k=1,
                                capacity_factor=8.0)
        np.testing.assert_allclose(np.asarray(y), np.asarray(y_dense),
                                   rtol=2e-4, atol=2e-5)
        one_wide = Mesh(np.array(jax.devices()[:1]).reshape(1), ("expert",))
        y1 = expert_parallel_ffn(one_wide, m.params, x, k=1,
                                 capacity_factor=8.0)
        np.testing.assert_allclose(np.asarray(y1), np.asarray(y_dense),
                                   rtol=2e-4, atol=2e-5)


@multidev
class TestReformNewAxes:
    def test_shrink_keeps_pipe_expert_block(self):
        """Engine.reform shrinks 'data' and keeps fsdp x tp x pipe x
        expert intact; LayoutSharding.remap re-derives the shards."""
        set_seed(13)
        plain = _mlp()
        plain.build()
        piped = partition_pipeline(plain, 2)
        Engine.reset()
        MeshLayout(2, 1, 1, 2, 1).install(jax.devices()[:4])
        strategy = LayoutSharding(piped, min_size=0)
        mesh = Engine.mesh()
        params = jax.device_put(piped.params,
                                strategy.param_sharding(mesh, piped.params))
        new_mesh = Engine.reform(world=1, rank=0, survivors=[0],
                                 devices=jax.devices()[:2])
        assert dict(zip(new_mesh.axis_names, new_mesh.devices.shape)) == \
            {"data": 1, "fsdp": 1, "tp": 1, "pipe": 2, "expert": 1}
        remapped = strategy.remap(new_mesh, params)
        stacked = remapped[0]
        assert memstats.tree_device_bytes(stacked) * 2 == \
            memstats.tree_total_bytes(stacked)

    def test_typed_error_when_block_cannot_survive(self):
        Engine.reset()
        MeshLayout(2, 1, 1, 1, 2).install(jax.devices()[:4])
        with pytest.raises(MeshReformError, match="shard groups intact"):
            Engine.reform(world=1, rank=0, survivors=[0],
                          devices=jax.devices()[:3])


@multidev
class TestRingAttnSeam:
    def test_ring_over_tp_parity(self, monkeypatch):
        """BIGDL_TPU_RING_ATTN=1 on a tp>1 mesh routes the attention
        core through the ring (seq sharded over 'tp'), matching the
        dense flash path."""
        x = jax.random.normal(jax.random.key(20), (2, 16, 32))
        mha = nn.MultiHeadAttention(32, 4, causal=True).build(
            jax.random.key(21))
        monkeypatch.delenv("BIGDL_TPU_RING_ATTN", raising=False)
        y_ref, _ = mha.apply(mha.params, mha.state, x)
        Engine.reset()
        mesh = MeshLayout(1, 1, 2).install(jax.devices()[:2])
        monkeypatch.setenv("BIGDL_TPU_RING_ATTN", "1")
        with mesh:
            y_ring, _ = mha.apply(mha.params, mha.state, x)
        np.testing.assert_allclose(np.asarray(y_ring), np.asarray(y_ref),
                                   atol=2e-5, rtol=2e-5)

    def test_seam_inert_when_indivisible_or_ungated(self, monkeypatch):
        x = jax.random.normal(jax.random.key(22), (2, 15, 32))  # 15 % 2
        mha = nn.MultiHeadAttention(32, 4, causal=True).build(
            jax.random.key(23))
        y_ref, _ = mha.apply(mha.params, mha.state, x)
        Engine.reset()
        mesh = MeshLayout(1, 1, 2).install(jax.devices()[:2])
        monkeypatch.setenv("BIGDL_TPU_RING_ATTN", "1")
        with mesh:
            y, _ = mha.apply(mha.params, mha.state, x)  # T=15: flash path
        np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                                   atol=1e-6)


def _mlp4():
    """Four identical blocks + a head: splits into 2 GPipe stages or 4
    interleaved 1F1B slices of the same params."""
    return nn.Sequential(
        nn.Linear(64, 64, with_bias=False), nn.ReLU(),
        nn.Linear(64, 64, with_bias=False), nn.ReLU(),
        nn.Linear(64, 64, with_bias=False), nn.ReLU(),
        nn.Linear(64, 64, with_bias=False), nn.ReLU(),
        nn.Linear(64, 8, with_bias=False))


def _pipe_step_temp_bytes(num_stages, batch=256):
    """XLA temp (peak scratch) budget of the real compiled train step
    under the CURRENT schedule env knobs (memstats proxy for peak live
    activations — never executed)."""
    jax.clear_caches()
    Engine.reset()
    mesh = MeshLayout(1, 1, 1, 2, 1).install(jax.devices()[:2])
    model = _mlp4()
    model.build(jax.random.key(0))
    model = partition_pipeline(model, num_stages)
    from bigdl_tpu.optim import Optimizer as _Opt
    opt = _Opt(model, dataset=None, criterion=nn.CrossEntropyCriterion(),
               end_trigger=Trigger.max_iteration(1),
               strategy=LayoutSharding(model, min_size=0))
    opt.set_optim_method(SGD(learning_rate=0.05))
    step, param_sh, data_sh = opt._build_step(mesh)
    rng = np.random.default_rng(0)
    inp = jax.device_put(
        jnp.asarray(rng.normal(size=(batch, 64)), jnp.float32), data_sh)
    tgt = jax.device_put(
        jnp.asarray(rng.integers(0, 8, size=batch), jnp.int32), data_sh)
    params = jax.device_put(model.params, param_sh)
    opt_state = jax.device_put(opt.optim_method.init_state(model.params),
                               opt._opt_sh)
    args = (params, model.state, opt_state, inp, tgt, jnp.float32(0.05),
            jax.random.key(1))
    ma = memstats.compiled_memory_analysis(step.lower(*args).compile())
    return (ma or {}).get("temp_bytes")


@multidev
class TestOneFOneB:
    """The 1F1B schedule + interleaved virtual stages (ISSUE 13
    tentpole): parity vs GPipe at the pinned tolerance, the bubble and
    activation-memory claims, remat/AOT composition, and the
    microbatch-clamp surfacing."""

    def _run(self, num_stages, steps=5, monkeypatch=None, **env):
        for k, val in env.items():
            monkeypatch.setenv(k, str(val))
        set_seed(13)
        model = _mlp4()
        model.build()
        piped = partition_pipeline(model, num_stages)
        Engine.reset()
        MeshLayout(1, 1, 1, 2, 1).install(jax.devices()[:2])
        return _train(piped, _dataset(16 * steps * 2, 16),
                      LayoutSharding(piped, min_size=0), steps)

    def test_1f1b_v1_loss_parity_vs_gpipe(self, monkeypatch):
        """pipe=2, equal m=8: 1F1B's explicitly staged backward must
        reproduce GPipe's losses within the pinned reassociation
        tolerance (docs/parallelism.md 'Choosing a schedule')."""
        monkeypatch.setenv("BIGDL_TPU_PIPE_MICROBATCHES", "8")
        g_losses, _ = self._run(2, monkeypatch=monkeypatch)
        f_losses, fopt = self._run(
            2, monkeypatch=monkeypatch, BIGDL_TPU_PIPE_SCHEDULE="1f1b")
        assert len(f_losses) == len(g_losses) == 5
        np.testing.assert_allclose(f_losses, g_losses, atol=LOSS_TOL)
        assert fopt._card_extra["pipe_schedule"] == "1f1b"
        assert fopt._card_extra["pipe_virtual_stages"] == 1
        # v=1 1F1B keeps GPipe's bubble — the win is memory
        assert fopt._card_extra["pipe_bubble_fraction"] == pytest.approx(
            bubble_fraction(2, 8), abs=1e-4)

    def test_1f1b_interleaved_parity_and_lower_bubble(self, monkeypatch):
        """pipe=2 with v=2 (4 interleaved slices): losses still match,
        and the card reports the strictly lower interleaved bubble
        (1/17 vs GPipe's 1/9 at m=8) — the acceptance geometry."""
        monkeypatch.setenv("BIGDL_TPU_PIPE_MICROBATCHES", "8")
        g_losses, gopt = self._run(2, monkeypatch=monkeypatch)
        f_losses, fopt = self._run(
            4, monkeypatch=monkeypatch, BIGDL_TPU_PIPE_SCHEDULE="1f1b",
            BIGDL_TPU_PIPE_VIRTUAL_STAGES="2")
        np.testing.assert_allclose(f_losses, g_losses, atol=LOSS_TOL)
        g_bubble = gopt._card_extra["pipe_bubble_fraction"]
        f_bubble = fopt._card_extra["pipe_bubble_fraction"]
        assert g_bubble == pytest.approx(1 / 9, abs=1e-4)
        assert f_bubble == pytest.approx(1 / 17, abs=1e-4)
        assert f_bubble < g_bubble
        assert fopt._step_knobs["pipe_schedule"] == "1f1b"
        assert fopt._step_knobs["pipe_virtual_stages"] == 2
        # per-device stage stack still 1/2 of the logical params
        stacked = next(p for c, p in zip(fopt.model.modules,
                                         fopt.model.params)
                       if isinstance(c, GPipeSequential))
        assert memstats.tree_device_bytes(stacked) * 2 == \
            memstats.tree_total_bytes(stacked)

    def test_1f1b_bubble_counter_from_actual_schedule(self, tmp_path,
                                                      monkeypatch):
        """The traced run emits the TABLE's bubble (1/17), not the
        gpipe closed form — the counter reads the realized schedule."""
        import json as _json
        monkeypatch.setenv("BIGDL_TPU_PIPE_MICROBATCHES", "8")
        monkeypatch.setenv("BIGDL_TPU_TRACE", str(tmp_path))
        self._run(4, steps=2, monkeypatch=monkeypatch,
                  BIGDL_TPU_PIPE_SCHEDULE="1f1b",
                  BIGDL_TPU_PIPE_VIRTUAL_STAGES="2")
        vals = []
        for name in os.listdir(tmp_path):
            if not name.startswith("trace."):
                continue
            blob = _json.loads((tmp_path / name).read_text())
            for ev in blob.get("traceEvents", []):
                if ev.get("ph") == "C" and ev.get("name") == "train":
                    v = ev.get("args", {}).get("pipe_bubble_fraction")
                    if v is not None:
                        vals.append(float(v))
        assert vals, "no pipe_bubble_fraction samples in the trace"
        assert all(v == pytest.approx(1 / 17, abs=1e-4) for v in vals)

    def test_activation_memory_bound(self, monkeypatch):
        """The memory claim, twice: the schedule table's analytic
        in-flight count is m-independent and below GPipe's keep-all,
        and XLA's own temp budget for the compiled 1F1B step is <= the
        GPipe step's at an activation-dominated batch."""
        from bigdl_tpu.parallel import build_schedule
        tbl = build_schedule("1f1b", 2, 8, 2)
        assert tbl.peak_inflight == 5 < 16  # GPipe keeps m*v
        assert build_schedule("1f1b", 2, 16, 2).peak_inflight == 5
        monkeypatch.setenv("BIGDL_TPU_PIPE_MICROBATCHES", "8")
        g_temp = _pipe_step_temp_bytes(2)
        monkeypatch.setenv("BIGDL_TPU_PIPE_SCHEDULE", "1f1b")
        f1_temp = _pipe_step_temp_bytes(2)
        monkeypatch.setenv("BIGDL_TPU_PIPE_VIRTUAL_STAGES", "2")
        f2_temp = _pipe_step_temp_bytes(4)
        if g_temp is None:
            pytest.skip("backend exposes no memory_analysis")
        assert f1_temp <= g_temp
        assert f2_temp <= g_temp

    def test_remat_composes_with_1f1b(self, monkeypatch):
        """remat=True (stage-level jax.checkpoint on the forward
        schedule) must compose with the 1F1B backward — parity held;
        the 1F1B backward already recomputes (full-remat by design)."""
        monkeypatch.setenv("BIGDL_TPU_PIPE_SCHEDULE", "1f1b")
        monkeypatch.setenv("BIGDL_TPU_PIPE_MICROBATCHES", "8")
        set_seed(13)
        model = _mlp4()
        model.build()
        piped = partition_pipeline(model, 2, remat=True)
        Engine.reset()
        MeshLayout(1, 1, 1, 2, 1).install(jax.devices()[:2])
        r_losses, _ = _train(piped, _dataset(160, 16),
                             LayoutSharding(piped, min_size=0), 5)
        p_losses, _ = self._run(2, monkeypatch=monkeypatch)
        assert len(r_losses) == 5 and all(np.isfinite(r_losses))
        np.testing.assert_allclose(r_losses, p_losses, atol=LOSS_TOL)

    def test_microbatch_clamp_logged_and_surfaced(self, monkeypatch,
                                                  caplog):
        """The silent-clamp satellite: a knob that does not divide the
        local batch is clamped, logged ONCE (requested -> effective),
        and the effective count lands in step_knobs + the compile card
        so bench records agree with reality."""
        import logging as _logging
        monkeypatch.setenv("BIGDL_TPU_PIPE_MICROBATCHES", "7")
        with caplog.at_level(_logging.WARNING, logger="bigdl_tpu"):
            _, opt = self._run(2, steps=3, monkeypatch=monkeypatch,
                               BIGDL_TPU_PIPE_SCHEDULE="1f1b")
        clamp_logs = [r for r in caplog.records
                      if "clamped to 4 microbatches" in r.getMessage()]
        assert len(clamp_logs) == 1  # once, not per trace/step
        # local batch 16: 7 -> 4 (largest feasible <= the knob)
        assert opt._step_knobs["pipe_microbatches"] == 4
        assert opt._card_extra["pipe_microbatches"] == 4
        assert opt._card_extra["pipe_bubble_fraction"] == pytest.approx(
            bubble_fraction(2, 4, "1f1b", 1), abs=1e-4)

    def test_aot_warm_run_zero_fresh_compiles_1f1b(self, tmp_path,
                                                   monkeypatch):
        """The AOT cache composes with the new schedule knobs (the
        fingerprint carries pipe_schedule/pipe_virtual_stages): a warm
        run of the 1F1B step performs zero fresh XLA compiles."""
        from jax._src import compilation_cache as _cc

        from bigdl_tpu.utils import aot
        monkeypatch.setenv("BIGDL_TPU_AOT_CACHE", str(tmp_path))
        monkeypatch.setenv("BIGDL_TPU_XLA_CACHE", "0")
        monkeypatch.setenv("BIGDL_TPU_PIPE_SCHEDULE", "1f1b")
        monkeypatch.setenv("BIGDL_TPU_PIPE_VIRTUAL_STAGES", "2")
        monkeypatch.setenv("BIGDL_TPU_PIPE_MICROBATCHES", "8")
        aot.reset()
        prior_xla = jax.config.jax_compilation_cache_dir
        jax.config.update("jax_compilation_cache_dir", None)
        _cc.reset_cache()

        def run():
            set_seed(11)
            model = _mlp4()
            model.build()
            piped = partition_pipeline(model, 4)
            Engine.reset()
            MeshLayout(1, 1, 1, 2, 1).install(jax.devices()[:2])
            return _train(piped, _dataset(64, 16),
                          LayoutSharding(piped, min_size=0), 2)

        try:
            run()
            s1 = aot.stats()
            assert s1["compiles"] >= 1 and s1["stores"] >= 1
            jax.clear_caches()
            run()
            s2 = aot.stats()
            assert s2["compiles"] == s1["compiles"], \
                "warm 1F1B step must not compile again"
            assert s2["misses"] == s1["misses"]
            assert s2["hits"] > s1["hits"]
        finally:
            jax.config.update("jax_compilation_cache_dir", prior_xla)
            _cc.reset_cache()
