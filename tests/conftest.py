"""Test environment: 8 virtual CPU devices so distributed machinery is exercised
without TPU hardware — the TPU-native version of the reference's
`Engine.setNodeAndCore(4, 4)` simulate-a-cluster-in-one-JVM trick
(DistriOptimizerSpec.scala:33-41, SURVEY.md §4).

Note: this image's sitecustomize imports jax at interpreter startup (axon TPU
plugin), so env vars are too late — use jax.config.update instead, which works
as long as no backend has been initialized yet.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")  # harmless if sitecustomize won

import jax  # noqa: E402

from bigdl_tpu.utils.platform import force_cpu  # noqa: E402

if not force_cpu(8):
    # backend already initialized — only acceptable if it is ALREADY the
    # 8-device CPU config (e.g. re-entrant collection); fail loudly instead
    # of running the suite on the wrong backend
    assert jax.default_backend() == "cpu" and jax.device_count() >= 8, (
        f"jax backend initialized before conftest: "
        f"{jax.default_backend()} x {jax.device_count()}")

import sys

# repo root on sys.path ONCE for every test module: examples/ (and any
# sibling repo content) stays importable when the suite runs against a
# pip-installed bigdl_tpu from outside the repo
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if os.environ.get("BIGDL_TPU_TEST_INSTALLED"):
    # packaging validation: the pip-installed wheel in site-packages must
    # win for bigdl_tpu — strip any repo-root entries (python -m pytest
    # from the repo puts one at sys.path[0]) and append instead, then
    # PROVE the import really came from outside the source tree; a silent
    # source-tree pass would validate nothing
    sys.path = [p for p in sys.path
                if os.path.abspath(p or os.getcwd()) != _REPO_ROOT]
    sys.path.append(_REPO_ROOT)
    import bigdl_tpu  # noqa: E402

    _origin = os.path.abspath(bigdl_tpu.__file__)
    # compare against the package SOURCE dir, not the whole repo root: an
    # in-repo virtualenv (repo/.venv/.../site-packages) is a legitimate
    # install location
    assert not _origin.startswith(
        os.path.join(_REPO_ROOT, "bigdl_tpu") + os.sep), (
        "BIGDL_TPU_TEST_INSTALLED=1 but bigdl_tpu resolved from the source "
        f"tree ({_origin}); install the wheel and run from outside the repo")
elif _REPO_ROOT not in sys.path:
    # dev default: the SOURCE tree must win even when some stale wheel
    # happens to be installed, or edits would go silently untested
    sys.path.insert(0, _REPO_ROOT)

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_engine():
    from bigdl_tpu.utils.engine import Engine
    Engine.reset()
    yield
    Engine.reset()


def spawn_multihost_workers(worker_src: str, tmp_path, n: int = 2,
                            timeout: int = 420):
    """Run `worker_src` as n real OS processes joined via the
    BIGDL_TPU_COORDINATOR env contract; returns the last JSON line each
    worker printed.  Shared by the multi-host integration tests."""
    import json
    import os
    import socket
    import subprocess
    import sys

    worker = tmp_path / "mh_worker.py"
    worker.write_text(worker_src)
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    env_base = {**os.environ,
                "PYTHONPATH": _REPO_ROOT,
                "BIGDL_TPU_COORDINATOR": f"127.0.0.1:{port}",
                "BIGDL_TPU_NUM_PROCESSES": str(n)}
    procs = [subprocess.Popen(
        [sys.executable, str(worker)],
        env={**env_base, "BIGDL_TPU_PROCESS_ID": str(i)},
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        for i in range(n)]
    # drain pipes CONCURRENTLY: workers run distributed barriers, so a
    # sequential communicate() deadlocks if a later worker fills its 64KB
    # pipe while an earlier one waits in a collective
    from concurrent.futures import ThreadPoolExecutor
    with ThreadPoolExecutor(max_workers=n) as pool:
        results = list(pool.map(
            lambda p: (p, *p.communicate(timeout=timeout)), procs))
    outs = []
    for p, out, err in results:
        assert p.returncode == 0, f"worker failed:\n{err[-3000:]}"
        line = [l for l in out.splitlines() if l.startswith("{")][-1]
        outs.append(json.loads(line))
    return outs
