"""Test environment: 8 virtual CPU devices so distributed machinery is exercised
without TPU hardware — the TPU-native version of the reference's
`Engine.setNodeAndCore(4, 4)` simulate-a-cluster-in-one-JVM trick
(DistriOptimizerSpec.scala:33-41, SURVEY.md §4).

Note: this image's sitecustomize imports jax at interpreter startup (axon TPU
plugin), so env vars are too late — use jax.config.update instead, which works
as long as no backend has been initialized yet.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")  # harmless if sitecustomize won

import jax  # noqa: E402

from bigdl_tpu.utils.platform import force_cpu  # noqa: E402

if not force_cpu(8):
    # backend already initialized — only acceptable if it is ALREADY the
    # 8-device CPU config (e.g. re-entrant collection); fail loudly instead
    # of running the suite on the wrong backend
    assert jax.default_backend() == "cpu" and jax.device_count() >= 8, (
        f"jax backend initialized before conftest: "
        f"{jax.default_backend()} x {jax.device_count()}")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_engine():
    from bigdl_tpu.utils.engine import Engine
    Engine.reset()
    yield
    Engine.reset()
