"""Distributed Evaluator/Predictor: inference must saturate the Engine mesh
the way training does (round-2 verdict weak #3 — bulk inference previously
ran on one device while Optimizer._run_validation sharded).

Reference: optim/Evaluator.scala:37-60 fans inference over every executor via
ModelBroadcast; here one SPMD forward spans every mesh device.
"""

import numpy as np
import jax
import jax.numpy as jnp

import bigdl_tpu.nn as nn
from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
from bigdl_tpu.models.lenet import LeNet5
from bigdl_tpu.optim import Evaluator, Predictor, Top1Accuracy, Loss
from bigdl_tpu.utils.engine import Engine


def _samples(n=96):
    r = np.random.default_rng(0)
    xs = r.normal(size=(n, 28, 28, 1)).astype(np.float32)
    ys = r.integers(0, 10, size=n)
    return [Sample(x, np.int32(y)) for x, y in zip(xs, ys)]


def test_evaluator_uses_all_mesh_devices():
    Engine.init()
    assert Engine.device_count() == 8  # conftest: 8 virtual CPU devices
    model = LeNet5(10).build(jax.random.key(0))
    ev = Evaluator(model)
    ds = DataSet.array(_samples())
    res = ev.test(ds, [Top1Accuracy(), Loss(nn.ClassNLLCriterion())],
                  batch_size=32)
    assert len(res) == 2
    acc_val, acc_n = res[0][1].result()
    assert acc_n == 96  # padding rows must not be counted
    # the compiled forward actually spanned the whole mesh
    out, _ = ev._engine(jnp.zeros((32, 28, 28, 1)))
    assert len(out.sharding.device_set) == 8
    spec = out.sharding.spec
    assert spec and spec[0] == Engine.DATA_AXIS


def test_evaluator_pads_odd_batches():
    """Batch sizes not divisible by the mesh width must still work (the last
    batch of an epoch, or a user-chosen odd batch size)."""
    Engine.init()
    model = LeNet5(10).build(jax.random.key(0))
    ds = DataSet.array(_samples(50))  # 50 % 8 != 0
    res = Evaluator(model).test(ds, [Top1Accuracy()], batch_size=24)
    _, n = res[0][1].result()
    assert n == 50


def test_predictor_sharded_matches_local_forward():
    Engine.init()
    model = LeNet5(10).build(jax.random.key(1))
    xs = np.random.default_rng(1).normal(size=(40, 28, 28, 1)).astype(
        np.float32)
    pred = Predictor(model, batch_size=16)
    got = pred.predict([Sample(x) for x in xs])
    # reference output from the plain single-device functional core
    expect, _ = model.apply(model.params, model.state, jnp.asarray(xs))
    np.testing.assert_allclose(got, np.asarray(expect), rtol=2e-4, atol=2e-5)
    cls = pred.predict_class([Sample(x) for x in xs])
    assert cls.shape == (40,)
    assert np.array_equal(cls, np.argmax(np.asarray(expect), axis=-1))


def test_evaluator_sees_updated_weights():
    """A reused Evaluator must re-place params after they change (regression:
    the placement cache keyed only on the mesh)."""
    Engine.init()
    model = LeNet5(10).build(jax.random.key(0))
    ev = Evaluator(model)
    x = jnp.zeros((8, 28, 28, 1))
    out1, _ = ev._engine(x)
    model.params = jax.tree.map(lambda t: t + 1.0, model.params)
    out2, _ = ev._engine(x)
    assert not np.allclose(np.asarray(out1), np.asarray(out2))


def test_module_evaluate_overload():
    """model.evaluate(dataset, methods) bulk evaluation — the reference's
    AbstractModule.evaluate(rdd, vMethods, batchSize) entry (SURVEY §3.4)."""
    from bigdl_tpu.optim import Top1Accuracy
    Engine.init()
    model = LeNet5(10).build(jax.random.key(0))
    res = model.evaluate(DataSet.array(_samples(64)), [Top1Accuracy()],
                         batch_size=32)
    _, n = res[0][1].result()
    assert n == 64
    # no-arg form still toggles training mode and chains
    assert model.evaluate() is model
    assert not model.is_training()


def test_module_evaluate_defaults_and_validation():
    from bigdl_tpu.optim import Top1Accuracy
    import pytest as _pytest
    Engine.init()
    model = LeNet5(10).build(jax.random.key(0))
    # batch_size omitted on an un-batched Sample dataset: defaulted, works
    res = model.evaluate(DataSet.array(_samples(40)), [Top1Accuracy()])
    _, n = res[0][1].result()
    assert n == 40
    with _pytest.raises(ValueError):
        model.evaluate(DataSet.array(_samples(8)))  # no methods


def test_evaluator_accepts_raw_sample_list():
    """Evaluator.test over a plain list of Samples — the RDD[Sample] analog
    (Evaluator.scala:48); mirrors Predictor.predict's list acceptance."""
    from bigdl_tpu.optim import Evaluator, Top1Accuracy
    Engine.init()
    model = LeNet5(10).build(jax.random.key(0))
    res = Evaluator(model).test(_samples(48), [Top1Accuracy()])
    _, n = res[0][1].result()
    assert n == 48


def test_module_evaluate_accepts_raw_sample_list():
    """The facade inherits Evaluator.test's coercion — same inputs at every
    entry point (module.evaluate / Evaluator / Validator)."""
    from bigdl_tpu.optim import Top1Accuracy
    Engine.init()
    model = LeNet5(10).build(jax.random.key(0))
    res = model.evaluate(_samples(24), [Top1Accuracy()])
    _, n = res[0][1].result()
    assert n == 24


def test_set_validation_accepts_raw_sample_list():
    """set_validation joins the raw-Sample-list contract of every other
    entry point (found by an end-to-end drive: _run_validation crashed on
    'list' object has no attribute 'data' while training ran fine)."""
    from bigdl_tpu.optim import Adam, Optimizer, Top1Accuracy, Trigger
    import bigdl_tpu.nn as nn
    Engine.init()
    samples = _samples(96)
    opt = Optimizer(LeNet5(10), samples, nn.ClassNLLCriterion(),
                    batch_size=32)
    opt.set_optim_method(Adam(1e-3))
    opt.set_validation(Trigger.several_iteration(2), samples[:32],
                       [Top1Accuracy()])
    opt.set_end_when(Trigger.max_iteration(5))
    assert opt.optimize() is not None
