"""Multi-host DP x TP integration: real OS processes x 2 virtual CPU devices
forming a (model, data) global mesh, training with a TensorParallel rule —
the cross-host form of the dryrun's flagship sharding.

Extends tests/test_multihost.py (pure DP) to 2-D meshes: TP shards cross
process boundaries, so every compiled step's collectives ride the Gloo
inter-process backend — evidence the net-new parallelism (SURVEY.md §7)
works beyond one host."""

import textwrap

import pytest

# subprocess integration: the slow lane (pyproject addopts)
pytestmark = pytest.mark.slow

from conftest import spawn_multihost_workers

# one template for every process count: the two scenarios must not drift
# (they once disagreed on incidental seeds/epochs)
_WORKER_TEMPLATE = textwrap.dedent("""
    import json
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 2)

    import numpy as np
    from jax.sharding import PartitionSpec as P
    from bigdl_tpu.utils.engine import Engine
    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.optim import Adam, Optimizer, Trigger
    from bigdl_tpu.parallel.sharding import TensorParallel

    # 'model' FIRST: the global device list orders process 0's devices
    # before process 1's (row-major reshape), so the leading axis is the
    # one that spans processes — TP collectives must ride the inter-process
    # backend, not stay intra-host.  With data > processes the data axis
    # crosses process boundaries too (the closest one machine gets to the
    # v5e-pod topology, BASELINE.md "linear 8 -> 64").
    mesh = Engine.init(mesh_shape={{"model": 2, "data": {data}}})
    assert jax.process_count() == {nproc}
    assert jax.device_count() == {data} * 2
    rank = jax.process_index()

    r = np.random.default_rng(7)  # SAME data on every process
    n, d, classes = 256, 16, 4
    ys = r.integers(0, classes, size=n)
    centers = r.normal(0, 2.0, size=(classes, d)).astype(np.float32)
    xs = (centers[ys] + r.normal(0, 0.3, size=(n, d))).astype(np.float32)
    samples = [Sample(x, np.int32(y)) for x, y in zip(xs, ys)]
    ds = DataSet.rdd(samples).transform(SampleToMiniBatch(32,
                                                          drop_last=True))

    def tp_rule(path, leaf):
        # column-parallel: shard the output-features axis of 2-D weights
        if leaf.ndim == 2 and leaf.shape[-1] % 2 == 0:
            return P(None, "model")
        return P()

    from bigdl_tpu.common import set_seed
    set_seed(123)  # identical init on every process
    model = nn.Sequential(nn.Linear(d, 32), nn.ReLU(),
                          nn.Linear(32, classes), nn.LogSoftMax())
    from bigdl_tpu.optim import Top1Accuracy
    ckpt = r"{ckpt}"
    opt = (Optimizer(model, ds, nn.ClassNLLCriterion(),
                     strategy=TensorParallel(rule=tp_rule))
           .set_optim_method(Adam(1e-2))
           # validation under TP sharding: the class axis of the output is
           # 'model'-sharded, exercising the _gather_non_batch path that
           # round 3 rejected with NotImplementedError
           .set_validation(Trigger.every_epoch(), samples,
                           [Top1Accuracy()], batch_size=32)
           .set_checkpoint(ckpt, Trigger.every_epoch())
           .set_end_when(Trigger.max_epoch({epochs})))
    trained = opt.optimize()  # TP validation runs every epoch in here —
    # round 3 raised NotImplementedError at the first validation boundary

    # checkpoint-resume under TP: fresh optimizer resumed from the last
    # epoch snapshot, one more epoch (validation included) must complete
    import glob, os
    from jax.experimental import multihost_utils
    # rank 0 writes the snapshots; barrier so every rank globs the SAME
    # completed set (divergent snaps[-1] would feed device_put different
    # values per rank)
    multihost_utils.sync_global_devices("ckpt-written")
    snaps = sorted(glob.glob(os.path.join(ckpt, "model.*")),
                   key=lambda p: int(p.rsplit(".", 1)[1]))
    assert snaps, os.listdir(ckpt)
    set_seed(123)
    model2 = nn.Sequential(nn.Linear(d, 32), nn.ReLU(),
                           nn.Linear(32, classes), nn.LogSoftMax())
    opt2 = (Optimizer(model2, ds, nn.ClassNLLCriterion(),
                      strategy=TensorParallel(rule=tp_rule))
            .set_optim_method(Adam(1e-2))
            .set_validation(Trigger.every_epoch(), samples,
                            [Top1Accuracy()], batch_size=32)
            .set_end_when(Trigger.max_epoch({epochs} + 1)))
    opt2.resume_from(snaps[-1])
    trained = opt2.optimize()

    # the TP-sharded weight spans processes; gather it for the digest
    from jax.experimental import multihost_utils
    w1 = multihost_utils.process_allgather(trained.params[0]["weight"],
                                           tiled=True)
    digest = float(np.abs(np.asarray(w1)).sum())
    loss = opt2.optim_method.hyper["loss"]
    print(json.dumps({{"rank": rank, "loss": loss, "digest": digest}}),
          flush=True)
""")


def _run_dp_tp(tmp_path, nproc, epochs):
    worker = _WORKER_TEMPLATE.format(nproc=nproc, data=nproc, epochs=epochs,
                                     ckpt=str(tmp_path / "ckpt"))
    outs = spawn_multihost_workers(worker, tmp_path, n=nproc)
    by_rank = {o["rank"]: o for o in outs}
    assert set(by_rank) == set(range(nproc))
    for o in outs:
        assert o["loss"] < 0.5, o  # learned the separable blobs
        # the allgathered TP weight must agree across all processes
        assert o["digest"] == pytest.approx(by_rank[0]["digest"], rel=1e-6)


def test_two_process_dp_tp_training(tmp_path):
    _run_dp_tp(tmp_path, nproc=2, epochs=20)


def test_four_process_dp_tp_training(tmp_path):
    """8 global devices across 4 OS processes — both mesh axes span
    process boundaries (the 2-process case's model axis does, but its data
    axis stays intra-process)."""
    _run_dp_tp(tmp_path, nproc=4, epochs=12)
