"""Static audit of the BigDL wire format against the reference Scala source.

Round-4 verdict item 5: reader and writer share one author, so an in-memory
roundtrip proves self-consistency, not fidelity.  This audit breaks the
circularity STATICALLY: every classdesc the writer emits is checked against
the reference's own class declarations —

- every emitted field NAME must be a declared (non-@transient) val/var/
  constructor-param of the Scala class or one of its superclasses
  (the JVM serializes exactly the non-transient fields, JOS spec §1.10);
- primitive field TYPES must match (Int->I, Double->D, Boolean->Z, ...);
- the emitted @SerialVersionUID must equal the source annotation where one
  exists (automating the judge's by-hand spot check), and the documented
  fallback of 1 is only allowed for classes with NO annotation;
- coverage: every com.intel.* entry in interop.bigdl._SUID must actually
  be exercised by the kitchen-sink models below.

The audit needs the reference checkout; it skips (loudly) where
/root/reference is absent (e.g. the installed-wheel lane).
"""

import os
import re

import numpy as np
import jax
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.interop import bigdl as bigdl_fmt
from bigdl_tpu.interop.javaser import JavaArray, JavaObject, JavaWriter, loads

_REF = "/root/reference/spark/dl/src/main/scala/com/intel/analytics/bigdl"
pytestmark = pytest.mark.skipif(
    not os.path.isdir(_REF),
    reason="reference checkout not present (installed-wheel lane)")

_PKG = "com.intel.analytics.bigdl."


# ---------------------------------------------------------------------------
# scala source model
# ---------------------------------------------------------------------------

def _source_file(classname: str):
    short = classname.rsplit(".", 1)[-1]
    special = {
        "Node": f"{_REF}/utils/DirectedGraph.scala",
        "DirectedGraph": f"{_REF}/utils/DirectedGraph.scala",
        "RnnCell": f"{_REF}/nn/RNN.scala",
        "DenseTensor": f"{_REF}/tensor/DenseTensor.scala",
        "ArrayStorage": f"{_REF}/tensor/ArrayStorage.scala",
        "Cell": f"{_REF}/nn/Cell.scala",
        "Container": f"{_REF}/nn/Container.scala",
        "AbstractModule": f"{_REF}/nn/abstractnn/AbstractModule.scala",
        "TensorModule": f"{_REF}/nn/abstractnn/AbstractModule.scala",
    }
    if short in special:
        return special[short]
    for sub in ("nn", "utils", "tensor"):
        p = f"{_REF}/{sub}/{short}.scala"
        if os.path.exists(p):
            return p
    return None


def _strip_comments(src: str) -> str:
    src = re.sub(r"/\*.*?\*/", "", src, flags=re.S)
    return re.sub(r"//[^\n]*", "", src)


def _split_depth0(s: str):
    out, depth, cur = [], 0, []
    for ch in s:
        if ch in "([{":
            depth += 1
        elif ch in ")]}":
            depth -= 1
        if ch == "," and depth == 0:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if cur:
        out.append("".join(cur))
    return out


def _class_region(src: str, short: str):
    """(header, body) of `class short...` up to the next top-level class."""
    m = re.search(rf"\bclass\s+{re.escape(short)}\b", src)
    if not m:
        return None, None
    rest = src[m.start():]
    nxt = re.search(
        r"\n(?:(?:abstract|sealed|final|private(?:\[\w+\])?|protected|"
        r"case)\s+)*(?:class|object|trait)\s+\w", rest[5:])
    region = rest[:nxt.start() + 5] if nxt else rest
    bm = re.search(r"\{", region)
    if bm is None:
        return region, ""
    return region[:bm.start()], region[bm.start():]


def _ctor_fields(header: str) -> dict:
    """name -> scala type for constructor params (val/var/plain — plain
    params used beyond the constructor become private fields of the same
    name, so they are legitimate wire fields)."""
    fields = {}
    for group in re.findall(r"\(((?:[^()]|\([^()]*\))*)\)", header):
        if group.lstrip().startswith("implicit"):
            continue
        for param in _split_depth0(group):
            pm = re.match(
                r"\s*(?:@\w+(?:\([^)]*\))?\s*)*"
                r"(?:(?:private|protected)(?:\[\w+\])?\s+)?"
                r"(?:(val|var)\s+)?"
                r"(\w+)\s*:\s*([^=]+?)(?:=.*)?$", param.strip(), re.S)
            if pm:
                fields[pm.group(2)] = pm.group(3).strip()
    return fields


def _body_fields(body: str) -> dict:
    """name -> scala type (or '') for non-@transient val/var CLASS members
    (brace depth 1 — local vals inside method bodies are not fields)."""
    fields = {}
    depth = 0
    transient_next = False
    for raw in _strip_comments(body).splitlines():
        line_depth = depth
        depth += raw.count("{") + raw.count("(") \
            - raw.count("}") - raw.count(")")
        if re.search(r"@transient", raw):
            transient_next = True
            if not re.search(r"\b(val|var)\s+\w+", raw):
                continue
        m = re.match(
            r"\s*(?:@\w+(?:\([^)]*\))?\s*)*"
            r"(?:(?:private|protected)(?:\[\w+\])?\s+)?"
            r"(?:override\s+)?(?:lazy\s+)?(val|var)\s+(\w+)"
            r"\s*(?::\s*([^=\n]+?))?\s*(?:=|$)", raw)
        if m and line_depth == 1:
            if not transient_next:
                fields[m.group(2)] = (m.group(3) or "").strip()
            transient_next = False
        elif raw.strip() and not raw.strip().startswith("@"):
            transient_next = False
    return fields


def _super_name(header: str):
    m = re.search(r"extends\s+(\w+)", header or "")
    return m.group(1) if m else None


def scala_fields(classname: str) -> dict:
    """Declared non-transient fields of the class + its bigdl superclasses."""
    fields = {}
    short = classname.rsplit(".", 1)[-1]
    seen = set()
    while short and short not in seen:
        seen.add(short)
        path = _source_file(short)
        if path is None:
            break
        src = _strip_comments(open(path).read())
        header, body = _class_region(src, short)
        if header is None:
            break
        fields.update(_ctor_fields(header))
        fields.update(_body_fields(body or ""))
        short = _super_name(header)
    return fields


def scala_own_fields(short: str) -> dict:
    """Declared non-transient fields of ONE class level (no super walk) —
    the set its own classdesc must cover on the wire."""
    path = _source_file(short)
    if path is None:
        return {}
    src = _strip_comments(open(path).read())
    header, body = _class_region(src, short)
    if header is None:
        return {}
    fields = dict(_ctor_fields(header))
    fields.update(_body_fields(body or ""))
    return fields


def scala_suid(classname: str):
    """The class's @SerialVersionUID, or None if the SOURCE carries none.
    Looks in a window above the class declaration (robust to modifiers,
    extra annotations, or comments between annotation and `class`) so an
    unmatched annotation cannot be confused with an absent one."""
    short = classname.rsplit(".", 1)[-1]
    path = _source_file(short)
    if path is None:
        return None
    src = _strip_comments(open(path).read())
    cm = re.search(rf"(?:^|\n)[^\n]*?\bclass\s+{re.escape(short)}\b", src)
    if cm is None:
        return None
    window = src[max(0, cm.start() - 300):cm.start() + 1]
    anns = re.findall(r"@SerialVersionUID\(\s*(-?)\s*(\d+)L?\s*\)", window)
    if not anns:
        return None
    sign, digits = anns[-1]
    return -int(digits) if sign else int(digits)


# ---------------------------------------------------------------------------
# audit engine
# ---------------------------------------------------------------------------

_PRIM_SCALA = {"I": {"Int"}, "D": {"Double"}, "Z": {"Boolean"},
               "F": {"Float"}, "J": {"Long"}, "S": {"Short"},
               "B": {"Byte"}, "C": {"Char"}}
_ARR_SCALA = {"[I": "Array[Int]", "[F": "Array[Float]",
              "[D": "Array[Double]"}


def audit_classdesc(cd) -> list:
    """Errors for one emitted classdesc vs the Scala source (empty = ok)."""
    errors = []
    declared = scala_fields(cd.name)
    if not declared:
        return [f"{cd.name}: no Scala source found to audit against"]
    for t, fname, sig in cd.fields:
        if fname not in declared:
            errors.append(f"{cd.name}.{fname}: not a declared field "
                          f"(have: {sorted(declared)[:12]}...)")
            continue
        styp = declared[fname].split("(")[0].strip()
        if not styp:
            continue  # body val with inferred type: name check only
        base = styp.split("[")[0]
        if t in _PRIM_SCALA:
            if base and base not in _PRIM_SCALA[t] and base != "T":
                errors.append(
                    f"{cd.name}.{fname}: emitted primitive '{t}' but "
                    f"declared type is {styp}")
        elif t == "[":
            st = styp.replace(" ", "")
            want = _ARR_SCALA.get(sig)
            if not (st.startswith("Array") or base == "T"):
                errors.append(
                    f"{cd.name}.{fname}: emitted array {sig} but declared "
                    f"type is {styp}")
            elif want and st not in (want, "Array[T]"):
                errors.append(
                    f"{cd.name}.{fname}: emitted array {sig} but declared "
                    f"element type is {styp}")
        else:  # 'L': any reference type — reject known primitives
            if base in ("Int", "Double", "Boolean", "Float", "Long"):
                errors.append(
                    f"{cd.name}.{fname}: emitted object ref but declared "
                    f"type is primitive {styp}")
    src_suid = scala_suid(cd.name)
    if src_suid is not None and cd.suid != src_suid:
        errors.append(f"{cd.name}: emitted SUID {cd.suid} != source "
                      f"@SerialVersionUID {src_suid}")
    if src_suid is None and cd.suid != 1:
        errors.append(f"{cd.name}: source has no @SerialVersionUID but "
                      f"emitted {cd.suid} (documented fallback is 1)")
    return errors


def _collect_classdescs(models) -> dict:
    """name -> classdesc for every bigdl class in the models' streams."""
    descs = {}
    for m in models:
        m.build(jax.random.PRNGKey(0))
        from bigdl_tpu.interop.bigdl import _DescCache, _w_module

        def host(tree):
            if isinstance(tree, dict):
                return {k: host(v) for k, v in tree.items()}
            if isinstance(tree, list):
                return [host(v) for v in tree]
            return np.asarray(tree)

        from bigdl_tpu.interop.bigdl import _fill_base_fields

        dc = _DescCache()
        root = _w_module(dc, m, host(m.params), host(m.state))
        _fill_base_fields(root)
        w = JavaWriter()
        w.write_object(root)
        [back] = loads(w.getvalue())

        def walk(o, seen):
            if id(o) in seen:
                return
            seen.add(id(o))
            if isinstance(o, JavaObject):
                cd = o.classdesc
                while cd is not None:
                    descs.setdefault(cd.name, cd)
                    cd = cd.super_desc
                for v in o.fields.values():
                    walk(v, seen)
                for anns in o.annotations.values():
                    for a in anns:
                        walk(a, seen)
            elif isinstance(o, JavaArray) and o.values is not None \
                    and getattr(o.values, "dtype", None) is None:
                for v in o.values:
                    walk(v, seen)

        walk(back, set())
    return descs


def _kitchen_sink_models():
    cnn = nn.Sequential()
    cnn.add(nn.SpatialZeroPadding(1, 1, 1, 1))
    cnn.add(nn.SpatialConvolution(3, 8, 3, 3))
    cnn.add(nn.SpatialShareConvolution(8, 8, 1, 1))
    cnn.add(nn.SpatialBatchNormalization(8))
    cnn.add(nn.ReLU())
    cnn.add(nn.SpatialCrossMapLRN(5))
    cnn.add(nn.SpatialMaxPooling(2, 2, 2, 2))
    branch = nn.Concat(-1)
    b1 = nn.Sequential()
    b1.add(nn.SpatialConvolution(8, 4, 1, 1))
    b1.add(nn.Threshold(0.1, 0.0))
    b2 = nn.Sequential()
    b2.add(nn.SpatialConvolution(8, 4, 1, 1))
    b2.add(nn.Power(2.0))
    branch.add(b1)
    branch.add(b2)
    cnn.add(branch)
    ct = nn.ConcatTable()
    ct.add(nn.Identity())
    ct.add(nn.Identity())
    cnn.add(ct)
    cnn.add(nn.CAddTable())
    cnn.add(nn.SpatialAveragePooling(2, 2, 2, 2))
    cnn.add(nn.Reshape([4 * 1 * 1]))
    cnn.add(nn.View(4))
    cnn.add(nn.Dropout(0.5))
    cnn.add(nn.Linear(4, 4))
    cnn.add(nn.Tanh())
    cnn.add(nn.Sigmoid())
    cnn.add(nn.LogSoftMax())

    joined = nn.Sequential()
    jt = nn.ConcatTable()
    jt.add(nn.Identity())
    jt.add(nn.Identity())
    joined.add(jt)
    joined.add(nn.MapTable(nn.Squeeze(1)))
    joined.add(nn.JoinTable(-1, 0))
    joined.add(nn.BatchNormalization(8))

    rnn = nn.Sequential()
    rnn.add(nn.Recurrent(nn.RnnCell(4, 6)))
    rnn.add(nn.TimeDistributed(nn.Linear(6, 3)))

    lstm = nn.Sequential()
    lstm.add(nn.Recurrent(nn.LSTM(4, 6)))

    gru = nn.Sequential()
    gru.add(nn.Recurrent(nn.GRU(4, 6)))

    peep = nn.Sequential()
    peep.add(nn.Recurrent(nn.LSTMPeephole(4, 6)))

    text = nn.Sequential()
    text.add(nn.LookupTable(10, 8, one_based=True))
    text.add(nn.TemporalConvolution(8, 6, 3))

    tree = nn.Sequential()
    tree.add(nn.BinaryTreeLSTM(4, 5))

    inp = nn.Input()
    h = nn.Linear(5, 5)(inp)
    a = nn.ReLU()(h)
    b = nn.Tanh()(h)
    out = nn.CAddTable()([a, b])
    graph = nn.Graph(inp, out)

    return [cnn, joined, rnn, lstm, gru, peep, text, tree, graph]


# ---------------------------------------------------------------------------
# the audit
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def kitchen_descs():
    return _collect_classdescs(_kitchen_sink_models())


def test_every_emitted_classdesc_matches_scala_source(kitchen_descs):
    descs = kitchen_descs
    errors = []
    audited = 0
    for name, cd in sorted(descs.items()):
        if not name.startswith(_PKG):
            continue  # scala stdlib (ArrayBuffer) / array descs
        audited += 1
        errors += audit_classdesc(cd)
    assert audited >= 30, f"only {audited} bigdl classdescs audited"
    assert not errors, "wire-format drift vs Scala source:\n" + \
        "\n".join(errors)


# Declared non-transient fields the writer deliberately leaves off the
# wire: a real JVM deserializes them as JOS zero-defaults (null/0.0/false),
# which these specific fields tolerate (null-checked config holders and
# init-time-only hints, not updateOutput inputs).  Key: (short class name
# or "*", field name).  Anything NOT listed here that the source declares
# non-transient and the writer omits is exactly the MulConstant.scalar /
# Dropout.p bug class — fail loudly so it gets emitted or triaged.
_ALLOWED_OMISSIONS = {
    # regularizer config: null-checked everywhere it is read
    ("*", "wRegularizer"), ("*", "bRegularizer"), ("*", "uRegularizer"),
    # init-time-only hints consumed by reset(); the serialized weight
    # tensors already carry their outcome
    ("*", "initWeight"), ("*", "initBias"), ("*", "initGradWeight"),
    ("*", "initGradBias"), ("*", "initMethod"),
    # gradient buffers: populated lazily on the first backward
    ("*", "gradWeight"), ("*", "gradBias"),
}


def test_writer_emits_every_declared_nontransient_field(kitchen_descs):
    """Inverse of the subset audit above: every field the Scala source
    declares non-transient at a class level must appear on that level's
    emitted classdesc.  JOS gives a missing field its zero-default on
    read, so an omission is invisible to roundtrip tests but breaks a real
    BigDL at forward time (the MulConstant `scalar` / Dropout `p` class of
    bug — derived non-transient vals the reference's updateOutput reads)."""
    errors = []
    checked = 0
    for name, cd in sorted(kitchen_descs.items()):
        if not name.startswith(_PKG):
            continue
        short = name.rsplit(".", 1)[-1]
        own = scala_own_fields(short)
        if not own:
            continue  # no source found: the subset audit already flags it
        checked += 1
        emitted = {fname for _t, fname, _sig in cd.fields}
        for fname in sorted(own):
            if fname in emitted or (short, fname) in _ALLOWED_OMISSIONS \
                    or ("*", fname) in _ALLOWED_OMISSIONS:
                continue
            errors.append(
                f"{name}.{fname}: declared non-transient but never emitted "
                "— a JVM deserializes the JOS zero-default; emit it or add "
                "a justified _ALLOWED_OMISSIONS entry")
    assert checked >= 20, f"only {checked} classes had auditable source"
    assert not errors, "writer omits declared non-transient fields:\n" + \
        "\n".join(errors)


def test_audit_covers_every_suid_entry(kitchen_descs):
    """100%-coverage contract: each com.intel entry in _SUID appears in the
    kitchen-sink streams, so none escapes the field/SUID audit."""
    descs = kitchen_descs
    missing = [name for name in bigdl_fmt._SUID
               if name.startswith(_PKG) and name not in descs]
    assert not missing, f"_SUID entries never exercised: {missing}"


def test_audit_detects_a_wrong_field_and_wrong_suid():
    """The audit must actually FAIL on drift (meta-test)."""
    from bigdl_tpu.interop.javaser import JavaClassDesc

    bogus = JavaClassDesc(_PKG + "nn.Linear", 359656776803598943, 2,
                          [("I", "notAField", None)], None)
    errs = audit_classdesc(bogus)
    assert any("notAField" in e for e in errs)

    wrong_suid = JavaClassDesc(_PKG + "nn.Linear", 42, 2,
                               [("I", "inputSize", None)], None)
    errs = audit_classdesc(wrong_suid)
    assert any("SUID" in e for e in errs)

    wrong_type = JavaClassDesc(_PKG + "nn.Linear", 359656776803598943, 2,
                               [("D", "inputSize", None)], None)
    errs = audit_classdesc(wrong_type)
    assert any("primitive" in e for e in errs)


def scala_parent_chain(short: str):
    """The class's superCLASS chain from the source (traits — Tensor,
    Storage, Serializable — end the chain, matching what JOS serializes)."""
    chain, seen = [], set()
    cur = short
    while True:
        path = _source_file(cur)
        if path is None:
            break
        src = _strip_comments(open(path).read())
        header, _ = _class_region(src, cur)
        if header is None:
            break
        parent = _super_name(header)
        if parent is None or parent in seen:
            break
        ppath = _source_file(parent)
        if ppath is None:
            break  # scala stdlib / java base
        ph, _ = _class_region(_strip_comments(open(ppath).read()), parent)
        if ph is None:
            break  # a trait, not a class
        chain.append(parent)
        seen.add(parent)
        cur = parent
    return chain


def test_super_chains_match_scala(kitchen_descs):
    """The emitted classdesc hierarchy must equal the reference's actual
    superclass chain (ReLU -> Threshold -> TensorModule -> AbstractModule,
    containers -> Container, cells -> Cell, ...) — a real
    ObjectInputStream validates exactly this."""
    errors = []
    checked = 0
    for name, cd in sorted(kitchen_descs.items()):
        if not name.startswith(_PKG):
            continue
        checked += 1
        emitted = []
        c = cd.super_desc
        while c is not None:
            emitted.append(c.name.rsplit(".", 1)[-1])
            c = c.super_desc
        expected = scala_parent_chain(name.rsplit(".", 1)[-1])
        if emitted != expected:
            errors.append(f"{name}: emitted super chain {emitted} != "
                          f"source {expected}")
    assert checked >= 30
    assert not errors, "super-chain drift vs Scala source:\n" + \
        "\n".join(errors)
