"""Remote-storage scheme dispatch (utils/file_io.py).

Reference: utils/File.scala:106-186 routes save/load through the Hadoop
filesystem selected by the path's scheme (HDFS/S3).  The TPU rebuild
dispatches by URL scheme to fsspec; these tests drive the full
checkpoint/resume and Module.save/load cycle against fsspec's in-memory
store (`memory://`) — a mocked remote in the verdict's sense: the bytes
never touch the local filesystem.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
from bigdl_tpu.models.lenet import LeNet5
from bigdl_tpu.optim import Optimizer, SGD, Trigger
from bigdl_tpu.utils import file_io


@pytest.fixture(autouse=True)
def _clean_memory_store():
    import fsspec
    fs = fsspec.filesystem("memory")
    yield
    try:
        fs.rm("/", recursive=True)
    except Exception:
        pass


def test_save_load_roundtrip_memory_scheme():
    blob = {"w": jnp.arange(6.0).reshape(2, 3), "name": "x", "n": 3}
    file_io.save(blob, "memory://ckpt/blob.bin")
    back = file_io.load("memory://ckpt/blob.bin")
    np.testing.assert_allclose(back["w"], np.arange(6.0).reshape(2, 3))
    assert back["name"] == "x" and back["n"] == 3
    # overwrite=False honored remotely too
    with pytest.raises(FileExistsError):
        file_io.save(blob, "memory://ckpt/blob.bin", overwrite=False)


def test_module_save_load_via_remote_scheme():
    m = LeNet5(10).build(jax.random.key(0))
    m.save("memory://models/lenet.bigdl")
    m2 = nn.Module.load("memory://models/lenet.bigdl")
    x = jnp.zeros((2, 28, 28, 1))
    np.testing.assert_allclose(np.asarray(m.forward(x)),
                               np.asarray(m2.forward(x)), rtol=1e-6)


def test_checkpoint_and_latest_on_remote_scheme():
    mp, op = file_io.save_checkpoint(
        "memory://run1", 5, {"params": {"w": jnp.ones(3)}}, {"t": 5})
    assert mp.startswith("memory://run1/")
    file_io.save_checkpoint(
        "memory://run1", 9, {"params": {"w": jnp.zeros(3)}}, {"t": 9})
    latest = file_io.latest_checkpoint("memory://run1")
    assert latest is not None
    mpath, opath, n = latest
    assert n == 9
    blob = file_io.load(mpath)
    np.testing.assert_allclose(blob["params"]["w"], 0.0)


def test_training_checkpoints_to_remote_scheme():
    """set_checkpoint with a remote URL: the full driver loop writes there."""
    r = np.random.default_rng(0)
    xs = r.normal(size=(64, 28, 28, 1)).astype(np.float32)
    ys = r.integers(0, 10, size=64)
    samples = [Sample(x, np.int32(y)) for x, y in zip(xs, ys)]
    ds = DataSet.array(samples).transform(
        SampleToMiniBatch(32, drop_last=True))
    opt = (Optimizer(LeNet5(10), ds, nn.ClassNLLCriterion())
           .set_optim_method(SGD(learning_rate=0.01))
           .set_end_when(Trigger.max_epoch(1))
           .set_checkpoint("memory://train_ckpt", Trigger.every_epoch()))
    opt.optimize()
    latest = file_io.latest_checkpoint("memory://train_ckpt")
    assert latest is not None, "driver loop never wrote the remote checkpoint"
    blob = file_io.load(latest[0])
    assert "params" in blob


def test_local_paths_still_work(tmp_path):
    p = tmp_path / "x.bin"
    file_io.save({"a": jnp.ones(2)}, str(p))
    assert p.exists()
    np.testing.assert_allclose(file_io.load(str(p))["a"], 1.0)
    # file:// scheme maps to the local filesystem
    file_io.save({"b": 1}, f"file://{tmp_path}/y.bin")
    assert (tmp_path / "y.bin").exists()
    assert file_io.load(f"file://{tmp_path}/y.bin")["b"] == 1
