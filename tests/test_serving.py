"""UDF serving (bigdl_tpu/serving.py).

Reference: example/udfpredictor/ — a trained text classifier registered as
a SQL UDF filtering DataFrame rows by predicted class.  Here the query
engine is pandas; the UDF must batch + mesh-shard internally and compose
with boolean filters.
"""

import numpy as np
import jax
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.serving import TextClassifierUDF, UDFPredictor


def test_udf_predictor_on_arrays_and_series():
    pd = pytest.importorskip("pandas")
    model = nn.Sequential().add(nn.Linear(4, 3)).build(jax.random.key(0))
    udf = UDFPredictor(model)
    X = np.random.default_rng(0).normal(size=(10, 4)).astype(np.float32)
    preds = udf(X)
    assert preds.shape == (10,) and preds.dtype.kind == "i"
    # pandas integration: filter rows by predicted class
    df = pd.DataFrame({"f": list(X)})
    feats = np.stack(df["f"].to_numpy())
    assert np.array_equal(udf(feats), preds)
    mask = udf(feats) == preds[0]
    assert mask[0]


def test_udf_register_namespace():
    model = nn.Sequential().add(nn.Linear(2, 2)).build(jax.random.key(1))
    registry = {}
    udf = UDFPredictor(model).register(registry, "classify")
    assert registry["classify"] is udf


def test_text_classifier_udf_end_to_end():
    """Tokenize -> dictionary -> embed -> model -> class id, with a model
    trained so the prediction is meaningful (word 'good' vs 'bad')."""
    from bigdl_tpu.dataset.text import Dictionary

    vocab = [["good", "great", "nice"], ["bad", "awful", "poor"]]
    dic = Dictionary(vocab)
    embed_dim, seq_len = 8, 6
    r = np.random.default_rng(0)
    table = r.normal(size=(len(dic.index2word()) + 2, embed_dim)) \
        .astype(np.float32)

    # linear model over mean-pooled... keep it simple: flatten the sequence
    model = (nn.Sequential()
             .add(nn.InferReshape((0, -1)))  # (batch, seq*embed)
             .add(nn.Linear(seq_len * embed_dim, 2)))
    model.build(jax.random.key(2))

    udf = TextClassifierUDF(model, dic, table, seq_len=seq_len,
                            batch_size=4)
    texts = ["good great nice", "bad awful poor", "good", "bad bad bad"]
    preds = udf(texts)
    assert preds.shape == (4,)
    assert set(np.unique(preds)) <= {0, 1}
    # deterministic: same text -> same class
    assert udf(["good great nice"])[0] == preds[0]
    # same-word texts map to identical features, so identical predictions
    assert udf(["bad awful poor"])[0] == preds[1]
