"""UDF serving (bigdl_tpu/serving.py).

Reference: example/udfpredictor/ — a trained text classifier registered as
a SQL UDF filtering DataFrame rows by predicted class.  Here the query
engine is pandas; the UDF must batch + mesh-shard internally and compose
with boolean filters.
"""

import numpy as np
import jax
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.serving import TextClassifierUDF, UDFPredictor


def test_udf_predictor_on_arrays_and_series():
    pd = pytest.importorskip("pandas")
    model = nn.Sequential().add(nn.Linear(4, 3)).build(jax.random.key(0))
    udf = UDFPredictor(model)
    X = np.random.default_rng(0).normal(size=(10, 4)).astype(np.float32)
    preds = udf(X)
    assert preds.shape == (10,) and preds.dtype.kind == "i"
    # pandas integration: filter rows by predicted class
    df = pd.DataFrame({"f": list(X)})
    feats = np.stack(df["f"].to_numpy())
    assert np.array_equal(udf(feats), preds)
    mask = udf(feats) == preds[0]
    assert mask[0]


def test_udf_empty_input_respects_postprocess():
    """The empty fast path must carry the POSTPROCESS dtype/shape — a
    float- or vector-returning postprocess used to get a hardcoded
    int64 (0,) back."""
    model = nn.Sequential().add(nn.Linear(4, 3)).build(jax.random.key(0))
    # default postprocess (argmax): empty int class ids
    out = UDFPredictor(model)([])
    assert out.shape == (0,) and out.dtype.kind == "i"
    # float-returning postprocess: empty FLOAT result
    udf_f = UDFPredictor(model, postprocess=lambda o: o.mean(axis=-1))
    out = udf_f([])
    assert out.shape == (0,) and out.dtype.kind == "f"
    # non-empty path still postprocesses normally
    x = np.zeros((2, 4), np.float32)
    assert udf_f(x).shape == (2,) and udf_f(x).dtype.kind == "f"


def test_udf_empty_input_column_indexing_postprocess():
    """A postprocess that indexes a class column (out[:, 1]) must not
    blow up on empty input: before any real call the guessed probe shape
    falls back to a plain empty array; after a real call the probe
    carries the model's true trailing shape, so the postprocess runs."""
    model = nn.Sequential().add(nn.Linear(4, 3)).build(jax.random.key(0))
    udf = UDFPredictor(model, postprocess=lambda o: o[:, 1])
    # cold: the (0, 1) probe would raise IndexError inside postprocess —
    # the empty answer degrades to an empty array instead of raising
    out = udf([])
    assert out.shape == (0,)
    # warm: a real call records the (N, 3) output spec; the empty path
    # now probes with (0, 3) and the postprocess itself shapes the answer
    x = np.zeros((2, 4), np.float32)
    assert udf(x).shape == (2,)
    out = udf([])
    assert out.shape == (0,) and out.dtype.kind == "f"


def test_udf_batching_shared_with_serve():
    """UDFPredictor chunks through the serving subsystem's shared
    fixed-shape batching (serve.batcher.predict_in_fixed_batches): a
    non-multiple row count gives the same answer as whole-array
    prediction, with the trailing chunk padded not recompiled."""
    from bigdl_tpu.optim import Predictor

    model = nn.Sequential().add(nn.Linear(4, 3)).build(jax.random.key(0))
    x = np.random.default_rng(2).normal(size=(10, 4)).astype(np.float32)
    udf = UDFPredictor(model, batch_size=4)  # 10 = 4 + 4 + 2 (padded)
    np.testing.assert_array_equal(
        udf(x), np.argmax(Predictor(model).predict(x), axis=-1))


def test_udf_register_namespace():
    model = nn.Sequential().add(nn.Linear(2, 2)).build(jax.random.key(1))
    registry = {}
    udf = UDFPredictor(model).register(registry, "classify")
    assert registry["classify"] is udf


def test_text_classifier_udf_end_to_end():
    """Tokenize -> dictionary -> embed -> model -> class id, with a model
    trained so the prediction is meaningful (word 'good' vs 'bad')."""
    from bigdl_tpu.dataset.text import Dictionary

    vocab = [["good", "great", "nice"], ["bad", "awful", "poor"]]
    dic = Dictionary(vocab)
    embed_dim, seq_len = 8, 6
    r = np.random.default_rng(0)
    table = r.normal(size=(len(dic.index2word()) + 2, embed_dim)) \
        .astype(np.float32)

    # linear model over mean-pooled... keep it simple: flatten the sequence
    model = (nn.Sequential()
             .add(nn.InferReshape((0, -1)))  # (batch, seq*embed)
             .add(nn.Linear(seq_len * embed_dim, 2)))
    model.build(jax.random.key(2))

    udf = TextClassifierUDF(model, dic, table, seq_len=seq_len,
                            batch_size=4)
    texts = ["good great nice", "bad awful poor", "good", "bad bad bad"]
    preds = udf(texts)
    assert preds.shape == (4,)
    assert set(np.unique(preds)) <= {0, 1}
    # deterministic: same text -> same class
    assert udf(["good great nice"])[0] == preds[0]
    # same-word texts map to identical features, so identical predictions
    assert udf(["bad awful poor"])[0] == preds[1]


class TestCachedGenerate:
    """KV-cache decode (models/decode.py) vs the full-forward generate."""

    def _trained_lm(self, num_experts=0):
        import numpy as np
        from bigdl_tpu.common import set_seed
        from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
        from bigdl_tpu.models import TransformerLM
        from bigdl_tpu.optim import Adam, Optimizer, Trigger
        import bigdl_tpu.nn as nn

        set_seed(2)
        vocab, t = 12, 8
        seqs = [[(s + i) % vocab for i in range(t + 1)] for s in range(vocab)] * 8
        samples = [Sample(np.asarray(s[:-1], np.int32),
                          np.asarray(s[1:], np.int32)) for s in seqs]
        ds = DataSet.array(samples).transform(
            SampleToMiniBatch(24, drop_last=True))
        model = TransformerLM(vocab_size=vocab, max_len=t, d_model=32,
                              num_heads=4, num_layers=2,
                              num_experts=num_experts)
        crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                           size_average=True)
        (Optimizer(model, ds, crit).set_optim_method(Adam(3e-3))
         .set_end_when(Trigger.max_epoch(3)).optimize())
        return model, vocab, t

    def test_greedy_parity_with_full_forward(self):
        import numpy as np
        from bigdl_tpu.models.decode import cached_generate
        from bigdl_tpu.models.transformer_lm import greedy_generate

        model, vocab, t = self._trained_lm()
        prompt = [[3, 4], [7, 8]]
        full = greedy_generate(model, prompt, num_tokens=5, max_len=t)
        cached = cached_generate(model, prompt, num_tokens=5, max_len=t)
        np.testing.assert_array_equal(np.asarray(full), np.asarray(cached))

    def test_greedy_parity_moe_lm(self):
        """The structural walker must decode the MoE variant too."""
        import numpy as np
        from bigdl_tpu.models.decode import cached_generate
        from bigdl_tpu.models.transformer_lm import greedy_generate

        model, vocab, t = self._trained_lm(num_experts=4)
        prompt = [[1, 2, 3]]
        full = greedy_generate(model, prompt, num_tokens=4, max_len=t)
        cached = cached_generate(model, prompt, num_tokens=4, max_len=t)
        np.testing.assert_array_equal(np.asarray(full), np.asarray(cached))

    def test_1d_prompt_returns_1d_like_greedy(self):
        import numpy as np
        import pytest
        from bigdl_tpu.models.decode import cached_generate
        from bigdl_tpu.models.transformer_lm import greedy_generate

        model, vocab, t = self._trained_lm()
        full = greedy_generate(model, [3, 4], num_tokens=3, max_len=t)
        cached = cached_generate(model, [3, 4], num_tokens=3, max_len=t)
        assert cached.ndim == 1 and full.ndim == 1
        np.testing.assert_array_equal(np.asarray(full), np.asarray(cached))
        # positions beyond the model's positional table fail loudly (the
        # full forward raises too; dynamic_slice would silently clamp)
        with pytest.raises(ValueError):
            cached_generate(model, [1], num_tokens=2, max_len=t + 4)

    def test_sampling_contract(self):
        import jax
        import numpy as np
        from bigdl_tpu.models.decode import cached_generate
        import pytest

        model, vocab, t = self._trained_lm()
        with pytest.raises(ValueError):
            cached_generate(model, [[1]], num_tokens=2, max_len=t,
                            temperature=0.5)  # rng required
        out = cached_generate(model, [[1]], num_tokens=3, max_len=t,
                              temperature=0.7, top_k=3,
                              rng=jax.random.key(0))
        assert out.shape == (1, 4)
        assert ((0 <= out) & (out < vocab)).all()


class TestBeamGenerate:
    """Beam search over the KV cache (models/decode.beam_generate)."""

    def test_beam1_equals_greedy(self):
        import numpy as np
        from bigdl_tpu.models import TransformerLM, beam_generate
        from bigdl_tpu.models.transformer_lm import greedy_generate
        from bigdl_tpu.common import set_seed

        set_seed(6)
        model = TransformerLM(vocab_size=20, max_len=12, d_model=32,
                              num_heads=4, num_layers=2).build()
        g = greedy_generate(model, [3, 4], num_tokens=6, max_len=12)
        b = beam_generate(model, [3, 4], num_tokens=6, max_len=12,
                          beam_size=1)
        np.testing.assert_array_equal(np.asarray(g), np.asarray(b))

    def test_beam_never_worse_than_greedy(self):
        """On a RANDOM (untrained) model, the beam-4 sequence's total
        log-prob under the model must be >= the greedy sequence's — the
        defining property of beam search."""
        import jax.numpy as jnp
        import numpy as np
        from bigdl_tpu.models import TransformerLM, beam_generate
        from bigdl_tpu.models.transformer_lm import greedy_generate
        from bigdl_tpu.common import set_seed

        set_seed(8)
        t, n = 12, 7
        model = TransformerLM(vocab_size=24, max_len=t, d_model=32,
                              num_heads=4, num_layers=2).build()

        def seq_logprob(seq):
            tok = jnp.asarray(np.asarray(seq)[None, :], jnp.int32)
            out, _ = model.apply(model.params, model.state, tok,
                                 training=False, rng=None)
            lp = np.asarray(out[0])  # [T, V] log-probs
            return sum(lp[i, seq[i + 1]] for i in range(len(seq) - 1))

        prompt = [5]
        g = list(greedy_generate(model, prompt, n, t))
        b = list(beam_generate(model, prompt, n, t, beam_size=4))
        assert seq_logprob(b) >= seq_logprob(g) - 1e-4, (g, b)

    def test_batched_prompts_shapes(self):
        from bigdl_tpu.models import TransformerLM, beam_generate
        from bigdl_tpu.common import set_seed

        set_seed(9)
        model = TransformerLM(vocab_size=16, max_len=10, d_model=32,
                              num_heads=4, num_layers=1).build()
        out = beam_generate(model, [[1, 2], [3, 4], [5, 6]], num_tokens=4,
                            max_len=10, beam_size=3)
        assert out.shape == (3, 6)
        assert (out[:, :2] == [[1, 2], [3, 4], [5, 6]]).all()


def test_beam_eos_freezes_finished_hypotheses():
    """With eos_token set, a hypothesis that emits EOS stops accumulating
    log-prob (pad-only continuation at score 0) and comes back padded."""
    import numpy as np
    import pytest
    from bigdl_tpu.models import TransformerLM, beam_generate
    from bigdl_tpu.models.transformer_lm import greedy_generate
    from bigdl_tpu.common import set_seed

    set_seed(11)
    vocab, t = 16, 12
    model = TransformerLM(vocab_size=vocab, max_len=t, d_model=32,
                          num_heads=4, num_layers=2).build()
    # DETERMINISTIC EOS emission: make the model's own greedy next token
    # the EOS — the top beam necessarily emits it at the first scored step
    eos = int(np.asarray(greedy_generate(model, [1, 2], 1, t))[-1])
    out = beam_generate(model, [[1, 2]], num_tokens=8, max_len=t,
                        beam_size=4, eos_token=eos, pad_token=0)
    row = np.asarray(out)[0]
    where = np.where(row == eos)[0]
    assert where.size > 0, row  # EOS must actually appear
    assert (row[int(where[0]) + 1:] == 0).all(), row
    # eos == pad is a config error
    with pytest.raises(ValueError):
        beam_generate(model, [[1]], 2, t, eos_token=0, pad_token=0)
