"""AOT executable cache (bigdl_tpu/utils/aot.py — ISSUE 6 tentpole).

Covers: fingerprint keying (shape / dtype / mesh / jax-version change =>
miss), executable round-trip through the CRC-framed store, corrupted-entry
quarantine => silent recompile, bit-identical loss sequence with the cache
on vs off on the 5-step LeNet run, serve warmup from a populated cache
performing zero fresh lowers, composition with the XLA persistent cache,
and the cross-process acceptance run (second process: warmup + 2-step
train with zero fresh compiles, proven by the aot counters in the emitted
trace)."""

import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import bigdl_tpu.nn as nn
from bigdl_tpu.common import set_seed
from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
from bigdl_tpu.optim import Adam, Optimizer, Trigger
from bigdl_tpu.utils import aot
from bigdl_tpu.utils.engine import Engine

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def aot_cache(tmp_path, monkeypatch):
    """A fresh cache dir armed via the env knob, counters zeroed, and the
    singleton dropped again afterwards (the tmp dir dies with the test)."""
    d = str(tmp_path / "aot")
    monkeypatch.setenv("BIGDL_TPU_AOT_CACHE", d)
    aot.reset()
    yield d
    aot.reset()


def _mnist_samples(n=160, seed=0):
    rng = np.random.default_rng(seed)
    xs = rng.normal(0.0, 0.1, size=(n, 28, 28, 1)).astype(np.float32)
    ys = rng.integers(0, 10, size=n)
    return [Sample(xs[i], np.int32(ys[i])) for i in range(n)]


class _LossCapture:
    def __init__(self):
        self.losses = []

    def add_scalar(self, name, value, step):
        if name == "Loss":
            self.losses.append(value)


def _train_lenet(samples, steps=5):
    from bigdl_tpu.models import LeNet5
    set_seed(7)
    model = LeNet5(10)
    ds = DataSet.array(samples).transform(
        SampleToMiniBatch(32, drop_last=True))
    cap = _LossCapture()
    opt = (Optimizer(model, ds, nn.ClassNLLCriterion())
           .set_optim_method(Adam(1e-3))
           .set_end_when(Trigger.max_iteration(steps))
           .set_log_interval(1)
           .set_train_summary(cap))
    opt.optimize()
    return cap.losses, [np.asarray(l) for l in jax.tree.leaves(model.params)]


# ----------------------------------------------------------------------
# fingerprint keying
# ----------------------------------------------------------------------

def test_fingerprint_sensitivity():
    """Every field the ISSUE names — avals (shape/dtype), mesh, jax
    version — flips the key; identical fields agree."""
    Engine.init()
    base = aot.base_fingerprint(Engine.mesh())
    f = dict(base)
    f["args"] = aot.aval_fingerprint(jnp.ones((8, 4)))
    k0 = aot.fingerprint(f)
    assert k0 == aot.fingerprint(dict(f))  # deterministic

    shp = dict(f, args=aot.aval_fingerprint(jnp.ones((16, 4))))
    dt = dict(f, args=aot.aval_fingerprint(jnp.ones((8, 4), jnp.bfloat16)))
    ver = dict(f, jax="99.99.0")
    mesh = dict(f, mesh={"shape": {"data": 4}, "axes": ["data"]})
    keys = {k0, aot.fingerprint(shp), aot.fingerprint(dt),
            aot.fingerprint(ver), aot.fingerprint(mesh)}
    assert len(keys) == 5  # all distinct


def test_module_fingerprint_structural():
    """Same architecture (fresh instances, different uids and weights) =>
    same fingerprint; different architecture or config => different.  No
    tracing happens — this is the zero-fresh-lowers key for serving."""
    from bigdl_tpu.models import LeNet5
    a = aot.module_fingerprint(LeNet5(10))
    b = aot.module_fingerprint(LeNet5(10))
    c = aot.module_fingerprint(LeNet5(12))  # class-count config change
    d = aot.module_fingerprint(nn.Sequential().add(nn.Linear(4, 2)))
    assert a == b
    assert len({a, c, d}) == 3


# ----------------------------------------------------------------------
# store / load / quarantine
# ----------------------------------------------------------------------

def test_roundtrip_hit_and_identical_result(aot_cache):
    Engine.init()

    def f(x):
        return jnp.tanh(x @ x.T) * 2 + 1

    x = jnp.ones((33, 7))
    lowered = jax.jit(f).lower(x)
    cold = aot.cached_compile(lowered, label="t.roundtrip",
                              example_args=(x,))
    want = np.asarray(cold(x))
    s = aot.stats()
    assert (s["misses"], s["stores"], s["hits"]) == (1, 1, 0)

    jax.clear_caches()
    warm = aot.cached_compile(jax.jit(f).lower(x), label="t.roundtrip",
                              example_args=(x,))
    s = aot.stats()
    assert s["hits"] == 1 and s["compiles"] == 1  # no second compile
    np.testing.assert_array_equal(np.asarray(warm(x)), want)


def test_corrupt_entry_quarantined_and_recompiled(aot_cache):
    """Bit rot in a cache entry must cost one recompile, never a crash:
    the CRC frame catches it, the entry is renamed *.corrupt, and the
    fresh compile re-stores a good entry."""
    Engine.init()

    def f(x):
        return x * 3 + 1

    x = jnp.ones((5, 5))
    aot.cached_compile(jax.jit(f).lower(x), label="t.corrupt",
                       example_args=(x,))
    cache = aot.get_cache()
    (key,) = cache.entries()
    path = cache._path(key)
    blob = open(path, "rb").read()
    with open(path, "wb") as fh:  # flip bytes mid-payload
        fh.write(blob[:100] + bytes([blob[100] ^ 0xFF]) + blob[101:])

    jax.clear_caches()
    warm = aot.cached_compile(jax.jit(f).lower(x), label="t.corrupt",
                              example_args=(x,))
    np.testing.assert_array_equal(np.asarray(warm(x)), np.asarray(f(x)))
    s = aot.stats()
    assert s["corrupt"] == 1 and s["hits"] == 0 and s["compiles"] == 2
    assert os.path.exists(path + ".corrupt")  # quarantined, not deleted
    assert key in cache.entries()  # re-stored after the recompile


def test_remote_scheme_cache_dir(monkeypatch):
    """The cache rides file_io, so a remote (fsspec) cache dir works —
    memory:// stands in for gs:// exactly as in the checkpoint tests."""
    monkeypatch.setenv("BIGDL_TPU_AOT_CACHE", "memory://aotcache")
    aot.reset()
    try:
        Engine.init()

        def f(x):
            return x * x + 1

        x = jnp.ones((6, 2))
        aot.cached_compile(jax.jit(f).lower(x), label="t.mem",
                           example_args=(x,))
        assert aot.stats()["stores"] == 1
        assert len(aot.get_cache().entries()) == 1
        jax.clear_caches()
        warm = aot.cached_compile(jax.jit(f).lower(x), label="t.mem",
                                  example_args=(x,))
        assert aot.stats()["hits"] == 1
        np.testing.assert_array_equal(np.asarray(warm(x)),
                                      np.full((6, 2), 2.0))
    finally:
        aot.reset()


def test_jax_version_change_is_miss(aot_cache, monkeypatch):
    Engine.init()

    def f(x):
        return x + 2

    x = jnp.ones((3,))
    aot.cached_compile(jax.jit(f).lower(x), label="t.ver",
                       example_args=(x,))
    jax.clear_caches()
    monkeypatch.setattr(jax, "__version__", "99.99.0")
    aot.cached_compile(jax.jit(f).lower(x), label="t.ver",
                       example_args=(x,))
    s = aot.stats()
    assert s["hits"] == 0 and s["misses"] == 2 and s["stores"] == 2


def test_disabled_is_default_and_inert(tmp_path):
    assert not aot.enabled()
    assert aot.get_cache() is None

    def f(x):
        return x - 1

    x = jnp.ones((4,))
    out = aot.cached_compile(jax.jit(f).lower(x), label="t.off",
                             example_args=(x,))(x)
    np.testing.assert_array_equal(np.asarray(out), np.zeros((4,)))
    assert not os.listdir(str(tmp_path))  # nothing written anywhere


# ----------------------------------------------------------------------
# train-step integration
# ----------------------------------------------------------------------

def test_train_bit_identical_cache_off_cold_warm(aot_cache, monkeypatch):
    """The 5-step LeNet loss sequence and final params are bit-identical
    across cache OFF, cache COLD (compile + store) and cache WARM
    (deserialized executable) — the cached program is the same XLA
    binary, so the arithmetic cannot drift."""
    Engine.init()
    samples = _mnist_samples()

    monkeypatch.setenv("BIGDL_TPU_AOT_CACHE", "")
    losses_off, params_off = _train_lenet(samples)

    monkeypatch.setenv("BIGDL_TPU_AOT_CACHE", aot_cache)
    aot.reset()
    losses_cold, params_cold = _train_lenet(samples)
    s = aot.stats()
    assert s["stores"] >= 1 and s["hits"] == 0

    jax.clear_caches()
    losses_warm, params_warm = _train_lenet(samples)
    s = aot.stats()
    assert s["hits"] >= 1
    assert s["compiles"] == s["stores"]  # the warm run compiled nothing new

    assert losses_off == losses_cold == losses_warm  # exact, not allclose
    for o, c, w in zip(params_off, params_cold, params_warm):
        np.testing.assert_array_equal(o, c)
        np.testing.assert_array_equal(o, w)


def test_composes_with_xla_persistent_cache(aot_cache, tmp_path):
    """Satellite: the AOT layer composes with, not fights, the XLA
    persistent cache — with both armed, a cold run stores an AOT entry
    (its compile having gone THROUGH the XLA cache, which fills too) and
    a warm run hits the AOT layer without consulting XLA at all."""
    from bigdl_tpu.utils.platform import enable_compilation_cache
    Engine.init()
    xla_dir = str(tmp_path / "xla")
    prior = jax.config.jax_compilation_cache_dir
    try:
        assert enable_compilation_cache(xla_dir) == xla_dir

        def f(x):
            return jnp.sin(x) @ jnp.cos(x).T

        x = jnp.ones((17, 9))
        aot.cached_compile(jax.jit(f).lower(x), label="t.compose",
                           example_args=(x,))
        assert aot.stats()["stores"] == 1
        assert os.listdir(xla_dir), "XLA persistent cache did not fill"
        jax.clear_caches()
        aot.cached_compile(jax.jit(f).lower(x), label="t.compose",
                           example_args=(x,))
        assert aot.stats()["hits"] == 1
    finally:
        # fully un-latch: restore the config AND drop the initialized
        # cache object, or the rest of the suite keeps writing into this
        # test's tmp dir
        jax.config.update("jax_compilation_cache_dir", prior)
        from jax._src import compilation_cache as _cc
        _cc.reset_cache()


# ----------------------------------------------------------------------
# serve warmup
# ----------------------------------------------------------------------

def test_serve_warmup_from_cache_zero_fresh_lowers(aot_cache):
    """A populated cache turns the serve bucket ladder into cache reads:
    the second warmup performs ZERO fresh lowers (the forward key is the
    structural module fingerprint + avals — no tracing), zero misses,
    zero compiles; and the warm server answers correctly."""
    from bigdl_tpu.models import LeNet5
    from bigdl_tpu.serve import InferenceServer
    Engine.init()
    set_seed(5)
    ex = np.zeros((28, 28, 1), np.float32)

    s1 = InferenceServer(LeNet5(10).build(), max_batch=16, example=ex)
    s1.warmup()
    first = aot.stats()
    assert first["stores"] >= 1 and first["lowers"] >= 1

    jax.clear_caches()
    set_seed(5)
    model2 = LeNet5(10).build()  # fresh instance, same arch+weights
    s2 = InferenceServer(model2, max_batch=16, example=ex)
    s2.warmup()
    after = aot.stats()
    assert after["lowers"] == first["lowers"], "warm warmup lowered"
    assert after["misses"] == first["misses"], "warm warmup missed"
    assert after["compiles"] == first["compiles"], "warm warmup compiled"
    assert after["hits"] > first["hits"]

    with s2:
        x = np.random.default_rng(3).normal(
            size=(28, 28, 1)).astype(np.float32)
        out = s2.predict(x)
    assert out.shape == (10,)
    assert np.isfinite(out).all()


def test_server_stats_carry_aot_ledger(aot_cache):
    from bigdl_tpu.models import LeNet5
    from bigdl_tpu.serve import InferenceServer
    Engine.init()
    ex = np.zeros((28, 28, 1), np.float32)
    srv = InferenceServer(LeNet5(10).build(), max_batch=8, example=ex)
    srv.warmup()
    ledger = srv.stats()["aot"]
    assert ledger["stores"] >= 1
    assert set(ledger) == {"hits", "misses", "stores", "lowers",
                           "compiles", "corrupt"}


# ----------------------------------------------------------------------
# the cross-process acceptance run
# ----------------------------------------------------------------------

_ACCEPTANCE = textwrap.dedent("""
    import json, os, sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, {repo!r})
    from bigdl_tpu.utils.platform import force_cpu
    force_cpu(8)
    os.environ["BIGDL_TPU_AOT_CACHE"] = {cache!r}
    os.environ["BIGDL_TPU_XLA_CACHE"] = "0"
    os.environ["BIGDL_TPU_TRACE"] = {trace!r}
    import numpy as np
    import bigdl_tpu.nn as nn
    from bigdl_tpu.common import set_seed
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.models import LeNet5
    from bigdl_tpu.optim import Adam, Optimizer, Trigger
    from bigdl_tpu.serve import InferenceServer
    from bigdl_tpu.utils import aot, telemetry
    from bigdl_tpu.utils.engine import Engine

    Engine.init()
    set_seed(1)
    tracer = telemetry.maybe_start()
    # serve bucket ladder warmup
    ex = np.zeros((28, 28, 1), np.float32)
    srv = InferenceServer(LeNet5(10).build(), max_batch=16, example=ex)
    srv.warmup()
    # 2-step train run
    rng = np.random.default_rng(0)
    samples = [Sample(rng.normal(size=(28, 28, 1)).astype(np.float32),
                      np.int32(i % 10)) for i in range(64)]
    ds = DataSet.array(samples).transform(SampleToMiniBatch(32,
                                                            drop_last=True))
    opt = (Optimizer(LeNet5(10), ds, nn.ClassNLLCriterion())
           .set_optim_method(Adam(1e-3))
           .set_end_when(Trigger.max_iteration(2)))
    opt.optimize()
    tracer.close()
    print(json.dumps(aot.stats()))
""")


def test_second_process_warm_starts_with_zero_compiles(tmp_path):
    """ISSUE 6 acceptance: a second process pointed at a populated
    BIGDL_TPU_AOT_CACHE executes InferenceServer.warmup() AND a 2-step
    train run with zero fresh XLA compiles — verified both by the
    process's own counters and by the aot hit/miss counter track in the
    trace it emitted."""
    cache = str(tmp_path / "aot")

    def run(tag):
        trace = str(tmp_path / f"trace_{tag}")
        code = _ACCEPTANCE.format(repo=_REPO_ROOT, cache=cache, trace=trace)
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True, timeout=600,
                           env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert r.returncode == 0, r.stderr[-2000:]
        return (json.loads(r.stdout.strip().splitlines()[-1]), trace)

    cold, _ = run("cold")
    assert cold["stores"] >= 2  # train step + at least one forward bucket
    assert cold["compiles"] >= 2

    warm, trace = run("warm")
    assert warm["compiles"] == 0, warm
    assert warm["misses"] == 0, warm
    assert warm["lowers"] == 1, warm  # ONLY the train step's hlo-key lower
    assert warm["hits"] >= cold["stores"] - 1

    # the emitted trace carries the proof too: the aot counter track's
    # final sample shows hits>0, misses==0
    events = json.load(open(os.path.join(
        trace, "trace.0.json")))["traceEvents"]
    samples = [e["args"] for e in events
               if e.get("ph") == "C" and e.get("name") == "aot"]
    assert samples, "no aot counter samples in the emitted trace"
    assert samples[-1]["misses"] == 0
    assert samples[-1]["hits"] >= 1
    assert not any(e.get("name") == "compile" for e in events
                   if e.get("ph") == "X"), "warm process compiled"


# ----------------------------------------------------------------------
# per-step MFU counter
# ----------------------------------------------------------------------

def test_mfu_counter_in_trace_and_report(tmp_path, monkeypatch):
    """ISSUE 6 acceptance: per-step `mfu` appears in the Optimizer's
    `train` counter track and in tools/trace_report.py output for a
    traced LeNet run."""
    from bigdl_tpu.utils import telemetry
    trace_dir = tmp_path / "trace"
    monkeypatch.setenv("BIGDL_TPU_TRACE", str(trace_dir))
    Engine.init()
    _train_lenet(_mnist_samples(), steps=4)

    merged = telemetry.merge_traces(str(trace_dir))
    counters = [e for e in merged["traceEvents"]
                if e.get("ph") == "C" and e.get("name") == "train"]
    with_mfu = [e for e in counters if "mfu" in e["args"]]
    assert with_mfu, "no mfu samples on the train counter track"
    assert all(e["args"]["mfu"] > 0 for e in with_mfu)
    assert all(e["args"]["model_flops_per_step"] > 0 for e in with_mfu)

    bd = telemetry.phase_breakdown(merged)
    assert "train.mfu" in bd["counters"]
    assert bd["counters"]["train.mfu"]["mean"] > 0

    r = subprocess.run(
        [sys.executable, os.path.join(_REPO_ROOT, "tools",
                                      "trace_report.py"), str(trace_dir)],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "PYTHONPATH": _REPO_ROOT})
    assert r.returncode == 0, r.stderr
    assert "train.mfu" in r.stdout


def test_mfu_not_armed_without_tracing(monkeypatch):
    """The flops trace is lazy: an untraced run must not pay for it."""
    monkeypatch.delenv("BIGDL_TPU_TRACE", raising=False)
    Engine.init()
    from bigdl_tpu.models import LeNet5
    set_seed(7)
    ds = DataSet.array(_mnist_samples(64)).transform(
        SampleToMiniBatch(32, drop_last=True))
    opt = (Optimizer(LeNet5(10), ds, nn.ClassNLLCriterion())
           .set_optim_method(Adam(1e-3))
           .set_end_when(Trigger.max_iteration(1)))
    opt.optimize()
    assert opt._mfu_denom is None  # never armed, never computed
