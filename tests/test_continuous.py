"""Continuous train->serve deployment (bigdl_tpu/serve/continuous.py).

The contract under test (docs/continuous.md):
  - ``file_io.watch_lineage`` yields new lineage entries in id order on
    any scheme, never yields ``.corrupt``/``.tmp`` names, and paces
    itself with the injectable clock/sleep (wall-clock-free here);
  - ``file_io.frame_fingerprint`` reads the integrity footer without the
    payload and pins a snapshot's identity into its release entry;
  - the publisher emits monotonic CRC-framed release entries (ids never
    reused, resumed from the directory, quarantined ids skipped) and the
    ``deploy.publish`` chaos point corrupts exactly the framed bytes;
  - the controller deploys only verified releases IN ORDER: corrupt or
    truncated entries, missing/rewritten snapshots (fingerprint
    mismatch) are quarantined + rejected typed, the next good release
    still deploys;
  - canary verdicts drive the state machine: promote resets the
    consecutive-rollback counter, rollbacks past the budget FREEZE the
    controller (healthy() False) instead of flapping;
  - the Optimizer's checkpoint path publishes releases (writer rank,
    every publish_every-th write), and an InferenceServer +
    DeployController serve the latest promoted release bit-for-bit;
  - the timeline rides stats()["deploy"], /v1/stats and /v1/versions,
    and the ``deploy`` counter track is a first-class trace_report
    section;
  - THE acceptance drill (tools/continuous_smoke.py): trainer and
    server as separate processes sharing only a lineage dir, all three
    chaos legs in one run, zero dropped requests.
"""

import json
import os
import pickle
import subprocess
import sys
import time

import numpy as np
import jax
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu import Engine
from bigdl_tpu.optim import Predictor
from bigdl_tpu.serve import (DeployController, InferenceServer,
                             ReleasePublisher, ReleaseRejected,
                             read_release)
from bigdl_tpu.serve.continuous import RELEASE_PATTERN
from bigdl_tpu.utils import chaos, file_io, telemetry

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _wait(pred, timeout=15.0):
    deadline = time.monotonic() + timeout
    while not pred() and time.monotonic() < deadline:
        time.sleep(0.02)
    assert pred(), "condition not reached in time"


def _snapshot(path, seed=0, din=6, dout=2):
    """A servable model snapshot blob on storage + the module that made
    it (the shape serve.swap loads: {"params", "state"})."""
    m = nn.Sequential().add(nn.Linear(din, dout)).build(
        jax.random.key(seed))
    file_io.save({"params": m.params, "state": m.state}, str(path))
    return m


class _StubServer:
    """Duck-typed swap/stats target for controller state-machine tests:
    records every swap, answers the canary summary the test scripts."""

    def __init__(self, default="promoted", decisions=None):
        self.swaps = []
        self.default = default
        self.decisions = dict(decisions or {})  # swap # -> state
        self.deploy = None
        self._vid = 1

    def attach_deploy(self, controller):
        self.deploy = controller

    def swap(self, source, canary_fraction=None):
        self._vid += 1
        self.swaps.append((str(source), canary_fraction))
        return self._vid

    def stats(self):
        state = self.decisions.get(len(self.swaps), self.default)
        return {"canary": {"version": self._vid, "state": state,
                           "reason": "scripted", "routed": 1, "total": 4}}


# ---------------------------------------------------------------------------
# watch_lineage + frame_fingerprint (utils/file_io.py)
# ---------------------------------------------------------------------------


def test_watch_lineage_local_order_and_filters(tmp_path):
    d = tmp_path / "lin"
    d.mkdir()
    (d / "release.2").write_bytes(b"b")
    (d / "release.1").write_bytes(b"a")
    (d / "release.3.corrupt").write_bytes(b"q")   # quarantined: invisible
    (d / "release.4.tmp").write_bytes(b"t")       # half-written: invisible
    got = []
    for n, p in file_io.watch_lineage(
            str(d), since=0, pattern=RELEASE_PATTERN, poll=0,
            sleep=lambda s: None, stop=lambda: len(got) >= 2):
        got.append((n, os.path.basename(p)))
    assert got == [(1, "release.1"), (2, "release.2")]
    # since= filters consumed ids; later entries picked up
    (d / "release.5").write_bytes(b"e")
    got2 = []
    for n, _p in file_io.watch_lineage(
            str(d), since=2, pattern=RELEASE_PATTERN, poll=0,
            sleep=lambda s: None, stop=lambda: len(got2) >= 1):
        got2.append(n)
    assert got2 == [5]


def test_watch_lineage_memory_scheme():
    d = f"memory://watch_lin_{os.getpid()}"
    fs = file_io.get_filesystem(d)
    fs.makedirs(d)
    fs.write_bytes(d + "/release.1", b"a")
    fs.write_bytes(d + "/release.7", b"b")
    got = []
    for n, p in file_io.watch_lineage(
            d, since=0, pattern=RELEASE_PATTERN, poll=0,
            sleep=lambda s: None, stop=lambda: len(got) >= 2):
        got.append(n)
        assert p.startswith("memory://")
    assert got == [1, 7]


def test_watch_lineage_idle_backoff_and_timeout(tmp_path):
    """Empty dir: the watch backs off on the injectable clock/sleep (no
    wall time burned) and ends after idle_timeout."""
    t = [0.0]
    delays = []

    def clock():
        return t[0]

    def sleep(s):
        delays.append(s)
        t[0] += max(s, 1e-3)

    out = list(file_io.watch_lineage(
        str(tmp_path / "nothing_here"), since=0,
        pattern=RELEASE_PATTERN, clock=clock, sleep=sleep,
        idle_timeout=1.0))
    assert out == []
    assert delays, "idle watch never slept"
    assert delays[1] > delays[0]          # exponential start
    assert max(delays) <= 2.0             # capped at IO_BACKOFF_MAX


def test_watch_lineage_absorbs_transient_remote_faults():
    """fail*2 inside the listing window: the IO retry layer absorbs the
    faults BELOW the watch — every release id still comes out, in order,
    none skipped, and nothing healthy gets quarantined."""
    d = f"memory://watch_chaos_{os.getpid()}"
    fs = file_io.get_filesystem(d)
    fs.makedirs(d)
    for i in (1, 2, 3):
        fs.write_bytes(f"{d}/release.{i}", b"r%d" % i)
    got = []
    with chaos.scoped("fs.remote=fail*2@2"):
        for n, _p in file_io.watch_lineage(
                d, since=0, pattern=RELEASE_PATTERN, poll=0,
                sleep=lambda s: None, stop=lambda: len(got) >= 4):
            got.append(n)
            if n == 3:  # keep publishing THROUGH the chaos window
                fs.write_bytes(f"{d}/release.4", b"r4")
    assert got == [1, 2, 3, 4]
    assert not [n for n in fs.listdir(d) if n.endswith(".corrupt")]


def test_watch_lineage_survives_retry_exhaustion():
    """A fault burst LONGER than the per-op retry budget: the failed
    listings read as empty polls (warn, not crash), and once the burst
    drains every id is yielded exactly once — no skips, no false
    quarantine, no dead watch."""
    d = f"memory://watch_burst_{os.getpid()}"
    fs = file_io.get_filesystem(d)
    fs.makedirs(d)
    fs.write_bytes(f"{d}/release.1", b"a")
    fs.write_bytes(f"{d}/release.2", b"b")
    got, polls = [], [0]

    def stop():
        polls[0] += 1
        assert polls[0] < 200, "watch never recovered from the burst"
        return len(got) >= 2

    # IO_RETRIES=3 -> 4 attempts per op: 8 faults = two full polls where
    # even the retried listing fails, then storage heals
    with chaos.scoped("fs.remote=fail*8@1"):
        for n, _p in file_io.watch_lineage(
                d, since=0, pattern=RELEASE_PATTERN, poll=0,
                sleep=lambda s: None, stop=stop):
            got.append(n)
    assert got == [1, 2]
    assert polls[0] > 2  # the burst really cost empty polls first
    assert not [n for n in fs.listdir(d) if n.endswith(".corrupt")]


def test_frame_fingerprint(tmp_path):
    p = tmp_path / "blob"
    file_io.save({"w": np.arange(8.0)}, str(p))
    fp = file_io.frame_fingerprint(str(p))
    assert fp is not None and len(fp) == 2
    length, crc = fp
    assert length == os.path.getsize(p) - 20  # footer = u64+u32+magic
    # rewriting the blob changes the fingerprint
    file_io.save({"w": np.arange(8.0) + 1}, str(p))
    assert file_io.frame_fingerprint(str(p)) != fp
    # legacy unframed files have none
    raw = tmp_path / "legacy"
    raw.write_bytes(pickle.dumps({"w": 1}))
    assert file_io.frame_fingerprint(str(raw)) is None


# ---------------------------------------------------------------------------
# the publisher
# ---------------------------------------------------------------------------


def test_publisher_entries_and_monotonic_ids(tmp_path):
    snap = tmp_path / "model.3"
    _snapshot(snap, seed=1)
    pub = ReleasePublisher(str(tmp_path))
    r1 = pub.publish(str(snap), neval=3, epoch=1,
                     metrics={"loss": 0.25})
    r2 = pub.publish(str(snap), neval=3)
    assert (r1, r2) == (1, 2)
    entry = read_release(str(tmp_path / "release.1"))
    assert entry["release_id"] == 1
    assert entry["neval"] == 3 and entry["epoch"] == 1
    assert entry["metrics"]["loss"] == 0.25
    assert entry["model_name"] == "model.3"
    assert tuple(entry["fingerprint"]) == \
        file_io.frame_fingerprint(str(snap))
    # a fresh publisher resumes AFTER every existing id — including
    # quarantined ones, which must never be reused
    (tmp_path / "release.2").rename(tmp_path / "release.2.corrupt")
    assert ReleasePublisher(str(tmp_path)).publish(
        str(snap), neval=4) == 3


def test_publisher_corrupt_chaos_point(tmp_path):
    """deploy.publish=corrupt@1 lands an entry whose CRC verification
    fails at the consumer — the mid-publish corruption drill."""
    snap = tmp_path / "model.1"
    _snapshot(snap)
    with chaos.scoped("deploy.publish=corrupt@1"):
        pub = ReleasePublisher(str(tmp_path))
        pub.publish(str(snap), neval=1)
        pub.publish(str(snap), neval=1)
    with pytest.raises(file_io.CorruptCheckpoint):
        read_release(str(tmp_path / "release.1"))
    read_release(str(tmp_path / "release.2"))  # next entry is clean


# ---------------------------------------------------------------------------
# the controller state machine (stub server: no jax, no threads beyond
# the controller's own)
# ---------------------------------------------------------------------------


def test_controller_lineage_walk_skips_bad_entries(tmp_path):
    """THE satellite walk: good release, truncated frame, quarantined
    entry, good release — only the good ones deploy, in order; the
    truncated one is quarantined with a typed rejection."""
    snap = tmp_path / "model.1"
    _snapshot(snap)
    pub = ReleasePublisher(str(tmp_path))
    pub.publish(str(snap), neval=1)                      # release.1 good
    payload = pickle.dumps({"format": "bigdl_tpu-release-v1"})
    framed = file_io.frame_bytes(payload)
    # a torn write: half the payload gone, footer intact -> the frame
    # declares more bytes than the file holds
    (tmp_path / "release.2").write_bytes(
        framed[len(payload) // 2:])
    # an already-quarantined entry: must never even be listed
    (tmp_path / "release.3.corrupt").write_bytes(framed)
    pub._next = 4
    pub.publish(str(snap), neval=2)                      # release.4 good
    srv = _StubServer()
    ctl = DeployController(srv, str(tmp_path), canary_fraction=0,
                           poll_s=0.01).start()
    try:
        _wait(lambda: ctl.stats()["promoted"] + ctl.stats()["rejected"]
              >= 3)
    finally:
        ctl.stop()
    st = ctl.stats()
    assert srv.deploy is ctl                   # attach_deploy happened
    assert [e["release"] for e in ctl.versions()["timeline"]
            if e["action"] == "deployed"] == [1, 4]
    rejected = [e for e in ctl.versions()["timeline"]
                if e["action"] == "rejected"]
    assert [e["release"] for e in rejected] == [2]
    assert rejected[0]["reason_type"] == "ReleaseRejected"
    assert (tmp_path / "release.2.corrupt").exists()
    assert st["healthy"] and st["promoted"] == 2 and st["rejected"] == 1


def test_controller_canary_promote_records_verdict(tmp_path):
    snap = tmp_path / "model.1"
    _snapshot(snap)
    ReleasePublisher(str(tmp_path)).publish(str(snap), neval=1)
    srv = _StubServer(default="promoted")
    ctl = DeployController(srv, str(tmp_path), canary_fraction=0.25,
                           poll_s=0.01).start()
    try:
        _wait(lambda: ctl.stats()["promoted"] >= 1)
    finally:
        ctl.stop()
    assert srv.swaps[0][1] == 0.25             # canary fraction forwarded
    promoted = [e for e in ctl.versions()["timeline"]
                if e["action"] == "promoted"]
    assert promoted[0]["verdict"]["state"] == "promoted"
    assert ctl.stats()["consecutive_rollbacks"] == 0


def test_controller_rollback_budget_freezes(tmp_path):
    """Consecutive rollbacks past the budget freeze the controller:
    healthy() False, frozen timeline event, NO further releases consumed
    — fail-stop beats flapping a bad trainer into production."""
    snap = tmp_path / "model.1"
    _snapshot(snap)
    pub = ReleasePublisher(str(tmp_path))
    for i in range(5):
        pub.publish(str(snap), neval=i + 1)
    srv = _StubServer(default="rolled_back")
    ctl = DeployController(srv, str(tmp_path), canary_fraction=0.25,
                           rollback_budget=2, poll_s=0.01).start()
    try:
        _wait(lambda: not ctl.healthy())
    finally:
        ctl.stop()
    st = ctl.stats()
    assert st["frozen"] and "consecutive canary rollbacks" in \
        st["frozen_reason"]
    assert st["rolled_back"] == 3              # budget 2 -> frozen on #3
    assert st["deployed"] == 3                 # releases 4, 5 never swap
    assert len(srv.swaps) == 3
    actions = [e["action"] for e in ctl.versions()["timeline"]]
    assert actions[-1] == "frozen"
    # a promote in between resets the counter (separate controller)
    srv2 = _StubServer(default="rolled_back", decisions={2: "promoted"})
    ctl2 = DeployController(srv2, str(tmp_path), canary_fraction=0.25,
                            rollback_budget=2, poll_s=0.01).start()
    try:
        _wait(lambda: not ctl2.healthy())
    finally:
        ctl2.stop()
    # rollback(1) promote(reset) rollback(1) rollback(2) rollback(3=freeze)
    assert ctl2.stats()["rolled_back"] == 4
    assert ctl2.stats()["promoted"] == 1
    assert len(srv2.swaps) == 5


def test_controller_rejects_rewritten_snapshot(tmp_path):
    """A snapshot rewritten AFTER publication (fingerprint mismatch)
    must never deploy — the elastic-recovery-rewrites-the-lineage case."""
    snap = tmp_path / "model.1"
    _snapshot(snap, seed=1)
    ReleasePublisher(str(tmp_path)).publish(str(snap), neval=1)
    _snapshot(snap, seed=2)                    # rewritten: new CRC
    srv = _StubServer()
    ctl = DeployController(srv, str(tmp_path), canary_fraction=0,
                           poll_s=0.01).start()
    try:
        _wait(lambda: ctl.stats()["rejected"] >= 1)
    finally:
        ctl.stop()
    ev = [e for e in ctl.versions()["timeline"]
          if e["action"] == "rejected"][0]
    assert "fingerprint" in ev["reason"]
    assert not srv.swaps
    assert (tmp_path / "release.1.corrupt").exists()


def test_controller_missing_snapshot_rejected(tmp_path):
    """A release whose snapshot was pruned/quarantined after publication
    is rejected typed, not crashed on."""
    snap = tmp_path / "model.9"
    _snapshot(snap)
    ReleasePublisher(str(tmp_path)).publish(str(snap), neval=9)
    snap.unlink()
    srv = _StubServer()
    ctl = DeployController(srv, str(tmp_path), canary_fraction=0,
                           poll_s=0.01).start()
    try:
        _wait(lambda: ctl.stats()["rejected"] >= 1)
    finally:
        ctl.stop()
    ev = [e for e in ctl.versions()["timeline"]
          if e["action"] == "rejected"][0]
    assert "does not exist" in ev["reason"]
    assert not srv.swaps


# ---------------------------------------------------------------------------
# the optimizer publish hook
# ---------------------------------------------------------------------------


def _tiny_optimizer(ckpt_dir, epochs=2, publish_every=2):
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.optim import Adam, Optimizer, Trigger

    rng = np.random.default_rng(0)
    samples = [Sample(rng.standard_normal(6).astype(np.float32),
                      np.float32(i % 2)) for i in range(32)]
    ds = DataSet.rdd(samples).transform(
        SampleToMiniBatch(16, drop_last=True))
    opt = (Optimizer(nn.Sequential().add(nn.Linear(6, 2)), ds,
                     nn.CrossEntropyCriterion())
           .set_optim_method(Adam(1e-2))
           .set_end_when(Trigger.max_epoch(epochs)))
    opt.set_checkpoint(str(ckpt_dir), Trigger.several_iteration(1),
                       publish=True, publish_every=publish_every)
    return opt


def test_optimizer_publishes_releases(tmp_path):
    """set_checkpoint(publish=True, publish_every=2): every 2nd snapshot
    write emits a verified release entry whose fingerprint matches the
    snapshot on disk."""
    opt = _tiny_optimizer(tmp_path / "ckpt")
    opt.optimize()
    # 2 epochs x 2 iterations + epoch-boundary writes, publish every 2nd
    # write -> releases 1..3 (write counts 1, 3, 5)
    assert opt._publisher is not None and opt._publisher.published == 3
    nevals = []
    for rid in (1, 2, 3):
        entry = read_release(str(tmp_path / "ckpt" / f"release.{rid}"))
        assert entry["release_id"] == rid
        mp = entry["model_path"]
        assert os.path.exists(mp)
        file_io.verify(mp)
        assert tuple(entry["fingerprint"]) == \
            file_io.frame_fingerprint(mp)
        assert "loss" in entry["metrics"]
        nevals.append(entry["neval"])
    assert nevals == sorted(nevals)


def test_optimizer_publish_async_write(tmp_path):
    """Async checkpoint writes publish from the write future — a release
    can never point at bytes that are not on storage yet."""
    opt = _tiny_optimizer(tmp_path / "ckpt")
    opt.checkpoint_async = True
    opt.optimize()
    # the final join guarantees the snapshots; the publish callbacks run
    # on write completion, so give the last one a beat
    _wait(lambda: os.path.exists(str(tmp_path / "ckpt" / "release.3")),
          timeout=10.0)
    for rid in (1, 2, 3):
        entry = read_release(str(tmp_path / "ckpt" / f"release.{rid}"))
        file_io.verify(entry["model_path"])
        assert tuple(entry["fingerprint"]) == \
            file_io.frame_fingerprint(entry["model_path"])


# ---------------------------------------------------------------------------
# live server integration: swap bit-match, stats, HTTP, trace section
# ---------------------------------------------------------------------------


def test_live_server_serves_last_promoted_release(tmp_path):
    """Real InferenceServer + controller: two published releases deploy
    in order (plain swaps) and the server then answers bit-for-bit what
    bulk Predictor computes from the LAST promoted snapshot."""
    Engine.init()
    _snapshot(tmp_path / "model.1", seed=1)
    m2 = _snapshot(tmp_path / "model.2", seed=2)
    pub = ReleasePublisher(str(tmp_path))
    pub.publish(str(tmp_path / "model.1"), neval=1)
    pub.publish(str(tmp_path / "model.2"), neval=2)
    arch = nn.Sequential().add(nn.Linear(6, 2)).build(jax.random.key(9))
    x = np.random.default_rng(3).normal(size=(8, 6)).astype(np.float32)
    server = InferenceServer(arch, example=x[0], max_batch=4).start()
    ctl = DeployController(server, str(tmp_path), canary_fraction=0,
                           poll_s=0.01).start()
    try:
        _wait(lambda: ctl.stats()["promoted"] >= 2)
        st = server.stats()
        assert st["deploy"]["healthy"] and st["deploy"]["promoted"] == 2
        assert st["version"] == 3              # initial=1, two swaps
        ref = np.stack([Predictor(m2).predict(x[i:i + 1])[0]
                        for i in range(len(x))])
        got = np.stack([server.predict(x[i]) for i in range(len(x))])
        assert np.array_equal(got, ref)
    finally:
        ctl.stop()
        server.stop()


def test_http_versions_and_stats(tmp_path):
    """/v1/versions exposes the model-version timeline + healthy/frozen
    state; /v1/stats carries the deploy block."""
    import urllib.request

    tools_dir = os.path.join(_REPO_ROOT, "tools")
    if tools_dir not in sys.path:
        sys.path.insert(0, tools_dir)
    import serve_http

    Engine.init()
    _snapshot(tmp_path / "model.1", seed=1)
    ReleasePublisher(str(tmp_path)).publish(str(tmp_path / "model.1"),
                                            neval=1)
    arch = nn.Sequential().add(nn.Linear(6, 2)).build(jax.random.key(0))
    server = InferenceServer(arch,
                             example=np.zeros((6,), np.float32)).start()
    httpd = serve_http.serve_forever(server, "127.0.0.1", 0)
    base = f"http://127.0.0.1:{httpd.server_address[1]}"

    def get(path):
        with urllib.request.urlopen(base + path, timeout=30) as r:
            return json.loads(r.read())

    ctl = None
    try:
        # no controller attached yet
        assert get("/v1/versions") == {"deploy": False, "timeline": [],
                                       "version": 1}
        ctl = DeployController(server, str(tmp_path), canary_fraction=0,
                               poll_s=0.01).start()
        _wait(lambda: ctl.stats()["promoted"] >= 1)
        v = get("/v1/versions")
        assert v["deploy"] and v["healthy"] and not v["frozen"]
        actions = [(e["release"], e["action"]) for e in v["timeline"]]
        assert (1, "deployed") in actions and (1, "promoted") in actions
        st = get("/v1/stats")
        assert st["deploy"]["healthy"] is True
        assert st["deploy"]["frozen"] is False
        assert st["deploy"]["last_release"] == 1
    finally:
        httpd.shutdown()
        if ctl is not None:
            ctl.stop()
        server.stop()


def test_deploy_counter_track_in_trace_report(tmp_path):
    """The deploy track is a first-class report section: publishes from
    the publisher, outcome totals from the controller, one merged
    timeline (tools/trace_report.py satellite)."""
    trace_dir = tmp_path / "trace"
    tracer = telemetry.Tracer(str(trace_dir), rank=0)
    telemetry.set_active(tracer)
    try:
        snap = tmp_path / "model.1"
        _snapshot(snap)
        pub = ReleasePublisher(str(tmp_path))
        pub.publish(str(snap), neval=1)
        pub.publish(str(snap), neval=2)
        srv = _StubServer()
        ctl = DeployController(srv, str(tmp_path), canary_fraction=0.5,
                               poll_s=0.01).start()
        try:
            _wait(lambda: ctl.stats()["promoted"] >= 2)
        finally:
            ctl.stop()
    finally:
        tracer.close()
        telemetry.set_active(None)
    breakdown = telemetry.phase_breakdown(
        telemetry.merge_traces(str(trace_dir)))
    dep = breakdown["deploy"]
    assert dep["published"] == 2
    assert dep["deployed"] == 2 and dep["promoted"] == 2
    assert dep["frozen"] == 0
    assert dep["events"] >= 6   # 2 publishes + 2 deploys + 2 promotes
    report = telemetry.format_report(breakdown)
    assert "deploy: " in report
    assert "instant events" in report


# ---------------------------------------------------------------------------
# THE acceptance drill
# ---------------------------------------------------------------------------


def test_continuous_drill_end_to_end(tmp_path):
    """THE acceptance drill (ISSUE 15): trainer (2 elastic subprocess
    ranks, rank 1 chaos-killed mid-train) and this server process share
    ONLY a lineage directory.  One run must show: the corrupt
    mid-publish entry skipped typed + quarantined, the host loss never
    interrupting the release feed, the latency-inflated canary rolled
    back exactly once, the LAST release promoted, the served model
    bit-matching its snapshot, and zero dropped requests — driven
    through tools/continuous_smoke.py, the exact artifact runbook
    cpu-smoke stage 2o runs."""
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO_ROOT, "tools",
                                      "continuous_smoke.py"),
         "--platform", "cpu", "--ckpt-dir", str(tmp_path)],
        capture_output=True, text=True, timeout=420,
        env={**os.environ, "PYTHONPATH": _REPO_ROOT})
    lines = [ln for ln in proc.stdout.splitlines() if ln.startswith("{")]
    assert lines, f"no JSON from the drill:\n{proc.stderr[-3000:]}"
    out = json.loads(lines[-1])
    assert proc.returncode == 0, out
    assert out["ok"] is True
    assert out["rank1_rc"] == 117              # chaos ExitAt's drill code
    assert out["recovered"] is True            # elastic leg closed
    assert out["rejected"] >= 1                # corrupt publish skipped
    assert out["rolled_back"] == 1             # canary regression leg
    assert out["healthy"] and not out["frozen"]
    assert out["bit_match"] is True
    assert out["traffic"]["served"] == out["traffic"]["submitted"]
    assert not out["traffic"]["errors"]
    assert out["deploy_report"]["published"] == out["published"]
    # the quarantined corrupt entry is still on disk for forensics
    assert os.path.exists(os.path.join(str(tmp_path), "ckpt",
                                       "release.2.corrupt"))
