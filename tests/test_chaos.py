"""Chaos-hardened checkpoint lineage tests.

Reference: the reference's fault story is "reload the latest snapshot and
retry" (DistriOptimizer.scala:750-816) with durability delegated to
Spark's block manager.  This suite drives the rebuild's own durability
machinery — CRC32C-framed snapshots (utils/file_io), lineage-walking
recovery with quarantine (optim/Optimizer), retried remote IO, and the
deterministic fault-injection layer (utils/chaos) — through the scenarios
MLPerf-scale training treats as routine: torn/corrupted snapshots,
transient storage faults, NaN losses.

Every schedule here is count-based (no wall clock, no RNG) and the retry
backoff runs on an injected zero-cost clock: the whole file is exactly
reproducible.
"""

import math
import os

import numpy as np
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
from bigdl_tpu.optim import Adam, Optimizer, Trigger
from bigdl_tpu.optim.optimizer import NonFiniteLossError
from bigdl_tpu.utils import chaos, file_io


@pytest.fixture(autouse=True)
def _fake_retry_time():
    """Deterministic, sleep-free backoff for every test in this file."""
    t = {"now": 0.0}

    def clock():
        return t["now"]

    def sleep(d):
        t["now"] += d

    prev = file_io.set_retry_timebase(clock, sleep)
    yield t
    file_io.set_retry_timebase(*prev)


@pytest.fixture(autouse=True)
def _clean_chaos_and_memory_store():
    chaos.clear()
    yield
    chaos.clear()
    try:
        import fsspec
        fsspec.filesystem("memory").rm("/", recursive=True)
    except Exception:
        pass


def _dataset(n=64, d=6, batch=16):
    rng = np.random.default_rng(0)
    samples = [Sample(rng.standard_normal(d).astype(np.float32),
                      np.float32(i % 2)) for i in range(n)]
    return DataSet.array(samples).transform(
        SampleToMiniBatch(batch, drop_last=True))


def _optimizer(ckpt_path, max_epoch=2, **ckpt_kw):
    model = nn.Sequential().add(nn.Linear(6, 2))
    return (Optimizer(model, _dataset(), nn.CrossEntropyCriterion())
            .set_optim_method(Adam(1e-2))
            .set_end_when(Trigger.max_epoch(max_epoch))
            .set_checkpoint(str(ckpt_path), Trigger.several_iteration(1),
                            **ckpt_kw))


# ---------------------------------------------------------------------------
# the chaos layer itself
# ---------------------------------------------------------------------------

def test_schedules_are_deterministic_counters():
    with chaos.scoped("data.batch=fail@2,4"):
        chaos.fire("data.batch")                      # 1
        with pytest.raises(chaos.ChaosFault):
            chaos.fire("data.batch")                  # 2
        chaos.fire("data.batch")                      # 3
        with pytest.raises(chaos.ChaosFault):
            chaos.fire("data.batch")                  # 4
        chaos.fire("data.batch")                      # 5
        assert chaos.counts()["data.batch"] == 5
    # cleared on exit: nothing armed, fire is free
    chaos.fire("data.batch")
    assert not chaos.armed("data.batch")


def test_fail_n_times_schedule():
    with chaos.scoped("fs.remote=fail*3@2"):
        chaos.fire("fs.remote")                       # 1 ok
        for _ in range(3):                            # 2,3,4 fail
            with pytest.raises(chaos.ChaosFault):
                chaos.fire("fs.remote")
        chaos.fire("fs.remote")                       # 5 ok again


def test_corrupt_and_truncate_mutators():
    data = bytes(range(64))
    with chaos.scoped("ckpt.write=corrupt@1;ckpt.read=truncate@1"):
        flipped = chaos.transform("ckpt.write", data)
        assert len(flipped) == len(data) and flipped != data
        cut = chaos.transform("ckpt.read", data)
        assert len(cut) < len(data)
    with chaos.scoped("step.loss_nan=nan@1"):
        assert math.isnan(chaos.transform("step.loss_nan", 0.25))


def test_spec_parse_errors_are_loud():
    with pytest.raises(ValueError):
        chaos.install("ckpt.write=explode@1")
    with pytest.raises(ValueError):
        chaos.install("ckpt.write=fail")  # no counts
    with pytest.raises(ValueError):
        chaos.install("no-equals-sign")


def test_retry_backoff_is_deterministic_and_bounded():
    p1 = file_io.RetryPolicy(retries=5, base=0.1, max_delay=1.0,
                             deadline=60.0)
    p2 = file_io.RetryPolicy(retries=5, base=0.1, max_delay=1.0,
                             deadline=60.0)
    d1 = [p1.delay(a) for a in range(1, 6)]
    assert d1 == [p2.delay(a) for a in range(1, 6)]  # no RNG anywhere
    assert all(d <= 1.0 for d in d1)                 # capped
    assert d1[0] < d1[1] < d1[2]                     # exponential ramp


def test_retry_deadline_exhausts(_fake_retry_time):
    calls = []

    def always_fails():
        calls.append(1)
        raise IOError("remote down")

    p = file_io.RetryPolicy(retries=100, base=1.0, max_delay=10.0,
                            deadline=5.0)
    with pytest.raises(IOError):
        p.run(always_fails, describe="test")
    assert 1 < len(calls) < 20  # deadline cut it off long before retries


# ---------------------------------------------------------------------------
# integrity frame
# ---------------------------------------------------------------------------

def test_frame_roundtrip_and_detects_flip_and_truncation(tmp_path):
    p = str(tmp_path / "blob")
    file_io.save({"w": np.arange(7.0)}, p)
    np.testing.assert_array_equal(file_io.load(p)["w"], np.arange(7.0))
    data = open(p, "rb").read()
    # flip one payload byte
    bad = data[:10] + bytes([data[10] ^ 0x01]) + data[11:]
    open(p, "wb").write(bad)
    with pytest.raises(file_io.CorruptCheckpoint, match="CRC mismatch"):
        file_io.load(p)
    # truncate mid-payload: the magic is gone, the torn pickle is caught
    open(p, "wb").write(data[:len(data) // 2])
    with pytest.raises(file_io.CorruptCheckpoint):
        file_io.load(p)


def test_legacy_unframed_pickle_still_loads(tmp_path):
    import pickle
    p = str(tmp_path / "legacy.bin")
    with open(p, "wb") as f:
        pickle.dump({"x": 41}, f)
    assert file_io.load(p)["x"] == 41


def test_remote_frame_verification_memory_scheme():
    file_io.save({"w": np.ones(3)}, "memory://chaos_fr/blob")
    np.testing.assert_array_equal(
        file_io.load("memory://chaos_fr/blob")["w"], 1.0)
    import fsspec
    fs = fsspec.filesystem("memory")
    raw = fs.cat_file("/chaos_fr/blob")
    fs.pipe_file("/chaos_fr/blob",
                 raw[:8] + bytes([raw[8] ^ 0xFF]) + raw[9:])
    with pytest.raises(file_io.CorruptCheckpoint, match="CRC mismatch"):
        file_io.load("memory://chaos_fr/blob")


def test_crc32c_update_matches_oneshot():
    from bigdl_tpu.utils.recordio import crc32c_update, masked_crc32c
    data = os.urandom(1 << 12)
    whole = crc32c_update(0, data)
    split = crc32c_update(crc32c_update(0, data[:100]), data[100:])
    assert whole == split
    # masked form consistent with the TFRecord framer
    assert masked_crc32c(data) == \
        ((whole >> 15) | (whole << 17)) + 0xA282EAD8 & 0xFFFFFFFF


# ---------------------------------------------------------------------------
# lineage recovery
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("backend", ["local", "memory"])
def test_corrupt_newest_snapshot_recovers_from_previous(tmp_path, backend):
    """The e2e acceptance scenario: the newest snapshot lands corrupted
    (chaos on ckpt.write), a data fault then forces recovery — with two
    injected transient remote-IO faults on the memory:// lane.  The run
    completes, recovery resumes from the newest VALID snapshot (weights
    equal to that snapshot's params on disk), and the corrupt file is
    quarantined, not deleted."""
    ckpt = (str(tmp_path / "ck") if backend == "local"
            else f"memory://chaos_e2e_{os.getpid()}")
    # ckpt.write counts blobs: model.1,opt.1,model.2,opt.2,model.3 -> the
    # 5th write (model.3) lands corrupted; data batch 4 then fails, so
    # recovery must skip model.3 and resume from model.2
    spec = "ckpt.write=corrupt@5;data.batch=fail@4"
    if backend == "memory":
        spec += ";fs.remote=fail*2@20"  # two transient remote faults
        # (count 20 lands mid-run: each remote checkpoint costs ~5 ops)
    os.environ["BIGDL_TPU_RETRY_TIMES"] = "1"  # data fault uses the only
    # optimizer retry: remote-IO faults MUST be absorbed by backoff below
    try:
        with chaos.scoped(spec):
            import jax
            opt = _optimizer(ckpt)
            resumed = {}
            orig = opt._load_snapshot

            def spy(mp, op=None):
                r = orig(mp, op)
                resumed["path"] = mp
                resumed["params"] = [np.asarray(leaf) for leaf in
                                     jax.tree.leaves(opt.model.params)]
                return r

            opt._load_snapshot = spy
            trained = opt.optimize()
        import jax
        assert trained.params is not None
        assert resumed["path"].endswith("model.2"), resumed
        # recovery loaded exactly snapshot 2's bytes
        blob = file_io.load(resumed["path"])
        for got, want in zip(resumed["params"],
                             jax.tree.leaves(blob["params"])):
            np.testing.assert_array_equal(got, np.asarray(want))
        fs = file_io.get_filesystem(ckpt)
        names = set(fs.listdir(ckpt))
        assert "model.3.corrupt" in names  # quarantined...
        assert "optimMethod.3.corrupt" in names
        assert any(n.startswith("model.") and not n.endswith(".corrupt")
                   for n in names)  # ...and training kept checkpointing
    finally:
        del os.environ["BIGDL_TPU_RETRY_TIMES"]


def test_whole_lineage_corrupt_falls_back_to_initial_weights(tmp_path):
    """Every snapshot corrupt -> recovery walks the entire lineage,
    quarantines all of it, and restores the run-start weights."""
    import jax
    with chaos.scoped("ckpt.write=corrupt@1,2,3,4,5,6,7,8;data.batch=fail@3"):
        opt = _optimizer(tmp_path, max_epoch=1)
        opt.model.build(jax.random.key(5))
        pretrained = jax.tree.map(np.asarray, opt.model.params)
        trained = opt.optimize()
    assert trained.params is not None
    names = os.listdir(str(tmp_path))
    assert any(n.endswith(".corrupt") for n in names)
    # the fallback blob was the user's starting weights (captured pre-run)
    assert opt._initial_blob is None  # released after the successful run
    del pretrained


def test_resume_from_explicit_corrupt_snapshot_falls_back(tmp_path):
    for n in (1, 2, 3):
        file_io.save_checkpoint(
            str(tmp_path), n,
            {"params": {"w": np.full(3, float(n))}, "state": {}},
            {"method": {"hyper": {}, "learning_rate": 0.1},
             "driver_state": {"epoch": 1, "neval": n + 1,
                              "evalCounter": n}})
    mp3, op3, _ = file_io.latest_checkpoint(str(tmp_path))
    data = open(mp3, "rb").read()
    open(mp3, "wb").write(data[:16] + bytes([data[16] ^ 0xFF]) + data[17:])

    model = nn.Sequential().add(nn.Linear(6, 2))
    opt = Optimizer(model, _dataset(), nn.CrossEntropyCriterion())
    opt.resume_from(mp3, op3)  # falls back loudly instead of raising
    np.testing.assert_array_equal(np.asarray(model.params["w"]), 2.0)
    assert os.path.exists(mp3 + ".corrupt")  # quarantined, not deleted
    assert not os.path.exists(mp3)


def test_resume_from_corrupt_with_no_valid_fallback_raises(tmp_path):
    file_io.save_checkpoint(
        str(tmp_path), 1, {"params": {"w": np.ones(2)}, "state": {}},
        {"method": {"hyper": {}, "learning_rate": 0.1},
         "driver_state": {}})
    mp, op, _ = file_io.latest_checkpoint(str(tmp_path))
    data = open(mp, "rb").read()
    open(mp, "wb").write(data[:12] + bytes([data[12] ^ 0xFF]) + data[13:])
    model = nn.Sequential().add(nn.Linear(6, 2))
    opt = Optimizer(model, _dataset(), nn.CrossEntropyCriterion())
    with pytest.raises(file_io.CorruptCheckpoint):
        opt.resume_from(mp, op)


# ---------------------------------------------------------------------------
# transient remote IO under backoff
# ---------------------------------------------------------------------------

def test_remote_transient_faults_do_not_burn_optimizer_retries():
    """fail*2 on every-other remote op window: the IO retry layer absorbs
    them below the optimizer, so training completes even with ZERO
    optimizer retries allowed."""
    os.environ["BIGDL_TPU_RETRY_TIMES"] = "0"
    try:
        with chaos.scoped("fs.remote=fail*2@3"):
            opt = _optimizer(f"memory://chaos_rt_{os.getpid()}",
                             max_epoch=1)
            trained = opt.optimize()
        assert trained.params is not None
        latest = file_io.latest_checkpoint(f"memory://chaos_rt_{os.getpid()}")
        assert latest is not None
    finally:
        del os.environ["BIGDL_TPU_RETRY_TIMES"]


def test_remote_faults_beyond_retry_budget_surface():
    with chaos.scoped("fs.remote=fail*50@1"):
        with pytest.raises(chaos.ChaosFault):
            file_io.save({"x": 1}, f"memory://chaos_dead_{os.getpid()}/b")


# ---------------------------------------------------------------------------
# retention
# ---------------------------------------------------------------------------

def test_retention_keeps_exactly_the_configured_set(tmp_path):
    opt = _optimizer(tmp_path, max_epoch=3, keep_last=2,
                     keep_every_epochs=2)
    opt.optimize()
    lineage = [n for _, _, n in file_io.checkpoint_lineage(str(tmp_path))]
    # 3 epochs x 4 iterations = snapshots 1..12: keep_last=2 -> {12, 11};
    # keep_every_epochs=2 -> the first write of epoch 2 (neval 4, the
    # epoch-1 boundary write) is a permanent keeper
    assert lineage == [12, 11, 4], lineage


def test_retention_env_default_and_quarantine_immunity(tmp_path):
    os.environ["BIGDL_TPU_CKPT_KEEP_LAST"] = "1"
    try:
        with chaos.scoped("ckpt.write=corrupt@3;data.batch=fail@3"):
            # model.2 corrupt -> quarantined during recovery; retention
            # must leave the .corrupt pair alone
            opt = _optimizer(tmp_path, max_epoch=1)
            opt.optimize()
    finally:
        del os.environ["BIGDL_TPU_CKPT_KEEP_LAST"]
    names = sorted(os.listdir(str(tmp_path)))
    assert "model.2.corrupt" in names
    live = [n for _, _, n in file_io.checkpoint_lineage(str(tmp_path))]
    assert len(live) == 1  # keep-last-1 enforced on the live lineage


# ---------------------------------------------------------------------------
# non-finite loss sentinel
# ---------------------------------------------------------------------------

def test_nan_loss_triggers_checkpoint_recovery(tmp_path):
    with chaos.scoped("step.loss_nan=nan@5"):
        opt = _optimizer(tmp_path, max_epoch=2)
        trained = opt.optimize()  # NaN at obs 5 -> recover -> complete
        assert chaos.counts()["step.loss_nan"] > 5  # training continued
    import jax
    assert trained.params is not None
    assert all(np.all(np.isfinite(np.asarray(leaf)))
               for leaf in jax.tree.leaves(trained.params))


def test_nan_loss_without_checkpoint_fails_fast():
    with chaos.scoped("step.loss_nan=nan@2"):
        model = nn.Sequential().add(nn.Linear(6, 2))
        opt = (Optimizer(model, _dataset(), nn.CrossEntropyCriterion())
               .set_end_when(Trigger.max_epoch(1)))
        with pytest.raises(NonFiniteLossError):
            opt.optimize()


# ---------------------------------------------------------------------------
# data.batch corruption (the batch now routes through chaos.transform)
# ---------------------------------------------------------------------------

def test_corrupt_at_poisons_minibatch_floats_keeps_labels():
    from bigdl_tpu.dataset import MiniBatch
    batch = MiniBatch(np.ones((4, 3), np.float32),
                      np.arange(4, dtype=np.int32))
    with chaos.scoped("data.batch=nan@1"):
        out = chaos.transform("data.batch", batch)
    assert np.isnan(out.get_input()).all()          # features poisoned
    np.testing.assert_array_equal(out.get_target(), np.arange(4))
    assert out.get_target().dtype.kind == "i"       # labels untouched
    assert np.isfinite(batch.get_input()).all()     # original not mutated


def test_poisoned_batch_caught_by_loss_sentinel_and_recovers(tmp_path):
    """data.batch=nan@N NaN-poisons the training features; the host-side
    non-finite-loss sentinel must catch the poisoned step and recovery
    must complete the run with finite weights."""
    import jax
    with chaos.scoped("data.batch=nan@3"):
        opt = _optimizer(tmp_path, max_epoch=2)
        trained = opt.optimize()
        assert chaos.counts()["data.batch"] > 3  # training continued
    assert all(np.all(np.isfinite(np.asarray(leaf)))
               for leaf in jax.tree.leaves(trained.params))


def test_poisoned_batch_without_checkpoint_fails_fast():
    with chaos.scoped("data.batch=nan@2"):
        model = nn.Sequential().add(nn.Linear(6, 2))
        opt = (Optimizer(model, _dataset(), nn.CrossEntropyCriterion())
               .set_end_when(Trigger.max_epoch(1)))
        with pytest.raises(NonFiniteLossError):
            opt.optimize()


# ---------------------------------------------------------------------------
# stall schedules (the supervision chaos points)
# ---------------------------------------------------------------------------

def test_stall_schedule_spec_parse_and_block():
    import time as _time
    with chaos.scoped("step.stall=stall*0.2@2"):
        t0 = _time.monotonic()
        chaos.fire("step.stall")                      # 1: no stall
        assert _time.monotonic() - t0 < 0.15
        t0 = _time.monotonic()
        chaos.fire("step.stall")                      # 2: blocks ~0.2s
        assert _time.monotonic() - t0 >= 0.18
    with pytest.raises(ValueError):
        chaos.install("step.stall=stall")             # no counts


def test_stall_default_duration_and_repr():
    s = chaos._parse_action("stall@7")
    assert isinstance(s, chaos.StallAt)
    assert s.seconds == 3600.0 and s.fires(7) and not s.fires(6)


# ---------------------------------------------------------------------------
# host.lost + @epoch:iteration addressing (the elastic drill grammar)
# ---------------------------------------------------------------------------

def test_host_lost_grammar_roundtrip():
    """`host.lost@<rank>` is a rank-addressed POINT name; `exit` and
    `wedge`/`lost` are its actions.  A shared spec only engages on the
    addressed rank because only that rank fires the suffixed point."""
    with chaos.scoped("host.lost@1=exit@3;host.lost@0=wedge*0.5@2"):
        assert chaos.armed("host.lost@1")
        assert chaos.armed("host.lost@0")
        assert not chaos.armed("host.lost@2")  # unaddressed rank: inert
    s = chaos._parse_action("exit@3")
    assert isinstance(s, chaos.ExitAt)
    assert s.fires(3) and not s.fires(2) and s.EXIT_CODE == 117
    w = chaos._parse_action("wedge*2.5@4")
    assert isinstance(w, chaos.WedgeAt) and w.seconds == 2.5
    assert chaos._parse_action("lost@4").seconds == 3600.0  # wedge alias
    with pytest.raises(ValueError):
        chaos.install("host.lost@1=exit")  # no counts
    with pytest.raises(ValueError):
        chaos.install("host.lost@1=lose@1")  # unknown action stays loud


def test_epoch_step_addressing_roundtrip():
    """`@epoch:iteration` pairs address the driver position published by
    chaos.at_position — alongside (and mixable with) plain counts."""
    s = chaos._parse_action("stall*30@2:5")
    assert s.positions == frozenset({(2, 5)}) and not s.counts
    mixed = chaos._parse_action("fail@3,2:5")
    assert mixed.counts == frozenset({3})
    assert mixed.positions == frozenset({(2, 5)})
    chaos.at_position(2, 5)
    assert chaos._matches(s, 99)       # position match, any count
    chaos.at_position(2, 4)
    assert not chaos._matches(s, 99)
    assert chaos._matches(mixed, 3)    # plain count still matches
    with pytest.raises(ValueError):
        chaos._parse_action("fail*2@2:5")  # fail*N takes one plain start


def test_epoch_step_addressed_fault_fires_at_position():
    with chaos.scoped("data.batch=fail@2:3"):
        chaos.at_position(1, 1)
        chaos.fire("data.batch")            # wrong position: clean
        chaos.at_position(2, 3)
        with pytest.raises(chaos.ChaosFault):
            chaos.fire("data.batch")
        chaos.at_position(2, 4)
        chaos.fire("data.batch")            # past it: clean again


def test_host_return_grammar_roundtrip():
    """`host.return@<rank>=join@epoch:iter` is the grow drill's gate: a
    rank-addressed point with fault-schedule addressing but no fault
    semantics — only gate() reports it, fire()/transform() ignore it."""
    with chaos.scoped("host.return@1=join@2:2"):
        assert chaos.armed("host.return@1")
        assert not chaos.armed("host.return@0")  # unaddressed rank: inert
        [s] = chaos._POINTS["host.return@1"].schedules
        assert isinstance(s, chaos.ReturnAt)
        assert s.positions == frozenset({(2, 2)}) and not s.counts
    for spec in ("join@2:2", "return@2:2", "@2:2"):  # all spell ReturnAt
        g = chaos._parse_action(spec)
        assert isinstance(g, chaos.ReturnAt)
        assert g.positions == frozenset({(2, 2)})
    by_count = chaos._parse_action("return@3")       # count addressing too
    assert by_count.counts == frozenset({3}) and by_count.fires(3)
    with pytest.raises(ValueError):
        chaos.install("host.return@1=join")          # no counts stays loud


def test_host_return_gate_fires_at_or_after_position():
    """gate() positions are AT-OR-AFTER (tuple order): the joiner POLLS
    positions sampled from the checkpoint stream and may never observe
    the exact coordinate — exact-match would be a silent never-fire."""
    with chaos.scoped("host.return@1=join@2:2"):
        assert not chaos.gate("host.return@1")   # no position published
        chaos.at_position(1, 4)
        assert not chaos.gate("host.return@1")   # before: held
        chaos.at_position(2, 2)
        assert chaos.gate("host.return@1")       # exact: fires
        chaos.at_position(2, 9)
        assert chaos.gate("host.return@1")       # after: still fires
        chaos.at_position(3, 0)
        assert chaos.gate("host.return@1")       # any later epoch too
    with chaos.scoped("host.return@1=return@2"):
        assert not chaos.gate("host.return@1")   # poll 1: not yet
        assert chaos.gate("host.return@1")       # poll 2: count matches
        assert not chaos.gate("host.return@1")   # counts stay EXACT
    assert not chaos.gate("host.return@1")       # nothing installed: False


def test_host_return_gate_never_faults_through_fire_or_transform():
    with chaos.scoped("host.return@1=join@1"):
        chaos.fire("host.return@1")              # count 1: would match...
        payload = chaos.transform("host.return@1", b"abc")
        assert payload == b"abc"                 # ...but gates never mutate


def test_exit_at_engages_and_suspends_liveness(monkeypatch, tmp_path):
    """ExitAt must go publication-silent then hard-exit (monkeypatched:
    the test process stays alive) — the survivors' detection signal."""
    from bigdl_tpu.utils.supervisor import Supervisor
    from bigdl_tpu.utils import supervisor as sup_mod
    calls = {}
    monkeypatch.setattr(os, "_exit", lambda code: calls.setdefault(
        "code", code))
    sup = Supervisor({"step": 60.0}, peer_dir=str(tmp_path), rank=1,
                     world=2, publish_interval=0.0)
    sup_mod.set_active(sup)
    try:
        with chaos.scoped("host.lost@1=exit@1"):
            chaos.fire("host.lost@1")
        assert calls["code"] == chaos.ExitAt.EXIT_CODE == 117
        assert sup._publish_suspended  # went silent before dying
        sup.beat("step")
        sup._publish_heartbeat()
        assert not os.path.exists(str(tmp_path / "heartbeat.1"))
    finally:
        sup_mod.set_active(None)


def test_wedge_at_blocks_for_duration_and_suspends():
    from bigdl_tpu.utils.supervisor import Supervisor
    from bigdl_tpu.utils import supervisor as sup_mod
    import time as _time
    sup = Supervisor({"step": 60.0})
    sup_mod.set_active(sup)
    try:
        with chaos.scoped("host.lost@0=wedge*0.2@1"):
            t0 = _time.monotonic()
            chaos.fire("host.lost@0")
            assert _time.monotonic() - t0 >= 0.18
        assert sup._publish_suspended
    finally:
        sup_mod.set_active(None)


# ---------------------------------------------------------------------------
# tier-1 chaos smoke: 5-step LeNet fit over a corrupt BDRecord shard
# ---------------------------------------------------------------------------

def _lenet_record_stream(tmp_path, skip_budget):
    from bigdl_tpu.utils.recordio import write_records
    rng = np.random.default_rng(0)
    images = rng.normal(0.0, 0.1, size=(120, 28, 28, 1)).astype(np.float32)
    labels = rng.integers(0, 10, size=120)
    samples = [Sample(images[i], np.int32(labels[i])) for i in range(120)]
    shard = str(tmp_path / "lenet.bd")
    write_records(shard, samples)
    return DataSet.record_stream([shard], skip_budget=skip_budget) \
        .transform(SampleToMiniBatch(16, drop_last=True))


def test_lenet_fit_with_record_corruption_and_skip_budget(tmp_path):
    """5-step LeNet fit with data.record corruption + skip budget 2:
    the run completes and exactly 2 records were quarantined (logged with
    offsets, counted process-wide)."""
    from bigdl_tpu.models.lenet import LeNet5
    from bigdl_tpu.utils import recordio

    ds = _lenet_record_stream(tmp_path, skip_budget=2)
    recordio.reset_quarantine_stats()
    with chaos.scoped("data.record=truncate@10,30"):
        opt = (Optimizer(LeNet5(10), ds, nn.ClassNLLCriterion())
               .set_optim_method(Adam(1e-3))
               .set_end_when(Trigger.max_iteration(5)))
        trained = opt.optimize()
    assert trained.params is not None
    assert recordio.quarantine_stats()["records"] == 2


def test_lenet_fit_record_corruption_budget_zero_fails_loud(tmp_path):
    """Same corruption with the default budget 0: fail loud with the
    typed CorruptRecord (today's semantics preserved)."""
    from bigdl_tpu.models.lenet import LeNet5
    from bigdl_tpu.utils.recordio import CorruptRecord

    ds = _lenet_record_stream(tmp_path, skip_budget=0)
    with chaos.scoped("data.record=truncate@10"):
        opt = (Optimizer(LeNet5(10), ds, nn.ClassNLLCriterion())
               .set_optim_method(Adam(1e-3))
               .set_end_when(Trigger.max_iteration(5)))
        with pytest.raises(CorruptRecord):
            opt.optimize()
