"""Graceful-preemption checkpointing (SIGTERM → final snapshot → stop).

Net-new vs the reference (its executor topology was fixed at init,
Engine.scala:326-338): on spot/preemptible TPUs the eviction signal is a
SIGTERM with a grace period, and the training loop must convert it into
one forced synchronous checkpoint plus TrainingPreempted.  Driven in a
subprocess so the signal handling is exercised for real.
"""

import json

import pytest

# subprocess + 20s sleeps: slow lane (pyproject addopts)
pytestmark = pytest.mark.slow
import os
import signal
import subprocess
import sys
import textwrap
import time

CHILD = textwrap.dedent("""
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 4)
    import sys, json
    import numpy as np
    sys.path.insert(0, {repo!r})
    from bigdl_tpu import Engine
    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import Sample
    from bigdl_tpu.models import LeNet5
    from bigdl_tpu.optim import (Optimizer, Adam, Trigger,
                                 TrainingPreempted)

    r = np.random.default_rng(0)
    samples = [Sample(r.normal(size=(28, 28)).astype(np.float32),
                      np.int32(r.integers(0, 10))) for _ in range(256)]
    Engine.init()
    opt = Optimizer(LeNet5(10), samples, nn.ClassNLLCriterion(),
                    batch_size=64)
    opt.set_optim_method(Adam(1e-3))
    opt.set_checkpoint({ckpt!r}, Trigger.several_iteration(10**9))
    opt.set_end_when(Trigger.max_epoch(10**6))   # run until preempted
    print("READY", flush=True)
    try:
        opt.optimize()
    except TrainingPreempted as e:
        print("PREEMPTED:" + str(e), flush=True)
        sys.exit(17)
    sys.exit(3)  # finished without preemption: the test failed to signal
""")


def _spawn(repo, ckpt):
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    return subprocess.Popen(
        [sys.executable, "-c", CHILD.format(repo=repo, ckpt=ckpt)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env)


def test_sigterm_writes_final_checkpoint_and_resume_works(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ckpt = str(tmp_path / "ckpt")
    proc = _spawn(repo, ckpt)
    try:
        # wait for the child to be inside optimize() (it prints READY just
        # before), then give it time to enter the step loop and deliver
        # SIGTERM mid-training
        line = proc.stdout.readline()
        assert "READY" in line, line
        time.sleep(20)
        proc.send_signal(signal.SIGTERM)
        out, err = proc.communicate(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    assert proc.returncode == 17, (proc.returncode, out, err[-2000:])
    assert "PREEMPTED:" in out, (out, err[-2000:])

    # the forced snapshot exists and a fresh process resumes from it
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    from bigdl_tpu.utils import file_io
    latest = file_io.latest_checkpoint(ckpt)
    assert latest is not None, os.listdir(ckpt)
    model_path, optim_path, neval = latest
    assert neval >= 1

    from bigdl_tpu import Engine
    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import Sample
    from bigdl_tpu.models import LeNet5
    from bigdl_tpu.optim import Optimizer, Adam, Trigger
    r = np.random.default_rng(0)
    samples = [Sample(r.normal(size=(28, 28)).astype(np.float32),
                      np.int32(r.integers(0, 10))) for _ in range(128)]
    Engine.init()
    opt = Optimizer(LeNet5(10), samples, nn.ClassNLLCriterion(),
                    batch_size=64)
    opt.set_optim_method(Adam(1e-3))
    opt.resume_from(model_path, optim_path)
    # resumed iteration counter carries on from the preempted run
    assert opt._resume_state["neval"] > 1
    opt.set_end_when(Trigger.max_iteration(
        opt._resume_state["neval"] + 2))
    trained = opt.optimize()   # a couple more steps complete cleanly
    assert trained is not None
