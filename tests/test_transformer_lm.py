"""TransformerLM: the long-context flagship workload (net-new, SURVEY.md §7).

Covers LayerNorm/GELU parity vs torch, causal-LM shape/masking, end-to-end
training through the Optimizer, and the ring-attention (seq_parallel) path
on a 'seq' mesh matching the dense result."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.models import TransformerLM


def test_layernorm_matches_torch():
    torch = pytest.importorskip("torch")
    x = np.random.default_rng(0).normal(size=(4, 7, 12)).astype(np.float32)
    m = nn.LayerNorm(12).build(jax.random.key(0))
    got = np.asarray(m.forward(jnp.asarray(x)))
    ref = torch.nn.LayerNorm(12)(torch.tensor(x)).detach().numpy()
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_gelu_matches_torch():
    torch = pytest.importorskip("torch")
    x = np.random.default_rng(1).normal(size=(5, 9)).astype(np.float32) * 3
    got = np.asarray(nn.GELU().build(jax.random.key(0))
                     .forward(jnp.asarray(x)))
    # jax.nn.gelu defaults to the tanh approximation
    ref = torch.nn.GELU(approximate="tanh")(torch.tensor(x)).numpy()
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


def test_transformer_lm_forward_shape_and_causality():
    model = TransformerLM(vocab_size=50, max_len=16, d_model=32,
                          num_heads=4, num_layers=2).build(jax.random.key(0))
    tok = jnp.asarray(np.random.default_rng(2).integers(0, 50, (2, 10)))
    out, _ = model.apply(model.params, model.state, tok, training=False,
                         rng=None)
    assert out.shape == (2, 10, 50)
    # log-probs normalize
    np.testing.assert_allclose(np.exp(np.asarray(out)).sum(-1),
                               np.ones((2, 10)), rtol=1e-4)
    # causality: perturbing a LATER token must not change earlier outputs
    tok2 = tok.at[:, 7].set((tok[:, 7] + 1) % 50)
    out2, _ = model.apply(model.params, model.state, tok2, training=False,
                          rng=None)
    np.testing.assert_allclose(np.asarray(out)[:, :7],
                               np.asarray(out2)[:, :7], atol=1e-5)
    assert not np.allclose(np.asarray(out)[:, 7:], np.asarray(out2)[:, 7:])


def test_transformer_lm_trains_copy_task():
    """Predict token t from token t-1 on a deterministic cycle — a few
    steps of Adam should crush it; drives the full Optimizer path."""
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.optim import Adam, Optimizer, Trigger
    from bigdl_tpu.utils.engine import Engine

    Engine.reset()
    vocab, t = 12, 8
    r = np.random.default_rng(3)
    seqs = []
    for _ in range(128):
        start = int(r.integers(0, vocab))
        toks = [(start + i) % vocab for i in range(t + 1)]
        seqs.append(toks)
    samples = [Sample(np.asarray(s[:-1], np.int32),
                      np.asarray(s[1:], np.int32)) for s in seqs]
    ds = DataSet.array(samples).transform(
        SampleToMiniBatch(32, drop_last=True))
    model = TransformerLM(vocab_size=vocab, max_len=t, d_model=32,
                          num_heads=4, num_layers=2)
    crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                       size_average=True)
    opt = (Optimizer(model, ds, crit)
           .set_optim_method(Adam(3e-3))
           .set_end_when(Trigger.max_epoch(15)))
    trained = opt.optimize()
    tok = jnp.asarray([s[:-1] for s in seqs[:8]], jnp.int32)
    out, _ = trained.apply(trained.params, trained.state,
                           tok, training=False, rng=None)
    pred = np.argmax(np.asarray(out), -1)
    tgt = np.asarray([s[1:] for s in seqs[:8]])
    assert (pred == tgt).mean() > 0.95


def test_greedy_generate_reproduces_learned_cycle():
    """Train on the +1-mod-vocab cycle, then greedy_generate must emit it;
    also checks batch input and the one-compile static-shape contract."""
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.models.transformer_lm import greedy_generate
    from bigdl_tpu.optim import Adam, Optimizer, Trigger
    from bigdl_tpu.utils.engine import Engine

    Engine.reset()
    vocab, t = 10, 12
    seqs = [[(s + i) % vocab for i in range(t + 1)] for s in range(vocab)] * 8
    samples = [Sample(np.asarray(s[:-1], np.int32),
                      np.asarray(s[1:], np.int32)) for s in seqs]
    ds = DataSet.array(samples).transform(
        SampleToMiniBatch(16, drop_last=True))
    model = TransformerLM(vocab_size=vocab, max_len=t, d_model=32,
                          num_heads=4, num_layers=2)
    crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                       size_average=True)
    Optimizer(model, ds, crit).set_optim_method(Adam(3e-3)) \
        .set_end_when(Trigger.max_epoch(12)).optimize()

    out = greedy_generate(model, [4, 5, 6], num_tokens=6, max_len=t)
    assert out.tolist() == [4, 5, 6, 7, 8, 9, 0, 1, 2]
    outs = greedy_generate(model, [[1, 2], [7, 8]], num_tokens=4,
                           max_len=t)
    assert outs.tolist() == [[1, 2, 3, 4, 5, 6], [7, 8, 9, 0, 1, 2]]
    with pytest.raises(ValueError):
        greedy_generate(model, [0] * 10, num_tokens=5, max_len=t)
    with pytest.raises(ValueError):
        greedy_generate(model, [], num_tokens=2, max_len=t)
    with pytest.raises(ValueError):
        greedy_generate(model, [1], num_tokens=2, max_len=t,
                        temperature=0.5)  # sampling without rng
    # sampling with near-zero temperature on a confident model follows the
    # learned cycle; top_k=1 is exactly greedy
    s = greedy_generate(model, [4, 5], num_tokens=4, max_len=t,
                        temperature=0.05, rng=jax.random.key(0))
    assert s.tolist() == [4, 5, 6, 7, 8, 9]
    s1 = greedy_generate(model, [4, 5], num_tokens=4, max_len=t,
                         temperature=2.0, top_k=1, rng=jax.random.key(1))
    assert s1.tolist() == [4, 5, 6, 7, 8, 9]
    # the per-model jit cache must not break native save (pickling)
    import os
    import tempfile
    path = os.path.join(tempfile.mkdtemp(), "lm.bin")
    model.save(path)
    assert nn.Module.load(path).params is not None


def test_transformer_lm_seq_parallel_matches_dense():
    """Ring attention under shard_map over a 'seq' axis must reproduce the
    dense forward bit-for-tolerance."""
    from jax.sharding import Mesh

    devs = np.array(jax.devices()[:4]).reshape(4)
    mesh = Mesh(devs, ("seq",))
    model = TransformerLM(vocab_size=30, max_len=16, d_model=32,
                          num_heads=4, num_layers=2, causal=True,
                          seq_parallel=False).build(jax.random.key(1))
    sp = TransformerLM(vocab_size=30, max_len=16, d_model=32,
                       num_heads=4, num_layers=2, causal=True,
                       seq_parallel=True)
    sp.build(jax.random.key(1))
    sp.params = model.params  # identical weights
    tok = jnp.asarray(np.random.default_rng(5).integers(0, 30, (2, 16)))
    dense, _ = model.apply(model.params, model.state, tok, training=False,
                           rng=None)
    with mesh:
        ring, _ = sp.apply(sp.params, sp.state, tok, training=False,
                           rng=None)
    np.testing.assert_allclose(np.asarray(dense), np.asarray(ring),
                               rtol=2e-4, atol=2e-5)


def test_moe_transformer_lm_trains_copy_task():
    """Switch-style MoE variant (num_experts>0 swaps the dense MLP for
    parallel/expert.MoEFFN): the copy task must be learnable through the
    full Optimizer path (gate gets gradient via the combine weights, aux
    load-balancing loss rides the state pytree).

    Seed pinned inside the test: the 128-sample task gives only 4 optimizer
    steps/epoch, so convergence depth at a fixed epoch count is RNG-stream
    sensitive — measured over seeds 0-5 this config lands 0.66-0.75
    accuracy (dense MLP behaves identically), hence the 0.55 bar."""
    from bigdl_tpu.common import set_seed
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.optim import Adam, Optimizer, Trigger
    from bigdl_tpu.utils.engine import Engine

    Engine.reset()
    set_seed(1)
    vocab, t = 12, 8
    r = np.random.default_rng(7)
    seqs = []
    for _ in range(128):
        start = int(r.integers(0, vocab))
        seqs.append([(start + i) % vocab for i in range(t + 1)])
    samples = [Sample(np.asarray(s[:-1], np.int32),
                      np.asarray(s[1:], np.int32)) for s in seqs]
    ds = DataSet.array(samples).transform(
        SampleToMiniBatch(32, drop_last=True))
    model = TransformerLM(vocab_size=vocab, max_len=t, d_model=32,
                          num_heads=4, num_layers=2, num_experts=4)
    crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                       size_average=True)
    opt = (Optimizer(model, ds, crit)
           .set_optim_method(Adam(3e-3))
           .set_end_when(Trigger.max_epoch(25)))
    trained = opt.optimize()
    assert opt.optim_method.hyper["loss"] < 1.5  # from ln(12) ~ 2.48
    tok = jnp.asarray([s[:-1] for s in seqs[:32]], jnp.int32)
    out, _ = trained.apply(trained.params, trained.state,
                           tok, training=False, rng=None)
    pred = np.argmax(np.asarray(out), -1)
    tgt = np.asarray([s[1:] for s in seqs[:32]])
    assert (pred == tgt).mean() > 0.55

