"""8-process multi-host ResNet-50 integration with mid-epoch preemption.

The strongest off-hardware evidence chain for the BASELINE "linear 8->64"
claim (VERDICT r3 #8): 8 REAL OS processes (1 virtual CPU device each)
forming the 8-way data mesh, training the flagship ResNet-50 architecture
(CIFAR variant, depth 50 = 6*8+2 — tiny images keep one shared core
feasible) under ShardedDataParallel (ZeRO param shards), then a SIGTERM to
ONE rank mid-epoch that must fan out into a collective forced checkpoint on
ALL ranks, and a resume that completes on every rank with bit-identical
parameters.

Scaling-ratio note: this image exposes ONE CPU core (nproc=1), so an 8-vs-1
process throughput ratio measures scheduler contention, not the framework —
the test instead asserts the ranks progress in lockstep (per-rank mean step
times within a loose band) and reports the timings in the worker output.
Reference pattern: optim/DistriOptimizerSpec.scala:33-41 scaled to 8.
"""

import textwrap

import pytest

from conftest import spawn_multihost_workers

_WORKER = textwrap.dedent("""
    import json, os, signal, threading, time
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 1)

    import numpy as np
    from bigdl_tpu.utils.engine import Engine
    import bigdl_tpu.nn as nn
    from bigdl_tpu.common import set_seed
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.models.resnet import ResNet
    from bigdl_tpu.optim import (Adam, Optimizer, Trigger, Top1Accuracy,
                                 TrainingPreempted)
    from bigdl_tpu.parallel.sharding import ShardedDataParallel

    mesh = Engine.init()
    assert jax.process_count() == 8, jax.process_count()
    assert jax.device_count() == 8, jax.device_count()
    rank = jax.process_index()
    ckpt = r"{ckpt}"

    r = np.random.default_rng(42)  # SAME corpus on every process
    n, classes = 512, 4
    xs = r.normal(0.0, 0.2, size=(n, 32, 32, 3)).astype(np.float32)
    ys = r.integers(0, classes, size=n)
    for i, l in enumerate(ys):  # separable: class k brightens column band k
        xs[i, :, 8 * int(l): 8 * int(l) + 8, :] += 2.0
    samples = [Sample(x, np.int32(y)) for x, y in zip(xs, ys)]
    ds = DataSet.rdd(samples).transform(SampleToMiniBatch(8,  # per-process rows: 8 x 8 = global 64
                                                          drop_last=True))

    set_seed(7)  # identical init everywhere
    model = ResNet(50, class_num=classes, dataset="cifar10",
                   with_softmax=True)
    opt = (Optimizer(model, ds, nn.ClassNLLCriterion(),
                     strategy=ShardedDataParallel())
           .set_optim_method(Adam(3e-3))
           .set_checkpoint(ckpt, Trigger.several_iteration(10 ** 9))
           .set_end_when(Trigger.max_epoch(10 ** 6)))  # until preempted

    # ONE rank self-preempts mid-epoch; the collective decision must force
    # a final checkpoint and raise TrainingPreempted on EVERY rank
    if rank == 3:
        def bomb():
            time.sleep(45)  # past compile, inside the step loop
            os.kill(os.getpid(), signal.SIGTERM)
        threading.Thread(target=bomb, daemon=True).start()

    t0 = time.monotonic()
    preempted = False
    try:
        opt.optimize()
    except TrainingPreempted:
        preempted = True
    assert preempted, "rank %d finished without preemption" % rank

    # resume from the forced snapshot: barrier so every rank sees the same
    # completed files, then train 2 more epochs to completion
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices("preempt-ckpt")
    import glob
    snaps = sorted(glob.glob(os.path.join(ckpt, "model.*")),
                   key=lambda p: int(p.rsplit(".", 1)[1]))
    osnaps = sorted(glob.glob(os.path.join(ckpt, "optimMethod.*")),
                    key=lambda p: int(p.rsplit(".", 1)[1]))
    assert snaps and osnaps, os.listdir(ckpt)

    # resume restores the driver epoch counter: give the resumed run a
    # FIXED amount of further work relative to the snapshot's epoch
    from bigdl_tpu.utils import file_io
    resume_epoch = int(file_io.load(osnaps[-1])["driver_state"]["epoch"])

    set_seed(7)
    model2 = ResNet(50, class_num=classes, dataset="cifar10",
                    with_softmax=True)
    opt2 = (Optimizer(model2, ds, nn.ClassNLLCriterion(),
                      strategy=ShardedDataParallel())
            .set_optim_method(Adam(3e-3))
            .set_validation(Trigger.every_epoch(), samples,
                            [Top1Accuracy()], batch_size=64)
            .set_end_when(Trigger.max_epoch(resume_epoch + 3)))
    opt2.resume_from(snaps[-1], osnaps[-1])
    t_resume = time.monotonic()
    trained = opt2.optimize()
    resume_s = time.monotonic() - t_resume

    # ZeRO leaves are process-sharded (not host-addressable): digest via a
    # jnp reduction, which computes distributedly and replicates the scalar
    import jax.numpy as jnp
    digest = float(sum(jnp.abs(l.astype(jnp.float32)).sum()
                       for l in jax.tree.leaves(trained.params)))
    loss = opt2.optim_method.hyper["loss"]
    print(json.dumps({{"rank": rank, "digest": digest, "loss": loss,
                       "preempted": preempted,
                       "resume_epochs_s": resume_s}}), flush=True)
""")


@pytest.mark.slow
def test_eight_process_resnet50_preempt_resume(tmp_path):
    worker = _WORKER.format(ckpt=str(tmp_path / "ckpt"))
    outs = spawn_multihost_workers(worker, tmp_path, n=8, timeout=1800)
    by_rank = {o["rank"]: o for o in outs}
    assert set(by_rank) == set(range(8))
    for o in outs:
        assert o["preempted"] is True
        # converged on the separable bands after resume
        assert o["loss"] < 1.0, o
        # ZeRO-sharded training stayed bit-consistent across all 8 ranks
        assert o["digest"] == pytest.approx(by_rank[0]["digest"], rel=1e-6)
    # lockstep: collective steps mean no rank can lag the others' wall time
    times = [o["resume_epochs_s"] for o in outs]
    assert max(times) < 3.0 * min(times) + 5.0, times
