"""Compile cards + perf gate (bigdl_tpu/utils/hlostats.py — ISSUE 11).

Covers: HLO/StableHLO text analysis units (op histogram, convert
direction pairs, alias counting on the nested-brace header), the
matmul-route card showing 0 convolutions in the compiled train step, the
wire card's up-cast count bounded by the BUCKET count (not the leaf
count), the fused-update card reporting the expected buffer count +
donation aliases, card round-trip through ``memory://``, disabled-mode
inertness, the forward (serve/eval) choke point, the trace_report
counter-track section + ``--diff`` CLI, the aot quarantine log carrying
the fingerprint, and the perf gate's check logic + full CLI pass against
the committed PERF_BASELINE.json.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import bigdl_tpu.nn as nn
from bigdl_tpu.utils import aot, hlostats, telemetry
from bigdl_tpu.utils.engine import Engine

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _reset_hlostats():
    hlostats.reset()
    aot.reset()
    yield
    hlostats.reset()
    aot.reset()


def _build_lenet_step(batch_size=16):
    """The real compiled train step on device 0 (tools/lenet_cold.py
    pattern); fresh Optimizer so env knobs re-bake."""
    from bigdl_tpu.models.lenet import LeNet5
    from bigdl_tpu.optim import Optimizer, SGD, Trigger

    Engine.reset()
    Engine.init(devices=[jax.devices()[0]])
    mesh = Engine.mesh()
    model = LeNet5(10)
    model.build(jax.random.key(0))
    opt = Optimizer(model, dataset=None, criterion=nn.ClassNLLCriterion(),
                    end_trigger=Trigger.max_iteration(1))
    opt.set_optim_method(SGD(learning_rate=0.01))
    step, param_sh, _ = opt._build_step(mesh)
    rng = np.random.default_rng(0)
    inp = jnp.asarray(rng.normal(size=(batch_size, 28, 28, 1)), jnp.float32)
    tgt = jnp.asarray(rng.integers(0, 10, size=batch_size), jnp.int32)
    params = jax.device_put(model.params, param_sh)
    args = (params, model.state, opt.optim_method.init_state(params),
            inp, tgt, jnp.float32(0.01), jax.random.key(1))
    return step, args, opt


def _step_once(step, args):
    out = step(*args)
    jax.block_until_ready(out[3])
    return out


# ----------------------------------------------------------------------
# text-analysis units (no backend)
# ----------------------------------------------------------------------

def test_op_histogram_hlo_text():
    txt = """HloModule jit_f, is_scheduled=true
%fused (p0: f32[8,8]) -> f32[8,8] {
  %p0 = f32[8,8]{1,0} parameter(0)
  %convert.1 = bf16[8,8]{1,0} convert(f32[8,8]{1,0} %p0)
  %convert.2 = f32[8,8]{1,0} convert(bf16[8,8]{1,0} %convert.1)
  ROOT %dot.1 = f32[8,8]{1,0} dot(f32[8,8]{1,0} %convert.2, f32[8,8]{1,0} %convert.2)
}
ENTRY %main (a: f32[8,8]) -> (f32[8,8], f32[8,8]) {
  %a = f32[8,8]{1,0} parameter(0)
  %conv = f32[8,8]{1,0} convolution(f32[8,8]{1,0} %a, f32[8,8]{1,0} %a)
  %ar = f32[8,8]{1,0} all-reduce(f32[8,8]{1,0} %conv)
  %t = (f32[8,8]{1,0}, f32[8,8]{1,0}) tuple(f32[8,8]{1,0} %ar, f32[8,8]{1,0} %a)
}
"""
    hist = hlostats.op_histogram(txt)
    assert hist["convert"] == 2
    assert hist["dot"] == 1
    assert hist["convolution"] == 1
    assert hist["all-reduce"] == 1
    assert "parameter" not in hist
    pairs = hlostats.convert_pairs(txt)
    assert pairs == {"bf16<-f32": 1, "f32<-bf16": 1}
    assert hlostats.collective_count(hist) == 1


def test_op_histogram_stablehlo_text():
    txt = """module @jit_f {
  func.func public @main(%arg0: tensor<8x8xf32>) -> tensor<128xf32> {
    %0 = stablehlo.convert %arg0 : (tensor<8x8xf32>) -> tensor<8x8xbf16>
    %1 = stablehlo.reshape %0 : (tensor<8x8xbf16>) -> tensor<64xbf16>
    %2 = stablehlo.concatenate %1, %1, dim = 0 : (tensor<64xbf16>, tensor<64xbf16>) -> tensor<128xbf16>
    %3 = stablehlo.convert %2 : (tensor<128xbf16>) -> tensor<128xf32>
    return %3 : tensor<128xf32>
  }
}
"""
    hist = hlostats.op_histogram(txt)
    assert hist["convert"] == 2
    assert hist["concatenate"] == 1
    pairs = hlostats.convert_pairs(txt)
    # the dim-prefixed dtype must parse as bf16, never "xbf16"
    assert pairs == {"bf16<-f32": 1, "f32<-bf16": 1}


def test_alias_count_nested_braces():
    hdr = ("HloModule jit_step, is_scheduled=true, input_output_alias="
           "{ {0}: (0, {}, may-alias), {1}: (2, {}, may-alias) }, "
           "entry_computation_layout={(f32[8]{0})->f32[8]{0}}\n%body...")
    assert hlostats.alias_count(hdr) == 2
    assert hlostats.alias_count("HloModule jit_f, is_scheduled=true\n") == 0


def test_collective_count_async_pairs_count_once():
    hist = {"all-reduce-start": 2, "all-reduce-done": 2, "all-gather": 1,
            "dot": 4}
    assert hlostats.collective_count(hist) == 3


# ----------------------------------------------------------------------
# the three structural cards (ISSUE 11 test checklist)
# ----------------------------------------------------------------------

def test_matmul_route_card_has_zero_convolutions(monkeypatch):
    monkeypatch.setenv("BIGDL_TPU_COMPILE_CARDS", "1")
    monkeypatch.setenv("BIGDL_TPU_CONV_ROUTE", "matmul")
    jax.clear_caches()
    step, args, _ = _build_lenet_step()
    _step_once(step, args)
    card = hlostats.last_card("optim.step")
    assert card is not None, "no compile card captured for the train step"
    assert card["convolutions"] == 0
    assert card["stablehlo_ops"].get("convolution", 0) == 0
    assert card["total_ops"] > 0
    assert card["cost"]["flops"] > 0
    # the pad route, for contrast, keeps its 5 conv programs
    monkeypatch.setenv("BIGDL_TPU_CONV_ROUTE", "pad")
    jax.clear_caches()
    hlostats.reset()
    step, args, _ = _build_lenet_step()
    _step_once(step, args)
    assert hlostats.last_card("optim.step")["convolutions"] > 0


def test_wire_card_upcasts_bounded_by_bucket_count(monkeypatch):
    monkeypatch.setenv("BIGDL_TPU_COMPILE_CARDS", "1")
    monkeypatch.setenv("BIGDL_TPU_WIRE_BUCKET_MB", "4")
    jax.clear_caches()
    step, args, opt = _build_lenet_step()
    _step_once(step, args)
    card = hlostats.last_card("optim.step")
    extra = card["extra"]
    assert extra["wire_leaves"] == 8      # LeNet: 4 layers x (W, b)
    assert extra["wire_buckets"] == 1     # all leaves fit one 4MB bucket
    upcasts = card["stablehlo_convert_pairs"]["f32<-bf16"]
    # THE wire invariant: up-casts per BUCKET, not per leaf
    assert upcasts == extra["wire_buckets"]
    assert upcasts < extra["wire_leaves"]
    # per-leaf wire (bucketing off) pays one up-cast per gradient leaf
    monkeypatch.setenv("BIGDL_TPU_WIRE_BUCKET_MB", "0")
    jax.clear_caches()
    hlostats.reset()
    step, args, _ = _build_lenet_step()
    _step_once(step, args)
    card = hlostats.last_card("optim.step")
    assert card["extra"]["wire_buckets"] == 0
    assert card["stablehlo_convert_pairs"]["f32<-bf16"] == 8


def test_fused_card_buffer_count_and_donation(monkeypatch):
    monkeypatch.setenv("BIGDL_TPU_COMPILE_CARDS", "1")
    monkeypatch.setenv("BIGDL_TPU_FUSED_UPDATE", "1")
    jax.clear_caches()
    step, args, opt = _build_lenet_step()
    _step_once(step, args)
    card = hlostats.last_card("optim.step")
    # LeNet params are all-f32: one dtype-homogeneous fused buffer
    assert card["extra"]["fused_buffers"] == 1
    assert card["donation"] is True
    assert card["input_output_aliases"] > 0
    # NO_DONATE compiles a step with zero aliases — the card proves it
    monkeypatch.setenv("BIGDL_TPU_NO_DONATE", "1")
    jax.clear_caches()
    hlostats.reset()
    step, args, _ = _build_lenet_step()
    _step_once(step, args)
    card = hlostats.last_card("optim.step")
    assert card["donation"] is False
    assert card["input_output_aliases"] == 0


def test_forward_card_from_sharded_forward(monkeypatch):
    monkeypatch.setenv("BIGDL_TPU_COMPILE_CARDS", "1")
    from bigdl_tpu.optim import Predictor
    model = nn.Sequential().add(nn.Linear(6, 4)).add(nn.ReLU())
    model.build(jax.random.key(0))
    out = Predictor(model).predict(
        np.random.default_rng(0).normal(size=(8, 6)).astype(np.float32))
    assert out.shape == (8, 4)
    card = hlostats.last_card("forward")
    assert card is not None
    assert card["total_ops"] > 0
    # the forward key_fields ARE fingerprinted even with the cache off:
    # the card records the key the executable would cache under
    assert card["aot_key"]
    assert hlostats.ledger().get("forward") == 1


# ----------------------------------------------------------------------
# emission: artifacts, ledger, telemetry, inertness
# ----------------------------------------------------------------------

def test_card_roundtrip_memory_scheme():
    card = hlostats.compile_card(None, None, label="unit.test",
                                 key="abc123", extra={"wire_buckets": 2})
    path = hlostats.write_card(card, "memory://cards_rt")
    assert path.endswith(".json")
    got = hlostats.read_cards("memory://cards_rt")
    assert got == [card]


def test_capture_writes_artifact_to_knob_dir(monkeypatch, tmp_path):
    d = str(tmp_path / "cards")
    monkeypatch.setenv("BIGDL_TPU_COMPILE_CARDS", d)
    jax.clear_caches()
    step, args, _ = _build_lenet_step()
    _step_once(step, args)
    got = hlostats.read_cards(d)
    assert len(got) == 1 and got[0]["label"] == "optim.step"
    assert got[0] == hlostats.last_card("optim.step")
    assert hlostats.stats()["writes"] == 1


def test_cards_dir_beside_trace_dir(monkeypatch):
    monkeypatch.setenv("BIGDL_TPU_TRACE", "memory://tr_cards")
    monkeypatch.delenv("BIGDL_TPU_COMPILE_CARDS", raising=False)
    assert hlostats.enabled()
    assert hlostats.cards_dir() == "memory://tr_cards/cards"
    monkeypatch.setenv("BIGDL_TPU_COMPILE_CARDS", "0")
    assert not hlostats.enabled()


def test_disabled_mode_is_inert(monkeypatch):
    monkeypatch.delenv("BIGDL_TPU_COMPILE_CARDS", raising=False)
    monkeypatch.delenv("BIGDL_TPU_TRACE", raising=False)
    step, args, _ = _build_lenet_step()
    _step_once(step, args)
    assert hlostats.capture(None, None, label="x") is None
    assert hlostats.stats() == {"cards": 0, "writes": 0, "errors": 0,
                                "dropped": 0}
    assert hlostats.cards() == []


def test_card_instant_and_counter_in_trace(monkeypatch):
    monkeypatch.setenv("BIGDL_TPU_TRACE", "memory://tr_card_ev")
    tr = telemetry.Tracer("memory://tr_card_ev", rank=0)
    telemetry.set_active(tr)
    try:
        jax.clear_caches()
        step, args, _ = _build_lenet_step()
        _step_once(step, args)
    finally:
        tr.close()
    merged = telemetry.merge_traces("memory://tr_card_ev")
    bd = telemetry.phase_breakdown(merged)
    assert bd["instants"].get("compile.card", 0) >= 1
    assert "compile.total_ops" in bd["counters"]
    assert bd["counters"]["compile.total_ops"]["last"] > 0


# ----------------------------------------------------------------------
# trace_report: counter-track section, aot section, --diff
# ----------------------------------------------------------------------

def _fake_trace(dir_, step_ms=(5.0, 7.0), counters=(), rank=0):
    t = [0.0]

    def clock():
        return t[0]

    tr = telemetry.Tracer(dir_, rank=rank, clock=clock,
                          wall_clock=lambda: 1000.0)
    for ms in step_ms:
        with tr.span("step"):
            t[0] += ms / 1e3
    for track, values in counters:
        tr.counter(track, **values)
    tr.close()


def _run_cli(argv):
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO_ROOT, "tools",
                                      "trace_report.py"), *argv],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    return proc


def test_trace_report_counter_track_cli(tmp_path):
    d = str(tmp_path / "tr")
    _fake_trace(d, counters=[
        ("zz", {"late": 3.0}), ("aa", {"early": 1.0}),
        ("aot", {"hits": 2, "misses": 1, "stores": 1, "lowers": 1,
                 "compiles": 1})])
    proc = _run_cli([d])
    assert proc.returncode == 0, proc.stderr
    lines = proc.stdout.splitlines()
    rows = [ln.split()[0] for ln in lines
            if ln.startswith(("aa.", "aot.", "zz."))]
    # deterministic: sorted series order, every run
    assert rows == sorted(rows) and "aa.early" in rows and "zz.late" in rows
    # the aot counter track surfaces as its own ledger section
    aot_line = [ln for ln in lines if ln.startswith("aot ledger:")]
    assert aot_line and "hits=2" in aot_line[0] \
        and "compiles=1" in aot_line[0]
    # --json carries the parsed ledger too
    blob = json.loads(_run_cli([d, "--json"]).stdout)
    assert blob["aot"] == {"hits": 2, "misses": 1, "stores": 1,
                           "lowers": 1, "compiles": 1}


def test_trace_report_empty_dir_names_path(tmp_path):
    d = str(tmp_path / "empty")
    os.makedirs(d)
    proc = _run_cli([d])
    assert proc.returncode == 2
    assert d in proc.stderr  # the message names the offending input path


def test_trace_report_diff_cli(tmp_path):
    a, b = str(tmp_path / "a"), str(tmp_path / "b")
    _fake_trace(a, step_ms=(5.0, 5.0),
                counters=[("train", {"mfu": 0.30})])
    _fake_trace(b, step_ms=(10.0, 10.0),
                counters=[("train", {"mfu": 0.15})])
    proc = _run_cli([a, "--diff", b])
    assert proc.returncode == 0, proc.stderr
    assert "B/A" in proc.stdout and "train.mfu" in proc.stdout
    blob = json.loads(_run_cli([a, "--diff", b, "--json"]).stdout)
    assert blob["phases"]["step"]["total_ratio"] == pytest.approx(2.0,
                                                                  rel=0.05)
    assert blob["counters"]["train.mfu"]["last"] == [0.3, 0.15]
    assert blob["counters"]["train.mfu"]["delta"] == pytest.approx(-0.15)


def test_diff_breakdowns_only_in_one_run():
    a = {"phases": {"step": {"count": 1, "total_s": 1.0, "p50_ms": 1.0}},
         "counters": {}, "data_wait_fraction": 0.1}
    b = {"phases": {}, "counters": {"aot.hits": {"count": 1, "mean": 1,
                                                 "max": 1, "last": 1}},
         "data_wait_fraction": 0.2}
    d = telemetry.diff_breakdowns(a, b)
    assert d["phases"]["step"] == {"only": "A"}
    assert d["counters"]["aot.hits"] == {"only": "B"}
    assert "only in run A" in telemetry.format_diff(d)


# ----------------------------------------------------------------------
# aot satellites: quarantine fingerprint in the log
# ----------------------------------------------------------------------

def test_quarantine_log_names_fingerprint(tmp_path, caplog):
    import logging
    d = str(tmp_path / "aotq")
    cache = aot.AOTCache(d)
    key = "deadbeef" * 8
    with open(os.path.join(d, key + ".aotx"), "wb") as f:
        f.write(b"not a framed entry")
    with caplog.at_level(logging.WARNING, logger="bigdl_tpu"):
        assert cache.load(key) is None
    msgs = [r.getMessage() for r in caplog.records
            if "quarantining" in r.getMessage()]
    assert msgs and key in msgs[0] and "fingerprint" in msgs[0]
    assert aot.stats()["corrupt"] == 1


# ----------------------------------------------------------------------
# the perf gate
# ----------------------------------------------------------------------

def _gate_mod():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "perf_gate", os.path.join(_REPO_ROOT, "tools", "perf_gate.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_perf_gate_check_logic():
    gate = _gate_mod()
    baseline = {"metrics": {
        "conv_ops": {"value": 0, "match": "exact"},
        "ratio": {"value": 1.25, "match": "max"},
        "floor": {"value": 2, "match": "min"},
        "unmeasured": {"value": 1, "match": "exact"}}}
    measured = {"conv_ops": 5, "ratio": 1.0, "floor": 3, "extra_new": 7}
    rows, regressions = gate.check(measured, baseline)
    assert regressions == ["conv_ops", "unmeasured"]
    by_name = {r[0]: r[3] for r in rows}
    assert by_name["conv_ops"].startswith("REGRESSED")
    assert by_name["ratio"] == "OK"
    assert by_name["floor"] == "OK"
    assert by_name["extra_new"].startswith("NEW")
    assert by_name["unmeasured"].startswith("MISSING")
    # time slack widens max bounds only
    _, regressions = gate.check({"conv_ops": 0, "ratio": 2.0, "floor": 2,
                                 "unmeasured": 1}, baseline, time_slack=2.0)
    assert regressions == []


def test_perf_gate_baseline_committed_and_wellformed():
    path = os.path.join(_REPO_ROOT, "PERF_BASELINE.json")
    assert os.path.exists(path), "PERF_BASELINE.json must be committed"
    blob = json.load(open(path))
    assert blob["format"] == "bigdl_tpu-perf-baseline-v1"
    m = blob["metrics"]
    assert m["lenet_matmul.conv_ops"] == {"value": 0, "match": "exact"}
    assert m["wire.upcasts"]["value"] == m["wire.buckets"]["value"]
    assert m["wire.buckets"]["value"] < m["wire.leaves"]["value"]
    assert m["fused.buffers"]["value"] == 1
    for name in ("conv_route.step_ratio", "aot.warm_over_cold"):
        assert m[name]["match"] == "max"


def test_perf_gate_cli_passes_on_clean_head():
    """The acceptance run: the gate against the committed baseline must
    exit 0 with every metric OK (the pad-forced regression demo is
    exercised by runbook stage 2l and test_perf_gate_check_logic)."""
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("BIGDL_TPU_")}
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO_ROOT, "tools", "perf_gate.py"),
         "--platform", "cpu", "--batch-size", "32"],
        capture_output=True, text=True, timeout=420,
        env={**env, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, f"{proc.stdout}\n{proc.stderr}"
    blob = json.loads(proc.stdout.splitlines()[-1])
    assert blob["ok"] is True and blob["regressions"] == []
    assert blob["measured"]["lenet_matmul.conv_ops"] == 0


# ----------------------------------------------------------------------
# bench artifact-proofing
# ----------------------------------------------------------------------

def test_bench_partial_and_error_records(tmp_path, monkeypatch):
    """_fail leaves BOTH artifacts: the final error record at --out and
    the partial record with env + traceback (the flaky-backend evidence
    contract) — exercised in-process, no subprocess bench run."""
    sys.path.insert(0, _REPO_ROOT)
    import bench
    out = str(tmp_path / "round.json")
    monkeypatch.setitem(bench._OUT_STATE, "path", out)
    monkeypatch.setenv("BIGDL_TPU_TEST_MARKER_KNOB", "1")
    bench._STALL_STATE["results"].clear()
    bench._flush_partial("init")
    p = json.load(open(out + ".partial.json"))
    assert p["metric"] == "bench_partial" and p["stage"] == "init"
    assert p["env"]["BIGDL_TPU_TEST_MARKER_KNOB"] == "1"
    # an exception with a traceback lands in both records
    monkeypatch.setattr(bench, "_claim_emit", lambda: True)
    monkeypatch.setattr(bench.os, "_exit", lambda code: None)
    try:
        raise TimeoutError("jax.devices() did not return within 5s")
    except TimeoutError as e:
        bench._fail(e, "init")
    f = json.load(open(out))
    assert f["metric"] == "bench_error" and f["stage"] == "init"
    assert "TimeoutError" in f["traceback"]
    assert "jax.devices" in f["error"]
    p = json.load(open(out + ".partial.json"))
    assert p["error_type"] == "TimeoutError"
    bench._EMIT_DONE.clear()  # module-global: leave it how we found it
