"""Tests for the analytic jaxpr FLOP counter (bigdl_tpu/utils/flops.py).

The counter is the bench harness's fallback FLOPs source when XLA
cost_analysis is unavailable (round-2 verdict: resnet50 MFU was null because
the probe died silently), so its numbers must match hand-computed
matmul/conv FLOPs exactly.
"""

import jax
import jax.numpy as jnp
import pytest

from bigdl_tpu.utils.flops import fn_flops


def test_matmul_flops():
    def f(a, b):
        return a @ b
    got = fn_flops(f, jnp.zeros((128, 256)), jnp.zeros((256, 64)))
    assert got == 2 * 128 * 256 * 64


def test_batched_dot_general_flops():
    def f(a, b):
        return jnp.einsum("bij,bjk->bik", a, b)
    got = fn_flops(f, jnp.zeros((4, 8, 16)), jnp.zeros((4, 16, 32)))
    assert got == 2 * 4 * 8 * 16 * 32


def test_scan_multiplies_by_length():
    def f(x, w):
        def body(c, _):
            return c @ w, ()
        y, _ = jax.lax.scan(body, x, None, length=7)
        return y
    got = fn_flops(f, jnp.zeros((32, 32)), jnp.zeros((32, 32)))
    assert got == 7 * 2 * 32 ** 3


def test_conv_flops():
    def f(x, k):
        return jax.lax.conv_general_dilated(
            x, k, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"))
    # out 2x16x16x4; per output element: 3*3*8 MACs
    got = fn_flops(f, jnp.zeros((2, 16, 16, 8)), jnp.zeros((3, 3, 8, 4)))
    assert got == 2 * (2 * 16 * 16 * 4) * (3 * 3 * 8)


def test_grouped_conv_divides_by_groups():
    def f(x, k):
        return jax.lax.conv_general_dilated(
            x, k, (1, 1), "SAME", feature_group_count=4,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
    got = fn_flops(f, jnp.zeros((1, 8, 8, 16)), jnp.zeros((3, 3, 4, 16)))
    assert got == 2 * (1 * 8 * 8 * 16) * (3 * 3 * 4)


def test_grad_counts_backward_matmuls():
    def f(a, b):
        return jax.value_and_grad(lambda a: jnp.sum(a @ b))(a)
    got = fn_flops(f, jnp.zeros((64, 64)), jnp.zeros((64, 64)))
    # forward a@b plus one backward matmul (cotangent @ b.T)
    assert got == 2 * 2 * 64 ** 3


def test_jitted_fn_recurses_into_pjit():
    @jax.jit
    def f(a, b):
        return a @ b
    got = fn_flops(f, jnp.zeros((32, 32)), jnp.zeros((32, 32)))
    assert got == 2 * 32 ** 3


def test_cond_takes_max_branch():
    def f(x, w):
        return jax.lax.cond(True, lambda: x @ w @ w, lambda: x @ w)
    got = fn_flops(f, jnp.zeros((16, 16)), jnp.zeros((16, 16)))
    assert got == 2 * 2 * 16 ** 3  # expensive branch: two matmuls


def test_elementwise_is_free():
    def f(x):
        return jnp.tanh(x) + x * 2.0
    assert fn_flops(f, jnp.zeros((128, 128))) == 0.0


def test_model_train_step_flops_sane():
    """LeNet's analytic step FLOPs: dominated by conv/fc, must be within the
    right order of magnitude (value asserted against an independent
    hand-count of the conv layers)."""
    from bigdl_tpu.models.lenet import LeNet5
    from bigdl_tpu.nn import ClassNLLCriterion

    model = LeNet5(10)
    model.build(jax.random.key(0))
    crit = ClassNLLCriterion()
    x = jnp.zeros((8, 28, 28, 1), jnp.float32)
    t = jnp.ones((8,), jnp.int32)

    def step(params, x, t):
        def loss_fn(p):
            out, _ = model.apply(p, model.state, x, training=True,
                                 rng=jax.random.key(1))
            return crit.loss(out, t)
        return jax.value_and_grad(loss_fn)(params)

    got = fn_flops(step, model.params, x, t)
    # forward conv1 (24x24x6 out, 5x5x1 kernel) at batch 8:
    fwd_conv1 = 2 * (8 * 24 * 24 * 6) * (5 * 5 * 1)
    assert got > fwd_conv1          # counts more than one layer
    assert got < 1e12               # and is not absurd for batch-8 LeNet
