"""Online inference serving (bigdl_tpu/serve): dynamic batching, replica
pool, deadline-aware load shedding, hot model swap.

The serving contract under test (docs/serving.md):
  - concurrent single requests coalesce into strictly fewer padded
    fixed-shape device batches, bit-identical to bulk Predictor.predict;
  - bounded queue -> typed ServerOverloaded at admission; per-request
    deadlines -> typed RequestTimeout at dequeue;
  - hot swap mid-traffic: zero dropped, zero misrouted requests;
  - chaos serve.batch faults surface as typed per-request errors;
  - a stalled replica trips its supervisor channel (crash report);
  - graceful shutdown leaks no threads.
"""

import glob
import json
import os
import threading
import time

import numpy as np
import jax
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu import Engine
from bigdl_tpu.optim import Predictor
from bigdl_tpu.serve import (DynamicBatcher, InferenceServer,
                             RequestTimeout, ServerClosed,
                             ServerOverloaded, default_buckets, pad_rows,
                             predict_in_fixed_batches)
from bigdl_tpu.utils import chaos
from bigdl_tpu.utils.supervisor import StallError, Supervisor


def _linear_model(seed=0, din=4, dout=3):
    return nn.Sequential().add(nn.Linear(din, dout)).build(
        jax.random.key(seed))


def _rows(n, din=4, seed=0):
    return np.random.default_rng(seed).normal(size=(n, din)) \
        .astype(np.float32)


# ---------------------------------------------------------------- batcher


def test_default_buckets_ladder():
    assert default_buckets(8) == (1, 2, 4, 8)
    assert default_buckets(12) == (1, 2, 4, 8, 12)
    assert default_buckets(1) == (1,)


def test_pad_rows_shared_padding():
    x = _rows(3)
    padded = pad_rows(x, 8)
    assert padded.shape == (8, 4)
    np.testing.assert_array_equal(padded[:3], x)
    np.testing.assert_array_equal(padded[3:], np.repeat(x[-1:], 5, axis=0))
    assert pad_rows(x, 3) is x  # full chunk untouched


def test_predict_in_fixed_batches_never_shows_new_shapes():
    """The shared bulk chunker: every forward sees exactly batch_size
    rows; outputs concatenate to the unpadded answer."""
    seen = []

    def forward(chunk):
        seen.append(len(chunk))
        return chunk * 2.0

    x = _rows(10)
    out = predict_in_fixed_batches(forward, x, 4)
    assert seen == [4, 4, 4]
    np.testing.assert_array_equal(out, x * 2.0)


def test_predict_in_fixed_batches_empty_input():
    """Zero-row feats never reach the forward and come back zero-row —
    the helper is public (__all__) and must be safe without the caller
    guarding the empty case first."""
    def forward(chunk):  # pragma: no cover — must not run
        raise AssertionError("forward called for empty feats")

    out = predict_in_fixed_batches(forward, _rows(0), 4)
    assert out.shape == (0, 4)


def test_batcher_deadline_shed_at_dequeue_counts():
    clock_box = [0.0]
    b = DynamicBatcher(max_batch=4, max_wait_s=0.0, queue_limit=8,
                       clock=lambda: clock_box[0])
    ok = b.submit(_rows(1)[0])
    late = b.submit(_rows(1)[0], deadline=5.0)
    clock_box[0] = 10.0  # both dequeue now; only `late` had a deadline
    live = b.collect()
    assert live == [ok]
    with pytest.raises(RequestTimeout):
        late.result(0)
    assert b.stats()["shed_timeout"] == 1


# ------------------------------------------------------------ acceptance


def test_coalescing_bit_identical_and_swap_mid_traffic(tmp_path):
    """Tier-1 acceptance: N concurrent single-sample requests are
    answered in strictly fewer than N device batches, bit-identical to
    per-sample Predictor.predict; a hot swap under sustained traffic
    completes with zero dropped and zero misrouted requests."""
    Engine.init()
    model_a = _linear_model(seed=0)
    model_b = _linear_model(seed=9)
    n = 32
    x = _rows(n)
    # per-sample bulk references for BOTH versions (bit-identity oracle)
    ref_a = np.stack([Predictor(model_a).predict(x[i:i + 1])[0]
                      for i in range(n)])
    ref_b = np.stack([Predictor(model_b).predict(x[i:i + 1])[0]
                      for i in range(n)])

    server = InferenceServer(model_a, max_batch=8, max_wait_ms=30,
                             queue_limit=2 * n, example=x[0]).start()
    results = {}
    lock = threading.Lock()

    def client(i):
        h = server.submit(x[i])
        with lock:
            results[i] = (h.result(30), h)

    # phase 1: pure coalescing on version 1
    threads = [threading.Thread(target=client, args=(i,))
               for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stats = server.stats()
    assert stats["batches"] < n, f"no coalescing: {stats}"
    assert stats["batch_rows"] == n
    for i in range(n):
        out, h = results[i]
        np.testing.assert_array_equal(out, ref_a[i])  # bit-identical
        assert h.version == 1

    # phase 2: hot swap during sustained traffic
    results.clear()
    stop_swap = threading.Event()

    def swapper():
        time.sleep(0.005)
        server.swap(model_b)
        stop_swap.set()

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(n)]
    sw = threading.Thread(target=swapper)
    sw.start()
    for t in threads:
        t.start()
        time.sleep(0.001)  # sustained trickle spanning the swap
    for t in threads:
        t.join()
    sw.join()
    stats = server.stats()
    assert stats["swaps"] == 1 and stats["version"] == 2
    assert len(results) == n  # zero dropped
    routed_new = 0
    for i in range(n):
        out, h = results[i]
        # zero misrouted: every answer is exactly one version's answer,
        # and the handle's version tag matches it
        if h.version == 2:
            np.testing.assert_array_equal(out, ref_b[i])
            routed_new += 1
        else:
            assert h.version == 1
            np.testing.assert_array_equal(out, ref_a[i])
    # after the swap the server answers only with the new version
    post = server.submit(x[0])
    np.testing.assert_array_equal(post.result(30), ref_b[0])
    assert post.version == 2
    server.stop()
    assert server.stats()["shed_overload"] == 0
    assert server.stats()["shed_timeout"] == 0


# ------------------------------------------------------------- shedding


def test_overload_typed_rejection_at_admission():
    Engine.init()
    server = InferenceServer(_linear_model(), max_batch=2, queue_limit=3)
    handles = [server.submit(_rows(1)[0]) for _ in range(3)]
    with pytest.raises(ServerOverloaded):
        server.submit(_rows(1)[0])
    assert server.stats()["shed_overload"] == 1
    server.start()  # the queued three still get answered
    for h in handles:
        assert h.result(30).shape == (3,)
    server.stop()


def test_deadline_timeout_typed_rejection():
    """Requests whose deadline passes while queued are shed with
    RequestTimeout and never reach the device."""
    Engine.init()
    server = InferenceServer(_linear_model(), max_batch=4, queue_limit=8)
    expired = [server.submit(_rows(1)[0], deadline_ms=1) for _ in range(3)]
    fresh = server.submit(_rows(1)[0])  # no deadline
    time.sleep(0.05)
    server.start()
    for h in expired:
        with pytest.raises(RequestTimeout):
            h.result(30)
    assert fresh.result(30).shape == (3,)
    stats = server.stats()
    assert stats["shed_timeout"] == 3
    assert stats["batch_rows"] == 1  # shed requests never hit the device
    server.stop()


def test_submit_shape_mismatch_typed_rejection():
    """A sample whose shape differs from the server's example is rejected
    typed at admission — it must never reach np.stack inside a coalesced
    batch where the failure would hit its batch-mates."""
    from bigdl_tpu.serve import ServeError

    Engine.init()
    with InferenceServer(_linear_model(), max_wait_ms=2,
                         example=_rows(1)[0]) as server:
        with pytest.raises(ServeError):
            server.submit(np.zeros((7,), np.float32))
        # the server keeps serving well-shaped traffic
        assert server.predict(_rows(1)[0], timeout=30).shape == (3,)


def test_stray_payload_fails_batch_typed_replica_survives():
    """A shape stray that defeats admission checks (here: enqueued via
    the batcher directly) fails ITS batch with a typed per-request error;
    the replica thread and the server survive."""
    Engine.init()
    server = InferenceServer(_linear_model(), max_batch=4, max_wait_ms=2,
                             example=_rows(1)[0])
    # both queued BEFORE start -> they coalesce into one batch
    good = server.batcher.submit(_rows(1)[0])
    bad = server.batcher.submit(np.zeros((7,), np.float32))
    server.start()
    with pytest.raises(ValueError):
        bad.result(30)
    with pytest.raises(ValueError):
        good.result(30)  # same batch: fails loudly, not a hang
    assert server.stats()["batch_errors"] == 1
    # the replica is still alive and answering
    assert server.predict(_rows(1)[0], timeout=30).shape == (3,)
    server.stop()


def test_graceful_drain_vs_hard_close():
    Engine.init()
    # graceful: queued requests are answered before workers exit
    server = InferenceServer(_linear_model(), queue_limit=8)
    hs = [server.submit(_rows(1)[0]) for _ in range(4)]
    server.start()
    server.stop(drain=True)
    for h in hs:
        assert h.result(1).shape == (3,)
    with pytest.raises(ServerClosed):
        server.submit(_rows(1)[0])
    # hard close: queued requests fail typed
    server = InferenceServer(_linear_model(), queue_limit=8)
    h = server.submit(_rows(1)[0])
    server.stop(drain=False)
    with pytest.raises(ServerClosed):
        h.result(1)


def test_shutdown_no_thread_leak():
    Engine.init()
    base = threading.active_count()
    server = InferenceServer(_linear_model(), replicas=3,
                             stall_seconds=5.0).start()
    assert server.predict(_rows(1)[0], timeout=30).shape == (3,)
    assert threading.active_count() > base
    server.stop()
    deadline = time.time() + 5
    while threading.active_count() > base and time.time() < deadline:
        time.sleep(0.01)
    assert threading.active_count() == base


# ------------------------------------------------------- chaos + stalls


def test_chaos_serve_batch_fault_is_typed_per_request():
    """An injected serve.batch fault fails exactly that batch's requests
    with the typed ChaosFault; the replica and later requests survive."""
    Engine.init()
    with chaos.scoped("serve.batch=fail@1"):
        with InferenceServer(_linear_model(), max_batch=4,
                             max_wait_ms=2) as server:
            h = server.submit(_rows(1)[0])
            with pytest.raises(chaos.ChaosFault):
                h.result(30)
            # the server is still serving
            assert server.predict(_rows(1)[0], timeout=30).shape == (3,)
            stats = server.stats()
            assert stats["batch_errors"] == 1 and stats["batches"] == 1


def test_chaos_serve_request_admission_fault():
    Engine.init()
    with chaos.scoped("serve.request=fail@2"):
        server = InferenceServer(_linear_model(), queue_limit=8)
        server.submit(_rows(1)[0])
        with pytest.raises(chaos.ChaosFault):
            server.submit(_rows(1)[0])
        server.stop(drain=False)


def test_stalled_replica_trips_supervisor_channel(tmp_path):
    """A replica wedged mid-batch (chaos stall) misses its 'serve'
    deadline: the supervisor writes a crash report naming the replica
    channel and async-raises StallError — the batch fails typed, the
    pool keeps serving."""
    Engine.init()
    sup = Supervisor({"serve": 0.3}, report_dir=str(tmp_path)).start()
    try:
        with chaos.scoped("serve.batch=stall*5@1"):
            with InferenceServer(_linear_model(), max_batch=4,
                                 max_wait_ms=2,
                                 supervisor=sup) as server:
                h = server.submit(_rows(1)[0])
                with pytest.raises(StallError):
                    h.result(30)
                reports = sorted(glob.glob(
                    os.path.join(str(tmp_path), "crash_report*.json")))
                assert reports, "supervisor wrote no crash report"
                with open(reports[0]) as f:
                    rep = json.load(f)
                assert rep["phase"] == "serve"
                assert any(k.startswith("serve-replica-0")
                           for k in rep["channels"]), rep["channels"]
                # the replica recovered: it still answers
                assert server.predict(_rows(1)[0],
                                      timeout=30).shape == (3,)
    finally:
        sup.stop()


# ------------------------------------------------------------- hot swap


def test_swap_from_checkpoint_lineage(tmp_path):
    """swap(dir) loads the NEWEST lineage snapshot through file_io
    (CRC-verified) and serves its params."""
    from bigdl_tpu.utils import file_io

    Engine.init()
    model = _linear_model(seed=0)
    new = _linear_model(seed=5)
    blob_np = jax.tree.map(np.asarray, new.params)
    # two snapshots: the newest (neval 7) must win
    file_io.save_checkpoint(str(tmp_path), 3,
                            {"params": jax.tree.map(np.asarray,
                                                    model.params),
                             "state": model.state}, {"method": {}})
    file_io.save_checkpoint(str(tmp_path), 7,
                            {"params": blob_np, "state": new.state},
                            {"method": {}})
    x = _rows(2)
    with InferenceServer(model, max_wait_ms=2, example=x[0]) as server:
        vid = server.swap(str(tmp_path))
        assert vid == 2
        assert "@7" in server.stats()["version_label"]
        out = server.predict(x[0], timeout=30)
        np.testing.assert_array_equal(out,
                                      Predictor(new).predict(x[:1])[0])


def test_swap_quantized_parity(tmp_path):
    """The swap path composes with quantize(): int8 replica answers agree
    with the float replica within the tolerance test_quantize.py pins for
    quantized logits (max abs < 0.15), and the int8 weights really are
    int8."""
    import jax.numpy as jnp

    from bigdl_tpu.models.lenet import LeNet5
    from bigdl_tpu.utils import file_io

    Engine.init()
    model = LeNet5(10).build(jax.random.key(0))
    file_io.save_checkpoint(
        str(tmp_path), 1,
        {"params": jax.tree.map(np.asarray, model.params),
         "state": model.state}, {"method": {}})
    x = np.random.default_rng(3).normal(size=(28, 28, 1)) \
        .astype(np.float32)
    with InferenceServer(model, max_wait_ms=2, example=x) as server:
        y_f = server.predict(x, timeout=60)
        server.swap(str(tmp_path), quantized=True)
        assert "+int8" in server.stats()["version_label"]
        q_leaves = jax.tree.leaves(server.version.module.params)
        assert any(l.dtype == jnp.int8 for l in q_leaves)
        y_q = server.predict(x, timeout=60)
    assert y_q.shape == y_f.shape
    assert float(np.max(np.abs(y_q - y_f))) < 0.15
    assert int(np.argmax(y_q)) == int(np.argmax(y_f))


def test_swap_build_does_not_block_data_path():
    """The slow half of swap() (checkpoint load / quantize / engine /
    warmup) must not hold the lock the replicas' stats updates take:
    while a swap is stuck in _load_module, predict() still answers."""
    Engine.init()
    x = _rows(2)
    with InferenceServer(_linear_model(seed=0), max_wait_ms=2,
                         example=x[0]) as server:
        gate = threading.Event()
        entered = threading.Event()
        orig = server._load_module

        def slow_load(source, state):
            entered.set()
            assert gate.wait(30), "test gate never opened"
            return orig(source, state)

        server._load_module = slow_load
        sw = threading.Thread(target=server.swap,
                              args=(_linear_model(seed=9),))
        sw.start()
        try:
            assert entered.wait(30)
            # swap is mid-build and holding its own lock — traffic and
            # stats() must proceed, not pause until the build finishes
            assert server.predict(x[0], timeout=30).shape == (3,)
            assert server.stats()["swaps"] == 0
        finally:
            gate.set()
            sw.join(30)
        assert server.stats()["swaps"] == 1
        assert server.stats()["version"] == 2


def test_swap_module_file(tmp_path):
    """swap() also accepts a Module.save file (bigdl_tpu-module-v1)."""
    Engine.init()
    new = _linear_model(seed=11)
    path = str(tmp_path / "model.bin")
    new.save(path)
    x = _rows(1)
    with InferenceServer(_linear_model(seed=0), max_wait_ms=2,
                         example=x[0]) as server:
        server.swap(path)
        np.testing.assert_array_equal(
            server.predict(x[0], timeout=30),
            Predictor(new).predict(x[:1])[0])


# ------------------------------------------------------ http front end


def test_http_front_end_roundtrip():
    """tools/serve_http.py: a real request path over the batcher —
    predict (single + batch), stats, health, typed error mapping."""
    import sys
    import urllib.error
    import urllib.request

    tools_dir = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools")
    if tools_dir not in sys.path:
        sys.path.insert(0, tools_dir)
    import serve_http

    Engine.init()
    model = _linear_model()
    server = InferenceServer(model, max_wait_ms=5,
                             example=np.zeros((4,), np.float32)).start()
    httpd = serve_http.serve_forever(server, "127.0.0.1", 0)
    port = httpd.server_address[1]
    base = f"http://127.0.0.1:{port}"

    def post(path, obj):
        req = urllib.request.Request(base + path,
                                     data=json.dumps(obj).encode(),
                                     method="POST")
        try:
            with urllib.request.urlopen(req, timeout=30) as r:
                return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    try:
        with urllib.request.urlopen(base + "/healthz", timeout=30) as r:
            assert json.loads(r.read())["ok"] is True
        x = _rows(3)
        status, body = post("/v1/predict", {"inputs": x[0].tolist()})
        assert status == 200
        np.testing.assert_allclose(
            np.asarray(body["outputs"], np.float32),
            Predictor(model).predict(x[:1])[0], rtol=1e-5)
        status, body = post("/v1/predict", {"inputs": x.tolist()})
        assert status == 200 and np.asarray(body["outputs"]).shape == (3, 3)
        status, body = post("/v1/predict", {})
        assert status == 400
        status, body = post("/v1/swap", {"source": "/does/not/exist"})
        assert status == 500 and "type" in body
        with urllib.request.urlopen(base + "/v1/stats", timeout=30) as r:
            stats = json.loads(r.read())
        assert stats["batches"] >= 2
    finally:
        httpd.shutdown()
        server.stop()


# ----------------------------------------------------------- bench mode


def test_bench_serve_mode_record():
    """bench.py --serve produces the serving record (closed+open loop +
    bursty traffic storm, percentiles, shed accounting by priority
    class) — tiny config on the test mesh."""
    import bench

    Engine.init()

    def builder():
        return _linear_model(), np.zeros((4,), np.float32)

    rec = bench._serve_bench(clients=3, requests=18, model_builder=builder)
    assert rec["metric"] == "serve_requests_per_sec"
    assert rec["value"] > 0
    closed, open_loop = rec["closed_loop"], rec["open_loop"]
    assert closed["requests"] == 18 and not closed["errors"]
    assert closed["batches"] < closed["requests"]  # coalescing in bench
    for k in ("p50_ms", "p95_ms", "p99_ms"):
        assert closed[k] is not None
    assert 0.0 <= open_loop["shed_rate"] <= 1.0
    # real failures get their OWN bucket (never lumped into shed) and
    # the four buckets partition the offered load exactly
    assert open_loop["errors"] == 0
    assert open_loop["served"] + open_loop["shed_overload"] + \
        open_loop["shed_timeout"] + open_loop["errors"] == \
        open_loop["offered"]
    # traffic storm: bursty load over three priority classes, shed rate
    # reported per class (the priority-aware-admission measurement)
    storm = rec["storm"]
    assert set(storm["by_priority"]) == {"0", "1", "2"}
    assert storm["offered"] == sum(v["offered"] for v in
                                   storm["by_priority"].values())
    assert 0.0 <= storm["shed_rate"] <= 1.0
    assert storm["errors"] == 0
    for v in storm["by_priority"].values():
        assert v["offered"] == (v["served"] + v["shed_overload"] +
                                v["shed_timeout"] + v["errors"])
        assert 0.0 <= v["shed_rate"] <= 1.0


# ------------------------------------------- restart x AOT warm start


def test_replica_restart_rewarms_ladder_from_aot_cache(tmp_path,
                                                       monkeypatch):
    """A respawned replica re-warms its FULL bucket ladder through the
    AOT executable cache: the rebuilt engine performs zero fresh lowers,
    zero misses, zero XLA compiles (pure cache reads), asserted via the
    stats()["aot"] ledger — restart is seconds, not a cold compile.

    The XLA persistent cache is un-latched for the duration (same
    attribution discipline as tools/lenet_cold.py --aot-cache): an
    executable that was itself loaded from the XLA disk cache serializes
    into an unloadable AOT entry on CPU (quarantined + recompiled — the
    system stays correct, but the zero-fresh-lowers ledger would lie)."""
    from jax._src import compilation_cache as _cc

    from bigdl_tpu.utils import aot

    monkeypatch.setenv("BIGDL_TPU_AOT_CACHE", str(tmp_path / "aot"))
    aot.reset()
    prior_xla = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", None)
    _cc.reset_cache()
    try:
        Engine.init()
        x = _rows(4)
        with chaos.scoped("serve.replica@0=exit@1"):
            server = InferenceServer(_linear_model(), max_batch=8,
                                     max_wait_ms=2, queue_limit=32,
                                     example=x[0], replica_lost=0.3,
                                     restart_budget=3,
                                     restart_backoff=0.01).start()
            # startup warmup populated the cache (fresh lowers + stores)
            first = aot.stats()
            assert first["stores"] >= 1 and first["lowers"] >= 1
            # the exit drill kills replica 0 on its first batch; the
            # monitor respawns it on a FRESH engine whose warmup must be
            # pure cache reads
            out = server.predict(x[0], timeout=60)
            assert out.shape == (3,)
            deadline = time.time() + 10
            while server.stats()["restarts"] < 1 and \
                    time.time() < deadline:
                time.sleep(0.05)
            stats = server.stats()
            server.stop()
        assert stats["restarts"] == 1
        ledger = stats["aot"]
        assert ledger["lowers"] == first["lowers"], \
            "restart re-warm performed a fresh lower"
        assert ledger["misses"] == first["misses"], \
            "restart re-warm missed the cache"
        assert ledger["compiles"] == first["compiles"], \
            "restart re-warm compiled"
        assert ledger["hits"] > first["hits"]  # the ladder was cache reads
    finally:
        aot.reset()
        jax.config.update("jax_compilation_cache_dir", prior_xla)
        _cc.reset_cache()
