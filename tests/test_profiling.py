"""Per-module profiling (utils/profiling.py) — the getTimes() analog.

Reference: AbstractModule.scala:193-217 accumulates per-module
forward/backward wall time; getTimes() returns (module, fwd, bwd) triples.
"""

import os

import numpy as np
import jax
import jax.numpy as jnp

import bigdl_tpu.nn as nn
from bigdl_tpu.models.lenet import LeNet5
from bigdl_tpu.utils.profiling import ModuleProfiler, trace_steps


def test_module_profiler_records_leaf_times():
    model = nn.Sequential().add(nn.Linear(32, 64)).add(nn.Tanh()) \
        .add(nn.Linear(64, 8))
    model.build(jax.random.key(0))
    x = jnp.ones((16, 32))
    with ModuleProfiler(model) as prof:
        y = model.forward(x)
    assert y.shape == (16, 8)
    times = prof.get_times()
    mods = [m for m, _, _ in times]
    assert model in mods and len(mods) == 4  # container + 3 leaves
    leaf_fwd = [f for m, f, _ in times if not getattr(m, "modules", None)]
    assert all(f > 0 for f in leaf_fwd), times
    leaf_bwd = [b for m, _, b in times if not getattr(m, "modules", None)]
    assert all(b > 0 for b in leaf_bwd), times
    # facade restored: no timing wrapper left in any instance __dict__
    def assert_clean(m):
        assert "apply" not in m.__dict__, m
        for c in getattr(m, "modules", []):
            assert_clean(c)
    assert_clean(model)
    assert model.forward(x).shape == (16, 8)


def test_get_times_parity_accessor():
    model = LeNet5(10).build(jax.random.key(0))
    with ModuleProfiler(model, measure_backward=False):
        model.forward(jnp.zeros((4, 28, 28, 1)))
    triples = model.get_times()
    assert len(triples) > 5  # the whole submodule tree reports
    total_leaf_fwd = sum(f for m, f, _ in triples
                         if not getattr(m, "modules", None))
    assert total_leaf_fwd > 0
    model.reset_times()
    assert all(f == 0.0 for _, f, _ in model.get_times())


def test_profiler_summary_renders():
    model = nn.Sequential().add(nn.Linear(8, 8)).build(jax.random.key(0))
    with ModuleProfiler(model) as prof:
        model.forward(jnp.ones((2, 8)))
    s = prof.summary()
    assert "fwd_ms" in s and "Linear" in s


def test_trace_steps_writes_xplane(tmp_path):
    logdir = str(tmp_path / "trace")

    @jax.jit
    def step(x):
        return (x @ x).sum()

    x = jnp.ones((64, 64))
    out = trace_steps(lambda: step(x), 3, logdir)
    assert out == logdir
    found = []
    for root, _dirs, files in os.walk(logdir):
        found += [f for f in files if f.endswith(".xplane.pb")]
    assert found, f"no xplane trace written under {logdir}"


def test_profiler_with_shared_module_and_backward():
    """Weight-sharing (same instance added twice) and facade backward under
    the profiler: wrappers must restore exactly and vjp tracing must not
    crash the sync hook."""
    shared = nn.Linear(4, 4)
    m = nn.Sequential().add(shared).add(nn.Tanh()).add(shared)
    m.build(jax.random.key(0))
    with ModuleProfiler(m) as prof:
        y = m.forward(jnp.ones((2, 4)))
        gx = m.backward(jnp.ones((2, 4)), jnp.ones_like(y))
    assert gx.shape == (2, 4)
    assert "apply" not in shared.__dict__ and "apply" not in m.__dict__
    # forward after exit is wrapper-free and works
    assert m.forward(jnp.ones((2, 4))).shape == (2, 4)


def test_nested_profilers_restore_in_order():
    """Inner profiler exit must restore the OUTER wrapper, not strip it."""
    m = nn.Sequential().add(nn.Linear(4, 4)).build(jax.random.key(0))
    x = jnp.ones((2, 4))
    with ModuleProfiler(m, measure_backward=False) as outer:
        with ModuleProfiler(m, measure_backward=False) as inner:
            m.forward(x)
        m.forward(x)  # outer wrapper must still observe this call
    assert outer.fwd and inner.fwd
    assert "apply" not in m.__dict__
    assert "apply" not in m.modules[0].__dict__


def test_backward_inside_profiled_region_keeps_concrete_captures():
    """model.backward under the profiler runs apply under jax.vjp tracing;
    recorded captures must stay concrete so backward times are measured."""
    m = nn.Sequential().add(nn.Linear(4, 4)).build(jax.random.key(0))
    x = jnp.ones((2, 4))
    with ModuleProfiler(m) as p:
        y = m.forward(x)
        m.backward(x, jnp.ones_like(y))
    assert p.bwd.get(id(m.modules[0]), 0.0) > 0.0, p.bwd
