"""End-to-end training: LeNet-5 on synthetic MNIST over an 8-device CPU mesh.

Mirrors the reference's DistriOptimizerSpec (SURVEY.md §4): node-count is a
parameter — the same distributed machinery (sharded batch, replicated params,
XLA all-reduce) runs on 8 virtual CPU devices exactly as it would on 8 TPU
chips.
"""

import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu import Engine
from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
from bigdl_tpu.models import LeNet5
from bigdl_tpu.optim import (Adam, SGD, Optimizer, Trigger, Top1Accuracy,
                             Evaluator, Predictor)
from bigdl_tpu.parallel import DataParallel, ShardedDataParallel


def synthetic_mnist(n=512, seed=0):
    """Separable synthetic digits: class k has a bright k-th 2x2 block."""
    rng = np.random.default_rng(seed)
    images = rng.normal(0.0, 0.1, size=(n, 28, 28)).astype(np.float32)
    labels = rng.integers(0, 10, size=n)
    for i, l in enumerate(labels):
        r, c = divmod(int(l), 5)
        images[i, 4 + r * 10: 12 + r * 10, 2 + c * 5: 7 + c * 5] += 1.5
    return [Sample.from_ndarray(images[i], np.int32(labels[i]))
            for i in range(n)]


def make_optimizer(strategy=None, batch_size=64, samples=None):
    model = LeNet5(10)
    ds = DataSet.array(samples or synthetic_mnist()) \
        .transform(SampleToMiniBatch(batch_size, drop_last=True))
    opt = Optimizer(model, ds, nn.ClassNLLCriterion(),
                    strategy=strategy or DataParallel())
    opt.set_optim_method(Adam(learning_rate=1e-3))
    opt.set_end_when(Trigger.max_epoch(3))
    opt.set_log_interval(4)
    return model, opt


def test_lenet_trains_on_8_device_mesh():
    Engine.init()  # all 8 virtual CPU devices on the 'data' axis
    assert Engine.device_count() == 8
    model, opt = make_optimizer()
    opt.optimize()
    # loss must have dropped well below random (ln(10) ~ 2.3)
    assert opt.optim_method.hyper["loss"] < 1.0
    # evaluate
    val = synthetic_mnist(256, seed=1)
    ds = DataSet.array(val)
    results = Evaluator(model).test(ds, [Top1Accuracy()], batch_size=64)
    acc, n = results[0][1].result()
    assert n == 256
    assert acc > 0.8, f"accuracy {acc}"


def test_lenet_sharded_data_parallel():
    Engine.init()
    model, opt = make_optimizer(strategy=ShardedDataParallel(min_size=1),
                                samples=synthetic_mnist(256))
    opt.set_end_when(Trigger.max_epoch(2))
    opt.optimize()
    assert np.isfinite(opt.optim_method.hyper["loss"])


def test_lenet_remat_conv_out():
    """set_remat('conv_out') saves only MXU conv outputs across fwd/bwd
    (nn/conv tags them with checkpoint_name); training must still converge
    identically in expectation — the policy changes the schedule, not math."""
    Engine.init()
    model, opt = make_optimizer()
    opt.set_remat("conv_out")
    opt.optimize()
    assert opt.optim_method.hyper["loss"] < 1.0


def test_checkpoint_and_resume(tmp_path):
    Engine.init()
    model, opt = make_optimizer(samples=synthetic_mnist(128))
    opt.set_end_when(Trigger.max_epoch(1))
    opt.set_checkpoint(str(tmp_path), Trigger.several_iteration(1))
    opt.optimize()
    from bigdl_tpu.utils import file_io
    latest = file_io.latest_checkpoint(str(tmp_path))
    assert latest is not None
    blob = file_io.load(latest[0])
    assert "params" in blob and "state" in blob
    # weights roundtrip
    w0 = jax.tree.leaves(blob["params"])[0]
    assert np.all(np.isfinite(np.asarray(w0)))


def test_predictor():
    Engine.init()
    model = LeNet5(10).build()
    pred = Predictor(model, batch_size=32)
    x = np.random.default_rng(0).normal(size=(50, 28, 28)).astype(np.float32)
    ds = DataSet.array([Sample.from_ndarray(x[i]) for i in range(50)])
    probs = pred.predict(ds)
    assert probs.shape == (50, 10)
    classes = pred.predict_class(ds)
    assert classes.shape == (50,) and classes.min() >= 0 and classes.max() < 10


def test_validation_during_training():
    Engine.init()
    samples = synthetic_mnist(256)
    model, opt = make_optimizer(samples=samples)
    opt.set_end_when(Trigger.max_epoch(2))
    val_ds = DataSet.array(synthetic_mnist(128, seed=2))
    opt.set_validation(Trigger.every_epoch(), val_ds, [Top1Accuracy()],
                       batch_size=64)
    opt.optimize()
    assert "score" in opt.optim_method.hyper


def test_lr_schedule_advances_during_training():
    """Regression: driver state must feed evalCounter to the schedule family."""
    from bigdl_tpu.optim import Step
    Engine.init()
    samples = synthetic_mnist(128)
    model = LeNet5(10)
    ds = DataSet.array(samples).transform(SampleToMiniBatch(32, drop_last=True))
    opt = Optimizer(model, ds, nn.ClassNLLCriterion())
    sgd = SGD(learning_rate=0.1, learning_rate_schedule=Step(2, 0.5))
    opt.set_optim_method(sgd)
    opt.set_end_when(Trigger.max_iteration(5))
    opt.optimize()
    # after 5 iterations (evalCounter=5) lr must have decayed 0.1 * 0.5^2
    lr = sgd.get_learning_rate(sgd.hyper)
    assert abs(lr - 0.1 * 0.25) < 1e-9, lr


def test_gradient_accumulation_matches_full_batch():
    """set_gradient_accumulation(4): microbatched grads averaged inside the
    step must reproduce the full-batch trajectory on an rng-free model
    (differences are float reassociation only)."""
    from bigdl_tpu.common import set_seed

    Engine.init()
    samples = synthetic_mnist(256)

    def train(accum):
        set_seed(5)
        model, opt = make_optimizer(batch_size=64, samples=samples)
        opt.set_optim_method(SGD(learning_rate=0.05))
        opt.set_end_when(Trigger.max_epoch(1))
        if accum > 1:
            opt.set_gradient_accumulation(accum)
        opt.optimize()
        return jax.tree.leaves(jax.tree.map(np.asarray, model.params))

    base, acc = train(1), train(4)
    for a, b in zip(base, acc):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5)


def test_gradient_accumulation_indivisible_batch_rejected():
    Engine.init()
    model, opt = make_optimizer(batch_size=64)
    opt.set_gradient_accumulation(7)  # 64 % 7 != 0
    with pytest.raises(ValueError, match="not divisible"):
        opt.optimize()


def test_gradient_accumulation_with_remat_and_bn():
    """accumulation composes with remat and BN state threading (each
    microbatch normalizes with its own stats; running stats advance)."""
    Engine.init()
    model = nn.Sequential() \
        .add(nn.Reshape((28, 28, 1))) \
        .add(nn.SpatialConvolution(1, 4, 3, 3, 1, 1, -1, -1)) \
        .add(nn.SpatialBatchNormalization(4)) \
        .add(nn.ReLU()) \
        .add(nn.Reshape((28 * 28 * 4,))) \
        .add(nn.Linear(28 * 28 * 4, 10)) \
        .add(nn.LogSoftMax())
    ds = DataSet.array(synthetic_mnist(256)).transform(
        SampleToMiniBatch(64, drop_last=True))
    opt = (Optimizer(model, ds, nn.ClassNLLCriterion())
           .set_optim_method(Adam(learning_rate=1e-3))
           .set_end_when(Trigger.max_epoch(3))
           .set_gradient_accumulation(4)
           .set_remat("conv_out"))
    opt.optimize()
    assert opt.optim_method.hyper["loss"] < 1.0
    # BN running stats advanced through the scan
    rm = np.asarray(jax.tree.leaves(model.state)[0])
    assert np.abs(rm).sum() > 0
