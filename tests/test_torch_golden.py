"""Golden-parity tests against pytorch (CPU) as an independent oracle.

This is the rebuild's analog of the reference's Torch7-golden suite — the
correctness backbone of its nn library (SURVEY.md §4: 122 specs under
test/.../torch/ shell out to a real `th` and compare numerics).  pytorch
implements the same Torch lineage semantics, is present in this image, and
shares no code with bigdl_tpu, so agreement here is genuine cross-
implementation evidence (unlike numpy goldens written next to the layer).

Layout notes: bigdl_tpu is NHWC/HWIO + 0-based; torch is NCHW/OIHW.  Each
test permutes explicitly.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import bigdl_tpu.nn as nn

torch = pytest.importorskip("torch")


def rng():
    return jax.random.key(0)


def _np(x, seed=0, scale=1.0):
    return (np.random.default_rng(seed).normal(size=x) * scale
            ).astype(np.float32)


def _t(a):
    return torch.tensor(np.asarray(a))


def test_spatial_convolution_matches_torch_conv2d():
    m = nn.SpatialConvolution(3, 8, 5, 3, 2, 1, 2, 1).build(rng())
    # ours: kernel_w=5 kernel_h=3 stride_w=2 stride_h=1 pad_w=2 pad_h=1
    x = _np((2, 9, 11, 3), 1)          # NHWC
    y = np.asarray(m.forward(jnp.asarray(x)))
    w = np.asarray(m.params["weight"])  # (kh, kw, in, out)
    b = np.asarray(m.params["bias"])
    conv = torch.nn.Conv2d(3, 8, kernel_size=(3, 5), stride=(1, 2),
                           padding=(1, 2))
    with torch.no_grad():
        conv.weight.copy_(_t(w.transpose(3, 2, 0, 1)))  # OIHW
        conv.bias.copy_(_t(b))
        ref = conv(_t(x.transpose(0, 3, 1, 2))).numpy()  # NCHW
    np.testing.assert_allclose(y.transpose(0, 3, 1, 2), ref,
                               rtol=1e-4, atol=1e-4)


def test_dilated_convolution_matches_torch():
    m = nn.SpatialDilatedConvolution(2, 4, 3, 3, 1, 1, 2, 2,
                                     dilation_w=2, dilation_h=2).build(rng())
    x = _np((1, 10, 10, 2), 2)
    y = np.asarray(m.forward(jnp.asarray(x)))
    w = np.asarray(m.params["weight"])
    b = np.asarray(m.params["bias"])
    conv = torch.nn.Conv2d(2, 4, 3, stride=1, padding=2, dilation=2)
    with torch.no_grad():
        conv.weight.copy_(_t(w.transpose(3, 2, 0, 1)))
        conv.bias.copy_(_t(b))
        ref = conv(_t(x.transpose(0, 3, 1, 2))).numpy()
    np.testing.assert_allclose(y.transpose(0, 3, 1, 2), ref,
                               rtol=1e-4, atol=1e-4)


def test_full_convolution_matches_torch_conv_transpose():
    m = nn.SpatialFullConvolution(3, 5, 4, 4, 2, 2, 1, 1).build(rng())
    x = _np((2, 6, 6, 3), 3)
    y = np.asarray(m.forward(jnp.asarray(x)))
    w = np.asarray(m.params["weight"])  # ours: (kh, kw, in, out)
    b = np.asarray(m.params["bias"])
    deconv = torch.nn.ConvTranspose2d(3, 5, 4, stride=2, padding=1)
    with torch.no_grad():
        # torch ConvTranspose2d weight: (in, out, kh, kw)
        deconv.weight.copy_(_t(w.transpose(2, 3, 0, 1)))
        deconv.bias.copy_(_t(b))
        ref = deconv(_t(x.transpose(0, 3, 1, 2))).numpy()
    np.testing.assert_allclose(y.transpose(0, 3, 1, 2), ref,
                               rtol=1e-4, atol=1e-4)


def test_volumetric_convolution_matches_torch_conv3d():
    m = nn.VolumetricConvolution(2, 3, 3, 3, 3, 1, 1, 1, 1, 1, 1).build(rng())
    x = _np((1, 6, 7, 7, 2), 4)        # NDHWC
    y = np.asarray(m.forward(jnp.asarray(x)))
    w = np.asarray(m.params["weight"])  # (kd, kh, kw, in, out)
    b = np.asarray(m.params["bias"])
    conv = torch.nn.Conv3d(2, 3, 3, stride=1, padding=1)
    with torch.no_grad():
        conv.weight.copy_(_t(w.transpose(4, 3, 0, 1, 2)))  # (out,in,d,h,w)
        conv.bias.copy_(_t(b))
        ref = conv(_t(x.transpose(0, 4, 1, 2, 3))).numpy()
    np.testing.assert_allclose(y.transpose(0, 4, 1, 2, 3), ref,
                               rtol=1e-4, atol=1e-4)


def test_batch_norm_training_and_eval_match_torch():
    m = nn.SpatialBatchNormalization(6, eps=1e-5, momentum=0.1).build(rng())
    bn = torch.nn.BatchNorm2d(6, eps=1e-5, momentum=0.1)
    with torch.no_grad():
        bn.weight.copy_(_t(np.asarray(m.params["weight"])))
        bn.bias.copy_(_t(np.asarray(m.params["bias"])))
    x = _np((4, 5, 5, 6), 5)
    xt = _t(x.transpose(0, 3, 1, 2))

    # training step: outputs + running-stat updates must agree
    out, new_state = m.apply(m.params, m.state, jnp.asarray(x),
                             training=True, rng=jax.random.key(1))
    bn.train()
    ref = bn(xt).detach().numpy()
    np.testing.assert_allclose(np.asarray(out).transpose(0, 3, 1, 2), ref,
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(
        np.asarray(jax.tree.leaves(new_state)[0]).ravel().sort() if False
        else np.sort(np.asarray(new_state["running_mean"]).ravel()),
        np.sort(bn.running_mean.numpy()), rtol=1e-4, atol=1e-5)

    # eval: uses running stats
    m.attach(m.params, new_state)
    m.evaluate()
    out_e = np.asarray(m.forward(jnp.asarray(x)))
    bn.eval()
    ref_e = bn(xt).detach().numpy()
    np.testing.assert_allclose(out_e.transpose(0, 3, 1, 2), ref_e,
                               rtol=1e-3, atol=1e-4)


def test_pooling_matches_torch():
    x = _np((2, 8, 8, 3), 6)
    xt = _t(x.transpose(0, 3, 1, 2))
    ym = np.asarray(nn.SpatialMaxPooling(2, 2, 2, 2).build(rng())
                    .forward(jnp.asarray(x)))
    ref = torch.nn.MaxPool2d(2, 2)(xt).numpy()
    np.testing.assert_allclose(ym.transpose(0, 3, 1, 2), ref, rtol=1e-6)
    ya = np.asarray(nn.SpatialAveragePooling(3, 3, 2, 2).build(rng())
                    .forward(jnp.asarray(x)))
    ref = torch.nn.AvgPool2d(3, 2)(xt).numpy()
    np.testing.assert_allclose(ya.transpose(0, 3, 1, 2), ref,
                               rtol=1e-5, atol=1e-6)


def test_lstm_matches_torch_cell_loop():
    """Our fused-gate LSTM vs torch.nn.LSTMCell iterated over time.
    Gate order: ours i,f,g,o; torch i,f,g,o as well — weights map directly."""
    H, I, T, B = 7, 5, 4, 3
    m = nn.Recurrent(nn.LSTM(I, H)).build(rng())
    kernel = np.asarray(m.params[0]["kernel"])   # (I+H, 4H)
    bias = np.asarray(m.params[0]["bias"])       # (4H,)
    cell = torch.nn.LSTMCell(I, H)
    with torch.no_grad():
        cell.weight_ih.copy_(_t(kernel[:I].T))   # (4H, I)
        cell.weight_hh.copy_(_t(kernel[I:].T))   # (4H, H)
        cell.bias_ih.copy_(_t(bias))
        cell.bias_hh.copy_(torch.zeros(4 * H))
    x = _np((B, T, I), 7)
    y = np.asarray(m.forward(jnp.asarray(x)))
    h = torch.zeros(B, H)
    c = torch.zeros(B, H)
    outs = []
    with torch.no_grad():
        for t in range(T):
            h, c = cell(_t(x[:, t]), (h, c))
            outs.append(h.numpy())
    ref = np.stack(outs, axis=1)
    np.testing.assert_allclose(y, ref, rtol=1e-4, atol=1e-5)


def test_gru_matches_torch_cell_loop():
    """GRU gate mapping: ours fuses reset/update in one gemm + candidate;
    torch packs (r, z, n).  Verify end-to-end sequence outputs."""
    H, I, T, B = 6, 4, 3, 2
    m = nn.Recurrent(nn.GRU(I, H)).build(rng())
    p = m.params[0]
    gk = np.asarray(p["gate_kernel"])    # (I+H, 2H) -> gates (r?, z?)
    gb = np.asarray(p["gate_bias"])
    ck = np.asarray(p["cand_kernel"])    # (I+H, H)
    cb = np.asarray(p["cand_bias"])
    x = _np((B, T, I), 8)
    y = np.asarray(m.forward(jnp.asarray(x)))

    # reference loop in numpy mirroring the documented semantics:
    # gates = sigmoid([x,h] @ gk + gb) -> split (r, z) order per source
    def ref_loop(r_first=True):
        h = np.zeros((B, H), np.float32)
        outs = []
        for t in range(T):
            z_in = np.concatenate([x[:, t], h], axis=-1)
            gates = 1 / (1 + np.exp(-(z_in @ gk + gb)))
            a, b2 = gates[:, :H], gates[:, H:]
            r, z = (a, b2) if r_first else (b2, a)
            cin = np.concatenate([x[:, t], r * h], axis=-1)
            cand = np.tanh(cin @ ck + cb)
            h = (1 - z) * h + z * cand
            outs.append(h)
        return np.stack(outs, axis=1)

    ok = any(np.allclose(y, ref_loop(rf), rtol=1e-4, atol=1e-5)
             for rf in (True, False))
    assert ok, "GRU disagrees with both gate orderings of the numpy loop"


CRITERION_CASES = [
    ("MSECriterion", lambda: nn.MSECriterion(),
     lambda: torch.nn.MSELoss(), (3, 4), "regression"),
    ("AbsCriterion", lambda: nn.AbsCriterion(),
     lambda: torch.nn.L1Loss(), (3, 4), "regression"),
    ("BCECriterion", lambda: nn.BCECriterion(),
     lambda: torch.nn.BCELoss(), (3, 4), "binary"),
    ("SmoothL1Criterion", lambda: nn.SmoothL1Criterion(),
     lambda: torch.nn.SmoothL1Loss(), (3, 4), "regression"),
    ("DistKLDivCriterion", lambda: nn.DistKLDivCriterion(),
     lambda: torch.nn.KLDivLoss(reduction="batchmean"), (3, 4), "kl"),
]


@pytest.mark.parametrize("name,ours,theirs,shape,kind", CRITERION_CASES,
                         ids=[c[0] for c in CRITERION_CASES])
def test_criterion_matches_torch(name, ours, theirs, shape, kind):
    r = np.random.default_rng(9)
    if kind == "binary":
        out = r.uniform(0.05, 0.95, size=shape).astype(np.float32)
        tgt = r.integers(0, 2, size=shape).astype(np.float32)
    elif kind == "kl":
        logits = r.normal(size=shape).astype(np.float32)
        out = logits - np.log(np.exp(logits).sum(-1, keepdims=True))
        t_raw = r.uniform(0.1, 1.0, size=shape).astype(np.float32)
        tgt = t_raw / t_raw.sum(-1, keepdims=True)
    else:
        out = r.normal(size=shape).astype(np.float32)
        tgt = r.normal(size=shape).astype(np.float32)
    got = float(ours().loss(jnp.asarray(out), jnp.asarray(tgt)))
    expect = float(theirs()(_t(out), _t(tgt)))
    np.testing.assert_allclose(got, expect, rtol=1e-4, atol=1e-6)


def test_class_nll_matches_torch():
    r = np.random.default_rng(10)
    logits = r.normal(size=(4, 6)).astype(np.float32)
    logp = logits - np.log(np.exp(logits).sum(-1, keepdims=True))
    tgt = r.integers(0, 6, size=4)
    got = float(nn.ClassNLLCriterion().loss(jnp.asarray(logp),
                                            jnp.asarray(tgt)))
    expect = float(torch.nn.NLLLoss()(_t(logp), torch.tensor(tgt)))
    np.testing.assert_allclose(got, expect, rtol=1e-5)


def test_cross_entropy_matches_torch():
    r = np.random.default_rng(11)
    logits = r.normal(size=(5, 7)).astype(np.float32)
    tgt = r.integers(0, 7, size=5)
    got = float(nn.CrossEntropyCriterion().loss(jnp.asarray(logits),
                                                jnp.asarray(tgt)))
    expect = float(torch.nn.CrossEntropyLoss()(_t(logits),
                                               torch.tensor(tgt)))
    np.testing.assert_allclose(got, expect, rtol=1e-5)


def test_lrn_matches_torch():
    m = nn.SpatialCrossMapLRN(size=5, alpha=1e-4, beta=0.75, k=1.0)
    m.build(rng())
    x = _np((2, 6, 6, 8), 12, scale=2.0)
    y = np.asarray(m.forward(jnp.asarray(x)))
    ref = torch.nn.LocalResponseNorm(5, alpha=1e-4, beta=0.75, k=1.0)(
        _t(x.transpose(0, 3, 1, 2))).numpy()
    np.testing.assert_allclose(y.transpose(0, 3, 1, 2), ref,
                               rtol=1e-4, atol=1e-5)


def test_activations_match_torch():
    x = _np((4, 5), 13, scale=2.0)
    xt = _t(x)
    pairs = [
        (nn.ELU(), torch.nn.ELU()),
        (nn.LeakyReLU(0.02), torch.nn.LeakyReLU(0.02)),
        (nn.ReLU6(), torch.nn.ReLU6()),
        (nn.SoftPlus(1.0), torch.nn.Softplus()),
        (nn.SoftSign(), torch.nn.Softsign()),
        (nn.HardTanh(), torch.nn.Hardtanh()),
        (nn.LogSoftMax(), torch.nn.LogSoftmax(dim=-1)),
        (nn.Sigmoid(), torch.nn.Sigmoid()),
        (nn.Tanh(), torch.nn.Tanh()),
    ]
    for ours, theirs in pairs:
        got = np.asarray(ours.build(rng()).forward(jnp.asarray(x)))
        expect = theirs(xt).numpy()
        np.testing.assert_allclose(got, expect, rtol=1e-4, atol=1e-5,
                                   err_msg=type(ours).__name__)


def test_embedding_matches_torch():
    m = nn.LookupTable(10, 6).build(rng())
    w = np.asarray(m.params["weight"])
    idx = np.array([[1, 3, 5], [0, 9, 2]])
    y = np.asarray(m.forward(jnp.asarray(idx)))
    emb = torch.nn.Embedding(10, 6)
    with torch.no_grad():
        emb.weight.copy_(_t(w))
        ref = emb(torch.tensor(idx)).numpy()
    np.testing.assert_allclose(y, ref, rtol=1e-6)


def _run_optim_pair(ours_method, torch_opt_fn, steps=25, n=40):
    """Drive our optimizer and torch.optim over IDENTICAL loss/grads
    (deterministic quadratic with rotating data) and compare trajectories —
    the optimizer analog of the layer goldens (reference: optim method
    ports are torch-lineage, optim/SGD.scala:38 etc.)."""
    import jax

    r = np.random.default_rng(3)
    w0 = r.normal(0, 0.5, size=(n,)).astype(np.float32)
    a_all = r.normal(size=(steps, n)).astype(np.float32)

    params = {"w": jnp.asarray(w0)}
    state = ours_method.init_state(params)

    wt = torch.tensor(w0.copy(), requires_grad=True)
    topt = torch_opt_fn([wt])

    for i in range(steps):
        a = a_all[i]
        grads = {"w": jnp.asarray(2 * a * (a * np.asarray(params["w"])))}
        lr = jnp.float32(ours_method.get_learning_rate())
        params, state = ours_method.update(grads, params, state, lr)

        topt.zero_grad()
        loss = ((torch.tensor(a) * wt) ** 2).sum()
        loss.backward()
        topt.step()
    np.testing.assert_allclose(np.asarray(params["w"]),
                               wt.detach().numpy(), rtol=2e-4, atol=2e-5)


def test_sgd_momentum_matches_torch_optim():
    from bigdl_tpu.optim import SGD
    # dampening pinned to 0: the Torch lineage (sgd.lua, SGD.scala) defaults
    # dampening to `momentum`, pytorch defaults it to 0
    _run_optim_pair(
        SGD(learning_rate=0.05, momentum=0.9, weight_decay=1e-3,
            dampening=0.0),
        lambda p: torch.optim.SGD(p, lr=0.05, momentum=0.9,
                                  weight_decay=1e-3))


def test_sgd_nesterov_matches_torch_optim():
    from bigdl_tpu.optim import SGD
    _run_optim_pair(
        SGD(learning_rate=0.03, momentum=0.8, nesterov=True, dampening=0.0),
        lambda p: torch.optim.SGD(p, lr=0.03, momentum=0.8, nesterov=True))


def test_adam_matches_torch_optim():
    from bigdl_tpu.optim import Adam
    _run_optim_pair(
        Adam(learning_rate=0.01),
        lambda p: torch.optim.Adam(p, lr=0.01))


def test_adagrad_matches_torch_optim():
    from bigdl_tpu.optim import Adagrad
    _run_optim_pair(
        Adagrad(learning_rate=0.05),
        lambda p: torch.optim.Adagrad(p, lr=0.05))


def test_rmsprop_matches_torch_optim():
    from bigdl_tpu.optim import RMSprop
    _run_optim_pair(
        RMSprop(learning_rate=0.01, decay_rate=0.9),
        lambda p: torch.optim.RMSprop(p, lr=0.01, alpha=0.9))
