"""Cross-process serving fleet (bigdl_tpu/serve/fleet + fleetfront).

The contract under test (docs/serving.md "Fleet"):
  - member records are CRC-framed: a torn/bit-rotted record reads as
    ABSENT (never half a registration), the newest verified generation
    wins, and the writer sweeps dead generations so a flapping member
    cannot grow the registry forever;
  - condemnation is a monotonic generation bump: records at or below
    the condemned generation are invisible to the registry, so a zombie
    can never attract traffic and a late verdict cannot un-condemn;
  - liveness is heartbeat publication freshness (the elastic-training
    silence rule): a registry record WITHOUT a fresh heartbeat is a
    stale entry, not a member;
  - the supervisor promotes silence into a typed MemberLostError,
    condemns, kills, respawns at generation+1 under backoff, and past
    the restart budget DEGRADES the slot instead of flapping;
  - the front tier routes by the TopologyRouter key over local
    in-flight counts, maps member HTTP rejections back to the typed
    serve exceptions, retries transport failures on the NEXT member
    (idempotent predicts only), and raises MemberLostError — a
    ReplicaLostError, so the HTTP 503 + Retry-After mapping applies —
    when no member is live;
  - DeployController detects a fleet target and fans the release out
    with the max-unavailable bound (rolling fleet mode);
  - THE acceptance drill (tools/fleet_smoke.py): kill -9, a wedged
    zombie, and a stale registry entry in one run, zero accepted loss.
"""

import json
import os
import threading
import time

import numpy as np
import jax
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu import Engine
from bigdl_tpu.optim import Predictor
from bigdl_tpu.serve import (DeployController, FleetFront, FleetSupervisor,
                             InferenceServer, MemberLostError, RequestTimeout,
                             ServeError, ServerOverloaded)
from bigdl_tpu.serve import fleet
from bigdl_tpu.serve.control import ReplicaLostError
from bigdl_tpu.utils import file_io

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _wait(pred, timeout=15.0):
    deadline = time.monotonic() + timeout
    while not pred() and time.monotonic() < deadline:
        time.sleep(0.02)
    assert pred(), "condition not reached in time"


# ------------------------------------------------------------- registry


def test_member_record_roundtrip(tmp_path):
    d = str(tmp_path)
    path = fleet.publish_member(d, index=2, generation=3, pid=4242,
                                port=8011, devices=["cpu:0"],
                                buckets=[1, 2, 4], max_batch=4)
    rec = fleet.read_member(path)
    assert rec["index"] == 2 and rec["generation"] == 3
    assert rec["pid"] == 4242 and rec["port"] == 8011
    assert rec["buckets"] == [1, 2, 4] and rec["max_batch"] == 4
    assert fleet.read_registry(d) == {2: rec}


def test_torn_record_reads_absent(tmp_path):
    """A half-written or bit-rotted record fails the CRC frame and is
    invisible — a consumer can never act on half a registration."""
    d = str(tmp_path)
    good = fleet.publish_member(d, index=0, generation=2, pid=1, port=8000)
    blob = open(good, "rb").read()
    (tmp_path / "member.0.3").write_bytes(blob[: len(blob) // 2])  # torn
    flipped = bytearray(blob)
    flipped[len(blob) // 2] ^= 0xFF
    (tmp_path / "member.0.4").write_bytes(bytes(flipped))  # bit rot
    assert fleet.read_member(str(tmp_path / "member.0.3")) is None
    assert fleet.read_member(str(tmp_path / "member.0.4")) is None
    # the registry falls back to the newest VERIFIED generation
    assert fleet.read_registry(d)[0]["generation"] == 2


def test_registry_newest_generation_wins(tmp_path):
    d = str(tmp_path)
    for gen in (1, 2, 3):
        fleet.publish_member(d, index=0, generation=gen, pid=gen, port=8000)
    assert fleet.read_registry(d)[0]["generation"] == 3


def test_publish_sweeps_dead_generations(tmp_path, monkeypatch):
    monkeypatch.setenv("BIGDL_TPU_FLEET_KEEP_GENERATIONS", "3")
    d = str(tmp_path)
    for gen in range(1, 9):
        fleet.publish_member(d, index=0, generation=gen, pid=gen, port=8000)
    names = sorted(n for n in os.listdir(d) if n.startswith("member."))
    assert names == ["member.0.6", "member.0.7", "member.0.8"]
    # other indices are untouched by this member's sweep
    fleet.publish_member(d, index=1, generation=1, pid=99, port=8001)
    assert (tmp_path / "member.0.8").exists()


def test_condemn_is_monotonic(tmp_path):
    d = str(tmp_path)
    assert fleet.condemned_generation(d, 0) == 0
    fleet.condemn(d, 0, 5)
    assert fleet.condemned_generation(d, 0) == 5
    fleet.condemn(d, 0, 3)  # a LATE verdict for an old generation
    assert fleet.condemned_generation(d, 0) == 5


def test_registry_skips_condemned_generations(tmp_path):
    d = str(tmp_path)
    for gen in (1, 2, 3):
        fleet.publish_member(d, index=0, generation=gen, pid=gen, port=8000)
    fleet.condemn(d, 0, 3)
    assert fleet.read_registry(d) == {}
    fleet.publish_member(d, index=0, generation=4, pid=4, port=8000)
    assert fleet.read_registry(d)[0]["generation"] == 4


def test_member_alive_is_publication_freshness(tmp_path):
    d = str(tmp_path)
    assert not fleet.member_alive(d, 0, lost_after=5.0)  # no heartbeat
    fleet.beat(d, 0, 2, 1, wall_time=1000.0)
    assert fleet.member_alive(d, 0, lost_after=5.0, now=1003.0)
    assert not fleet.member_alive(d, 0, lost_after=5.0, now=1006.0)
    # generation filter: an OLD life's heartbeat does not vouch for a
    # newer one
    assert not fleet.member_alive(d, 0, generation=3, lost_after=5.0,
                                  now=1001.0)
    assert fleet.member_alive(d, 0, generation=2, lost_after=5.0,
                              now=1001.0)


def test_sweep_numbered_retention(tmp_path):
    for i in (1, 3, 5, 7, 9):
        (tmp_path / f"grow.{i}").write_text("x")
    (tmp_path / "grow.2.corrupt").write_text("x")  # quarantine: kept
    (tmp_path / "other.4").write_text("x")
    removed = file_io.sweep_numbered(str(tmp_path), r"grow\.(\d+)", keep=2)
    assert sorted(removed) == ["grow.1", "grow.3", "grow.5"]
    left = sorted(os.listdir(tmp_path))
    assert left == ["grow.2.corrupt", "grow.7", "grow.9", "other.4"]
    # keep<=0 disables the sweep entirely
    assert file_io.sweep_numbered(str(tmp_path), r"grow\.(\d+)",
                                  keep=0) == []
    assert (tmp_path / "grow.7").exists()


def test_grow_offer_sweep_keeps_newest(tmp_path, monkeypatch):
    """elastic's grow-offer files ride the same bounded retention —
    and the sweep never touches the newest offer the scale-up
    negotiation reads."""
    monkeypatch.setenv("BIGDL_TPU_PROTOCOL_KEEP", "2")
    from bigdl_tpu.parallel import elastic
    d = str(tmp_path)
    for epoch in range(1, 6):
        elastic.publish_grow_offer(d, 0, epoch, [0, 1], float(epoch))
    names = sorted(n for n in os.listdir(elastic.elastic_dir(d))
                   if n.startswith("grow."))
    assert names == ["grow.4", "grow.5"]
    assert elastic.latest_grow_epoch(d) == 5


# ----------------------------------------------------------- supervisor


class _FakeProc:
    """A Popen stand-in the supervisor can poll/kill."""

    _pids = iter(range(30000, 40000))

    def __init__(self):
        self.pid = next(self._pids)
        self.returncode = None
        self.killed = False

    def poll(self):
        return self.returncode

    def kill(self):
        self.killed = True
        self.returncode = -9

    def terminate(self):
        self.returncode = -15

    def wait(self, timeout=None):
        return self.returncode


class _FakeMember:
    """A fake worker life: publishes its record, beats on a thread until
    told to go silent (the wedge) or killed."""

    def __init__(self, fleet_dir, index, generation):
        self.proc = _FakeProc()
        self.fleet_dir, self.index, self.generation = \
            fleet_dir, index, generation
        self._silent = threading.Event()
        fleet.publish_member(fleet_dir, index=index, generation=generation,
                             pid=self.proc.pid, port=8000 + index)
        fleet.beat(fleet_dir, index, generation, 0)
        self._thread = threading.Thread(target=self._beat, daemon=True)
        self._thread.start()

    def _beat(self):
        count = 0
        while not self._silent.is_set() and self.proc.poll() is None:
            count += 1
            fleet.beat(self.fleet_dir, self.index, self.generation, count)
            self._silent.wait(0.03)

    def wedge(self):
        self._silent.set()


def test_supervisor_condemns_and_respawns_silent_member(tmp_path):
    d = str(tmp_path)
    lives = []

    def spawn(index, generation):
        lives.append(_FakeMember(d, index, generation))
        return lives[-1].proc

    sup = FleetSupervisor(d, spawn, members=1, lost_after_s=0.15,
                          poll_s=0.03, backoff_s=0.03, grace_s=5.0,
                          restart_budget=10)
    sup.start()
    try:
        _wait(lambda: sup.live_count() == 1)
        lives[0].wedge()  # publication silence; the process still "runs"
        _wait(lambda: len(lives) >= 2 and sup.live_count() == 1)
    finally:
        sup.stop(terminate=False)
    # the lost life was condemned (the bump a waking zombie exits on),
    # best-effort killed, and replaced at generation+1
    assert [m.generation for m in lives[:2]] == [1, 2]
    assert fleet.condemned_generation(d, 0) >= 1
    assert lives[0].proc.killed
    assert isinstance(sup.last_error, MemberLostError)
    assert sup.last_error.index == 0 and sup.last_error.generation == 1
    st = sup.stats()
    assert st["restarts"] >= 1 and st["degraded"] == 0
    assert fleet.read_registry(d)[0]["generation"] == lives[-1].generation


def test_supervisor_degrades_past_restart_budget(tmp_path):
    d = str(tmp_path)
    spawns = []

    def spawn(index, generation):  # never beats: every life is lost
        spawns.append(generation)
        return _FakeProc()

    sup = FleetSupervisor(d, spawn, members=1, lost_after_s=0.05,
                          poll_s=0.02, backoff_s=0.01, grace_s=0.05,
                          restart_budget=2)
    sup.start()
    try:
        _wait(lambda: sup.stats()["degraded"] == 1)
        n = len(spawns)
        time.sleep(0.1)  # degraded means NO further respawns
        assert len(spawns) == n
    finally:
        sup.stop(terminate=False)
    # budget=2 -> the first life + 2 respawns, then the slot degrades
    assert spawns == [1, 2, 3]
    assert not sup.healthy()
    st = sup.stats()
    assert st["slots"]["0"]["degraded"] and st["live"] == 0


def test_supervisor_spawns_past_ghost_heartbeat(tmp_path):
    """A returning supervisor must outrank BOTH the condemnation floor
    and any frozen heartbeat a previous run left behind (the elastic
    announce_join rule)."""
    d = str(tmp_path)
    fleet.condemn(d, 0, 3)
    fleet.beat(d, 0, 7, 42, wall_time=time.time() - 3600)  # stale ghost
    seen = []

    def spawn(index, generation):
        seen.append((index, generation))
        return _FakeProc()

    sup = FleetSupervisor(d, spawn, members=1, grace_s=30.0)
    sup._spawn(0)
    assert seen == [(0, 8)]
    assert sup.stats()["slots"]["0"]["generation"] == 8


def test_supervisor_stop_condemns_survivors(tmp_path):
    d = str(tmp_path)
    lives = []

    def spawn(index, generation):
        lives.append(_FakeMember(d, index, generation))
        return lives[-1].proc

    sup = FleetSupervisor(d, spawn, members=2, lost_after_s=5.0,
                          poll_s=0.02, grace_s=5.0)
    sup.start()
    _wait(lambda: sup.live_count() == 2)
    sup.stop()
    for idx in (0, 1):
        assert fleet.condemned_generation(d, idx) >= 1
    assert all(m.proc.poll() is not None for m in lives)


# ----------------------------------------------------------- front tier


def test_front_no_live_member_is_typed(tmp_path):
    front = FleetFront(str(tmp_path), refresh_s=0)
    assert not front.healthy()
    with pytest.raises(MemberLostError) as ei:
        front.submit(np.zeros((4,), np.float32))
    assert isinstance(ei.value, ReplicaLostError)  # -> HTTP 503 mapping
    assert ei.value.retry_after_s is not None
    front.close()


def test_front_ignores_stale_registry_entry(tmp_path):
    """A record without a fresh heartbeat — or from a condemned
    generation — can never attract traffic."""
    d = str(tmp_path)
    fleet.publish_member(d, index=7, generation=1, pid=1, port=9999)
    front = FleetFront(d, refresh_s=0, lost_after_s=0.5)
    assert front.members() == {}          # no heartbeat at all
    fleet.beat(d, 7, 1, 1, wall_time=time.time() - 60)
    assert front.members() == {}          # stale heartbeat
    fleet.publish_member(d, index=0, generation=2, pid=2, port=8000)
    fleet.beat(d, 0, 2, 1)
    assert sorted(front.members()) == [0]  # only the fresh member
    fleet.condemn(d, 0, 2)
    assert front.members() == {}          # condemned = gone
    front.close()


def test_front_typed_error_mapping():
    err = FleetFront._typed(429, {"error": "full", "retry_after_s": 2.5})
    assert isinstance(err, ServerOverloaded) and err.retry_after_s == 2.5
    assert isinstance(FleetFront._typed(504, {"error": "late"}),
                      RequestTimeout)
    assert isinstance(FleetFront._typed(400, {"error": "bad"}), ServeError)
    # 503/5xx are NOT terminal: the caller retries on the next member
    assert FleetFront._typed(503, {}) is None
    assert FleetFront._typed(500, {}) is None


def test_front_pick_routing_key(tmp_path):
    d = str(tmp_path)
    for i in (0, 1):
        fleet.publish_member(d, index=i, generation=1, pid=i, port=8000 + i,
                             max_batch=4)
        fleet.beat(d, i, 1, 1)
    front = FleetFront(d, refresh_s=0, lost_after_s=60)
    try:
        assert front._pick() == 0                    # tie -> lowest index
        front._inflight = {0: 9}
        assert front._pick() == 1                    # fewest pending
        assert front._pick(exclude={1}) == 0         # failover bound
        assert front._pick(exclude={0, 1}) is None   # exhausted
        front._inflight = {}
        front._deploying = {0}
        assert front._pick() == 1                    # in-swap deprioritized
        front._deploying = {0, 1}
        assert front._pick() == 0                    # ...but never excluded
    finally:
        front.close()


def test_front_swap_requires_path(tmp_path):
    d = str(tmp_path)
    fleet.publish_member(d, index=0, generation=1, pid=1, port=8000)
    fleet.beat(d, 0, 1, 1)
    front = FleetFront(d, refresh_s=0, lost_after_s=60)
    with pytest.raises(ServeError):
        front.swap({"params": {}})  # members load the path themselves
    front.close()


# ------------------------------------- front over real member processes


def _linear_model(seed=0):
    return nn.Sequential().add(nn.Linear(4, 3)).build(jax.random.key(seed))


def _start_member(tmp_path, index, server):
    """One in-process 'member': a real InferenceServer behind the stock
    HTTP handler, registered in the fleet dir."""
    import sys
    tools_dir = os.path.join(_REPO_ROOT, "tools")
    if tools_dir not in sys.path:
        sys.path.insert(0, tools_dir)
    import serve_http

    httpd = serve_http.serve_forever(server, "127.0.0.1", 0)
    port = httpd.server_address[1]
    d = str(tmp_path)
    fleet.publish_member(d, index=index, generation=1, pid=os.getpid(),
                         port=port, max_batch=server.max_batch)
    fleet.beat(d, index, 1, 1)
    return httpd


def test_front_end_to_end_route_retry_and_roll(tmp_path):
    """Two real members behind the front: bit-exact routing vs bulk
    Predictor, transport-failure failover onto the surviving member, and
    a rolling swap that lands the release on the whole fleet."""
    Engine.init()
    model = _linear_model(0)
    servers = [InferenceServer(_linear_model(0), max_wait_ms=2,
                               example=np.zeros((4,), np.float32)).start()
               for _ in range(2)]
    httpds = [_start_member(tmp_path, i, s) for i, s in enumerate(servers)]
    front = FleetFront(str(tmp_path), refresh_s=0, lost_after_s=3600,
                       retries=2, timeout_s=30)
    try:
        assert front.healthy() and sorted(front.members()) == [0, 1]
        rng = np.random.default_rng(7)
        x = rng.standard_normal((6, 4)).astype(np.float32)
        want = Predictor(model).predict(x)
        handles = [front.submit(row) for row in x]
        got = np.stack([h.result(timeout=30) for h in handles])
        # float32 survives the JSON round trip bit-for-bit
        np.testing.assert_array_equal(got, want)
        st = front.stats()
        assert st["replicas_live"] == 2
        assert sum(m["routed"] for m in st["fleet"]["members"].values()) == 6

        # rolling deploy: full swap (no canary) fans out to every member
        new = _snapshot_model(tmp_path / "model.new", seed=1)
        front.swap(str(tmp_path / "model.new"))
        want2 = Predictor(new).predict(x)
        np.testing.assert_array_equal(
            np.stack([front.predict(row, timeout=30) for row in x]), want2)
        assert front.stats()["canary"]["reason"] == "full_swap"

        # kill member 0's socket mid-fleet (close, not just stop — a
        # kill -9'd process refuses connections): the front retries the
        # transport failure on member 1 — no caller-visible error
        httpds[0].shutdown()
        httpds[0].server_close()
        np.testing.assert_array_equal(front.predict(x[0], timeout=30),
                                      want2[0])
        assert front.stats()["fleet"]["retried"] >= 1
    finally:
        front.close()
        for httpd in httpds:
            httpd.shutdown()
        for s in servers:
            s.stop()


def _snapshot_model(path, seed=1):
    m = _linear_model(seed)
    file_io.save({"params": m.params, "state": m.state}, str(path))
    return m


# -------------------------------------------- deploy controller (fleet)


class _StubFront:
    """Duck-typed fleet target: records the rolling-deploy kwargs the
    controller passes and answers a promoted canary."""

    fleet = True

    def __init__(self):
        self.swaps = []
        self.deploy = None
        self._vid = 1

    def attach_deploy(self, controller):
        self.deploy = controller

    def swap(self, source, canary_fraction=None, max_unavailable=None):
        self._vid += 1
        self.swaps.append((str(source), canary_fraction, max_unavailable))
        return self._vid

    def stats(self):
        return {"canary": {"version": self._vid, "state": "promoted",
                           "fraction": 0.25, "routed": 8, "total": 32}}

    def healthy(self):
        return True


def test_deploy_controller_fleet_mode(tmp_path):
    """A fleet-shaped server flips the controller into rolling mode: the
    max-unavailable bound rides every swap and the timeline records the
    fleet deploy."""
    from bigdl_tpu.serve import ReleasePublisher
    snap = _snapshot_model(tmp_path / "model.1")
    del snap
    pub = ReleasePublisher(str(tmp_path))
    pub.publish(str(tmp_path / "model.1"), neval=1)
    front = _StubFront()
    ctl = DeployController(front, str(tmp_path), canary_fraction=0.25,
                           poll_s=0.01, max_unavailable=2).start()
    try:
        _wait(lambda: ctl.stats()["promoted"] >= 1)
    finally:
        ctl.stop()
    assert ctl.fleet_mode
    assert front.swaps == [(str(tmp_path / "model.1"), 0.25, 2)]
    deployed = [e for e in ctl.versions()["timeline"]
                if e["action"] == "deployed"]
    assert deployed and deployed[0]["fleet"] is True


def test_deploy_controller_plain_server_unchanged(tmp_path):
    """A non-fleet target never sees the fleet kwarg (the PR 15 swap
    signature is untouched)."""
    from bigdl_tpu.serve import ReleasePublisher

    class _Plain:
        def __init__(self):
            self.kwargs = []
            self._vid = 1

        def swap(self, source, canary_fraction=None):
            self._vid += 1
            self.kwargs.append(canary_fraction)
            return self._vid

        def stats(self):
            return {"canary": {"version": self._vid, "state": "promoted"}}

    _snapshot_model(tmp_path / "model.1")
    pub = ReleasePublisher(str(tmp_path))
    pub.publish(str(tmp_path / "model.1"), neval=1)
    srv = _Plain()
    ctl = DeployController(srv, str(tmp_path), canary_fraction=0.25,
                           poll_s=0.01).start()
    try:
        _wait(lambda: ctl.stats()["promoted"] >= 1)
    finally:
        ctl.stop()
    assert not ctl.fleet_mode and srv.kwargs == [0.25]


# --------------------------------------------------- worker process (1)


@pytest.mark.slow
def test_worker_process_registers_and_exits_on_condemn(tmp_path):
    """One REAL worker process: registers with its bound port, beats,
    answers /v1/predict with the bulk-Predictor answer, and exits
    gracefully when its generation is condemned."""
    import subprocess
    import sys
    import urllib.request

    d = str(tmp_path / "fleet")
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("BIGDL_TPU_ELASTIC", "BIGDL_TPU_CHAOS",
                                "BIGDL_TPU_TRACE"))}
    env.update(PYTHONPATH=_REPO_ROOT, JAX_PLATFORMS="cpu",
               BIGDL_TPU_PREFETCH_DEPTH="0", BIGDL_TPU_FLEET_HEARTBEAT="0.1")
    proc = subprocess.Popen(
        [sys.executable, os.path.join(_REPO_ROOT, "tools", "serve_worker.py"),
         "--fleet-dir", d, "--index", "0", "--generation", "1",
         "--model", "linear", "--platform", "cpu"],
        env=env, stdout=subprocess.PIPE, text=True)
    try:
        _wait(lambda: 0 in fleet.read_registry(d), timeout=120)
        _wait(lambda: fleet.member_alive(d, 0, generation=1, lost_after=5.0),
              timeout=30)
        rec = fleet.read_registry(d)[0]
        assert rec["pid"] == proc.pid and rec["port"] > 0
        body = json.dumps({"inputs": [0.0, 0.0, 0.0, 0.0]}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{rec['port']}/v1/predict", data=body,
            method="POST")
        with urllib.request.urlopen(req, timeout=30) as r:
            out = json.loads(r.read())
        assert np.asarray(out["outputs"]).shape == (3,)
        fleet.condemn(d, 0, 1)
        assert proc.wait(timeout=30) == 0  # graceful condemned exit
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
