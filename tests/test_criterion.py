"""Criterion tests: golden values vs numpy and gradient sanity."""

import numpy as np
import jax
import jax.numpy as jnp

import bigdl_tpu.nn as nn


def test_mse():
    c = nn.MSECriterion()
    o = jnp.asarray([[1.0, 2.0], [3.0, 4.0]])
    t = jnp.zeros((2, 2))
    np.testing.assert_allclose(float(c.forward(o, t)), (1 + 4 + 9 + 16) / 4)
    g = c.backward(o, t)
    np.testing.assert_allclose(np.asarray(g), np.asarray(o) / 2)


def test_abs_criterion():
    c = nn.AbsCriterion()
    o = jnp.asarray([1.0, -2.0])
    np.testing.assert_allclose(float(c.forward(o, jnp.zeros(2))), 1.5)


def test_classnll_and_crossentropy_agree():
    logits = jnp.asarray(np.random.default_rng(0).normal(size=(4, 5)),
                         dtype=jnp.float32)
    labels = jnp.asarray([0, 2, 4, 1])
    ce = nn.CrossEntropyCriterion()
    nll = nn.ClassNLLCriterion()
    lsm = jax.nn.log_softmax(logits, axis=-1)
    np.testing.assert_allclose(float(ce.forward(logits, labels)),
                               float(nll.forward(lsm, labels)), rtol=1e-6)
    # golden: manual mean of -logp[label]
    expect = -np.mean(np.asarray(lsm)[np.arange(4), np.asarray(labels)])
    np.testing.assert_allclose(float(ce.forward(logits, labels)), expect,
                               rtol=1e-6)


def test_classnll_one_based_and_weights():
    lsm = jax.nn.log_softmax(jnp.asarray([[1.0, 2.0, 3.0]]), axis=-1)
    a = nn.ClassNLLCriterion(one_based=True).forward(lsm, jnp.asarray([3]))
    b = nn.ClassNLLCriterion().forward(lsm, jnp.asarray([2]))
    np.testing.assert_allclose(float(a), float(b))
    w = jnp.asarray([1.0, 1.0, 2.0])
    c = nn.ClassNLLCriterion(weights=w).forward(lsm, jnp.asarray([2]))
    np.testing.assert_allclose(float(c), float(b))  # normalized by weight sum


def test_bce():
    c = nn.BCECriterion()
    o = jnp.asarray([0.9, 0.1])
    t = jnp.asarray([1.0, 0.0])
    expect = -np.mean([np.log(0.9), np.log(0.9)])
    np.testing.assert_allclose(float(c.forward(o, t)), expect, rtol=1e-5)


def test_smooth_l1():
    c = nn.SmoothL1Criterion()
    o = jnp.asarray([0.5, 2.0])
    t = jnp.zeros(2)
    np.testing.assert_allclose(float(c.forward(o, t)),
                               (0.5 * 0.25 + 1.5) / 2, rtol=1e-6)


def test_margin_and_hinge():
    c = nn.MarginCriterion()
    o = jnp.asarray([0.5, 2.0])
    t = jnp.asarray([1.0, 1.0])
    np.testing.assert_allclose(float(c.forward(o, t)), 0.25)
    h = nn.HingeEmbeddingCriterion(margin=1.0)
    np.testing.assert_allclose(
        float(h.forward(jnp.asarray([0.3]), jnp.asarray([-1.0]))), 0.7,
        rtol=1e-6)


def test_kldiv():
    c = nn.DistKLDivCriterion()
    target = jnp.asarray([[0.5, 0.5]])
    logp = jnp.log(jnp.asarray([[0.5, 0.5]]))
    np.testing.assert_allclose(float(c.forward(logp, target)), 0.0, atol=1e-6)


def test_multi_and_parallel_criterion():
    mc = nn.MultiCriterion().add(nn.MSECriterion()).add(nn.AbsCriterion(), 0.5)
    o, t = jnp.asarray([2.0]), jnp.asarray([0.0])
    np.testing.assert_allclose(float(mc.forward(o, t)), 4.0 + 0.5 * 2.0)
    pc = (nn.ParallelCriterion()
          .add(nn.MSECriterion())
          .add(nn.AbsCriterion()))
    np.testing.assert_allclose(
        float(pc.forward([o, o], [t, t])), 4.0 + 2.0)


def test_cosine_embedding():
    c = nn.CosineEmbeddingCriterion()
    x = jnp.asarray([[1.0, 0.0]])
    l = c.forward([x, x], jnp.asarray([1.0]))
    np.testing.assert_allclose(float(l), 0.0, atol=1e-6)


def test_multimargin_and_multilabel():
    o = jnp.asarray([[0.1, 0.2, 0.7]])
    t = jnp.asarray([2])
    l = nn.MultiMarginCriterion().forward(o, t)
    expect = (max(0, 1 - 0.7 + 0.1) + max(0, 1 - 0.7 + 0.2)) / 3
    np.testing.assert_allclose(float(l), expect, rtol=1e-5)
    ml = nn.MultiLabelSoftMarginCriterion()
    val = ml.forward(jnp.asarray([[0.0, 0.0]]), jnp.asarray([[1.0, 0.0]]))
    np.testing.assert_allclose(float(val), np.log(2), rtol=1e-5)


def test_softmax_with_criterion_spatial():
    logits = jnp.asarray(np.random.default_rng(1).normal(size=(2, 4, 4, 3)),
                         dtype=jnp.float32)
    labels = jnp.asarray(np.random.default_rng(2).integers(0, 3, size=(2, 4, 4)))
    l = nn.SoftmaxWithCriterion().forward(logits, labels)
    assert np.isfinite(float(l))


def test_time_distributed_criterion():
    c = nn.TimeDistributedCriterion(nn.MSECriterion(), size_average=True)
    o = jnp.ones((2, 3, 4))
    t = jnp.zeros((2, 3, 4))
    np.testing.assert_allclose(float(c.forward(o, t)), 1.0, rtol=1e-6)


def test_dice():
    c = nn.DiceCoefficientCriterion()
    o = jnp.asarray([[1.0, 1.0]])
    np.testing.assert_allclose(float(c.forward(o, o)), 0.0, atol=1e-6)


def test_label_smoothing_matches_torch():
    """CrossEntropyCriterion(label_smoothing=eps) == torch
    F.cross_entropy(..., label_smoothing=eps)."""
    import pytest
    torch = pytest.importorskip("torch")

    rng = np.random.default_rng(0)
    logits = rng.normal(size=(16, 7)).astype(np.float32)
    labels = rng.integers(0, 7, size=16)
    for eps in (0.0, 0.1, 0.3):
        got = float(nn.CrossEntropyCriterion(label_smoothing=eps).loss(
            jnp.asarray(logits), jnp.asarray(labels)))
        ref = float(torch.nn.functional.cross_entropy(
            torch.tensor(logits), torch.tensor(labels),
            label_smoothing=eps))
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_label_smoothing_ignores_padding():
    logits = jnp.asarray(np.random.default_rng(1)
                         .normal(size=(4, 5)).astype(np.float32))
    labels = jnp.asarray([2, -1, 0, -1])  # two padded rows
    crit = nn.ClassNLLCriterion(label_smoothing=0.1)
    lp = jax.nn.log_softmax(logits, axis=-1)
    full = crit.loss(lp, labels)
    sub = crit.loss(lp[jnp.asarray([0, 2])], jnp.asarray([2, 0]))
    np.testing.assert_allclose(float(full), float(sub), rtol=1e-6)


def test_label_smoothing_rejects_weights():
    import pytest as _pytest

    with _pytest.raises(ValueError):
        nn.ClassNLLCriterion(weights=jnp.ones(5), label_smoothing=0.1)
