"""Interop against COMMITTED foreign bytes (tests/fixtures/interop/).

Round-2 verdict demand #6: self-round-trips cannot catch a convention bug
shared by saver and loader.  These fixtures were produced by independent
encoders (tools/gen_interop_fixtures.py): the TF GraphDef by real
tensorflow, the caffemodel by a standalone protobuf wire writer with a
plain-numpy NCHW oracle, the .t7 by a standalone Torch7 writer — none of
them import bigdl_tpu.interop.  Reference analog: the genuine fixture
models under spark/dl/src/test/resources/{caffe,tf/models,torch}.
"""

import os

import numpy as np
import jax.numpy as jnp
import pytest

FIX = os.path.join(os.path.dirname(__file__), "fixtures", "interop")


def _forward(model, params, state, x):
    out, _ = model.apply(params, state, x, training=False, rng=None)
    return np.asarray(out)


def test_caffe_fixture_loads_with_numeric_parity():
    """conv + BatchNorm(+scale_factor!) + Scale fold + MaxPool + FC layout
    permutation + Softmax, against the independent numpy NCHW oracle."""
    from bigdl_tpu.interop.caffe import load_caffe
    blob = np.load(os.path.join(FIX, "lenet_bn_expected.npz"))
    model, params = load_caffe(os.path.join(FIX, "lenet_bn.caffemodel"))
    got = _forward(model, params, model.state,
                   jnp.asarray(blob["input_nhwc"]))
    np.testing.assert_allclose(got, blob["prob"], rtol=1e-4, atol=1e-5)


def test_tf_fixture_loads_with_numeric_parity():
    """Frozen GraphDef emitted by REAL tensorflow; expected output from a
    real tf session run."""
    from bigdl_tpu.interop.tensorflow import load_tf
    blob = np.load(os.path.join(FIX, "convnet_expected.npz"))
    model, params = load_tf(os.path.join(FIX, "convnet.pb"),
                            inputs=["input"], outputs="output")
    got = _forward(model, params, model.state, jnp.asarray(blob["input"]))
    np.testing.assert_allclose(got, blob["output"], rtol=1e-4, atol=1e-5)


def test_t7_fixture_decodes():
    """Torch7 bytes from the independent writer: tensors (with storages and
    strides), booleans, strings, numbers, nested tables."""
    from bigdl_tpu.interop.torchfile import load_t7
    blob = np.load(os.path.join(FIX, "codec_t7_expected.npz"))
    obj = load_t7(os.path.join(FIX, "codec.t7"))
    np.testing.assert_array_equal(obj["weight"], blob["weight"])
    np.testing.assert_array_equal(obj["bias"], blob["bias"])
    assert obj["train"] is False
    assert obj["name"] == "fixture"
    assert obj["epoch"] == 3
    assert obj["nested"] == [10.5, "two"]  # 1..n keys -> list


def test_fixture_bytes_are_stable():
    """Fixture regeneration must be deterministic — drift means either the
    generator or the committed bytes changed, both of which should be
    deliberate."""
    import hashlib
    digests = {}
    for name in ("lenet_bn.caffemodel", "codec.t7"):
        with open(os.path.join(FIX, name), "rb") as f:
            digests[name] = hashlib.sha256(f.read()).hexdigest()[:16]
    assert digests == {
        "lenet_bn.caffemodel": "683a1cba951e641b",
        "codec.t7": "8c52e35d0c99f718",
    }, digests
