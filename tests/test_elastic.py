"""Elastic multi-host training: coordinated host-loss recovery
(bigdl_tpu.parallel.elastic + the supervisor/engine/optimizer wiring).

The failure mode under test is the one neither checkpoint lineage (PR 1)
nor stall supervision (PR 2) can reach alone: a peer HOST dies, every
surviving rank's next collective would hang forever, and recovering in
place is useless because the dead rank will never rejoin.  The elastic
subsystem turns the supervisor's stale-peer observation into a typed
PeerLostError, negotiates the newest lineage entry valid for every
survivor over pure file_io (no collectives), re-forms the topology over
the surviving slice with the global batch preserved, and resumes — the
BigDL driver's re-form-the-job semantics without a driver.
"""

import glob
import json
import math
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
from bigdl_tpu.optim import Adam, Optimizer, Trigger
from bigdl_tpu.parallel import elastic
from bigdl_tpu.parallel.sharding import DataParallel, ShardedDataParallel
from bigdl_tpu.utils import chaos, file_io, telemetry
from bigdl_tpu.utils import supervisor as sup_mod
from bigdl_tpu.utils.engine import Engine
from bigdl_tpu.utils.supervisor import Supervisor

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean():
    chaos.clear()
    yield
    chaos.clear()
    sup_mod.set_active(None)
    telemetry.set_active(None)


def _write_lineage(path, nevals):
    for n in nevals:
        file_io.save_checkpoint(str(path), n,
                                {"params": {"w": np.arange(4.0) + n},
                                 "state": {}},
                                {"method": {}, "driver_state": {}})


# ---------------------------------------------------------------------------
# lineage negotiation (pure file_io — no jax backend needed)
# ---------------------------------------------------------------------------

def test_survey_lists_valid_entries_newest_first(tmp_path):
    _write_lineage(tmp_path, [3, 5, 8])
    assert elastic.survey(str(tmp_path)) == [8, 5, 3]


def test_survey_excludes_corrupt_entries_without_quarantining(tmp_path):
    _write_lineage(tmp_path, [3, 5])
    p = tmp_path / "model.5"
    data = p.read_bytes()
    p.write_bytes(data[:10] + bytes([data[10] ^ 0xFF]) + data[11:])
    assert elastic.survey(str(tmp_path)) == [3]
    # exclusion is an observation, not a mutation: whether 5 dies is the
    # CLUSTER's call during negotiation
    assert (tmp_path / "model.5").exists()


def test_negotiate_single_survivor_picks_newest(tmp_path):
    _write_lineage(tmp_path, [3, 5, 8])
    plan = elastic.negotiate(str(tmp_path), rank=0, survivors=[0],
                             epoch=1, timeout=0.1, poll=0.01)
    assert plan.neval == 8
    assert plan.model_path.endswith("model.8")
    assert plan.survivors == (0,)


def test_negotiate_disjoint_newest_entries(tmp_path):
    """Survivors whose newest entries differ (store visibility lag) must
    agree on the newest COMMON one, and the divergent tail must be
    quarantined so every later resume converges."""
    _write_lineage(tmp_path, [3, 5, 8])
    # rank 1 cannot see entry 8 yet; its published view is [5, 3]
    elastic.publish_lineage_view(str(tmp_path), 1, 2, [5, 3])
    plan = elastic.negotiate(str(tmp_path), rank=0, survivors=[0, 1],
                             epoch=2, timeout=1.0, poll=0.01)
    assert plan.neval == 5
    # the leader (rank 0) quarantined the tail: 8 is .corrupt now
    assert (tmp_path / "model.8.corrupt").exists()
    assert (tmp_path / "optimMethod.8.corrupt").exists()
    assert not (tmp_path / "model.8").exists()
    # a late/independent recovery now lands on the same entry
    assert elastic.survey(str(tmp_path))[0] == 5


def test_negotiate_corrupt_on_one_rank_skipped_cluster_wide(tmp_path):
    """An entry corrupt for ONE survivor must be skipped by everyone:
    it drops out of the intersection and the tail quarantine removes it
    from the shared lineage."""
    _write_lineage(tmp_path, [3, 5, 8])
    # rank 1 verified the lineage and found 8 corrupt on its mount
    elastic.publish_lineage_view(str(tmp_path), 1, 4, [5, 3])
    plan = elastic.negotiate(str(tmp_path), rank=0, survivors=[0, 1],
                             epoch=4, my_valid=[8, 5, 3],
                             timeout=1.0, poll=0.01)
    assert plan.neval == 5
    assert (tmp_path / "model.8.corrupt").exists()


def test_negotiate_empty_lineage_typed_failure_no_hang(tmp_path):
    """No snapshots anywhere -> typed ElasticNegotiationError, not a
    hang (driven with an injected clock: zero wall-time waiting)."""
    fake = {"t": 0.0}

    def clock():
        return fake["t"]

    def sleep(s):
        fake["t"] += s

    with pytest.raises(elastic.ElasticNegotiationError,
                       match="no checkpoint lineage entry"):
        elastic.negotiate(str(tmp_path), rank=0, survivors=[0, 1],
                          epoch=1, timeout=5.0, poll=0.5,
                          clock=clock, sleep=sleep)
    assert fake["t"] >= 5.0  # it waited for rank 1's view, then gave up


def test_negotiate_drops_silent_survivor_after_timeout(tmp_path):
    """A survivor that never publishes its view is dropped from the
    agreement (it is effectively lost too) instead of blocking forever."""
    _write_lineage(tmp_path, [3, 5])
    fake = {"t": 0.0}
    plan = elastic.negotiate(
        str(tmp_path), rank=0, survivors=[0, 1], epoch=1, timeout=2.0,
        poll=0.5, clock=lambda: fake["t"],
        sleep=lambda s: fake.__setitem__("t", fake["t"] + s))
    assert plan.neval == 5
    assert plan.survivors == (0,)


def test_joiner_divergent_tail_quarantined_then_adopted(tmp_path):
    """GROW negotiation from the JOINER's seat: the returning rank's
    previous life can hold lineage entries the survivor never saw
    (written in the instants before it died).  The widened set agrees on
    the newest COMMON entry, the leader (the lowest rank — a survivor)
    quarantines the divergent tail, and the joiner ADOPTS the agreed
    snapshot — never the reverse."""
    _write_lineage(tmp_path, [3, 5, 8])
    # survivor rank 0's published view: it never saw the joiner's 8
    elastic.publish_lineage_view(str(tmp_path), 0, 7, [5, 3])
    plan = elastic.negotiate(str(tmp_path), rank=1, survivors=[0, 1],
                             epoch=7, my_valid=[8, 5, 3],
                             timeout=1.0, poll=0.01)
    assert plan.neval == 5 and plan.survivors == (0, 1)
    assert plan.model_path.endswith("model.5")
    # the joiner is NOT the leader: the tail is still intact here...
    assert (tmp_path / "model.8").exists()
    # ...until the survivor's own negotiate call (same round) runs
    plan0 = elastic.negotiate(str(tmp_path), rank=0, survivors=[0, 1],
                              epoch=7, my_valid=[5, 3],
                              timeout=1.0, poll=0.01)
    assert plan0.neval == 5
    assert (tmp_path / "model.8.corrupt").exists()
    assert not (tmp_path / "model.8").exists()
    assert elastic.survey(str(tmp_path))[0] == 5  # every later resume agrees


def test_stale_intents_from_previous_rounds_ignored(tmp_path):
    elastic.publish_intent(str(tmp_path), 1, epoch=1, lost=[2],
                           wall_time=0.0)
    elastic.publish_intent(str(tmp_path), 2, epoch=3, lost=[0],
                           wall_time=0.0)
    intents = elastic.read_intents(str(tmp_path), min_epoch=2)
    assert list(intents) == [2]
    assert intents[2]["lost"] == [0]
    # own intent excluded
    assert elastic.read_intents(str(tmp_path), min_epoch=2,
                                exclude_rank=2) == {}


# ---------------------------------------------------------------------------
# detection: supervisor promotes publication silence to PeerLostError
# ---------------------------------------------------------------------------

def _lost_supervisor(ckpt, rank, wall, **kw):
    return Supervisor({}, peer_dir=os.path.join(ckpt, "heartbeats"),
                      rank=rank, world=2, peer_stale=5.0, peer_lost=10.0,
                      wall_clock=lambda: wall["now"], publish_interval=0.0,
                      lineage_dir=ckpt, poll_interval=0.05, **kw)


def test_peer_lost_promotion_raises_and_publishes_intent(tmp_path):
    """A peer whose heartbeat PUBLICATION goes silent past the elastic
    threshold -> PeerLostError async-raised into the supervised thread
    (carrying the lost ranks + recovery round) and an epoch-stamped
    intent file for the slower survivors."""
    ckpt = str(tmp_path)
    wall = {"now": 1000.0}
    dead = _lost_supervisor(ckpt, 1, wall)
    dead.beat("step")
    dead._publish_heartbeat()  # last sign of life from rank 1

    sup = _lost_supervisor(ckpt, 0, wall)
    caught = {}

    def worker():
        sup.beat("step")
        try:
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                time.sleep(0.01)
            caught["err"] = None
        except elastic.PeerLostError as e:
            caught["err"] = e

    t = threading.Thread(target=worker, name="elastic-supervised")
    t.start()
    time.sleep(0.1)
    sup.start()
    wall["now"] = 1030.0  # rank 1 publication-silent for 30s > 10s
    t.join(10)
    sup.stop()
    assert not t.is_alive(), "PeerLostError never landed"
    err = caught["err"]
    assert isinstance(err, elastic.PeerLostError)
    assert err.lost_ranks == (1,) and err.epoch == 1
    assert "host(s) [1]" in str(err)
    intents = elastic.read_intents(ckpt, min_epoch=1)
    assert intents[0]["lost"] == [1] and intents[0]["epoch"] == 1
    # accessors: beat-staleness and publication-loss views
    assert list(sup.stale_peers()) == [1]
    assert sup.lost_peers()[1] == pytest.approx(30.0)
    # reform() records the round and stops re-promoting the dead rank
    sup.reform(rank=0, world=1, epoch=1, lost=[1])
    assert sup.elastic_epoch == 1 and sup.stale_peers() == {}


def test_foreign_intent_converges_other_survivor(tmp_path):
    """A rank that has NOT yet observed the silence itself must promote
    as soon as another survivor's recover intent appears."""
    ckpt = str(tmp_path)
    wall = {"now": 50.0}
    # rank 1 already called recovery round 1 against lost rank 2
    elastic.publish_intent(ckpt, 1, epoch=1, lost=[2], wall_time=50.0)
    sup = Supervisor({}, peer_dir=os.path.join(ckpt, "heartbeats"),
                     rank=0, world=3, peer_stale=500.0, peer_lost=1000.0,
                     wall_clock=lambda: wall["now"], publish_interval=0.0,
                     lineage_dir=ckpt, poll_interval=0.05)
    caught = {}

    def worker():
        sup.beat("step")
        try:
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                time.sleep(0.01)
            caught["err"] = None
        except elastic.PeerLostError as e:
            caught["err"] = e

    t = threading.Thread(target=worker, name="elastic-follower")
    t.start()
    time.sleep(0.1)
    sup.start()
    t.join(10)
    sup.stop()
    assert not t.is_alive(), "intent convergence never fired"
    err = caught["err"]
    assert isinstance(err, elastic.PeerLostError)
    assert err.lost_ranks == (2,) and err.epoch == 1


def test_stale_peer_ages_on_telemetry_counter_track(tmp_path):
    """Stragglers-about-to-die show in traces: stale-peer ages land on
    the 'peers' counter track every monitor poll."""
    ckpt = str(tmp_path)
    wall = {"now": 100.0}
    seen = []
    dead = _lost_supervisor(ckpt, 1, wall)
    dead.beat("step")
    dead._publish_heartbeat()
    sup = _lost_supervisor(ckpt, 0, wall,
                           on_peer_stale=lambda r, age: seen.append((r,
                                                                     age)))
    tr = telemetry.Tracer(str(tmp_path / "trace"), rank=0)
    telemetry.set_active(tr)
    try:
        wall["now"] = 107.0  # stale (> 5s) but not lost (< 10s)
        sup._check_peers(log=True)
        sup._check_peers(log=True)
    finally:
        telemetry.set_active(None)
        tr.close()
    counters = [e for e in tr.events_tail(64)
                if e.get("ph") == "C" and e.get("name") == "peers"]
    assert counters and counters[-1]["args"]["stale_age_r1"] == \
        pytest.approx(7.0)
    # the programmatic callback fired ONCE (new stale episode), not per poll
    assert seen == [(1, pytest.approx(7.0))]


def test_heartbeat_publish_failure_counted_retried_monitor_survives():
    """Satellite: a transient store failure publishing heartbeat.<rank>
    is counted and re-attempted on the next poll — never allowed to kill
    the monitor or silently stop beats."""

    class FlakyFS:
        def __init__(self, fail_first):
            self.fail_first = fail_first
            self.writes = 0
            self.stored = {}

        def write_bytes(self, path, data):
            self.writes += 1
            if self.writes <= self.fail_first:
                raise IOError("store flake")
            self.stored[path] = data

        def read_bytes(self, path):
            return self.stored[path]

        def exists(self, path):
            return path in self.stored

        def isdir(self, path):
            return True

        def listdir(self, path):
            return [p.rsplit("/", 1)[-1] for p in self.stored]

        def makedirs(self, path):
            pass

        def rename(self, src, dst):
            self.stored[dst] = self.stored.pop(src)

        def remove(self, path):
            self.stored.pop(path, None)

    fs = FlakyFS(fail_first=5)  # first op: 4 attempts all fail; next: ok
    file_io.register_filesystem("elastictest", fs)
    prev = file_io.set_retry_timebase(lambda: 0.0, lambda s: None)
    try:
        sup = Supervisor({"step": 60.0},
                         peer_dir="elastictest://hb", rank=0, world=2,
                         publish_interval=0.0)
        sup.beat("step")
        sup._publish_heartbeat()  # fails after retries -> counted
        assert sup.heartbeat_errors == 1
        assert sup._last_publish is None  # next poll retries immediately
        sup._publish_heartbeat()  # attempt 5 fails, 6 succeeds
        assert sup.heartbeat_errors == 1
        assert fs.exists("elastictest://hb/heartbeat.0")
        blob = json.loads(fs.read_bytes("elastictest://hb/heartbeat.0"))
        assert "published" in blob and "time" in blob
    finally:
        file_io.set_retry_timebase(*prev)


def test_suspend_heartbeat_stops_publication(tmp_path):
    wall = {"now": 10.0}
    sup = _lost_supervisor(str(tmp_path), 0, wall)
    sup.beat("step")
    sup._publish_heartbeat()
    hb = os.path.join(str(tmp_path), "heartbeats", "heartbeat.0")
    first = open(hb).read()
    wall["now"] = 20.0
    sup.suspend_heartbeat()  # the host.lost drill's go-silent switch
    sup._publish_heartbeat()
    assert open(hb).read() == first


def test_supervisor_peer_returned_once_per_episode(tmp_path):
    """A lost peer's RETURN (a generation-bumped heartbeat) is observed
    exactly once per episode: on_peer_returned fires once, the rank
    parks in returned_peers() until reform(returned=...) re-admits it to
    the live watch — admission itself happens at the optimizer's next
    checkpoint boundary, never from the monitor thread."""
    ckpt = str(tmp_path)
    wall = {"now": 1000.0}
    dead = _lost_supervisor(ckpt, 1, wall)
    dead.beat("step")
    dead._publish_heartbeat()                        # generation-0 life
    seen = []
    sup = _lost_supervisor(
        ckpt, 0, wall,
        on_peer_returned=lambda r, g: seen.append((r, g)))
    sup._check_peers(log=True)                       # baseline generation
    sup.reform(rank=0, world=1, epoch=1, lost=[1])   # shrink completed
    sup._check_peers(log=True)                       # frozen file: not news
    assert seen == [] and sup.returned_peers() == {}
    elastic.announce_join(ckpt, 1, wall["now"])      # next life: gen 1
    sup._check_peers(log=True)
    sup._check_peers(log=True)                       # same episode: silent
    assert seen == [(1, 1)]
    assert sup.returned_peers() == {1: 1}
    sup.reform(rank=0, world=2, epoch=2, returned=[1])
    assert sup.returned_peers() == {}
    # re-admitted to the live watch: staleness applies to it again
    wall["now"] = 1020.0
    assert sup._check_peers(log=False)[1] == pytest.approx(20.0)


def test_reform_grace_holds_promotion_then_rearms(tmp_path):
    """Every member recompiles its jitted step right after a re-form; a
    compile can starve the monitor past a tight peer_lost threshold.
    reform() therefore arms a detection-grace window: silence inside it
    is observed, never promoted — and promotion re-arms after it."""
    ckpt = str(tmp_path)
    wall = {"now": 1000.0}
    dead = _lost_supervisor(ckpt, 1, wall)
    dead.beat("step")
    dead._publish_heartbeat()
    mono = {"t": 100.0}
    sup = _lost_supervisor(ckpt, 0, wall, clock=lambda: mono["t"])
    sup._thread_id = 1 << 30  # raise delivery is another test's business
    sup.reform(rank=0, world=2, epoch=0)             # arms the grace
    assert sup._promotion_grace_until == pytest.approx(
        100.0 + sup.reform_grace)
    wall["now"] = 1030.0                             # silent 30s > lost 10s
    sup._check_elastic(sup._check_peers(log=False))  # inside grace: held
    assert elastic.read_intents(ckpt, min_epoch=1) == {}
    assert not sup.peer_lost_pending()
    mono["t"] += sup.reform_grace + 0.1              # grace expired
    sup._check_elastic(sup._check_peers(log=False))  # now it promotes
    assert elastic.read_intents(ckpt, min_epoch=1)[0]["lost"] == [1]
    assert sup.peer_lost_pending()


# ---------------------------------------------------------------------------
# GROW: join intents, announcement hygiene, admission (pure file_io)
# ---------------------------------------------------------------------------

def test_join_intent_roundtrip_and_clear(tmp_path):
    ckpt = str(tmp_path)
    elastic.publish_join_intent(ckpt, 1, 5.0, generation=3)
    intents = elastic.read_join_intents(ckpt)
    assert intents[1]["generation"] == 3 and intents[1]["rank"] == 1
    # own intent excluded (a joiner never admits itself)
    assert elastic.read_join_intents(ckpt, exclude_rank=1) == {}
    elastic.clear_join_intent(ckpt, 1)
    assert elastic.read_join_intents(ckpt) == {}
    elastic.clear_join_intent(ckpt, 1)  # consuming twice is harmless


def test_announce_join_hygiene_and_generation_bump(tmp_path):
    """The returning rank's previous life left a frozen heartbeat and
    stale protocol files; announce_join must bump the heartbeat
    GENERATION past the old one, delete the stale recover./lineage.
    views, and record the grow floor BEFORE publishing the intent."""
    ckpt = str(tmp_path)
    hb_dir = tmp_path / "heartbeats"
    hb_dir.mkdir()
    (hb_dir / "heartbeat.1").write_text(json.dumps(
        {"rank": 1, "phase": "step", "count": 7, "time": 1.0,
         "published": 1.0, "generation": 3}))
    elastic.publish_intent(ckpt, 1, epoch=1, lost=[0], wall_time=1.0)
    elastic.publish_lineage_view(ckpt, 1, 1, [5, 3])
    elastic.publish_grow_offer(ckpt, 0, 2, [0, 1], 1.0)  # older episode
    info = elastic.announce_join(ckpt, 1, 9.0)
    assert info == {"generation": 4, "floor": 2}
    edir = tmp_path / "elastic"
    assert not (edir / "recover.1").exists()   # stale previous-life view
    assert not (edir / "lineage.1").exists()
    hb = json.loads((hb_dir / "heartbeat.1").read_text())
    assert hb["generation"] == 4 and hb["phase"] == "join"
    assert elastic.read_join_intents(ckpt)[1]["generation"] == 4
    # a genuinely NEW rank announces at generation 1 with no floor
    fresh = elastic.announce_join(str(tmp_path / "other"), 0, 9.0)
    assert fresh == {"generation": 1, "floor": 0}


def test_death_certificate_and_previous_generation(tmp_path):
    """A RETURNING rank (previous_generation is not None) must hold its
    announcement until a survivor's recovery round declares it lost — a
    generation-bumped fresh heartbeat would otherwise reset the very
    publication silence the loss is detected by, and the shrink this
    grow stacks on would never run."""
    ckpt = str(tmp_path)
    assert elastic.previous_generation(ckpt, 1) is None   # a NEW rank
    assert elastic.death_certificate(ckpt, 1) == 0        # not declared
    wall = {"now": 10.0}
    dead = _lost_supervisor(ckpt, 1, wall)
    dead.beat("step")
    dead._publish_heartbeat()
    assert elastic.previous_generation(ckpt, 1) == 0      # a previous life
    elastic.publish_intent(ckpt, 0, epoch=3, lost=[1], wall_time=10.0)
    assert elastic.death_certificate(ckpt, 1) == 3
    assert elastic.death_certificate(ckpt, 0) == 0        # not this rank
    # rounds at or below the grow floor are a PREVIOUS episode's news
    assert elastic.death_certificate(ckpt, 1, floor=3) == 0
    assert elastic.death_certificate(ckpt, 1, floor=2) == 3


def test_grow_offer_floor_and_wait_for_admission(tmp_path):
    ckpt = str(tmp_path)
    assert elastic.latest_grow_epoch(ckpt) == 0
    elastic.publish_grow_offer(ckpt, 0, 2, [0, 1], 1.0)
    elastic.publish_grow_offer(ckpt, 0, 5, [0, 2], 2.0)
    assert elastic.latest_grow_epoch(ckpt) == 5
    # newest offer above the floor NAMING the rank, or nothing
    assert elastic.read_grow_offer(ckpt, min_epoch=0, rank=1)["epoch"] == 2
    assert elastic.read_grow_offer(ckpt, min_epoch=2, rank=1) is None
    assert elastic.read_grow_offer(ckpt, min_epoch=0, rank=2)["epoch"] == 5
    got = elastic.wait_for_admission(ckpt, 2, floor=2, timeout=1.0,
                                     poll=0.01)
    assert got["epoch"] == 5 and got["survivors"] == [0, 2]
    # typed failure — never a hang — when no survivor answers (injected
    # clock: zero wall-time waiting)
    fake = {"t": 0.0}
    with pytest.raises(elastic.ElasticJoinError, match="no survivor"):
        elastic.wait_for_admission(
            ckpt, 7, floor=5, timeout=30.0, poll=1.0,
            clock=lambda: fake["t"],
            sleep=lambda s: fake.__setitem__("t", fake["t"] + s))
    assert fake["t"] >= 30.0


def test_cluster_position_reads_newest_loadable_driver_state(tmp_path):
    """cluster_position is the joiner's gate coordinate: the newest
    loadable snapshot's (epoch, neval) — stored already incremented to
    the NEXT iteration, the exact coordinate chaos.at_position
    publishes, so host.return@rank=@epoch:iteration gates line up."""
    assert elastic.cluster_position(str(tmp_path)) is None
    file_io.save_checkpoint(str(tmp_path), 4,
                            {"params": {}, "state": {}},
                            {"method": {},
                             "driver_state": {"epoch": 2, "neval": 5}})
    assert elastic.cluster_position(str(tmp_path)) == (2, 5)
    # an entry without a position is skipped, the older one still answers
    file_io.save_checkpoint(str(tmp_path), 9,
                            {"params": {}, "state": {}},
                            {"method": {}, "driver_state": {}})
    assert elastic.cluster_position(str(tmp_path)) == (2, 5)


def test_join_deferred_during_inflight_shrink(tmp_path, monkeypatch):
    """A join intent observed while a SHRINK promotion is pending must
    be DEFERRED (not dropped): re-forms never interleave.  Once the
    shrink's reform completes, the same boundary check raises the
    planned _ElasticJoinSignal — internal control flow that consumes no
    retry budget."""
    from bigdl_tpu.optim.optimizer import _ElasticJoinSignal
    monkeypatch.setenv("BIGDL_TPU_ELASTIC_WORLD", "2")
    monkeypatch.setenv("BIGDL_TPU_ELASTIC_RANK", "0")
    monkeypatch.setenv("BIGDL_TPU_ELASTIC_PEER_LOST", "3600")
    opt = (Optimizer(nn.Sequential().add(nn.Linear(6, 2)), _dataset(),
                     nn.CrossEntropyCriterion())
           .set_checkpoint(str(tmp_path), Trigger.every_epoch()))
    try:
        Engine.reform(world=1, rank=0, survivors=[0])  # post-shrink world
        elastic.publish_join_intent(str(tmp_path), 1, 0.0, generation=1)
        sup = Supervisor({}, peer_dir=os.path.join(str(tmp_path),
                                                   "heartbeats"),
                         rank=0, world=1, publish_interval=0.0)
        opt._sup = sup
        sup.hold_elastic()                    # an in-flight shrink round
        opt._check_join(None)                 # deferred: no signal
        sup.reform(rank=0, world=1, epoch=1, lost=[1])  # shrink done
        with pytest.raises(_ElasticJoinSignal) as ei:
            opt._check_join(None)
        assert ei.value.joiners == (1,)
        # an intent from THIS rank is excluded outright
        elastic.clear_join_intent(str(tmp_path), 1)
        elastic.publish_join_intent(str(tmp_path), 0, 0.0, generation=1)
        opt._check_join(None)                 # no signal
    finally:
        Engine.reset()


# ---------------------------------------------------------------------------
# re-form: Engine topology + sharding remap + batch rescale
# ---------------------------------------------------------------------------

def test_engine_logical_world_env_and_reform(monkeypatch):
    monkeypatch.setenv("BIGDL_TPU_ELASTIC_WORLD", "2")
    monkeypatch.setenv("BIGDL_TPU_ELASTIC_RANK", "1")
    assert Engine.world() == 2 and Engine.rank() == 1
    assert Engine.elastic_active()
    assert Engine.data_shard_info() == (1, 2)
    assert not Engine.is_writer()
    # shrink to the surviving slice: rank 1 alone, keeping its id
    Engine.reform(world=1, rank=1, survivors=[1])
    assert Engine.world() == 1 and Engine.rank() == 1
    assert Engine.survivors() == (1,)
    assert Engine.data_shard_info() == (0, 1)
    assert Engine.is_writer()
    with pytest.raises(ValueError, match="not in survivors"):
        Engine.reform(rank=0, survivors=[1])
    Engine.reset()
    assert Engine._elastic is None


def test_engine_reform_device_subset_rebuilds_mesh():
    """In-process simulated host loss: reform over a device subset
    rebuilds the 1-D data mesh (8 virtual devices -> 4)."""
    import jax
    Engine.init()
    assert Engine.device_count() == 8
    mesh = Engine.reform(world=1, rank=0, survivors=[0],
                         devices=jax.devices()[:4])
    assert mesh.shape["data"] == 4
    assert Engine.mesh() is mesh


def test_mesh_reform_error_when_widened_world_breaks_shard_groups():
    """Widening must keep the non-data shard block intact or fail TYPED
    (MeshReformError) — never silently re-lay-out sharded parameters."""
    import jax
    from jax.sharding import Mesh

    from bigdl_tpu.parallel.layout import MeshReformError
    Engine.init()
    devs = jax.devices()
    narrow = Mesh(np.array(devs[:4]).reshape(1, 4), ("data", "fsdp"))
    # the happy widen: data 1 -> 2, the fsdp block of 4 preserved
    wide = Engine._reform_data_axis(narrow, devs[:8])
    assert wide.shape["data"] == 2 and wide.shape["fsdp"] == 4
    # 2x3 -> 8 devices: 8 % 3 != 0, the fsdp groups cannot survive
    mesh = Mesh(np.array(devs[:6]).reshape(2, 3), ("data", "fsdp"))
    with pytest.raises(MeshReformError, match="must divide"):
        Engine._reform_data_axis(mesh, devs[:8])
    # no data axis at all: nothing to widen
    flat = Mesh(np.array(devs[:4]), ("fsdp",))
    with pytest.raises(MeshReformError, match="no 'data' axis"):
        Engine._reform_data_axis(flat, devs[:8])


def test_sharding_remap_widens_zero_params_value_equal():
    """Grow direction: ZeRO slots sharded 1/1 re-place to 1/2 with
    identical values — the joiner-admission re-slice of _elastic_grow."""
    import jax
    from jax.sharding import Mesh

    strategy = ShardedDataParallel(min_size=1)
    one = Mesh(np.array(jax.devices()[:1]), ("data",))
    two = Mesh(np.array(jax.devices()[:2]), ("data",))
    params = {"w": np.arange(32.0, dtype=np.float32).reshape(4, 8),
              "b": np.arange(8.0, dtype=np.float32)}
    placed = strategy.remap(one, params)
    widened = strategy.remap(two, placed)
    assert widened["w"].sharding.mesh.shape["data"] == 2
    np.testing.assert_array_equal(np.asarray(widened["w"]), params["w"])
    np.testing.assert_array_equal(np.asarray(widened["b"]), params["b"])


def test_sharding_remap_reslices_zero_params():
    """ZeRO params sharded 1/N re-place to 1/N' on the shrunken mesh with
    identical values — the fused-buffer/slot re-slice the compiled-step
    rebuild relies on."""
    import jax
    from jax.sharding import Mesh

    strategy = ShardedDataParallel(min_size=1)
    big = Mesh(np.array(jax.devices()[:8]), ("data",))
    small = Mesh(np.array(jax.devices()[:4]), ("data",))
    params = {"w": np.arange(64.0, dtype=np.float32).reshape(8, 8),
              "b": np.arange(8.0, dtype=np.float32)}
    placed = strategy.remap(big, params)
    assert placed["w"].sharding.mesh.shape["data"] == 8
    replaced = strategy.remap(small, placed)
    assert replaced["w"].sharding.mesh.shape["data"] == 4
    np.testing.assert_array_equal(np.asarray(replaced["w"]), params["w"])
    np.testing.assert_array_equal(np.asarray(replaced["b"]), params["b"])
    # DataParallel remap lands replicated on the new mesh
    rep = DataParallel().remap(small, placed)
    assert rep["w"].sharding.is_fully_replicated


def _dataset(n=64, batch=16):
    rng = np.random.default_rng(0)
    samples = [Sample(rng.standard_normal(6).astype(np.float32),
                      np.float32(i % 2)) for i in range(n)]
    return DataSet.array(samples).transform(
        SampleToMiniBatch(batch, drop_last=True))


def test_rescale_batches_ceil_rounding_rule():
    """Global batch preserved across the shrink: per-host batch becomes
    ceil(B*W/W') — it may GROW by up to W'-1 rows, never shrink."""
    opt = Optimizer(nn.Sequential().add(nn.Linear(6, 2)), _dataset(),
                    nn.CrossEntropyCriterion())
    opt._rescale_batches(4, 2)           # 16*4=64 over 2 -> 32
    b = opt._find_batchers(opt.dataset)[0]
    assert b.batch_size == 32
    opt._rescale_batches(2, 3)           # 32*2=64 over 3 -> ceil = 22
    assert b.batch_size == math.ceil(64 / 3) == 22
    opt._rescale_batches(3, 3)           # no-op on equal worlds
    assert b.batch_size == 22


def test_rescale_batches_grow_restores_configured_value():
    """The grow invariant: after a shrink DOUBLES the per-host batch, a
    grow back to the original world returns it exactly to the configured
    value (the shrink/grow round-trip is lossless), and ceil rounding
    applies in the grow direction too."""
    opt = Optimizer(nn.Sequential().add(nn.Linear(6, 2)), _dataset(),
                    nn.CrossEntropyCriterion())
    b = opt._find_batchers(opt.dataset)[0]
    opt._rescale_batches(2, 1)           # shrink: 16*2=32 over 1
    assert b.batch_size == 32
    opt._rescale_batches(1, 2)           # grow back: 32 over 2 -> 16
    assert b.batch_size == 16
    opt._rescale_batches(2, 3)           # widen past it: ceil(32/3) = 11
    assert b.batch_size == math.ceil(32 / 3) == 11


# ---------------------------------------------------------------------------
# acceptance: armed-but-no-fault bit-identity + the 2-rank drill
# ---------------------------------------------------------------------------

def _train_losses(tmp_path, tag):
    from bigdl_tpu.common import set_seed
    set_seed(11)
    losses = []
    opt = (Optimizer(nn.Sequential().add(nn.Linear(6, 2)), _dataset(),
                     nn.CrossEntropyCriterion())
           .set_optim_method(Adam(1e-2))
           .set_end_when(Trigger.max_epoch(2))
           .set_checkpoint(str(tmp_path / tag), Trigger.every_epoch()))
    orig = opt._observe_loss
    opt._observe_loss = lambda lossf, state: losses.append(
        orig(lossf, state)) or losses[-1]
    trained = opt.optimize()
    import jax
    params = [np.asarray(l).tobytes() for l in jax.tree.leaves(
        trained.params)]
    return losses, params


def test_elasticity_armed_no_fault_bit_identical(tmp_path, monkeypatch):
    """Acceptance bound: arming elasticity (threshold + supervision)
    with no fault must leave training bit-identical to an unarmed run —
    the subsystem watches, it never touches the math."""
    base_losses, base_params = _train_losses(tmp_path, "plain")
    monkeypatch.setenv("BIGDL_TPU_ELASTIC_PEER_LOST", "60")
    monkeypatch.setenv("BIGDL_TPU_SUPERVISE_STEP", "120")
    armed_losses, armed_params = _train_losses(tmp_path, "armed")
    assert armed_losses == base_losses
    assert armed_params == base_params


def test_optimizer_elastic_recover_in_process_zero(tmp_path, monkeypatch):
    """Optimizer-level recovery without subprocesses: a logical world-2
    run under ShardedDataParallel checkpoints, a staged PeerLostError
    drives _elastic_recover, and the run RE-TRAINS to completion on the
    shrunken world with the per-host batch rescaled — proving the jitted
    step, ZeRO slices, and fused-buffer specs rebuild against the
    re-formed topology."""
    monkeypatch.setenv("BIGDL_TPU_ELASTIC_WORLD", "2")
    monkeypatch.setenv("BIGDL_TPU_ELASTIC_RANK", "0")
    monkeypatch.setenv("BIGDL_TPU_ELASTIC_PEER_LOST", "3600")
    ds = _dataset(n=128, batch=16)
    opt = (Optimizer(nn.Sequential().add(nn.Linear(6, 2)), ds,
                     nn.CrossEntropyCriterion(),
                     strategy=ShardedDataParallel(min_size=1))
           .set_optim_method(Adam(1e-2))
           .set_end_when(Trigger.max_epoch(2))
           .set_checkpoint(str(tmp_path), Trigger.several_iteration(1)))
    opt.optimize()
    assert file_io.latest_checkpoint(str(tmp_path)) is not None
    assert Engine.data_shard_info() == (0, 2)  # fed half the corpus

    elastic.set_last_peer_lost("host 1 gone", [1], 1)
    err = elastic.PeerLostError()
    opt._elastic_recover(err)
    plan = opt._elastic_plan
    assert plan.neval == file_io.latest_checkpoint(str(tmp_path))[2]
    assert Engine.world() == 1 and Engine.survivors() == (0,)
    assert opt._find_batchers(opt.dataset)[0].batch_size == 32
    assert opt._compiled is None  # the old-world step is torn down

    # the shrunken world trains to the (restored) end trigger: the
    # compiled step rebuilt with the new shardings and batch shape
    opt.set_end_when(Trigger.max_epoch(3))
    trained = opt.optimize()
    import jax
    assert all(np.all(np.isfinite(np.asarray(l)))
               for l in jax.tree.leaves(trained.params))
    assert Engine.data_shard_info() == (0, 1)  # full corpus now


def test_elastic_drill_two_ranks_end_to_end(tmp_path):
    """THE acceptance drill (ISSUE 8): 2 subprocess CPU ranks, chaos
    host.lost@1 kills rank 1 mid-epoch; rank 0 detects, negotiates,
    shrinks to world=1 with the global batch preserved, resumes from the
    negotiated entry with elastic.* events in its trace, and its final
    loss bit-matches a clean world-1 run from the same entry.  Driven
    through tools/elastic_smoke.py — the exact artifact the runbook's
    cpu-smoke stage 2i runs."""
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO_ROOT, "tools",
                                      "elastic_smoke.py"),
         "--platform", "cpu", "--ckpt-dir", str(tmp_path)],
        capture_output=True, text=True, timeout=420,
        env={**os.environ, "PYTHONPATH": _REPO_ROOT})
    lines = [l for l in proc.stdout.splitlines() if l.startswith("{")]
    assert lines, f"no JSON from the drill:\n{proc.stderr[-3000:]}"
    out = json.loads(lines[-1])
    assert proc.returncode == 0, out
    assert out["recovered"] is True
    assert out["world_after"] == 1
    assert out["batch_after"] == 32          # 16 x 2 ranks, preserved
    assert out["rank1_rc"] == 117            # chaos ExitAt's drill code
    assert out["loss_match"] is True
    assert {"elastic.detect", "elastic.negotiate", "elastic.reform",
            "elastic.resume"} <= set(out["elastic_events"])
    # the drill rolled back to a real lineage entry
    assert out["neval_resumed"] >= 1
    snaps = glob.glob(os.path.join(str(tmp_path), "ckpt", "model.*"))
    assert snaps, "drill left no lineage behind"


def test_elastic_grow_drill_two_ranks_end_to_end(tmp_path):
    """THE acceptance drill (ISSUE 16): kill-then-return in ONE run.
    Chaos kills rank 1 mid-epoch (world 2 -> 1, per-host batch doubles);
    the same rank re-spawns as a joiner, waits for its own death
    certificate, announces via host.return@1 chaos gating, and is
    admitted at the next checkpoint boundary (world 1 -> 2, batch back
    down).  The release feed must stay gap-free across BOTH resizes with
    promotions after the grow, and both ranks must bit-match a clean
    world-2 run resumed from the join snapshot.  Driven through
    tools/elastic_smoke.py --grow — the runbook's cpu-smoke stage 2p."""
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO_ROOT, "tools",
                                      "elastic_smoke.py"),
         "--grow", "--platform", "cpu", "--ckpt-dir", str(tmp_path)],
        capture_output=True, text=True, timeout=420,
        env={**os.environ, "PYTHONPATH": _REPO_ROOT})
    lines = [l for l in proc.stdout.splitlines() if l.startswith("{")]
    assert lines, f"no JSON from the drill:\n{proc.stderr[-3000:]}"
    out = json.loads(lines[-1])
    assert proc.returncode == 0, out
    assert out["recovered"] is True and out["joined"] is True
    assert out["rank1_rc"] == 117            # chaos ExitAt's drill code
    # the survivor lived through shrink THEN grow, batch 16 -> 32 -> 16
    assert [h["kind"] for h in out["history_rank0"]] == ["shrink", "grow"]
    assert [h["world"] for h in out["history_rank0"]] == [1, 2]
    assert [h["batch"] for h in out["history_rank0"]] == [32, 16]
    assert [h["kind"] for h in out["history_joiner"]] == ["join"]
    # both ranks' final params bit-match the clean world-2 resume
    assert out["loss_match"] is True
    # the deployment loop never saw a gap or a rejection, and promoted
    # a release published AFTER the grow
    assert out["release_gap_free"] is True and out["rejected"] == 0
    assert out["promoted_after_grow"] >= 1
    for events in out["elastic_events"].values():
        assert {"elastic.join", "elastic.agree", "elastic.reform",
                "elastic.resume"} <= set(events)
