"""Sequence parallelism (ring/Ulysses attention), pipeline parallelism, and
the flash-attention op — on the 8-virtual-CPU-device mesh (conftest.py),
mirroring the reference's simulate-a-cluster-in-one-process test strategy
(DistriOptimizerSpec.scala:33-41)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh

from bigdl_tpu.ops.attention import flash_attention, mha_reference
from bigdl_tpu.parallel import (ring_attention, ulysses_attention,
                                pipeline_apply, stack_stage_params)


def _qkv(B=2, H=4, T=32, D=16, seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    return tuple(jax.random.normal(k, (B, H, T, D), jnp.float32) for k in ks)


class TestFlashAttention:
    def test_matches_reference_noncausal(self):
        q, k, v = _qkv()
        out = flash_attention(q, k, v, use_pallas=True, interpret=True,
                              block_q=16, block_k=16)
        ref = mha_reference(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_matches_reference_causal(self):
        q, k, v = _qkv(seed=1)
        out = flash_attention(q, k, v, causal=True, use_pallas=True,
                              interpret=True, block_q=16, block_k=16)
        ref = mha_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_grad_through_pallas_path(self):
        """Training differentiates through flash_attention: the pallas
        forward must carry a VJP (pallas_call itself has no autodiff rule
        — without the custom_vjp this raises on real TPUs) and its
        gradients must match differentiating the dense reference."""
        q, k, v = _qkv(seed=3)

        def f_pallas(q, k, v):
            out = flash_attention(q, k, v, causal=True, use_pallas=True,
                                  interpret=True, block_q=16, block_k=16)
            return jnp.sum(out * out)

        def f_ref(q, k, v):
            out = mha_reference(q, k, v, causal=True)
            return jnp.sum(out * out)

        gp = jax.grad(f_pallas, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gp, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-5, rtol=2e-5)

    def test_grad_pallas_ragged_blocks_and_noncausal(self):
        """Blockwise bwd edge cases: Tq not a multiple of block_q, and the
        non-causal mask — both must match dense-reference gradients."""
        r = np.random.default_rng(9)
        q = jnp.asarray(r.normal(size=(2, 2, 21, 8)), jnp.float32)
        k = jnp.asarray(r.normal(size=(2, 2, 21, 8)), jnp.float32)
        v = jnp.asarray(r.normal(size=(2, 2, 21, 8)), jnp.float32)
        for causal in (False, True):
            def f_pallas(q, k, v):
                out = flash_attention(q, k, v, causal=causal,
                                      use_pallas=True, interpret=True,
                                      block_q=8, block_k=8)
                return jnp.sum(jnp.sin(out))

            def f_ref(q, k, v):
                return jnp.sum(jnp.sin(mha_reference(q, k, v,
                                                     causal=causal)))

            gp = jax.grad(f_pallas, argnums=(0, 1, 2))(q, k, v)
            gr = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
            for a, b in zip(gp, gr):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           atol=3e-5, rtol=3e-5,
                                           err_msg=f"causal={causal}")

    def test_fallback_path(self):
        q, k, v = _qkv(seed=2)
        out = flash_attention(q, k, v)  # auto: jnp path on CPU
        ref = mha_reference(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref))


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_full_attention(self, causal):
        n = 8
        mesh = Mesh(np.array(jax.devices()[:n]), ("seq",))
        q, k, v = _qkv(B=2, H=2, T=4 * n, D=8, seed=3)
        out = ring_attention(q, k, v, mesh=mesh, causal=causal,
                             batch_axis=None)
        ref = mha_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_2d_mesh_data_and_seq(self):
        mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4),
                    ("data", "seq"))
        q, k, v = _qkv(B=4, H=2, T=16, D=8, seed=4)
        out = ring_attention(q, k, v, mesh=mesh, causal=True)
        ref = mha_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_grad_flows(self):
        mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))
        q, k, v = _qkv(B=1, H=2, T=8, D=8, seed=5)

        def loss(q, k, v):
            return jnp.sum(ring_attention(q, k, v, mesh=mesh, causal=True,
                                          batch_axis=None) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(mha_reference(q, k, v, causal=True) ** 2)

        g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-4, rtol=2e-4)


class TestUlyssesAttention:
    def test_matches_full_attention(self):
        n = 4
        mesh = Mesh(np.array(jax.devices()[:n]), ("seq",))
        q, k, v = _qkv(B=2, H=4, T=4 * n, D=8, seed=6)
        out = ulysses_attention(q, k, v, mesh=mesh, causal=True,
                                batch_axis=None)
        ref = mha_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_rejects_indivisible_heads(self):
        mesh = Mesh(np.array(jax.devices()[:8]), ("seq",))
        q, k, v = _qkv(B=1, H=4, T=16, D=8)
        with pytest.raises(ValueError):
            ulysses_attention(q, k, v, mesh=mesh)


class TestPipeline:
    def _stages(self, n, F=16, seed=7):
        keys = jax.random.split(jax.random.key(seed), n)
        return [{"w": jax.random.normal(k, (F, F)) * 0.1,
                 "b": jnp.zeros((F,))} for k in keys]

    @staticmethod
    def _stage_fn(p, x):
        return jnp.tanh(x @ p["w"] + p["b"])

    def test_forward_matches_sequential(self):
        n = 4
        mesh = Mesh(np.array(jax.devices()[:n]), ("pipe",))
        stages = self._stages(n)
        stacked = stack_stage_params(stages)
        x = jax.random.normal(jax.random.key(8), (8, 16))
        y = pipeline_apply(self._stage_fn, stacked, x, mesh=mesh,
                           num_microbatches=4, batch_axis=None)
        y_ref = x
        for p in stages:
            y_ref = self._stage_fn(p, y_ref)
        np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                                   atol=1e-5, rtol=1e-5)

    def test_grad_matches_sequential(self):
        n = 4
        mesh = Mesh(np.array(jax.devices()[:n]), ("pipe",))
        stages = self._stages(n, seed=9)
        stacked = stack_stage_params(stages)
        x = jax.random.normal(jax.random.key(10), (8, 16))

        def loss(sp):
            y = pipeline_apply(self._stage_fn, sp, x, mesh=mesh,
                               num_microbatches=4, batch_axis=None)
            return jnp.mean(y ** 2)

        def loss_ref(stages_list):
            y = x
            for p in stages_list:
                y = self._stage_fn(p, y)
            return jnp.mean(y ** 2)

        g = jax.jit(jax.grad(loss))(stacked)
        g_ref = jax.grad(loss_ref)(stages)
        g_ref_stacked = stack_stage_params(g_ref)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4),
            g, g_ref_stacked)

    def test_remat_same_result(self):
        n = 2
        mesh = Mesh(np.array(jax.devices()[:n]), ("pipe",))
        stages = self._stages(n, seed=11)
        stacked = stack_stage_params(stages)
        x = jax.random.normal(jax.random.key(12), (4, 16))
        y1 = pipeline_apply(self._stage_fn, stacked, x, mesh=mesh,
                            num_microbatches=2, batch_axis=None, remat=True)
        y2 = pipeline_apply(self._stage_fn, stacked, x, mesh=mesh,
                            num_microbatches=2, batch_axis=None, remat=False)
        np.testing.assert_allclose(np.asarray(y1), np.asarray(y2), atol=1e-6)

    def test_data_parallel_times_pipeline(self):
        mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4),
                    ("data", "pipe"))
        stages = self._stages(4, seed=13)
        stacked = stack_stage_params(stages)
        x = jax.random.normal(jax.random.key(14), (8, 16))
        y = pipeline_apply(self._stage_fn, stacked, x, mesh=mesh,
                           num_microbatches=2)
        y_ref = x
        for p in stages:
            y_ref = self._stage_fn(p, y_ref)
        np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                                   atol=1e-5, rtol=1e-5)


class TestDryrunExtras:
    def test_run(self):
        from bigdl_tpu.parallel import dryrun_extras
        mesh = Mesh(np.array(jax.devices()[:8]), ("data",))
        dryrun_extras.run(mesh)


class TestMultiHeadAttention:
    def test_forward_shapes_and_seq_parallel_parity(self):
        from bigdl_tpu.nn import MultiHeadAttention
        from bigdl_tpu.utils.engine import Engine
        x = jax.random.normal(jax.random.key(20), (2, 16, 32))
        mha = MultiHeadAttention(32, 4, causal=True).build(jax.random.key(21))
        y, _ = mha.apply(mha.params, mha.state, x)
        assert y.shape == (2, 16, 32)

        Engine.init(mesh_shape={"seq": 4}, devices=jax.devices()[:4])
        sp = MultiHeadAttention(32, 4, causal=True, seq_parallel=True)
        sp.params, sp.state = mha.params, mha.state
        with Engine.mesh():
            y2, _ = sp.apply(sp.params, sp.state, x)
        np.testing.assert_allclose(np.asarray(y), np.asarray(y2),
                                   atol=2e-5, rtol=2e-5)


class TestFlashAttentionPadding:
    def test_non_divisible_lengths(self):
        # T=40 with block 16 exercises the pad+mask path
        q, k, v = _qkv(B=2, H=2, T=40, D=16, seed=30)
        for causal in (False, True):
            out = flash_attention(q, k, v, causal=causal, use_pallas=True,
                                  interpret=True, block_q=16, block_k=16)
            ref = mha_reference(q, k, v, causal=causal)
            np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                       atol=1e-5, rtol=1e-5)

    def test_cross_attention_lengths(self):
        ks = jax.random.split(jax.random.key(31), 3)
        q = jax.random.normal(ks[0], (1, 2, 24, 8), jnp.float32)
        k = jax.random.normal(ks[1], (1, 2, 40, 8), jnp.float32)
        v = jax.random.normal(ks[2], (1, 2, 40, 8), jnp.float32)
        out = flash_attention(q, k, v, use_pallas=True, interpret=True,
                              block_q=16, block_k=16)
        ref = mha_reference(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)


class TestRingChunkedInner:
    def test_ring_with_chunked_inner(self, monkeypatch):
        # force tiny chunks so the scan path in _block_attn is exercised
        import importlib
        ra = importlib.import_module("bigdl_tpu.parallel.ring_attention")
        monkeypatch.setattr(ra, "_CHUNK", 4)
        mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))
        q, k, v = _qkv(B=1, H=2, T=32, D=8, seed=32)
        out = ra.ring_attention(q, k, v, mesh=mesh, causal=True,
                                batch_axis=None)
        ref = mha_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)


class TestOptStateSharding:
    """ShardedDataParallel must shard same-shaped optimizer slots like the
    params (ZeRO — the TPU-native form of the reference's per-node 1/N slice
    update, DistriOptimizer.scala:265-280)."""

    def test_momentum_inherits_param_sharding(self):
        from bigdl_tpu.parallel.sharding import ShardedDataParallel
        from bigdl_tpu.optim import SGD
        mesh = Mesh(np.array(jax.devices()).reshape(8), ("data",))
        params = {"w": jnp.zeros((1024, 64)), "b": jnp.zeros((64,))}
        strat = ShardedDataParallel(min_size=1024)
        p_sh = strat.param_sharding(mesh, params)
        opt = SGD(learning_rate=0.1, momentum=0.9)
        opt_state = opt.init_state(params)
        os_sh = strat.opt_state_sharding(mesh, opt_state, params, p_sh)
        placed = jax.device_put(opt_state, os_sh)
        flat = jax.tree_util.tree_flatten_with_path(placed)[0]
        mom_w = [l for kp, l in flat if l.ndim == 2]
        assert mom_w, "expected a 2-D momentum slot"
        for leaf in mom_w:
            assert len(leaf.sharding.device_set) == 8  # sharded, not replicated
            assert "data" in jax.tree.leaves(
                [ax for ax in leaf.sharding.spec if ax])

    def test_scalars_replicate(self):
        from bigdl_tpu.parallel.sharding import ShardedDataParallel
        mesh = Mesh(np.array(jax.devices()).reshape(8), ("data",))
        params = {"w": jnp.zeros((1024, 64))}
        strat = ShardedDataParallel(min_size=1024)
        p_sh = strat.param_sharding(mesh, params)
        state = {"t": jnp.zeros(()), "m": {"w": jnp.zeros((1024, 64))}}
        sh = strat.opt_state_sharding(mesh, state, params, p_sh)
        assert sh["t"].spec == jax.sharding.PartitionSpec()

    def test_ambiguous_shapes_replicate(self):
        """Two same-shaped params with different shardings: their optimizer
        slots must not be guessed by shape (row- vs column-parallel TP)."""
        from bigdl_tpu.parallel.sharding import ShardingStrategy
        from jax.sharding import NamedSharding, PartitionSpec as P
        mesh = Mesh(np.array(jax.devices()).reshape(8), ("data",))
        params = {"a": jnp.zeros((64, 64)), "b": jnp.zeros((64, 64))}
        p_sh = {"a": NamedSharding(mesh, P("data", None)),
                "b": NamedSharding(mesh, P(None, "data"))}
        # state that is NOT structurally identical to params (extra leaf)
        state = {"slot_a": jnp.zeros((64, 64)), "t": jnp.zeros(())}
        sh = ShardingStrategy().opt_state_sharding(mesh, state, params, p_sh)
        assert sh["slot_a"].spec == P()  # ambiguous -> replicated
        # structurally-matching subtree still inherits exactly
        state2 = {"m": {"a": jnp.zeros((64, 64)), "b": jnp.zeros((64, 64))},
                  "t": jnp.zeros(())}
        sh2 = ShardingStrategy().opt_state_sharding(mesh, state2, params, p_sh)
        assert sh2["m"]["a"].spec == P("data", None)
        assert sh2["m"]["b"].spec == P(None, "data")


class TestExpertParallel:
    """EP: capacity-routed MoE (parallel/expert.py) — dense GSPMD module vs
    explicit shard_map all-to-all implementation."""

    def _model(self, E=8, D=16, H=32, k=1, cf=4.0, axis=None):
        from bigdl_tpu.parallel import MoEFFN
        return MoEFFN(D, H, E, k=k, capacity_factor=cf,
                      expert_axis=axis).build(jax.random.key(0))

    def test_dense_routing_matches_manual(self):
        """With ample capacity and k=1, MoE output == gate-prob-weighted
        output of each token's argmax expert."""
        m = self._model().evaluate()  # eval: no router jitter
        x = jax.random.normal(jax.random.key(1), (32, 16))
        y = m.forward(x)
        p = m.params
        logits = x @ p["gate"]
        probs = jax.nn.softmax(logits, axis=-1)
        idx = jnp.argmax(logits, axis=-1)
        h = jnp.maximum(jnp.einsum("td,edh->teh", x, p["w1"])
                        + p["b1"][None], 0.0)
        out_e = jnp.einsum("teh,ehd->ted", h, p["w2"]) + p["b2"][None]
        expect = (jnp.take_along_axis(
            out_e, idx[:, None, None].repeat(16, -1), 1)[:, 0]
            * jnp.take_along_axis(probs, idx[:, None], 1))
        np.testing.assert_allclose(np.asarray(y), np.asarray(expect),
                                   rtol=2e-4, atol=2e-5)

    def test_top2_and_capacity_drop(self):
        """k=2 routes each token to two experts; capacity 1 forces drops —
        dispatch mask never exceeds capacity."""
        from bigdl_tpu.parallel import top_k_routing
        logits = jax.random.normal(jax.random.key(2), (16, 4))
        combine, dispatch, probs, assign = top_k_routing(logits,
                                                         capacity=2, k=2)
        # pre-capacity assignment counts every router choice, dropped or not
        assert float(jnp.sum(assign)) == 32.0  # 16 tokens x k=2
        # per-token: at most 2 slots
        assert float(jnp.max(jnp.sum(dispatch, axis=(1, 2)))) <= 2.0
        # per-expert: never more tokens than capacity
        assert float(jnp.max(jnp.sum(dispatch, axis=(0, 2)))) <= 2.0
        # slot uniqueness: one token per (expert, slot)
        assert float(jnp.max(jnp.sum(dispatch, axis=0))) <= 1.0

    def test_gate_gradient_flows(self):
        m = self._model()
        x = jax.random.normal(jax.random.key(3), (32, 16))

        def loss(params):
            y = m.apply(params, m.state, x, training=True)[0]
            return jnp.sum(jnp.square(y))

        g = jax.grad(loss)(m.params)
        assert float(jnp.sum(jnp.abs(g["gate"]))) > 0.0
        assert float(jnp.sum(jnp.abs(g["w1"]))) > 0.0

    def test_shard_map_matches_dense(self):
        """expert_parallel_ffn (explicit all_to_all over the expert axis)
        must match the dense MoEFFN math when nothing overflows."""
        from bigdl_tpu.parallel import expert_parallel_ffn
        mesh = Mesh(np.array(jax.devices()[:4]), ("expert",))
        m = self._model(E=8, cf=8.0).evaluate()  # eval: no router jitter
        x = jax.random.normal(jax.random.key(4), (64, 16))
        y_dense = m.forward(x)
        y_ep = expert_parallel_ffn(mesh, m.params, x, k=1,
                                   capacity_factor=8.0)
        np.testing.assert_allclose(np.asarray(y_ep), np.asarray(y_dense),
                                   rtol=2e-4, atol=2e-5)

    def test_aux_loss_balanced_vs_collapsed(self):
        from bigdl_tpu.parallel import top_k_routing, load_balancing_loss
        T, E = 64, 4
        balanced = jnp.tile(jnp.eye(E) * 10.0, (T // E, 1))
        collapsed = jnp.zeros((T, E)).at[:, 0].set(10.0)
        _, _, p1, a1 = top_k_routing(balanced, capacity=T, k=1)
        _, _, p2, a2 = top_k_routing(collapsed, capacity=T, k=1)
        assert float(load_balancing_loss(p1, a1)) < \
            float(load_balancing_loss(p2, a2))
        # aux pressure must NOT saturate under capacity overflow: with a
        # tiny capacity the collapsed router keeps the same (pre-drop) loss
        _, _, p3, a3 = top_k_routing(collapsed, capacity=2, k=1)
        np.testing.assert_allclose(float(load_balancing_loss(p3, a3)),
                                   float(load_balancing_loss(p2, a2)),
                                   rtol=1e-6)
        # k > num_experts is a hard error, not silent expert-0 double-dispatch
        with pytest.raises(ValueError):
            top_k_routing(balanced, capacity=4, k=5)

    def test_moe_lm_trains_on_data_x_expert_mesh(self):
        """GSPMD EP end-to-end: the MoE TransformerLM trains through the
        Optimizer's compiled step on a {"data": 2, "expert": 4} mesh with
        expert_axis sharding constraints active (MoEFFN._constrain) —
        proving EP composes with data-parallel training, not just the
        shard_map parity path."""
        import bigdl_tpu.nn as nn
        from bigdl_tpu.common import set_seed
        from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
        from bigdl_tpu.models import TransformerLM
        from bigdl_tpu.optim import Adam, Optimizer, Trigger
        from bigdl_tpu.utils.engine import Engine

        Engine.reset()
        Engine.init(mesh_shape={"data": 2, "expert": 4})
        set_seed(3)
        vocab, t = 12, 8
        seqs = [[(s + i) % vocab for i in range(t + 1)]
                for s in range(vocab)] * 8
        samples = [Sample(np.asarray(s[:-1], np.int32),
                          np.asarray(s[1:], np.int32)) for s in seqs]
        ds = DataSet.array(samples).transform(
            SampleToMiniBatch(32, drop_last=True))
        model = TransformerLM(vocab_size=vocab, max_len=t, d_model=32,
                              num_heads=4, num_layers=2, num_experts=4,
                              expert_axis="expert")
        crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                           size_average=True)
        from bigdl_tpu.optim import Loss, Trigger as Trg
        opt = (Optimizer(model, ds, crit)
               .set_optim_method(Adam(3e-3))
               .set_end_when(Trigger.max_epoch(5))
               .set_validation(Trg.every_epoch(), ds, [Loss(crit)]))
        from bigdl_tpu.parallel import MoEFFN
        MoEFFN._warned_no_mesh = False
        opt.optimize()
        # the expert-axis constraint must have BOUND (the step is traced
        # under the mesh context) — a silent replicated-experts fallback
        # would set the warning latch
        assert MoEFFN._warned_no_mesh is False
        loss = opt.optim_method.hyper["loss"]
        assert np.isfinite(loss) and loss < 2.4  # descending from ln(12)


def test_attn_impl_env_override(monkeypatch):
    """BIGDL_TPU_ATTN_IMPL forces the dispatch; both paths agree (the
    flash-vs-XLA race is measured on hardware, so the default must stay
    overridable — and plugin platform names must not silently reroute)."""
    import numpy as np
    import jax

    from bigdl_tpu.ops.attention import flash_attention

    q = jax.random.normal(jax.random.PRNGKey(0), (1, 2, 16, 8))
    k = jax.random.normal(jax.random.PRNGKey(1), (1, 2, 16, 8))
    v = jax.random.normal(jax.random.PRNGKey(2), (1, 2, 16, 8))
    monkeypatch.setenv("BIGDL_TPU_ATTN_IMPL", "jnp")
    o_jnp = flash_attention(q, k, v, causal=True)
    monkeypatch.setenv("BIGDL_TPU_ATTN_IMPL", "pallas")
    o_pl = flash_attention(q, k, v, causal=True, interpret=True)
    np.testing.assert_allclose(np.asarray(o_jnp), np.asarray(o_pl),
                               rtol=1e-4, atol=1e-4)
    monkeypatch.setenv("BIGDL_TPU_ATTN_IMPL", "xla")
    with pytest.raises(ValueError, match="ATTN_IMPL"):
        flash_attention(q, k, v, causal=True)
