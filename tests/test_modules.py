"""Layer unit tests: shapes, gradients, and golden values vs numpy references.

Models the reference's three-tier strategy (SURVEY.md §4): the Torch7 oracle of
`test/.../torch/` (122 specs) is replaced by numpy-computed golden values.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import bigdl_tpu.nn as nn


def rng():
    return jax.random.key(0)


def test_linear_forward_matches_numpy():
    m = nn.Linear(4, 3).build(rng())
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 4)),
                    dtype=jnp.float32)
    y = m.forward(x)
    w, b = np.asarray(m.params["weight"]), np.asarray(m.params["bias"])
    expect = np.asarray(x) @ w.T + b
    np.testing.assert_allclose(np.asarray(y), expect, rtol=1e-5, atol=1e-5)


def test_linear_backward_accumulates():
    m = nn.Linear(4, 3).build(rng())
    x = jnp.ones((2, 4))
    y = m.forward(x)
    g = jnp.ones_like(y)
    gx = m.backward(x, g)
    assert gx.shape == x.shape
    # accGradParameters semantics: second backward doubles the grads
    g1 = np.asarray(m.grads["weight"]).copy()
    m.backward(x, g)
    np.testing.assert_allclose(np.asarray(m.grads["weight"]), 2 * g1, rtol=1e-6)
    m.zero_grad_parameters()
    assert float(jnp.sum(jnp.abs(m.grads["weight"]))) == 0.0


def test_get_parameters_flat_contract():
    m = nn.Sequential().add(nn.Linear(4, 3)).add(nn.ReLU()).add(nn.Linear(3, 2))
    m.build(rng())
    w, g = m.get_parameters()
    assert w.ndim == 1 and w.shape == g.shape
    assert w.shape[0] == 4 * 3 + 3 + 3 * 2 + 2
    m.set_flat_parameters(jnp.zeros_like(w))
    w2, _ = m.get_parameters()
    assert float(jnp.sum(jnp.abs(w2))) == 0.0


def test_spatial_convolution_shape_and_golden():
    m = nn.SpatialConvolution(3, 8, 3, 3, 1, 1, 1, 1).build(rng())
    x = jnp.asarray(np.random.default_rng(1).normal(size=(2, 8, 8, 3)),
                    dtype=jnp.float32)
    y = m.forward(x)
    assert y.shape == (2, 8, 8, 8)
    # golden check of one output pixel against explicit correlation
    w = np.asarray(m.params["weight"])  # (3,3,3,8)
    b = np.asarray(m.params["bias"])
    xp = np.pad(np.asarray(x), ((0, 0), (1, 1), (1, 1), (0, 0)))
    patch = xp[0, 3:6, 4:7, :]  # output pixel (0, 3, 4): window starts at (3, 4)
    expect = np.tensordot(patch, w, axes=([0, 1, 2], [0, 1, 2])) + b
    np.testing.assert_allclose(np.asarray(y)[0, 3, 4], expect, rtol=1e-4,
                               atol=1e-4)


def test_conv_groups():
    m = nn.SpatialConvolution(4, 8, 3, 3, n_group=2).build(rng())
    x = jnp.ones((1, 5, 5, 4))
    assert m.forward(x).shape == (1, 3, 3, 8)


def test_dilated_and_full_convolution():
    m = nn.SpatialDilatedConvolution(3, 4, 3, 3, dilation_w=2, dilation_h=2)
    y = m.build(rng()).forward(jnp.ones((1, 9, 9, 3)))
    assert y.shape == (1, 5, 5, 4)
    # transposed conv doubles spatial size with stride 2
    d = nn.SpatialFullConvolution(3, 4, 4, 4, 2, 2, 1, 1).build(rng())
    y2 = d.forward(jnp.ones((1, 8, 8, 3)))
    assert y2.shape == (1, 16, 16, 4)


def test_temporal_convolution():
    m = nn.TemporalConvolution(16, 32, 5, 2).build(rng())
    y = m.forward(jnp.ones((4, 21, 16)))
    assert y.shape == (4, 9, 32)


def test_max_pooling_golden():
    m = nn.SpatialMaxPooling(2, 2, 2, 2)
    x = jnp.asarray(np.arange(16, dtype=np.float32).reshape(1, 4, 4, 1))
    y = m.build(rng()).forward(x)
    np.testing.assert_allclose(
        np.asarray(y)[0, :, :, 0], [[5, 7], [13, 15]])


def test_avg_pooling():
    m = nn.SpatialAveragePooling(2, 2, 2, 2)
    x = jnp.ones((1, 4, 4, 2))
    np.testing.assert_allclose(np.asarray(m.build(rng()).forward(x)),
                               np.ones((1, 2, 2, 2)))


def test_pool_ceil_mode():
    m = nn.SpatialMaxPooling(3, 3, 2, 2).ceil()
    y = m.build(rng()).forward(jnp.ones((1, 6, 6, 1)))
    assert y.shape == (1, 3, 3, 1)
    m2 = nn.SpatialMaxPooling(3, 3, 2, 2)
    assert m2.build(rng()).forward(jnp.ones((1, 6, 6, 1))).shape == (1, 2, 2, 1)


def test_batchnorm_train_and_eval():
    m = nn.BatchNormalization(6).build(rng())
    x = jnp.asarray(np.random.default_rng(2).normal(3.0, 2.0, size=(32, 6)),
                    dtype=jnp.float32)
    m.training()
    y = m.forward(x)
    np.testing.assert_allclose(np.asarray(jnp.mean(y, 0)), np.zeros(6),
                               atol=1e-4)
    np.testing.assert_allclose(np.asarray(jnp.std(y, 0)), np.ones(6),
                               atol=1e-2)
    # running stats moved toward batch stats
    assert float(jnp.sum(jnp.abs(m.state["running_mean"]))) > 0
    m.evaluate()
    y2 = m.forward(x)
    assert y2.shape == x.shape


def test_batchnorm_fused_vjp_parity(monkeypatch):
    """BIGDL_TPU_BN_FUSED_VJP routes training-mode BN through the hand-written
    backward (nn/normalization._fused_bn_train); values, running stats, and
    grads w.r.t. (x, weight, bias) must match autodiff exactly."""
    x = jnp.asarray(np.random.default_rng(5).normal(1.0, 3.0, size=(16, 5, 7)),
                    dtype=jnp.float32)

    def run():
        m = nn.BatchNormalization(7).build(rng())

        def loss(params, x):
            y, st = m.apply(params, m.state, x, training=True)
            return (jnp.sum(jnp.sin(y)),
                    (st["running_mean"], st["running_var"]))

        (val, stats), grads = jax.value_and_grad(loss, argnums=(0, 1),
                                                 has_aux=True)(m.params, x)
        return val, stats, grads

    v0, s0, g0 = run()
    monkeypatch.setenv("BIGDL_TPU_BN_FUSED_VJP", "1")
    v1, s1, g1 = run()
    np.testing.assert_allclose(float(v0), float(v1), rtol=1e-6)
    for a, b in zip(jax.tree.leaves((s0, g0)), jax.tree.leaves((s1, g1))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-6)


def test_dropout_train_vs_eval():
    m = nn.Dropout(0.5).build(rng())
    x = jnp.ones((1000,))
    m.training()
    y = m.forward(x)
    zeros = float(jnp.sum(y == 0))
    assert 300 < zeros < 700
    kept = np.asarray(y)[np.asarray(y) != 0]
    np.testing.assert_allclose(kept, 2.0, rtol=1e-6)  # inverted scaling
    m.evaluate()
    np.testing.assert_allclose(np.asarray(m.forward(x)), np.asarray(x))


def test_lookup_table():
    m = nn.LookupTable(10, 4).build(rng())
    idx = jnp.asarray([[1, 2], [3, 4]])
    y = m.forward(idx)
    assert y.shape == (2, 2, 4)
    np.testing.assert_allclose(np.asarray(y[0, 0]),
                               np.asarray(m.params["weight"])[1])


def test_activations_golden():
    x = jnp.asarray([-2.0, -0.5, 0.0, 0.5, 2.0])
    cases = {
        nn.ReLU(): np.maximum(np.asarray(x), 0),
        nn.ReLU6(): np.clip(np.asarray(x), 0, 6),
        nn.Tanh(): np.tanh(np.asarray(x)),
        nn.Sigmoid(): 1 / (1 + np.exp(-np.asarray(x))),
        nn.ELU(): np.where(np.asarray(x) > 0, np.asarray(x),
                           np.expm1(np.asarray(x))),
        nn.LeakyReLU(0.1): np.where(np.asarray(x) >= 0, np.asarray(x),
                                    0.1 * np.asarray(x)),
        nn.HardTanh(): np.clip(np.asarray(x), -1, 1),
        nn.SoftSign(): np.asarray(x) / (1 + np.abs(np.asarray(x))),
        nn.TanhShrink(): np.asarray(x) - np.tanh(np.asarray(x)),
    }
    for mod, expect in cases.items():
        got = np.asarray(mod.build(rng()).forward(x))
        np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-6,
                                   err_msg=type(mod).__name__)


def test_softmax_logsoftmax():
    x = jnp.asarray([[1.0, 2.0, 3.0]])
    sm = np.asarray(nn.SoftMax().build(rng()).forward(x))
    np.testing.assert_allclose(sm.sum(), 1.0, rtol=1e-6)
    lsm = np.asarray(nn.LogSoftMax().build(rng()).forward(x))
    np.testing.assert_allclose(np.exp(lsm), sm, rtol=1e-5)


def test_containers_concat_table_ops():
    ct = nn.ConcatTable().add(nn.Identity()).add(nn.MulConstant(2.0))
    ct.build(rng())
    x = jnp.ones((2, 3))
    outs = ct.forward(x)
    assert len(outs) == 2
    add = nn.CAddTable().build(rng())
    np.testing.assert_allclose(np.asarray(add.forward(outs)),
                               3 * np.ones((2, 3)))
    j = nn.JoinTable(1).build(rng())
    assert j.forward(outs).shape == (2, 6)


def test_concat_module():
    c = nn.Concat(-1).add(nn.Linear(4, 2)).add(nn.Linear(4, 3))
    y = c.build(rng()).forward(jnp.ones((5, 4)))
    assert y.shape == (5, 5)


def test_graph_dag():
    inp = nn.Input()
    h = nn.Linear(4, 8)(inp)
    a = nn.ReLU()(h)
    b = nn.Tanh()(h)
    out = nn.CAddTable()([a, b])
    g = nn.Graph(inp, out).build(rng())
    y = g.forward(jnp.ones((2, 4)))
    assert y.shape == (2, 8)
    gx = g.backward(jnp.ones((2, 4)), jnp.ones_like(y))
    assert gx.shape == (2, 4)


def test_recurrent_lstm_gru():
    for cell in (nn.LSTM(5, 7), nn.GRU(5, 7), nn.RnnCell(5, 7),
                 nn.LSTMPeephole(5, 7)):
        m = nn.Recurrent(cell).build(rng())
        y = m.forward(jnp.ones((3, 11, 5)))
        assert y.shape == (3, 11, 7), type(cell).__name__
        gx = m.backward(jnp.ones((3, 11, 5)), jnp.ones_like(y))
        assert gx.shape == (3, 11, 5)


def test_bi_recurrent_and_time_distributed():
    m = nn.BiRecurrent(nn.LSTM(5, 7), merge="concat").build(rng())
    assert m.forward(jnp.ones((2, 6, 5))).shape == (2, 6, 14)
    td = nn.TimeDistributed(nn.Linear(7, 3)).build(rng())
    assert td.forward(jnp.ones((2, 6, 7))).shape == (2, 6, 3)


def test_shape_ops():
    x = jnp.asarray(np.arange(24, dtype=np.float32).reshape(2, 3, 4))
    assert nn.Reshape((12,)).build(rng()).forward(x).shape == (2, 12)
    assert nn.Transpose([(1, 2)]).build(rng()).forward(x).shape == (2, 4, 3)
    assert nn.Squeeze().build(rng()).forward(jnp.ones((2, 1, 3))).shape == (2, 3)
    assert nn.Unsqueeze(1).build(rng()).forward(x).shape == (2, 1, 3, 4)
    assert nn.Select(1, 0).build(rng()).forward(x).shape == (2, 4)
    assert nn.Narrow(1, 1, 2).build(rng()).forward(x).shape == (2, 2, 4)
    assert nn.Reverse(1).build(rng()).forward(x).shape == x.shape
    assert nn.Padding(1, 2).build(rng()).forward(x).shape == (2, 5, 4)
    assert nn.SpatialZeroPadding(1).build(rng()).forward(
        jnp.ones((1, 4, 4, 2))).shape == (1, 6, 6, 2)


def test_spatial_crossmap_lrn():
    m = nn.SpatialCrossMapLRN(5, 1.0, 0.75, 1.0).build(rng())
    x = jnp.ones((1, 2, 2, 8))
    y = m.forward(x)
    assert y.shape == x.shape
    assert float(y[0, 0, 0, 4]) < 1.0  # normalized down


def test_prelu_and_scale():
    m = nn.PReLU().build(rng())
    y = m.forward(jnp.asarray([-4.0, 4.0]))
    np.testing.assert_allclose(np.asarray(y), [-1.0, 4.0], rtol=1e-6)
    s = nn.Scale((3,)).build(rng())
    assert s.forward(jnp.ones((2, 3))).shape == (2, 3)


def test_gradient_reversal():
    m = nn.GradientReversal(0.5).build(rng())
    x = jnp.ones((3,))
    y = m.forward(x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(x))
    gx = m.backward(x, jnp.ones((3,)))
    np.testing.assert_allclose(np.asarray(gx), -0.5 * np.ones(3))


def test_gradient_check_small_mlp():
    """Finite-difference gradient check (the reference's GradientChecker,
    test/.../nn/ shape/gradient specs)."""
    m = nn.Sequential().add(nn.Linear(3, 4)).add(nn.Tanh()).add(nn.Linear(4, 2))
    m.build(rng())
    x = jnp.asarray(np.random.default_rng(3).normal(size=(5, 3)),
                    dtype=jnp.float32)

    def f(params):
        y, _ = m.apply(params, m.state, x)
        return jnp.sum(jnp.square(y))

    g = jax.grad(f)(m.params)
    eps = 1e-3
    leaf = m.params[0]["weight"]
    for idx in [(0, 0), (2, 1)]:
        p_plus = jax.tree.map(lambda t: t, m.params)
        p_plus[0]["weight"] = leaf.at[idx].add(eps)
        p_minus = jax.tree.map(lambda t: t, m.params)
        p_minus[0]["weight"] = leaf.at[idx].add(-eps)
        fd = (f(p_plus) - f(p_minus)) / (2 * eps)
        np.testing.assert_allclose(float(g[0]["weight"][idx]), float(fd),
                                   rtol=1e-2, atol=1e-3)


def test_module_summary():
    """summary(): one row per module, accurate totals, container nesting."""
    m = (nn.Sequential()
         .add(nn.Linear(4, 8))
         .add(nn.ReLU())
         .add(nn.Linear(8, 2))).build(rng())
    text = m.summary(print_fn=None)
    assert "Sequential" in text and text.count("Linear") == 2
    total = 4 * 8 + 8 + 8 * 2 + 2
    assert f"{total:,}" in text.splitlines()[-1]
    # a parameter-free leaf renders with 0 params
    relu_line = [l for l in text.splitlines() if "ReLU" in l][0]
    assert " 0  " in relu_line or relu_line.rstrip().endswith("-") or \
        " 0 " in relu_line


def test_cell_step_matches_step_projected_paths():
    """Cell.step (the public single-step API, also Cell._apply's path) must
    agree with Recurrent's hoisted step_projected scan — same equations,
    shared via the base-class delegation — for every dense cell; the conv
    cell's hoisted split must equal the original fused conv formulation;
    and custom step()-only cells still take the plain scan fallback."""
    import numpy as np
    from bigdl_tpu.nn import GRU, LSTM, LSTMPeephole, Recurrent, RnnCell

    B, T, I, H = 3, 4, 5, 6
    x = jnp.asarray(np.random.default_rng(0).normal(
        size=(B, T, I)).astype(np.float32))
    for cell_fn in (lambda: RnnCell(I, H), lambda: LSTM(I, H),
                    lambda: LSTMPeephole(I, H), lambda: GRU(I, H)):
        m = Recurrent(cell_fn()).build(jax.random.key(0))
        cell = m.modules[0]
        out_scan = np.asarray(m.forward(x))
        # manual unroll through the public step() API
        h = cell.init_hidden(B, x.dtype)
        outs = []
        for t in range(T):
            o, h = cell.step(m.params[0], x[:, t], h)
            outs.append(np.asarray(o))
        np.testing.assert_allclose(np.stack(outs, axis=1), out_scan,
                                   rtol=1e-5, atol=1e-6)

    # the split-kernel hoisting must equal the ORIGINAL fused formulation
    # conv([x,h], K): an inline independent reference, so a consistent-but-
    # wrong slice split in project_inputs/step_projected cannot self-verify
    from jax import lax as _lax
    from bigdl_tpu.nn import ConvLSTMPeephole
    xc = jnp.asarray(np.random.default_rng(1).normal(
        size=(2, 3, 4, 4, 3)).astype(np.float32))  # (B, T, H, W, C)
    mc = Recurrent(ConvLSTMPeephole(3, 5, 3)).build(jax.random.key(1))
    out = np.asarray(mc.forward(xc))
    assert out.shape == (2, 3, 4, 4, 5)
    p = mc.params[0]
    hh = np.zeros((2, 4, 4, 5), np.float32)
    cc = np.zeros((2, 4, 4, 5), np.float32)
    fused = []
    for t in range(3):
        z = jnp.concatenate([xc[:, t], jnp.asarray(hh)], axis=-1)
        gates = np.asarray(_lax.conv_general_dilated(
            z, p["kernel"], (1, 1), [(1, 1), (1, 1)],
            dimension_numbers=("NHWC", "HWIO", "NHWC"))) + np.asarray(p["bias"])
        i, f, g, o = np.split(gates, 4, axis=-1)
        i = 1 / (1 + np.exp(-(i + np.asarray(p["peep_i"]) * cc)))
        f = 1 / (1 + np.exp(-(f + np.asarray(p["peep_f"]) * cc)))
        g = np.tanh(g)
        cc = f * cc + i * g
        o = 1 / (1 + np.exp(-(o + np.asarray(p["peep_o"]) * cc)))
        hh = o * np.tanh(cc)
        fused.append(hh)
    np.testing.assert_allclose(np.stack(fused, axis=1), out,
                               rtol=1e-4, atol=1e-5)

    # fused-formulation reference for the dense peephole LSTM too (LSTM/GRU
    # already have independent torch goldens)
    from bigdl_tpu.nn import LSTMPeephole
    mlp = Recurrent(LSTMPeephole(I, H)).build(jax.random.key(3))
    out_lp = np.asarray(mlp.forward(x))
    pp = mlp.params[0]
    K, bb = np.asarray(pp["kernel"]), np.asarray(pp["bias"])
    hh = np.zeros((B, H), np.float32)
    cc = np.zeros((B, H), np.float32)
    fused = []
    for t in range(T):
        gates = np.concatenate([np.asarray(x[:, t]), hh], axis=-1) @ K + bb
        i, f, g, o = np.split(gates, 4, axis=-1)
        i = 1 / (1 + np.exp(-(i + np.asarray(pp["peep_i"]) * cc)))
        f = 1 / (1 + np.exp(-(f + np.asarray(pp["peep_f"]) * cc)))
        g = np.tanh(g)
        cc = f * cc + i * g
        o = 1 / (1 + np.exp(-(o + np.asarray(pp["peep_o"]) * cc)))
        hh = o * np.tanh(cc)
        fused.append(hh)
    np.testing.assert_allclose(np.stack(fused, axis=1), out_lp,
                               rtol=1e-4, atol=1e-5)

    # the non-hoisted scan branch stays for custom user cells that only
    # implement step()
    from bigdl_tpu.nn.recurrent import Cell

    class _PlainSum(Cell):
        hidden_size = I

        def _init(self, rng_):
            return {}

        def init_hidden(self, batch_size, dtype=jnp.float32):
            return jnp.zeros((batch_size, I), dtype)

        def step(self, params, x_t, h):
            h_new = h + x_t
            return h_new, h_new

    mp = Recurrent(_PlainSum()).build(jax.random.key(2))
    assert mp.modules[0].project_inputs({}, x) is None
    out_p = np.asarray(mp.forward(x))
    np.testing.assert_allclose(out_p[:, -1], np.asarray(x).sum(axis=1),
                               rtol=1e-6)


def test_convlstm_hoist_cap_falls_back_without_crashing(monkeypatch):
    """Over BIGDL_TPU_RNN_HOIST_MAX_ELEMENTS the sequence projection is
    refused (per-step scan fallback), but the t=1 Cell.step delegation is
    exempt — a one-step projection is the same gates tensor the fused conv
    would materialize, so there is no smaller-footprint fallback to prefer.
    Regression: with the cap applied at t=1 too, forward() raised
    NotImplementedError in exactly the regime the cap was meant to protect."""
    import numpy as np
    from bigdl_tpu.nn import ConvLSTMPeephole, Recurrent

    monkeypatch.setenv("BIGDL_TPU_RNN_HOIST_MAX_ELEMENTS", "1")
    xc = jnp.asarray(np.random.default_rng(2).normal(
        size=(2, 3, 4, 4, 3)).astype(np.float32))
    m = Recurrent(ConvLSTMPeephole(3, 5, 3)).build(jax.random.key(0))
    cell = m.modules[0]
    xs_tm = jnp.moveaxis(xc, 1, 0)
    assert cell.project_inputs(m.params[0], xs_tm) is None  # sequence: refused
    out = np.asarray(m.forward(xc))                          # fallback works
    assert out.shape == (2, 3, 4, 4, 5) and np.isfinite(out).all()

    # and it computes the same thing as the unguarded hoisted path
    monkeypatch.setenv("BIGDL_TPU_RNN_HOIST_MAX_ELEMENTS", str(1 << 28))
    out_hoisted = np.asarray(m.forward(xc))
    np.testing.assert_allclose(out, out_hoisted, rtol=1e-5, atol=1e-6)


def test_facade_parity_surface(tmp_path):
    """The AbstractModule public-surface tail (AbstractModule.scala):
    weight interchange (getWeightsBias/setWeightsBias/saveWeights/
    loadWeights/loadModelWeights), predict/predictClass, updateOutput,
    scale getters, inputs(), clearState, copyStatus, and the interop
    saver delegates."""
    import os
    from bigdl_tpu.dataset import Sample
    from bigdl_tpu.nn.graph import ModuleNode

    def mk():
        return nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 3),
                             nn.LogSoftMax())

    m = mk().build(jax.random.key(0))
    x = jnp.asarray(np.random.default_rng(0).normal(size=(5, 4)),
                    jnp.float32)
    y0 = np.asarray(m.forward(x))

    m2 = mk().build(jax.random.key(9))
    m2.set_weights_bias(m.get_weights_bias())
    np.testing.assert_allclose(np.asarray(m2.forward(x)), y0, rtol=1e-6)

    m3 = mk().build(jax.random.key(5))
    m.save_weights(str(tmp_path / "wb.bin"))
    m3.load_weights(str(tmp_path / "wb.bin"))
    np.testing.assert_allclose(np.asarray(m3.forward(x)), y0, rtol=1e-6)

    m4 = mk()
    m4.load_model_weights(m)   # also covers the copy_weights alias
    np.testing.assert_allclose(np.asarray(m4.forward(x)), y0, rtol=1e-6)

    samples = [Sample(np.asarray(x[i]), np.int32(0)) for i in range(5)]
    pc = m.predict_class(samples)
    assert pc.shape == (5,) and (pc == y0.argmax(-1)).all()

    assert np.allclose(np.asarray(m.update_output(x)), y0)
    assert m.get_scale_w() == 1.0 and m.get_scale_b() == 1.0
    assert isinstance(nn.Linear(4, 2).inputs(nn.Input()), ModuleNode)
    m.clear_state()
    assert m.output is None and m.grad_input is None

    conv = nn.Sequential(
        nn.SpatialConvolution(3, 4, 3, 3, 1, 1, 1, 1), nn.ReLU(),
        nn.SpatialMaxPooling(2, 2, 2, 2)).build(jax.random.key(1))
    conv.save_caffe(str(tmp_path / "net.prototxt"), str(tmp_path / "net.caffemodel"))
    conv.save_tf(str(tmp_path / "graph.pb"))
    conv.save_torch(str(tmp_path / "net.t7"))
    for f in ("net.caffemodel", "graph.pb", "net.t7"):
        assert os.path.getsize(tmp_path / f) > 100, f
    # two-arg saveCaffe writes BOTH files; the prototxt is a text net def
    proto = (tmp_path / "net.prototxt").read_text()
    assert proto.startswith('name:') and 'type: "Convolution"' in proto
    # wrong-layout arrays are rejected, not silently reshaped
    import pytest as _pytest
    bad = [np.asarray(a) for a in m.get_weights_bias()]
    i2d = next(i for i, a in enumerate(bad) if a.ndim == 2)
    bad[i2d] = bad[i2d].T
    with _pytest.raises(ValueError, match="shape"):
        mk().build(jax.random.key(2)).set_weights_bias(bad)
