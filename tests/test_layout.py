"""MeshLayout (named data/fsdp/tp axes), role-based sharding assignment,
FSDP/TP training + serving, donated train-step buffers, and multi-axis
elastic re-formation — on the 8-virtual-CPU-device mesh (conftest.py),
the simulate-a-cluster-in-one-process strategy the reference uses
(DistriOptimizerSpec.scala:33-41)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

import bigdl_tpu.nn as nn
from bigdl_tpu.common import set_seed
from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
from bigdl_tpu.optim import Optimizer, SGD, Trigger
from bigdl_tpu.parallel import (LayoutSharding, MeshLayout, MeshReformError,
                                UnannotatedParameterError, assign_shardings,
                                assign_specs)
from bigdl_tpu.utils import memstats
from bigdl_tpu.utils.engine import Engine

# the simulated multi-device host mesh: conftest forces 8 virtual CPU
# devices; skip (rather than fail) where that did not take hold
multidev = pytest.mark.skipif(
    jax.device_count() < 4,
    reason="needs >= 4 devices (XLA_FLAGS=--xla_force_host_platform_"
           "device_count=4 / conftest force_cpu)")


def _mlp(bias=False):
    """All dims divide 4; bias-free variant makes shard-fraction
    arithmetic exact."""
    return nn.Sequential(
        nn.Linear(64, 256, with_bias=bias), nn.ReLU(),
        nn.Linear(256, 256, with_bias=bias), nn.ReLU(),
        nn.Linear(256, 8, with_bias=bias))


def _dataset(n, batch, in_dim=64, classes=8, seed=0):
    rng = np.random.default_rng(seed)
    xs = rng.normal(0.0, 1.0, size=(n, in_dim)).astype(np.float32)
    ys = rng.integers(0, classes, size=n)
    return DataSet.array(
        [Sample(x, np.int32(y)) for x, y in zip(xs, ys)]).transform(
        SampleToMiniBatch(batch, drop_last=True))


def _train(model, ds, strategy, steps, lr=0.05, momentum=0.9):
    losses = []

    class Cap:
        def add_scalar(self, name, value, step):
            if name == "Loss":
                losses.append(float(value))

    opt = (Optimizer(model, ds, nn.CrossEntropyCriterion(),
                     strategy=strategy)
           .set_optim_method(SGD(learning_rate=lr, momentum=momentum))
           .set_end_when(Trigger.max_iteration(steps))
           .set_log_interval(1)
           .set_train_summary(Cap()))
    opt.optimize()
    return losses, opt


class TestMeshLayout:
    def test_sizes_and_parse(self):
        lay = MeshLayout.parse("2,2,1")
        assert lay.sizes == (2, 2, 1) and lay.size == 4
        assert MeshLayout.parse("1x2x2").tp == 2
        with pytest.raises(ValueError):
            MeshLayout.parse("2,2")
        with pytest.raises(ValueError):
            MeshLayout(0, 1, 1)

    @multidev
    def test_build_mesh_and_of_mesh(self):
        lay = MeshLayout(2, 2, 1)
        mesh = lay.build_mesh()
        assert tuple(mesh.axis_names) == ("data", "fsdp", "tp")
        assert MeshLayout.of_mesh(mesh) == lay
        # legacy 1-D mesh is not a layout mesh
        from jax.sharding import Mesh
        legacy = Mesh(np.array(jax.devices()[:2]), ("data",))
        assert MeshLayout.of_mesh(legacy) is None

    def test_role_table_specs(self):
        lay = MeshLayout(1, 2, 2)
        # column-parallel (out, in): tp on out, fsdp on in
        assert lay.spec_for("kernel_out", (256, 64), min_size=0) == \
            P("tp", "fsdp")
        # in-major (in, out): tp on out, fsdp on in
        assert lay.spec_for("kernel_in", (64, 256), min_size=0) == \
            P("fsdp", "tp")
        # HWIO conv: tp on cout, fsdp on cin
        assert lay.spec_for("conv_kernel", (3, 3, 64, 128), min_size=0) == \
            P(None, None, "fsdp", "tp")
        # embedding rows over fsdp x tp together
        assert lay.spec_for("embedding_row", (64, 32), min_size=0) == \
            P(("fsdp", "tp"), None)
        # small per-feature roles replicate
        assert lay.spec_for("bias", (256,), min_size=0) == P(None)
        assert lay.spec_for("norm_scale", (256,), min_size=0) == P(None)
        with pytest.raises(KeyError):
            lay.spec_for("no_such_role", (4,))

    def test_divisibility_degrades_per_axis(self):
        lay = MeshLayout(1, 4, 2)
        # out=6 not divisible by tp=2? 6 % 2 == 0 -> keep; in=5 % 4 != 0
        # -> fsdp falls back to the other (out) axis? out already used by
        # tp -> replicate along fsdp
        assert lay.spec_for("kernel_out", (6, 5), min_size=0) == \
            P("tp", None)
        # nothing divides -> fully replicated
        assert lay.spec_for("kernel_out", (7, 5), min_size=0) == P(None, None)
        # embedding vocab not divisible by fsdp*tp=8 but by fsdp=4
        assert lay.spec_for("embedding_row", (12, 3), min_size=0) == \
            P("fsdp", None)

    def test_min_size_keeps_small_leaves_replicated(self):
        lay = MeshLayout(1, 2, 1)
        assert lay.spec_for("kernel_out", (8, 8), min_size=1024) == \
            P(None, None)
        assert lay.spec_for("kernel_out", (64, 64), min_size=1024) == \
            P(None, "fsdp")

    def test_single_device_layout_replicates_everything(self):
        lay = MeshLayout(1, 1, 1)
        for role in ("kernel_out", "kernel_in", "conv_kernel",
                     "embedding_row", "bias"):
            spec = lay.spec_for(role, (64, 64), min_size=0)
            assert all(s is None for s in spec)


class TestAssigner:
    @multidev
    def test_roles_resolved_through_containers(self):
        model = _mlp(bias=True)
        model.build(jax.random.key(0))
        lay = MeshLayout(2, 2, 1)
        specs = assign_specs(model, model.params, lay, min_size=0)
        flat = {jax.tree_util.keystr(kp): s for kp, s in
                jax.tree_util.tree_flatten_with_path(
                    specs, is_leaf=lambda x: isinstance(x, P))[0]}
        assert flat["[0]['weight']"] == P(None, "fsdp")  # tp=1: no split
        assert flat["[0]['bias']"] == P(None)
        assert flat["[2]['weight']"] == P(None, "fsdp")

    @multidev
    def test_unannotated_leaf_fails_loudly(self):
        class Mystery(nn.Module):
            def _init(self, rng):
                return {"blob": jnp.zeros((16, 16))}

            def _apply(self, params, x):
                return x

        model = nn.Sequential(nn.Linear(8, 8), Mystery())
        model.build(jax.random.key(0))
        mesh = MeshLayout(2, 2, 1).build_mesh()
        with pytest.raises(UnannotatedParameterError, match="Mystery.*blob"):
            assign_shardings(model, model.params, mesh, min_size=0)

    @multidev
    def test_wildcard_role(self):
        class Annotated(nn.Module):
            PARAM_ROLES = {"*": "elementwise"}

            def _init(self, rng):
                return {"a": jnp.zeros((8,)), "b": jnp.zeros((8, 8))}

            def _apply(self, params, x):
                return x

        m = Annotated()
        m.build(jax.random.key(0))
        mesh = MeshLayout(2, 2, 1).build_mesh()
        sh = assign_shardings(m, m.params, mesh, min_size=0)
        assert all(s.spec in (P(), P(None), P(None, None))
                   for s in jax.tree.leaves(sh))

    @multidev
    def test_legacy_mesh_replicates(self):
        from jax.sharding import Mesh
        model = _mlp()
        model.build(jax.random.key(0))
        mesh = Mesh(np.array(jax.devices()[:4]), ("data",))
        sh = assign_shardings(model, model.params, mesh)
        assert all(tuple(s.spec) == () for s in jax.tree.leaves(sh))


@multidev
class TestFSDPTraining:
    def test_fsdp4_shard_bytes_per_device(self):
        """(a) addressable shard bytes per device == total/N under
        FSDP=4 (bias-free model where every leaf divides)."""
        set_seed(3)
        model = _mlp(bias=False)
        ds = _dataset(64, 16)
        MeshLayout(1, 4, 1).install(jax.devices()[:4])
        _, opt = _train(model, ds, LayoutSharding(model, min_size=0), 2)
        total = memstats.tree_total_bytes(model.params)
        per_dev = memstats.tree_device_bytes(model.params)
        assert per_dev * 4 == total
        # slots (momentum) inherit the param shardings leaf-for-leaf
        slots = opt._final_opt_state
        slot_total = memstats.tree_total_bytes(slots)
        slot_dev = memstats.tree_device_bytes(slots)
        # acceptance: params+slots per device <= 30% of replicated bytes
        assert (per_dev + slot_dev) <= 0.30 * (total + slot_total)

    def test_fsdp_loss_parity_vs_data_parallel(self):
        """(b) loss sequence matches pure DP within the documented
        reassociation tolerance (docs/parallelism.md)."""
        set_seed(3)
        dp_model = _mlp(bias=True)
        MeshLayout(4, 1, 1).install(jax.devices()[:4])
        dp_losses, _ = _train(dp_model, _dataset(80, 16),
                              LayoutSharding(dp_model, min_size=0), 5)
        Engine.reset()
        set_seed(3)
        fs_model = _mlp(bias=True)
        MeshLayout(2, 2, 1).install(jax.devices()[:4])
        fs_losses, _ = _train(fs_model, _dataset(80, 16),
                              LayoutSharding(fs_model, min_size=0), 5)
        assert len(dp_losses) == len(fs_losses) == 5
        np.testing.assert_allclose(fs_losses, dp_losses, atol=2e-3)

    def test_wide_embedding_model_shards_and_trains(self):
        """(c) a wide-embedding model shards its table over fsdp x tp
        and trains on a (1,2,2) layout."""
        set_seed(5)
        model = nn.Sequential(
            nn.LookupTable(64, 32),
            nn.Mean(1),                      # (B, T, E) -> (B, E)
            nn.Linear(32, 64, with_bias=True), nn.ReLU(),
            nn.Linear(64, 8, with_bias=True))
        rng = np.random.default_rng(1)
        seqs = rng.integers(0, 64, size=(64, 12)).astype(np.int32)
        ys = rng.integers(0, 8, size=64)
        ds = DataSet.array(
            [Sample(s, np.int32(y)) for s, y in zip(seqs, ys)]).transform(
            SampleToMiniBatch(16, drop_last=True))
        MeshLayout(1, 2, 2).install(jax.devices()[:4])
        losses, _ = _train(model, ds, LayoutSharding(model, min_size=0), 4)
        assert len(losses) == 4 and all(np.isfinite(losses))
        # the table landed in fsdp x tp row shards: 1/4 per device
        table = model.params[0]["weight"]
        assert table.sharding.spec == P(("fsdp", "tp"), None)
        assert memstats.tree_device_bytes({"w": table}) * 4 == \
            memstats.tree_total_bytes({"w": table})

    def test_tp_wide_linear_trains_and_serves_bucket_ladder(self):
        """A tp=2 wide-Linear model trains, then answers through the
        serve bucket ladder with outputs matching bulk Predictor."""
        from bigdl_tpu.serve import InferenceServer

        set_seed(11)
        model = _mlp(bias=True)
        ds = _dataset(64, 16)
        MeshLayout(1, 2, 2).install(jax.devices()[:4])
        strategy = LayoutSharding(model, min_size=0)
        losses, _ = _train(model, ds, strategy, 3)
        assert all(np.isfinite(losses))
        # wide kernels split over tp
        w0 = model.params[0]["weight"]
        assert "tp" in tuple(w0.sharding.spec)
        rng = np.random.default_rng(2)
        xs = rng.normal(size=(6, 64)).astype(np.float32)
        from bigdl_tpu.optim.optimizer import Predictor
        bulk = Predictor(model, batch_size=8, strategy=strategy).predict(
            [Sample(x, np.int32(0)) for x in xs])
        server = InferenceServer(model, max_batch=4, replicas=1,
                                 strategy=strategy, example=xs[0])
        try:
            server.start()  # warms every ladder bucket before traffic
            outs = [server.submit(x).result(timeout=60) for x in xs]
        finally:
            server.stop()
        np.testing.assert_allclose(np.stack(outs), bulk, atol=1e-5,
                                   rtol=1e-5)


@multidev
class TestDonation:
    def _lenet_losses(self, steps=5, batch=16):
        from bigdl_tpu.models.lenet import LeNet5

        set_seed(7)
        rng = np.random.default_rng(0)
        n = batch * steps
        xs = rng.normal(0.0, 0.1, size=(n, 28, 28, 1)).astype(np.float32)
        ys = rng.integers(0, 10, size=n)
        model = LeNet5(10)
        ds = DataSet.array(
            [Sample(x, np.int32(y)) for x, y in zip(xs, ys)]).transform(
            SampleToMiniBatch(batch, drop_last=True))
        losses, _ = _train(model, ds, None, steps, lr=0.01)
        return losses, [np.asarray(p) for p in jax.tree.leaves(model.params)]

    def test_no_donate_knob_bit_identical(self, monkeypatch):
        """Donated and undonated 5-step LeNet runs are bit-identical:
        donation changes buffer lifetime, never values."""
        monkeypatch.delenv("BIGDL_TPU_NO_DONATE", raising=False)
        l0, p0 = self._lenet_losses()
        monkeypatch.setenv("BIGDL_TPU_NO_DONATE", "1")
        l1, p1 = self._lenet_losses()
        assert l0 == l1 and len(l0) >= 5
        assert all(np.array_equal(a, b) for a, b in zip(p0, p1))

    def _built_step(self, monkeypatch, no_donate):
        if no_donate:
            monkeypatch.setenv("BIGDL_TPU_NO_DONATE", "1")
        else:
            monkeypatch.delenv("BIGDL_TPU_NO_DONATE", raising=False)
        set_seed(9)
        model = _mlp(bias=True)
        model.build(jax.random.key(0))
        opt = Optimizer(model, dataset=None,
                        criterion=nn.CrossEntropyCriterion(),
                        end_trigger=Trigger.max_iteration(1))
        opt.set_optim_method(SGD(learning_rate=0.05, momentum=0.9))
        mesh = Engine.mesh()
        step, param_sh, data_sh = opt._build_step(mesh)
        params = jax.device_put(model.params, param_sh)
        opt_state = jax.device_put(opt.optim_method.init_state(params),
                                   opt._opt_sh)
        net_state = jax.device_put(
            model.state, jax.sharding.NamedSharding(mesh, P()))
        rngk = jax.random.key(1)
        x = jnp.asarray(np.random.default_rng(0).normal(
            size=(16, 64)).astype(np.float32))
        y = jnp.asarray(np.zeros((16,), np.int32))
        inp = jax.device_put(x, data_sh)
        tgt = jax.device_put(y, data_sh)
        args = (params, net_state, opt_state, inp, tgt,
                jnp.float32(0.05), rngk)
        return step, args, opt

    def test_donated_buffers_deleted_and_not_reused(self, monkeypatch):
        """The donation contract: after the step, the donated input
        buffers are DELETED (in-place update happened) and nothing in
        the loop touches them again — the classic 'referenced deleted
        buffer' class would raise right here."""
        step, args, opt = self._built_step(monkeypatch, no_donate=False)
        assert opt._step_knobs["donate"] is True
        out = step(*args)
        jax.block_until_ready(out[0])
        params, net_state, opt_state = args[0], args[1], args[2]
        assert all(leaf.is_deleted()
                   for leaf in jax.tree.leaves(params))
        assert all(leaf.is_deleted()
                   for leaf in jax.tree.leaves(opt_state)
                   if hasattr(leaf, "is_deleted"))
        # outputs are fresh, alive, and feed the next step cleanly
        out2 = step(*out[:3], args[3], args[4], jnp.float32(0.05), args[6])
        assert np.isfinite(float(out2[3]))
        # a reuse of the donated buffer is exactly this error:
        with pytest.raises(RuntimeError):
            np.asarray(jax.tree.leaves(params)[0])

    def test_no_donate_keeps_buffers_and_costs_live_bytes(self, monkeypatch):
        """BIGDL_TPU_NO_DONATE=1 keeps the inputs alive — and therefore
        holds TWO params+slots copies after the step, which is the peak
        memory donation removes (measured via the live-buffer sum, the
        CPU fallback bench.py records)."""
        step, args, opt = self._built_step(monkeypatch, no_donate=True)
        assert opt._step_knobs["donate"] is False
        before = memstats.live_device_bytes()
        out = step(*args)
        jax.block_until_ready(out[0])
        growth_undonated = memstats.live_device_bytes() - before
        assert not any(leaf.is_deleted()
                       for leaf in jax.tree.leaves(args[0]))
        del step, args, out, opt

        step, args, opt = self._built_step(monkeypatch, no_donate=False)
        before = memstats.live_device_bytes()
        out = step(*args)
        jax.block_until_ready(out[0])
        growth_donated = memstats.live_device_bytes() - before
        # donated step: old params+slots die, so live growth is smaller
        assert growth_donated < growth_undonated


@multidev
class TestMultiAxisReform:
    def test_shrink_data_axis_keeps_fsdp_tp(self):
        MeshLayout(2, 2, 1).install(jax.devices()[:4])
        model = _mlp()
        model.build(jax.random.key(0))
        strategy = LayoutSharding(model, min_size=0)
        mesh = Engine.mesh()
        params = jax.device_put(model.params,
                                strategy.param_sharding(mesh, model.params))
        # lose half the devices: data 2 -> 1, fsdp x tp intact
        new_mesh = Engine.reform(world=1, rank=0, survivors=[0],
                                 devices=jax.devices()[:2])
        assert dict(zip(new_mesh.axis_names,
                        new_mesh.devices.shape)) == \
            {"data": 1, "fsdp": 2, "tp": 1}
        remapped = strategy.remap(new_mesh, params)
        per_dev = memstats.tree_device_bytes(remapped)
        assert per_dev * 2 == memstats.tree_total_bytes(remapped)

    def test_typed_error_when_block_cannot_survive(self):
        MeshLayout(2, 2, 1).install(jax.devices()[:4])
        with pytest.raises(MeshReformError,
                           match="shard groups intact"):
            Engine.reform(world=1, rank=0, survivors=[0],
                          devices=jax.devices()[:3])
        # fewer devices than the fsdp x tp block itself
        with pytest.raises(MeshReformError):
            Engine.reform(world=1, rank=0, survivors=[0],
                          devices=jax.devices()[:1])

    def test_typed_error_without_data_axis(self):
        from jax.sharding import Mesh
        Engine.set_mesh(Mesh(
            np.array(jax.devices()[:4]).reshape(2, 2), ("fsdp", "tp")))
        with pytest.raises(MeshReformError, match="no 'data' axis"):
            Engine.reform(world=1, rank=0, survivors=[0],
                          devices=jax.devices()[:2])
