"""Data pipeline tests: transformers, batching, record IO, image ops."""

import numpy as np
import pytest

from bigdl_tpu.dataset import (DataSet, Sample, MiniBatch, SampleToMiniBatch,
                               FixedLength, PaddingParam)
from bigdl_tpu.dataset.image import (LabeledImage, ImgCropper, ImgRdmCropper,
                                     ImgNormalizer, HFlip, ColorJitter,
                                     Lighting, ImgToSample, RdmResizedCrop,
                                     _resize_bilinear)
from bigdl_tpu.utils import recordio


def samples(n=10):
    return [Sample.from_ndarray(np.full((3,), i, np.float32), np.int32(i))
            for i in range(n)]


def test_sample_to_minibatch():
    ds = DataSet.array(samples(10)).transform(SampleToMiniBatch(4))
    batches = list(ds.data(train=False))
    assert [b.size() for b in batches] == [4, 4, 2]
    ds2 = DataSet.array(samples(10)).transform(
        SampleToMiniBatch(4, drop_last=True))
    assert [b.size() for b in list(ds2.data(train=False))] == [4, 4]
    ds3 = DataSet.array(samples(10)).transform(
        SampleToMiniBatch(4, pad_last=True))
    batches = list(ds3.data(train=False))
    assert [b.size() for b in batches] == [4, 4, 4]
    assert batches[-1].valid == 2


def test_minibatch_slice():
    ds = DataSet.array(samples(8)).transform(SampleToMiniBatch(8))
    b = next(iter(ds.data(train=False)))
    sub = b.slice(2, 3)
    assert sub.size() == 3
    np.testing.assert_allclose(sub.get_input()[0], [2, 2, 2])


def test_variable_length_padding():
    recs = [Sample.from_ndarray(np.ones((l, 2), np.float32), np.int32(0))
            for l in (3, 5, 2)]
    ds = DataSet.array(recs).transform(
        SampleToMiniBatch(3, feature_padding=PaddingParam(0.0)))
    b = next(iter(ds.data(train=False)))
    assert b.get_input().shape == (3, 5, 2)
    ds2 = DataSet.array(recs).transform(
        SampleToMiniBatch(3, feature_padding=FixedLength(8)))
    b2 = next(iter(ds2.data(train=False)))
    assert b2.get_input().shape == (3, 8, 2)


def test_shuffle_deterministic():
    ds = DataSet.array(samples(10), seed=42)
    ds.shuffle()
    order1 = [int(s.label) for s in ds.data(train=True)]
    ds2 = DataSet.array(samples(10), seed=42)
    ds2.shuffle()
    order2 = [int(s.label) for s in ds2.data(train=True)]
    assert order1 == order2 and order1 != list(range(10))


def test_distributed_dataset_shards():
    from bigdl_tpu.dataset import DistributedDataSet
    all_seen = []
    for pi in range(4):
        ds = DistributedDataSet(samples(20), process_index=pi, process_count=4)
        assert ds.size() == 20
        local = [int(s.label) for s in ds.data(train=False)]
        assert len(local) == 5
        all_seen += local
    assert sorted(all_seen) == list(range(20))


def test_transformer_chaining():
    imgs = [LabeledImage(np.ones((8, 8, 3), np.float32), float(i))
            for i in range(4)]
    chain = (ImgCropper(4, 4)
             >> ImgNormalizer([0.5, 0.5, 0.5], [0.5, 0.5, 0.5])
             >> ImgToSample())
    out = list(chain(iter(imgs)))
    assert len(out) == 4
    assert out[0].feature.shape == (4, 4, 3)
    np.testing.assert_allclose(out[0].feature, 1.0)


def test_image_augmentations_shapes():
    imgs = [LabeledImage(np.random.default_rng(0).random((16, 12, 3))
                         .astype(np.float32), 1.0)]
    for t in (ImgRdmCropper(8, 8, padding=2), HFlip(1.0), ColorJitter(),
              Lighting(), RdmResizedCrop(8, 8)):
        out = list(t(iter([imgs[0]])))
        assert out[0].data.shape[2] == 3


def test_resize_bilinear_golden():
    img = np.asarray([[0.0, 1.0], [2.0, 3.0]], np.float32)[:, :, None]
    out = _resize_bilinear(img, 4, 4)
    assert out.shape == (4, 4, 1)
    np.testing.assert_allclose(out[0, 0, 0], 0.0)
    np.testing.assert_allclose(out.mean(), img.mean(), atol=0.1)


def test_recordio_roundtrip(tmp_path):
    recs = samples(13)
    path = str(tmp_path / "data.rec")
    recordio.write_records(path, recs)
    back = list(recordio.read_records(path))
    assert len(back) == 13
    np.testing.assert_allclose(back[5].feature, recs[5].feature)


def test_recordio_sharded(tmp_path):
    path = str(tmp_path / "shards")
    paths = recordio.write_records(path, samples(10), shards=4)
    assert len(paths) == 4
    back = list(recordio.read_records(path))
    assert sorted(int(s.label) for s in back) == list(range(10))


def test_recordio_corruption_detected(tmp_path):
    path = str(tmp_path / "data.rec")
    recordio.write_records(path, samples(2))
    raw = bytearray(open(path, "rb").read())
    raw[20] ^= 0xFF
    open(path, "wb").write(bytes(raw))
    with pytest.raises((IOError, Exception)):
        list(recordio.read_records(path))


def test_crc32c_golden():
    # known CRC32C test vector: "123456789" -> 0xE3069283
    from bigdl_tpu.utils.recordio import _crc32c_py
    assert _crc32c_py(b"123456789") == 0xE3069283


def test_dataset_record_file_builder(tmp_path):
    path = str(tmp_path / "ds.rec")
    recordio.write_records(path, samples(6))
    ds = DataSet.record_file(path)
    assert ds.size() == 6


def test_dataset_record_files_glob(tmp_path):
    """Sharded SeqFileFolder role: glob over BDRecord shards, sorted order."""
    for shard in range(3):
        recordio.write_records(str(tmp_path / f"part-{shard}.bdr"),
                               samples(4))
    ds = DataSet.record_files(str(tmp_path / "part-*.bdr"))
    assert ds.size() == 12
    import pytest
    with pytest.raises(FileNotFoundError):
        DataSet.record_files(str(tmp_path / "nope-*.bdr"))


def test_movielens_provider(tmp_path):
    from bigdl_tpu.dataset.providers import load_movielens
    (tmp_path / "ratings.dat").write_text(
        "1::1193::5::978300760\n1::661::3::978302109\n2::1357::5::978298709\n")
    r = load_movielens(str(tmp_path))
    assert r.shape == (3, 3) and r.dtype.name == "float32"
    assert r[0].tolist() == [1.0, 1193.0, 5.0]
    # ml-latest CSV with header; half-star ratings must survive
    (tmp_path / "ratings.csv").write_text(
        "userId,movieId,rating,timestamp\n7,2,4.0,123\n8,3,3.5,456\n")
    r2 = load_movielens(str(tmp_path), "ratings.csv")
    assert r2.tolist() == [[7.0, 2.0, 4.0], [8.0, 3.0, 3.5]]


def test_sorted_array_group_shuffle():
    """DataSet.sortRDD + groupSize role: records sorted by length, shuffle
    permutes groups only — batches stay length-homogeneous."""
    recs = [np.zeros(n) for n in [7, 3, 9, 1, 5, 8, 2, 6]]
    ds = DataSet.sorted_array(recs, key=len, group_size=2, seed=3)
    for _ in range(5):
        ds.shuffle()
        lens = [len(r) for r in ds.data(train=True)]
        assert sorted(lens) == [1, 2, 3, 5, 6, 7, 8, 9]
        # each adjacent pair must be one of the sorted-order groups
        pairs = {(lens[i], lens[i + 1]) for i in range(0, 8, 2)}
        assert pairs <= {(1, 2), (3, 5), (6, 7), (8, 9)}, lens
    # eval order is the sorted order, untouched by shuffling
    assert [len(r) for r in ds.data(train=False)] == [1, 2, 3, 5, 6, 7, 8, 9]


def test_mt_sample_to_minibatch_matches_single_threaded():
    import numpy as np
    from bigdl_tpu.dataset import (MTSampleToMiniBatch, Sample,
                                   SampleToMiniBatch)
    rng = np.random.default_rng(0)
    samples = [Sample(rng.standard_normal((7, 3)).astype(np.float32),
                      np.float32(i)) for i in range(50)]
    ref = list(SampleToMiniBatch(16, pad_last=True)(iter(samples)))
    got = list(MTSampleToMiniBatch(16, pad_last=True, num_threads=4)(
        iter(samples)))
    assert len(got) == len(ref)
    for r, g in zip(ref, got):
        np.testing.assert_array_equal(np.asarray(r.get_input()),
                                      np.asarray(g.get_input()))
        np.testing.assert_array_equal(np.asarray(r.get_target()),
                                      np.asarray(g.get_target()))
        assert r.valid == g.valid


def test_mt_batcher_with_upstream_transformer():
    import numpy as np
    from bigdl_tpu.dataset import MTSampleToMiniBatch, Sample, Transformer

    class Scale(Transformer):
        def __call__(self, it):
            for s in it:
                yield Sample(s.feature * 2.0, s.label)

    samples = [Sample(np.full((2, 2), i, np.float32), np.float32(i))
               for i in range(10)]
    got = list(MTSampleToMiniBatch(4, transformer=Scale(), drop_last=True,
                                   num_threads=2)(iter(samples)))
    assert len(got) == 2
    np.testing.assert_array_equal(
        np.asarray(got[0].get_input())[3], np.full((2, 2), 6.0))


def test_thread_pool_api():
    from bigdl_tpu.utils import ThreadPool
    pool = ThreadPool(4)
    results = pool.invoke_and_wait([lambda i=i: i * i for i in range(8)])
    assert results == [i * i for i in range(8)]
    futs = pool.invoke([lambda: 42])
    assert pool.sync(futs) == [42]
    import pytest as _p
    import time as _t
    with _p.raises(Exception):
        pool.invoke_and_wait([lambda: _t.sleep(0.3)], timeout=0.05)
    pool.shutdown()


def test_mt_batcher_rejects_filtering_transformer():
    import numpy as np
    import pytest
    from bigdl_tpu.dataset import MTSampleToMiniBatch, Sample, Transformer

    class DropOdd(Transformer):
        def __call__(self, it):
            for s in it:
                if int(s.label) % 2 == 0:
                    yield s

    samples = [Sample(np.zeros(3, np.float32), np.float32(i))
               for i in range(8)]
    mt = MTSampleToMiniBatch(4, transformer=DropOdd(), num_threads=2)
    with pytest.raises(ValueError, match="1:1"):
        list(mt(iter(samples)))


def test_gather_rows_heterogeneous_matches_np_stack():
    import numpy as np
    from bigdl_tpu.utils import native
    rows = [np.zeros((2, 2), np.float32), np.zeros((2, 2), np.float64)]
    got = native.gather_rows(rows)
    ref = np.stack(rows)
    assert got.dtype == ref.dtype
    np.testing.assert_array_equal(got, ref)


class TestStreamingRecordDataSet:
    """Out-of-core shard streaming (DataSet.record_stream)."""

    def _shards(self, tmp_path, n_shards=4, per_shard=25):
        from bigdl_tpu.utils.recordio import write_records
        paths = []
        k = 0
        for s in range(n_shards):
            p = str(tmp_path / f"s{s}.bd")
            write_records(p, list(range(k, k + per_shard)))
            k += per_shard
            paths.append(p)
        return paths

    def test_streams_all_records_every_epoch(self, tmp_path):
        from bigdl_tpu.dataset import DataSet

        paths = self._shards(tmp_path)
        ds = DataSet.record_stream(paths)
        assert ds.size() == 100
        first = list(ds.data(train=True))
        assert sorted(first) == list(range(100))
        ds.shuffle()
        second = list(ds.data(train=True))
        assert sorted(second) == list(range(100))
        # shard-granular shuffle: different shard order is possible, but
        # within-shard order is preserved
        for s in range(4):
            blk = [x for x in second if s * 25 <= x < (s + 1) * 25]
            assert blk == list(range(s * 25, (s + 1) * 25))

    def test_eval_pass_is_deterministic(self, tmp_path):
        from bigdl_tpu.dataset import DataSet

        paths = self._shards(tmp_path)
        ds = DataSet.record_stream(paths)
        ds.shuffle()
        assert list(ds.data(train=False)) == list(range(100))

    def test_native_threads_same_multiset(self, tmp_path):
        from bigdl_tpu.dataset import DataSet
        from bigdl_tpu.utils import native

        if not (native.is_native_loaded() and native.has_prefetch()):
            pytest.skip("native prefetch unavailable")
        paths = self._shards(tmp_path)
        ds = DataSet.record_stream(paths, num_threads=3)
        assert sorted(ds.data(train=True)) == list(range(100))

    def test_distributed_strided_disjoint(self, tmp_path):
        """Real sharding path via explicit process_index/process_count:
        ranks stream disjoint shard subsets covering the corpus."""
        from bigdl_tpu.dataset import StreamingRecordDataSet

        paths = self._shards(tmp_path, n_shards=6)
        seen = []
        for rank in range(3):
            ds = StreamingRecordDataSet(paths, distributed=True,
                                        process_index=rank,
                                        process_count=3)
            seen.append(sorted(ds.data(train=True)))
        flat = [x for part in seen for x in part]
        assert sorted(flat) == sorted(set(flat))  # disjoint
        assert len(flat) == 150  # 6 shards x 25, all covered

    def test_distributed_indivisible_shards_rejected(self, tmp_path):
        from bigdl_tpu.dataset import StreamingRecordDataSet

        paths = self._shards(tmp_path, n_shards=5)
        ds = StreamingRecordDataSet(paths, distributed=True,
                                    process_index=0, process_count=3)
        with pytest.raises(ValueError, match="not.*divisible|divisible"):
            list(ds.data(train=True))

    def test_distributed_unequal_shards_equal_steps(self, tmp_path):
        """Unequal shard sizes: every rank truncates to the smallest
        rank's record count for the epoch (collective-step safety)."""
        from bigdl_tpu.dataset import StreamingRecordDataSet
        from bigdl_tpu.utils.recordio import write_records

        paths = []
        for s, n in enumerate([30, 20]):  # rank0 shard bigger than rank1
            p = str(tmp_path / f"u{s}.bd")
            write_records(p, list(range(n)))
            paths.append(p)
        lens = []
        for rank in range(2):
            ds = StreamingRecordDataSet(paths, distributed=True,
                                        process_index=rank, process_count=2)
            lens.append(len(list(ds.data(train=True))))
        assert lens[0] == lens[1] == 20

    def test_eval_pass_sequential_even_with_threads(self, tmp_path):
        """train=False must preserve input order (Predictor aligns outputs
        positionally) even when num_threads requests the interleaving
        prefetcher for training passes."""
        from bigdl_tpu.dataset import DataSet

        paths = self._shards(tmp_path)
        ds = DataSet.record_stream(paths, num_threads=4)
        assert list(ds.data(train=False)) == list(range(100))

    def test_size_counts_without_decoding(self, tmp_path):
        from bigdl_tpu.utils.recordio import count_records

        paths = self._shards(tmp_path, n_shards=2, per_shard=7)
        assert [count_records(p) for p in paths] == [7, 7]

    def test_trains_through_optimizer(self, tmp_path):
        """End-to-end: stream shards -> transform -> train (the dataset is
        re-read from disk each epoch)."""
        import bigdl_tpu.nn as nn
        from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
        from bigdl_tpu.models import LeNet5
        from bigdl_tpu.optim import Adam, Optimizer, Trigger
        from bigdl_tpu.utils.engine import Engine
        from bigdl_tpu.utils.recordio import write_records
        from test_e2e_lenet import synthetic_mnist

        Engine.reset()
        Engine.init()
        samples = synthetic_mnist(256)
        write_records(str(tmp_path / "mnist.bd"), samples, shards=4)
        paths = sorted(str(p) for p in tmp_path.glob("mnist.bd-*"))
        ds = DataSet.record_stream(paths).transform(
            SampleToMiniBatch(64, drop_last=True))
        opt = (Optimizer(LeNet5(10), ds, nn.ClassNLLCriterion())
               .set_optim_method(Adam(1e-3))
               .set_end_when(Trigger.max_epoch(6)))
        opt.optimize()
        # shard-granular shuffle mixes less than record-level, so allow
        # a couple more epochs than the in-memory path needs
        assert opt.optim_method.hyper["loss"] < 1.0


class TestCorruptRecordQuarantine:
    """Corrupt-record tolerance on the BDRecord streaming path: typed
    CorruptRecord with path+offset, opt-in bounded skip budget
    (BIGDL_TPU_DATA_SKIP_BUDGET / skip_budget=), default fail-loud."""

    def _shard(self, tmp_path, n=20):
        from bigdl_tpu.utils.recordio import write_records
        p = str(tmp_path / "c.bd")
        write_records(p, list(range(n)))
        return p

    def test_chaos_corruption_skip_budget(self, tmp_path):
        from bigdl_tpu.dataset import StreamingRecordDataSet
        from bigdl_tpu.utils import chaos
        from bigdl_tpu.utils import recordio

        p = self._shard(tmp_path)
        recordio.reset_quarantine_stats()
        with chaos.scoped("data.record=truncate@4,9"):
            ds = StreamingRecordDataSet([p], skip_budget=2)
            out = list(ds.data(train=False))
        assert len(out) == 18
        assert ds.last_quarantined == 2
        assert recordio.quarantine_stats()["records"] == 2

    def test_chaos_corruption_default_fails_loud(self, tmp_path):
        from bigdl_tpu.dataset import StreamingRecordDataSet
        from bigdl_tpu.utils import chaos
        from bigdl_tpu.utils.recordio import CorruptRecord

        p = self._shard(tmp_path)
        with chaos.scoped("data.record=truncate@4"):
            ds = StreamingRecordDataSet([p])
            with pytest.raises(CorruptRecord) as ei:
                list(ds.data(train=False))
        assert ei.value.path == p and ei.value.offset is not None

    def test_on_disk_bitflip_quarantined_with_offset(self, tmp_path):
        """Real bit-rot: one flipped byte mid-payload is caught by the
        frame CRC, quarantined under budget with its byte offset."""
        from bigdl_tpu.utils.recordio import (CorruptRecord, SkipBudget,
                                              write_records, read_records)

        # fat payloads so a mid-record flip lands in PAYLOAD bytes (a
        # flipped length header is untrusted-length, fatal by design)
        p = str(tmp_path / "c.bd")
        write_records(p, ["x" * 64] * 19 + ["y" * 64])
        data = bytearray(open(p, "rb").read())
        data[30] ^= 0xFF  # inside the first record's payload
        open(p, "wb").write(bytes(data))
        with pytest.raises(CorruptRecord):
            list(read_records(p))
        skip = SkipBudget(1)
        out = list(read_records(p, skip=skip))
        assert len(out) == 19 and skip.count == 1
        path_, offset, reason = skip.quarantined[0]
        assert path_ == p and offset is not None and "crc" in reason

    def test_budget_exhaustion_reraises(self, tmp_path):
        from bigdl_tpu.utils import chaos
        from bigdl_tpu.utils.recordio import (CorruptRecord, SkipBudget,
                                              read_records)

        p = self._shard(tmp_path)
        with chaos.scoped("data.record=truncate@2,5,8"):
            skip = SkipBudget(2)
            with pytest.raises(CorruptRecord):
                list(read_records(p, skip=skip))
        assert skip.count == 2  # absorbed two, the third was over budget

    def test_env_knob_default(self, tmp_path, monkeypatch):
        from bigdl_tpu.dataset import StreamingRecordDataSet
        from bigdl_tpu.utils import chaos

        monkeypatch.setenv("BIGDL_TPU_DATA_SKIP_BUDGET", "1")
        p = self._shard(tmp_path)
        with chaos.scoped("data.record=truncate@3"):
            ds = StreamingRecordDataSet([p])  # budget from the env knob
            out = list(ds.data(train=False))
        assert len(out) == 19 and ds.last_quarantined == 1
