"""Unified run telemetry: span tracer, per-step metrics timeline,
cross-host trace merge (bigdl_tpu.utils.telemetry + tools/trace_report).

Covers the PR-4 acceptance surface: emitted traces are valid Chrome
trace-event JSON with correct span nesting; a crashed/stalled run's
trace survives (flush-on-crash, supervisor trace tail); multi-rank
traces merge into one timeline with a phase breakdown + straggler
detection; and with tracing off the train loop allocates no tracer
thread and emits nothing.
"""

import glob
import json
import logging
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
from bigdl_tpu.optim import Adam, Optimizer, Trigger
from bigdl_tpu.optim.metrics import Metrics
from bigdl_tpu.utils import chaos, file_io, telemetry
from bigdl_tpu.utils.supervisor import Supervisor
from bigdl_tpu.utils.telemetry import (Tracer, merge_traces,
                                       phase_breakdown, format_report)

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_telemetry(monkeypatch):
    monkeypatch.delenv("BIGDL_TPU_TRACE", raising=False)
    telemetry.set_active(None)
    chaos.clear()
    yield
    tr = telemetry.get_active()
    if tr is not None:
        tr.close()
    telemetry.set_active(None)
    chaos.clear()


def _dataset(n=64, d=6, batch=16):
    rng = np.random.default_rng(0)
    samples = [Sample(rng.standard_normal(d).astype(np.float32),
                      np.float32(i % 2)) for i in range(n)]
    return DataSet.array(samples).transform(
        SampleToMiniBatch(batch, drop_last=True))


def _linear_opt(ds=None, **kw):
    return (Optimizer(nn.Sequential().add(nn.Linear(6, 2)),
                      ds or _dataset(), nn.CrossEntropyCriterion(), **kw)
            .set_optim_method(Adam(1e-2))
            .set_end_when(Trigger.max_epoch(1)))


def _load_trace(path):
    blob = json.loads(file_io.get_filesystem(path).read_bytes(path))
    assert isinstance(blob["traceEvents"], list)
    return blob


# ---------------------------------------------------------------------------
# the Tracer core
# ---------------------------------------------------------------------------

def test_spans_nest_and_json_is_perfetto_shaped(tmp_path):
    tr = Tracer(str(tmp_path), rank=0)
    with tr.span("outer", kind="test"):
        time.sleep(0.002)
        with tr.span("inner"):
            time.sleep(0.002)
        tr.instant("marker", reason="mid-outer")
    tr.counter("train", data_wait_s=0.25, step_s=0.5)
    path = tr.flush()
    blob = _load_trace(path)
    evs = blob["traceEvents"]
    # metadata names the process by rank
    meta = [e for e in evs if e["ph"] == "M" and e["name"] == "process_name"]
    assert meta and "rank 0" in meta[0]["args"]["name"]
    spans = {e["name"]: e for e in evs if e["ph"] == "X"}
    outer, inner = spans["outer"], spans["inner"]
    assert outer["args"] == {"kind": "test"}
    # nesting by time containment on the same pid/tid (how Perfetto nests)
    assert inner["pid"] == outer["pid"] == 0
    assert inner["tid"] == outer["tid"]
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1
    inst = [e for e in evs if e["ph"] == "i"]
    assert inst and inst[0]["name"] == "marker"
    ctr = [e for e in evs if e["ph"] == "C"]
    assert ctr and ctr[0]["args"] == {"data_wait_s": 0.25, "step_s": 0.5}
    # every timed event carries a wall-anchored timestamp in micros
    assert all(e["ts"] > 1e12 for e in evs if e["ph"] != "M")


def test_ring_bounds_memory_and_counts_drops(tmp_path):
    tr = Tracer(str(tmp_path), rank=0, ring=10, flush_every=0)
    for i in range(25):
        tr.instant(f"e{i}")
    assert len(tr.events_tail(100)) == 10
    assert tr.dropped == 15
    blob = _load_trace(tr.flush())
    assert blob["otherData"]["dropped_events"] == 15
    names = [e["name"] for e in blob["traceEvents"] if e["ph"] == "i"]
    assert names == [f"e{i}" for i in range(15, 25)]  # newest survive


def test_flush_through_memory_scheme_and_autoflush():
    dir_ = f"memory://telemetry_{os.getpid()}"
    tr = Tracer(dir_, rank=3, flush_every=2)
    tr.instant("a")
    tr.instant("b")  # second append crosses flush_every -> inline flush
    blob = _load_trace(tr.path)
    assert blob["otherData"]["rank"] == 3
    assert [e["name"] for e in blob["traceEvents"]
            if e["ph"] == "i"] == ["a", "b"]


def test_worker_threads_get_named_tracks(tmp_path):
    tr = Tracer(str(tmp_path), rank=0)
    telemetry.set_active(tr)

    def worker():
        telemetry.thread_name("my-worker")
        telemetry.complete("prefetch.item", 0.004)

    t = threading.Thread(target=worker, name="py-worker")
    t.start()
    t.join()
    with telemetry.span("data"):
        pass
    blob = _load_trace(tr.flush())
    names = {e["args"]["name"] for e in blob["traceEvents"]
             if e["ph"] == "M" and e["name"] == "thread_name"}
    assert "my-worker" in names
    spans = {e["name"]: e for e in blob["traceEvents"] if e["ph"] == "X"}
    assert spans["prefetch.item"]["tid"] != spans["data"]["tid"]


# ---------------------------------------------------------------------------
# disabled mode: zero overhead, no thread, no events
# ---------------------------------------------------------------------------

def test_disabled_mode_is_inert_and_allocation_free(tmp_path):
    assert not telemetry.enabled()
    assert telemetry.maybe_start() is None
    # module helpers hand out one shared no-op singleton and emit nothing
    s1, s2 = telemetry.span("data"), telemetry.span("step", x=1)
    assert s1 is s2
    with s1:
        pass
    telemetry.complete("step", 0.1)
    telemetry.instant("x")
    telemetry.counter("train", v=1.0)
    threads_before = threading.active_count()
    opt = _linear_opt()
    opt.optimize()
    assert telemetry.get_active() is None
    # the tracer has no thread even when ON; OFF certainly adds none
    assert threading.active_count() <= threads_before
    assert glob.glob(str(tmp_path / "trace.*.json")) == []


# ---------------------------------------------------------------------------
# instrumented training: spans, counters, prefetch worker, chaos markers
# ---------------------------------------------------------------------------

def test_traced_lenet_run_has_phase_spans_and_counters(tmp_path,
                                                       monkeypatch):
    """The acceptance scenario: a 5-step LeNet CPU run under
    BIGDL_TPU_TRACE produces per-rank Perfetto-loadable JSON whose
    trace_report breakdown shows data/step/checkpoint spans and a
    data_wait_fraction in [0, 1]."""
    from bigdl_tpu.models import LeNet5
    trace_dir = tmp_path / "trace"
    monkeypatch.setenv("BIGDL_TPU_TRACE", str(trace_dir))
    rng = np.random.default_rng(0)
    samples = [Sample(rng.normal(size=(28, 28, 1)).astype(np.float32),
                      np.int32(i % 10)) for i in range(5 * 64)]
    ds = DataSet.array(samples).transform(
        SampleToMiniBatch(64, drop_last=True))
    opt = (Optimizer(LeNet5(10), ds, nn.ClassNLLCriterion())
           .set_optim_method(Adam(1e-3))
           .set_end_when(Trigger.max_epoch(1))
           .set_checkpoint(str(tmp_path / "ckpt"),
                           Trigger.several_iteration(2)))
    opt.optimize()
    # the optimizer owned the tracer and closed (flushed) it
    assert telemetry.get_active() is None
    files = glob.glob(str(trace_dir / "trace.*.json"))
    assert len(files) == 1
    merged = merge_traces(str(trace_dir))
    bd = phase_breakdown(merged)
    for phase in ("data", "step", "checkpoint"):
        assert bd["phases"][phase]["count"] >= 1, bd["phases"]
    assert bd["phases"]["step"]["count"] == 5
    assert 0.0 <= bd["data_wait_fraction"] <= 1.0
    # per-step counter track: the four loop series plus the per-step MFU
    # pair (armed because tracing is on — utils/flops.device_peak_flops
    # always yields a denominator, nominal on CPU) and the gradient-wire
    # collective pair (armed with it; the 8-device data axis has a real
    # cross-device reduce to measure)
    ctr = [e for e in merged["traceEvents"]
           if e["ph"] == "C" and e["name"] == "train"]
    assert len(ctr) == 5
    assert set(ctr[0]["args"]) == {"data_wait_s", "step_s",
                                   "records_per_sec",
                                   "prefetch_queue_depth",
                                   "mfu", "model_flops_per_step",
                                   "collective_s", "collective_fraction"}
    # the prefetch worker produced on its own named thread track
    spans = [e for e in merged["traceEvents"]
             if e["ph"] == "X" and e["name"] == "prefetch.item"]
    step = next(e for e in merged["traceEvents"]
                if e["ph"] == "X" and e["name"] == "step")
    assert spans and all(s["tid"] != step["tid"] for s in spans)
    # checkpoint IO spans from file_io under the optimizer's checkpoint
    assert bd["phases"]["ckpt.write"]["count"] >= 2
    # the report renders
    text = format_report(bd, merged)
    assert "data_wait_fraction" in text and "step" in text


def test_flush_on_crash_preserves_chaos_marker(tmp_path, monkeypatch):
    """A run that dies mid-epoch still leaves a loadable trace whose
    last events include the injected fault marker (chaos instant)."""
    trace_dir = tmp_path / "trace"
    monkeypatch.setenv("BIGDL_TPU_TRACE", str(trace_dir))
    with chaos.scoped("data.batch=fail@2"):
        opt = _linear_opt()  # no checkpoint path: the failure re-raises
        with pytest.raises(chaos.ChaosFault):
            opt.optimize()
    merged = merge_traces(str(trace_dir))
    names = [e["name"] for e in merged["traceEvents"] if e["ph"] == "i"]
    assert "chaos:data.batch" in names
    bd = phase_breakdown(merged)
    assert bd["phases"].get("data", {}).get("count", 0) >= 1
    assert bd["instants"]["chaos:data.batch"] == 1


def test_evaluator_and_predictor_spans(tmp_path):
    from bigdl_tpu.optim import Evaluator, Predictor, Top1Accuracy
    tr = Tracer(str(tmp_path), rank=0)
    telemetry.set_active(tr)
    model = nn.Sequential().add(nn.Linear(6, 2)).add(nn.LogSoftMax())
    rng = np.random.default_rng(0)
    samples = [Sample(rng.standard_normal(6).astype(np.float32),
                      np.float32(i % 2)) for i in range(32)]
    Evaluator(model).test(DataSet.array(samples), [Top1Accuracy()],
                          batch_size=16)
    Predictor(model, batch_size=16).predict(DataSet.array(samples))
    tr.close()
    blob = _load_trace(tr.path)
    names = {e["name"] for e in blob["traceEvents"] if e["ph"] == "X"}
    assert {"evaluate", "eval.batch", "predict",
            "predict.batch"} <= names


# ---------------------------------------------------------------------------
# supervisor integration: trace tail + flush-on-stall
# ---------------------------------------------------------------------------

def test_crash_report_embeds_trace_tail_and_flushes(tmp_path):
    tr = Tracer(str(tmp_path / "trace"), rank=0, flush_every=0)
    telemetry.set_active(tr)
    with tr.span("step", neval=7):
        pass
    sup = Supervisor({"step": 1.0}, report_dir=str(tmp_path))
    path = sup._write_report("step", 2.0, 1.0, {}, "test stall")
    rep = json.loads(file_io.get_filesystem(path).read_bytes(path))
    tail_names = [e["name"] for e in rep["trace_tail"]]
    assert "step" in tail_names
    # flush-on-crash: the trace file exists WITHOUT close() ever running,
    # and carries the supervisor's stall marker
    blob = _load_trace(tr.path)
    names = [e["name"] for e in blob["traceEvents"]]
    assert "stall" in names
    tr.close()


def test_crash_report_without_tracer_has_no_tail(tmp_path):
    sup = Supervisor({"step": 1.0}, report_dir=str(tmp_path))
    rep = sup.crash_report("step", 2.0, 1.0, {})
    assert "trace_tail" not in rep


# ---------------------------------------------------------------------------
# multi-rank merge + phase breakdown + straggler detection
# ---------------------------------------------------------------------------

def _write_rank_trace(dir_, rank, step_s, steps=4):
    tr = Tracer(str(dir_), rank=rank, flush_every=0)
    for i in range(steps):
        tr.complete("data", 0.002, neval=i)
        tr.complete("step", step_s, neval=i)
    tr.flush()


def test_merge_and_straggler_rank_detection(tmp_path):
    _write_rank_trace(tmp_path, 0, step_s=0.010)
    _write_rank_trace(tmp_path, 1, step_s=0.100)  # the slow host
    merged = merge_traces(str(tmp_path))
    assert merged["otherData"]["ranks"] == [0, 1]
    assert {e["pid"] for e in merged["traceEvents"]
            if e["ph"] == "X"} == {0, 1}
    # time-sorted with metadata first
    ts = [e["ts"] for e in merged["traceEvents"] if e["ph"] != "M"]
    assert ts == sorted(ts)
    bd = phase_breakdown(merged)
    assert bd["phases"]["step"]["count"] == 8
    assert set(bd["ranks"]) == {"0", "1"}
    assert bd["ranks"]["1"]["step_mean_s"] == pytest.approx(0.1, rel=0.01)
    stragglers = bd["straggler_ranks"]
    assert [s["rank"] for s in stragglers] == [1]
    assert stragglers[0]["x_median"] == pytest.approx(10.0, rel=0.05)
    assert "STRAGGLER rank 1" in format_report(bd, merged)


def test_merge_empty_dir_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        merge_traces(str(tmp_path))
    with pytest.raises(FileNotFoundError):
        merge_traces(str(tmp_path / "missing"))


def test_trace_report_cli(tmp_path):
    _write_rank_trace(tmp_path, 0, step_s=0.004)
    merged_out = tmp_path / "merged.json"
    res = subprocess.run(
        [sys.executable, os.path.join(_REPO_ROOT, "tools",
                                      "trace_report.py"),
         str(tmp_path), "--json", "--out", str(merged_out)],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "PYTHONPATH": _REPO_ROOT})
    assert res.returncode == 0, res.stderr
    bd = json.loads(res.stdout)
    assert bd["phases"]["step"]["count"] == 4
    assert merged_out.exists()
    # empty dir -> non-zero exit (the runbook smoke asserts on this)
    empty = tmp_path / "empty"
    empty.mkdir()
    res2 = subprocess.run(
        [sys.executable, os.path.join(_REPO_ROOT, "tools",
                                      "trace_report.py"), str(empty)],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "PYTHONPATH": _REPO_ROOT})
    assert res2.returncode != 0


# ---------------------------------------------------------------------------
# Metrics snapshot/summary + the epoch-done log line
# ---------------------------------------------------------------------------

def test_metrics_snapshot_and_summary():
    m = Metrics()
    m.add("get batch time average", 0.2)
    m.add("get batch time average", 0.4)
    m.set("dropped iterations", 3.0)
    snap = m.snapshot()
    assert snap["get batch time average"] == {
        "mean": pytest.approx(0.3), "count": 2,
        "total": pytest.approx(0.6)}
    assert snap["dropped iterations"]["count"] == 1
    s = m.summary()
    assert "get batch time average" in s
    assert "mean 0.3" in s and "count 2" in s and "total 0.6" in s


def test_epoch_done_line_prints_metrics_summary(caplog):
    caplog.set_level(logging.INFO, logger="bigdl_tpu")
    opt = _linear_opt()
    opt.optimize()
    done = [r.message for r in caplog.records
            if "done:" in r.message and "Epoch" in r.message]
    assert done, "no epoch-done log line"
    assert "get batch time average" in done[-1]
    assert "mean" in done[-1] and "count" in done[-1]


def test_train_summary_writes_all_three_reference_scalars(tmp_path):
    """Reference parity (TrainSummary.scala tags): Loss + LearningRate +
    Throughput land for every logged iteration."""
    from bigdl_tpu.visualization import TrainSummary
    ts = TrainSummary(str(tmp_path), "job")
    opt = _linear_opt().set_train_summary(ts).set_log_interval(1)
    opt.optimize()
    loss = ts.read_scalar("Loss")
    assert len(loss) >= 2
    assert len(ts.read_scalar("LearningRate")) == len(loss)
    thr = ts.read_scalar("Throughput")
    assert len(thr) == len(loss)
    assert all(v > 0 for _, v, _ in thr)
    ts.close()
