"""Smoke tests for the runnable examples (reference: pyspark's
simple_integration_test drives the shipped examples the same way)."""

import numpy as np
import pytest

# subprocess integration: the slow lane (pyproject addopts)
pytestmark = pytest.mark.slow

from bigdl_tpu.utils.engine import Engine


@pytest.fixture(autouse=True)
def fresh_engine():
    Engine.reset()
    yield
    Engine.reset()


def test_lenet_local_example(tmp_path):
    from examples import lenet_local
    res = lenet_local.main(["--epochs", "3",
                            "--checkpoint", str(tmp_path)])
    (_, acc), = [(m, r.result()[0]) for m, r in res]
    assert acc > 0.9
    assert any(p.name.startswith("model.") for p in tmp_path.iterdir())


def test_image_classification_example():
    from examples import image_classification
    acc, res = image_classification.main(["--n", "256"])
    assert acc > 0.9


def test_ml_pipeline_example():
    pytest.importorskip("pandas")
    from examples import ml_pipeline
    assert ml_pipeline.main(["--n", "256"]) > 0.85


def test_udf_predictor_example():
    pytest.importorskip("pandas")
    from examples import udf_predictor
    assert udf_predictor.main(["--n", "192"]) > 0.9


def test_tensorflow_interop_example():
    from examples import tensorflow_interop
    assert tensorflow_interop.main([]) < 1e-4


def test_transformer_lm_long_context_example():
    from examples import transformer_lm_long_context
    acc, err = transformer_lm_long_context.main(["--epochs", "10"])
    assert acc > 0.9 and err < 1e-3


def test_text_classification_example():
    from examples import text_classification
    res = text_classification.main(["--n", "256"])
    (_, acc), = [(m, r.result()[0]) for m, r in res]
    assert acc > 0.9


def test_moe_expert_parallel_example():
    from examples import moe_expert_parallel
    loss, err = moe_expert_parallel.main(["--epochs", "5"])
    assert loss < 2.4 and err < 1e-3


def test_quantized_serving_example():
    from examples import quantized_serving
    full, beam = quantized_serving.main(["--epochs", "5"])
    assert len(full) == 7 and len(beam) == 7


def test_fine_tuning_example(tmp_path):
    from examples import fine_tuning
    acc, frozen = fine_tuning.main(
        ["--pretrain-epochs", "3", "--tune-epochs", "3",
         "--weights", str(tmp_path / "w.bin")])
    assert frozen               # scale_w=0 froze the feature extractor
    assert acc > 0.9            # head alone adapts to the permuted labels


def test_migrate_from_bigdl_example():
    from examples import migrate_from_bigdl
    acc = migrate_from_bigdl.main(["--epochs", "4"])
    assert acc > 0.9, acc
