"""Wide & Deep recommender tests (models/widedeep + nn/embedding):
forward contract over the recsys feature layout, embedding_row role
coverage, tables sharded exactly 1/N over fsdp×tp with a bit-identical
forward, gradient flow into BOTH tables, and the LookupTable move to
nn/embedding.py staying import- and save/load-compatible."""

import jax
import jax.numpy as jnp
import numpy as np

import bigdl_tpu.nn as nn
from bigdl_tpu.dataset import FeatureSpec, synthetic_criteo_records
from bigdl_tpu.models import WideDeep
from bigdl_tpu.parallel import LayoutSharding, MeshLayout
from bigdl_tpu.utils import memstats
from bigdl_tpu.utils.engine import Engine


def _small_spec():
    return FeatureSpec(n_cat=4, n_dense=2, multihot_slots=2,
                       deep_buckets=512, wide_buckets=256)


def _batch(spec, n=16, seed=3):
    return np.stack([spec.featurize(r).feature for r in
                     synthetic_criteo_records(n, seed=seed, spec=spec)])


def _labels(spec, n=16, seed=3):
    return np.array([r["label"] for r in
                     synthetic_criteo_records(n, seed=seed, spec=spec)],
                    dtype=np.int32)


def test_forward_logprobs_shape():
    spec = _small_spec()
    m = WideDeep.from_spec(spec, embed_dim=8, hidden=(16,)).build(
        jax.random.key(0))
    assert m.input_dim == spec.input_dim
    x = _batch(spec, 8)
    y = m.forward(jnp.asarray(x))
    assert y.shape == (8, 2)
    np.testing.assert_allclose(np.exp(np.asarray(y)).sum(axis=-1),
                               np.ones(8), rtol=1e-5)


def test_pad_slots_masked_out_of_bag():
    """-1 multihot pad slots must contribute NOTHING to the bag sum
    (they clip to row 0 in the gather, then mask to zero)."""
    spec = _small_spec()
    m = WideDeep.from_spec(spec, embed_dim=8, hidden=(16,)).build(
        jax.random.key(0))
    rec = {"cats": [f"c{i}:v1" for i in range(spec.n_cat)], "tags": [],
           "dense": [1.0, 2.0], "label": 0}
    x = spec.featurize(rec).feature
    x_row0 = x.copy()
    # same record with pad slots pointing AT row 0 explicitly — masked,
    # so the output must not change
    x_row0[spec.n_cat:spec.n_cat + spec.multihot_slots] = -1.0
    y1 = m.forward(jnp.asarray(x[None]))
    y2 = m.forward(jnp.asarray(x_row0[None]))
    np.testing.assert_array_equal(np.asarray(y1), np.asarray(y2))


def test_both_tables_carry_embedding_row_role():
    m = WideDeep()
    deep_t, wide_t = m.modules[0], m.modules[1]
    assert isinstance(deep_t, nn.LookupTable)
    assert isinstance(wide_t, nn.LookupTable)
    assert deep_t.param_roles() == {"weight": "embedding_row"}
    assert wide_t.param_roles() == {"weight": "embedding_row"}


def test_tables_shard_one_over_n_bit_identical():
    """Under fsdp=2 × tp=2 each embedding table is resident at exactly
    1/4 per device (the recommender FSDP story), and the sharded forward
    bit-matches the replicated one — a local gather, no full-table
    reassembly changing numerics."""
    Engine.reset()
    Engine.init()
    spec = _small_spec()
    m = WideDeep.from_spec(spec, embed_dim=8, hidden=(16,)).build(
        jax.random.key(0))
    x = jnp.asarray(_batch(spec, 8))
    ref = np.asarray(m.forward(x))

    mesh = MeshLayout(1, 2, 2).install(jax.devices()[:4])
    shardings = LayoutSharding(m, min_size=0).param_sharding(mesh, m.params)
    placed = jax.device_put(m.params, shardings)

    tables = memstats.embedding_table_bytes(m, placed)
    assert tables is not None and len(tables) == 2
    for t in tables:
        assert t["device_fraction"] == 0.25, t
        assert t["table_bytes_per_device"] * 4 == t["table_bytes"]
    rows = sorted(t["rows"] for t in tables)
    assert rows == [spec.wide_buckets, spec.deep_buckets]

    # the gather itself is exact; the MLP's sharded matmuls may reduce
    # in a different order, so allow float32 ulps (bit-identity proper
    # is asserted serving-vs-Predictor under the SAME sharding, in
    # tools/workload_smoke.py)
    y, _ = m.apply(placed, m.state, x)
    np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-5, atol=1e-6)
    Engine.reset()


def test_gradients_reach_both_tables():
    spec = _small_spec()
    m = WideDeep.from_spec(spec, embed_dim=8, hidden=(16,)).build(
        jax.random.key(0))
    crit = nn.ClassNLLCriterion()
    x = jnp.asarray(_batch(spec, 16))
    y = jnp.asarray(_labels(spec, 16))

    def loss_fn(p):
        out, _ = m.apply(p, m.state, x, training=True,
                         rng=jax.random.key(1))
        return crit.loss(out, y)

    loss, grads = jax.value_and_grad(loss_fn)(m.params)
    assert np.isfinite(float(loss))
    g_deep = float(jnp.sum(jnp.abs(grads[0]["weight"])))
    g_wide = float(jnp.sum(jnp.abs(grads[1]["weight"])))
    assert g_deep > 0.0 and g_wide > 0.0


def test_learns_synthetic_labels():
    """The synthetic label is crc-weight-deterministic, so a few SGD
    steps must actually reduce the loss (not noise-fitting)."""
    spec = _small_spec()
    m = WideDeep.from_spec(spec, embed_dim=8, hidden=(16,)).build(
        jax.random.key(0))
    crit = nn.ClassNLLCriterion()
    x = jnp.asarray(_batch(spec, 64, seed=5))
    y = jnp.asarray(_labels(spec, 64, seed=5))

    @jax.jit
    def step(p):
        def loss_fn(q):
            out, _ = m.apply(q, m.state, x, training=True,
                             rng=jax.random.key(1))
            return crit.loss(out, y)
        loss, grads = jax.value_and_grad(loss_fn)(p)
        return loss, jax.tree.map(lambda a, g: a - 0.5 * g, p, grads)

    params = m.params
    first, params = step(params)
    for _ in range(25):
        loss, params = step(params)
    assert float(loss) < float(first)


# ------------------------------------- LookupTable move (nn/embedding)


def test_lookup_table_reexports_one_class():
    """The PR-20 move to nn/embedding.py keeps every historical import
    path resolving to the SAME class object."""
    from bigdl_tpu.nn.dropout import LookupTable as from_dropout
    from bigdl_tpu.nn.embedding import LookupTable as from_embedding
    assert from_dropout is from_embedding is nn.LookupTable


def test_lookup_table_save_load_format_compatible(tmp_path):
    """bigdl_tpu-module-v1 blobs round-trip across the module move —
    a checkpoint written before the move loads after it."""
    tbl = nn.Sequential().add(nn.LookupTable(16, 4)).build(
        jax.random.key(2))
    path = str(tmp_path / "tbl")
    tbl.save(path)
    loaded = nn.Module.load(path)
    idx = jnp.asarray([[0, 3, 15]], jnp.float32)
    np.testing.assert_array_equal(np.asarray(tbl.forward(idx)),
                                  np.asarray(loaded.forward(idx)))
    np.testing.assert_array_equal(np.asarray(tbl.params[0]["weight"]),
                                  np.asarray(loaded.params[0]["weight"]))


def test_widedeep_save_load_roundtrip(tmp_path):
    spec = _small_spec()
    m = WideDeep.from_spec(spec, embed_dim=8, hidden=(16,)).build(
        jax.random.key(0))
    x = jnp.asarray(_batch(spec, 4))
    path = str(tmp_path / "wd")
    m.save(path)
    loaded = nn.Module.load(path)
    np.testing.assert_array_equal(np.asarray(m.forward(x)),
                                  np.asarray(loaded.forward(x)))
