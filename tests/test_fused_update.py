"""Fused train-step arithmetic (ISSUE 7 tentpole): multi-tensor optimizer
update (optim/fused.py) and the bucketed bf16 gradient wire
(parallel/wire.py).

The contract under test is BIT-parity: fusing changes the compiled
program's granularity (a handful of large kernels instead of one per
leaf), never the scalar expression each element sees.  The one documented
exception: under ZeRO (ShardedDataParallel) on a multi-device axis the
bucket/buffer sharding constraints change how GSPMD decomposes the
cross-device gradient reduction, reassociating the float sum — parity
there is ~1e-7 relative (pinned below), not bitwise.

Also pins the wire/clip ORDERING: clipping always sees wire-rounded
gradients (compress-then-aggregate, docs/performance.md "Step arithmetic
& overlap"); the bucketed wire must preserve that bit-for-bit.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import bigdl_tpu.nn as nn
from bigdl_tpu.common import set_seed
from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
from bigdl_tpu.optim import Adam, Optimizer, SGD, Trigger
from bigdl_tpu.optim.method import Adadelta, Adagrad, Adamax, LBFGS, RMSprop
from bigdl_tpu.optim import fused as fused_mod
from bigdl_tpu.parallel import wire as wire_mod
from bigdl_tpu.parallel.sharding import DataParallel, ShardedDataParallel
from bigdl_tpu.utils.engine import Engine


def _tree(seed=0):
    """A mixed-dtype pytree shaped like a small model's params."""
    k = jax.random.split(jax.random.PRNGKey(seed), 6)
    return {
        "conv": {"weight": jax.random.normal(k[0], (5, 5, 1, 6)),
                 "bias": jax.random.normal(k[1], (6,))},
        "bn": {"weight": jax.random.normal(k[2], (6,), jnp.bfloat16)},
        "fc": [jax.random.normal(k[3], (84, 10)),
               jax.random.normal(k[4], (10,), jnp.bfloat16)],
    }


def _assert_bitwise(a, b, msg=""):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        assert x.dtype == y.dtype, msg
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y),
                                      err_msg=msg)


# ----------------------------------------------------------------------
# layout / fuse / unfuse
# ----------------------------------------------------------------------

def test_fuse_unfuse_roundtrip_bitwise():
    t = _tree()
    layout = fused_mod.plan(t)
    # one buffer per dtype present (f32 + bf16 here)
    assert len(layout.groups) == 2
    bufs = fused_mod.fuse(layout, t)
    assert all(b.ndim == 1 for b in bufs)
    assert sum(int(b.size) for b in bufs) == sum(layout.sizes)
    _assert_bitwise(fused_mod.unfuse(layout, bufs), t, "roundtrip")


def test_layout_matches_rejects_scalars_and_shape_drift():
    t = _tree()
    layout = fused_mod.plan(t)
    assert layout.matches(jax.tree.map(jnp.zeros_like, t))
    # same structure, different leaf shape => not a param-shaped slot tree
    bad = jax.tree.map(lambda x: jnp.zeros(x.size), t)
    assert not layout.matches(bad)
    # scalar state (Adam's t counter) must never fuse
    single = {"w": jnp.ones((4, 4))}
    l2 = fused_mod.plan(single)
    assert not l2.matches({"w": jnp.float32(3.0)})


def test_single_leaf_per_dtype_falls_back():
    """Nothing to fuse => the per-leaf update runs (no added reshapes)."""
    m = SGD(0.1)
    p = {"w": jnp.ones((8,))}
    g = {"w": jnp.full((8,), 0.5)}
    s = m.init_state(p)
    ref = m.update(g, p, s, 0.1)
    out = m.update_fused(g, p, s, 0.1)
    _assert_bitwise(out[0], ref[0])
    _assert_bitwise(out[1], ref[1])


# ----------------------------------------------------------------------
# per-method bit parity
# ----------------------------------------------------------------------

@pytest.mark.parametrize("method", [
    SGD(0.1, momentum=0.9, weight_decay=1e-4),
    Adam(1e-3),
    Adagrad(1e-2),
    Adadelta(),
    Adamax(2e-3),
    RMSprop(1e-3),
], ids=lambda m: type(m).__name__)
def test_method_fused_update_bitwise(method):
    p = _tree(1)
    g = jax.tree.map(lambda x: (x * 0.01).astype(x.dtype), _tree(2))
    s = method.init_state(p)
    lr = method.get_learning_rate()
    p_ref, s_ref = method.update(g, p, s, lr)
    p_f, s_f = method.update_fused(g, p, s, lr)
    _assert_bitwise(p_f, p_ref, type(method).__name__)
    _assert_bitwise(s_f, s_ref, type(method).__name__ + " state")
    # second step from the fused state keeps agreeing (slot trees took the
    # roundtrip once already)
    p_ref2, s_ref2 = method.update(g, p_ref, s_ref, lr)
    p_f2, s_f2 = method.update_fused(g, p_f, s_f, lr)
    _assert_bitwise(p_f2, p_ref2, type(method).__name__ + " step2")
    _assert_bitwise(s_f2, s_ref2, type(method).__name__ + " state2")


def test_lbfgs_opts_out():
    m = LBFGS()
    assert m.supports_fused is False
    p = {"w": jnp.ones((6,)), "v": jnp.ones((3, 2))}
    g = jax.tree.map(lambda x: x * 0.1, p)
    s = m.init_state(p)
    ref = m.update(g, p, s, 1.0)
    out = m.update_fused(g, p, s, 1.0)  # silently the per-leaf path
    _assert_bitwise(out[0], ref[0])


# ----------------------------------------------------------------------
# bucketed gradient wire
# ----------------------------------------------------------------------

def test_bucket_assignment_caps_and_order():
    sizes = [100, 200, 50, 1000, 10]
    itemsize = 2  # bf16
    cap_mb = 600 * 2 / (1 << 20)  # 600 elements
    buckets = wire_mod.bucket_assignment(sizes, itemsize, cap_mb)
    assert [i for b in buckets for i in b] == list(range(len(sizes)))
    for b in buckets:
        elems = sum(sizes[i] for i in b)
        assert elems <= 600 or len(b) == 1  # oversized leaf rides alone
    assert buckets == [[0, 1, 2], [3], [4]]


def test_wire_cast_bucketed_bitwise():
    g = _tree(3)
    ref = wire_mod.wire_cast(g, jnp.bfloat16, 0.0)
    for mb in (0.001, 0.01, 1024.0):
        out = wire_mod.wire_cast(g, jnp.bfloat16, mb)
        _assert_bitwise(out, ref, f"bucket_mb={mb}")


def test_wire_cast_none_passthrough():
    g = _tree(4)
    assert wire_mod.wire_cast(g, None, 8.0) is g


def test_measure_collective_seconds():
    Engine.reset()
    Engine.init()
    mesh = Engine.mesh()
    t = wire_mod.measure_collective_seconds(mesh, _tree(5), jnp.bfloat16,
                                            bucket_mb=0.01)
    if mesh.shape.get("data", 1) > 1:
        assert t > 0.0
    # single-device axis: no collective exists
    Engine.reset()
    Engine.init(devices=[jax.devices()[0]])
    assert wire_mod.measure_collective_seconds(
        Engine.mesh(), _tree(5), jnp.bfloat16) == 0.0
    Engine.reset()


# ----------------------------------------------------------------------
# end-to-end parity (the acceptance criterion)
# ----------------------------------------------------------------------

def _samples(n=128, seed=0):
    rng = np.random.default_rng(seed)
    xs = rng.normal(0.0, 0.1, size=(n, 28, 28, 1)).astype(np.float32)
    ys = rng.integers(0, 10, size=n)
    return [Sample(xs[i], np.int32(ys[i])) for i in range(n)]


class _LossCapture:
    def __init__(self):
        self.losses = []

    def add_scalar(self, name, value, step):
        if name == "Loss":
            self.losses.append(float(value))


def _resnet_block_model():
    """A ResNet-style model small enough for 5 CPU steps: conv stem, one
    basic residual block, pool, linear head."""
    from bigdl_tpu.models.resnet import ShortcutType, _basic_block
    set_seed(11)
    m = nn.Sequential()
    m.add(nn.SpatialConvolution(1, 8, 3, 3, 2, 2, 1, 1))
    m.add(nn.SpatialBatchNormalization(8))
    m.add(nn.ReLU())
    blk, _ = _basic_block(8, 8, 1, ShortcutType.B)
    m.add(blk)
    m.add(nn.Reshape([14 * 14 * 8]))
    m.add(nn.Linear(14 * 14 * 8, 10))
    m.add(nn.LogSoftMax())
    return m


def _train(model_fn, steps=5, strategy=None, clip_norm=None):
    set_seed(7)
    model = model_fn()
    ds = DataSet.array(_samples()).transform(
        SampleToMiniBatch(32, drop_last=True))
    cap = _LossCapture()
    opt = (Optimizer(model, ds, nn.ClassNLLCriterion())
           .set_optim_method(Adam(1e-3))
           .set_end_when(Trigger.max_iteration(steps))
           .set_log_interval(1)
           .set_train_summary(cap))
    if strategy is not None:
        opt.set_strategy(strategy)
    if clip_norm is not None:
        opt.set_gradient_clipping_by_l2_norm(clip_norm)
    opt.optimize()
    return cap.losses, [np.asarray(l) for l in jax.tree.leaves(model.params)]


def _lenet():
    from bigdl_tpu.models import LeNet5
    return LeNet5(10)


@pytest.fixture(autouse=True)
def _fresh_engine():
    Engine.reset()
    yield
    Engine.reset()


@pytest.mark.parametrize("model_fn", [_lenet, _resnet_block_model],
                         ids=["lenet", "resnet_block"])
def test_fused_update_parity_data_parallel(model_fn, monkeypatch):
    """Acceptance: 5-step LeNet and a ResNet-block model, pure DP — the
    fused update is bit-identical to the per-leaf path."""
    Engine.init()
    losses0, params0 = _train(model_fn)
    monkeypatch.setenv("BIGDL_TPU_FUSED_UPDATE", "1")
    losses1, params1 = _train(model_fn)
    assert losses1 == losses0
    for a, b in zip(params1, params0):
        np.testing.assert_array_equal(a, b)


@pytest.mark.parametrize("model_fn", [_lenet, _resnet_block_model],
                         ids=["lenet", "resnet_block"])
def test_fused_update_parity_zero(model_fn, monkeypatch):
    """Acceptance: the same runs under ZeRO (ShardedDataParallel).  The
    fused buffers' P('data') sharding constraint changes how GSPMD
    decomposes the cross-device reduction, so parity is the documented
    float tolerance (reassociation-level, ~1e-7 relative), not bitwise."""
    Engine.init()
    losses0, params0 = _train(
        model_fn, strategy=ShardedDataParallel(min_size=1))
    monkeypatch.setenv("BIGDL_TPU_FUSED_UPDATE", "1")
    losses1, params1 = _train(
        model_fn, strategy=ShardedDataParallel(min_size=1))
    np.testing.assert_allclose(losses1, losses0, rtol=1e-5)
    for a, b in zip(params1, params0):
        np.testing.assert_allclose(
            a.astype(np.float32), b.astype(np.float32),
            rtol=1e-4, atol=1e-5)


def test_bucketed_wire_parity_and_clip_ordering(monkeypatch):
    """The bucketed wire is bit-identical to the per-leaf wire, INCLUDING
    under L2-norm clipping — which proves the ordering: the norm is
    computed on wire-rounded grads either way (wire-before-clip).  If the
    bucketed path clipped first, the bf16 rounding of already-scaled
    grads would diverge bitwise within a step."""
    Engine.init()
    for clip in (None, 1.0):
        monkeypatch.delenv("BIGDL_TPU_WIRE_BUCKET_MB", raising=False)
        losses0, params0 = _train(_lenet, clip_norm=clip)
        monkeypatch.setenv("BIGDL_TPU_WIRE_BUCKET_MB", "0.25")
        losses1, params1 = _train(_lenet, clip_norm=clip)
        assert losses1 == losses0, f"clip={clip}"
        for a, b in zip(params1, params0):
            np.testing.assert_array_equal(a, b, err_msg=f"clip={clip}")


def test_bucketed_wire_with_fused_update_and_zero(monkeypatch):
    """All three knobs at once (bucketed wire + fused update + ZeRO): the
    full fused-arithmetic step trains to the same losses within the
    documented ZeRO tolerance."""
    Engine.init()
    losses0, params0 = _train(
        _lenet, strategy=ShardedDataParallel(min_size=1))
    monkeypatch.setenv("BIGDL_TPU_FUSED_UPDATE", "1")
    monkeypatch.setenv("BIGDL_TPU_WIRE_BUCKET_MB", "0.25")
    losses1, params1 = _train(
        _lenet, strategy=ShardedDataParallel(min_size=1))
    np.testing.assert_allclose(losses1, losses0, rtol=1e-5)
    for a, b in zip(params1, params0):
        np.testing.assert_allclose(
            a.astype(np.float32), b.astype(np.float32),
            rtol=1e-4, atol=1e-5)


def test_collective_counter_in_trace_and_report(tmp_path, monkeypatch):
    """Acceptance: `train.collective_s` (and collective_fraction) appear
    in the Optimizer's counter track and in tools/trace_report.py output
    when tracing is armed, beside the existing mfu track."""
    import os
    import subprocess
    import sys

    from bigdl_tpu.utils import telemetry
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    trace_dir = tmp_path / "trace"
    monkeypatch.setenv("BIGDL_TPU_TRACE", str(trace_dir))
    monkeypatch.setenv("BIGDL_TPU_WIRE_BUCKET_MB", "0.25")
    Engine.init()
    _train(_lenet, steps=4)

    merged = telemetry.merge_traces(str(trace_dir))
    counters = [e for e in merged["traceEvents"]
                if e.get("ph") == "C" and e.get("name") == "train"]
    with_coll = [e for e in counters if "collective_s" in e["args"]]
    assert with_coll, "no collective_s samples on the train counter track"
    # 8-device data axis: a real cross-device reduce was measured
    assert all(e["args"]["collective_s"] > 0 for e in with_coll)
    assert all(0 <= e["args"]["collective_fraction"] <= 1
               for e in with_coll)

    bd = telemetry.phase_breakdown(merged)
    assert "train.collective_s" in bd["counters"]

    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "trace_report.py"),
         str(trace_dir)],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "PYTHONPATH": repo})
    assert r.returncode == 0, r.stderr
    assert "train.collective_s" in r.stdout


def test_collective_not_armed_without_tracing():
    Engine.init()
    model = _lenet()
    ds = DataSet.array(_samples()).transform(
        SampleToMiniBatch(32, drop_last=True))
    opt = (Optimizer(model, ds, nn.ClassNLLCriterion())
           .set_optim_method(Adam(1e-3))
           .set_end_when(Trigger.max_iteration(2)))
    opt.optimize()
    assert opt._collective_s is None


def test_step_knobs_recorded(monkeypatch):
    """_build_step records the knobs it was traced with — bench embeds
    them in the per-config record for MFU attribution."""
    monkeypatch.setenv("BIGDL_TPU_FUSED_UPDATE", "1")
    monkeypatch.setenv("BIGDL_TPU_WIRE_BUCKET_MB", "4")
    Engine.init(devices=[jax.devices()[0]])
    model = _lenet()
    model.build(jax.random.PRNGKey(0))
    opt = Optimizer(model, dataset=None, criterion=nn.ClassNLLCriterion(),
                    end_trigger=Trigger.max_iteration(1))
    opt.set_optim_method(SGD(0.1))
    opt._build_step(Engine.mesh())
    assert opt._step_knobs == {"fused_update": True, "wire_bucket_mb": 4.0,
                               "donate": True}


def test_collective_counter_verified_against_probe(tmp_path, monkeypatch):
    """Collective-overlap VERIFICATION (ISSUE 13 satellite of the PR 7
    flags): the emitted train.collective_s / collective_fraction
    counters on a multi-axis (2,2,1) layout mesh must be internally
    consistent (fraction == min(1, collective_s/step_s) of the same
    sample, modulo the trace's 1e-6 arg rounding) AND agree with an
    independent wire.measure_collective_seconds probe over the same
    data x fsdp axes — the counter is a checked claim, not a hope."""
    import json as _json
    import os

    from bigdl_tpu.common import get_policy
    from bigdl_tpu.parallel import LayoutSharding, MeshLayout

    monkeypatch.setenv("BIGDL_TPU_TRACE", str(tmp_path))
    monkeypatch.setenv("BIGDL_TPU_WIRE_BUCKET_MB", "0.25")
    set_seed(11)
    model = nn.Sequential(nn.Linear(64, 64, with_bias=False), nn.ReLU(),
                          nn.Linear(64, 8, with_bias=False))
    rng = np.random.default_rng(3)
    xs = rng.normal(0.0, 1.0, size=(96, 64)).astype(np.float32)
    ys = rng.integers(0, 8, size=96)
    ds = DataSet.array(
        [Sample(x, np.int32(y)) for x, y in zip(xs, ys)]).transform(
        SampleToMiniBatch(32, drop_last=True))
    Engine.reset()
    mesh = MeshLayout(2, 2, 1).install(jax.devices()[:4])
    opt = (Optimizer(model, ds, nn.CrossEntropyCriterion(),
                     strategy=LayoutSharding(model, min_size=0))
           .set_optim_method(SGD(learning_rate=0.05))
           .set_end_when(Trigger.max_iteration(3))
           .set_log_interval(1))
    opt.optimize()

    samples = []
    for name in os.listdir(tmp_path):
        if not name.startswith("trace."):
            continue
        blob = _json.loads((tmp_path / name).read_text())
        for ev in blob.get("traceEvents", []):
            if ev.get("ph") == "C" and ev.get("name") == "train":
                a = ev.get("args", {})
                if "collective_s" in a and "step_s" in a:
                    samples.append((a["collective_s"],
                                    a["collective_fraction"], a["step_s"]))
    assert samples, "no collective samples on the train counter track"
    for cs, frac, ss in samples:
        assert cs > 0  # 4-device data x fsdp axes: a real reduce
        expect = min(1.0, cs / max(ss, 1e-9))
        assert abs(frac - expect) <= 0.02 * expect + 1e-5
    # the armed value vs an independent probe of the SAME reduce
    probe = wire_mod.measure_collective_seconds(
        mesh, model.params, get_policy().wire_dtype, bucket_mb=0.25,
        axis=("data", "fsdp"))
    assert probe > 0
    ratio = samples[0][0] / probe
    assert 0.02 <= ratio <= 50.0, \
        f"armed collective_s {samples[0][0]} vs probe {probe}"
