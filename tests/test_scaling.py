"""Scaling-evidence tooling (bigdl_tpu/tools/scaling.py): the compiled
distributed train step must contain real XLA collectives, and the HLO
introspection that bench.py / dryrun_multichip rely on must find them.
"""

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

import bigdl_tpu.nn as nn
from bigdl_tpu.optim import Optimizer, SGD, Trigger
from bigdl_tpu.tools.scaling import collective_counts
from bigdl_tpu.models.lenet import LeNet5


def test_dp_step_contains_gradient_allreduce():
    mesh = Mesh(np.asarray(jax.devices()).reshape(8), ("data",))
    model = LeNet5(10).build(jax.random.key(0))
    opt = Optimizer(model, dataset=None, criterion=nn.ClassNLLCriterion(),
                    end_trigger=Trigger.max_iteration(1))
    opt.set_optim_method(SGD(learning_rate=0.05))
    step, param_sh, data_sh = opt._build_step(mesh)
    params = jax.device_put(model.params, param_sh)
    opt_state = opt.optim_method.init_state(params)
    inp = jax.device_put(jnp.zeros((16, 28, 28, 1), jnp.float32), data_sh)
    tgt = jax.device_put(jnp.ones((16,), jnp.int32), data_sh)
    compiled = step.lower(params, model.state, opt_state, inp, tgt,
                          jnp.float32(0.05), jax.random.key(1)).compile()
    colls = collective_counts(compiled.as_text())
    assert colls.get("all-reduce", 0) >= 1, colls


def test_collective_counts_parses_hlo_snippets():
    hlo = """
    %all-reduce.1 = f32[100]{0} all-reduce(%p), replica_groups={}
    %all-gather.2 = f32[8,4]{1,0} all-gather(%x), dimensions={0}
    %add.3 = f32[] add(%a, %b)
    """
    counts = collective_counts(hlo)
    assert counts.get("all-reduce") == 1
    assert counts.get("all-gather") == 1
    assert "reduce-scatter" not in counts


def test_strategy_collective_signatures():
    """Each parallelism strategy must lower to its expected ICI collectives
    on the virtual mesh (evidence the strategies are real XLA programs, not
    Python-side simulations): DP = one gradient all-reduce; ZeRO adds
    all-gathers of the sharded params/opt-state; engaged TP adds
    activation-path collectives beyond the single gradient all-reduce;
    ring SP = a collective-permute chain; Ulysses SP = all-to-alls."""
    from bigdl_tpu.tools.scaling import strategy_signatures

    sig = strategy_signatures(8)
    # >= 1, not == 1: async lowering counts all-reduce-start/-done as
    # separate matches (same convention as the committed DP test above)
    assert sig["dp8"].get("all-reduce", 0) >= 1, sig["dp8"]
    assert sig["zero8"].get("all-gather", 0) >= 1, sig["zero8"]
    tp = sig["dp4xtp2"]
    assert sum(tp.values()) > 1 and tp.get("all-reduce", 0) >= 1, tp
    assert sig["ring_sp8"].get("collective-permute", 0) >= 1, sig["ring_sp8"]
    assert sig["ulysses_sp8"].get("all-to-all", 0) >= 1, sig["ulysses_sp8"]
