"""Self-healing serving control plane (bigdl_tpu/serve/control.py).

The control-plane contract under test (docs/serving.md "Self-healing &
resilience"):
  - a wedged replica (uninterruptible chaos wedge) is detected by
    heartbeat silence, condemned, and restarted — with zero accepted
    requests dropped or answered incorrectly (bit-match vs per-sample
    bulk Predictor.predict);
  - a dead replica thread (chaos exit drill) requeues its held batch
    before dying and is respawned — zero loss again;
  - the restart budget bounds self-healing: past it the server flips
    unhealthy, queued requests fail typed, /healthz goes 503;
  - a chaos-degraded canary is auto-rolled-back with a typed
    CanaryRejected reason and never serves more than its fraction; a
    healthy canary auto-promotes;
  - admission is priority/tenant aware: expired queue slots are swept
    before fresh traffic is shed, a full queue sheds its lowest-priority
    entry for a higher-priority arrival, per-tenant token buckets raise
    QuotaExceeded with retry_after_s;
  - stop() never strands a queued caller on result() forever.
"""

import json
import os
import threading
import time

import numpy as np
import jax
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu import Engine
from bigdl_tpu.optim import Predictor
from bigdl_tpu.serve import (CanaryRejected, DynamicBatcher,
                             InferenceServer, QuotaExceeded,
                             ReplicaLostError, RequestTimeout,
                             ServerClosed, ServerOverloaded, TenantQuotas)
from bigdl_tpu.utils import chaos


def _linear_model(seed=0, din=4, dout=3):
    return nn.Sequential().add(nn.Linear(din, dout)).build(
        jax.random.key(seed))


def _rows(n, din=4, seed=0):
    return np.random.default_rng(seed).normal(size=(n, din)) \
        .astype(np.float32)


def _per_sample_ref(model, x):
    p = Predictor(model)
    return np.stack([p.predict(x[i:i + 1])[0] for i in range(len(x))])


def _wait(pred, timeout=10.0):
    deadline = time.monotonic() + timeout
    while not pred() and time.monotonic() < deadline:
        time.sleep(0.02)
    return pred()


# -------------------------------------------------- admission satellites


def test_admission_sweeps_expired_queue_slots():
    """A queue full of expired-deadline requests must shed ITSELF at
    admission, not the fresh arrival (the queued-dead-request slot
    leak)."""
    clock = [0.0]
    b = DynamicBatcher(max_batch=4, max_wait_s=0.0, queue_limit=3,
                       clock=lambda: clock[0])
    x = _rows(1)[0]
    stale = [b.submit(x, deadline=5.0) for _ in range(3)]
    clock[0] = 10.0  # every queued deadline is now past
    fresh = b.submit(x)  # would have been ServerOverloaded before
    assert b.depth() == 1 and not fresh.done()
    for h in stale:
        with pytest.raises(RequestTimeout):
            h.result(0)
    stats = b.stats()
    assert stats["shed_timeout"] == 3
    assert stats["shed_overload"] == 0


def test_priority_eviction_sheds_lowest_first():
    """Under queue pressure a strictly-higher-priority arrival evicts the
    newest lowest-priority queued request (typed ServerOverloaded on the
    victim); an equal-priority arrival is refused with retry_after_s."""
    b = DynamicBatcher(max_batch=4, max_wait_s=0.0, queue_limit=2)
    x = _rows(1)[0]
    low_old = b.submit(x, priority=0)
    low_new = b.submit(x, priority=0)
    high = b.submit(x, priority=2)  # full queue: evicts low_new
    assert not high.done() and not low_old.done()
    with pytest.raises(ServerOverloaded):
        low_new.result(0)
    assert b.stats()["shed_priority"] == 1
    # an arrival that outranks nobody is refused, with a retry estimate
    with pytest.raises(ServerOverloaded) as ei:
        b.submit(x, priority=0)
    assert ei.value.retry_after_s and ei.value.retry_after_s > 0
    stats = b.stats()
    assert stats["shed_overload"] == 1
    assert stats["shed_by_priority"]["0"] == 2  # victim + refused arrival


def test_tenant_token_bucket_quota():
    """Independent per-tenant buckets: burst tokens, QuotaExceeded with
    retry_after_s when empty, refill at qps."""
    clock = [0.0]
    q = TenantQuotas(qps=2.0, burst=2.0, clock=lambda: clock[0])
    q.admit("a")
    q.admit("a")
    with pytest.raises(QuotaExceeded) as ei:
        q.admit("a")
    assert ei.value.retry_after_s == pytest.approx(0.5)
    assert isinstance(ei.value, ServerOverloaded)  # HTTP 429 mapping
    q.admit("b")  # tenant b has its own full bucket
    clock[0] = 0.5  # one token refilled for a
    q.admit("a")
    stats = q.stats()
    assert stats["denied"] == 1
    assert stats["denied_by_tenant"] == {"a": 1}


def test_server_submit_enforces_quota():
    Engine.init()
    server = InferenceServer(_linear_model(), queue_limit=8,
                             tenant_qps=1.0, tenant_burst=1.0)
    x = _rows(1)[0]
    server.submit(x, tenant="t1")
    with pytest.raises(QuotaExceeded):
        server.submit(x, tenant="t1")
    server.submit(x, tenant="t2")  # unaffected
    assert server.stats()["quota"]["denied"] == 1
    server.stop(drain=False)


# ------------------------------------------------------ replica restart


def test_wedged_replica_restarted_zero_loss():
    """Tier-1 acceptance: a chaos-wedged replica goes heartbeat-silent,
    the monitor condemns + respawns it, and every accepted request is
    answered bit-identically to per-sample bulk Predictor.predict —
    zero dropped, zero wrong."""
    Engine.init()
    model = _linear_model()
    n = 16
    x = _rows(n)
    ref = _per_sample_ref(model, x)
    results, lock = {}, threading.Lock()
    with chaos.scoped("serve.replica@0=wedge*1.0@2"):
        server = InferenceServer(model, max_batch=4, max_wait_ms=5,
                                 queue_limit=2 * n, example=x[0],
                                 replica_lost=0.25,
                                 restart_backoff=0.02).start()

        def client(i):
            h = server.submit(x[i])
            out = h.result(60)
            with lock:
                results[i] = out

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(n)]
        for t in threads:
            t.start()
            time.sleep(0.015)  # sustained trickle spanning the wedge
        for t in threads:
            t.join()
        assert _wait(lambda: server.stats()["restarts"] >= 1)
        stats = server.stats()
        server.stop()
    assert len(results) == n  # zero dropped
    for i in range(n):
        np.testing.assert_array_equal(results[i], ref[i])
    assert stats["restarts"] >= 1
    assert stats["healthy"] is True
    assert stats["replica_monitor"]["lost"] >= 1
    ev = stats["replica_monitor"]["events"][0]
    assert ev["error_type"] == "ReplicaLostError"


def test_dead_replica_requeues_batch_and_respawns():
    """The exit drill kills exactly one worker THREAD: it hands its held
    batch back to the queue first (zero accepted-request loss), the
    monitor detects the dead thread and respawns."""
    Engine.init()
    model = _linear_model()
    n = 10
    x = _rows(n)
    ref = _per_sample_ref(model, x)
    results, lock = {}, threading.Lock()
    with chaos.scoped("serve.replica@0=exit@2"):
        server = InferenceServer(model, max_batch=4, max_wait_ms=5,
                                 queue_limit=2 * n, example=x[0],
                                 replica_lost=0.3,
                                 restart_backoff=0.02).start()

        def client(i):
            h = server.submit(x[i])
            out = h.result(60)
            with lock:
                results[i] = out

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(n)]
        for t in threads:
            t.start()
            time.sleep(0.01)
        for t in threads:
            t.join()
        stats = server.stats()
        server.stop()
    assert len(results) == n
    for i in range(n):
        np.testing.assert_array_equal(results[i], ref[i])
    assert stats["restarts"] >= 1


def test_restart_budget_exhausted_flips_unhealthy():
    """A replica that keeps dying consumes its restart budget; past it
    the server flips unhealthy, fails queued requests typed, and rejects
    new admissions — no infinite self-healing loop, no hung callers."""
    Engine.init()
    with chaos.scoped("serve.replica@0=exit@1,2"):
        server = InferenceServer(_linear_model(), max_batch=4,
                                 max_wait_ms=2, queue_limit=8,
                                 example=_rows(1)[0],
                                 replica_lost=0.5, restart_budget=1,
                                 restart_backoff=0.01).start()
        h = server.submit(_rows(1)[0])
        with pytest.raises(ReplicaLostError):
            h.result(30)
        assert _wait(lambda: not server.healthy())
        with pytest.raises(ReplicaLostError):
            server.submit(_rows(1)[0])
        stats = server.stats()
        server.stop()
    assert stats["healthy"] is False
    assert stats["unhealthy_type"] == "ReplicaLostError"
    assert stats["restarts"] == 1  # one respawn, then the budget ended


def test_stop_never_strands_queued_callers():
    """stop() — drain or not — must resolve every still-queued request
    typed even when the whole pool died without draining (the
    blocked-on-result()-forever fix)."""
    Engine.init()
    with chaos.scoped("serve.replica@0=exit@1"):
        # no monitor armed (replica_lost=0): the dead replica stays dead
        server = InferenceServer(_linear_model(), max_batch=4,
                                 max_wait_ms=2, queue_limit=8,
                                 example=_rows(1)[0]).start()
        h1 = server.submit(_rows(1)[0])
        # the worker collects h1, the drill kills it (batch requeued)
        assert _wait(lambda: not server._pool_alive())
        h2 = server.submit(_rows(1)[0])  # admitted into a dead pool
        server.stop(drain=True)  # drain requested, nobody left to drain
    for h in (h1, h2):
        with pytest.raises(ServerClosed):
            h.result(1)


# -------------------------------------------------------------- canary


def test_canary_latency_regression_rolled_back():
    """serve.canary chaos inflates exactly the canary's batch latency:
    the rolling p99 comparator rolls it back (typed CanaryRejected in
    stats), it never serves past its fraction, and the incumbent stays
    live."""
    Engine.init()
    model = _linear_model(seed=0)
    x = _rows(24)
    fraction = 0.25
    with chaos.scoped("serve.canary=stall*0.3@1,2,3,4,5,6,7,8"):
        server = InferenceServer(model, max_batch=2, max_wait_ms=1,
                                 queue_limit=64, example=x[0],
                                 canary_min_batches=4).start()
        vid = server.swap(_linear_model(seed=9),
                          canary_fraction=fraction)
        assert vid == 2
        for i in range(60):
            server.predict(x[i % len(x)], timeout=60)
            if (server.stats().get("canary") or {}).get("state") \
                    != "running":
                break
        stats = server.stats()
        server.stop()
    c = stats["canary"]
    assert c["state"] == "rolled_back"
    assert c["reason_type"] == "CanaryRejected"
    assert "p99" in c["reason"]
    assert c["routed"] <= fraction * c["total"] + 1  # the fraction bound
    assert stats["version"] == 1  # incumbent still live
    assert stats["canary_rollbacks"] == 1
    assert stats["swaps"] == 0  # a rollback is not a swap


def test_canary_error_regression_rolled_back():
    """An erroring canary (chaos fail on the canary point) trips the
    error-rate comparator — fast-fail from its second batch."""
    Engine.init()
    model = _linear_model(seed=0)
    x = _rows(16)
    with chaos.scoped("serve.canary=fail@1,2"):
        server = InferenceServer(model, max_batch=2, max_wait_ms=1,
                                 queue_limit=64, example=x[0],
                                 canary_min_batches=6).start()
        server.swap(_linear_model(seed=9), canary_fraction=0.34)
        for i in range(60):
            try:
                server.predict(x[i % len(x)], timeout=60)
            except chaos.ChaosFault:
                pass  # the canary batch's typed per-request error
            if (server.stats().get("canary") or {}).get("state") \
                    != "running":
                break
        stats = server.stats()
        server.stop()
    c = stats["canary"]
    assert c["state"] == "rolled_back"
    assert "error rate" in c["reason"]
    assert stats["version"] == 1


def test_canary_clean_run_promoted():
    """A healthy canary auto-promotes after min_batches clean batches on
    both arms; the promotion counts as a swap and the canary version
    answers afterwards."""
    Engine.init()
    model = _linear_model(seed=0)
    new = _linear_model(seed=9)
    x = _rows(24)
    ref_new = _per_sample_ref(new, x)
    server = InferenceServer(model, max_batch=2, max_wait_ms=1,
                             queue_limit=64, example=x[0],
                             canary_min_batches=3,
                             canary_latency_ratio=100.0,
                             canary_error_margin=1.0).start()
    vid = server.swap(new, canary_fraction=0.4)
    for i in range(120):
        server.predict(x[i % len(x)], timeout=60)
        if (server.stats().get("canary") or {}).get("state") \
                == "promoted":
            break
    stats = server.stats()
    assert stats["canary"]["state"] == "promoted"
    assert stats["version"] == vid == 2
    assert stats["swaps"] == 1
    post = server.submit(x[0])
    np.testing.assert_array_equal(post.result(30), ref_new[0])
    assert post.version == vid
    server.stop()


def test_canary_fraction_validated():
    Engine.init()
    x = _rows(1)
    with InferenceServer(_linear_model(), max_wait_ms=2,
                         example=x[0]) as server:
        with pytest.raises(ValueError):
            server.swap(_linear_model(seed=3), canary_fraction=1.5)
        # a rejected canary must not burn the data path: still serving
        assert server.predict(x[0], timeout=30).shape == (3,)


def test_full_swap_supersedes_running_canary():
    Engine.init()
    model = _linear_model(seed=0)
    x = _rows(4)
    with InferenceServer(model, max_wait_ms=2, example=x[0]) as server:
        server.swap(_linear_model(seed=5), canary_fraction=0.5)
        assert server.stats()["canary"]["state"] == "running"
        vid = server.swap(_linear_model(seed=7))  # full cutover
        stats = server.stats()
        assert stats["version"] == vid == 3
        # the canary was discarded without a decision record
        assert stats.get("canary", {}).get("state") in (None, "running") \
            or stats["canary"]["version"] == 2


# ------------------------------------------------------ http front end


def test_http_retry_after_and_unhealthy_healthz():
    """429 rejections carry the typed retry_after_s as a Retry-After
    header; /healthz flips 503 once the server is unhealthy."""
    import sys
    import urllib.error
    import urllib.request

    tools_dir = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools")
    if tools_dir not in sys.path:
        sys.path.insert(0, tools_dir)
    import serve_http

    Engine.init()
    x = _rows(4)
    server = InferenceServer(_linear_model(), max_batch=2, queue_limit=2,
                             example=x[0])  # NOT started: queue fills
    httpd = serve_http.serve_forever(server, "127.0.0.1", 0)
    port = httpd.server_address[1]
    base = f"http://127.0.0.1:{port}"

    def post(path, obj):
        req = urllib.request.Request(base + path,
                                     data=json.dumps(obj).encode(),
                                     method="POST")
        try:
            with urllib.request.urlopen(req, timeout=30) as r:
                return r.status, json.loads(r.read()), dict(r.headers)
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read()), dict(e.headers)

    try:
        server.submit(x[0])
        server.submit(x[1])  # queue_limit reached
        status, body, headers = post("/v1/predict",
                                     {"inputs": x[2].tolist()})
        assert status == 429
        assert body["type"] == "ServerOverloaded"
        assert body["retry_after_s"] and body["retry_after_s"] > 0
        assert int(headers["Retry-After"]) >= 1
        # healthz: healthy then unhealthy
        with urllib.request.urlopen(base + "/healthz", timeout=30) as r:
            assert json.loads(r.read())["ok"] is True
        server._mark_unhealthy(ReplicaLostError("drill: budget spent"))
        try:
            with urllib.request.urlopen(base + "/healthz",
                                        timeout=30) as r:
                raise AssertionError(f"healthz returned {r.status}")
        except urllib.error.HTTPError as e:
            assert e.code == 503
            body = json.loads(e.read())
            assert body["ok"] is False
            assert body["type"] == "ReplicaLostError"
    finally:
        httpd.shutdown()
        server.stop(drain=False)


def test_http_tenant_priority_and_quota_429():
    """/v1/predict forwards tenant/priority; an over-quota tenant gets
    the typed QuotaExceeded as a 429 with Retry-After."""
    import sys
    import urllib.error
    import urllib.request

    tools_dir = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools")
    if tools_dir not in sys.path:
        sys.path.insert(0, tools_dir)
    import serve_http

    Engine.init()
    x = _rows(4)
    server = InferenceServer(_linear_model(), max_wait_ms=2,
                             example=x[0], tenant_qps=0.001,
                             tenant_burst=1.0).start()
    httpd = serve_http.serve_forever(server, "127.0.0.1", 0)
    port = httpd.server_address[1]
    base = f"http://127.0.0.1:{port}"

    def post(path, obj):
        req = urllib.request.Request(base + path,
                                     data=json.dumps(obj).encode(),
                                     method="POST")
        try:
            with urllib.request.urlopen(req, timeout=30) as r:
                return r.status, json.loads(r.read()), dict(r.headers)
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read()), dict(e.headers)

    try:
        status, body, _ = post("/v1/predict",
                               {"inputs": x[0].tolist(),
                                "tenant": "acme", "priority": 2})
        assert status == 200
        status, body, headers = post("/v1/predict",
                                     {"inputs": x[1].tolist(),
                                      "tenant": "acme"})
        assert status == 429
        assert body["type"] == "QuotaExceeded"
        assert "Retry-After" in headers
        # another tenant is unaffected
        status, _, _ = post("/v1/predict", {"inputs": x[2].tolist(),
                                            "tenant": "other"})
        assert status == 200
        assert server.stats()["quota"]["denied_by_tenant"] == {"acme": 1}
    finally:
        httpd.shutdown()
        server.stop()
