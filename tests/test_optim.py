"""Optim method + schedule + trigger unit tests.

Models the reference's optimizer unit tier (SURVEY.md §4): simple reference
implementations cross-checked against the real ones (RefLocalOptimizer idea) and
LR-schedule math specs.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from bigdl_tpu.optim import (SGD, Adam, Adagrad, Adadelta, Adamax, RMSprop,
                             LBFGS, Trigger, Poly, Step, MultiStep, EpochStep,
                             Default, Warmup, SequentialSchedule,
                             Top1Accuracy, Top5Accuracy)


def quadratic_min(method, steps=150, tol=1e-2):
    """All methods must minimize f(x) = ||x - c||^2."""
    c = jnp.asarray([1.0, -2.0, 3.0])
    params = {"w": jnp.zeros(3)}
    state = method.init_state(params)
    for i in range(steps):
        grads = {"w": 2 * (params["w"] - c)}
        lr = method.get_learning_rate({"evalCounter": i, "epoch": 1})
        params, state = method.update(grads, params, state, lr)
    return float(jnp.max(jnp.abs(params["w"] - c)))


@pytest.mark.parametrize("method,steps,tol", [
    (SGD(learning_rate=0.1), 100, 1e-2),
    (SGD(learning_rate=0.05, momentum=0.9), 200, 1e-2),
    (SGD(learning_rate=0.05, momentum=0.9, nesterov=True, dampening=0.0),
     200, 1e-2),
    (SGD(learning_rate=0.1, weight_decay=1e-4), 150, 2e-2),
    (Adam(learning_rate=0.1), 300, 1e-2),
    (Adagrad(learning_rate=0.5), 400, 5e-2),
    (Adadelta(epsilon=1e-2), 500, 5e-2),
    (Adamax(learning_rate=0.2), 300, 2e-2),
    (RMSprop(learning_rate=0.05), 400, 2e-2),
    (LBFGS(learning_rate=0.5), 60, 1e-2),
])
def test_methods_minimize_quadratic(method, steps, tol):
    assert quadratic_min(method, steps) < tol


def test_sgd_matches_manual_momentum():
    m = SGD(learning_rate=0.1, momentum=0.9, dampening=0.0)
    params = {"w": jnp.asarray([1.0])}
    state = m.init_state(params)
    g = {"w": jnp.asarray([1.0])}
    params, state = m.update(g, params, state, 0.1)
    np.testing.assert_allclose(np.asarray(params["w"]), [0.9])
    params, state = m.update(g, params, state, 0.1)
    # v = 0.9*1 + 1 = 1.9; w = 0.9 - 0.1*1.9 = 0.71
    np.testing.assert_allclose(np.asarray(params["w"]), [0.71], rtol=1e-6)


def test_schedules_golden():
    opt = SGD(learning_rate=0.1)
    assert Default().get_lr(opt, {"evalCounter": 0}) == 0.1
    opt2 = SGD(learning_rate=0.1, learning_rate_decay=0.1)
    np.testing.assert_allclose(
        Default().get_lr(opt2, {"evalCounter": 10}), 0.1 / 2)
    np.testing.assert_allclose(
        Poly(0.5, 100).get_lr(opt, {"evalCounter": 75}), 0.1 * 0.5)
    np.testing.assert_allclose(
        Step(10, 0.5).get_lr(opt, {"evalCounter": 25}), 0.1 * 0.25)
    np.testing.assert_allclose(
        MultiStep([10, 20], 0.1).get_lr(opt, {"evalCounter": 15}), 0.01)
    np.testing.assert_allclose(
        EpochStep(2, 0.1).get_lr(opt, {"epoch": 5}), 0.1 * 0.01)
    w = Warmup(0.01, 5, Step(10, 0.5))
    np.testing.assert_allclose(w.get_lr(opt, {"evalCounter": 3}), 0.13)
    seq = SequentialSchedule().add(Poly(1.0, 10), 10).add(Default(), 100)
    np.testing.assert_allclose(seq.get_lr(opt, {"evalCounter": 5}), 0.05)
    np.testing.assert_allclose(seq.get_lr(opt, {"evalCounter": 50}), 0.1)


def test_triggers():
    assert Trigger.max_epoch(3)({"epoch": 4})
    assert not Trigger.max_epoch(3)({"epoch": 3})
    assert Trigger.several_iteration(5)({"neval": 10})
    assert not Trigger.several_iteration(5)({"neval": 11})
    t = Trigger.every_epoch()
    assert not t({"epoch": 1})  # records the starting epoch
    assert not t({"epoch": 1})  # same epoch: no fire
    assert t({"epoch": 2})      # epoch advanced: fire
    assert not t({"epoch": 2})  # fires once per epoch
    assert t({"epoch": 3})
    assert Trigger.min_loss(0.1)({"loss": 0.05})
    assert Trigger.max_score(0.9)({"score": 0.95})


def test_validation_methods():
    out = np.asarray([[0.1, 0.9], [0.8, 0.2], [0.3, 0.7]])
    tgt = np.asarray([1, 0, 0])
    r = Top1Accuracy()(out, tgt)
    acc, n = r.result()
    assert n == 3
    np.testing.assert_allclose(acc, 2 / 3)
    r2 = r + Top1Accuracy()(out, np.asarray([1, 0, 1]))
    np.testing.assert_allclose(r2.result()[0], 5 / 6)
    out5 = np.tile(np.arange(10, dtype=np.float64), (2, 1))
    assert Top5Accuracy()(out5, np.asarray([9, 5])).result()[0] == 1.0
    assert Top5Accuracy()(out5, np.asarray([0, 4])).result()[0] == 0.0


def test_lbfgs_rosenbrock_improves():
    m = LBFGS(learning_rate=2e-3, history_size=10)

    def f(w):
        return (1 - w[0]) ** 2 + 100 * (w[1] - w[0] ** 2) ** 2

    params = {"w": jnp.asarray([-1.0, 1.0])}
    state = m.init_state(params)
    f0 = float(f(params["w"]))
    for _ in range(200):
        grads = {"w": jax.grad(f)(params["w"])}
        params, state = m.update(grads, params, state, 2e-3)
    assert float(f(params["w"])) < f0 * 0.5


def test_lbfgs_wolfe_line_search_converges_rosenbrock():
    """optimize(feval, x) = the reference's LBFGS+lswolfe entry
    (optim/OptimMethod.scala:38 + LineSearch.scala): strong-Wolfe probes of
    feval should drive Rosenbrock essentially to its (1,1) minimum — far
    beyond what the fixed-step in-jit path achieves."""
    m = LBFGS(learning_rate=1.0, max_iter=20, history_size=10)

    def f(w):
        return (1 - w[0]) ** 2 + 100 * (w[1] - w[0] ** 2) ** 2

    def feval(params):
        w = params["w"]
        return f(w), {"w": jax.grad(f)(w)}

    params = {"w": jnp.asarray([-1.0, 1.0])}
    for _ in range(10):  # 10 outer calls x 20 inner iterations
        params, losses = m.optimize(feval, params)
    assert losses[-1] < 1e-6
    np.testing.assert_allclose(np.asarray(params["w"]), [1.0, 1.0],
                               atol=1e-3)


def test_optim_method_host_optimize_quadratic():
    """Base OptimMethod.optimize: repeated host steps on a quadratic bowl
    reach the minimum, and state (momentum) persists across calls."""
    m = SGD(learning_rate=0.1, momentum=0.9)

    def feval(params):
        w = params["w"]
        return jnp.sum((w - 3.0) ** 2), {"w": 2 * (w - 3.0)}

    params = {"w": jnp.zeros((4,))}
    for _ in range(200):
        params, losses = m.optimize(feval, params)
    np.testing.assert_allclose(np.asarray(params["w"]), np.full(4, 3.0),
                               atol=1e-3)
    assert m.hyper["evalCounter"] == 200


def test_host_optimize_state_survives_checkpoint():
    """state_dict/load_state_dict carry the host-optimize trajectory
    (momentum velocity), so a restored instance continues identically."""
    m = SGD(learning_rate=0.1, momentum=0.9)

    def feval(p):
        return jnp.sum((p["w"] - 3.0) ** 2), {"w": 2 * (p["w"] - 3.0)}

    p = {"w": jnp.zeros(3)}
    for _ in range(5):
        p, _ = m.optimize(feval, p)
    m2 = SGD(learning_rate=0.1, momentum=0.9)
    m2.load_state_dict(m.state_dict())
    p_resumed, _ = m2.optimize(feval, p)
    p_straight, _ = m.optimize(feval, p)
    np.testing.assert_allclose(np.asarray(p_straight["w"]),
                               np.asarray(p_resumed["w"]), atol=1e-7)


def test_tree_nn_accuracy():
    import numpy as np
    from bigdl_tpu.optim import TreeNNAccuracy
    # (batch=2, nodes=3, classes=2): root = last node slot
    out = np.zeros((2, 3, 2))
    out[0, -1] = [0.9, 0.1]   # predicts 0
    out[1, -1] = [0.2, 0.8]   # predicts 1
    res = TreeNNAccuracy()(out, np.array([0.0, 0.0]))
    acc, n = res.result()
    assert n == 2 and acc == 0.5


def test_validator_facade():
    import numpy as np
    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import DataSet, Sample
    from bigdl_tpu.optim import (DistriValidator, LocalValidator,
                                 Top1Accuracy, Validator)
    assert DistriValidator is Validator and LocalValidator is Validator
    rng = np.random.default_rng(0)
    samples = [Sample(rng.standard_normal(4).astype(np.float32),
                      np.float32(i % 2)) for i in range(32)]
    model = nn.Sequential().add(nn.Linear(4, 2)).add(nn.LogSoftMax())
    model.build()
    res = Validator(model, DataSet.array(samples)).test(
        [Top1Accuracy()], batch_size=16)
    _, r = res[0]
    acc, n = r.result()
    assert n == 32 and 0.0 <= acc <= 1.0


def test_import_does_not_touch_devices():
    # importing the library must not initialize a jax backend (a hung TPU
    # tunnel would block every import); run in a clean subprocess
    import subprocess
    import sys
    code = (
        "import jax, bigdl_tpu, bigdl_tpu.optim, bigdl_tpu.nn\n"
        "from jax._src import xla_bridge\n"
        "assert not xla_bridge._backends, xla_bridge._backends\n"
        "print('clean')\n")
    out = subprocess.run([sys.executable, "-c", code], capture_output=True,
                         text=True, timeout=120)
    assert "clean" in out.stdout, out.stderr


def test_tree_nn_accuracy_per_node_targets():
    import numpy as np
    from bigdl_tpu.optim import TreeNNAccuracy
    out = np.zeros((2, 3, 2))
    out[0, -1] = [0.9, 0.1]
    out[1, -1] = [0.2, 0.8]
    # per-node (batch, nodes) labels: root label is the last column
    target = np.array([[1.0, 1.0, 0.0], [0.0, 0.0, 1.0]])
    acc, n = TreeNNAccuracy()(out, target).result()
    assert n == 2 and acc == 1.0


def test_cosine_decay_schedule():
    from bigdl_tpu.optim import SGD, CosineDecay

    sgd = SGD(learning_rate=1.0, learning_rate_schedule=CosineDecay(100))
    assert abs(sgd.get_learning_rate({"evalCounter": 0}) - 1.0) < 1e-9
    assert abs(sgd.get_learning_rate({"evalCounter": 50}) - 0.5) < 1e-9
    assert abs(sgd.get_learning_rate({"evalCounter": 100})) < 1e-9
    assert abs(sgd.get_learning_rate({"evalCounter": 999})) < 1e-9
    s2 = SGD(learning_rate=1.0,
             learning_rate_schedule=CosineDecay(100, min_factor=0.1))
    assert abs(s2.get_learning_rate({"evalCounter": 100}) - 0.1) < 1e-9


def test_warmup_cosine_continuity():
    """Warmup hands the after-schedule the PEAK lr and a re-zeroed
    counter: ramp-to-peak then cosine is continuous and T-phased."""
    from bigdl_tpu.optim import SGD, CosineDecay, Warmup

    sgd = SGD(learning_rate=0.1,
              learning_rate_schedule=Warmup(0.009, 100,
                                            after=CosineDecay(1000)))
    end_warm = sgd.get_learning_rate({"evalCounter": 99})
    start_cos = sgd.get_learning_rate({"evalCounter": 100})
    peak = 0.1 + 0.009 * 100
    assert abs(start_cos - peak) < 0.01 * peak  # continuous at handoff
    assert abs(end_warm - (peak - 0.009)) < 1e-9
    # cosine floor is reached T iters AFTER warmup, not at global T
    assert sgd.get_learning_rate({"evalCounter": 1100}) < 1e-9
    assert sgd.get_learning_rate({"evalCounter": 600}) > 0.1


def test_ema_update_math():
    """shadow = d*shadow + (1-d)*params after each inner update, exactly."""
    from bigdl_tpu.optim import EMA, SGD

    inner = SGD(learning_rate=0.5)
    ema = EMA(inner, decay=0.9)
    p = {"w": jnp.asarray([1.0, 2.0])}
    st = ema.init_state(p)
    np.testing.assert_allclose(np.asarray(st["shadow"]["w"]), [1.0, 2.0])
    g = {"w": jnp.asarray([1.0, 1.0])}
    p1, st1 = ema.update(g, p, st, jnp.float32(0.5))
    np.testing.assert_allclose(np.asarray(p1["w"]), [0.5, 1.5])  # sgd step
    np.testing.assert_allclose(np.asarray(st1["shadow"]["w"]),
                               0.9 * np.array([1.0, 2.0])
                               + 0.1 * np.array([0.5, 1.5]))
    p2, st2 = ema.update(g, p1, st1, jnp.float32(0.5))
    np.testing.assert_allclose(
        np.asarray(st2["shadow"]["w"]),
        0.9 * np.asarray(st1["shadow"]["w"]) + 0.1 * np.asarray(p2["w"]),
        rtol=1e-6)


def test_ema_through_optimizer_training():
    """EMA(Adam) trains through the compiled step; the shadow weights are a
    lagged average (differ from live, same structure) and serve a working
    model via EMA.apply_to."""
    from bigdl_tpu.optim import Adam, EMA, Evaluator, Top1Accuracy
    from bigdl_tpu.utils.engine import Engine
    from test_e2e_lenet import make_optimizer, synthetic_mnist
    from bigdl_tpu.dataset import DataSet
    from bigdl_tpu.models import LeNet5

    from bigdl_tpu.common import set_seed

    Engine.reset()
    Engine.init()
    set_seed(0)  # order-independent: the model init draws from the global
    # RNG stream, and convergence at 3 epochs depends on the draw
    model, opt = make_optimizer()
    # decay=0.9: at 3 epochs x 8 steps the shadow still lags the live
    # weights (the "differs" assertion below) but carries < 0.9^24 ~ 8%
    # of the random init.  The previous 0.98 left ~62% init weight in the
    # shadow, putting the accuracy bound at the mercy of jax-version
    # numeric drift (0.80 passed on jax<=0.4.30, 0.77 on 0.4.37).
    opt.set_optim_method(EMA(Adam(learning_rate=1e-3), decay=0.9))
    opt.optimize()
    live = jax.tree.leaves(model.params)
    shadow = jax.tree.leaves(
        opt.optim_method.ema_params(opt._final_opt_state))
    assert any(not np.allclose(np.asarray(a), np.asarray(b))
               for a, b in zip(live, shadow))
    ema_model = EMA.apply_to(LeNet5(10).build(), opt)
    val = DataSet.array(synthetic_mnist(256, seed=3))
    acc, _ = Evaluator(ema_model).test(val, [Top1Accuracy()],
                                       batch_size=64)[0][1].result()
    assert acc > 0.8, acc


def test_ema_apply_to_transfers_bn_state():
    """apply_to must carry the trained BN running stats, not leave the
    fresh model's zeros/ones (a BN model would otherwise eval at chance
    with no error)."""
    import bigdl_tpu.nn as nn
    from bigdl_tpu.common import set_seed
    from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
    from bigdl_tpu.optim import Adam, EMA, Optimizer, Trigger
    from bigdl_tpu.utils.engine import Engine
    from test_e2e_lenet import synthetic_mnist

    Engine.reset()
    Engine.init()
    set_seed(0)

    def bn_model():
        return (nn.Sequential()
                .add(nn.Reshape((28, 28, 1)))
                .add(nn.SpatialConvolution(1, 4, 3, 3, 1, 1, -1, -1))
                .add(nn.SpatialBatchNormalization(4))
                .add(nn.ReLU())
                .add(nn.Reshape((28 * 28 * 4,)))
                .add(nn.Linear(28 * 28 * 4, 10))
                .add(nn.LogSoftMax()))

    ds = DataSet.array(synthetic_mnist(256)).transform(
        SampleToMiniBatch(64, drop_last=True))
    opt = (Optimizer(bn_model(), ds, nn.ClassNLLCriterion())
           .set_optim_method(EMA(Adam(1e-3), decay=0.95))
           .set_end_when(Trigger.max_epoch(2)))
    opt.optimize()
    fresh = bn_model().build()
    ema_model = EMA.apply_to(fresh, opt)
    rm = np.asarray(jax.tree.leaves(ema_model.state)[0])
    assert np.abs(rm).sum() > 0  # trained running stats, not init zeros


def test_warmup_preserves_plateau_bookkeeping():
    """Warmup's counter re-basing must pass schedule writes through to the
    REAL state dict: Plateau counts one observation per epoch, not one per
    iteration (a dict copy would drop its _plateau_seen marker and the LR
    would collapse patience-fold too fast)."""
    from bigdl_tpu.optim import SGD, Warmup
    from bigdl_tpu.optim.schedules import Plateau

    sched = Warmup(0.0, 2, after=Plateau(monitor="score", patience=3,
                                         factor=0.1, mode="max"))
    sgd = SGD(learning_rate=0.1, learning_rate_schedule=sched)
    state = {"evalCounter": 5, "epoch": 1, "score": 1.0}
    for _ in range(10):  # many iterations inside ONE epoch
        lr = sgd.get_learning_rate(state)
    assert abs(lr - 0.1) < 1e-9  # patience must not tick per iteration
    # non-improving epochs tick patience once each; the 3rd (epoch 4)
    # fires the drop
    for epoch in (2, 3):
        state["epoch"] = epoch
        lr = sgd.get_learning_rate(state)
        assert abs(lr - 0.1) < 1e-9, (epoch, lr)
    state["epoch"] = 4
    lr = sgd.get_learning_rate(state)
    assert abs(lr - 0.01) < 1e-9, lr


def test_perplexity_metric():
    """exp(mean token NLL) with padding exclusion; aggregation across
    batches matches one big batch."""
    from bigdl_tpu.optim import Perplexity

    rng = np.random.default_rng(0)
    logits = rng.normal(size=(2, 5, 7)).astype(np.float32)
    lp = np.asarray(jax.nn.log_softmax(jnp.asarray(logits), axis=-1))
    tgt = np.array([[1, 2, 3, -1, -1], [0, 6, 5, 4, -1]])
    m = Perplexity()
    r = m(lp, tgt)
    ppl, n = r.result()
    assert n == 7  # 3 + 4 valid tokens
    manual = -np.mean([lp[b, t, tgt[b, t]]
                       for b in range(2) for t in range(5)
                       if tgt[b, t] >= 0])
    np.testing.assert_allclose(ppl, np.exp(manual), rtol=1e-6)
    # additive aggregation == single evaluation
    r2 = m(lp[:1], tgt[:1]) + m(lp[1:], tgt[1:])
    np.testing.assert_allclose(r2.result()[0], ppl, rtol=1e-12)
    # uniform log-probs -> ppl == vocab
    uni = np.full((1, 4, 7), -np.log(7.0))
    np.testing.assert_allclose(m(uni, np.zeros((1, 4), int)).result()[0],
                               7.0, rtol=1e-6)


def test_layerwise_grad_scaling_reaches_compiled_step():
    """set_scale_w/set_scale_b must scale gradients inside the COMPILED
    train step (the reference applies scaleW/scaleB in accGradParameters,
    so layer-wise LR scaling reaches the distributed update —
    DistriOptimizer.scala:729), not just the facade backward."""
    import numpy as np
    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import Sample
    from bigdl_tpu.optim import Optimizer, SGD, Trigger

    def run(scaled):
        from bigdl_tpu.common import set_seed
        set_seed(77)
        model = nn.Sequential(nn.Linear(6, 5), nn.Tanh(), nn.Linear(5, 3),
                              nn.LogSoftMax())
        if scaled:
            model.modules[0].set_scale_w(2.0).set_scale_b(3.0)
        r = np.random.default_rng(0)
        samples = [Sample(r.normal(size=(6,)).astype(np.float32),
                          np.int32(r.integers(0, 3))) for _ in range(8)]
        model.build()
        opt = Optimizer(model, samples, nn.ClassNLLCriterion(), batch_size=8)
        opt.set_optim_method(SGD(learning_rate=0.1))  # no momentum: delta = lr*g
        # 8 samples / batch 8 -> one batch per epoch: exactly ONE step
        opt.set_end_when(Trigger.max_epoch(1))
        before = [np.asarray(x).copy() for x in jax.tree.leaves(model.params)]
        opt.optimize()
        after = [np.asarray(x) for x in jax.tree.leaves(model.params)]
        return [a - b for a, b in zip(after, before)]

    base = run(False)
    scaled = run(True)
    # leaves order: [layer0 bias, layer0 weight, layer2 bias, layer2 weight]
    # bf16-wire tolerance: scaling happens BEFORE the wire cast (reference
    # order), so scaled-then-quantized differs from quantized-then-scaled
    # by one bf16 ulp (~0.4% relative)
    np.testing.assert_allclose(scaled[0], 3.0 * base[0], rtol=1e-2, atol=1e-7)
    np.testing.assert_allclose(scaled[1], 2.0 * base[1], rtol=1e-2, atol=1e-7)
    np.testing.assert_allclose(scaled[2], base[2], rtol=1e-2, atol=1e-8)
    np.testing.assert_allclose(scaled[3], base[3], rtol=1e-2, atol=1e-8)
    # and the scale genuinely engaged: layer0 deltas are ~3x/2x, not ~1x
    assert np.abs(scaled[1]).sum() > 1.5 * np.abs(base[1]).sum()


def test_container_level_scale_propagates():
    """Container.set_scale_w propagates to children (reference
    Container.setScaleW), so container-level scales reach both the facade
    and the compiled step's grad-scale tree."""
    import bigdl_tpu.nn as nn
    m = nn.Sequential(nn.Linear(4, 3), nn.Sequential(nn.Linear(3, 2)))
    m.set_scale_w(2.0).set_scale_b(3.0)
    assert m.modules[0].scale_w == 2.0
    assert m.modules[1].modules[0].scale_b == 3.0
    st = m._grad_scale_tree()
    leaves = jax.tree.leaves(st)
    assert sorted(set(leaves)) == [2.0, 3.0]


def test_scale_change_after_first_optimize_recompiles():
    """scaleW is baked into the compiled step as a static factor, so
    changing it between optimize() calls must recompile — the freeze idiom
    (set_scale_w(0) after a warmup phase) has to actually freeze."""
    import bigdl_tpu.nn as nn
    import numpy as np
    from bigdl_tpu.dataset import Sample
    from bigdl_tpu.optim import Optimizer, SGD, Trigger

    model = nn.Sequential(nn.Linear(4, 3), nn.LogSoftMax()).build(
        jax.random.key(0))
    r = np.random.default_rng(0)
    samples = [Sample(r.normal(size=(4,)).astype(np.float32),
                      np.int32(r.integers(0, 3))) for _ in range(8)]
    opt = Optimizer(model, samples, nn.ClassNLLCriterion(), batch_size=8)
    opt.set_optim_method(SGD(learning_rate=0.1))
    opt.set_end_when(Trigger.max_epoch(1))
    opt.optimize()                      # phase 1: trains normally
    w1 = np.asarray(model.params[0]["weight"]).copy()

    model.set_scale_w(0.0).set_scale_b(0.0)   # freeze everything
    opt.set_end_when(Trigger.max_epoch(2))
    opt.optimize()                      # phase 2: must be a no-op
    w2 = np.asarray(model.params[0]["weight"])
    np.testing.assert_array_equal(w1, w2)


def test_graph_scale_propagates_and_regularizer_is_scaled():
    """set_scale_w on a Graph reaches its nodes (reference: setScaleW on
    any module scales its parameters), and scaleW=0 freezes the
    regularizer contribution too (accRegularization takes scaleW)."""
    import bigdl_tpu.nn as nn
    import numpy as np
    from bigdl_tpu.dataset import Sample
    from bigdl_tpu.optim import Optimizer, SGD, Trigger
    from bigdl_tpu.optim.regularizer import L2Regularizer

    inp = nn.Input()
    lin = nn.Linear(4, 3, w_regularizer=L2Regularizer(10.0))
    out = nn.LogSoftMax()(lin(inp))
    g = nn.Graph(inp, out).build(jax.random.key(0))
    g.set_scale_w(0.0).set_scale_b(0.0)
    st = g._grad_scale_tree()
    assert st is not None and set(jax.tree.leaves(st)) == {0.0}

    r = np.random.default_rng(0)
    samples = [Sample(r.normal(size=(4,)).astype(np.float32),
                      np.int32(r.integers(0, 3))) for _ in range(8)]
    opt = Optimizer(g, samples, nn.ClassNLLCriterion(), batch_size=8)
    opt.set_optim_method(SGD(learning_rate=0.5))
    opt.set_end_when(Trigger.max_epoch(2))
    before = [np.asarray(x).copy() for x in jax.tree.leaves(g.params)]
    opt.optimize()
    after = [np.asarray(x) for x in jax.tree.leaves(g.params)]
    for a, b in zip(before, after):   # fully frozen incl. weight decay
        np.testing.assert_array_equal(a, b)
