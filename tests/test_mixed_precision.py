"""bf16 mixed-precision coverage (DTypePolicy compute_dtype=bfloat16).

The MFU-target bench config trains ResNet-50 under this policy
(bench.py::_cfg_resnet50_bf16) but no test exercised it — a dtype bug in
any layer's compute path would only surface on the real chip.  Contract
under test: params stay f32, forward/backward run, values agree with the
f32 path within bf16 tolerance, and end-to-end training converges.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.common import DTypePolicy, get_policy, set_policy


@pytest.fixture(autouse=True)
def _restore_policy():
    old = get_policy()
    yield
    set_policy(old)


def _models():
    from bigdl_tpu.models.lenet import LeNet5
    from bigdl_tpu.models.resnet import ResNet
    from bigdl_tpu.models.transformer_lm import TransformerLM
    # the two heavyweight sweeps carry the slow mark; lenet/lstm keep
    # bf16-policy coverage in the default lane
    return [
        ("lenet", lambda: LeNet5(10), (4, 28, 28, 1), "img"),
        pytest.param("resnet20",
                     lambda: ResNet(20, class_num=10, dataset="cifar10"),
                     (2, 32, 32, 3), "img", id="resnet20",
                     marks=pytest.mark.slow),
        ("lstm", lambda: nn.Sequential(
            nn.Recurrent(nn.LSTM(8, 12)), nn.Select(1, -1),
            nn.Linear(12, 5), nn.LogSoftMax()), (4, 6, 8), "img"),
        pytest.param("transformer", lambda: TransformerLM(
            vocab_size=50, max_len=8, d_model=16, num_heads=2,
            num_layers=1), (2, 8), "tok", id="transformer",
            marks=pytest.mark.slow),
    ]


@pytest.mark.parametrize("name,build,shape,kind",
                         _models(), ids=[m[0] for m in _models()])
def test_bf16_forward_backward_matches_f32(name, build, shape, kind):
    r = np.random.default_rng(3)
    if kind == "tok":
        x = jnp.asarray(r.integers(0, 50, size=shape), jnp.int32)
    else:
        x = jnp.asarray(r.normal(size=shape), jnp.float32)

    def run():
        m = build()
        m.build(jax.random.key(0))
        # params must be created in param_dtype regardless of compute dtype
        for leaf in jax.tree.leaves(m.params):
            assert leaf.dtype == jnp.float32, (name, leaf.dtype)

        def loss(p, xx):
            out, _ = m.apply(p, m.state, xx, training=True,
                             rng=jax.random.key(1))
            return jnp.mean(out.astype(jnp.float32) ** 2)

        val, g = jax.value_and_grad(loss)(m.params, x)
        gl = jax.tree.leaves(g)
        assert all(np.isfinite(np.asarray(leaf)).all() for leaf in gl), name
        return float(val), gl

    set_policy(DTypePolicy())              # f32 reference
    v32, g32 = run()
    set_policy(DTypePolicy(compute_dtype=jnp.bfloat16))
    v16, g16 = run()

    # bf16 has ~3 decimal digits; activations/grads agree loosely.
    # Compare the CONCATENATED gradient vector — per-leaf relative error is
    # meaningless for near-zero leaves (e.g. BN betas), where bf16 noise
    # relative to the activation scale dwarfs the f32 value
    assert v16 == pytest.approx(v32, rel=0.05), (name, v32, v16)
    va = np.concatenate([np.asarray(a).ravel() for a in g32])
    vb = np.concatenate([np.asarray(b).ravel() for b in g16])
    rel_l2 = np.linalg.norm(va - vb) / (np.linalg.norm(va) + 1e-12)
    assert rel_l2 < 0.15, (name, rel_l2)


def test_bf16_training_converges():
    """End-to-end: the bench's mixed-precision configuration (f32 params,
    bf16 compute, bf16 wire) trains to high accuracy."""
    from test_e2e_lenet import synthetic_mnist
    from bigdl_tpu.models.lenet import LeNet5
    from bigdl_tpu.optim import Adam, Evaluator, Optimizer, Top1Accuracy, \
        Trigger
    from bigdl_tpu.utils.engine import Engine

    set_policy(DTypePolicy(compute_dtype=jnp.bfloat16))
    Engine.reset()
    Engine.init()
    samples = synthetic_mnist(512)
    opt = Optimizer(LeNet5(10), samples, nn.ClassNLLCriterion(),
                    batch_size=128)
    opt.set_optim_method(Adam(1e-3))
    opt.set_end_when(Trigger.max_epoch(4))
    trained = opt.optimize()
    acc, n = Evaluator(trained).test(
        samples[:256], [Top1Accuracy()])[0][1].result()
    assert n == 256 and acc > 0.95, acc
