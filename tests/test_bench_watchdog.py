"""Regression tests for bench.py's stall watchdog (the lost-RPC guard).

The tunneled TPU backend can drop an RPC mid-run, blocking the benching
process forever (observed 2026-07-31, docs/benchmarking.md "Stall
watchdog").  These tests run bench.py's watchdog machinery in a
subprocess with an artificial stall and assert the driver-facing
contract: exactly ONE JSON line always lands on stdout — partial results
(exit 0, `stall` field) when at least one config completed, a
bench_error naming the stage (exit 1, carrying earlier per-config
errors) when none did.
"""

import pytest

# sleep-driven watchdog integration: slow lane
pytestmark = pytest.mark.slow
import json
import subprocess
import sys
import textwrap


def _run(body, timeout=90):
    import os
    code = ("import time, sys, argparse\n"
            "sys.argv = ['bench.py']\n"
            "import bench\n" + textwrap.dedent(body))
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return subprocess.run([sys.executable, "-c", code], capture_output=True,
                          text=True, timeout=timeout, cwd=repo, env=env)


def _json_lines(out):
    return [json.loads(l) for l in out.splitlines() if l.strip().startswith("{")]


def test_stall_with_no_results_emits_bench_error_with_prior_errors():
    r = _run("""
        bench._STALL_STATE['errors']['resnet50'] = 'OOM: earlier failure'
        bench._beat('put:lenet')
        bench._start_watchdog(1.0, 2.0)
        time.sleep(60)
    """)
    lines = _json_lines(r.stdout)
    assert r.returncode == 1 and len(lines) == 1
    out = lines[0]
    assert out["metric"] == "bench_error"
    assert out["stage"] == "stall:put:lenet"
    assert "OOM: earlier failure" in out["error"]


def test_stall_with_results_emits_partial_artifact_exit_zero():
    r = _run("""
        bench._STALL_STATE['results']['lenet'] = {
            'name': 'lenet', 'images_per_sec': 100.0, 'mode': 'train',
            'mfu': None, 'model_flops_per_step': 1.0}
        class D: device_kind = 'cpu'
        bench._STALL_STATE['meta'] = dict(
            args=argparse.Namespace(no_scaling=True, budget_seconds=1500.0,
                                    configs=['lenet', 'resnet50_bf16', 'lstm']),
            table_peak=None, measured_peak=None, peak=None, devices=[D()],
            t_start=0.0)
        bench._beat('compile:resnet50_bf16')
        bench._start_watchdog(1.0, 2.0)
        time.sleep(60)
    """)
    lines = _json_lines(r.stdout)
    assert r.returncode == 0 and len(lines) == 1
    out = lines[0]
    assert out["configs"]["lenet"]["images_per_sec"] == 100.0
    assert out["stall"]["stage"] == "compile:resnet50_bf16"
    # hung config excluded; untouched configs recorded, not silently lost
    assert out["stall"]["configs_not_attempted"] == ["lstm"]


def test_main_thread_claim_wins_and_watchdog_stays_silent():
    """A stale heartbeat must not produce a second JSON line once the main
    thread has claimed the emit (the late-resolving-RPC race)."""
    r = _run("""
        import threading
        bench._STALL_STATE['results']['lenet'] = {
            'name': 'lenet', 'images_per_sec': 100.0, 'mode': 'train',
            'mfu': None, 'model_flops_per_step': 1.0}
        class D: device_kind = 'cpu'
        meta = dict(
            args=argparse.Namespace(no_scaling=True, budget_seconds=1500.0,
                                    configs=['lenet']),
            table_peak=None, measured_peak=None, peak=None, devices=[D()],
            t_start=0.0)
        bench._STALL_STATE['meta'] = meta
        bench._beat('put:lenet')
        assert bench._claim_emit()
        bench._start_watchdog(0.5, 0.5)
        # the watchdog loop ticks every 10s regardless of the limits, so
        # sleeping 12s guarantees exactly one tick observes the stale beat;
        # do not shorten below 10s or the race stops being exercised
        time.sleep(12)
        bench._assemble_and_print(results=bench._STALL_STATE['results'],
                                  errors={}, skipped=[], **meta)
    """)
    lines = _json_lines(r.stdout)
    assert r.returncode == 0 and len(lines) == 1
    assert "stall" not in lines[0]


def test_healthy_fast_run_unaffected_by_watchdog():
    """End-to-end: the real lenet config on CPU with tight-but-ample limits
    completes normally and emits one line with no stall field."""
    import os
    repo = __import__("pathlib").Path(__file__).resolve().parent.parent
    env = {**os.environ}
    r = subprocess.run(
        [sys.executable, "bench.py", "--configs", "lenet", "--platform",
         "cpu", "--no-scaling"],
        capture_output=True, text=True, timeout=420, cwd=repo, env=env)
    lines = _json_lines(r.stdout)
    assert r.returncode == 0 and len(lines) == 1, r.stderr[-500:]
    out = lines[0]
    assert out["metric"] == "lenet_train_images_per_sec_per_chip"
    assert "stall" not in out


def test_flash_attention_bench_record(monkeypatch):
    """The flash_attention op bench produces a well-formed record with the
    pallas-vs-reference comparison fields (VERDICT r3 #6)."""
    monkeypatch.setenv("BIGDL_TPU_BENCH_FLASH_SHAPE", "1,2,128,32")
    import bench

    rec = bench._bench_flash("flash_attention",
                             bench.CONFIGS["flash_attention"], None)
    assert rec["mode"] == "op" and rec["shape"] == [1, 2, 128, 32]
    assert rec["reference_dt_seconds"] > 0
    assert rec["speedup_vs_reference"] > 0
    assert rec["model_flops_per_step"] == 3.5 * 4 * 1 * 2 * 128 * 128 * 32 / 2
