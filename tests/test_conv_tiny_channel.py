"""Tiny-input-channel conv padding (the LeNet compile-pathology fix).

XLA's TPU backend compiles grad-of-conv at C_in=1 pathologically slowly
(docs/benchmarking.md); `_pad_tiny_cin` pads C_in up to 8 with zero channels.
These tests pin the numerics: forward values and every gradient must be
identical with the pad on (default) and off (BIGDL_TPU_CONV_PAD_MIN_CIN=0).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from bigdl_tpu.nn import SpatialConvolution, SpatialDilatedConvolution


def _fwd(conv, params, x):
    return conv.apply(params, {}, x)[0]


def _loss_and_grads(monkeypatch, min_cin, seed=0):
    monkeypatch.setenv("BIGDL_TPU_CONV_PAD_MIN_CIN", str(min_cin))
    conv = SpatialConvolution(1, 6, 5, 5, pad_w=2, pad_h=2)
    params, _ = conv.init(jax.random.PRNGKey(seed))
    x = jax.random.normal(jax.random.PRNGKey(seed + 1), (4, 28, 28, 1))

    def loss(p, xx):
        return jnp.sum(_fwd(conv, p, xx) ** 2)

    val, grads = jax.value_and_grad(loss)(params, x)
    gx = jax.grad(loss, argnums=1)(params, x)
    return val, grads, gx


def test_pad_preserves_forward_and_grads(monkeypatch):
    v1, g1, gx1 = _loss_and_grads(monkeypatch, 8)
    v0, g0, gx0 = _loss_and_grads(monkeypatch, 0)
    np.testing.assert_allclose(np.asarray(v1), np.asarray(v0), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g0)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5,
                                   atol=1e-6)
    np.testing.assert_allclose(np.asarray(gx1), np.asarray(gx0), rtol=1e-5,
                               atol=1e-6)


def test_pad_changes_compiled_shapes(monkeypatch):
    """The whole point: with the pad on, the conv the compiler sees has C_in=8."""
    monkeypatch.setenv("BIGDL_TPU_CONV_PAD_MIN_CIN", "8")
    conv = SpatialConvolution(1, 6, 5, 5)
    params, _ = conv.init(jax.random.PRNGKey(0))
    x = jnp.zeros((2, 28, 28, 1))
    hlo = jax.jit(lambda p, xx: _fwd(conv, p, xx)).lower(params, x).as_text()
    assert "2x28x28x8" in hlo, hlo[:2000]


def test_pad_skips_wide(monkeypatch):
    monkeypatch.setenv("BIGDL_TPU_CONV_PAD_MIN_CIN", "8")
    # wide input: no pad inserted
    conv = SpatialConvolution(16, 8, 3, 3)
    p, _ = conv.init(jax.random.PRNGKey(0))
    hlo = jax.jit(lambda pp, xx: _fwd(conv, pp, xx)).lower(
        p, jnp.zeros((2, 8, 8, 16))).as_text()
    assert "stablehlo.pad" not in hlo


def test_grouped_conv_pads_per_group(monkeypatch):
    """Grouped convs used to bypass the pad entirely (their grad-of-conv
    pathology included); the pad is now group-aware — each group's channel
    block is zero-extended so feature_group_count still divides."""
    g = SpatialConvolution(4, 8, 3, 3, n_group=4)
    pg, _ = g.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 8, 4))

    def loss(p, xx):
        return jnp.sum(_fwd(g, p, xx) ** 2)

    monkeypatch.setenv("BIGDL_TPU_CONV_PAD_MIN_CIN", "8")
    v1, g1 = jax.value_and_grad(loss)(pg, x)
    gx1 = jax.grad(loss, argnums=1)(pg, x)
    # the compiler sees the padded per-group width: C_in = 4 groups x 8
    hlo = jax.jit(lambda pp, xx: _fwd(g, pp, xx)).lower(pg, x).as_text()
    assert "2x8x8x32" in hlo, hlo[:2000]
    monkeypatch.setenv("BIGDL_TPU_CONV_PAD_MIN_CIN", "0")
    v0, g0 = jax.value_and_grad(loss)(pg, x)
    gx0 = jax.grad(loss, argnums=1)(pg, x)
    np.testing.assert_allclose(np.asarray(v1), np.asarray(v0), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g0)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(gx1), np.asarray(gx0),
                               rtol=1e-5, atol=1e-6)


def test_dilated_conv_inherits_pad(monkeypatch):
    conv = SpatialDilatedConvolution(1, 4, 3, 3, dilation_w=2, dilation_h=2)
    p, _ = conv.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 12, 12, 1))
    monkeypatch.setenv("BIGDL_TPU_CONV_PAD_MIN_CIN", "8")
    y_on = _fwd(conv, p, x)
    monkeypatch.setenv("BIGDL_TPU_CONV_PAD_MIN_CIN", "0")
    y_off = _fwd(conv, p, x)
    np.testing.assert_allclose(np.asarray(y_on), np.asarray(y_off), rtol=1e-6)


def test_other_conv_families_inherit_pad(monkeypatch):
    """Temporal (WIO), Volumetric (DHWIO) and Full (lhs-dilated) convs get the
    same treatment — the Full conv's forward IS a gradient-conv-shaped program."""
    from bigdl_tpu.nn import (SpatialFullConvolution, TemporalConvolution,
                              VolumetricConvolution)
    cases = [
        (TemporalConvolution(1, 4, 3), jax.random.normal(
            jax.random.PRNGKey(1), (2, 16, 1))),
        (VolumetricConvolution(1, 4, 3, 3, 3), jax.random.normal(
            jax.random.PRNGKey(2), (2, 8, 8, 8, 1))),
        (SpatialFullConvolution(1, 4, 3, 3, stride_w=2, stride_h=2),
         jax.random.normal(jax.random.PRNGKey(3), (2, 8, 8, 1))),
    ]
    for conv, x in cases:
        p, _ = conv.init(jax.random.PRNGKey(0))
        monkeypatch.setenv("BIGDL_TPU_CONV_PAD_MIN_CIN", "8")
        y_on = _fwd(conv, p, x)
        hlo = jax.jit(lambda pp, xx, c=conv: _fwd(c, pp, xx)).lower(
            p, x).as_text()
        assert "stablehlo.pad" in hlo, type(conv).__name__
        monkeypatch.setenv("BIGDL_TPU_CONV_PAD_MIN_CIN", "0")
        y_off = _fwd(conv, p, x)
        np.testing.assert_allclose(np.asarray(y_on), np.asarray(y_off),
                                   rtol=1e-5, atol=1e-6,
                                   err_msg=type(conv).__name__)


def test_bench_flops_count_nominal_model(monkeypatch):
    """bench._step_flops must count NOMINAL FLOPs (pad disabled) even though
    the compiled step contains the padded convs — and must trace the raw
    (unjitted) step so pjit's cached padded trace can't leak through."""
    import sys, os
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench
    import jax
    import bigdl_tpu.nn as nn
    from bigdl_tpu import Engine
    from bigdl_tpu.optim import Optimizer, SGD, Trigger
    from bigdl_tpu.utils.flops import jaxpr_flops

    Engine.reset()
    Engine.init(devices=[jax.devices()[0]])
    model = nn.Sequential().add(nn.SpatialConvolution(1, 6, 5, 5)) \
        .add(nn.Reshape([24 * 24 * 6])).add(nn.Linear(24 * 24 * 6, 4)) \
        .add(nn.LogSoftMax())
    model.build(jax.random.PRNGKey(0))
    opt = Optimizer(model, dataset=None, criterion=nn.ClassNLLCriterion(),
                    end_trigger=Trigger.max_iteration(1))
    opt.set_optim_method(SGD(0.1))
    step, param_sh, _ = opt._build_step(Engine.mesh())
    inp = jnp.zeros((8, 28, 28, 1))
    tgt = jnp.zeros((8,), jnp.int32)
    args = (jax.device_put(model.params, param_sh), model.state,
            opt.optim_method.init_state(model.params), inp, tgt,
            jnp.float32(0.1), jax.random.key(1))
    # compile FIRST (pad active) so pjit's cache holds the padded trace —
    # the exact leak scenario
    compiled = step.lower(*args).compile()
    flops, detail = bench._step_flops(step, compiled, args)
    # nominal vs padded reference counts: fresh lambda wrappers per trace —
    # make_jaxpr caches by function identity, so re-tracing step.raw itself
    # would return the first call's jaxpr regardless of the env toggle
    monkeypatch.setenv("BIGDL_TPU_CONV_PAD_MIN_CIN", "0")
    nominal = jaxpr_flops(jax.make_jaxpr(lambda *a: step.raw(*a))(*args))
    monkeypatch.setenv("BIGDL_TPU_CONV_PAD_MIN_CIN", "8")
    padded = jaxpr_flops(jax.make_jaxpr(lambda *a: step.raw(*a))(*args))
    assert padded > 1.5 * nominal          # the pad is visible in FLOPs
    assert flops == pytest.approx(nominal)  # but the bench reports nominal
    Engine.reset()


# ----------------------------------------------------------------------
# reshaped-matmul (im2col) route — ops/convmm.py via BIGDL_TPU_CONV_ROUTE
# ----------------------------------------------------------------------

@pytest.mark.parametrize("conv,shape", [
    # the LeNet pathology shape family: C_in=1, 5x5
    (SpatialConvolution(1, 6, 5, 5), (4, 28, 28, 1)),
    (SpatialConvolution(1, 6, 5, 5, 2, 2, pad_w=-1, pad_h=-1),
     (4, 28, 28, 1)),                                # SAME + stride
    (SpatialConvolution(2, 8, 3, 3, pad_w=1, pad_h=1), (2, 12, 12, 2)),
    (SpatialConvolution(1, 4, 1, 1), (2, 9, 9, 1)),  # 1x1 degenerate
    (SpatialDilatedConvolution(1, 4, 3, 3, dilation_w=2, dilation_h=2),
     (2, 12, 12, 1)),
], ids=["lenet5x5", "same_stride2", "pad1", "1x1", "dilated"])
def test_matmul_route_forward_and_grad_parity(monkeypatch, conv, shape):
    """Acceptance: the reshaped-matmul route matches the lax.conv route
    (pad disabled = the untouched program) on forward values and every
    gradient, at float tolerance (the contraction is reassociated)."""
    p, _ = conv.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), shape)

    def loss(pp, xx):
        return jnp.mean(_fwd(conv, pp, xx) ** 2)

    monkeypatch.setenv("BIGDL_TPU_CONV_PAD_MIN_CIN", "0")
    v0, g0 = jax.value_and_grad(loss)(p, x)
    gx0 = jax.grad(loss, argnums=1)(p, x)
    monkeypatch.setenv("BIGDL_TPU_CONV_PAD_MIN_CIN", "8")
    monkeypatch.setenv("BIGDL_TPU_CONV_ROUTE", "matmul")
    v1, g1 = jax.value_and_grad(loss)(p, x)
    gx1 = jax.grad(loss, argnums=1)(p, x)
    np.testing.assert_allclose(np.asarray(v1), np.asarray(v0),
                               rtol=1e-5, atol=1e-6)
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g0)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(gx1), np.asarray(gx0),
                               rtol=1e-4, atol=1e-5)


def test_matmul_route_eliminates_grad_of_conv(monkeypatch):
    """The route's point: the train-step gradient program contains NO
    convolution at all — XLA never sees the pathological grad-of-conv."""
    monkeypatch.setenv("BIGDL_TPU_CONV_PAD_MIN_CIN", "8")
    conv = SpatialConvolution(1, 6, 5, 5)
    p, _ = conv.init(jax.random.PRNGKey(0))
    x = jnp.zeros((2, 28, 28, 1))

    def loss(pp, xx):
        return jnp.sum(_fwd(conv, pp, xx) ** 2)

    monkeypatch.setenv("BIGDL_TPU_CONV_ROUTE", "matmul")
    hlo = jax.jit(jax.grad(loss, argnums=(0, 1))).lower(p, x).as_text()
    assert "stablehlo.convolution" not in hlo
    assert "dot_general" in hlo
    # the pad route keeps the conv (and its grad-conv)
    monkeypatch.setenv("BIGDL_TPU_CONV_ROUTE", "pad")
    hlo_pad = jax.jit(jax.grad(loss, argnums=(0, 1))).lower(p, x).as_text()
    assert "stablehlo.convolution" in hlo_pad


def test_matmul_route_scope(monkeypatch):
    """Route selection: wide C_in stays on lax; grouped and lhs-dilated
    convs fall back to the pad (the matmul route covers the single-group
    correlation shape only)."""
    from bigdl_tpu.nn.conv import _conv_route
    monkeypatch.setenv("BIGDL_TPU_CONV_PAD_MIN_CIN", "8")
    monkeypatch.setenv("BIGDL_TPU_CONV_ROUTE", "matmul")
    wide = jnp.zeros((3, 3, 16, 8))
    tiny = jnp.zeros((5, 5, 1, 6))
    assert _conv_route(wide, 1) == "lax"
    assert _conv_route(tiny, 1) == "matmul"
    assert _conv_route(tiny, 4) == "pad"              # grouped
    assert _conv_route(tiny, 1, (2, 2)) == "pad"      # lhs-dilated (Full)
    monkeypatch.setenv("BIGDL_TPU_CONV_ROUTE", "pad")
    assert _conv_route(tiny, 1) == "pad"
    monkeypatch.setenv("BIGDL_TPU_CONV_ROUTE", "lax")
    assert _conv_route(tiny, 1) == "lax"


def test_matmul_route_bf16_policy(monkeypatch):
    """Under the bf16 compute policy the matmul route casts exactly like
    the lax route (x and w to compute dtype, f32 accumulation)."""
    from bigdl_tpu.common import DTypePolicy, get_policy, set_policy
    prev = get_policy()
    set_policy(DTypePolicy(compute_dtype=jnp.bfloat16))
    try:
        conv = SpatialConvolution(1, 6, 5, 5)
        p, _ = conv.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 14, 14, 1))
        monkeypatch.setenv("BIGDL_TPU_CONV_PAD_MIN_CIN", "8")
        monkeypatch.setenv("BIGDL_TPU_CONV_ROUTE", "pad")
        y_pad = _fwd(conv, p, x)
        monkeypatch.setenv("BIGDL_TPU_CONV_ROUTE", "matmul")
        y_mm = _fwd(conv, p, x)
        assert y_mm.dtype == y_pad.dtype
        np.testing.assert_allclose(np.asarray(y_mm, np.float32),
                                   np.asarray(y_pad, np.float32),
                                   rtol=0.05, atol=0.05)
    finally:
        set_policy(prev)


def test_lenet_trains_on_matmul_route(monkeypatch):
    """End-to-end: LeNet forwards identically on the matmul route."""
    from bigdl_tpu.models.lenet import LeNet5
    model = LeNet5(class_num=10)
    params, state = model.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 28, 28, 1))
    monkeypatch.setenv("BIGDL_TPU_CONV_PAD_MIN_CIN", "0")
    y0, _ = model.apply(params, state, x)
    monkeypatch.setenv("BIGDL_TPU_CONV_PAD_MIN_CIN", "8")
    monkeypatch.setenv("BIGDL_TPU_CONV_ROUTE", "matmul")
    y1, _ = model.apply(params, state, x)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y0),
                               rtol=1e-5, atol=1e-6)


def test_lenet_stack_trains_with_pad(monkeypatch):
    """End-to-end: the LeNet front conv forwards identically with the pad."""
    from bigdl_tpu.models.lenet import LeNet5
    model = LeNet5(class_num=10)
    params, state = model.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 28, 28, 1))
    monkeypatch.setenv("BIGDL_TPU_CONV_PAD_MIN_CIN", "8")
    y, _ = model.apply(params, state, x)
    assert y.shape == (8, 10) and bool(jnp.isfinite(y).all())
    monkeypatch.setenv("BIGDL_TPU_CONV_PAD_MIN_CIN", "0")
    y0, _ = model.apply(params, state, x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(y0), rtol=1e-5,
                               atol=1e-6)
