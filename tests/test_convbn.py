"""Conv-epilogue BN stat fusion (ops/convbn.py + nn.fused) parity tests.

The fused path must be numerically identical to the unfused conv→BN
composition — it deletes an HBM pass, not semantics (round-4 verdict #2's
untried lever; reference nn/SpatialBatchNormalization.scala semantics).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.nn.fused import ConvBN, fuse_conv_bn
from bigdl_tpu.ops.convbn import (fused_conv_bn_train, matmul_stats,
                                  matmul_stats_reference)
from bigdl_tpu.ops.batchnorm import bn_train_reference

EPS = 1e-5


def _rand(shape, seed, dtype=jnp.float32):
    return jax.random.normal(jax.random.PRNGKey(seed), shape).astype(dtype)


@pytest.mark.parametrize("R,K,C,bias", [
    (64, 16, 24, False),     # everything unaligned to the 128 lane
    (100, 128, 128, True),   # ragged rows (pad rows must not enter stats)
    (256, 96, 130, True),    # C just past one lane
])
def test_matmul_stats_parity(R, K, C, bias):
    x = _rand((R, K), 0)
    w = _rand((K, C), 1) * 0.1
    b = _rand((C,), 2) if bias else None
    y, s, ss = matmul_stats(x, w, b, interpret=True)
    yr, sr, ssr = matmul_stats_reference(x, w, b)
    np.testing.assert_allclose(np.asarray(y), np.asarray(yr),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(s), np.asarray(sr),
                               rtol=1e-4, atol=1e-3)
    np.testing.assert_allclose(np.asarray(ss), np.asarray(ssr),
                               rtol=1e-4, atol=1e-3)


def test_fused_train_forward_and_grad_parity():
    R, K, C = 96, 32, 48
    x = _rand((R, K), 3)
    w = _rand((K, C), 4) * 0.2
    gamma = 1.0 + 0.1 * _rand((C,), 5)
    beta = 0.1 * _rand((C,), 6)

    z, mean, var = fused_conv_bn_train(x, w, None, gamma, beta, EPS, True)
    y_ref = jnp.dot(x, w)
    z_ref, m_ref, v_ref = bn_train_reference(y_ref, gamma, beta, EPS)
    np.testing.assert_allclose(np.asarray(z), np.asarray(z_ref),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(mean), np.asarray(m_ref),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(var), np.asarray(v_ref),
                               rtol=1e-5, atol=1e-5)

    t = _rand((R, C), 7)

    def loss_fused(x, w, gamma, beta):
        z, _, _ = fused_conv_bn_train(x, w, None, gamma, beta, EPS, True)
        return jnp.sum((z - t) ** 2)

    def loss_ref(x, w, gamma, beta):
        z, _, _ = bn_train_reference(jnp.dot(x, w), gamma, beta, EPS)
        return jnp.sum((z - t) ** 2)

    gf = jax.grad(loss_fused, argnums=(0, 1, 2, 3))(x, w, gamma, beta)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(x, w, gamma, beta)
    for a, b_, name in zip(gf, gr, ("dx", "dw", "dgamma", "dbeta")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-3, atol=1e-3, err_msg=name)


def test_conv_bias_grad_is_zero_through_bn():
    """A pre-BN bias shifts the mean only, so its gradient is exactly 0 —
    the fused backward returns zeros rather than burning a reduction."""
    R, K, C = 40, 8, 16
    x, w = _rand((R, K), 8), _rand((K, C), 9)
    b = _rand((C,), 10)
    gamma, beta = jnp.ones((C,)), jnp.zeros((C,))

    def loss(b):
        z, _, _ = fused_conv_bn_train(x, w, b, gamma, beta, EPS, True)
        return jnp.sum(jnp.sin(z))

    db = jax.grad(loss)(b)
    np.testing.assert_allclose(np.asarray(db), 0.0, atol=1e-12)
    # and the autodiff oracle agrees it is (numerically) zero
    def loss_ref(b):
        z, _, _ = bn_train_reference(jnp.dot(x, w) + b, gamma, beta, EPS)
        return jnp.sum(jnp.sin(z))
    db_ref = jax.grad(loss_ref)(b)
    np.testing.assert_allclose(np.asarray(db_ref), 0.0, atol=1e-3)


def _regroup(params, model):
    """Regroup an unfused Sequential's param/state list to the fused
    model's structure (pairs nested one level deeper)."""
    out, i = [], 0
    for m in model.modules:
        if isinstance(m, ConvBN):
            out.append([params[i], params[i + 1]])
            i += 2
        else:
            out.append(params[i])
            i += 1
    return out


def test_module_fusion_parity(monkeypatch):
    """fuse_conv_bn rewrite: identical training forward + EMA state to the
    unfused model, on the same parameter values."""
    m = nn.Sequential()
    m.add(nn.SpatialConvolution(8, 16, 1, 1, with_bias=False))
    m.add(nn.SpatialBatchNormalization(16))
    m.add(nn.ReLU())
    m.add(nn.SpatialConvolution(16, 16, 3, 3, pad_w=1, pad_h=1))  # not 1x1
    m.add(nn.SpatialBatchNormalization(16))
    m.build(jax.random.PRNGKey(0))
    x = _rand((4, 6, 6, 8), 11)
    y0, s0 = m.apply(m.params, m.state, x, training=True)

    params, state = m.params, m.state
    # bypass the fuse-before-build guard deliberately: this test keeps the
    # pre-built param VALUES and regroups them to the fused tree itself
    from bigdl_tpu.nn.fused import _fuse
    _fuse(m)
    assert isinstance(m.modules[0], ConvBN)          # the 1x1 pair fused
    assert isinstance(m.modules[2], nn.SpatialConvolution)  # 3x3 untouched
    fp, fs = _regroup(params, m), _regroup(state, m)

    monkeypatch.setenv("BIGDL_TPU_BN_IMPL", "pallas_interpret")
    y1, s1 = m.apply(fp, fs, x, training=True)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y0),
                               rtol=1e-4, atol=1e-4)
    flat0 = jax.tree.leaves(s0)
    flat1 = jax.tree.leaves(s1)
    assert len(flat0) == len(flat1)
    for a, b in zip(flat1, flat0):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)

    # grads through the fused module match the unfused model
    t = _rand(y0.shape, 12)

    def loss_fused(fp):
        y, _ = m.apply(fp, fs, x, training=True)
        return jnp.mean((y - t) ** 2)

    g1 = jax.grad(loss_fused)(fp)
    monkeypatch.delenv("BIGDL_TPU_BN_IMPL")
    g1_fallback = jax.grad(loss_fused)(fp)  # unfused fallback, same tree
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g1_fallback)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-3)


def test_fused_add_relu_forward_and_grad_parity():
    """ops-level: fused_conv_bn_add_relu_train == BN(x@w) + resid, relu'd,
    on values and every gradient (incl. the residual's)."""
    from bigdl_tpu.ops.convbn import fused_conv_bn_add_relu_train

    R, K, C = 96, 32, 48
    x = _rand((R, K), 3)
    w = _rand((K, C), 4) * 0.2
    gamma = 1.0 + 0.1 * _rand((C,), 5)
    beta = 0.1 * _rand((C,), 6)
    resid = _rand((R, C), 8)

    z, mean, var = fused_conv_bn_add_relu_train(
        x, w, None, gamma, beta, resid, EPS, True)
    z_ref, m_ref, v_ref = bn_train_reference(jnp.dot(x, w), gamma, beta, EPS)
    z_ref = jax.nn.relu(z_ref + resid)
    np.testing.assert_allclose(np.asarray(z), np.asarray(z_ref),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(mean), np.asarray(m_ref),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(var), np.asarray(v_ref),
                               rtol=1e-5, atol=1e-5)

    t = _rand((R, C), 7)

    def loss_fused(x, w, gamma, beta, resid):
        z, _, _ = fused_conv_bn_add_relu_train(
            x, w, None, gamma, beta, resid, EPS, True)
        return jnp.sum((z - t) ** 2)

    def loss_ref(x, w, gamma, beta, resid):
        z, _, _ = bn_train_reference(jnp.dot(x, w), gamma, beta, EPS)
        return jnp.sum((jax.nn.relu(z + resid) - t) ** 2)

    gf = jax.grad(loss_fused, argnums=(0, 1, 2, 3, 4))(
        x, w, gamma, beta, resid)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2, 3, 4))(
        x, w, gamma, beta, resid)
    for a, b_, name in zip(gf, gr,
                           ("dx", "dw", "dgamma", "dbeta", "dresid")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-3, atol=1e-3, err_msg=name)


def test_residual_tail_module_parity(monkeypatch):
    """ConvBNAddReLU on a real bottleneck block: the fused path matches
    the unfused fallback on forward, BN EMA state, and every param grad."""
    from bigdl_tpu.models.resnet import ShortcutType, _bottleneck
    from bigdl_tpu.nn.fused import ConvBNAddReLU

    blk, _ = _bottleneck(16, 4, 1, ShortcutType.B)
    fuse_conv_bn(blk)
    assert any(isinstance(m, ConvBNAddReLU) for m in blk.modules)
    p, s = blk.init(jax.random.PRNGKey(0))
    x = _rand((8, 6, 6, 16), 1)

    monkeypatch.setenv("BIGDL_TPU_BN_IMPL", "pallas_interpret")
    y1, s1 = blk.apply(p, s, x, training=True)
    monkeypatch.delenv("BIGDL_TPU_BN_IMPL")
    y0, s0 = blk.apply(p, s, x, training=True)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y0),
                               rtol=1e-4, atol=1e-4)
    for a, b in zip(jax.tree.leaves(s1), jax.tree.leaves(s0)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)

    t = _rand(y0.shape, 12)

    def loss(pp):
        y, _ = blk.apply(pp, s, x, training=True)
        return jnp.mean((y - t) ** 2)

    monkeypatch.setenv("BIGDL_TPU_BN_IMPL", "pallas_interpret")
    g1 = jax.grad(loss)(p)
    monkeypatch.delenv("BIGDL_TPU_BN_IMPL")
    g0 = jax.grad(loss)(p)
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g0)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-3)


def test_residual_tail_eval_mode_falls_back(monkeypatch):
    """Eval mode must use the running stats (unfused children) — the fused
    kernel computes batch stats and must not engage."""
    from bigdl_tpu.models.resnet import ShortcutType, _bottleneck

    blk, _ = _bottleneck(16, 4, 1, ShortcutType.B)
    import copy
    ref = copy.deepcopy(blk)
    fuse_conv_bn(blk)
    p, s = blk.init(jax.random.PRNGKey(0))
    x = _rand((4, 6, 6, 16), 2)
    monkeypatch.setenv("BIGDL_TPU_BN_IMPL", "pallas_interpret")
    y_eval, _ = blk.apply(p, s, x, training=False)
    assert bool(jnp.isfinite(y_eval).all())


def test_residual_tail_bigdl_format_defuses(tmp_path):
    """Saving a tail-fused model in bigdl format de-fuses it back to the
    reference block shape (ConcatTable -> CAddTable -> ReLU) — the fusion
    is a TPU-local rewrite, not a wire class — and the reload forwards
    identically."""
    from bigdl_tpu.interop import bigdl as bigdl_fmt
    from bigdl_tpu.models.resnet import ShortcutType, _bottleneck

    blk, _ = _bottleneck(16, 4, 1, ShortcutType.B)
    fuse_conv_bn(blk)
    blk.build(jax.random.PRNGKey(0))
    x = _rand((2, 6, 6, 16), 1)
    y0, _ = blk.apply(blk.params, blk.state, x)
    p = str(tmp_path / "tail.bigdl")
    bigdl_fmt.save(blk, p)
    m2 = bigdl_fmt.load(p)
    assert not any(type(m).__name__ == "ConvBNAddReLU"
                   for m in m2.modules)
    y1, _ = m2.apply(m2.params, m2.state, x)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y0),
                               rtol=1e-5, atol=1e-6)


def test_resnet50_rewrite_fuses_bottleneck_convs():
    """ResNet-50's bottleneck 1x1 convs fuse: the block-opening 1x1 pairs
    become ConvBN, every residual tail (closing 1x1 conv + BN + shortcut
    add + ReLU, one per block x 16 blocks) becomes ConvBNAddReLU; the
    3x3/7x7/strided-shortcut convs stay unfused."""
    from bigdl_tpu.models.resnet import ResNet
    from bigdl_tpu.nn.fused import ConvBNAddReLU

    model = ResNet(50, class_num=10, dataset="imagenet")
    fuse_conv_bn(model)

    def count(m, cls):
        if isinstance(m, cls):
            return 1
        if isinstance(m, nn.Sequential) or hasattr(m, "modules"):
            return sum(count(c, cls) for c in getattr(m, "modules", []))
        return 0

    pairs = count(model, ConvBN)
    tails = count(model, ConvBNAddReLU)
    assert tails == 16, f"expected 16 fused residual tails, got {tails}"
    assert pairs >= 16, f"expected >=16 fused pairs in ResNet-50, got {pairs}"


def test_module_fusion_parity_bf16(monkeypatch):
    """Under a bf16 compute policy the fused path must cast exactly like
    the unfused conv (x and w to compute dtype) — caught by review: the
    all-f32 parity tests could not see a missing cast."""
    from bigdl_tpu.common import DTypePolicy, get_policy, set_policy

    prev = get_policy()
    set_policy(DTypePolicy(compute_dtype=jnp.bfloat16))
    try:
        m = nn.Sequential()
        m.add(nn.SpatialConvolution(8, 16, 1, 1, with_bias=False))
        m.add(nn.SpatialBatchNormalization(16))
        fuse_conv_bn(m)
        m.build(jax.random.PRNGKey(0))
        x = _rand((4, 6, 6, 8), 21)
        monkeypatch.setenv("BIGDL_TPU_BN_IMPL", "pallas_interpret")
        y1, s1 = m.apply(m.params, m.state, x, training=True)
        monkeypatch.delenv("BIGDL_TPU_BN_IMPL")
        y0, s0 = m.apply(m.params, m.state, x, training=True)
        assert y1.dtype == y0.dtype
        np.testing.assert_allclose(
            np.asarray(y1, np.float32), np.asarray(y0, np.float32),
            rtol=0.05, atol=0.05)
        for a, b in zip(jax.tree.leaves(s1), jax.tree.leaves(s0)):
            np.testing.assert_allclose(np.asarray(a, np.float32),
                                       np.asarray(b, np.float32),
                                       rtol=0.05, atol=0.05)
    finally:
        set_policy(prev)


def test_fuse_after_build_fails_loud():
    m = nn.Sequential()
    m.add(nn.SpatialConvolution(4, 8, 1, 1))
    m.add(nn.SpatialBatchNormalization(8))
    m.build(jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="BEFORE build"):
        fuse_conv_bn(m)


def test_module_fusion_mesh_parity(monkeypatch):
    """The fused path composes with a data-only mesh: per-shard matmul
    epilogues + psum'd stats == the unfused global-batch model (same
    shard_map+psum construction as BatchNormalization's pallas route)."""
    from bigdl_tpu.nn.fused import _fuse
    from bigdl_tpu.utils.engine import Engine

    Engine.init()  # 8-device data mesh from the conftest virtual CPUs
    m = nn.Sequential()
    m.add(nn.SpatialConvolution(8, 16, 1, 1, with_bias=False))
    m.add(nn.SpatialBatchNormalization(16))
    m.build(jax.random.PRNGKey(0))
    x = _rand((16, 5, 5, 8), 31)  # batch 16 over the 8-way data axis
    y0, s0 = m.apply(m.params, m.state, x, training=True)

    params, state = m.params, m.state
    _fuse(m)
    fp, fs = _regroup(params, m), _regroup(state, m)
    monkeypatch.setenv("BIGDL_TPU_BN_IMPL", "pallas_interpret")
    y1, s1 = jax.jit(
        lambda p, s, x: m.apply(p, s, x, training=True))(fp, fs, x)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y0),
                               rtol=1e-4, atol=1e-4)
    for a, b in zip(jax.tree.leaves(s1), jax.tree.leaves(s0)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)

    t = _rand(np.asarray(y0).shape, 32)

    def loss(p):
        y, _ = m.apply(p, fs, x, training=True)
        return jnp.mean((y - t) ** 2)

    g1 = jax.jit(jax.grad(loss))(fp)
    monkeypatch.delenv("BIGDL_TPU_BN_IMPL")
    g0 = jax.grad(loss)(fp)  # unfused fallback on the same tree
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g0)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-4)
