"""TreeLSTM sentiment example tests (reference analog: the
example/treeLSTMSentiment workload) + ModelValidator CLI."""

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.models import TreeLSTMSentiment, encode_tree
from bigdl_tpu.optim import TreeNNAccuracy


def test_encode_tree_topological():
    #    root
    #    /  \
    #   .    2
    #  / \
    # 0   1
    children, leaf_ids, root = encode_tree(((0, 1), 2), n_nodes=5)
    assert children.shape == (5, 2) and leaf_ids.shape == (5,)
    assert root == 4  # children-before-parent layout, root last
    # every internal node's children precede it
    for i, (l, r) in enumerate(children):
        if l >= 0:
            assert l < i and r < i
    assert sorted(leaf_ids[leaf_ids >= 0]) == [0, 1, 2]


def test_tree_sentiment_forward_and_learn():
    model = TreeLSTMSentiment(vocab_size=20, embed_dim=8, hidden_size=6,
                              class_num=3)
    params, state = model.init(jax.random.key(0))
    children, leaf_ids, root = encode_tree(((0, 1), (2, 3)), n_nodes=7)
    tokens = np.array([[1, 2, 3, 4]], np.int32)
    batch = (jnp.asarray(tokens),
             jnp.asarray(children[None]), jnp.asarray(leaf_ids[None]))
    out, _ = jax.jit(lambda p, b: model.apply(p, {}, b))(params, batch)
    assert out.shape == (1, 7, 3)
    # log-probs sum to 1 after exp
    np.testing.assert_allclose(np.exp(np.asarray(out[0, root])).sum(), 1.0,
                               rtol=1e-5)

    # a few SGD steps on the root loss must decrease it
    def loss_fn(p):
        o, _ = model.apply(p, {}, batch)
        return -o[0, root, 1]  # target class 1

    loss0 = float(loss_fn(params))
    g = jax.jit(jax.grad(loss_fn))
    for _ in range(20):
        grads = g(params)
        params = jax.tree.map(lambda w, d: w - 0.1 * d, params, grads)
    assert float(loss_fn(params)) < loss0


def test_tree_nn_accuracy_on_model_output():
    model = TreeLSTMSentiment(vocab_size=10, embed_dim=4, hidden_size=4,
                              class_num=2)
    params, _ = model.init(jax.random.key(1))
    children, leaf_ids, root = encode_tree((0, 1), n_nodes=3)
    batch = (jnp.asarray(np.array([[1, 2]], np.int32)),
             jnp.asarray(children[None]), jnp.asarray(leaf_ids[None]))
    out, _ = model.apply(params, {}, batch)
    res = TreeNNAccuracy()(np.asarray(out), np.array([0.0]))
    acc, n = res.result()
    assert n == 1 and acc in (0.0, 1.0)


def test_model_validator_cli(tmp_path):
    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import Sample
    from bigdl_tpu.tools.model_validator import validate
    from bigdl_tpu.utils.recordio import write_records

    model = nn.Sequential().add(nn.Linear(6, 3)).add(nn.LogSoftMax())
    model.build()
    mp = str(tmp_path / "m.bigdl")
    model.save(mp)
    rng = np.random.default_rng(0)
    recs = [Sample(rng.standard_normal(6).astype(np.float32),
                   np.float32(i % 3)) for i in range(32)]
    dp = str(tmp_path / "val.bdr")
    write_records(dp, recs)
    out = validate("bigdl", mp, dp, batch_size=16)
    assert out["Top1Accuracy"]["count"] == 32
    assert 0.0 <= out["Top1Accuracy"]["accuracy"] <= 1.0
    assert out["Top5Accuracy"]["accuracy"] >= out["Top1Accuracy"]["accuracy"]
