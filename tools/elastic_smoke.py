#!/usr/bin/env python
"""Elastic host-loss drill: prove detect->negotiate->re-form->resume
end-to-end with REAL processes (the runbook's cpu-smoke stage 2i and the
tier-1 acceptance test both drive this).

Orchestration (default mode):

1. Spawn 2 subprocess ranks — single-process jax runtimes coordinated
   ONLY through file_io (the simulated multi-host harness: logical
   topology from ``BIGDL_TPU_ELASTIC_WORLD``/``_ELASTIC_RANK``, shared
   checkpoint + heartbeat dirs).  Rank 1 carries chaos
   ``host.lost@1=exit@1:<iter>`` — at epoch 1 iteration <iter> it stops
   publishing and dies (exit 117, the expected outcome).
2. Rank 0 must DETECT the publication silence (PeerLostError), negotiate
   the newest common lineage entry, shrink to world=1 with the per-host
   batch rescaled 16 -> 32 (global batch preserved), resume, and finish
   training — its trace must carry the ``elastic.*`` events.
3. A third, CLEAN world-1 process resumes from the SAME negotiated
   lineage entry at batch 32 and trains to the same end trigger: its
   final loss must match rank 0's bit-for-bit (shuffle disabled and the
   snapshot's RNG state restored in both, so the post-resume iteration
   sequences are identical).

Prints ONE JSON line; exit 0 iff the whole drill closed:

    {"metric": "elastic_smoke", "recovered": true, "neval_resumed": 7,
     "world_after": 1, "batch_after": 32, "loss": ..., "clean_loss": ...,
     "loss_match": true, "elastic_events": [...], ...}
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import shutil
import subprocess
import sys
import tempfile

# runnable as `python tools/elastic_smoke.py` from the repo root (the
# runbook's invocation): sys.path[0] is tools/, so add the repo root
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

LOST_EXIT = 117  # chaos.ExitAt.EXIT_CODE


def _worker(args) -> int:
    """One logical rank (or the clean comparison run)."""
    if args.platform:
        import jax
        try:
            jax.config.update("jax_platforms", args.platform)
        except RuntimeError:
            pass
    import numpy as np

    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.dataset.transformer import Transformer
    from bigdl_tpu.optim import Adam, Optimizer, Trigger
    from bigdl_tpu.utils.engine import Engine

    rng = np.random.default_rng(0)
    samples = [Sample(rng.standard_normal(6).astype(np.float32),
                      np.float32(i % 2)) for i in range(128)]

    class Pace(Transformer):
        """Per-minibatch pacing so the run outlives the detection window
        (the drill's clock is the peer-lost threshold, not the model)."""

        def __init__(self, seconds):
            self.seconds = seconds

        def __call__(self, it):
            import time
            for x in it:
                if self.seconds:
                    time.sleep(self.seconds)
                yield x

    ds = (DataSet.rdd(samples)
          .transform(SampleToMiniBatch(args.batch, drop_last=True))
          .transform(Pace(args.pace)))
    # identical epoch order for the faulted and clean runs: post-resume
    # bit-identity is the acceptance bound, and dataset shuffle RNGs are
    # per-instance (not in the snapshot) — so the drill pins the order
    ds.shuffle = lambda: None

    opt = (Optimizer(nn.Sequential().add(nn.Linear(6, 2)), ds,
                     nn.CrossEntropyCriterion())
           .set_optim_method(Adam(1e-2))
           .set_end_when(Trigger.max_epoch(args.epochs)))
    out = {"rank": args.rank, "recovered": False}
    if args.resume_neval:
        # clean world-1 comparison: resume from the negotiated entry, no
        # new checkpoints (the lineage under test must stay untouched)
        opt.resume_from(os.path.join(args.ckpt_dir,
                                     f"model.{args.resume_neval}"),
                        os.path.join(args.ckpt_dir,
                                     f"optimMethod.{args.resume_neval}"))
    else:
        opt.set_checkpoint(args.ckpt_dir, Trigger.several_iteration(1))
    trained = opt.optimize()
    plan = getattr(opt, "_elastic_plan", None)
    if plan is not None:
        out.update(recovered=True, neval_resumed=plan.neval,
                   world_after=Engine.world(),
                   batch_after=opt._find_batchers(opt.dataset)[0].batch_size)
    out["loss"] = float(opt.optim_method.hyper["loss"])
    out["finite"] = bool(all(np.all(np.isfinite(np.asarray(leaf)))
                             for leaf in
                             __import__("jax").tree.leaves(trained.params)))
    print(json.dumps(out), flush=True)
    return 0


def _spawn(args, rank: int, extra_env: dict, worker_args: list):
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("BIGDL_TPU_ELASTIC", "BIGDL_TPU_CHAOS",
                                "BIGDL_TPU_TRACE", "BIGDL_TPU_SUPERVISE"))}
    env.update({"PYTHONPATH": _REPO_ROOT,
                "JAX_PLATFORMS": args.platform or "cpu",
                "BIGDL_TPU_PREFETCH_DEPTH": "0",  # sync data path: the
                # faulted and clean runs must be bit-comparable
                **extra_env})
    return subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--worker",
         "--rank", str(rank), *worker_args],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)


def _last_json(out: str):
    lines = [l for l in out.splitlines() if l.startswith("{")]
    return json.loads(lines[-1]) if lines else None


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default=None)
    ap.add_argument("--worker", action="store_true")
    ap.add_argument("--rank", type=int, default=0)
    ap.add_argument("--ckpt-dir", default=None)
    ap.add_argument("--epochs", type=int, default=10)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--pace", type=float, default=0.05)
    ap.add_argument("--resume-neval", type=int, default=0)
    ap.add_argument("--lost-iter", type=int, default=3,
                    help="epoch-1 iteration at which rank 1 dies "
                         "(chaos host.lost@1=exit@1:N)")
    ap.add_argument("--peer-lost", type=float, default=0.8)
    ap.add_argument("--timeout", type=int, default=240)
    args = ap.parse_args(argv)

    if args.worker:
        return _worker(args)

    base = args.ckpt_dir or tempfile.mkdtemp(prefix="elastic_smoke_")
    cleanup = args.ckpt_dir is None
    ckpt = os.path.join(base, "ckpt")
    trace = os.path.join(base, "trace")
    os.makedirs(ckpt, exist_ok=True)
    out = {"metric": "elastic_smoke", "recovered": False,
           "loss_match": False, "elastic_events": []}
    try:
        wargs = ["--ckpt-dir", ckpt, "--epochs", str(args.epochs),
                 "--batch", str(args.batch), "--pace", str(args.pace)]
        if args.platform:
            wargs += ["--platform", args.platform]
        common = {"BIGDL_TPU_ELASTIC_WORLD": "2",
                  "BIGDL_TPU_ELASTIC_PEER_LOST": str(args.peer_lost),
                  "BIGDL_TPU_SUPERVISE_PEER_STALE":
                      str(args.peer_lost / 2),
                  # a live phase deadline beside elasticity: recovery must
                  # run under the 'checkpoint' phase, not trip this
                  "BIGDL_TPU_SUPERVISE_STEP": "20"}
        p0 = _spawn(args, 0, {**common, "BIGDL_TPU_ELASTIC_RANK": "0",
                              "BIGDL_TPU_TRACE": trace}, wargs)
        p1 = _spawn(args, 1, {**common, "BIGDL_TPU_ELASTIC_RANK": "1",
                              "BIGDL_TPU_CHAOS":
                                  f"host.lost@1=exit@1:{args.lost_iter}"},
                    wargs)
        out1, err1 = p1.communicate(timeout=args.timeout)
        out0, err0 = p0.communicate(timeout=args.timeout)
        out["rank1_rc"] = p1.returncode
        out["rank0_rc"] = p0.returncode
        if p1.returncode != LOST_EXIT:
            out["error"] = (f"rank 1 exited {p1.returncode}, expected the "
                            f"host-lost drill exit {LOST_EXIT}: "
                            f"{err1[-1500:]}")
            return 1
        if p0.returncode != 0:
            out["error"] = f"rank 0 failed: {err0[-2000:]}"
            return 1
        r0 = _last_json(out0)
        if not r0 or not r0.get("recovered") or not r0.get("finite"):
            out["error"] = f"rank 0 never ran elastic recovery: {r0}"
            return 1
        out.update(recovered=True, neval_resumed=r0["neval_resumed"],
                   world_after=r0["world_after"],
                   batch_after=r0["batch_after"], loss=r0["loss"])
        if r0["world_after"] != 1 or \
                r0["batch_after"] != 2 * args.batch:
            out["error"] = ("shrink did not preserve the global batch: "
                            f"{r0}")
            return 1
        # the survivor's trace must show the recovery next to the fault
        events = set()
        for tf in glob.glob(os.path.join(trace, "trace.*.json")):
            try:
                for ev in json.load(open(tf)).get("traceEvents", []):
                    if str(ev.get("name", "")).startswith("elastic."):
                        events.add(ev["name"])
            except ValueError:
                pass
        out["elastic_events"] = sorted(events)
        need = {"elastic.detect", "elastic.negotiate", "elastic.reform",
                "elastic.resume"}
        if not need <= events:
            out["error"] = f"missing elastic trace events: {need - events}"
            return 1
        # clean world-1 run from the SAME lineage entry at the rescaled
        # batch: final loss must match the recovered run bit-for-bit
        cargs = ["--ckpt-dir", ckpt, "--epochs", str(args.epochs),
                 "--batch", str(2 * args.batch), "--pace", "0",
                 "--resume-neval", str(r0["neval_resumed"])]
        if args.platform:
            cargs += ["--platform", args.platform]
        pc = _spawn(args, 0, {}, cargs)
        outc, errc = pc.communicate(timeout=args.timeout)
        if pc.returncode != 0:
            out["error"] = f"clean run failed: {errc[-2000:]}"
            return 1
        rc = _last_json(outc)
        out["clean_loss"] = rc["loss"]
        out["loss_match"] = bool(abs(rc["loss"] - r0["loss"]) < 1e-9)
        if not out["loss_match"]:
            out["error"] = (f"recovered loss {r0['loss']!r} != clean "
                            f"world-1 loss {rc['loss']!r}")
            return 1
        return 0
    except subprocess.TimeoutExpired as e:
        out["error"] = f"drill timed out: {e}"
        for p in ("p0", "p1", "pc"):
            proc = locals().get(p)
            if proc is not None and proc.poll() is None:
                proc.kill()
        return 1
    except Exception as e:  # noqa: BLE001 — one JSON line, always
        out["error"] = f"{type(e).__name__}: {e}"
        return 1
    finally:
        print(json.dumps(out))
        sys.stdout.flush()
        if cleanup:
            shutil.rmtree(base, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
