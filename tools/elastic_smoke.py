#!/usr/bin/env python
"""Elastic host-loss drill: prove detect->negotiate->re-form->resume
end-to-end with REAL processes (the runbook's cpu-smoke stage 2i and the
tier-1 acceptance test both drive this).

Orchestration (default mode):

1. Spawn 2 subprocess ranks — single-process jax runtimes coordinated
   ONLY through file_io (the simulated multi-host harness: logical
   topology from ``BIGDL_TPU_ELASTIC_WORLD``/``_ELASTIC_RANK``, shared
   checkpoint + heartbeat dirs).  Rank 1 carries chaos
   ``host.lost@1=exit@1:<iter>`` — at epoch 1 iteration <iter> it stops
   publishing and dies (exit 117, the expected outcome).
2. Rank 0 must DETECT the publication silence (PeerLostError), negotiate
   the newest common lineage entry, shrink to world=1 with the per-host
   batch rescaled 16 -> 32 (global batch preserved), resume, and finish
   training — its trace must carry the ``elastic.*`` events.
3. A third, CLEAN world-1 process resumes from the SAME negotiated
   lineage entry at batch 32 and trains to the same end trigger: its
   final loss must match rank 0's bit-for-bit (shuffle disabled and the
   snapshot's RNG state restored in both, so the post-resume iteration
   sequences are identical).

``--grow`` runs the full preemption-AND-reclamation drill instead
(runbook cpu-smoke stage 2p; parallel/elastic step 4):

1. Same kill: rank 1 dies at epoch 1 (exit 117), rank 0 shrinks to
   world=1 / batch 32 — but rank 0 also PUBLISHES a release entry per
   checkpoint (``set_checkpoint(..., publish=True)``), so a deployment
   feed crosses both resizes.
2. Rank 1 is re-spawned as a JOINER (``BIGDL_TPU_ELASTIC_JOIN=1``,
   chaos ``host.return@1=join@2:2``): it gates on the survivor's
   checkpoint stream reaching epoch 2, announces itself
   (``elastic/join.1`` + generation-bumped heartbeat), waits for the
   admission offer rank 0 writes at its next checkpoint boundary, and
   both negotiate the join snapshot — rank 0 widens back to world=2 and
   rescales batch 32 -> 16, the joiner adopts the agreed lineage entry.
3. Asserted: world 2 -> 1 -> 2 and per-host batch 16 -> 32 -> 16 (from
   ``Optimizer._elastic_history``), ``elastic.join`` / ``.agree`` /
   ``.reform`` / ``.resume`` in BOTH ranks' traces, release ids
   gap-free across both resizes, a stub-served DeployController
   promotes a release published AFTER the grow, and clean world-2
   runs resumed from the join snapshot bit-match both ranks' final
   losses.

Prints ONE JSON line; exit 0 iff the whole drill closed:

    {"metric": "elastic_smoke", "recovered": true, "neval_resumed": 7,
     "world_after": 1, "batch_after": 32, "loss": ..., "clean_loss": ...,
     "loss_match": true, "elastic_events": [...], ...}
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import shutil
import subprocess
import sys
import tempfile

# runnable as `python tools/elastic_smoke.py` from the repo root (the
# runbook's invocation): sys.path[0] is tools/, so add the repo root
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

LOST_EXIT = 117  # chaos.ExitAt.EXIT_CODE


def _worker(args) -> int:
    """One logical rank (or the clean comparison run)."""
    if args.platform:
        import jax
        try:
            jax.config.update("jax_platforms", args.platform)
        except RuntimeError:
            pass
    import numpy as np

    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.dataset.transformer import Transformer
    from bigdl_tpu.optim import Adam, Optimizer, Trigger
    from bigdl_tpu.utils.engine import Engine

    rng = np.random.default_rng(0)
    samples = [Sample(rng.standard_normal(6).astype(np.float32),
                      np.float32(i % 2)) for i in range(128)]

    class Pace(Transformer):
        """Per-minibatch pacing so the run outlives the detection window
        (the drill's clock is the peer-lost threshold, not the model)."""

        def __init__(self, seconds):
            self.seconds = seconds

        def __call__(self, it):
            import time
            for x in it:
                if self.seconds:
                    time.sleep(self.seconds)
                yield x

    ds = (DataSet.rdd(samples)
          .transform(SampleToMiniBatch(args.batch, drop_last=True))
          .transform(Pace(args.pace)))
    # identical epoch order for the faulted and clean runs: post-resume
    # bit-identity is the acceptance bound, and dataset shuffle RNGs are
    # per-instance (not in the snapshot) — so the drill pins the order
    ds.shuffle = lambda: None

    opt = (Optimizer(nn.Sequential().add(nn.Linear(6, 2)), ds,
                     nn.CrossEntropyCriterion())
           .set_optim_method(Adam(1e-2))
           .set_end_when(Trigger.max_epoch(args.epochs)))
    out = {"rank": args.rank, "recovered": False}
    if args.resume_neval:
        # clean world-1 comparison: resume from the negotiated entry, no
        # new checkpoints (the lineage under test must stay untouched)
        opt.resume_from(os.path.join(args.ckpt_dir,
                                     f"model.{args.resume_neval}"),
                        os.path.join(args.ckpt_dir,
                                     f"optimMethod.{args.resume_neval}"))
    else:
        opt.set_checkpoint(args.ckpt_dir, Trigger.several_iteration(1),
                           publish=True if args.publish else None)
    trained = opt.optimize()
    plan = getattr(opt, "_elastic_plan", None)
    if plan is not None:
        out.update(recovered=True, neval_resumed=plan.neval,
                   world_after=Engine.world(),
                   batch_after=opt._find_batchers(opt.dataset)[0].batch_size)
    out["history"] = getattr(opt, "_elastic_history", [])
    out["loss"] = float(opt.optim_method.hyper["loss"])
    out["finite"] = bool(all(np.all(np.isfinite(np.asarray(leaf)))
                             for leaf in
                             __import__("jax").tree.leaves(trained.params)))
    print(json.dumps(out), flush=True)
    return 0


def _spawn(args, rank: int, extra_env: dict, worker_args: list):
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("BIGDL_TPU_ELASTIC", "BIGDL_TPU_CHAOS",
                                "BIGDL_TPU_TRACE", "BIGDL_TPU_SUPERVISE"))}
    env.update({"PYTHONPATH": _REPO_ROOT,
                "JAX_PLATFORMS": args.platform or "cpu",
                "BIGDL_TPU_PREFETCH_DEPTH": "0",  # sync data path: the
                # faulted and clean runs must be bit-comparable
                **extra_env})
    return subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--worker",
         "--rank", str(rank), *worker_args],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)


def _last_json(out: str):
    lines = [l for l in out.splitlines() if l.startswith("{")]
    return json.loads(lines[-1]) if lines else None


def _trace_events(trace_dir: str) -> dict:
    """Per trace file: sorted list of elastic.* event names."""
    by_file = {}
    for tf in glob.glob(os.path.join(trace_dir, "trace.*.json")):
        names = set()
        try:
            for ev in json.load(open(tf)).get("traceEvents", []):
                if str(ev.get("name", "")).startswith("elastic."):
                    names.add(ev["name"])
        except ValueError:
            pass
        by_file[os.path.basename(tf)] = sorted(names)
    return by_file


def _grow_drill(args, ckpt: str, trace: str) -> int:
    """Kill-then-return: shrink 2->1, joiner re-admitted, grow 1->2,
    release feed gap-free across both resizes, clean world-2 bit-match."""
    import re

    out = {"metric": "elastic_grow_smoke", "recovered": False,
           "joined": False, "loss_match": False, "elastic_events": {}}
    procs = []
    try:
        wargs = ["--ckpt-dir", ckpt, "--epochs", str(args.epochs),
                 "--batch", str(args.batch), "--pace", str(args.pace)]
        if args.platform:
            wargs += ["--platform", args.platform]
        common = {"BIGDL_TPU_ELASTIC_WORLD": "2",
                  "BIGDL_TPU_ELASTIC_PEER_LOST": str(args.peer_lost),
                  "BIGDL_TPU_SUPERVISE_PEER_STALE":
                      str(args.peer_lost / 2),
                  "BIGDL_TPU_SUPERVISE_STEP": "20"}
        # rank 0: the survivor — traces AND publishes (the deployment
        # feed whose continuity across both resizes is under test)
        p0 = _spawn(args, 0, {**common, "BIGDL_TPU_ELASTIC_RANK": "0",
                              "BIGDL_TPU_TRACE": trace},
                    wargs + ["--publish"])
        procs.append(p0)
        p1 = _spawn(args, 1, {**common, "BIGDL_TPU_ELASTIC_RANK": "1",
                              "BIGDL_TPU_CHAOS":
                                  f"host.lost@1=exit@1:{args.lost_iter}"},
                    wargs)
        procs.append(p1)
        def _keep(tag, stdout, stderr):
            # worker logs beside the lineage: the runbook captures only
            # the orchestrator's one JSON line, so a failing stage needs
            # these for the post-mortem
            try:
                with open(os.path.join(ckpt, f"{tag}.log"), "w") as f:
                    f.write(stdout + "\n--- stderr ---\n" + stderr)
            except OSError:
                pass

        out1, err1 = p1.communicate(timeout=args.timeout)
        _keep("rank1", out1, err1)
        out["rank1_rc"] = p1.returncode
        if p1.returncode != LOST_EXIT:
            out["error"] = (f"rank 1 exited {p1.returncode}, expected the "
                            f"host-lost drill exit {LOST_EXIT}: "
                            f"{err1[-1500:]}")
            return 1
        # rank 1 returns: same logical rank, join-armed, gated on the
        # survivor's checkpoint stream reaching --return-at (at-or-after)
        pj = _spawn(args, 1, {**common, "BIGDL_TPU_ELASTIC_RANK": "1",
                              "BIGDL_TPU_ELASTIC_JOIN": "1",
                              "BIGDL_TPU_ELASTIC_JOIN_POLL": "0.05",
                              "BIGDL_TPU_ELASTIC_JOIN_TIMEOUT": "60",
                              "BIGDL_TPU_TRACE": trace,
                              "BIGDL_TPU_CHAOS":
                                  f"host.return@1=join@{args.return_at}"},
                    wargs)
        procs.append(pj)
        outj, errj = pj.communicate(timeout=args.timeout)
        _keep("joiner", outj, errj)
        out0, err0 = p0.communicate(timeout=args.timeout)
        _keep("rank0", out0, err0)
        out["rank0_rc"] = p0.returncode
        out["joiner_rc"] = pj.returncode
        if pj.returncode != 0:
            out["error"] = f"joiner failed: {errj[-2000:]}"
            return 1
        if p0.returncode != 0:
            out["error"] = f"rank 0 failed: {err0[-2000:]}"
            return 1
        r0, rj = _last_json(out0), _last_json(outj)
        if not r0 or not r0.get("recovered") or not r0.get("finite"):
            out["error"] = f"rank 0 never ran elastic recovery: {r0}"
            return 1
        if not rj or not rj.get("recovered") or not rj.get("finite"):
            out["error"] = f"joiner never joined: {rj}"
            return 1
        out["recovered"] = True
        # world 2 -> 1 -> 2 and per-host batch B -> 2B -> B, from the
        # survivor's audit trail; the joiner records exactly one join
        kinds0 = [h["kind"] for h in r0.get("history", [])]
        out["history_rank0"] = r0.get("history", [])
        out["history_joiner"] = rj.get("history", [])
        if kinds0 != ["shrink", "grow"]:
            out["error"] = f"rank 0 episode kinds {kinds0} != " \
                           "['shrink', 'grow']"
            return 1
        shrink, grow = r0["history"]
        if [shrink["world"], grow["world"]] != [1, 2] or \
                [shrink["batch"], grow["batch"]] != \
                [2 * args.batch, args.batch]:
            out["error"] = ("resize trajectory wrong (want world 2->1->2, "
                            f"batch {args.batch}->{2 * args.batch}->"
                            f"{args.batch}): {r0['history']}")
            return 1
        if [h["kind"] for h in rj.get("history", [])] != ["join"] or \
                rj["history"][0]["world"] != 2 or \
                rj["history"][0]["batch"] != args.batch:
            out["error"] = f"joiner episode wrong: {rj.get('history')}"
            return 1
        out["joined"] = True
        grow_neval = int(grow["neval"])
        out["grow_neval"] = grow_neval
        if int(rj["history"][0]["neval"]) != grow_neval:
            out["error"] = ("survivor and joiner adopted different "
                            f"snapshots: {grow_neval} != "
                            f"{rj['history'][0]['neval']}")
            return 1
        # BOTH ranks' traces must carry the grow episode
        out["elastic_events"] = _trace_events(trace)
        need = {"elastic.join", "elastic.agree", "elastic.reform",
                "elastic.resume"}
        for rk in (0, 1):
            have = set(out["elastic_events"].get(f"trace.{rk}.json", []))
            if not need <= have:
                out["error"] = (f"rank {rk} trace missing elastic grow "
                                f"events: {sorted(need - have)}")
                return 1
        # release feed: ids must be gap-free across BOTH resizes, and a
        # stub-served DeployController must promote a release published
        # AFTER the grow (the train->serve loop survived the resize)
        from bigdl_tpu.serve.continuous import (DeployController,
                                                RELEASE_PATTERN)
        ids = sorted(int(m.group(1)) for n in os.listdir(ckpt)
                     for m in [re.fullmatch(RELEASE_PATTERN, n)] if m)
        out["releases"] = len(ids)
        out["release_gap_free"] = bool(
            ids and ids == list(range(ids[0], ids[0] + len(ids))))
        if not out["release_gap_free"]:
            out["error"] = f"release feed has gaps: {ids}"
            return 1

        class _Server:
            def __init__(self):
                self.versions = 0

            def swap(self, source, canary_fraction=None):
                self.versions += 1
                return self.versions

            def stats(self):
                return {}

        # canary_fraction=0 -> full swaps, each deploy promotes at once
        ctrl = DeployController(_Server(), ckpt, canary_fraction=0.0,
                                since=0)
        for rid in ids:
            ctrl._handle(rid, os.path.join(ckpt, f"release.{rid}"))
        out["promoted"] = ctrl.counts["promoted"]
        out["rejected"] = ctrl.counts["rejected"]
        promoted_after = [t for t in ctrl.timeline
                          if t.get("action") == "promoted" and
                          (t.get("neval") or -1) > grow_neval]
        out["promoted_after_grow"] = len(promoted_after)
        if ctrl.counts["rejected"] or not promoted_after:
            out["error"] = ("deployment did not survive the resize: "
                            f"rejected={ctrl.counts['rejected']} "
                            f"promoted_after_grow={len(promoted_after)}")
            return 1
        # clean world-2 runs resumed from the join snapshot: each rank's
        # final loss must match the drilled run bit-for-bit
        cargs = ["--ckpt-dir", ckpt, "--epochs", str(args.epochs),
                 "--batch", str(args.batch), "--pace", "0",
                 "--resume-neval", str(grow_neval)]
        if args.platform:
            cargs += ["--platform", args.platform]
        cleans = []
        for rk in (0, 1):
            pc = _spawn(args, rk,
                        {"BIGDL_TPU_ELASTIC_WORLD": "2",
                         "BIGDL_TPU_ELASTIC_RANK": str(rk)}, cargs)
            procs.append(pc)
            cleans.append(pc)
        losses = {0: r0["loss"], 1: rj["loss"]}
        for rk, pc in zip((0, 1), cleans):
            outc, errc = pc.communicate(timeout=args.timeout)
            if pc.returncode != 0:
                out["error"] = f"clean rank {rk} failed: {errc[-2000:]}"
                return 1
            rc_ = _last_json(outc)
            out[f"clean_loss_rank{rk}"] = rc_["loss"]
            if abs(rc_["loss"] - losses[rk]) >= 1e-9:
                out["error"] = (f"rank {rk}: drilled loss "
                                f"{losses[rk]!r} != clean world-2 loss "
                                f"{rc_['loss']!r}")
                return 1
        out["loss"] = r0["loss"]
        out["join_loss"] = rj["loss"]
        out["loss_match"] = True
        return 0
    except subprocess.TimeoutExpired as e:
        out["error"] = f"grow drill timed out: {e}"
        return 1
    except Exception as e:  # noqa: BLE001 — one JSON line, always
        out["error"] = f"{type(e).__name__}: {e}"
        return 1
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        print(json.dumps(out))
        sys.stdout.flush()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default=None)
    ap.add_argument("--worker", action="store_true")
    ap.add_argument("--rank", type=int, default=0)
    ap.add_argument("--ckpt-dir", default=None)
    ap.add_argument("--epochs", type=int, default=None)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--pace", type=float, default=None)
    ap.add_argument("--resume-neval", type=int, default=0)
    ap.add_argument("--lost-iter", type=int, default=3,
                    help="epoch-1 iteration at which rank 1 dies "
                         "(chaos host.lost@1=exit@1:N)")
    ap.add_argument("--grow", action="store_true",
                    help="kill-then-RETURN drill: rank 1 rejoins at "
                         "epoch 2 and the cluster widens back to "
                         "world=2 (runbook stage 2p)")
    ap.add_argument("--return-at", default="2:2",
                    help="epoch:iteration join gate for the re-spawned "
                         "rank 1 (chaos host.return@1=join@E:I, fires "
                         "at-or-after)")
    ap.add_argument("--publish", action="store_true",
                    help="worker flag: publish a release entry per "
                         "checkpoint (the --grow deployment feed)")
    ap.add_argument("--peer-lost", type=float, default=0.8)
    ap.add_argument("--timeout", type=int, default=240)
    args = ap.parse_args(argv)
    if args.pace is None:
        # the grow drill paces slower: the survivor must still be
        # training when the re-spawned joiner (a fresh jax runtime)
        # finishes importing, gates on epoch 2, and negotiates
        args.pace = 0.35 if args.grow else 0.05
    if args.epochs is None:
        args.epochs = 12 if args.grow else 10

    if args.worker:
        return _worker(args)

    base = args.ckpt_dir or tempfile.mkdtemp(prefix="elastic_smoke_")
    cleanup = args.ckpt_dir is None
    ckpt = os.path.join(base, "ckpt")
    trace = os.path.join(base, "trace")
    os.makedirs(ckpt, exist_ok=True)
    if args.grow:
        try:
            return _grow_drill(args, ckpt, trace)
        finally:
            if cleanup:
                shutil.rmtree(base, ignore_errors=True)
    out = {"metric": "elastic_smoke", "recovered": False,
           "loss_match": False, "elastic_events": []}
    try:
        wargs = ["--ckpt-dir", ckpt, "--epochs", str(args.epochs),
                 "--batch", str(args.batch), "--pace", str(args.pace)]
        if args.platform:
            wargs += ["--platform", args.platform]
        common = {"BIGDL_TPU_ELASTIC_WORLD": "2",
                  "BIGDL_TPU_ELASTIC_PEER_LOST": str(args.peer_lost),
                  "BIGDL_TPU_SUPERVISE_PEER_STALE":
                      str(args.peer_lost / 2),
                  # a live phase deadline beside elasticity: recovery must
                  # run under the 'checkpoint' phase, not trip this
                  "BIGDL_TPU_SUPERVISE_STEP": "20"}
        p0 = _spawn(args, 0, {**common, "BIGDL_TPU_ELASTIC_RANK": "0",
                              "BIGDL_TPU_TRACE": trace}, wargs)
        p1 = _spawn(args, 1, {**common, "BIGDL_TPU_ELASTIC_RANK": "1",
                              "BIGDL_TPU_CHAOS":
                                  f"host.lost@1=exit@1:{args.lost_iter}"},
                    wargs)
        out1, err1 = p1.communicate(timeout=args.timeout)
        out0, err0 = p0.communicate(timeout=args.timeout)
        out["rank1_rc"] = p1.returncode
        out["rank0_rc"] = p0.returncode
        if p1.returncode != LOST_EXIT:
            out["error"] = (f"rank 1 exited {p1.returncode}, expected the "
                            f"host-lost drill exit {LOST_EXIT}: "
                            f"{err1[-1500:]}")
            return 1
        if p0.returncode != 0:
            out["error"] = f"rank 0 failed: {err0[-2000:]}"
            return 1
        r0 = _last_json(out0)
        if not r0 or not r0.get("recovered") or not r0.get("finite"):
            out["error"] = f"rank 0 never ran elastic recovery: {r0}"
            return 1
        out.update(recovered=True, neval_resumed=r0["neval_resumed"],
                   world_after=r0["world_after"],
                   batch_after=r0["batch_after"], loss=r0["loss"])
        if r0["world_after"] != 1 or \
                r0["batch_after"] != 2 * args.batch:
            out["error"] = ("shrink did not preserve the global batch: "
                            f"{r0}")
            return 1
        # the survivor's trace must show the recovery next to the fault
        events = set()
        for tf in glob.glob(os.path.join(trace, "trace.*.json")):
            try:
                for ev in json.load(open(tf)).get("traceEvents", []):
                    if str(ev.get("name", "")).startswith("elastic."):
                        events.add(ev["name"])
            except ValueError:
                pass
        out["elastic_events"] = sorted(events)
        need = {"elastic.detect", "elastic.negotiate", "elastic.reform",
                "elastic.resume"}
        if not need <= events:
            out["error"] = f"missing elastic trace events: {need - events}"
            return 1
        # clean world-1 run from the SAME lineage entry at the rescaled
        # batch: final loss must match the recovered run bit-for-bit
        cargs = ["--ckpt-dir", ckpt, "--epochs", str(args.epochs),
                 "--batch", str(2 * args.batch), "--pace", "0",
                 "--resume-neval", str(r0["neval_resumed"])]
        if args.platform:
            cargs += ["--platform", args.platform]
        pc = _spawn(args, 0, {}, cargs)
        outc, errc = pc.communicate(timeout=args.timeout)
        if pc.returncode != 0:
            out["error"] = f"clean run failed: {errc[-2000:]}"
            return 1
        rc = _last_json(outc)
        out["clean_loss"] = rc["loss"]
        out["loss_match"] = bool(abs(rc["loss"] - r0["loss"]) < 1e-9)
        if not out["loss_match"]:
            out["error"] = (f"recovered loss {r0['loss']!r} != clean "
                            f"world-1 loss {rc['loss']!r}")
            return 1
        return 0
    except subprocess.TimeoutExpired as e:
        out["error"] = f"drill timed out: {e}"
        for p in ("p0", "p1", "pc"):
            proc = locals().get(p)
            if proc is not None and proc.poll() is None:
                proc.kill()
        return 1
    except Exception as e:  # noqa: BLE001 — one JSON line, always
        out["error"] = f"{type(e).__name__}: {e}"
        return 1
    finally:
        print(json.dumps(out))
        sys.stdout.flush()
        if cleanup:
            shutil.rmtree(base, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
