#!/usr/bin/env python
"""Two-workload train->publish->canary->serve drill: the ISSUE-20
zero-workload-specific-pipeline claim, end-to-end (runbook cpu-smoke
stage 2s).

ONE invocation runs BOTH production workloads through the IDENTICAL
generic chain — same Optimizer checkpoint/publish path, same
DeployController, same InferenceServer — with zero recommendation- or
text-specific branches anywhere in that chain:

1. Recommendation (wide-and-deep): two subprocess trainer ranks
   (``BIGDL_TPU_ELASTIC_WORLD=2``) stream synthetic Criteo shards
   through ``TabularToSample`` and train ``models/widedeep.WideDeep``.
   Rank 0 carries ``data.record=corrupt`` chaos on its reader (bounded
   quarantine under ``BIGDL_TPU_DATA_SKIP_BUDGET``); rank 1 carries
   ``host.lost@1=exit@1:3`` and dies mid-train — rank 0 must recover
   elastically and keep publishing.  The parent serves the lineage live
   (canary per release) under closed-loop traffic.

2. Text (token-id classifier): one trainer rank feeds the
   ``dataset/text.py`` chain (SentenceTokenizer -> Dictionary ->
   encoded ids) into a ``TextClassifier(vocab_size=...)`` and publishes
   the same way; the Dictionary ships beside the checkpoints.  The
   parent serves VARIABLE-LENGTH token requests over a
   (batch, seq)-bucket ladder, padded per request — no text-specific
   serving code, just ``seq_buckets``.

Asserted in one run, per workload: every published release reaches a
terminal outcome and the LAST one is promoted; every embedding table on
the SERVED version is resident at exactly 1/N per device under the
(1,2,2) fsdp×tp layout; served answers bit-match a bulk ``Predictor``
oracle loaded from the promoted snapshot (text: at the same padded
sequence bucket); ZERO requests dropped or errored.  Across workloads:
the serve-side span/counter track sets of the two traces are IDENTICAL
(same generic code paths), and a literal grep proves the optimizer /
publisher / DeployController / InferenceServer sources contain no
workload-specific branch.

Prints ONE JSON line; exit 0 iff every leg closed::

    {"metric": "workload_smoke", "ok": true,
     "recsys": {"published": ..., "promoted": ..., "table_fractions":
                [0.25, 0.25], "bit_match": true, ...},
     "text": {...}, "spans_equal": true, "generic_chain_clean": true}
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import shutil
import subprocess
import sys
import tempfile
import threading
import time

# runnable as `python tools/workload_smoke.py` from the repo root
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

LOST_EXIT = 117      # chaos.ExitAt.EXIT_CODE
SERVE_RANK = 7       # the parent's trace rank in both workload traces
SEQ_LADDER = (192, 256, 384)
TEXT_SEQ = 192       # training length (textclassifier conv needs >= 149)

# the generic chain: files that must contain ZERO workload-specific
# branches (checked by literal grep below)
GENERIC_FILES = ("bigdl_tpu/optim/optimizer.py",
                 "bigdl_tpu/serve/continuous.py",
                 "bigdl_tpu/serve/server.py")
WORKLOAD_WORDS = ("widedeep", "wide_deep", "recsys", "criteo",
                  "textclassifier", "text_classifier")


def _spec():
    """The drill's tabular schema — small tables, everything else the
    production default shape."""
    from bigdl_tpu.dataset import FeatureSpec
    return FeatureSpec(n_cat=4, n_dense=2, multihot_slots=2,
                       deep_buckets=512, wide_buckets=256)


def _widedeep(spec):
    from bigdl_tpu.models import WideDeep
    return WideDeep.from_spec(spec, embed_dim=8, hidden=(16,))


def _text_corpus(n=96, seed=0):
    """Deterministic 3-class corpus: class k docs carry the marker word
    ``markk`` often — learnable through the Dictionary chain."""
    import numpy as np
    rng = np.random.default_rng(seed)
    filler = [f"w{i}" for i in range(60)]
    docs, labels = [], []
    for i in range(n):
        k = i % 3
        body = [filler[int(j)] for j in rng.integers(0, 60, 60)]
        body += [f"mark{k}"] * 12
        order = rng.permutation(len(body))
        docs.append(" ".join(body[int(j)] for j in order))
        labels.append(k)
    return docs, labels


class _Pace:
    """Per-minibatch pacing so the elastic run outlives the peer-lost
    detection window (the drill's clock, not the model's)."""

    def __init__(self, seconds):
        self.seconds = seconds

    def __call__(self, it):
        for x in it:
            if self.seconds:
                time.sleep(self.seconds)
            yield x


# ---------------------------------------------------------------------------
# trainer workers (subprocesses)
# ---------------------------------------------------------------------------

def _recsys_trainer(args) -> int:
    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import (DataSet, SampleToMiniBatch,
                                   TabularToSample)
    from bigdl_tpu.optim import Adam, Optimizer, Trigger
    from bigdl_tpu.utils import recordio

    spec = _spec()
    paths = sorted(glob.glob(os.path.join(args.data_dir, "criteo.bd-*")))
    stream = DataSet.record_stream(paths)
    ds = (stream
          .transform(TabularToSample(spec)
                     >> SampleToMiniBatch(args.batch, drop_last=True))
          .transform(_Pace(args.pace)))

    opt = (Optimizer(_widedeep(spec), ds, nn.ClassNLLCriterion())
           .set_optim_method(Adam(1e-2))
           .set_end_when(Trigger.max_epoch(args.epochs)))
    opt.set_checkpoint(args.ckpt_dir, Trigger.several_iteration(1),
                       publish=True, publish_every=args.publish_every)
    opt.optimize()
    plan = getattr(opt, "_elastic_plan", None)
    out = {"rank": args.rank, "workload": "recsys",
           "recovered": plan is not None,
           "neval_resumed": plan.neval if plan is not None else None,
           "published": (opt._publisher.published
                         if opt._publisher is not None else 0),
           "quarantined": recordio.quarantine_stats()["records"],
           "loss": float(opt.optim_method.hyper.get("loss", 0.0))}
    print(json.dumps(out), flush=True)
    return 0


def _text_trainer(args) -> int:
    import numpy as np

    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import (DataSet, Dictionary, Sample,
                                   SampleToMiniBatch, SentenceTokenizer)
    from bigdl_tpu.models import TextClassifier
    from bigdl_tpu.optim import Adam, Optimizer, Trigger

    docs, labels = _text_corpus()
    tokenized = list(SentenceTokenizer()(iter(docs)))
    d = Dictionary(tokenized)
    d.save(args.ckpt_dir)  # the vocabulary ships beside the lineage
    samples = []
    for toks, k in zip(tokenized, labels):
        ids = d.encode(toks)[:TEXT_SEQ]
        ids = np.pad(ids, (0, TEXT_SEQ - len(ids)))
        samples.append(Sample(ids.astype(np.int32), np.int32(k)))
    ds = (DataSet.array(samples)
          .transform(SampleToMiniBatch(args.batch, drop_last=True))
          .transform(_Pace(args.pace)))

    model = TextClassifier(3, embed_dim=16, seq_len=TEXT_SEQ,
                           vocab_size=d.vocab_size())
    opt = (Optimizer(model, ds, nn.ClassNLLCriterion())
           .set_optim_method(Adam(1e-3))
           .set_end_when(Trigger.max_epoch(args.epochs)))
    opt.set_checkpoint(args.ckpt_dir, Trigger.several_iteration(1),
                       publish=True, publish_every=args.publish_every)
    opt.optimize()
    out = {"rank": args.rank, "workload": "text",
           "vocab": d.vocab_size(),
           "published": (opt._publisher.published
                         if opt._publisher is not None else 0),
           "loss": float(opt.optim_method.hyper.get("loss", 0.0))}
    print(json.dumps(out), flush=True)
    return 0


def _spawn(args, workload: str, rank: int, ckpt_dir: str, epochs: int,
           publish_every: int, extra_env: dict):
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("BIGDL_TPU_ELASTIC", "BIGDL_TPU_CHAOS",
                                "BIGDL_TPU_TRACE", "BIGDL_TPU_SUPERVISE",
                                "BIGDL_TPU_DEPLOY", "BIGDL_TPU_DATA"))}
    env.update({"PYTHONPATH": _REPO_ROOT,
                "JAX_PLATFORMS": args.platform or "cpu",
                "BIGDL_TPU_PREFETCH_DEPTH": "0",
                **extra_env})
    wargs = ["--worker", workload, "--rank", str(rank),
             "--ckpt-dir", ckpt_dir, "--data-dir", args.data_dir or "",
             "--epochs", str(epochs), "--batch", str(args.batch),
             "--pace", str(args.pace),
             "--publish-every", str(publish_every)]
    if args.platform:
        wargs += ["--platform", args.platform]
    return subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), *wargs],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)


def _last_json(out: str):
    lines = [ln for ln in out.splitlines() if ln.startswith("{")]
    return json.loads(lines[-1]) if lines else None


# ---------------------------------------------------------------------------
# the serving side (this process)
# ---------------------------------------------------------------------------

class _Traffic:
    """Closed-loop traffic: one request at a time, every answer counted.
    Zero-drop is the contract — any error or unanswered submit fails
    the smoke."""

    def __init__(self, server, queries):
        self.server = server
        self.queries = queries
        self.submitted = 0
        self.served = 0
        self.errors = []
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="workload-smoke-traffic")

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=120.0)

    def _run(self):
        i = 0
        while not self._stop.is_set():
            x = self.queries[i % len(self.queries)]
            i += 1
            try:
                self.submitted += 1
                self.server.submit(x).result(120)
                self.served += 1
            except Exception as e:  # noqa: BLE001 — recorded, fails smoke
                self.errors.append(f"{type(e).__name__}: {e}")
                if len(self.errors) > 8:
                    return
            time.sleep(0.002)


def _drain_controller(controller, published: int, timeout_s=150.0):
    """Wait until every published release reached a terminal outcome."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        st = controller.stats()
        terminal = st["promoted"] + st["rolled_back"] + st["rejected"]
        if terminal >= published and st["seen"] >= published:
            return st
        time.sleep(0.1)
    return controller.stats()


def _table_fractions(module, engine) -> list:
    """device_fraction per embedding table on the SERVED (placed)
    params — the 1/N-sharded-serving assertion."""
    from bigdl_tpu.utils import memstats
    placed = getattr(engine, "_placed", None)
    if placed is None:
        return []
    tables = memstats.embedding_table_bytes(module, placed[1]) or []
    return [t["device_fraction"] for t in tables]


def _serve_tracks(trace_dir: str):
    """(span names, counter tracks) emitted by the serving rank."""
    from bigdl_tpu.utils import telemetry
    merged = telemetry.merge_traces(trace_dir)
    spans, counters = set(), set()
    for e in merged["traceEvents"]:
        if int(e.get("pid", -1)) != SERVE_RANK:
            continue
        if e.get("ph") == "X":
            spans.add(e["name"])
        elif e.get("ph") == "C":
            counters.add(e["name"])
    return spans, counters


def _check_last_promoted(timeline) -> tuple:
    """-> (last_release, neval) or raises AssertionError."""
    last = max(e["release"] for e in timeline)
    terminal = [e for e in timeline if e["release"] == last and
                e["action"] in ("promoted", "rolled_back", "rejected")]
    if not terminal or terminal[-1]["action"] != "promoted":
        raise AssertionError(f"last release {last} did not promote: "
                             f"{terminal}")
    return last, terminal[-1]["neval"]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default=None)
    ap.add_argument("--worker", default=None,
                    choices=(None, "recsys", "text"))
    ap.add_argument("--rank", type=int, default=0)
    ap.add_argument("--ckpt-dir", default=None)
    ap.add_argument("--data-dir", default=None)
    ap.add_argument("--epochs", type=int, default=10)
    ap.add_argument("--text-epochs", type=int, default=4)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--pace", type=float, default=0.05)
    ap.add_argument("--publish-every", type=int, default=5)
    ap.add_argument("--text-publish-every", type=int, default=6)
    ap.add_argument("--lost-iter", type=int, default=3)
    ap.add_argument("--peer-lost", type=float, default=0.8)
    ap.add_argument("--canary-fraction", type=float, default=0.3)
    ap.add_argument("--timeout", type=int, default=300)
    args = ap.parse_args(argv)

    if args.platform == "cpu":
        # the (1,2,2) layout needs >= 4 devices; force_cpu handles the
        # sitecustomize-already-imported-jax idiom per jax version
        from bigdl_tpu.utils.platform import force_cpu
        force_cpu(8)
    elif args.platform:
        import jax
        try:
            jax.config.update("jax_platforms", args.platform)
        except RuntimeError:
            pass

    if args.worker == "recsys":
        return _recsys_trainer(args)
    if args.worker == "text":
        return _text_trainer(args)

    base = args.ckpt_dir or tempfile.mkdtemp(prefix="workload_smoke_")
    cleanup = args.ckpt_dir is None
    ckpt_rec = os.path.join(base, "ckpt_recsys")
    ckpt_txt = os.path.join(base, "ckpt_text")
    trace_rec = os.path.join(base, "trace_recsys")
    trace_txt = os.path.join(base, "trace_text")
    args.data_dir = os.path.join(base, "data")
    for d in (ckpt_rec, ckpt_txt, args.data_dir):
        os.makedirs(d, exist_ok=True)
    out = {"metric": "workload_smoke", "ok": False}
    procs = []
    try:
        import numpy as np

        import bigdl_tpu.nn as nn
        from bigdl_tpu.dataset import (Dictionary,
                                       synthetic_criteo_records,
                                       write_criteo_shards)
        from bigdl_tpu.models import TextClassifier
        from bigdl_tpu.optim import Predictor
        from bigdl_tpu.parallel import LayoutSharding, MeshLayout
        from bigdl_tpu.serve import InferenceServer, fit_bucket, pad_tail
        from bigdl_tpu.serve.continuous import DeployController
        from bigdl_tpu.utils import file_io, telemetry
        from bigdl_tpu.utils.engine import Engine

        # --- leg 0: the generic chain is literally workload-free -------
        hits = []
        for rel in GENERIC_FILES:
            src = open(os.path.join(_REPO_ROOT, rel)).read().lower()
            hits += [f"{rel}:{w}" for w in WORKLOAD_WORDS if w in src]
        out["generic_chain_clean"] = not hits
        if hits:
            out["error"] = f"workload-specific branches found: {hits}"
            return 1

        import jax
        Engine.init()
        if jax.device_count() < 4:
            out["error"] = (f"need >= 4 devices for the (1,2,2) layout, "
                            f"have {jax.device_count()} — run with "
                            "XLA_FLAGS=--xla_force_host_platform_"
                            "device_count=8")
            return 1
        layout = MeshLayout(1, 2, 2)
        layout.install(jax.devices()[:4])
        n_shards = layout.fsdp * layout.tp
        out["layout"] = {"fsdp": layout.fsdp, "tp": layout.tp,
                         "n_shards": n_shards}

        spec = _spec()
        write_criteo_shards(os.path.join(args.data_dir, "criteo.bd"),
                            128, shards=4, seed=11, spec=spec)

        # spawn ALL trainers up front; serve recsys live, text after
        common = {"BIGDL_TPU_ELASTIC_WORLD": "2",
                  "BIGDL_TPU_ELASTIC_PEER_LOST": str(args.peer_lost),
                  "BIGDL_TPU_SUPERVISE_PEER_STALE": str(args.peer_lost / 2),
                  "BIGDL_TPU_SUPERVISE_STEP": "20"}
        p_rec0 = _spawn(args, "recsys", 0, ckpt_rec, args.epochs,
                        args.publish_every,
                        {**common, "BIGDL_TPU_ELASTIC_RANK": "0",
                         "BIGDL_TPU_CHAOS": "data.record=corrupt@6,13",
                         "BIGDL_TPU_DATA_SKIP_BUDGET": "4"})
        p_rec1 = _spawn(args, "recsys", 1, ckpt_rec, args.epochs,
                        args.publish_every,
                        {**common, "BIGDL_TPU_ELASTIC_RANK": "1",
                         "BIGDL_TPU_CHAOS":
                             f"host.lost@1=exit@1:{args.lost_iter}"})
        p_txt = _spawn(args, "text", 0, ckpt_txt, args.text_epochs,
                       args.text_publish_every, {})
        procs = [p_rec0, p_rec1, p_txt]

        # ============ workload 1: recommendation, served LIVE ==========
        rec = {}
        out["recsys"] = rec
        tracer = telemetry.Tracer(trace_rec, rank=SERVE_RANK)
        telemetry.set_active(tracer)
        arch = _widedeep(spec).build(jax.random.key(7))
        queries = np.stack(
            [spec.featurize(r).feature for r in
             synthetic_criteo_records(32, seed=21, spec=spec)])
        server = InferenceServer(
            arch, max_batch=4, max_wait_ms=2, queue_limit=4096,
            example=queries[0],
            strategy=LayoutSharding(arch, min_size=0),
            canary_min_batches=3, canary_window=16,
            canary_latency_ratio=20.0).start()
        controller = DeployController(
            server, ckpt_rec, canary_fraction=args.canary_fraction,
            rollback_budget=3, poll_s=0.05,
            decision_timeout=60.0).start()
        traffic = _Traffic(server, queries).start()

        out1, err1 = p_rec1.communicate(timeout=args.timeout)
        out0, err0 = p_rec0.communicate(timeout=args.timeout)
        rec["rank0_rc"], rec["rank1_rc"] = \
            p_rec0.returncode, p_rec1.returncode
        if p_rec1.returncode != LOST_EXIT:
            out["error"] = (f"recsys rank 1 exited {p_rec1.returncode}, "
                            f"expected the host-lost exit {LOST_EXIT}: "
                            f"{err1[-1500:]}")
            return 1
        if p_rec0.returncode != 0:
            out["error"] = f"recsys rank 0 failed: {err0[-2000:]}"
            return 1
        r0 = _last_json(out0)
        if not r0 or not r0.get("recovered") or not r0.get("published"):
            out["error"] = f"recsys rank 0 never recovered/published: {r0}"
            return 1
        if not r0.get("quarantined"):
            out["error"] = ("data.record chaos left nothing quarantined: "
                            f"{r0}")
            return 1
        published = int(r0["published"])
        rec.update(published=published, recovered=True,
                   quarantined=r0["quarantined"], loss=r0["loss"])

        st = _drain_controller(controller, published)
        traffic.stop()
        rec.update({k: st[k] for k in ("seen", "promoted", "rolled_back",
                                       "rejected")})
        rec["traffic"] = {"submitted": traffic.submitted,
                          "served": traffic.served,
                          "errors": traffic.errors[:5]}
        terminal = st["promoted"] + st["rolled_back"] + st["rejected"]
        if terminal < published:
            out["error"] = (f"recsys controller consumed {terminal} of "
                            f"{published} releases in time: {st}")
            return 1
        timeline = controller.versions()["timeline"]
        last, neval = _check_last_promoted(timeline)
        rec["final_release"], rec["final_neval"] = last, neval

        # the SERVED tables are resident at exactly 1/N per device
        fracs = _table_fractions(server.version.module,
                                 server.version._engine)
        rec["table_fractions"] = fracs
        if len(fracs) != 2 or \
                any(f != round(1.0 / n_shards, 6) for f in fracs):
            out["error"] = (f"served embedding tables not 1/{n_shards}-"
                            f"sharded: {fracs}")
            return 1

        # served answers bit-match the promoted snapshot's bulk oracle
        blob = file_io.load(os.path.join(ckpt_rec, f"model.{neval}"))
        oracle = _widedeep(spec).build(jax.random.key(0))
        oracle.attach(blob["params"], blob["state"])
        # the oracle runs the SAME fsdp×tp-sharded program as serving —
        # bit-identity includes the sharded reduction order
        ref = Predictor(oracle, strategy=LayoutSharding(oracle, min_size=0))
        mismatches = sum(
            not np.array_equal(server.predict(queries[i], timeout=60),
                               ref.predict(queries[i:i + 1])[0])
            for i in range(8))
        rec["bit_match"] = mismatches == 0
        if mismatches:
            out["error"] = (f"recsys: {mismatches}/8 served answers "
                            "differ from the promoted snapshot oracle")
            return 1
        if traffic.errors or traffic.served != traffic.submitted:
            out["error"] = f"recsys dropped requests: {rec['traffic']}"
            return 1
        controller.stop()
        server.stop()
        tracer.close()

        # ====== workload 2: text, variable-length over the ladder ======
        txt = {}
        out["text"] = txt
        outt, errt = p_txt.communicate(timeout=args.timeout)
        txt["rc"] = p_txt.returncode
        if p_txt.returncode != 0:
            out["error"] = f"text trainer failed: {errt[-2000:]}"
            return 1
        rt = _last_json(outt)
        if not rt or not rt.get("published"):
            out["error"] = f"text trainer never published: {rt}"
            return 1
        published_t = int(rt["published"])
        txt.update(published=published_t, loss=rt["loss"],
                   vocab=rt["vocab"])

        # the Dictionary shipped beside the lineage round-trips (pinned
        # UNK contract) — serving sizes its oracle from IT
        d = Dictionary.load(ckpt_txt)
        if d.vocab_size() != rt["vocab"] or \
                d.unk_index() != d.vocab_size() - 1:
            out["error"] = (f"dictionary round-trip broke: vocab "
                            f"{d.vocab_size()} vs {rt['vocab']}")
            return 1

        tracer = telemetry.Tracer(trace_txt, rank=SERVE_RANK)
        telemetry.set_active(tracer)
        arch_t = TextClassifier(3, embed_dim=16, seq_len=TEXT_SEQ,
                                vocab_size=d.vocab_size()).build(
            jax.random.key(8))
        rng = np.random.default_rng(5)
        lengths = [160, 192, 250, 300, 384]
        tqueries = [rng.integers(0, d.vocab_size(),
                                 size=(n,)).astype(np.int32)
                    for n in lengths for _ in range(3)]
        server = InferenceServer(
            arch_t, max_batch=4, max_wait_ms=2, queue_limit=4096,
            seq_buckets=SEQ_LADDER,
            example=np.zeros((TEXT_SEQ,), np.int32),
            strategy=LayoutSharding(arch_t, min_size=0),
            canary_min_batches=3, canary_window=16,
            canary_latency_ratio=20.0).start()
        controller = DeployController(
            server, ckpt_txt, canary_fraction=args.canary_fraction,
            rollback_budget=3, poll_s=0.05,
            decision_timeout=60.0).start()
        traffic = _Traffic(server, tqueries).start()

        st = _drain_controller(controller, published_t)
        traffic.stop()
        txt.update({k: st[k] for k in ("seen", "promoted", "rolled_back",
                                       "rejected")})
        txt["traffic"] = {"submitted": traffic.submitted,
                          "served": traffic.served,
                          "errors": traffic.errors[:5]}
        terminal = st["promoted"] + st["rolled_back"] + st["rejected"]
        if terminal < published_t:
            out["error"] = (f"text controller consumed {terminal} of "
                            f"{published_t} releases in time: {st}")
            return 1
        timeline = controller.versions()["timeline"]
        last, neval = _check_last_promoted(timeline)
        txt["final_release"], txt["final_neval"] = last, neval

        fracs = _table_fractions(server.version.module,
                                 server.version._engine)
        txt["table_fractions"] = fracs
        if len(fracs) != 1 or fracs[0] != round(1.0 / n_shards, 6):
            out["error"] = (f"served text embedding table not "
                            f"1/{n_shards}-sharded: {fracs}")
            return 1

        # bit-match at the SAME padded sequence bucket the server used
        blob = file_io.load(os.path.join(ckpt_txt, f"model.{neval}"))
        oracle = TextClassifier(3, embed_dim=16, seq_len=TEXT_SEQ,
                                vocab_size=d.vocab_size()).build(
            jax.random.key(0))
        oracle.attach(blob["params"], blob["state"])
        ref = Predictor(oracle, strategy=LayoutSharding(oracle, min_size=0))
        mismatches = 0
        for i in range(len(lengths)):
            q = tqueries[i * 3]
            seq = fit_bucket(len(q), SEQ_LADDER)
            got = server.predict(q, timeout=60)
            want = ref.predict(pad_tail(q, seq)[None, :])[0]
            if not np.array_equal(got, want):
                mismatches += 1
        txt["bit_match"] = mismatches == 0
        if mismatches:
            out["error"] = (f"text: {mismatches}/{len(lengths)} served "
                            "answers differ from the oracle at the same "
                            "padded bucket")
            return 1
        if traffic.errors or traffic.served != traffic.submitted:
            out["error"] = f"text dropped requests: {txt['traffic']}"
            return 1
        controller.stop()
        server.stop()
        tracer.close()

        # ====== cross-workload: identical generic serving tracks =======
        spans_r, counters_r = _serve_tracks(trace_rec)
        spans_t, counters_t = _serve_tracks(trace_txt)
        out["serve_spans"] = sorted(spans_r)
        out["serve_counters"] = sorted(counters_r)
        out["spans_equal"] = (spans_r == spans_t
                              and counters_r == counters_t)
        if not out["spans_equal"]:
            out["error"] = ("the two workloads ran DIFFERENT serve "
                            f"tracks: spans {sorted(spans_r ^ spans_t)}, "
                            f"counters {sorted(counters_r ^ counters_t)}")
            return 1
        if "serve.batch" not in spans_r:
            out["error"] = f"no serve.batch spans recorded: {spans_r}"
            return 1

        out["ok"] = True
        return 0
    except subprocess.TimeoutExpired as e:
        out["error"] = f"drill timed out: {e}"
        return 1
    except Exception as e:  # noqa: BLE001 — one JSON line, always
        import traceback
        out["error"] = f"{type(e).__name__}: {e}"
        out["traceback"] = traceback.format_exc()[-2000:]
        return 1
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
        print(json.dumps(out))
        sys.stdout.flush()
        if cleanup:
            shutil.rmtree(base, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
