#!/usr/bin/env python
"""Continuous train->serve drill: the optimizer->canary loop end-to-end
with trainer and server as SEPARATE processes sharing only a lineage
directory (runbook cpu-smoke stage 2o; the tier-1 acceptance test in
tests/test_continuous.py drives the same artifact).

Orchestration:

1. Two subprocess trainer ranks (the simulated multi-host harness,
   ``BIGDL_TPU_ELASTIC_WORLD=2``) train a Linear model, checkpoint every
   iteration, and PUBLISH a release entry every ``--publish-every``-th
   snapshot (``set_checkpoint(..., publish=True)``).  Rank 0 carries
   chaos ``deploy.publish=corrupt@2`` — its 2nd release entry lands
   corrupt on storage.  Rank 1 carries ``host.lost@1=exit@1:3`` — it
   dies mid-epoch-1 and rank 0 must run the elastic recovery and KEEP
   PUBLISHING from the shrunken world.

2. This process is the serving side: a live ``InferenceServer`` (fresh
   random weights) + a ``DeployController`` watching the shared lineage
   dir with ``canary_fraction`` routing, while a closed-loop traffic
   thread keeps submitting.  Chaos ``serve.canary=stall*S@4,5`` inflates
   exactly the SECOND deployed release's canary latency — the comparator
   must auto-roll it back.

3. The three failure legs asserted in ONE run: the corrupt entry is
   quarantined + skipped with a typed ``ReleaseRejected`` (and the next
   good entry deploys), the host loss never interrupts the release feed
   (a release with ``neval`` past the recovery point promotes), and the
   canary regression rolls back exactly once without degrading serving.
   End state: the LAST release is promoted, the served model answers
   bit-for-bit what bulk ``Predictor.predict`` computes from that
   release's snapshot, and ZERO submitted requests were dropped or
   errored.  The merged trainer+server trace must carry the ``deploy``
   counter track (publishes + deploy outcomes on one timeline).

Prints ONE JSON line; exit 0 iff every leg closed::

    {"metric": "continuous_smoke", "ok": true, "published": 8,
     "promoted": 6, "rolled_back": 1, "rejected": 1, "recovered": true,
     "traffic": {"submitted": N, "served": N, "errors": []},
     "bit_match": true, ...}
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import threading
import time

# runnable as `python tools/continuous_smoke.py` from the repo root
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

LOST_EXIT = 117  # chaos.ExitAt.EXIT_CODE


# ---------------------------------------------------------------------------
# trainer worker (one logical rank, subprocess)
# ---------------------------------------------------------------------------

def _trainer(args) -> int:
    if args.platform:
        import jax
        try:
            jax.config.update("jax_platforms", args.platform)
        except RuntimeError:
            pass
    import numpy as np

    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.dataset.transformer import Transformer
    from bigdl_tpu.optim import Adam, Optimizer, Trigger

    rng = np.random.default_rng(0)
    samples = [Sample(rng.standard_normal(6).astype(np.float32),
                      np.float32(i % 2)) for i in range(128)]

    class Pace(Transformer):
        """Per-minibatch pacing so the run outlives the peer-lost
        detection window (the drill's clock, not the model's)."""

        def __init__(self, seconds):
            self.seconds = seconds

        def __call__(self, it):
            for x in it:
                if self.seconds:
                    time.sleep(self.seconds)
                yield x

    ds = (DataSet.rdd(samples)
          .transform(SampleToMiniBatch(args.batch, drop_last=True))
          .transform(Pace(args.pace)))
    ds.shuffle = lambda: None  # deterministic epoch order

    opt = (Optimizer(nn.Sequential().add(nn.Linear(6, 2)), ds,
                     nn.CrossEntropyCriterion())
           .set_optim_method(Adam(1e-2))
           .set_end_when(Trigger.max_epoch(args.epochs)))
    opt.set_checkpoint(args.ckpt_dir, Trigger.several_iteration(1),
                       publish=True, publish_every=args.publish_every)
    opt.optimize()
    plan = getattr(opt, "_elastic_plan", None)
    out = {"rank": args.rank,
           "recovered": plan is not None,
           "neval_resumed": plan.neval if plan is not None else None,
           "published": (opt._publisher.published
                         if opt._publisher is not None else 0),
           "loss": float(opt.optim_method.hyper.get("loss", 0.0))}
    print(json.dumps(out), flush=True)
    return 0


def _spawn(args, rank: int, extra_env: dict):
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("BIGDL_TPU_ELASTIC", "BIGDL_TPU_CHAOS",
                                "BIGDL_TPU_TRACE", "BIGDL_TPU_SUPERVISE",
                                "BIGDL_TPU_DEPLOY"))}
    env.update({"PYTHONPATH": _REPO_ROOT,
                "JAX_PLATFORMS": args.platform or "cpu",
                "BIGDL_TPU_PREFETCH_DEPTH": "0",
                **extra_env})
    wargs = ["--worker", "--rank", str(rank),
             "--ckpt-dir", args.ckpt_dir,
             "--epochs", str(args.epochs), "--batch", str(args.batch),
             "--pace", str(args.pace),
             "--publish-every", str(args.publish_every)]
    if args.platform:
        wargs += ["--platform", args.platform]
    return subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), *wargs],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)


def _last_json(out: str):
    lines = [ln for ln in out.splitlines() if ln.startswith("{")]
    return json.loads(lines[-1]) if lines else None


# ---------------------------------------------------------------------------
# the serving side (this process)
# ---------------------------------------------------------------------------

class _Traffic:
    """Closed-loop traffic: one request at a time, every answer counted.
    Zero-drop is the contract — any error or unanswered submit fails
    the smoke."""

    def __init__(self, server, queries):
        self.server = server
        self.queries = queries
        self.submitted = 0
        self.served = 0
        self.errors = []
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="continuous-smoke-traffic")

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=120.0)

    def _run(self):
        i = 0
        while not self._stop.is_set():
            x = self.queries[i % len(self.queries)]
            i += 1
            try:
                self.submitted += 1
                self.server.submit(x).result(120)
                self.served += 1
            except Exception as e:  # noqa: BLE001 — recorded, fails smoke
                self.errors.append(f"{type(e).__name__}: {e}")
                if len(self.errors) > 8:
                    return
            time.sleep(0.002)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default=None)
    ap.add_argument("--worker", action="store_true")
    ap.add_argument("--rank", type=int, default=0)
    ap.add_argument("--ckpt-dir", default=None)
    ap.add_argument("--epochs", type=int, default=10)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--pace", type=float, default=0.05)
    ap.add_argument("--publish-every", type=int, default=5)
    ap.add_argument("--lost-iter", type=int, default=3)
    ap.add_argument("--peer-lost", type=float, default=0.8)
    ap.add_argument("--canary-fraction", type=float, default=0.3)
    ap.add_argument("--canary-stall", type=float, default=0.4)
    ap.add_argument("--timeout", type=int, default=240)
    args = ap.parse_args(argv)

    if args.worker:
        return _trainer(args)

    if args.platform:
        import jax
        try:
            jax.config.update("jax_platforms", args.platform)
        except RuntimeError:
            pass

    base = args.ckpt_dir or tempfile.mkdtemp(prefix="continuous_smoke_")
    cleanup = args.ckpt_dir is None
    ckpt = os.path.join(base, "ckpt")
    trace = os.path.join(base, "trace")
    os.makedirs(ckpt, exist_ok=True)
    args.ckpt_dir = ckpt
    out = {"metric": "continuous_smoke", "ok": False}
    p0 = p1 = None
    try:
        import numpy as np

        import bigdl_tpu.nn as nn
        from bigdl_tpu.optim import Predictor
        from bigdl_tpu.serve import InferenceServer
        from bigdl_tpu.serve.continuous import DeployController
        from bigdl_tpu.utils import chaos, file_io, telemetry
        from bigdl_tpu.utils.engine import Engine

        Engine.init()
        import jax
        arch = nn.Sequential().add(nn.Linear(6, 2)).build(
            jax.random.key(7))
        queries = np.random.default_rng(1).normal(
            size=(32, 6)).astype(np.float32)

        # the server side writes its own rank-2 trace beside the trainer
        # ranks' so trace_report merges train + deploy on one timeline
        tracer = telemetry.Tracer(trace, rank=2)
        telemetry.set_active(tracer)

        # trainer chaos: rank 0 corrupts its 2nd release entry mid-
        # publish; rank 1 dies mid-epoch-1 (the host-loss leg)
        common = {"BIGDL_TPU_ELASTIC_WORLD": "2",
                  "BIGDL_TPU_ELASTIC_PEER_LOST": str(args.peer_lost),
                  "BIGDL_TPU_SUPERVISE_PEER_STALE": str(args.peer_lost / 2),
                  "BIGDL_TPU_SUPERVISE_STEP": "20"}
        p0 = _spawn(args, 0, {**common, "BIGDL_TPU_ELASTIC_RANK": "0",
                              "BIGDL_TPU_TRACE": trace,
                              "BIGDL_TPU_CHAOS":
                                  "deploy.publish=corrupt@2"})
        p1 = _spawn(args, 1, {**common, "BIGDL_TPU_ELASTIC_RANK": "1",
                              "BIGDL_TPU_CHAOS":
                                  f"host.lost@1=exit@1:{args.lost_iter}"})

        # serving-side chaos: canary batches 4-5 are exactly the SECOND
        # deployed release's canary episode (3 clean batches promote the
        # first) — its latency inflates and the comparator must roll it
        # back, while stalled requests are still answered (zero drop)
        with chaos.scoped(f"serve.canary=stall*{args.canary_stall}@4,5"):
            # latency_ratio 20: the injected 0.4s stall is a >100x
            # regression, while natural CPU scheduler jitter (2-5x on a
            # 2-sample window under load) must not flake the drill
            server = InferenceServer(
                arch, max_batch=4, max_wait_ms=2, queue_limit=4096,
                example=queries[0], canary_min_batches=3,
                canary_window=16, canary_latency_ratio=20.0).start()
            controller = DeployController(
                server, ckpt, canary_fraction=args.canary_fraction,
                rollback_budget=3, poll_s=0.05,
                decision_timeout=60.0).start()
            traffic = _Traffic(server, queries).start()

            out1, err1 = p1.communicate(timeout=args.timeout)
            out0, err0 = p0.communicate(timeout=args.timeout)
            out["rank0_rc"], out["rank1_rc"] = p0.returncode, p1.returncode
            if p1.returncode != LOST_EXIT:
                out["error"] = (f"rank 1 exited {p1.returncode}, expected "
                                f"the host-lost drill exit {LOST_EXIT}: "
                                f"{err1[-1500:]}")
                return 1
            if p0.returncode != 0:
                out["error"] = f"rank 0 failed: {err0[-2000:]}"
                return 1
            r0 = _last_json(out0)
            if not r0 or not r0.get("recovered") or \
                    not r0.get("published"):
                out["error"] = ("rank 0 never recovered/published: "
                                f"{r0}")
                return 1
            published = int(r0["published"])
            out.update(published=published, recovered=True,
                       neval_resumed=r0["neval_resumed"])

            # every published release must reach a terminal outcome:
            # promoted, rolled_back, or rejected
            deadline = time.monotonic() + 120.0
            while time.monotonic() < deadline:
                st = controller.stats()
                terminal = (st["promoted"] + st["rolled_back"]
                            + st["rejected"])
                if terminal >= published and st["seen"] >= published:
                    break
                time.sleep(0.1)
            traffic.stop()
            st = controller.stats()
            timeline = controller.versions()["timeline"]
            out.update({k: st[k] for k in
                        ("seen", "deployed", "promoted", "rolled_back",
                         "rejected", "consecutive_rollbacks")},
                       healthy=st["healthy"], frozen=st["frozen"])
            out["traffic"] = {"submitted": traffic.submitted,
                              "served": traffic.served,
                              "errors": traffic.errors[:5]}
            terminal = st["promoted"] + st["rolled_back"] + st["rejected"]
            if terminal < published:
                out["error"] = (f"controller consumed {terminal} of "
                                f"{published} releases in time; stats "
                                f"{st}")
                return 1

            # leg 1 — corrupt publish: skipped typed + quarantined, and
            # good entries still deployed in order
            rejected = [e for e in timeline if e["action"] == "rejected"]
            corrupt = [e for e in rejected
                       if "unreadable entry" in e.get("reason", "")]
            if not corrupt or not os.path.exists(
                    os.path.join(ckpt, "release.2.corrupt")):
                out["error"] = ("corrupt release was not skipped typed + "
                                f"quarantined: rejected={rejected}")
                return 1
            deployed_ids = [e["release"] for e in timeline
                            if e["action"] == "deployed"]
            if deployed_ids != sorted(deployed_ids) or 2 in deployed_ids:
                out["error"] = f"bad deploy order: {deployed_ids}"
                return 1

            # leg 2 — host loss: the feed survived recovery (a release
            # with neval past the resume point was promoted)
            promoted = [e for e in timeline if e["action"] == "promoted"]
            if not any(e.get("neval", -1) > (r0["neval_resumed"] or 0)
                       for e in promoted):
                out["error"] = ("no release promoted past the elastic "
                                f"recovery point: {promoted}")
                return 1

            # leg 3 — canary regression: exactly one auto-rollback, the
            # controller still healthy (budget not exhausted)
            if st["rolled_back"] != 1 or not st["healthy"]:
                out["error"] = ("expected exactly 1 canary rollback on a "
                                f"healthy controller: {st}")
                return 1

            # end state — the LAST release promoted, and the live server
            # answers bit-for-bit what that release's snapshot computes
            last = max(e["release"] for e in timeline)
            last_terminal = [e for e in timeline if e["release"] == last
                             and e["action"] in ("promoted", "rolled_back",
                                                 "rejected")]
            if not last_terminal or \
                    last_terminal[-1]["action"] != "promoted":
                out["error"] = (f"last release {last} did not promote: "
                                f"{last_terminal}")
                return 1
            out["final_release"] = last
            neval = last_terminal[-1]["neval"]
            out["final_neval"] = neval
            blob = file_io.load(os.path.join(ckpt, f"model.{neval}"))
            oracle = nn.Sequential().add(nn.Linear(6, 2)).build(
                jax.random.key(0))
            oracle.attach(blob["params"], blob["state"])
            ref = Predictor(oracle)
            mismatches = 0
            for i in range(8):
                got = server.predict(queries[i], timeout=60)
                want = ref.predict(queries[i:i + 1])[0]
                if not np.array_equal(got, want):
                    mismatches += 1
            out["bit_match"] = mismatches == 0
            if mismatches:
                out["error"] = (f"{mismatches}/8 served answers differ "
                                "from the promoted snapshot's oracle")
                return 1
            if traffic.errors or traffic.served != traffic.submitted:
                out["error"] = ("dropped/errored requests: "
                                f"{out['traffic']}")
                return 1

            controller.stop()
            server.stop()
        tracer.close()

        # the merged trainer+server trace must carry the deploy track
        breakdown = telemetry.phase_breakdown(telemetry.merge_traces(trace))
        out["deploy_report"] = breakdown.get("deploy", {})
        if breakdown.get("deploy", {}).get("published") != published or \
                "promoted" not in breakdown.get("deploy", {}):
            out["error"] = ("merged trace is missing the deploy track: "
                            f"{out['deploy_report']}")
            return 1
        out["ok"] = True
        return 0
    except subprocess.TimeoutExpired as e:
        out["error"] = f"drill timed out: {e}"
        for proc in (p0, p1):
            if proc is not None and proc.poll() is None:
                proc.kill()
        return 1
    except Exception as e:  # noqa: BLE001 — one JSON line, always
        import traceback
        out["error"] = f"{type(e).__name__}: {e}"
        out["traceback"] = traceback.format_exc()[-2000:]
        return 1
    finally:
        print(json.dumps(out))
        sys.stdout.flush()
        if cleanup:
            shutil.rmtree(base, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
