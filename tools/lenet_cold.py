#!/usr/bin/env python
"""Cold-compile timing for the LeNet train step (versioned: was the
unversioned /tmp/lenet_cold.py the round-5 runbook depended on).

Why LeNet: XLA compile of this SMALL model is the pathological case on
the tunneled backend (809s+ measured, vs 27s for ResNet-50 —
docs/benchmarking.md), driven by the C_in<8 conv backward.  The runbook
runs this twice against fresh cache dirs for the pad A/B:

    BIGDL_TPU_XLA_CACHE_DIR=/tmp/xla_cold_pad   python tools/lenet_cold.py
    BIGDL_TPU_CONV_PAD_MIN_CIN=0 \
    BIGDL_TPU_XLA_CACHE_DIR=/tmp/xla_cold_nopad python tools/lenet_cold.py

Prints one JSON line: wall seconds for the first optimizer iteration
(compile-dominated: the step itself is milliseconds) plus the knob state,
so the A/B is self-describing.  `--platform cpu` dry-runs the same code
path off-TPU (the runbook's smoke mode).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

# runnable as `python tools/lenet_cold.py` from the repo root (or anywhere)
# without an installed wheel — same trick as tests/conftest.py
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default=None,
                    help="force a jax platform (e.g. cpu) for smoke runs")
    ap.add_argument("--batch-size", type=int, default=256)
    args = ap.parse_args(argv)

    if args.platform:
        import jax
        try:
            jax.config.update("jax_platforms", args.platform)
        except RuntimeError:
            pass
    from bigdl_tpu.utils.platform import enable_compilation_cache
    cache_dir = enable_compilation_cache()

    import jax
    import numpy as np

    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.models.lenet import LeNet5
    from bigdl_tpu.optim import Optimizer, SGD, Trigger

    rng = np.random.default_rng(0)
    n = args.batch_size
    xs = rng.normal(size=(n, 28, 28, 1)).astype(np.float32)
    ys = rng.integers(0, 10, size=n)
    ds = DataSet.array(
        [Sample(x, np.int32(y)) for x, y in zip(xs, ys)]).transform(
        SampleToMiniBatch(n, drop_last=True))
    opt = (Optimizer(LeNet5(10), ds, nn.ClassNLLCriterion())
           .set_optim_method(SGD(learning_rate=0.01))
           .set_end_when(Trigger.max_iteration(1)))

    t0 = time.perf_counter()
    opt.optimize()  # one iteration: cold compile + one step
    dt = time.perf_counter() - t0
    print(json.dumps({
        "metric": "lenet_cold_compile_seconds",
        "value": round(dt, 3),
        "batch_size": n,
        "backend": jax.default_backend(),
        "device_kind": jax.devices()[0].device_kind,
        "conv_pad_min_cin": os.environ.get("BIGDL_TPU_CONV_PAD_MIN_CIN",
                                           "default(8)"),
        "xla_cache_dir": cache_dir,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
