#!/usr/bin/env python
"""Cold-compile timing for the LeNet train step (versioned: was the
unversioned /tmp/lenet_cold.py the round-5 runbook depended on).

Why LeNet: XLA compile of this SMALL model is the pathological case on
the tunneled backend (809s+ measured, vs 27s for ResNet-50 —
docs/benchmarking.md), driven by the C_in<8 conv backward.  The runbook
runs this twice against fresh cache dirs for the pad A/B:

    BIGDL_TPU_XLA_CACHE_DIR=/tmp/xla_cold_pad   python tools/lenet_cold.py
    BIGDL_TPU_CONV_PAD_MIN_CIN=0 \
    BIGDL_TPU_XLA_CACHE_DIR=/tmp/xla_cold_nopad python tools/lenet_cold.py

Prints one JSON line: wall seconds for the first optimizer iteration
(compile-dominated: the step itself is milliseconds) plus the knob state,
so the A/B is self-describing.  `--platform cpu` dry-runs the same code
path off-TPU (the runbook's smoke mode).

`--aot-cache DIR` switches to the AOT executable-cache A/B
(utils/aot.py): the SAME training run twice in one process against DIR —
cold (compile + store) then warm (jit caches cleared, executable
deserialized from DIR) — emitting one JSON line with `compile_s_cold` /
`compile_s_warm` (time spent compiling + loading, from the aot counters)
and the hit/miss ledger.  The XLA persistent cache is disabled in this
mode so the warm number is attributable to the AOT layer alone.

`--conv-route matmul` switches to the conv-lowering A/B (ISSUE 7): the
LeNet train step built twice in one process — pad route (the default
zero-pad mitigation) vs the reshaped-matmul route
(BIGDL_TPU_CONV_ROUTE=matmul, ops/convmm.py) — emitting one JSON line
with, per route, the conv-op count of the compiled train step (the
CPU-side proxy for the 809 s TPU compile: the pathology lives in the TPU
backend's grad-of-conv emitter, so the HLO that matters is the
convolution subprogram, which the matmul route deletes outright), total
HLO size for context, compile seconds, and steady-state step seconds.
Exit 1 unless the matmul route eliminates every conv from the step AND
its step time is no worse (<= 1.25x, measurement slack).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

# runnable as `python tools/lenet_cold.py` from the repo root (or anywhere)
# without an installed wheel — same trick as tests/conftest.py
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)


def _make_run(batch_size):
    import numpy as np

    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.models.lenet import LeNet5
    from bigdl_tpu.optim import Optimizer, SGD, Trigger

    rng = np.random.default_rng(0)
    n = batch_size
    xs = rng.normal(size=(n, 28, 28, 1)).astype(np.float32)
    ys = rng.integers(0, 10, size=n)

    def run():
        """One fresh optimizer, one iteration: cold compile + one step.
        Returns wall seconds."""
        ds = DataSet.array(
            [Sample(x, np.int32(y)) for x, y in zip(xs, ys)]).transform(
            SampleToMiniBatch(n, drop_last=True))
        opt = (Optimizer(LeNet5(10), ds, nn.ClassNLLCriterion())
               .set_optim_method(SGD(learning_rate=0.01))
               .set_end_when(Trigger.max_iteration(1)))
        t0 = time.perf_counter()
        opt.optimize()
        return time.perf_counter() - t0

    return run


def _aot_mode(args):
    """Cold-vs-warm A/B against the AOT executable cache: one JSON line."""
    # attribute the warm number to the AOT layer alone — no XLA disk cache
    os.environ["BIGDL_TPU_AOT_CACHE"] = args.aot_cache
    os.environ.setdefault("BIGDL_TPU_XLA_CACHE", "0")

    import jax

    from bigdl_tpu.utils import aot

    run = _make_run(args.batch_size)

    def compile_cost(before, after):
        # XLA compile time + executable-deserialize time: the "how long
        # until the step is runnable" number the acceptance bound reads
        return (after["compile_s"] - before["compile_s"] +
                after["load_s"] - before["load_s"])

    s0 = aot.stats()
    wall_cold = run()
    s1 = aot.stats()
    # drop every in-memory jit/pjit cache so the second run re-lowers and
    # must go through the persistent AOT cache, as a fresh process would
    jax.clear_caches()
    wall_warm = run()
    s2 = aot.stats()

    cold = compile_cost(s0, s1)
    warm = compile_cost(s1, s2)
    print(json.dumps({
        "metric": "lenet_aot_cold_warm",
        "compile_s_cold": round(cold, 3),
        "compile_s_warm": round(warm, 3),
        "warm_over_cold": round(warm / max(cold, 1e-9), 4),
        "wall_s_cold": round(wall_cold, 3),
        "wall_s_warm": round(wall_warm, 3),
        "aot": {k: (int(v) if k not in ("compile_s", "load_s")
                    else round(v, 3)) for k, v in s2.items()},
        "batch_size": args.batch_size,
        "backend": jax.default_backend(),
        "device_kind": jax.devices()[0].device_kind,
        "aot_cache_dir": args.aot_cache,
    }))
    # acceptance bound (ISSUE 6): warm must be < 20% of cold
    return 0 if warm < 0.2 * cold else 1


def _build_step(batch_size):
    """The real compiled train step (Optimizer._build_step) on device 0;
    returns (step_fn, args, hlo_text)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    import bigdl_tpu.nn as nn
    from bigdl_tpu.models.lenet import LeNet5
    from bigdl_tpu.optim import Optimizer, SGD, Trigger
    from bigdl_tpu.utils.engine import Engine

    Engine.reset()
    Engine.init(devices=[jax.devices()[0]])
    mesh = Engine.mesh()
    model = LeNet5(10)
    model.build(jax.random.key(0))
    opt = Optimizer(model, dataset=None, criterion=nn.ClassNLLCriterion(),
                    end_trigger=Trigger.max_iteration(1))
    opt.set_optim_method(SGD(learning_rate=0.01))
    step, param_sh, _ = opt._build_step(mesh)

    rng = np.random.default_rng(0)
    inp = jnp.asarray(rng.normal(size=(batch_size, 28, 28, 1)),
                      jnp.float32)
    tgt = jnp.asarray(rng.integers(0, 10, size=batch_size), jnp.int32)
    params = jax.device_put(model.params, param_sh)
    args = (params, model.state, opt.optim_method.init_state(params),
            inp, tgt, jnp.float32(0.01), jax.random.key(1))
    hlo = step.lower(*args).as_text()
    return step, args, hlo


def _conv_route_mode(args):
    """Pad-vs-matmul conv-lowering A/B on the LeNet train step."""
    import jax

    results = {}
    for route in ("pad", args.conv_route):
        os.environ["BIGDL_TPU_CONV_ROUTE"] = route
        jax.clear_caches()
        step, step_args, hlo = _build_step(args.batch_size)
        t0 = time.perf_counter()
        compiled = step.lower(*step_args).compile()
        compile_s = time.perf_counter() - t0
        opt_hlo = compiled.as_text()
        out = step(*step_args)
        jax.block_until_ready(out)
        # steady state: params/opt_state threaded so shapes stay fixed
        params, net_state, opt_state = out[0], out[1], out[2]
        t0 = time.perf_counter()
        iters = 10
        for _ in range(iters):
            params, net_state, opt_state, loss = step(
                params, net_state, opt_state, *step_args[3:])
        jax.block_until_ready(loss)
        results[route] = {
            # the pathology metric: convolution ops in the COMPILED step
            # (each is a program the TPU conv emitter must lower; the
            # 809 s case is one grad-of-conv among these)
            "hlo_conv_ops": opt_hlo.count(" convolution"),
            "hlo_ops": opt_hlo.count("\n"),
            "stablehlo_ops": hlo.count("\n"),
            "compile_s": round(compile_s, 3),
            "step_s": round((time.perf_counter() - t0) / iters, 6),
        }
    pad, mm = results["pad"], results[args.conv_route]
    ok = (mm["hlo_conv_ops"] == 0 and pad["hlo_conv_ops"] > 0
          and mm["step_s"] <= 1.25 * pad["step_s"])
    print(json.dumps({
        "metric": "lenet_conv_route_ab",
        "routes": results,
        "conv_ops_eliminated": pad["hlo_conv_ops"] - mm["hlo_conv_ops"],
        "step_ratio": round(mm["step_s"] / max(pad["step_s"], 1e-9), 4),
        "batch_size": args.batch_size,
        "backend": jax.default_backend(),
        "device_kind": jax.devices()[0].device_kind,
        "ok": ok,
    }))
    return 0 if ok else 1


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default=None,
                    help="force a jax platform (e.g. cpu) for smoke runs")
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--aot-cache", metavar="DIR", default=None,
                    help="AOT executable-cache mode: run cold then warm "
                         "against DIR, emit compile_s_cold/compile_s_warm; "
                         "exit 1 unless warm < 20%% of cold")
    ap.add_argument("--conv-route", metavar="ROUTE", default=None,
                    choices=["matmul", "lax"],
                    help="conv-lowering A/B mode: pad route vs ROUTE on "
                         "the train step, one JSON line; exit 1 unless "
                         "ROUTE's HLO is smaller with step time no worse")
    args = ap.parse_args(argv)

    if args.platform:
        import jax
        try:
            jax.config.update("jax_platforms", args.platform)
        except RuntimeError:
            pass
    if args.aot_cache:
        return _aot_mode(args)
    if args.conv_route:
        return _conv_route_mode(args)
    from bigdl_tpu.utils.platform import enable_compilation_cache
    cache_dir = enable_compilation_cache()

    import jax

    run = _make_run(args.batch_size)
    dt = run()
    print(json.dumps({
        "metric": "lenet_cold_compile_seconds",
        "value": round(dt, 3),
        "batch_size": args.batch_size,
        "backend": jax.default_backend(),
        "device_kind": jax.devices()[0].device_kind,
        "conv_pad_min_cin": os.environ.get("BIGDL_TPU_CONV_PAD_MIN_CIN",
                                           "default(8)"),
        "xla_cache_dir": cache_dir,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
