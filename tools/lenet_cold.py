#!/usr/bin/env python
"""Cold-compile timing for the LeNet train step (versioned: was the
unversioned /tmp/lenet_cold.py the round-5 runbook depended on).

Why LeNet: XLA compile of this SMALL model is the pathological case on
the tunneled backend (809s+ measured, vs 27s for ResNet-50 —
docs/benchmarking.md), driven by the C_in<8 conv backward.  The runbook
runs this twice against fresh cache dirs for the pad A/B:

    BIGDL_TPU_XLA_CACHE_DIR=/tmp/xla_cold_pad   python tools/lenet_cold.py
    BIGDL_TPU_CONV_PAD_MIN_CIN=0 \
    BIGDL_TPU_XLA_CACHE_DIR=/tmp/xla_cold_nopad python tools/lenet_cold.py

Prints one JSON line: wall seconds for the first optimizer iteration
(compile-dominated: the step itself is milliseconds) plus the knob state,
so the A/B is self-describing.  `--platform cpu` dry-runs the same code
path off-TPU (the runbook's smoke mode).

`--aot-cache DIR` switches to the AOT executable-cache A/B
(utils/aot.py): the SAME training run twice in one process against DIR —
cold (compile + store) then warm (jit caches cleared, executable
deserialized from DIR) — emitting one JSON line with `compile_s_cold` /
`compile_s_warm` (time spent compiling + loading, from the aot counters)
and the hit/miss ledger.  The XLA persistent cache is disabled in this
mode so the warm number is attributable to the AOT layer alone.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

# runnable as `python tools/lenet_cold.py` from the repo root (or anywhere)
# without an installed wheel — same trick as tests/conftest.py
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)


def _make_run(batch_size):
    import numpy as np

    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.models.lenet import LeNet5
    from bigdl_tpu.optim import Optimizer, SGD, Trigger

    rng = np.random.default_rng(0)
    n = batch_size
    xs = rng.normal(size=(n, 28, 28, 1)).astype(np.float32)
    ys = rng.integers(0, 10, size=n)

    def run():
        """One fresh optimizer, one iteration: cold compile + one step.
        Returns wall seconds."""
        ds = DataSet.array(
            [Sample(x, np.int32(y)) for x, y in zip(xs, ys)]).transform(
            SampleToMiniBatch(n, drop_last=True))
        opt = (Optimizer(LeNet5(10), ds, nn.ClassNLLCriterion())
               .set_optim_method(SGD(learning_rate=0.01))
               .set_end_when(Trigger.max_iteration(1)))
        t0 = time.perf_counter()
        opt.optimize()
        return time.perf_counter() - t0

    return run


def _aot_mode(args):
    """Cold-vs-warm A/B against the AOT executable cache: one JSON line."""
    # attribute the warm number to the AOT layer alone — no XLA disk cache
    os.environ["BIGDL_TPU_AOT_CACHE"] = args.aot_cache
    os.environ.setdefault("BIGDL_TPU_XLA_CACHE", "0")

    import jax

    from bigdl_tpu.utils import aot

    run = _make_run(args.batch_size)

    def compile_cost(before, after):
        # XLA compile time + executable-deserialize time: the "how long
        # until the step is runnable" number the acceptance bound reads
        return (after["compile_s"] - before["compile_s"] +
                after["load_s"] - before["load_s"])

    s0 = aot.stats()
    wall_cold = run()
    s1 = aot.stats()
    # drop every in-memory jit/pjit cache so the second run re-lowers and
    # must go through the persistent AOT cache, as a fresh process would
    jax.clear_caches()
    wall_warm = run()
    s2 = aot.stats()

    cold = compile_cost(s0, s1)
    warm = compile_cost(s1, s2)
    print(json.dumps({
        "metric": "lenet_aot_cold_warm",
        "compile_s_cold": round(cold, 3),
        "compile_s_warm": round(warm, 3),
        "warm_over_cold": round(warm / max(cold, 1e-9), 4),
        "wall_s_cold": round(wall_cold, 3),
        "wall_s_warm": round(wall_warm, 3),
        "aot": {k: (int(v) if k not in ("compile_s", "load_s")
                    else round(v, 3)) for k, v in s2.items()},
        "batch_size": args.batch_size,
        "backend": jax.default_backend(),
        "device_kind": jax.devices()[0].device_kind,
        "aot_cache_dir": args.aot_cache,
    }))
    # acceptance bound (ISSUE 6): warm must be < 20% of cold
    return 0 if warm < 0.2 * cold else 1


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default=None,
                    help="force a jax platform (e.g. cpu) for smoke runs")
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--aot-cache", metavar="DIR", default=None,
                    help="AOT executable-cache mode: run cold then warm "
                         "against DIR, emit compile_s_cold/compile_s_warm; "
                         "exit 1 unless warm < 20%% of cold")
    args = ap.parse_args(argv)

    if args.platform:
        import jax
        try:
            jax.config.update("jax_platforms", args.platform)
        except RuntimeError:
            pass
    if args.aot_cache:
        return _aot_mode(args)
    from bigdl_tpu.utils.platform import enable_compilation_cache
    cache_dir = enable_compilation_cache()

    import jax

    run = _make_run(args.batch_size)
    dt = run()
    print(json.dumps({
        "metric": "lenet_cold_compile_seconds",
        "value": round(dt, 3),
        "batch_size": args.batch_size,
        "backend": jax.default_backend(),
        "device_kind": jax.devices()[0].device_kind,
        "conv_pad_min_cin": os.environ.get("BIGDL_TPU_CONV_PAD_MIN_CIN",
                                           "default(8)"),
        "xla_cache_dir": cache_dir,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
